#include "net/cluster.h"

#include "net/concurrency_limiter.h"
#include "net/span.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <fstream>
#include <sstream>

#include "base/flags.h"
#include "base/logging.h"
#include "base/rand.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/deadline.h"
#include "net/fault.h"
#include "net/lb_hint.h"
#include "net/naming.h"
#include "stat/reducer.h"
#include "stat/timeline.h"

namespace trpc {

// ---- load balancers -------------------------------------------------------

namespace {

uint64_t mix_u64(uint64_t v) {
  v ^= v >> 33;
  v *= 0xff51afd7ed558ccdull;
  v ^= v >> 33;
  return v;
}

// This client's locality label for the zone-preferring balancer.
Flag* zone_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_string(
        "trpc_cluster_zone", "",
        "this client's locality zone for the zone_la balancer: same-"
        "zone members keep their full latency-derived share, members in "
        "a DIFFERENT non-empty zone pay a 4x share penalty ('' = no "
        "preference); max 15 chars (the naming wire zone field)");
    if (flag != nullptr) {
      flag->set_validator(
          [](const std::string& v) { return v.size() <= 15; });
    }
    return flag;
  }();
  return f;
}

// Bounded-load factor for c_hash_bl (Mirrokni et al: consistent hashing
// with bounded loads — ring affinity, but a node already carrying more
// than factor x the mean in-flight load is skipped clockwise).
Flag* chash_load_factor_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_double(
        "trpc_cluster_chash_load_factor", 1.25,
        "bounded-load factor for the c_hash_bl balancer ([1.0, 16.0]): "
        "a ring-preferred node whose in-flight count exceeds factor x "
        "the healthy-set mean is skipped clockwise, trading affinity "
        "for overload diffusion");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const double d = strtod(v.c_str(), &end);
        return end != v.c_str() && *end == '\0' && d >= 1.0 && d <= 16.0;
      });
    }
    return flag;
  }();
  return f;
}

Flag* subset_size_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_cluster_subset_size", 0,
        "deterministic subsetting: each ClusterChannel holds member "
        "channels to at most this many servers (rendezvous-hashed by a "
        "per-process seed, so the fleet's clients spread evenly and "
        "each keeps a STABLE subset across refreshes).  0 = unlimited.  "
        "Mandatory at scale — N clients x M servers full-mesh is what "
        "exhausts the fd budget ([0, 65536])");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 0 && n <= 65536;
      });
    }
    return flag;
  }();
  return f;
}

class RoundRobinLB : public LoadBalancer {
 public:
  size_t select(const std::vector<size_t>& healthy,
                const std::vector<ServerNode>&, uint64_t, int) override {
    return healthy[next_.fetch_add(1, std::memory_order_relaxed) %
                   healthy.size()];
  }

 private:
  std::atomic<uint64_t> next_{0};
};

class RandomLB : public LoadBalancer {
 public:
  size_t select(const std::vector<size_t>& healthy,
                const std::vector<ServerNode>&, uint64_t, int) override {
    return healthy[fast_rand_less_than(healthy.size())];
  }
};

// Ketama-style ring with virtual nodes (parity: policy/
// consistent_hashing_load_balancer — single hash points skew badly on small
// clusters, so each endpoint contributes kReplicas ring points).
class ConsistentHashLB : public LoadBalancer {
 public:
  static constexpr int kReplicas = 32;

  size_t select(const std::vector<size_t>& healthy,
                const std::vector<ServerNode>& nodes, uint64_t key,
                int attempt) override {
    size_t best = healthy[0];
    uint64_t best_dist = UINT64_MAX;
    const uint64_t h = mix_u64(key);
    for (size_t idx : healthy) {
      const uint64_t base = EndPointHash()(nodes[idx].ep);
      for (int r = 0; r < kReplicas; ++r) {
        const uint64_t nh = mix_u64(base + r * 0x9e3779b97f4a7c15ull);
        const uint64_t dist = nh - h;  // wrapping distance clockwise
        if (dist < best_dist) {
          best_dist = dist;
          best = idx;
        }
      }
    }
    if (attempt > 0) {
      return healthy[(std::find(healthy.begin(), healthy.end(), best) -
                      healthy.begin() + attempt) %
                     healthy.size()];
    }
    return best;
  }
};

// Consistent hashing with BOUNDED loads (c_hash_bl): same ketama ring,
// but the clockwise walk skips any node whose live in-flight count
// exceeds trpc_cluster_chash_load_factor x the healthy-set mean — key
// affinity holds while a node is healthy-and-not-hot, and a hotspot
// key's overflow diffuses to the next nodes on the ring instead of
// melting one server (the fabric-serving failure mode plain c_hash has).
class ConsistentHashBoundedLB : public LoadBalancer {
 public:
  size_t select(const std::vector<size_t>& healthy,
                const std::vector<ServerNode>& nodes, uint64_t key,
                int attempt) override {
    // Ring order: every healthy node's minimal clockwise distance.
    const uint64_t h = mix_u64(key);
    std::vector<std::pair<uint64_t, size_t>> order;
    order.reserve(healthy.size());
    int64_t inflight_sum = 0;
    for (size_t idx : healthy) {
      const uint64_t base = EndPointHash()(nodes[idx].ep);
      uint64_t best_dist = UINT64_MAX;
      for (int r = 0; r < ConsistentHashLB::kReplicas; ++r) {
        const uint64_t nh = mix_u64(base + r * 0x9e3779b97f4a7c15ull);
        best_dist = std::min(best_dist, nh - h);  // wrapping clockwise
      }
      order.emplace_back(best_dist, idx);
      // Relaxed: advisory load sample; staleness only softens the bound.
      inflight_sum +=
          nodes[idx].inflight->load(std::memory_order_relaxed);
    }
    std::sort(order.begin(), order.end());
    Flag* f = chash_load_factor_flag();
    const double factor = f != nullptr ? f->double_value() : 1.25;
    // +1: the candidate's own admission counts against the bound, and
    // the ceiling keeps a cold cluster (mean 0) from rejecting everyone.
    const double bound =
        factor * (static_cast<double>(inflight_sum) / healthy.size() + 1);
    // Cache-aware routing (ISSUE 17): a caller-installed hint names the
    // member holding the longest cached prefix.  Honor it on the FIRST
    // attempt only (retries already exclude the tried node) and only
    // while it is under the same bounded-load bound the ring walk
    // enforces — affinity never outranks overload diffusion (veto).
    EndPoint hinted;
    if (attempt == 0 && lb_hint_get(&hinted)) {
      bool found = false;
      for (size_t idx : healthy) {
        if (nodes[idx].ep == hinted) {
          found = true;
          // Relaxed: advisory load sample, see the ring walk below.
          if (nodes[idx].inflight->load(std::memory_order_relaxed) + 1 <=
              bound) {
            lb_hint_counters().bump(lb_hint_counters().hit);
            return idx;
          }
          lb_hint_counters().bump(lb_hint_counters().veto);
          break;
        }
      }
      if (!found) {
        lb_hint_counters().bump(lb_hint_counters().miss);
      }
    }
    const size_t start = static_cast<size_t>(attempt) % order.size();
    // Full wrap from the retry offset: an under-bound node earlier in
    // ring order must stay reachable on retries, or the walk would hand
    // a retry to an over-bound node while an idle one exists.
    for (size_t i = 0; i < order.size(); ++i) {
      const size_t idx = order[(start + i) % order.size()].second;
      // Relaxed: see above.
      if (nodes[idx].inflight->load(std::memory_order_relaxed) + 1 <=
          bound) {
        return idx;
      }
    }
    // Every node over the bound (burst): ring-preferred wins anyway.
    return order[start].second;
  }
};

// Weighted round robin: node i is picked weight_i times per cycle,
// interleaved (parity: policy/weighted_round_robin_load_balancer.*,
// condensed to the smooth-wrr scheme).
class WeightedRoundRobinLB : public LoadBalancer {
 public:
  size_t select(const std::vector<size_t>& healthy,
                const std::vector<ServerNode>& nodes, uint64_t,
                int) override {
    // Smooth WRR over the healthy subset using a stateless stride: walk
    // the cumulative weights with an incrementing cursor.
    int64_t total = 0;
    for (size_t idx : healthy) {
      total += std::max(1, nodes[idx].weight);
    }
    int64_t tick = static_cast<int64_t>(
        cursor_.fetch_add(1, std::memory_order_relaxed) % total);
    for (size_t idx : healthy) {
      tick -= std::max(1, nodes[idx].weight);
      if (tick < 0) {
        return idx;
      }
    }
    return healthy.back();
  }

 private:
  std::atomic<uint64_t> cursor_{0};
};

// Power-of-two-choices with EWMA latency x in-flight scoring (parity:
// policy/p2c_ewma and the locality-aware balancer's latency/load feedback
// tree, condensed: same feedback signals, two-probe selection).
class P2cEwmaLB : public LoadBalancer {
 public:
  size_t select(const std::vector<size_t>& healthy,
                const std::vector<ServerNode>& nodes, uint64_t,
                int attempt) override {
    if (healthy.size() == 1) {
      return healthy[0];
    }
    const size_t a = healthy[fast_rand_less_than(healthy.size())];
    size_t b = healthy[fast_rand_less_than(healthy.size())];
    if (a == b) {
      b = healthy[(std::find(healthy.begin(), healthy.end(), a) -
                   healthy.begin() + 1 + attempt) %
                  healthy.size()];
    }
    return score(nodes[a]) <= score(nodes[b]) ? a : b;
  }

 private:
  static int64_t score(const ServerNode& n) {
    // Untried nodes (ewma 0) score lowest so every node gets probed.
    const int64_t lat = n.ewma_latency_us->load(std::memory_order_relaxed);
    const int64_t load = n.inflight->load(std::memory_order_relaxed) + 1;
    return lat * load / std::max(1, n.weight);
  }
};

// Locality-aware: weighted random over every node's expected quality,
// where weight ~ 1 / (ewma_latency x (1 + inflight) x error-deceleration)
// (parity: policy/locality_aware_load_balancer.h:41 — same signals and
// semantics: requests iterate toward lowest-expected-latency servers,
// errors collapse a node's share sharply, recovery re-earns it).
// Redesigned at altitude: the reference's partial-sum weight tree buys
// O(log n) selection for thousand-node clusters; at this runtime's
// cluster sizes an O(n) scan over the healthy subset is cheaper than the
// tree's bookkeeping, so the SAME weights feed a direct weighted pick.
// zone_la extension: constructed with this client's zone, the same
// latency/load/error weights additionally pay kZonePenalty when the
// member sits in a DIFFERENT non-empty zone — traffic prefers local
// replicas while remote ones stay warm enough to absorb a zone failure
// (locality-aware parity, locality made literal).
class LocalityAwareLB : public LoadBalancer {
 public:
  explicit LocalityAwareLB(std::string my_zone = "")
      : my_zone_(std::move(my_zone)) {}

  size_t select(const std::vector<size_t>& healthy,
                const std::vector<ServerNode>& nodes, uint64_t,
                int) override {
    if (healthy.size() == 1) {
      return healthy[0];
    }
    // Pass 1: per-node QUALITY (latency x load x error deceleration) for
    // nodes with history, tracking the mean so untried nodes (ewma 0)
    // enter at quality parity — every node gets probed without handing
    // newcomers the whole cluster.  Static weights multiply at the end
    // so a newcomer's configured share is respected too.
    int64_t quality[kMaxScan];
    const size_t n = std::min(healthy.size(), kMaxScan);
    int64_t tried_sum = 0;
    size_t tried = 0;
    for (size_t i = 0; i < n; ++i) {
      const ServerNode& node = nodes[healthy[i]];
      const int64_t lat =
          node.ewma_latency_us->load(std::memory_order_relaxed);
      if (lat == 0) {
        quality[i] = -1;  // untried: filled in pass 2
        continue;
      }
      const int64_t inflight =
          node.inflight->load(std::memory_order_relaxed);
      const int64_t fails =
          node.consecutive_failures->load(std::memory_order_relaxed);
      // Deceleration: each consecutive error quarters the share again;
      // one success resets fails and the node re-earns weight from its
      // (still-remembered) latency.
      int64_t q = kScale / (lat * (1 + inflight));
      q >>= std::min<int64_t>(fails * 2, 30);
      q = std::max<int64_t>(q, kMinWeight);
      quality[i] = q;
      tried_sum += q;
      ++tried;
    }
    const int64_t newcomer =
        tried == 0 ? kScale / 1000 : tried_sum / static_cast<int64_t>(tried);
    int64_t weights[kMaxScan];
    for (size_t i = 0; i < n; ++i) {
      int64_t q = quality[i] >= 0
                      ? quality[i]
                      : std::max<int64_t>(newcomer, kMinWeight);
      // Zone preference: penalize only a KNOWN-remote member (both
      // zones non-empty and different) — unlabeled members ride at par
      // so a partially-labeled fleet degrades to plain la, not to
      // starving the unlabeled half.
      const std::string& nz = nodes[healthy[i]].zone;
      if (!my_zone_.empty() && !nz.empty() && nz != my_zone_) {
        q = std::max<int64_t>(q / kZonePenalty, kMinWeight);
      }
      weights[i] = q * std::max(1, nodes[healthy[i]].weight);
    }
    return healthy[weighted_pick(weights, n)];
  }

 private:
  static constexpr size_t kMaxScan = 1024;  // bound the stack scan
  static constexpr int64_t kScale = 1ll << 40;
  static constexpr int64_t kMinWeight = 16;  // floor (min_weight parity)
  static constexpr int64_t kZonePenalty = 4;
  const std::string my_zone_;
};

// Routing-hint outcome vars (net/lb_hint.h): dashboards read the
// hit/veto split to judge whether cache-aware routing is actually
// landing on prefix owners or being load-vetoed back onto the ring.
struct LbHintVars {
  std::unique_ptr<PassiveStatus<long>> hit;
  std::unique_ptr<PassiveStatus<long>> veto;
  std::unique_ptr<PassiveStatus<long>> miss;
  LbHintVars() {
    hit = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          LbHintCounters::read(lb_hint_counters().hit));
    });
    hit->expose("lb_hint_hit_total",
                "cluster calls routed to their cache-affinity hint (the "
                "hinted member was healthy and under the c_hash_bl "
                "bounded-load bound)");
    veto = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          LbHintCounters::read(lb_hint_counters().veto));
    });
    veto->expose(
        "lb_hint_veto_total",
        "cluster calls whose cache-affinity hint was VETOED by the "
        "bounded-load check (hinted member over factor x mean in-flight) "
        "and fell back to the ring walk");
    miss = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          LbHintCounters::read(lb_hint_counters().miss));
    });
    miss->expose(
        "lb_hint_miss_total",
        "cluster calls whose cache-affinity hint named a member not in "
        "the healthy set (drained, quarantined, or gone) — routed by "
        "the plain ring walk");
  }
};

LbHintVars& lb_hint_vars() {
  static LbHintVars* v = new LbHintVars();
  return *v;
}

}  // namespace

LbHintCounters& lb_hint_counters() {
  static LbHintCounters* c = new LbHintCounters();
  return *c;
}

void cluster_ensure_registered() {
  zone_flag();
  chash_load_factor_flag();
  subset_size_flag();
  lb_hint_vars();
}

int64_t asym_ewma(int64_t prev, int64_t sample) {
  if (prev == 0) {
    return sample;
  }
  if (sample < prev) {
    return (prev + sample * 3) / 4;  // improvements take hold fast
  }
  return (prev * 7 + sample) / 8;  // degradations blend in slowly
}

size_t weighted_pick(const int64_t* weights, size_t n) {
  int64_t total = 0;
  for (size_t i = 0; i < n; ++i) {
    total += weights[i];
  }
  int64_t dice = static_cast<int64_t>(
      fast_rand_less_than(static_cast<uint64_t>(std::max<int64_t>(total,
                                                                  1))));
  for (size_t i = 0; i < n; ++i) {
    dice -= weights[i];
    if (dice < 0) {
      return i;
    }
  }
  return n - 1;
}

LoadBalancer* LoadBalancer::create(const std::string& name) {
  if (name == "rr" || name.empty()) {
    return new RoundRobinLB();
  }
  if (name == "random") {
    return new RandomLB();
  }
  if (name == "c_hash") {
    return new ConsistentHashLB();
  }
  if (name == "c_hash_bl") {
    chash_load_factor_flag();  // register before first /flags read
    return new ConsistentHashBoundedLB();
  }
  if (name == "wrr") {
    return new WeightedRoundRobinLB();
  }
  if (name == "p2c") {
    return new P2cEwmaLB();
  }
  if (name == "la") {
    return new LocalityAwareLB();
  }
  if (name == "zone_la") {
    Flag* f = zone_flag();
    return new LocalityAwareLB(f != nullptr ? f->string_value() : "");
  }
  return nullptr;
}

// ---- naming services ------------------------------------------------------

namespace {

int parse_server_list(const std::string& text,
                      std::vector<NsEntry>* out) {
  std::stringstream ss(text);
  std::string token;
  while (std::getline(ss, token, ',')) {
    // Trim whitespace/newlines.
    const size_t b = token.find_first_not_of(" \t\r\n");
    const size_t e = token.find_last_not_of(" \t\r\n");
    if (b == std::string::npos) {
      continue;
    }
    token = token.substr(b, e - b + 1);
    // Optional "host:port <weight> <zone>" columns (file-NS parity: the
    // weight feeds wrr/p2c, the zone feeds zone_la).
    NsEntry entry;
    size_t sp = token.find_first_of(" \t");
    if (sp != std::string::npos) {
      std::stringstream cols(token.substr(sp + 1));
      std::string w, z;
      cols >> w >> z;
      entry.weight = std::max(1, atoi(w.c_str()));
      entry.zone = z;
      token = token.substr(0, sp);
    }
    if (hostname2endpoint(token.c_str(), &entry.ep) == 0) {
      out->push_back(std::move(entry));
    } else {
      LOG(Warning) << "bad server '" << token << "' in list";
    }
  }
  return out->empty() ? -1 : 0;
}

class ListNS : public NamingService {
 public:
  int resolve(const std::string& param,
              std::vector<NsEntry>* out) override {
    return parse_server_list(param, out);
  }
};

// One server per line (or comma separated), re-read each refresh.
class FileNS : public NamingService {
 public:
  int resolve(const std::string& param,
              std::vector<NsEntry>* out) override {
    std::ifstream in(param);
    if (!in) {
      return -1;
    }
    std::stringstream buf;
    buf << in.rdbuf();
    std::string text = buf.str();
    for (char& c : text) {
      if (c == '\n') {
        c = ',';
      }
    }
    return parse_server_list(text, out);
  }
};

// dns://host:port — getaddrinfo resolution of EVERY address behind the
// name, re-resolved on each refresher cycle (parity: the http:// DNS
// naming service + details/naming_service_thread periodic re-resolve).
class DnsNS : public NamingService {
 public:
  int resolve(const std::string& param,
              std::vector<NsEntry>* out) override {
    const size_t colon = param.rfind(':');
    if (colon == std::string::npos) {
      return -1;
    }
    const std::string host = param.substr(0, colon);
    const std::string port = param.substr(colon + 1);
    addrinfo hints;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) {
      return -1;
    }
    for (addrinfo* p = res; p != nullptr; p = p->ai_next) {
      const auto* sa = reinterpret_cast<sockaddr_in*>(p->ai_addr);
      NsEntry entry;
      entry.ep.ip = sa->sin_addr.s_addr;
      entry.ep.port = ntohs(sa->sin_port);
      out->push_back(std::move(entry));
    }
    freeaddrinfo(res);
    return out->empty() ? -1 : 0;
  }
};

// naming://registry_host:port/service — the in-repo naming service
// (net/naming.h): members announced into the registry resolve with
// their zone/weight, and watch() long-polls the registry so membership
// deltas PUSH into the cluster channel instead of waiting a refresh
// tick.  One channel to the registry, shared by resolve and watch (the
// tstd connection multiplexes; a parked watch never blocks a resolve).
class RegistryNS : public NamingService {
 public:
  int resolve(const std::string& param,
              std::vector<NsEntry>* out) override {
    std::vector<NamingMember> members;
    {
      // A watch() answer already carried the full member view; the
      // refresh it triggers consumes it here (one-shot) instead of
      // paying a second Naming.Resolve round-trip per push.
      std::lock_guard<std::mutex> g(mu_);
      if (pushed_valid_) {
        members = std::move(pushed_view_);
        pushed_view_.clear();
        pushed_valid_ = false;
      }
    }
    if (members.empty()) {
      Channel* ch = channel(param);
      if (ch == nullptr) {
        return -1;
      }
      uint64_t version = 0;
      if (naming_resolve(ch, service_of(param), &members, &version) !=
          0) {
        return -1;
      }
    }
    for (const NamingMember& m : members) {
      NsEntry entry;
      if (hostname2endpoint(m.addr.c_str(), &entry.ep) != 0) {
        LOG(Warning) << "bad member addr '" << m.addr << "' in naming view";
        continue;
      }
      entry.weight = std::max<int>(m.weight, 1);
      entry.zone = m.zone;
      out->push_back(std::move(entry));
    }
    return out->empty() ? -1 : 0;
  }

  int watch(const std::string& param, uint64_t* version,
            int64_t park_budget_ms) override {
    Channel* ch = channel(param);
    if (ch == nullptr) {
      return -1;
    }
    const uint64_t before = version != nullptr ? *version : 0;
    std::vector<NamingMember> members;
    const int rc = naming_watch(ch, service_of(param), &members, version,
                                park_budget_ms, park_budget_ms + 2000);
    if (rc == 0 && version != nullptr && *version != before) {
      // Stash the pushed view for the refresh this answer triggers.
      std::lock_guard<std::mutex> g(mu_);
      pushed_view_ = std::move(members);
      pushed_valid_ = true;
    }
    return rc;
  }

  bool supports_watch() const override { return true; }

 private:
  static std::string addr_of(const std::string& param) {
    return param.substr(0, param.find('/'));
  }
  static std::string service_of(const std::string& param) {
    const size_t slash = param.find('/');
    return slash == std::string::npos ? "default" : param.substr(slash + 1);
  }
  Channel* channel(const std::string& param) {
    std::lock_guard<std::mutex> g(mu_);
    if (ch_ == nullptr) {
      auto ch = std::make_unique<Channel>();
      Channel::Options opts;
      opts.timeout_ms = 2000;
      if (ch->Init(addr_of(param), &opts) != 0) {
        return nullptr;
      }
      ch_ = std::move(ch);
    }
    return ch_.get();
  }
  std::mutex mu_;
  std::unique_ptr<Channel> ch_;
  // One-shot view handed from watch() to the resolve() it triggers.
  std::vector<NamingMember> pushed_view_;
  bool pushed_valid_ = false;
};

}  // namespace

std::unique_ptr<NamingService> NamingService::create(const std::string& url,
                                                     std::string* param) {
  if (url.rfind("list://", 0) == 0) {
    *param = url.substr(7);
    return std::make_unique<ListNS>();
  }
  if (url.rfind("file://", 0) == 0) {
    *param = url.substr(7);
    return std::make_unique<FileNS>();
  }
  if (url.rfind("dns://", 0) == 0) {
    *param = url.substr(6);
    return std::make_unique<DnsNS>();
  }
  if (url.rfind("naming://", 0) == 0) {
    *param = url.substr(9);  // "registry_host:port/service"
    return std::make_unique<RegistryNS>();
  }
  // Bare "host:port" degenerates to a one-server list.
  *param = url;
  return std::make_unique<ListNS>();
}

// ---- ClusterChannel -------------------------------------------------------

ClusterChannel::~ClusterChannel() {
  stopping_.store(true, std::memory_order_release);
  if (watcher_started_.load(std::memory_order_acquire)) {
    // Wake + join the naming watch fiber first (it may be parked inside
    // a long-poll RPC; its bounded park budget caps this wait).
    watch_wake_.value.fetch_add(1, std::memory_order_release);
    watch_wake_.wake_all();
    while (watch_done_.value.load(std::memory_order_acquire) == 0) {
      watch_done_.wait(0, -1);
    }
    while (!watcher_exited_.load(std::memory_order_acquire)) {
      sched_yield();
    }
  }
  if (refresher_started_.load(std::memory_order_acquire)) {
    // Wake the refresher out of its sleep and wait for it to exit — it
    // holds `this`, so destruction must not race it.
    refresh_wake_.value.fetch_add(1, std::memory_order_release);
    refresh_wake_.wake_all();
    while (refresh_done_.value.load(std::memory_order_acquire) == 0) {
      refresh_done_.wait(0, -1);
    }
    // The wake that satisfied us may still be INSIDE refresh_done_.wake_all
    // touching the Event; spin until the fiber's final store says it is
    // completely done with this object.
    while (!refresher_exited_.load(std::memory_order_acquire)) {
      sched_yield();
    }
  }
}

int ClusterChannel::Init(const std::string& naming_url,
                         const std::string& lb_name, const Options* opts) {
  if (opts != nullptr) {
    opts_ = *opts;
  }
  lb_.reset(LoadBalancer::create(lb_name));
  if (lb_ == nullptr) {
    return -1;
  }
  ns_ = NamingService::create(naming_url, &ns_param_);
  return refresh();
}

int ClusterChannel::refresh() {
  std::vector<NsEntry> eps;
  if (ns_->resolve(ns_param_, &eps) != 0) {
    return -1;
  }
  // Deterministic subsetting (fd-budget discipline): rendezvous-hash
  // every member against this client's seed and keep the top-k.  The
  // same (seed, member) pair always scores the same, so a member
  // add/remove perturbs the subset minimally and a plain refresh never
  // churns connections; different seeds (default: pid) spread the
  // fleet's clients evenly over the servers.
  int64_t subset = opts_.subset_size;
  if (subset == 0) {
    Flag* f = subset_size_flag();
    subset = f != nullptr ? f->int64_value() : 0;
  }
  if (subset > 0 && eps.size() > static_cast<size_t>(subset)) {
    // The seed is PRE-mixed: small consecutive seeds (pids) xor'd raw
    // into an avalanched endpoint hash barely perturb the final mix's
    // ordering, and every client would elect the same subset.
    const uint64_t seed = mix_u64(opts_.subset_seed != 0
                                      ? opts_.subset_seed
                                      : static_cast<uint64_t>(getpid()));
    std::stable_sort(eps.begin(), eps.end(),
                     [seed](const NsEntry& a, const NsEntry& b) {
                       return mix_u64(seed ^ EndPointHash()(a.ep)) >
                              mix_u64(seed ^ EndPointHash()(b.ep));
                     });
    eps.resize(static_cast<size_t>(subset));
  }
  // Preserve breaker state + channels of endpoints that survive.
  auto fresh = std::make_shared<Cluster>();
  {
    auto cur = cluster_.Read();
    const Cluster* old = cur->get();
    for (const auto& [ep, weight, zone] : eps) {
      ServerNode node;
      node.ep = ep;
      node.weight = weight;
      node.zone = zone;
      std::shared_ptr<Channel> ch;
      if (old != nullptr) {
        for (size_t i = 0; i < old->nodes.size(); ++i) {
          if (old->nodes[i].ep == ep) {
            node = old->nodes[i];
            node.weight = weight;  // refresh may re-weight...
            node.zone = zone;      // ...and re-label
            ch = old->channels[i];
            break;
          }
        }
      }
      if (ch == nullptr) {
        ch = std::make_shared<Channel>();
        Channel::Options copts;
        copts.timeout_ms = opts_.timeout_ms;
        copts.connection_type = opts_.connection_type;
        copts.auth = opts_.auth;
        copts.protocol = opts_.protocol;
        {
          std::lock_guard<std::mutex> qg(qos_mu_);
          copts.qos_tenant = opts_.qos_tenant;
          copts.qos_priority = opts_.qos_priority;
        }
        if (ch->Init(endpoint2str(ep), &copts) != 0) {
          continue;
        }
      }
      fresh->nodes.push_back(std::move(node));
      fresh->channels.push_back(std::move(ch));
    }
  }
  if (fresh->nodes.empty()) {
    return -1;
  }
  cluster_.Modify([&fresh](std::shared_ptr<Cluster>& c) {
    c = fresh;
    return true;
  });
  // Start the periodic refresher once.
  bool expect = false;
  if (refresher_started_.compare_exchange_strong(expect, true)) {
    fiber_init(0);
    fiber_start(nullptr, &ClusterChannel::refresh_fiber, this, 0);
  }
  // Push-based membership: when the NS can long-poll, a watch fiber
  // turns registry version bumps into immediate refreshes (the periodic
  // refresher stays as the poll fallback / health-check cadence).
  if (ns_->supports_watch()) {
    expect = false;
    if (watcher_started_.compare_exchange_strong(expect, true)) {
      if (fiber_start(nullptr, &ClusterChannel::watch_fiber, this, 0) !=
          0) {
        // Spawn failed: keep watcher_started_ TRUE and settle the join
        // state the destructor waits on.  Resetting the flag would let a
        // later refresh() (possibly racing the destructor) spawn a
        // watcher the destructor never joins — push degrades to the
        // periodic poll instead.
        watch_done_.value.store(1, std::memory_order_release);
        watch_done_.wake_all();
        watcher_exited_.store(true, std::memory_order_release);
      }
    }
  }
  return 0;
}

void ClusterChannel::watch_fiber(void* arg) {
  auto* self = static_cast<ClusterChannel*>(arg);
  uint64_t version = 0;
  while (!self->stopping_.load(std::memory_order_acquire)) {
    const uint64_t before = version;
    // Bounded park budget per round: a change still answers IMMEDIATELY
    // (the registry wakes the parked handler); the budget only caps how
    // long the destructor can be stuck behind an idle poll.
    const int rc = self->ns_->watch(self->ns_param_, &version, 1000);
    if (self->stopping_.load(std::memory_order_acquire)) {
      break;
    }
    if (rc == 0) {
      if (version != before) {
        self->refresh();  // push delivery: apply the delta NOW
      }
      continue;
    }
    // Registry unreachable (or watch unsupported after all): back off
    // briefly, interruptibly; the periodic refresher keeps polling.
    const uint32_t snap =
        self->watch_wake_.value.load(std::memory_order_acquire);
    self->watch_wake_.wait(snap, monotonic_time_us() + 500000);
  }
  self->watch_done_.value.store(1, std::memory_order_release);
  self->watch_done_.wake_all();
  // LAST access to *self (see ~ClusterChannel).
  self->watcher_exited_.store(true, std::memory_order_release);
}

void ClusterChannel::set_default_qos(const std::string& tenant,
                                     uint8_t priority) {
  std::string capped = tenant.size() > 64 ? tenant.substr(0, 64) : tenant;
  {
    std::lock_guard<std::mutex> qg(qos_mu_);
    opts_.qos_tenant = capped;
    opts_.qos_priority = priority;
  }
  std::shared_ptr<Cluster> cluster;
  {
    auto cur = cluster_.Read();
    cluster = *cur;
  }
  if (cluster != nullptr) {
    for (const auto& ch : cluster->channels) {
      ch->set_default_qos(capped, priority);
    }
  }
}

void ClusterChannel::refresh_fiber(void* arg) {
  auto* self = static_cast<ClusterChannel*>(arg);
  while (!self->stopping_.load(std::memory_order_acquire)) {
    // Interruptible sleep: the destructor bumps refresh_wake_ to end it.
    const uint32_t snap =
        self->refresh_wake_.value.load(std::memory_order_acquire);
    self->refresh_wake_.wait(
        snap, monotonic_time_us() + self->opts_.refresh_interval_ms * 1000);
    if (self->stopping_.load(std::memory_order_acquire)) {
      break;
    }
    self->refresh();       // PeriodicNamingService parity
    self->health_check();  // details/health_check.cpp parity
  }
  self->refresh_done_.value.store(1, std::memory_order_release);
  self->refresh_done_.wake_all();
  // LAST access to *self (see ~ClusterChannel).
  self->refresher_exited_.store(true, std::memory_order_release);
}

namespace {

struct ProbeCtx {
  std::shared_ptr<void> cluster_keepalive;
  std::shared_ptr<Channel> channel;
  std::shared_ptr<std::atomic<int64_t>> quarantined_until;
  std::shared_ptr<std::atomic<int>> fail_counter;
  std::string method;
  int64_t timeout_ms;
  std::shared_ptr<CountdownEvent> latch;
};

void probe_fiber(void* p) {
  std::unique_ptr<ProbeCtx> ctx(static_cast<ProbeCtx*>(p));
  Controller cntl;
  cntl.set_timeout_ms(ctx->timeout_ms);
  IOBuf req, resp;
  ctx->channel->CallMethod(ctx->method, req, &resp, &cntl);
  // ALLOWLIST of "the server definitely answered": success, or the
  // server-side errors a probe legitimately produces (no such method,
  // admission-limited, tenant-shed).  Everything else — including local
  // failures like fid exhaustion — must NOT revive the node.  A
  // kEOverloaded answer proves the TRANSPORT alive (the shed is QoS
  // policy, not node death), so the node revives and the next real call
  // re-judges it.
  // kEDraining joins the allowlist for the same reason as kEOverloaded:
  // a draining node's transport demonstrably works (and its successor
  // revives on this endpoint), so the breaker may open.
  const bool answered = !cntl.Failed() || cntl.error_code() == ENOENT ||
                        cntl.error_code() == kELimit ||
                        cntl.error_code() == kEOverloaded ||
                        cntl.error_code() == kEDraining ||
                        cntl.error_code() == ESHUTDOWN;
  if (answered) {
    ctx->quarantined_until->store(0, std::memory_order_relaxed);
    ctx->fail_counter->store(0, std::memory_order_relaxed);
  }
  ctx->latch->signal();
}

}  // namespace

void ClusterChannel::health_check() {
  if (opts_.health_check_method.empty()) {
    return;
  }
  std::shared_ptr<Cluster> cluster;
  {
    auto cur = cluster_.Read();
    cluster = *cur;
  }
  if (cluster == nullptr) {
    return;
  }
  // Probes fan out concurrently so N blackholed nodes cost one probe
  // timeout per tick, not N (and shutdown isn't stalled behind them).
  const int64_t now = monotonic_time_us();
  std::vector<ProbeCtx*> probes;
  for (size_t i = 0; i < cluster->nodes.size(); ++i) {
    ServerNode& node = cluster->nodes[i];
    if (node.quarantined_until_us->load(std::memory_order_relaxed) <= now) {
      continue;  // healthy (or already expired)
    }
    probes.push_back(new ProbeCtx{cluster, cluster->channels[i],
                                  node.quarantined_until_us,
                                  node.consecutive_failures,
                                  opts_.health_check_method,
                                  opts_.health_check_timeout_ms, nullptr});
  }
  if (probes.empty()) {
    return;
  }
  auto latch =
      std::make_shared<CountdownEvent>(static_cast<int>(probes.size()));
  for (ProbeCtx* p : probes) {
    p->latch = latch;
    if (fiber_start(nullptr, probe_fiber, p, 0) != 0) {
      latch->signal();
      delete p;
    }
  }
  // Sliced wait so a concurrent destructor (stopping_) isn't stalled a full
  // probe timeout behind blackholed nodes; probe fibers own their state via
  // shared_ptrs and finish safely after we stop waiting.
  const int64_t wait_deadline =
      monotonic_time_us() + opts_.health_check_timeout_ms * 1000 + 1000000;
  while (!stopping_.load(std::memory_order_acquire) &&
         monotonic_time_us() < wait_deadline) {
    if (latch->wait(monotonic_time_us() + 50000) == 0) {
      break;
    }
  }
}

size_t ClusterChannel::healthy_count() {
  auto cur = cluster_.Read();
  const Cluster* c = cur->get();
  if (c == nullptr) {
    return 0;
  }
  const int64_t now = monotonic_time_us();
  size_t n = 0;
  for (const ServerNode& node : c->nodes) {
    if (node.quarantined_until_us->load(std::memory_order_relaxed) <= now) {
      ++n;
    }
  }
  return n;
}

namespace {
struct AsyncCall {
  ClusterChannel* ch;
  std::string method;
  IOBuf request;
  IOBuf* response;
  Controller* cntl;
  Closure done;
  uint64_t hash_key;
  // The caller's ambient trace context, captured at submit: the retry
  // fiber has its own (empty) fiber-local storage, so without this the
  // attempt's client span would root a fresh trace instead of linking
  // under the caller's (rpcz propagation, ISSUE 4).
  uint64_t amb_trace = 0;
  uint64_t amb_span = 0;
  // Ambient deadline, same capture rationale (value-only: the caller's
  // cancel scope may die before this detached fiber runs).
  int64_t amb_deadline = 0;
  // Ambient routing hint (net/lb_hint.h), same capture rationale: the
  // retry fiber's thread has no hint installed.
  bool amb_hint_set = false;
  EndPoint amb_hint;
};
}  // namespace

namespace {
// EWMA latency feedback for p2c/la (OnComplete parity, controller.cpp:804).
void feed_latency(ServerNode& node, int64_t lat_us) {
  if (lat_us <= 0) {
    return;
  }
  const int64_t prev =
      node.ewma_latency_us->load(std::memory_order_relaxed);
  node.ewma_latency_us->store(asym_ewma(prev, lat_us),
                              std::memory_order_relaxed);
}
}  // namespace

namespace {
// One retry token in bucket units, and the bucket cap (100 banked
// retries — the SRE convention: the budget bounds a STORM, it never
// starves the occasional isolated retry).
constexpr int64_t kRetryTokenCost = 100;
constexpr int64_t kRetryTokenCap = 100 * kRetryTokenCost;
}  // namespace

void ClusterChannel::retry_budget_earn() {
  const int64_t pct = cluster_retry_budget_pct();
  if (pct <= 0) {
    return;  // budget off
  }
  // Relaxed CAS loop: the bucket is advisory rate-limiting state — no
  // data is published through it.
  int64_t cur = retry_tokens_.load(std::memory_order_relaxed);
  while (cur < kRetryTokenCap) {
    const int64_t next = std::min(cur + pct, kRetryTokenCap);
    if (retry_tokens_.compare_exchange_weak(cur, next,
                                            std::memory_order_relaxed)) {
      break;
    }
  }
}

bool ClusterChannel::retry_budget_take() {
  if (cluster_retry_budget_pct() <= 0) {
    return true;  // budget off: pre-budget retry semantics
  }
  // Relaxed: see retry_budget_earn.
  int64_t cur = retry_tokens_.load(std::memory_order_relaxed);
  while (cur >= kRetryTokenCost) {
    if (retry_tokens_.compare_exchange_weak(cur, cur - kRetryTokenCost,
                                            std::memory_order_relaxed)) {
      return true;
    }
  }
  return false;
}

void ClusterChannel::feed_cluster_latency(int64_t lat_us) {
  if (lat_us <= 0) {
    return;
  }
  // Relaxed: advisory smoothing state (hedge feasibility estimate).
  const int64_t prev = lat_ewma_us_.load(std::memory_order_relaxed);
  lat_ewma_us_.store(asym_ewma(prev, lat_us), std::memory_order_relaxed);
}

void ClusterChannel::feed_breaker(ServerNode& node, bool success) {
  if (success) {
    node.consecutive_failures->store(0, std::memory_order_relaxed);
    // Relaxed: advisory backoff state, no ordering carried.
    node.backoff_ms->store(0, std::memory_order_relaxed);
    return;
  }
  node.consecutive_failures->fetch_add(1, std::memory_order_relaxed);
  // Decorrelated jitter (AWS-style: window ~ U[base, min(cap, prev*3)]),
  // drawn from the FaultActor splitmix64 SIDE stream so a seeded chaos
  // schedule replays the identical backoff sequence.  Plain doubling
  // synchronized every client that watched the same node die — they all
  // re-probed the reviving node in lockstep, re-knocking it over.
  // Relaxed: advisory backoff state, no ordering carried.
  const int64_t prev = node.backoff_ms->load(std::memory_order_relaxed);
  const int64_t base = std::max<int64_t>(opts_.quarantine_base_ms, 1);
  const int64_t hi = std::min(opts_.quarantine_max_ms,
                              std::max(prev * 3, base));
  int64_t quarantine_ms = base;
  if (hi > base) {
    quarantine_ms +=
        static_cast<int64_t>(FaultActor::global().jitter_draw() %
                             static_cast<uint64_t>(hi - base + 1));
  }
  // Relaxed: see above.
  node.backoff_ms->store(quarantine_ms, std::memory_order_relaxed);
  node.quarantined_until_us->store(monotonic_time_us() + quarantine_ms * 1000,
                                   std::memory_order_relaxed);
}

namespace {

// Shared state of one hedged call; attempt fibers keep it alive past the
// caller (a losing attempt may still be in flight when the call returns).
struct HedgeCtx {
  std::shared_ptr<void> cluster_keepalive;
  std::string method;
  IOBuf request;
  IOBuf attachment;
  std::shared_ptr<Channel> channels[2];
  size_t node_idx[2] = {0, 0};
  Controller cntls[2];
  IOBuf responses[2];
  // An attempt's cntls[i]/responses[i] may only be read after done[i]
  // (release-stored when its fiber finished writing them).
  std::atomic<bool> done[2] = {{false}, {false}};
  // False when the attempt never ran (fiber spawn failed): its synthetic
  // EAGAIN must not shadow a real error from the other attempt.
  bool spawned[2] = {true, true};
  std::atomic<int> winner{-1};   // first successful attempt index
  std::atomic<int> failures{0};
  std::atomic<int> launched{1};
  Event ev;  // bumped on every attempt completion
  // Caller's ambient trace context (attempt fibers have empty fls).
  uint64_t amb_trace = 0;
  uint64_t amb_span = 0;
  // Caller's ambient deadline, re-installed in each attempt fiber so
  // the wire stamp carries the caller's REMAINING budget, not a fresh
  // full timeout.  Value-only: the caller's cancel scope is not
  // propagated — a losing attempt may outlive the serving request, and
  // the scope's lifetime is bounded by it (net/deadline.h).
  int64_t amb_deadline = 0;

  bool settled() const {
    return winner.load(std::memory_order_acquire) >= 0 ||
           failures.load(std::memory_order_acquire) >=
               launched.load(std::memory_order_acquire);
  }

  void on_attempt_done(int i) {
    done[i].store(true, std::memory_order_release);
    if (!cntls[i].Failed()) {
      int expect = -1;
      winner.compare_exchange_strong(expect, i);
    } else {
      failures.fetch_add(1, std::memory_order_acq_rel);
    }
    ev.value.fetch_add(1, std::memory_order_release);
    ev.wake_all();
  }
};

struct HedgeFiberArg {
  std::shared_ptr<HedgeCtx> ctx;
  int index;
};

void hedge_attempt_fiber(void* p) {
  std::unique_ptr<HedgeFiberArg> arg(static_cast<HedgeFiberArg*>(p));
  HedgeCtx* ctx = arg->ctx.get();
  const int i = arg->index;
  // Both racing attempts carry the caller's trace: their spans show up
  // side by side under one parent in /rpcz (hedges are exactly the kind
  // of tail behavior a timeline exists to expose).
  set_ambient_trace(ctx->amb_trace, ctx->amb_span);
  set_ambient_deadline(ctx->amb_deadline);
  ctx->channels[i]->CallMethod(ctx->method, ctx->request,
                               &ctx->responses[i], &ctx->cntls[i]);
  ctx->on_attempt_done(i);
}

void wait_settled(HedgeCtx* ctx, int64_t deadline_us) {
  while (!ctx->settled()) {
    const uint32_t snap = ctx->ev.value.load(std::memory_order_acquire);
    if (ctx->settled()) {
      break;
    }
    if (ctx->ev.wait(snap, deadline_us) == ETIMEDOUT) {
      break;
    }
  }
}

}  // namespace

// Hedged execution: fire the primary, and if it hasn't answered within
// backup_request_ms (or failed outright), race a backup on a different
// node; the first success wins and the loser's late response dies on its
// stale correlation id — the same guarantee that makes brpc's backup
// requests safe (channel.cpp:582-603).
std::atomic<int> test_fail_hedge_spawns{0};

void ClusterChannel::call_hedged(std::shared_ptr<Cluster> cluster,
                                 const std::string& method,
                                 const IOBuf& request, IOBuf* response,
                                 Controller* cntl, uint64_t hash_key) {
  const int64_t now = monotonic_time_us();
  std::vector<size_t> healthy;
  for (size_t i = 0; i < cluster->nodes.size(); ++i) {
    if (cluster->nodes[i].quarantined_until_us->load(
            std::memory_order_relaxed) <= now) {
      healthy.push_back(i);
    }
  }
  if (healthy.empty()) {
    for (size_t i = 0; i < cluster->nodes.size(); ++i) {
      healthy.push_back(i);
    }
  }
  // Reset per-call state on the caller's controller, preserving the
  // attachment (mirrors the retry path's contract).  The caller's own
  // timeout takes precedence over the channel default (as in the
  // reference, where the controller wins over ChannelOptions).
  const int64_t eff_timeout_ms = cntl->timeout_ms_or(opts_.timeout_ms);
  IOBuf attachment = cntl->request_attachment();
  cntl->Reset();
  cntl->request_attachment() = attachment;

  auto ctx = std::make_shared<HedgeCtx>();
  ctx->cluster_keepalive = cluster;
  ctx->method = method;
  ctx->request = request;  // zero-copy share
  ctx->attachment = attachment;
  get_ambient_trace(&ctx->amb_trace, &ctx->amb_span);
  ctx->amb_deadline = ambient_deadline();
  retry_budget_earn();  // the primary attempt funds the bucket

  auto arm = [&](int slot, size_t node_idx) {
    ctx->channels[slot] = cluster->channels[node_idx];
    ctx->node_idx[slot] = node_idx;
    ctx->cntls[slot].set_timeout_ms(eff_timeout_ms);
    ctx->cntls[slot].set_request_compress_type(cntl->request_compress_type());
    ctx->cntls[slot].set_enable_checksum(cntl->checksum_enabled());
    if (cntl->qos_set()) {
      // Per-call tag outranks the member channels' default on BOTH
      // racing attempts (the retry loop keeps the caller's controller,
      // so it propagates there for free).
      ctx->cntls[slot].set_qos(cntl->qos_tenant(), cntl->qos_priority());
    }
    ctx->cntls[slot].request_attachment() = ctx->attachment;
    auto* arg = new HedgeFiberArg{ctx, slot};
    bool inject = false;
    int cur = test_fail_hedge_spawns.load(std::memory_order_relaxed);
    while (cur > 0 &&
           !test_fail_hedge_spawns.compare_exchange_weak(cur, cur - 1)) {
    }
    inject = cur > 0;
    if (inject || fiber_start(nullptr, hedge_attempt_fiber, arg, 0) != 0) {
      // A failed spawn must still settle the slot, or wait_settled(-1)
      // blocks forever (mirrors run_fanout's spawn-failure path).
      delete arg;
      ctx->spawned[slot] = false;
      ctx->cntls[slot].SetFailed(EAGAIN, "fiber_start failed");
      ctx->on_attempt_done(slot);
    }
  };

  const size_t primary = lb_->select(healthy, cluster->nodes, hash_key, 0);
  arm(0, primary);
  wait_settled(ctx.get(), now + opts_.backup_request_ms * 1000);

  if (ctx->winner.load(std::memory_order_acquire) < 0) {
    // Slow or failed primary: race a backup on another node if one exists.
    std::vector<size_t> others;
    for (size_t i : healthy) {
      if (i != primary) {
        others.push_back(i);
      }
    }
    // Hedge governance (net/deadline.h): a backup is pure extra load
    // when the remaining budget cannot cover a typical attempt (the
    // cluster's observed smoothed latency), and it spends a retry
    // token like any other extra attempt.
    bool allow = !others.empty();
    if (allow) {
      // Relaxed: advisory estimate (see feed_cluster_latency).
      const int64_t p50 = lat_ewma_us_.load(std::memory_order_relaxed);
      int64_t remaining = INT64_MAX;
      if (eff_timeout_ms > 0) {
        remaining = now + eff_timeout_ms * 1000 - monotonic_time_us();
      }
      if (ctx->amb_deadline != 0) {
        remaining = std::min(remaining,
                             ctx->amb_deadline - monotonic_time_us());
      }
      if (p50 > 0 && remaining < p50) {
        allow = false;
        deadline_vars().hedge_suppressed << 1;
        if (timeline::enabled()) {
          timeline::record(
              timeline::kDeadline, 0,
              (timeline::kDeadlineHedgeSuppressed << 56) |
                  static_cast<uint64_t>(remaining > 0 ? remaining : 0));
        }
      } else if (!retry_budget_take()) {
        allow = false;
        deadline_vars().hedge_suppressed << 1;
        if (timeline::enabled()) {
          timeline::record(timeline::kDeadline, 0,
                           timeline::kDeadlineRetrySuppressed << 56);
        }
      }
    }
    if (allow) {
      ctx->launched.store(2, std::memory_order_release);
      arm(1, lb_->select(others, cluster->nodes, hash_key, 1));
    }
    wait_settled(ctx.get(), -1);
  }

  const int w = ctx->winner.load(std::memory_order_acquire);
  // Breaker feedback: judge only attempts that COMPLETED (done[i] is the
  // release barrier for their controllers; a still-flying loser is not
  // touched — its late completion only writes ctx, which the fibers keep
  // alive via shared_ptr).  A failed primary a backup rescued still counts
  // against the primary's node.
  for (int i = 0; i < 2; ++i) {
    if (ctx->channels[i] == nullptr ||
        !ctx->done[i].load(std::memory_order_acquire)) {
      continue;
    }
    if (ctx->cntls[i].Failed() &&
        (ctx->cntls[i].error_code() == kEDraining ||
         ctx->cntls[i].error_code() == kEDeadlineExpired ||
         ctx->cntls[i].error_code() == ECANCELED)) {
      // Graceful leave / expired budget / cancelled caller: the node is
      // healthy either way — quarantining it would punish it for the
      // caller's clock.
      continue;
    }
    feed_breaker(cluster->nodes[ctx->node_idx[i]], !ctx->cntls[i].Failed());
    if (!ctx->cntls[i].Failed()) {
      feed_latency(cluster->nodes[ctx->node_idx[i]],
                   ctx->cntls[i].latency_us());
      feed_cluster_latency(ctx->cntls[i].latency_us());
    }
  }
  if (w < 0) {
    // Prefer an attempt that actually ran; among those, the backup's
    // (fresher) error, matching the reference's last-error reporting.
    int chosen = ctx->done[1].load(std::memory_order_acquire) ? 1 : 0;
    if (!ctx->spawned[chosen] &&
        ctx->done[1 - chosen].load(std::memory_order_acquire) &&
        ctx->spawned[1 - chosen]) {
      chosen = 1 - chosen;
    }
    cntl->SetFailed(ctx->cntls[chosen].error_code(),
                    ctx->cntls[chosen].error_text());
  } else {
    *response = std::move(ctx->responses[w]);
    cntl->response_attachment() =
        std::move(ctx->cntls[w].response_attachment());
    cntl->set_latency_us(ctx->cntls[w].latency_us());
  }
}

void ClusterChannel::CallMethod(const std::string& method,
                                const IOBuf& request, IOBuf* response,
                                Controller* cntl, Closure done,
                                uint64_t hash_key) {
  if (done) {
    // Async: the retry loop must not block the caller — run it in a fiber.
    auto* call = new AsyncCall{this,     method, request, response,
                               cntl,     {},     hash_key};
    call->done = std::move(done);
    get_ambient_trace(&call->amb_trace, &call->amb_span);
    call->amb_deadline = ambient_deadline();
    call->amb_hint_set = lb_hint_get(&call->amb_hint);
    if (fiber_start(
            nullptr,
            [](void* arg) {
              std::unique_ptr<AsyncCall> c(static_cast<AsyncCall*>(arg));
              // Fresh fiber, empty fls: re-install the caller's trace
              // context (cleared with the fiber's fls at exit).
              set_ambient_trace(c->amb_trace, c->amb_span);
              set_ambient_deadline(c->amb_deadline);
              if (c->amb_hint_set) {
                lb_hint_set(c->amb_hint);
              }
              c->ch->CallMethod(c->method, c->request, c->response, c->cntl,
                                nullptr, c->hash_key);
              lb_hint_clear();
              c->done();
            },
            call, 0) != 0) {
      // Spawn failure must still complete the call (fiber_start does not
      // take ownership of arg on failure).
      std::unique_ptr<AsyncCall> c(call);
      cntl->SetFailed(EAGAIN, "fiber_start failed");
      c->done();
    }
    return;
  }
  std::shared_ptr<Cluster> cluster;
  {
    auto cur = cluster_.Read();
    cluster = *cur;
  }
  if (cluster == nullptr || cluster->nodes.empty()) {
    cntl->SetFailed(ENOENT, "no servers in cluster");
    if (done) {
      done();
    }
    return;
  }
  if (opts_.backup_request_ms > 0) {
    call_hedged(cluster, method, request, response, cntl, hash_key);
    if (done) {
      done();
    }
    return;
  }
  // Retry loop (sync under the hood; async wraps the final completion).
  // Parity: retries pick a different node and quarantined nodes are skipped
  // (circuit_breaker + cluster_recover semantics condensed).  Captured
  // before the first Reset: the caller's own timeout outranks the channel
  // default on every attempt.
  const int64_t eff_timeout_ms = cntl->timeout_ms_or(opts_.timeout_ms);
  const int attempts = 1 + opts_.max_retry;
  retry_budget_earn();  // this primary call funds the bucket
  std::vector<size_t> tried;
  for (int attempt = 0; attempt < attempts; ++attempt) {
    if (attempt > 0 && !retry_budget_take()) {
      // Retry-storm governor (net/deadline.h): the budget bounds attempt
      // amplification at ~(1 + pct/100)x under total downstream failure
      // — every layer retrying independently is how outages multiply.
      deadline_vars().retry_suppressed << 1;
      if (timeline::enabled()) {
        timeline::record(timeline::kDeadline, 0,
                         timeline::kDeadlineRetrySuppressed << 56);
      }
      break;
    }
    const int64_t now = monotonic_time_us();
    std::vector<size_t> healthy;
    for (size_t i = 0; i < cluster->nodes.size(); ++i) {
      const ServerNode& n = cluster->nodes[i];
      const bool quarantined =
          n.quarantined_until_us->load(std::memory_order_relaxed) > now;
      const bool already_tried =
          std::find(tried.begin(), tried.end(), i) != tried.end();
      if (!quarantined && !already_tried) {
        healthy.push_back(i);
      }
    }
    if (healthy.empty()) {
      // All quarantined/tried: fall back to every untried node (cluster
      // recovery — never fail purely because breakers are open).
      for (size_t i = 0; i < cluster->nodes.size(); ++i) {
        if (std::find(tried.begin(), tried.end(), i) == tried.end()) {
          healthy.push_back(i);
        }
      }
    }
    if (healthy.empty()) {
      break;  // genuinely nothing left
    }
    const size_t idx = lb_->select(healthy, cluster->nodes, hash_key, attempt);
    tried.push_back(idx);
    ServerNode& node = cluster->nodes[idx];

    // Reset per-attempt state but preserve the caller's attachment (shared
    // zero-copy, so re-attaching per retry is free) and the caller's own
    // timeout, which takes precedence over the channel default.
    IOBuf attachment = cntl->request_attachment();
    cntl->Reset();
    cntl->request_attachment() = std::move(attachment);
    cntl->set_timeout_ms(eff_timeout_ms);
    const bool last_attempt = attempt == attempts - 1;
    node.inflight->fetch_add(1, std::memory_order_relaxed);
    cluster->channels[idx]->CallMethod(method, request, response, cntl);
    node.inflight->fetch_sub(1, std::memory_order_relaxed);
    if (!cntl->Failed()) {
      feed_breaker(node, true);
      feed_latency(node, cntl->latency_us());
      feed_cluster_latency(cntl->latency_us());
      if (done) {
        done();
      }
      return;
    }
    if (cntl->error_code() == kEDeadlineExpired ||
        cntl->error_code() == ECANCELED) {
      // The caller's budget is just as dead on every other node (and a
      // cancelled caller wants nothing at all): retrying the chain is
      // pure wasted work (net/deadline.h).  The breaker stays closed —
      // the server is healthy, the clock ran out / the caller left.
      break;
    }
    // kEDraining (Server::Drain, concurrency_limiter.h) is immediate-
    // failover-WITHOUT-quarantine: the node is healthy, just leaving —
    // the tried-set exclusion already moves this call to a different
    // node, and leaving the breaker closed keeps the endpoint clean for
    // the hot-restart successor that revives on it.
    if (cntl->error_code() == kEDraining) {
      if (last_attempt) {
        break;
      }
      continue;
    }
    // Exponential (jittered) quarantine.  kEOverloaded (per-tenant
    // admission shed, net/qos.h) rides this same path BY DESIGN: the
    // node is alive but shedding, so the retry moves to a different node
    // immediately (the tried-set exclusion above never re-picks this
    // one) and the breaker backs traffic off it until the quarantine
    // window expires or a health probe answers.
    feed_breaker(node, false);
    if (last_attempt) {
      break;
    }
  }
  if (done) {
    done();
  }
}

}  // namespace trpc
