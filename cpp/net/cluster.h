// Cluster client: naming services + load balancers + health quarantine.
//
// Parity (SURVEY.md §2.4): LoadBalancer over DoublyBufferedData
// (/root/reference/src/brpc/load_balancer.h:35-95; policy/
// {round_robin,randomized,consistent_hashing,p2c_ewma}_load_balancer),
// NamingService push model (naming_service.h:45-56) with list:// and
// file:// resolvers and periodic re-resolve, per-node CircuitBreaker
// (circuit_breaker.h:25-58) quarantining failed endpoints with growing
// isolation windows, and retry with server exclusion.
#pragma once

#include <atomic>
#include <memory>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

#include "base/doubly_buffered.h"
#include "base/endpoint.h"
#include "fiber/event.h"
#include "net/auth.h"
#include "net/channel.h"
#include "net/controller.h"

namespace trpc {

struct ServerNode {
  EndPoint ep;
  // Static weight (wrr; parsed from the server list, default 1).
  int weight = 1;
  // Locality label from the naming view ("" = unknown).  Fed to the
  // zone-preferring balancer (zone_la): same-zone nodes keep their full
  // latency-derived share, cross-zone nodes pay a penalty.
  std::string zone;
  // Previous quarantine window (ms) for decorrelated backoff jitter
  // (feed_breaker): each new window draws from [base, min(max, prev*3)]
  // via the FaultActor splitmix64 side stream.
  std::shared_ptr<std::atomic<int64_t>> backoff_ms =
      std::make_shared<std::atomic<int64_t>>(0);
  // Circuit-breaker state.
  std::shared_ptr<std::atomic<int64_t>> quarantined_until_us =
      std::make_shared<std::atomic<int64_t>>(0);
  std::shared_ptr<std::atomic<int>> consecutive_failures =
      std::make_shared<std::atomic<int>>(0);
  // Feedback for latency-aware balancing (p2c-EWMA / locality-aware
  // parity): smoothed per-call latency and live in-flight count.
  std::shared_ptr<std::atomic<int64_t>> ewma_latency_us =
      std::make_shared<std::atomic<int64_t>>(0);
  std::shared_ptr<std::atomic<int64_t>> inflight =
      std::make_shared<std::atomic<int64_t>>(0);
};

// Flag registration (idempotent): trpc_cluster_zone,
// trpc_cluster_chash_load_factor, trpc_cluster_subset_size — the capi
// calls it so /flags sees the cluster knobs before first traffic.
void cluster_ensure_registered();

// Shared feedback/selection primitives (the LA balancer and
// DynamicPartitionChannel use identical smoothing and dice logic).
//
// Asymmetric latency smoothing: degradations blend in slowly (one spike
// must not evict a node), improvements take hold fast — a recovered node
// would otherwise need dozens of probes it no longer receives to shed
// its remembered bad latency (lalb ClearOld/ResetWeight parity).
int64_t asym_ewma(int64_t prev, int64_t sample);
// Weighted random pick: index i with probability weights[i]/sum.
size_t weighted_pick(const int64_t* weights, size_t n);

// One resolved member of a cluster.  zone rides from the naming view
// (3rd column of list://, file:// rows; the registry's announce field).
struct NsEntry {
  EndPoint ep;
  int weight = 1;
  std::string zone;
};

class LoadBalancer {
 public:
  virtual ~LoadBalancer() = default;
  // Picks an index into `nodes` (already filtered to healthy ones).
  // `key` is the request hash for consistent hashing; `attempt` excludes
  // previously tried nodes on retry.
  virtual size_t select(const std::vector<size_t>& healthy,
                        const std::vector<ServerNode>& nodes, uint64_t key,
                        int attempt) = 0;
  // The balancing-policy seam: rr | random | c_hash | c_hash_bl (bounded
  // load: trpc_cluster_chash_load_factor) | wrr | p2c | la | zone_la
  // (locality/weighted-latency preferring this client's
  // trpc_cluster_zone).
  static LoadBalancer* create(const std::string& name);
};

class NamingService {
 public:
  virtual ~NamingService() = default;
  // Resolves the member set; weight defaults to 1 and feeds the wrr/p2c
  // balancers, zone feeds zone_la.
  virtual int resolve(const std::string& param,
                      std::vector<NsEntry>* out) = 0;
  // Push support (long-poll): parks up to park_budget_ms until the
  // view's version differs from *version, then updates *version.
  // Returns 0 (answered — the caller re-resolves if the version moved),
  // -1 when this NS has no push path (the periodic refresher is the
  // poll fallback), or a positive transport error.
  virtual int watch(const std::string& /*param*/, uint64_t* /*version*/,
                    int64_t /*park_budget_ms*/) {
    return -1;
  }
  virtual bool supports_watch() const { return false; }
  // "list://h1:p1,h2:p2" | "file:///path" | "dns://host:port" |
  // "naming://registry_host:port/service" (push-based) | "host:port"
  static std::unique_ptr<NamingService> create(const std::string& url,
                                               std::string* param);
};

// Channel over a resolved cluster (parity: Channel::Init(ns_url, lb, opts)
// composed via details/load_balancer_with_naming).
// TEST INJECTION (regression coverage): fail the next N hedge-attempt
// fiber spawns, exercising the spawn-failure settle path — a failed
// spawn must synthetically settle its slot or wait_settled(-1) hangs
// forever.  Production value is 0.
extern std::atomic<int> test_fail_hedge_spawns;

class ClusterChannel {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
    int max_retry = 2;                   // additional attempts on failure
    // Health checking (parity: details/health_check.cpp): quarantined nodes
    // are probed every refresh tick with this method; ANY response — even a
    // method-not-found error — proves the transport alive and revives the
    // node early (socket.h:498-505 revive semantics).  "" disables probing
    // (nodes then revive only when their quarantine window expires).
    std::string health_check_method = "Echo.Health";
    int64_t health_check_timeout_ms = 300;
    // Hedging (parity: backup_request_policy.h + the backup timer in
    // channel.cpp:582-603): if > 0 and the first attempt hasn't answered
    // within this budget, a second attempt races it on another node; the
    // first success wins and the loser's late response is dropped by its
    // correlation id.
    int64_t backup_request_ms = 0;       // 0 = disabled
    int64_t refresh_interval_ms = 5000;  // periodic re-resolve
    int64_t quarantine_base_ms = 100;    // doubles per consecutive failure
    int64_t quarantine_max_ms = 10000;
    // Passed through to every member Channel (socket_map.h connection
    // matrix / auth.h credentials / wire protocol: "tstd", "h2", "grpc").
    std::string connection_type = "single";
    const Authenticator* auth = nullptr;
    std::string protocol = "tstd";
    // Default QoS tag for every member channel (net/qos.h); per-call
    // Controller::set_qos overrides.  A tagged cluster client pairs the
    // shed status (kEOverloaded) with the failover machinery above.
    std::string qos_tenant;
    uint8_t qos_priority = 0;
    // Deterministic subsetting: cap how many members THIS client holds
    // channels to (rendezvous-hash by subset_seed, so the fleet's
    // clients spread evenly over the servers while each keeps a stable
    // subset across refreshes).  0 = the trpc_cluster_subset_size flag;
    // negative = explicitly unlimited.  Mandatory at scale: N clients x
    // M servers full-mesh is what blows the fd budget.
    int subset_size = 0;
    // Seed for the rendezvous hash (0 = derive from pid: every process
    // lands on a different-but-stable subset).
    uint64_t subset_seed = 0;
  };

  ~ClusterChannel();
  int Init(const std::string& naming_url, const std::string& lb_name,
           const Options* opts = nullptr);
  void CallMethod(const std::string& method, const IOBuf& request,
                  IOBuf* response, Controller* cntl, Closure done = nullptr,
                  uint64_t hash_key = 0);

  // Retargets the default QoS tag: stored for future member channels
  // (mutex-guarded — the refresh fiber reads it when building them) AND
  // pushed into the live ones.  Set before issuing traffic: the push
  // into live member channels follows Channel::set_default_qos's
  // unsynchronized-vs-CallMethod contract.
  void set_default_qos(const std::string& tenant, uint8_t priority);

  // Re-resolves now (also runs periodically in a refresh fiber, and
  // immediately whenever the naming watch fiber sees a version bump —
  // push-based membership, no reconnect storm: surviving endpoints keep
  // their channels and breaker state across every refresh).
  int refresh();
  // Probes quarantined nodes; revives any that answer (runs periodically).
  void health_check();
  size_t healthy_count();

 private:
  struct Cluster {
    std::vector<ServerNode> nodes;
    std::vector<std::shared_ptr<Channel>> channels;  // parallel to nodes
  };
  static void refresh_fiber(void* arg);
  static void watch_fiber(void* arg);
  void call_hedged(std::shared_ptr<Cluster> cluster, const std::string& method,
                   const IOBuf& request, IOBuf* response, Controller* cntl,
                   uint64_t hash_key);
  void feed_breaker(ServerNode& node, bool success);
  // Retry-budget token bucket (net/deadline.h,
  // trpc_cluster_retry_budget_pct): each primary attempt deposits pct
  // hundredths of a token, each retry/hedge withdraws 100.  take()
  // always succeeds with the budget off (pct 0).
  void retry_budget_earn();
  bool retry_budget_take();
  void feed_cluster_latency(int64_t lat_us);

  std::unique_ptr<NamingService> ns_;
  std::string ns_param_;
  std::unique_ptr<LoadBalancer> lb_;
  Options opts_;
  // Guards opts_.qos_tenant/qos_priority ONLY: set_default_qos may run
  // while the refresh fiber is building member channels from opts_ (a
  // torn std::string read would be UB).  The rest of opts_ is
  // immutable after Init.
  mutable std::mutex qos_mu_;
  DoublyBufferedData<std::shared_ptr<Cluster>> cluster_;
  std::atomic<bool> stopping_{false};
  std::atomic<bool> refresher_started_{false};
  Event refresh_wake_;  // interrupts the refresher's sleep at shutdown
  Event refresh_done_;  // value 1 once the refresher has exited
  // Set strictly AFTER the refresher's last touch of this object; the
  // destructor spins on it so it can't free members mid-wake.
  std::atomic<bool> refresher_exited_{false};
  // Naming watch fiber (push-based membership; only when the NS
  // supports_watch): long-polls the registry and refreshes on every
  // version bump.  Same teardown protocol as the refresher.
  std::atomic<bool> watcher_started_{false};
  Event watch_wake_;
  Event watch_done_;
  std::atomic<bool> watcher_exited_{false};
  // Retry-budget tokens in hundredths (capped: an idle cluster must not
  // bank unlimited retries) and the cluster-wide smoothed success
  // latency — the hedge-feasibility estimate: a hedge whose remaining
  // budget cannot cover a typical attempt is suppressed as pure load.
  std::atomic<int64_t> retry_tokens_{0};
  std::atomic<int64_t> lat_ewma_us_{0};
};

}  // namespace trpc
