#include "net/collective.h"

#include <string.h>

#include <algorithm>
#include <mutex>
#include <unordered_map>

#include "base/flags.h"
#include "base/logging.h"
#include "base/time.h"
#include "fiber/event.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/kvstore.h"
#include "net/naming.h"
#include "net/rma.h"
#include "net/server.h"
#include "stat/latency_recorder.h"
#include "stat/reducer.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

// ---- flags ---------------------------------------------------------------

Flag* int_flag(const char* name, int64_t dflt, const char* desc, int64_t lo,
               int64_t hi) {
  Flag* f = Flag::define_int64(name, dflt, desc);
  if (f != nullptr) {
    // Range validator + introspectable bounds in one declaration (the
    // tuner and /flags?format=json read them back).
    f->set_int_range(lo, hi);
  }
  return f;
}

Flag* chunk_flag() {
  static Flag* f = int_flag(
      "trpc_coll_chunk_bytes", 8 << 20,
      "chunk size collective transfers are cut into (bytes, [64KB, "
      "256MB]); each chunk is one Coll.Put riding the one-sided RMA "
      "plane, so smaller chunks pipeline deeper (T3 overlap) at more "
      "per-put cost",
      64 << 10, 256ll << 20);
  return f;
}

Flag* inflight_flag() {
  static Flag* f = int_flag(
      "trpc_coll_inflight", 4,
      "concurrent in-flight Coll.Put chunks per member per schedule "
      "step ([1, 64]); depth >1 overlaps chunk k+1's put with chunk "
      "k's verification",
      1, 64);
  return f;
}

Flag* rendezvous_flag() {
  static Flag* f = int_flag(
      "trpc_coll_rendezvous_ms", 15000,
      "how long a Coll.Put handler parks waiting for the local member "
      "to register its receive session (ms, [50, 600000]) — members "
      "enter a collective at slightly different times; past this the "
      "put fails and the sender aborts the step",
      50, 600000);
  return f;
}

Flag* ready_granularity_flag() {
  static Flag* f = int_flag(
      "trpc_coll_ready_granularity_bytes", 1 << 20,
      "chunk granularity of collective readiness maps (bytes, [4KB, "
      "256MB]) — producers stamp send-buffer ranges at this grain and "
      "readiness-triggered transfers fire per stamped chunk; finer "
      "grains overlap earlier at more stamp/scan cost",
      4 << 10, 256ll << 20);
  return f;
}

Flag* overlap_flag() {
  static Flag* f = [] {
    Flag* fl = Flag::define_bool(
        "trpc_coll_overlap", false,
        "fire collective transfers as their input chunks are stamped "
        "ready (T3-style compute/comm overlap) instead of waiting for "
        "the whole send buffer; off = barrier semantics, byte-identical "
        "with or without a readiness map attached");
    if (fl != nullptr) {
      fl->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
    }
    return fl;
  }();
  return f;
}

int64_t flag_val(Flag* f, int64_t dflt) {
  return f != nullptr ? f->int64_value() : dflt;
}

bool overlap_enabled() {
  Flag* f = overlap_flag();
  return f != nullptr && f->bool_value();
}

// ---- vars ----------------------------------------------------------------

struct CollVars {
  Adder runs_total;
  Adder steps_total;
  Adder puts_total;
  Adder put_bytes;
  Adder aborts_total;
  Adder epoch_fails_total;
  Adder reshard_plans_total;
  Adder reshard_execs_total;
  Adder ready_triggers_total;
  Adder overlap_runs_total;
  std::unique_ptr<PassiveStatus<long>> sessions;
  // Per-op step latency, Prometheus-exposed with HELP so dashboards can
  // tell a slow reshard from a slow all-gather.
  LatencyRecorder step_all_gather;
  LatencyRecorder step_reduce_scatter;
  LatencyRecorder step_all_to_all;
  LatencyRecorder step_reshard;
  CollVars() {
    runs_total.expose("coll_runs_total",
                      "collective schedules executed by this member "
                      "(all_gather / reduce_scatter / all_to_all / "
                      "reshard runs, success or failure)");
    steps_total.expose("coll_steps_total",
                       "schedule steps this member completed (sends "
                       "acked AND expected receives landed)");
    puts_total.expose("coll_puts_total",
                      "Coll.Put chunk RPCs issued by this member");
    put_bytes.expose("coll_put_bytes",
                     "payload bytes this member moved over the fabric "
                     "via Coll.Put chunks");
    aborts_total.expose("coll_aborts_total",
                        "collective runs that failed whole-or-nothing "
                        "(local step failure or a peer's Coll.Abort)");
    epoch_fails_total.expose(
        "coll_epoch_fails_total",
        "schedule steps failed because the group's naming view changed "
        "mid-run (membership epoch moved under the schedule)");
    reshard_plans_total.expose(
        "coll_reshard_plans_total",
        "Reshard.Plan requests answered by this node");
    reshard_execs_total.expose(
        "coll_reshard_execs_total",
        "Reshard.Execute runs this node participated in");
    ready_triggers_total.expose(
        "coll_ready_triggers_total",
        "collective transfers fired by a readiness stamp before the "
        "whole-buffer barrier would have released them (frozen at 0 "
        "with trpc_coll_overlap off)");
    overlap_runs_total.expose(
        "coll_overlap_runs_total",
        "collective runs executed with readiness-triggered overlap "
        "(a ready map attached AND trpc_coll_overlap on; frozen at 0 "
        "otherwise)");
    sessions = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(coll_sessions_live()); });
    sessions->expose("coll_sessions",
                     "collective receive sessions currently registered "
                     "(0 when no run is in flight)");
    step_all_gather.expose(
        "coll_step_all_gather",
        "wall time of one completed all_gather schedule step (sends "
        "acked + receives landed)");
    step_reduce_scatter.expose(
        "coll_step_reduce_scatter",
        "wall time of one completed reduce_scatter schedule step");
    step_all_to_all.expose(
        "coll_step_all_to_all",
        "wall time of one completed all_to_all schedule step");
    step_reshard.expose(
        "coll_step_reshard",
        "wall time of one completed reshard schedule step");
  }
  LatencyRecorder& step_lat(CollOp op) {
    switch (op) {
      case CollOp::kAllGather:
        return step_all_gather;
      case CollOp::kReduceScatter:
        return step_reduce_scatter;
      case CollOp::kAllToAll:
        return step_all_to_all;
      default:
        return step_reshard;
    }
  }
};

CollVars& coll_vars() {
  static CollVars* v = new CollVars();
  return *v;
}

uint64_t fnv1a(const void* data, size_t n, uint64_t h = 14695981039346656037ull) {
  const auto* p = static_cast<const unsigned char*>(data);
  for (size_t i = 0; i < n; ++i) {
    h = (h ^ p[i]) * 1099511628211ull;
  }
  return h;
}

void noop_deleter(void*, void*) {}

// ---- receive sessions ----------------------------------------------------

// One member's receive state for one (group, run).  Registered by the
// executor BEFORE it issues any put; Coll.Put handlers park (bounded by
// trpc_coll_rendezvous_ms) for it to appear, place chunks, and wake the
// executor's per-step countdown.  `busy` guards the destination buffer:
// unregistration drains in-flight handler copies before run() returns
// the buffer to the caller.
struct RecvSession {
  uint64_t group_id = 0;
  uint64_t run_seq = 0;
  uint32_t dst_rank = 0;
  char* dst = nullptr;  // recv buffer (accumulator for reduce ops)
  uint64_t dst_len = 0;
  // Serve source (Coll.Get pulls read the member's buffers directly):
  // the send buffer, and `dst` again for ring-forwarded bytes.
  const char* send_base = nullptr;
  uint64_t send_len = 0;
  // Readiness map over send_base (0 = none): Coll.Get serves of
  // NON-forwarded bytes additionally gate on the producer's stamp, so a
  // pull fires the moment its input chunks land (overlap mode).
  uint64_t ready_handle = 0;
  Event changed;  // bumped on every arrival / serve / abort / put-ack
  std::mutex mu;  // guards the fields below
  std::vector<uint64_t> expected_bytes;  // per step (my receives)
  std::vector<uint64_t> arrived_bytes;   // per step
  // Pull serves this member must complete per step: a member's step is
  // done only when its peers' pulls were served too — unregistering
  // earlier would fail a slow peer's get against a dead session.
  std::vector<uint64_t> expected_serve;  // per step (my pulled sends)
  std::vector<uint64_t> served_bytes;    // per step
  int abort_code = 0;
  std::string abort_why;
  uint32_t busy = 0;  // handlers copying into dst / serving out of it
};

struct SessionReg {
  std::mutex mu;
  std::unordered_map<uint64_t, std::shared_ptr<RecvSession>> map;
  // Aborts that arrived before the local member registered (a fast peer
  // failed step 0 while we were still compiling): poison the key so the
  // late registration fails fast instead of timing out.  Bounded FIFO.
  std::unordered_map<uint64_t, int> poisoned;
  std::vector<uint64_t> poison_order;
  Event registered;  // bumped on every registration
};

SessionReg& sessions() {
  static SessionReg* s = new SessionReg();
  return *s;
}

uint64_t session_key(uint64_t group_id, uint64_t run_seq,
                     uint32_t dst_rank) {
  return (group_id * 1099511628211ull ^ run_seq) * 1099511628211ull ^
         dst_rank;
}

void wake_session(RecvSession* s) {
  // Release pairs with the waiter's acquire load of `value`; the state
  // mutated under s->mu is published by the mutex itself.
  s->changed.value.fetch_add(1, std::memory_order_release);
  s->changed.wake_all();
}

std::shared_ptr<RecvSession> find_session(uint64_t key) {
  SessionReg& r = sessions();
  std::lock_guard<std::mutex> g(r.mu);
  auto it = r.map.find(key);
  return it != r.map.end() ? it->second : nullptr;
}

// Handler-side lookup: parks (bounded) until the session exists.
std::shared_ptr<RecvSession> wait_session(uint64_t key) {
  const int64_t deadline =
      monotonic_time_us() + flag_val(rendezvous_flag(), 15000) * 1000;
  SessionReg& r = sessions();
  while (true) {
    uint32_t v;
    {
      std::lock_guard<std::mutex> g(r.mu);
      auto it = r.map.find(key);
      if (it != r.map.end()) {
        return it->second;
      }
      // Acquire pairs with the registrar's release bump: the map insert
      // happens-before a woken waiter's re-check.
      v = r.registered.value.load(std::memory_order_acquire);
    }
    if (monotonic_time_us() >= deadline) {
      return nullptr;
    }
    r.registered.wait(v, deadline);
  }
}

std::shared_ptr<RecvSession> register_session(
    uint64_t group_id, uint64_t run_seq, uint32_t dst_rank, char* dst,
    uint64_t dst_len, const char* send_base, uint64_t send_len,
    std::vector<uint64_t> expected, std::vector<uint64_t> expected_serve,
    int* poison_code, uint64_t ready_handle = 0) {
  auto s = std::make_shared<RecvSession>();
  s->group_id = group_id;
  s->run_seq = run_seq;
  s->dst_rank = dst_rank;
  s->dst = dst;
  s->dst_len = dst_len;
  s->send_base = send_base;
  s->send_len = send_len;
  s->ready_handle = ready_handle;
  s->expected_bytes = std::move(expected);
  s->arrived_bytes.assign(s->expected_bytes.size(), 0);
  s->expected_serve = std::move(expected_serve);
  s->served_bytes.assign(s->expected_serve.size(), 0);
  const uint64_t key = session_key(group_id, run_seq, dst_rank);
  SessionReg& r = sessions();
  std::lock_guard<std::mutex> g(r.mu);
  auto pit = r.poisoned.find(key);
  if (pit != r.poisoned.end()) {
    *poison_code = pit->second;
    r.poisoned.erase(pit);
    for (auto it = r.poison_order.begin(); it != r.poison_order.end(); ++it) {
      if (*it == key) {
        r.poison_order.erase(it);
        break;
      }
    }
    return nullptr;
  }
  if (r.map.find(key) != r.map.end()) {
    // A LIVE session already holds this (group, run, rank): the caller
    // reused a run_seq that has not torn down — overwriting would land
    // run A's in-flight puts in run B's buffers.  Refuse whole.
    *poison_code = kECollMismatch;
    return nullptr;
  }
  r.map[key] = s;
  // Release pairs with wait_session's acquire re-check.
  r.registered.value.fetch_add(1, std::memory_order_release);
  r.registered.wake_all();
  return s;
}

void unregister_session(const std::shared_ptr<RecvSession>& s) {
  {
    SessionReg& r = sessions();
    std::lock_guard<std::mutex> g(r.mu);
    r.map.erase(session_key(s->group_id, s->run_seq, s->dst_rank));
  }
  // Drain in-flight handler copies: the caller reclaims the destination
  // buffer the moment run() returns, so no handler may still be writing.
  while (true) {
    uint32_t v;
    {
      std::lock_guard<std::mutex> g(s->mu);
      if (s->busy == 0) {
        return;
      }
      // Acquire pairs with wake_session's release bump (busy drop).
      v = s->changed.value.load(std::memory_order_acquire);
    }
    s->changed.wait(v, monotonic_time_us() + 100 * 1000);
  }
}

void poison_run(uint64_t key, int code) {
  constexpr size_t kPoisonCap = 128;
  SessionReg& r = sessions();
  std::lock_guard<std::mutex> g(r.mu);
  if (r.map.find(key) != r.map.end()) {
    return;  // session live: the abort path marked it directly
  }
  if (r.poisoned.emplace(key, code).second) {
    r.poison_order.push_back(key);
    if (r.poison_order.size() > kPoisonCap) {
      r.poisoned.erase(r.poison_order.front());
      r.poison_order.erase(r.poison_order.begin());
    }
  }
}

// ---- wire helpers --------------------------------------------------------

bool parse_put_wire(const IOBuf& req, CollPutWire* w) {
  if (req.size() < sizeof(CollPutWire)) {
    return false;
  }
  req.copy_to(w, sizeof(CollPutWire));
  return true;
}

void record_coll_step(CollOp op, uint32_t step, uint64_t bytes) {
  if (timeline::enabled()) {
    timeline::record(timeline::kCollStep, step,
                     (static_cast<uint64_t>(op) << 56) |
                         (bytes & ((1ull << 56) - 1)));
  }
}

// A transfer fired off a readiness stamp instead of the barrier:
// a = step, b = chunk<<32|bytes (chunk = dep offset / granularity).
void record_coll_ready(uint32_t step, uint64_t dep_off, uint64_t bytes) {
  coll_vars().ready_triggers_total << 1;
  if (timeline::enabled()) {
    const uint64_t g =
        static_cast<uint64_t>(flag_val(ready_granularity_flag(), 1 << 20));
    timeline::record(timeline::kCollReady, step,
                     ((dep_off / g) << 32) | (bytes & 0xFFFFFFFFull));
  }
}

}  // namespace

const char* coll_op_name(CollOp op) {
  switch (op) {
    case CollOp::kAllGather:
      return "all_gather";
    case CollOp::kReduceScatter:
      return "reduce_scatter";
    case CollOp::kAllToAll:
      return "all_to_all";
    case CollOp::kReshard:
      return "reshard";
  }
  return "?";
}

void coll_ensure_registered() {
  chunk_flag();
  inflight_flag();
  rendezvous_flag();
  ready_granularity_flag();
  overlap_flag();
  coll_vars();
}

uint64_t coll_ready_default_granularity() {
  return static_cast<uint64_t>(
      flag_val(ready_granularity_flag(), 1 << 20));
}

size_t coll_sessions_live() {
  SessionReg& r = sessions();
  std::lock_guard<std::mutex> g(r.mu);
  return r.map.size();
}

// ---- plans ---------------------------------------------------------------

uint64_t TransferSchedule::bytes_moved() const {
  uint64_t n = 0;
  for (const CollStep& s : steps) {
    for (const CollTransfer& t : s.puts) {
      n += t.len;
    }
  }
  return n;
}

uint64_t TransferSchedule::bytes_reused() const {
  uint64_t n = 0;
  for (const CollTransfer& t : local_copies) {
    n += t.len;
  }
  return n;
}

CollDep transfer_input_dep(const CollTransfer& t) {
  if (t.src_from_recv) {
    // Ring-forwarded bytes: produced by a PRIOR step's arrivals, which
    // the step barrier already orders — no send-buffer dependency.
    return CollDep{};
  }
  return CollDep{t.src_off, t.len};
}

uint64_t plan_producer_extent(const TransferSchedule& plan, uint32_t rank) {
  uint64_t extent = 0;
  auto fold = [&](const CollTransfer& t) {
    if (t.src != rank) {
      return;
    }
    const CollDep d = transfer_input_dep(t);
    if (d.len != 0) {
      extent = std::max(extent, d.off + d.len);
    }
  };
  for (const CollTransfer& t : plan.local_copies) {
    fold(t);
  }
  for (const CollStep& s : plan.steps) {
    for (const CollTransfer& t : s.puts) {
      fold(t);
    }
  }
  return extent;
}

TransferSchedule plan_all_gather(uint32_t n, uint64_t shard) {
  TransferSchedule p;
  p.op = CollOp::kAllGather;
  p.nmembers = n;
  p.shard_bytes = shard;
  for (uint32_t r = 0; r < n; ++r) {
    p.local_copies.push_back({r, r, 0, static_cast<uint64_t>(r) * shard,
                              shard, false, false});
  }
  for (uint32_t s = 0; n > 1 && s < n - 1; ++s) {
    CollStep step;
    for (uint32_t r = 0; r < n; ++r) {
      // Ring: at step s rank r forwards chunk (r - s) mod n to its right
      // neighbor; step 0 reads the member's own shard (sendbuf), later
      // steps forward what landed in recvbuf the step before.
      const uint32_t c = (r + n - s) % n;
      CollTransfer t;
      t.src = r;
      t.dst = (r + 1) % n;
      t.src_off = s == 0 ? 0 : static_cast<uint64_t>(c) * shard;
      t.src_from_recv = s != 0;
      t.dst_off = static_cast<uint64_t>(c) * shard;
      t.len = shard;
      step.puts.push_back(t);
    }
    p.steps.push_back(std::move(step));
  }
  return p;
}

TransferSchedule plan_reduce_scatter(uint32_t n, uint64_t shard) {
  TransferSchedule p;
  p.op = CollOp::kReduceScatter;
  p.nmembers = n;
  p.shard_bytes = shard;
  for (uint32_t s = 0; n > 1 && s < n - 1; ++s) {
    CollStep step;
    for (uint32_t r = 0; r < n; ++r) {
      // Ring reduce: at step s rank r ships its accumulated chunk
      // (r - 1 - s) mod n rightward; the receiver u32-adds it into ITS
      // accumulator (= sendbuf) copy of the same chunk.  After n-1
      // steps rank r's chunk r is fully reduced.
      const uint32_t c = (r + 2 * n - 1 - s) % n;
      CollTransfer t;
      t.src = r;
      t.dst = (r + 1) % n;
      t.src_off = static_cast<uint64_t>(c) * shard;
      t.dst_off = static_cast<uint64_t>(c) * shard;
      t.len = shard;
      t.reduce = true;
      step.puts.push_back(t);
    }
    p.steps.push_back(std::move(step));
  }
  for (uint32_t r = 0; r < n; ++r) {
    // Final local copy: the fully-reduced chunk r out of the
    // accumulator into recvbuf.
    p.final_copies.push_back({r, r, static_cast<uint64_t>(r) * shard, 0,
                              shard, false, false});
  }
  return p;
}

TransferSchedule plan_all_to_all(uint32_t n, uint64_t shard) {
  TransferSchedule p;
  p.op = CollOp::kAllToAll;
  p.nmembers = n;
  p.shard_bytes = shard;
  for (uint32_t r = 0; r < n; ++r) {
    p.local_copies.push_back({r, r, static_cast<uint64_t>(r) * shard,
                              static_cast<uint64_t>(r) * shard, shard,
                              false, false});
  }
  for (uint32_t s = 1; s < n; ++s) {
    // Pairwise rounds: at round s rank r exchanges with (r + s) mod n —
    // bounded fan-in per step, every pair exactly once.
    CollStep step;
    for (uint32_t r = 0; r < n; ++r) {
      const uint32_t d = (r + s) % n;
      CollTransfer t;
      t.src = r;
      t.dst = d;
      t.src_off = static_cast<uint64_t>(d) * shard;
      t.dst_off = static_cast<uint64_t>(r) * shard;
      t.len = shard;
      step.puts.push_back(t);
    }
    p.steps.push_back(std::move(step));
  }
  return p;
}

bool sharding_valid(const Sharding& s, uint32_t nmembers) {
  if (s.total == 0 || s.ranges.empty()) {
    return false;
  }
  uint64_t at = 0;
  for (const ShardRange& r : s.ranges) {
    if (r.rank >= nmembers || r.len == 0 || r.off != at) {
      return false;  // must tile [0, total) in order, no gaps/overlaps
    }
    at += r.len;
  }
  return at == s.total;
}

uint64_t sharding_local_bytes(const Sharding& s, uint32_t rank) {
  uint64_t n = 0;
  for (const ShardRange& r : s.ranges) {
    if (r.rank == rank) {
      n += r.len;
    }
  }
  return n;
}

namespace {

// Local-buffer offset of global byte `goff` under sharding `s` for the
// rank owning it (a rank's local buffer is its ranges concatenated in
// ascending global order).  Caller guarantees goff lies in a range owned
// by `rank`.
uint64_t local_off(const Sharding& s, uint32_t rank, uint64_t goff) {
  uint64_t acc = 0;
  for (const ShardRange& r : s.ranges) {
    if (r.rank != rank) {
      continue;
    }
    if (goff >= r.off && goff < r.off + r.len) {
      return acc + (goff - r.off);
    }
    acc += r.len;
  }
  return acc;  // unreachable under a valid plan
}

}  // namespace

TransferSchedule plan_reshard(const Sharding& src, const Sharding& dst,
                              uint32_t n) {
  TransferSchedule p;
  p.op = CollOp::kReshard;
  p.nmembers = n;
  // Bucket cross-owner moves into (dst - src) mod n rounds so per-step
  // fan-in is bounded; same-owner bytes are REUSED in place — the
  // 2112.01075 decomposition's whole point.
  std::vector<CollStep> rounds(n > 1 ? n - 1 : 0);
  for (const ShardRange& d : dst.ranges) {
    for (const ShardRange& srange : src.ranges) {
      const uint64_t lo = std::max(d.off, srange.off);
      const uint64_t hi = std::min(d.off + d.len, srange.off + srange.len);
      if (lo >= hi) {
        continue;
      }
      CollTransfer t;
      t.src = srange.rank;
      t.dst = d.rank;
      t.src_off = local_off(src, srange.rank, lo);
      t.dst_off = local_off(dst, d.rank, lo);
      t.len = hi - lo;
      if (srange.rank == d.rank) {
        p.local_copies.push_back(t);
      } else {
        rounds[(d.rank + n - srange.rank) % n - 1].puts.push_back(t);
      }
    }
  }
  for (CollStep& r : rounds) {
    if (!r.puts.empty()) {
      p.steps.push_back(std::move(r));
    }
  }
  return p;
}

uint64_t reshard_naive_bytes(const Sharding& src, uint32_t n) {
  uint64_t total = 0;
  for (uint32_t r = 0; r < n; ++r) {
    total += sharding_local_bytes(src, r) * (n > 0 ? n - 1 : 0);
  }
  return total;
}

// ---- handlers ------------------------------------------------------------

namespace {

void handle_put(Controller* cntl, const IOBuf& req, IOBuf* resp,
                Closure done) {
  CollPutWire w;
  if (!parse_put_wire(req, &w) || req.size() != sizeof(w) + w.len) {
    cntl->SetFailed(EINVAL, "bad Coll.Put request");
    done();
    return;
  }
  const uint64_t key = session_key(w.group_id, w.run_seq, w.dst_rank);
  std::shared_ptr<RecvSession> s = wait_session(key);
  if (s == nullptr) {
    cntl->SetFailed(kECollAbort,
                    "coll-abort: no receive session (member never "
                    "entered, or the run already tore down)");
    done();
    return;
  }
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->abort_code != 0) {
      cntl->SetFailed(s->abort_code, "coll-abort: " + s->abort_why);
      done();
      return;
    }
    // Overflow-safe bounds (the frame is network input): subtract,
    // never add — dst_off + len could wrap past a 2^64 check.
    // The reduce fold is word-wise: an unaligned chunk would silently
    // drop its tail bytes while crediting the full length — reject it
    // like any other out-of-plan put (mirrors the sender-side check).
    const bool bad_reduce =
        (w.flags & kCollFlagReduce) != 0 &&
        (w.len % 4 != 0 || w.dst_off % 4 != 0);
    if (bad_reduce || w.step >= s->expected_bytes.size() ||
        w.dst_off > s->dst_len || w.len > s->dst_len - w.dst_off ||
        w.len > s->expected_bytes[w.step] ||
        s->arrived_bytes[w.step] >
            s->expected_bytes[w.step] - w.len) {
      LOG(Warning) << "coll put mismatch: step=" << w.step << "/"
                   << s->expected_bytes.size() << " dst_off=" << w.dst_off
                   << " len=" << w.len << " dst_len=" << s->dst_len
                   << " arrived="
                   << (w.step < s->arrived_bytes.size()
                           ? s->arrived_bytes[w.step]
                           : 0)
                   << " expected="
                   << (w.step < s->expected_bytes.size()
                           ? s->expected_bytes[w.step]
                           : 0)
                   << " src_rank=" << w.src_rank;
      cntl->SetFailed(kECollMismatch,
                      "coll-mismatch: put outside the compiled plan");
      done();
      return;
    }
    s->busy += 1;  // pin dst against unregistration while copying
  }
  if ((w.flags & kCollFlagReduce) != 0 && s->ready_handle != 0 &&
      s->dst == s->send_base) {
    // In-place reduce with a readiness map: the accumulator IS the
    // producer-stamped send buffer, so folding into an unstamped range
    // would be overwritten by the still-running producer (a lost
    // update).  Park until the local producer stamped the target range,
    // bounded by the rendezvous budget — a producer that never stamps
    // fails the put (and with it the step, whole-or-nothing) instead of
    // wedging.
    const int64_t rdl =
        monotonic_time_us() + flag_val(rendezvous_flag(), 15000) * 1000;
    while (rma_ready_test(s->ready_handle, w.dst_off, w.len) != 1) {
      int abort_code = 0;
      {
        std::lock_guard<std::mutex> g(s->mu);
        abort_code = s->abort_code;
      }
      const int64_t now = monotonic_time_us();
      if (abort_code != 0 || now >= rdl) {
        {
          std::lock_guard<std::mutex> g(s->mu);
          s->busy -= 1;
        }
        wake_session(s.get());
        if (abort_code != 0) {
          cntl->SetFailed(abort_code, "coll-abort: aborted while "
                                      "waiting for accumulator stamp");
        } else {
          cntl->SetFailed(kECollAbort,
                          "coll-abort: accumulator range never stamped "
                          "ready (producer stalled)");
        }
        done();
        return;
      }
      // Sliced park: woken the instant the range is stamped, re-checks
      // abort/teardown every slice.
      rma_ready_wait(s->ready_handle, w.dst_off, w.len,
                     std::min(rdl, now + 10 * 1000));
    }
  }
  if ((w.flags & kCollFlagReduce) != 0) {
    // Element-wise u32 add.  One bounded staging copy: the payload may
    // arrive as a chained IOBuf whose block boundaries are not
    // 4-aligned.
    std::vector<char> tmp(w.len);
    req.copy_to(tmp.data(), w.len, sizeof(w));
    auto* acc = reinterpret_cast<uint32_t*>(s->dst + w.dst_off);
    const auto* add = reinterpret_cast<const uint32_t*>(tmp.data());
    const size_t words = w.len / 4;
    for (size_t i = 0; i < words; ++i) {
      acc[i] += add[i];
    }
  } else {
    req.copy_to(s->dst + w.dst_off, w.len, sizeof(w));
  }
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->busy -= 1;
    s->arrived_bytes[w.step] += w.len;
  }
  wake_session(s.get());
  (void)resp;  // ack is the empty response — the tiny control frame
  done();
}

// Serve-side pin for pulled bytes: the response IOBuf wraps the
// member's own buffer zero-copy; `busy` holds the session (and with it
// the caller's buffer lifetime guarantee) until the transport's last
// reference drops — after the rails memcpy'd the bytes into the
// getter's region.
struct ServeCtx {
  std::shared_ptr<RecvSession> sess;
};

void serve_deleter(void*, void* vctx) {
  auto* ctx = static_cast<ServeCtx*>(vctx);
  {
    std::lock_guard<std::mutex> g(ctx->sess->mu);
    ctx->sess->busy -= 1;
  }
  wake_session(ctx->sess.get());
  delete ctx;
}

void handle_get(Controller* cntl, const IOBuf& req, IOBuf* resp,
                Closure done) {
  CollPutWire w;
  if (!parse_put_wire(req, &w) || w.len == 0) {
    cntl->SetFailed(EINVAL, "bad Coll.Get request");
    done();
    return;
  }
  // A get reads the SOURCE member's buffers: its session is the key.
  const uint64_t key = session_key(w.group_id, w.run_seq, w.src_rank);
  std::shared_ptr<RecvSession> s = wait_session(key);
  if (s == nullptr) {
    cntl->SetFailed(kECollAbort,
                    "coll-abort: no serve session (member never "
                    "entered, or the run already tore down)");
    done();
    return;
  }
  const bool from_recv = (w.flags & kCollFlagFromRecv) != 0;
  const int64_t deadline =
      monotonic_time_us() + flag_val(rendezvous_flag(), 15000) * 1000;
  while (true) {
    uint32_t v;
    bool ready_blocked = false;
    uint64_t ready_handle = 0;
    {
      std::lock_guard<std::mutex> g(s->mu);
      if (s->abort_code != 0) {
        cntl->SetFailed(s->abort_code, "coll-abort: " + s->abort_why);
        done();
        return;
      }
      // Overflow-safe bounds (the frame is network input): subtract,
      // never add — shard_off + len could wrap past a 2^64 check.
      const uint64_t src_lim = from_recv ? s->dst_len : s->send_len;
      if (w.step >= s->expected_serve.size() ||
          (from_recv && w.step == 0) ||
          w.shard_off > src_lim || w.len > src_lim - w.shard_off ||
          w.len > s->expected_serve[w.step] ||
          s->served_bytes[w.step] >
              s->expected_serve[w.step] - w.len) {
        cntl->SetFailed(kECollMismatch,
                        "coll-mismatch: get outside the compiled plan");
        done();
        return;
      }
      // Ring-forwarded bytes exist only once the PREVIOUS step's
      // arrivals landed here — the data dependency the schedule
      // encodes; sendbuf reads are ready from registration — UNLESS a
      // readiness map is attached, in which case the producer's stamp
      // over the requested range is the send-buffer dependency (a get
      // never ships unstamped bytes, overlap flag on or off).
      const bool dep_ok =
          from_recv
              ? s->arrived_bytes[w.step - 1] >= s->expected_bytes[w.step - 1]
              : (s->ready_handle == 0 ||
                 rma_ready_test(s->ready_handle, w.shard_off, w.len) == 1);
      if (dep_ok) {
        if (!from_recv && s->ready_handle != 0 && overlap_enabled()) {
          record_coll_ready(w.step, w.shard_off, w.len);
        }
        s->busy += 1;  // released by the response payload's deleter
        s->served_bytes[w.step] += w.len;
        break;
      }
      ready_blocked = !from_recv;
      ready_handle = s->ready_handle;
      // Acquire pairs with wake_session's release bump.
      v = s->changed.value.load(std::memory_order_acquire);
    }
    if (monotonic_time_us() >= deadline) {
      cntl->SetFailed(kECollAbort,
                      "coll-abort: serve readiness timed out (peer "
                      "stalled a step behind)");
      done();
      return;
    }
    if (ready_blocked) {
      // Blocked on the producer's stamp: park on the ready map (woken
      // the instant the range is stamped), sliced so abort/teardown is
      // still observed promptly.
      rma_ready_wait(ready_handle, w.shard_off, w.len,
                     std::min(deadline, monotonic_time_us() + 10 * 1000));
    } else {
      s->changed.wait(v, deadline);
    }
  }
  const char* base = from_recv ? s->dst : s->send_base;
  auto* ctx = new ServeCtx{s};
  resp->append_user_data(const_cast<char*>(base) + w.shard_off, w.len,
                         &serve_deleter, ctx);
  wake_session(s.get());
  done();
}

void handle_abort(Controller* cntl, const IOBuf& req, IOBuf* resp,
                  Closure done) {
  CollPutWire w;
  if (!parse_put_wire(req, &w)) {
    cntl->SetFailed(EINVAL, "bad Coll.Abort request");
    done();
    return;
  }
  const int code = w.flags != 0 ? static_cast<int>(w.flags) : kECollAbort;
  const uint64_t key = session_key(w.group_id, w.run_seq, w.dst_rank);
  std::shared_ptr<RecvSession> s = find_session(key);
  if (s != nullptr) {
    {
      std::lock_guard<std::mutex> g(s->mu);
      if (s->abort_code == 0) {
        s->abort_code = code;
        s->abort_why = "peer rank " + std::to_string(w.src_rank) +
                       " failed step " + std::to_string(w.step);
      }
    }
    wake_session(s.get());
  } else {
    poison_run(key, code);
  }
  (void)resp;
  done();
}

bool parse_shardings(const IOBuf& req, size_t off, const ReshardReqWire& h,
                     Sharding* src, Sharding* dst) {
  constexpr uint32_t kMaxRanges = 4096;
  if (h.nsrc == 0 || h.ndst == 0 || h.nsrc > kMaxRanges ||
      h.ndst > kMaxRanges ||
      req.size() < off + (static_cast<size_t>(h.nsrc) + h.ndst) *
                             sizeof(ShardRangeWire)) {
    return false;
  }
  src->total = h.total;
  dst->total = h.total;
  for (uint32_t i = 0; i < h.nsrc + h.ndst; ++i) {
    ShardRangeWire rw;
    req.copy_to(&rw, sizeof(rw), off + i * sizeof(rw));
    ShardRange r;
    r.rank = rw.rank;
    r.off = rw.off;
    r.len = rw.len;
    (i < h.nsrc ? src : dst)->ranges.push_back(r);
  }
  return sharding_valid(*src, h.nmembers) &&
         sharding_valid(*dst, h.nmembers);
}

void handle_reshard_plan(Controller* cntl, const IOBuf& req, IOBuf* resp,
                         Closure done) {
  ReshardReqWire h;
  if (req.size() < sizeof(h)) {
    cntl->SetFailed(EINVAL, "bad Reshard.Plan request");
    done();
    return;
  }
  req.copy_to(&h, sizeof(h));
  Sharding src, dst;
  if (h.nmembers == 0 || h.nmembers > 4096 ||
      !parse_shardings(req, sizeof(h), h, &src, &dst)) {
    cntl->SetFailed(kECollMismatch, "coll-mismatch: bad shardings");
    done();
    return;
  }
  const TransferSchedule plan = plan_reshard(src, dst, h.nmembers);
  ReshardPlanWire out;
  memset(&out, 0, sizeof(out));
  out.bytes_moved = plan.bytes_moved();
  out.bytes_reused = plan.bytes_reused();
  out.naive_bytes = reshard_naive_bytes(src, h.nmembers);
  out.steps = static_cast<uint32_t>(plan.steps.size());
  for (const CollStep& s : plan.steps) {
    out.transfers += static_cast<uint32_t>(s.puts.size());
  }
  resp->append(&out, sizeof(out));
  coll_vars().reshard_plans_total << 1;
  done();
}

// Reshard.Execute state: cached GroupChannels (keyed by member-list
// hash) and the dst-shard regions this node allocated per block id, so a
// re-execute replaces (withdraw + free) instead of leaking.
struct ReshardHost {
  std::mutex mu;
  std::unordered_map<uint64_t, std::shared_ptr<GroupChannel>> groups;
  std::unordered_map<uint64_t, void*> owned_regions;  // block id → base
};

ReshardHost& reshard_host() {
  static ReshardHost* h = new ReshardHost();
  return *h;
}

void handle_reshard_execute(Controller* cntl, const IOBuf& req, IOBuf* resp,
                            Closure done) {
  ReshardReqWire h;
  if (req.size() < sizeof(h)) {
    cntl->SetFailed(EINVAL, "bad Reshard.Execute request");
    done();
    return;
  }
  req.copy_to(&h, sizeof(h));
  if (h.run_id == 0 || h.nmembers == 0 || h.nmembers > 256 ||
      h.my_rank >= h.nmembers ||
      req.size() < sizeof(h) + static_cast<uint64_t>(h.nmembers) * 64) {
    cntl->SetFailed(kECollMismatch,
                    "coll-mismatch: bad member list (run_id must be "
                    "nonzero — the cached group is shared)");
    done();
    return;
  }
  std::vector<std::string> members(h.nmembers);
  for (uint32_t i = 0; i < h.nmembers; ++i) {
    char row[64];
    req.copy_to(row, sizeof(row), sizeof(h) + i * 64);
    row[63] = '\0';
    members[i] = row;
  }
  Sharding src, dst;
  if (!parse_shardings(req, sizeof(h) + h.nmembers * 64, h, &src, &dst)) {
    cntl->SetFailed(kECollMismatch, "coll-mismatch: bad shardings");
    done();
    return;
  }
  // Source bytes: the published KV block src_block_base + my_rank — the
  // PR 11 registry IS the group's addressing layer.
  const uint64_t src_block = h.src_block_base + h.my_rank;
  const char* src_ptr = nullptr;
  uint64_t src_len = 0;
  std::shared_ptr<RmaMapping> src_map;
  if (kv_store().pin(src_block, 0, &src_ptr, &src_len, &src_map, nullptr) !=
      0) {
    cntl->SetFailed(kEKvMiss, "kv-miss: source shard block " +
                                  std::to_string(src_block) +
                                  " not published on this node");
    done();
    return;
  }
  if (src_len != sharding_local_bytes(src, h.my_rank)) {
    cntl->SetFailed(kECollMismatch,
                    "coll-mismatch: source block bytes != sharding's "
                    "local bytes for this rank");
    done();
    return;
  }
  // Group channel (cached by member list + transport).
  std::shared_ptr<GroupChannel> group;
  {
    std::string ident;
    for (const std::string& m : members) {
      ident += m;
      ident += '\n';
    }
    const uint64_t gkey =
        fnv1a(ident.data(), ident.size()) ^ (h.use_shm ? 1 : 0) ^
        (static_cast<uint64_t>(h.my_rank) << 32);
    ReshardHost& host = reshard_host();
    std::lock_guard<std::mutex> g(host.mu);
    auto it = host.groups.find(gkey);
    if (it != host.groups.end()) {
      group = it->second;
    } else {
      group = std::make_shared<GroupChannel>();
      GroupChannel::Options gopts;
      gopts.timeout_ms = h.timeout_ms > 0 ? h.timeout_ms : 30000;
      gopts.use_shm = h.use_shm != 0;
      if (group->Init(members, h.my_rank, &gopts) != 0) {
        cntl->SetFailed(EINVAL, "coll: group init failed");
        done();
        return;
      }
      host.groups[gkey] = group;
    }
  }
  const uint64_t dst_len = sharding_local_bytes(dst, h.my_rank);
  uint64_t dst_rkey = 0;
  char* dst_ptr = static_cast<char*>(rma_alloc(dst_len, &dst_rkey));
  if (dst_ptr == nullptr) {
    cntl->SetFailed(ENOMEM, "coll: cannot allocate the target shard");
    done();
    return;
  }
  const TransferSchedule plan = plan_reshard(src, dst, h.nmembers);
  const int rc = group->run(plan, src_ptr, src_len, dst_ptr, dst_len,
                            h.run_id);
  if (rc != 0) {
    rma_free(dst_ptr);
    cntl->SetFailed(rc, std::string("coll: reshard run failed (") +
                            coll_op_name(CollOp::kReshard) + ")");
    done();
    return;
  }
  // Publish the resharded shard as dst_block_base + rank: the fleet's
  // new layout is immediately block-addressable.
  const uint64_t dst_block = h.dst_block_base + h.my_rank;
  kv_store().withdraw(dst_block);  // replace semantics (kEKvMiss is fine)
  KvBlockMeta meta;
  const int prc = kv_store().publish(dst_block, dst_ptr, dst_len,
                                     /*lease_ms=*/0, &meta);
  if (prc != 0) {
    rma_free(dst_ptr);
    cntl->SetFailed(prc, "coll: publishing the resharded block failed");
    done();
    return;
  }
  {
    ReshardHost& host = reshard_host();
    std::lock_guard<std::mutex> g(host.mu);
    auto it = host.owned_regions.find(dst_block);
    if (it != host.owned_regions.end()) {
      rma_free(it->second);  // previous layout's region: munmap deferred
    }
    host.owned_regions[dst_block] = dst_ptr;
  }
  coll_vars().reshard_execs_total << 1;
  uint64_t out[2] = {dst_len, meta.generation};
  resp->append(out, sizeof(out));
  done();
}

}  // namespace

int coll_attach(Server* s) {
  coll_ensure_registered();
  kv_ensure_registered();
  int rcs[5];
  rcs[0] = s->RegisterMethod(kCollPutMethod, handle_put);
  rcs[1] = s->RegisterMethod(kCollGetMethod, handle_get);
  rcs[2] = s->RegisterMethod(kCollAbortMethod, handle_abort);
  rcs[3] = s->RegisterMethod(kReshardPlanMethod, handle_reshard_plan);
  rcs[4] = s->RegisterMethod(kReshardExecuteMethod, handle_reshard_execute);
  return rcs[0] == 0 && rcs[1] == 0 && rcs[2] == 0 && rcs[3] == 0 &&
                 rcs[4] == 0
             ? 0
             : -1;
}

// ---- GroupChannel --------------------------------------------------------

GroupChannel::~GroupChannel() = default;

int GroupChannel::init_channels(const Options* opts) {
  if (opts != nullptr) {
    opts_ = *opts;
  }
  group_id_ = 0;
  std::string ident;
  for (const std::string& m : members_) {
    ident += m;
    ident += '\n';
  }
  group_id_ = fnv1a(ident.data(), ident.size());
  chans_.clear();
  chans_.resize(members_.size());
  for (size_t r = 0; r < members_.size(); ++r) {
    if (r == my_rank_) {
      continue;  // local moves never ride a channel
    }
    auto ch = std::make_unique<Channel>();
    Channel::Options copts;
    copts.timeout_ms = opts_.timeout_ms;
    copts.use_shm = opts_.use_shm;
    copts.connection_type = "single";
    if (ch->Init(members_[r], &copts) != 0) {
      return -1;
    }
    chans_[r] = std::move(ch);
  }
  return 0;
}

int GroupChannel::Init(const std::vector<std::string>& members,
                       uint32_t my_rank, const Options* opts) {
  if (members.empty() || my_rank >= members.size()) {
    return -1;
  }
  members_ = members;
  my_rank_ = my_rank;
  naming_registry_.clear();
  coll_ensure_registered();
  return init_channels(opts);
}

int GroupChannel::InitNaming(const std::string& naming_url,
                             const std::string& self_addr,
                             const Options* opts) {
  constexpr const char* kScheme = "naming://";
  if (naming_url.rfind(kScheme, 0) != 0) {
    return -1;
  }
  const std::string rest = naming_url.substr(strlen(kScheme));
  const size_t slash = rest.find('/');
  if (slash == std::string::npos || slash + 1 >= rest.size()) {
    return -1;
  }
  naming_registry_ = rest.substr(0, slash);
  naming_service_ = rest.substr(slash + 1);
  naming_ch_ = std::make_unique<Channel>();
  Channel::Options copts;
  copts.timeout_ms = opts != nullptr ? opts->timeout_ms : 30000;
  if (naming_ch_->Init(naming_registry_, &copts) != 0) {
    return -1;
  }
  std::vector<NamingMember> view;
  uint64_t version = 0;
  if (naming_resolve(naming_ch_.get(), naming_service_, &view, &version) !=
      0) {
    return -1;
  }
  // Deterministic rank order: every member resolves the same view and
  // sorts by address.  Draining members have withdrawn (Server::Drain
  // runs the naming hook FIRST) and are absent by construction.
  std::vector<std::string> members;
  for (const NamingMember& m : view) {
    members.push_back(m.addr);
  }
  std::sort(members.begin(), members.end());
  auto self = std::find(members.begin(), members.end(), self_addr);
  if (self == members.end()) {
    return -1;  // not a member of the snapshot
  }
  members_ = std::move(members);
  my_rank_ = static_cast<uint32_t>(self - members_.begin());
  naming_version_ = version;
  coll_ensure_registered();
  return init_channels(opts);
}

int GroupChannel::check_epoch() {
  if (naming_registry_.empty()) {
    return 0;  // explicit group: membership is the caller's contract
  }
  std::vector<NamingMember> view;
  uint64_t version = 0;
  if (naming_resolve(naming_ch_.get(), naming_service_, &view, &version) !=
      0) {
    return 0;  // registry unreachable: no verdict — do not kill the run
  }
  if (version != naming_version_) {
    coll_vars().epoch_fails_total << 1;
    return kECollEpoch;
  }
  return 0;
}

namespace {

// One in-flight Coll.Put chunk.  Owned by the run until every chunk
// completed (complete_locked_call may touch the controller after done
// runs, so contexts outlive their dones and are reaped at run end).
struct PutCtx {
  Controller cntl;
  IOBuf req;
  IOBuf resp;
};

struct RunState {
  std::shared_ptr<RecvSession> sess;
  // Relaxed would do for the counter alone, but the release/acquire
  // pair orders the done-closure's failure write before the waiter's
  // read (see on_done / wait below).
  std::atomic<uint32_t> outstanding{0};
  std::atomic<int> fail_code{0};
  std::mutex mu;  // guards fail_why + ctxs
  std::string fail_why;
  std::vector<std::unique_ptr<PutCtx>> ctxs;
};

}  // namespace

int GroupChannel::run(const TransferSchedule& plan, const void* sendbuf,
                      uint64_t send_len, void* recvbuf, uint64_t recv_len,
                      uint64_t run_seq, uint64_t ready) {
  coll_vars().runs_total << 1;
  if (plan.nmembers != nmembers() || my_rank_ >= plan.nmembers) {
    return kECollMismatch;
  }
  const bool reduce_op = plan.op == CollOp::kReduceScatter;
  // The arrival target: recvbuf, or the accumulator (= sendbuf, which
  // reduce ops MUTATE — the documented in-place ring contract).
  char* acc = reduce_op ? static_cast<char*>(const_cast<void*>(sendbuf))
                        : static_cast<char*>(recvbuf);
  const uint64_t acc_len = reduce_op ? send_len : recv_len;
  // Validate every extent the plan references against the caller's
  // buffers before a single byte moves.
  std::vector<uint64_t> expected(plan.steps.size(), 0);
  for (size_t s = 0; s < plan.steps.size(); ++s) {
    for (const CollTransfer& t : plan.steps[s].puts) {
      if (t.reduce && (t.len % 4 != 0 || t.dst_off % 4 != 0)) {
        return kECollMismatch;  // u32 reduction needs aligned words
      }
      if (t.src == my_rank_) {
        const uint64_t lim = t.src_from_recv ? recv_len : send_len;
        if (t.src_off + t.len > lim) {
          return kECollMismatch;
        }
      }
      if (t.dst == my_rank_) {
        if (t.dst_off + t.len > acc_len) {
          return kECollMismatch;
        }
        expected[s] += t.len;
      }
    }
  }
  for (const CollTransfer& t : plan.local_copies) {
    if (t.src == my_rank_ &&
        (t.src_off + t.len > send_len || t.dst_off + t.len > acc_len)) {
      return kECollMismatch;
    }
  }
  for (const CollTransfer& t : plan.final_copies) {
    if (t.src == my_rank_ &&
        (t.src_off + t.len > acc_len || t.dst_off + t.len > recv_len)) {
      return kECollMismatch;
    }
  }
  if (run_seq == 0) {
    run_seq = ++run_counter_;
  }
  // Pull serves this member owes per step (copy transfers are gets BY
  // the destination; my step is complete only once my peers pulled it).
  std::vector<uint64_t> expected_serve(plan.steps.size(), 0);
  for (size_t s = 0; s < plan.steps.size(); ++s) {
    for (const CollTransfer& t : plan.steps[s].puts) {
      if (t.src == my_rank_ && !t.reduce) {
        expected_serve[s] += t.len;
      }
    }
  }

  // Heap-owned run state, CO-OWNED by every done closure: a completion's
  // tail (the counter decrement + wake) can run a beat after the waiter
  // observed outstanding == 0 and run() tore down — stack state or a raw
  // session pointer there would be a use-after-free under exactly the
  // loaded schedules collectives create.
  auto rs_owner = std::make_shared<RunState>();
  RunState& rs = *rs_owner;
  int poison = 0;
  rs.sess = register_session(group_id_, run_seq, my_rank_, acc, acc_len,
                             static_cast<const char*>(sendbuf), send_len,
                             expected, expected_serve, &poison, ready);
  if (rs.sess == nullptr) {
    coll_vars().aborts_total << 1;
    return poison != 0 ? poison : kECollAbort;
  }

  const uint64_t chunk_bytes =
      static_cast<uint64_t>(flag_val(chunk_flag(), 8 << 20));
  const uint32_t inflight =
      static_cast<uint32_t>(flag_val(inflight_flag(), 4));
  auto fail = [&](int code, const std::string& why) {
    int want = 0;
    if (rs.fail_code.compare_exchange_strong(want, code,
                                             std::memory_order_acq_rel)) {
      std::lock_guard<std::mutex> g(rs.mu);
      rs.fail_why = why;
    }
  };
  auto failed = [&]() -> int {
    // Acquire pairs with fail()'s release store.
    int code = rs.fail_code.load(std::memory_order_acquire);
    if (code == 0) {
      std::lock_guard<std::mutex> g(rs.sess->mu);
      code = rs.sess->abort_code;
    }
    return code;
  };
  // Parks until [off, off+len) of the send buffer is stamped ready,
  // sliced so peer aborts/failures stay promptly observed.  0, or the
  // error code the step fails with.
  auto wait_ready = [&](uint64_t off, uint64_t len,
                        int64_t rdl) -> int {
    while (true) {
      const int r = rma_ready_test(ready, off, len);
      if (r == 1) {
        return 0;
      }
      if (r < 0) {
        return kECollMismatch;  // dep outside the map: plan/map mismatch
      }
      int code;
      {
        std::lock_guard<std::mutex> g(rs.sess->mu);
        code = rs.sess->abort_code;
      }
      if (code != 0) {
        return code;
      }
      const int64_t now = monotonic_time_us();
      if (now >= rdl) {
        return ETIMEDOUT;
      }
      rma_ready_wait(ready, off, len, std::min(rdl, now + 10 * 1000));
    }
  };

  const bool overlap = ready != 0 && overlap_enabled();
  if (overlap) {
    coll_vars().overlap_runs_total << 1;
  }
  int rc = 0;
  // Entry budget for producer stamps (the step budget, ambient-folded —
  // the PR 15 deadline plane reaches a stalled producer too).
  int64_t entry_deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  {
    const int64_t amb = ambient_deadline();
    if (amb != 0) {
      entry_deadline = std::min(entry_deadline, amb);
    }
  }
  if (ready != 0 && !overlap) {
    // Overlap off: wait ONCE for everything this rank will ever read
    // from its send buffer, then run the unchanged barrier path —
    // byte-identical semantics, single wait.
    const uint64_t extent = plan_producer_extent(plan, my_rank_);
    if (extent != 0) {
      const int wrc = wait_ready(0, extent, entry_deadline);
      if (wrc != 0) {
        rc = wrc == ETIMEDOUT ? kEDeadlineExpired : wrc;
        fail(rc, "send buffer never stamped ready (producer stalled)");
      }
    }
  }

  // Local moves first: the member's own bytes never ride the fabric.
  // Overlap mode gates each copy on its input stamp (the producer may
  // still be filling later ranges).
  for (const CollTransfer& t : plan.local_copies) {
    if (t.src != my_rank_ || rc != 0) {
      continue;
    }
    if (overlap) {
      const CollDep d = transfer_input_dep(t);
      if (d.len != 0) {
        const int wrc = wait_ready(d.off, d.len, entry_deadline);
        if (wrc != 0) {
          rc = wrc == ETIMEDOUT ? kEDeadlineExpired : wrc;
          fail(rc, "local copy input never stamped ready");
          break;
        }
        record_coll_ready(0, d.off, d.len);
      }
    }
    memcpy(acc + t.dst_off,
           static_cast<const char*>(sendbuf) + t.src_off, t.len);
  }
  uint32_t steps_done = 0;
  for (size_t s = 0; s < plan.steps.size() && rc == 0; ++s) {
    const int64_t step_start = monotonic_time_us();
    int64_t deadline = step_start + opts_.timeout_ms * 1000;
    // Deadline plane (net/deadline.h): the serving request's remaining
    // budget bounds every step — an expired budget aborts the schedule
    // whole-or-nothing through the same group-abort path a failed put
    // takes, instead of grinding out steps nobody is waiting for.
    const int64_t amb = ambient_deadline();
    if (amb != 0) {
      if (step_start >= amb) {
        rc = kEDeadlineExpired;
        fail(rc, "caller deadline expired before step " +
                     std::to_string(s));
        break;
      }
      deadline = std::min(deadline, amb);
    }
    if ((rc = check_epoch()) != 0) {
      fail(rc, "membership epoch moved under the schedule");
      break;
    }
    uint64_t step_bytes = 0;
    // Shared (not raw) handles for the done closures — see rs_owner.
    std::shared_ptr<RunState> rsp = rs_owner;
    std::shared_ptr<RecvSession> sess = rs.sess;
    // Bound the in-flight window (trpc_coll_inflight): transfer k+1
    // overlaps transfer k's verification, never more than the window.
    auto throttle = [&]() {
      while (rs.outstanding.load(std::memory_order_acquire) >= inflight) {
        if ((rc = failed()) != 0 || monotonic_time_us() > deadline) {
          rc = rc != 0 ? rc : ETIMEDOUT;
          return;
        }
        const uint32_t v =
            // Acquire pairs with wake_session's release bump.
            rs.sess->changed.value.load(std::memory_order_acquire);
        if (rs.outstanding.load(std::memory_order_acquire) >= inflight) {
          rs.sess->changed.wait(v, monotonic_time_us() + 20 * 1000);
        }
      }
    };
    auto fail_call = [rsp](size_t step, const char* what,
                           Controller* cntl) {  // rsp: shared, see above
      int want = 0;
      const int code =
          cntl->error_code() != 0 ? cntl->error_code() : kECollAbort;
      if (rsp->fail_code.compare_exchange_strong(
              want, code, std::memory_order_acq_rel)) {
        std::lock_guard<std::mutex> g(rsp->mu);
        rsp->fail_why = std::string(what) + " failed at step " +
                        std::to_string(step) + ": " + cntl->error_text();
      }
    };
    for (const CollTransfer& t : plan.steps[s].puts) {
      if (rc != 0) {
        break;
      }
      if (t.dst == my_rank_ && !t.reduce) {
        // PULL: one Coll.Get per transfer, landing DIRECT in my
        // registered buffer slice — the serving member's rails write
        // the bytes straight into place, one memcpy end to end.  (The
        // transfer is chunked INSIDE the one-sided put by the rma
        // plane; trpc_coll_chunk_bytes governs the push path below.)
        throttle();
        if (rc != 0) {
          break;
        }
        CollPutWire w;
        memset(&w, 0, sizeof(w));
        w.group_id = group_id_;
        w.run_seq = run_seq;
        w.op = static_cast<uint32_t>(plan.op);
        w.src_rank = t.src;
        w.step = static_cast<uint32_t>(s);
        w.nchunks = 1;
        w.flags = t.src_from_recv ? kCollFlagFromRecv : 0;
        w.dst_off = t.dst_off;
        w.len = t.len;
        w.shard_off = t.src_off;  // source-buffer offset to serve
        w.shard_len = t.len;
        w.dst_rank = my_rank_;
        auto ctx = std::make_unique<PutCtx>();
        ctx->req.append(&w, sizeof(w));
        ctx->cntl.set_timeout_ms(opts_.timeout_ms);
        char* target = acc + t.dst_off;
        ctx->cntl.call().land_buf = target;
        ctx->cntl.call().land_cap = t.len;
        PutCtx* raw = ctx.get();
        {
          std::lock_guard<std::mutex> g(rs.mu);
          rs.ctxs.push_back(std::move(ctx));
        }
        // Release on the increment: the context set up above is
        // published before the done closure can observe the counter.
        rs.outstanding.fetch_add(1, std::memory_order_release);
        coll_vars().puts_total << 1;
        coll_vars().put_bytes << static_cast<int64_t>(t.len);
        step_bytes += t.len;
        const uint64_t want_len = t.len;
        chans_[t.src]->CallMethod(
            kCollGetMethod, raw->req, &raw->resp, &raw->cntl,
            [rsp, sess, raw, s, target, want_len]() {
              if (raw->cntl.Failed()) {
                int want = 0;
                const int code = raw->cntl.error_code() != 0
                                     ? raw->cntl.error_code()
                                     : kECollAbort;
                if (rsp->fail_code.compare_exchange_strong(
                        want, code, std::memory_order_acq_rel)) {
                  std::lock_guard<std::mutex> g(rsp->mu);
                  rsp->fail_why = "get failed at step " +
                                  std::to_string(s) + ": " +
                                  raw->cntl.error_text();
                }
              } else if (raw->resp.size() != want_len) {
                int want = 0;
                if (rsp->fail_code.compare_exchange_strong(
                        want, kECollMismatch, std::memory_order_acq_rel)) {
                  std::lock_guard<std::mutex> g(rsp->mu);
                  rsp->fail_why = "get answered the wrong length";
                }
              } else {
                // Landed in place (direct put / striped landing)?  If
                // the response is a bounce view instead, place it now.
                const bool in_place =
                    raw->resp.block_count() == 1 &&
                    raw->resp.ref_at(0).block->data +
                            raw->resp.ref_at(0).offset ==
                        target;
                if (!in_place) {
                  raw->resp.copy_to(target, want_len);
                }
                {
                  std::lock_guard<std::mutex> g(sess->mu);
                  sess->arrived_bytes[s] += want_len;
                }
              }
              // Release orders the placement (and any failure write)
              // before the waiter's acquire observation.  rsp/sess are
              // shared_ptr copies: this tail may outlive run().
              rsp->outstanding.fetch_sub(1, std::memory_order_release);
              wake_session(sess.get());
            });
        continue;
      }
      if (t.src != my_rank_ || !t.reduce) {
        continue;  // not mine to initiate (pulled by its destination)
      }
      // PUSH (reduce transfers): chunked Coll.Put — the receiver folds
      // each chunk into its accumulator.
      const char* base = acc;  // reduce reads the accumulator (sendbuf)
      const uint32_t nchunks = static_cast<uint32_t>(
          (t.len + chunk_bytes - 1) / chunk_bytes);
      for (uint32_t c = 0; c < nchunks && rc == 0; ++c) {
        const uint64_t off = static_cast<uint64_t>(c) * chunk_bytes;
        const uint64_t len = std::min(chunk_bytes, t.len - off);
        if (overlap && !t.src_from_recv) {
          // Readiness-triggered push: this chunk fires the moment the
          // producer stamped its input range — the T3 per-chunk
          // overlap seam.  A producer that never stamps trips the step
          // deadline (whole-or-nothing), never a wedge.
          const int wrc = wait_ready(t.src_off + off, len, deadline);
          if (wrc != 0) {
            rc = wrc;
            fail(rc, "push input never stamped ready at step " +
                         std::to_string(s));
            break;
          }
          record_coll_ready(static_cast<uint32_t>(s), t.src_off + off,
                            len);
        }
        throttle();
        if (rc != 0) {
          break;
        }
        CollPutWire w;
        memset(&w, 0, sizeof(w));
        w.group_id = group_id_;
        w.run_seq = run_seq;
        w.op = static_cast<uint32_t>(plan.op);
        w.src_rank = my_rank_;
        w.step = static_cast<uint32_t>(s);
        w.nchunks = nchunks;
        w.chunk = c;
        w.flags = kCollFlagReduce;
        w.dst_off = t.dst_off + off;
        w.len = len;
        w.shard_off = t.dst_off;
        w.shard_len = t.len;
        w.dst_rank = t.dst;
        auto ctx = std::make_unique<PutCtx>();
        ctx->req.append(&w, sizeof(w));
        // Zero-copy payload ref: the caller's buffer outlives the run
        // (run() only returns after every chunk completed or was
        // cancelled), so no deleter is needed.
        ctx->req.append_user_data(
            const_cast<char*>(base) + t.src_off + off, len, &noop_deleter);
        ctx->cntl.set_timeout_ms(opts_.timeout_ms);
        PutCtx* raw = ctx.get();
        {
          std::lock_guard<std::mutex> g(rs.mu);
          rs.ctxs.push_back(std::move(ctx));
        }
        // Release on the increment: the context set up above is
        // published before the done closure can observe the counter.
        rs.outstanding.fetch_add(1, std::memory_order_release);
        coll_vars().puts_total << 1;
        coll_vars().put_bytes << static_cast<int64_t>(len);
        step_bytes += len;
        chans_[t.dst]->CallMethod(
            kCollPutMethod, raw->req, &raw->resp, &raw->cntl,
            [rsp, sess, raw, s, fail_call]() {
              if (raw->cntl.Failed()) {
                fail_call(s, "put", &raw->cntl);
              }
              // Release orders this chunk's completion (and any failure
              // write) before the waiter's acquire observation.  rsp/
              // sess are shared_ptr copies: this tail may outlive run().
              rsp->outstanding.fetch_sub(1, std::memory_order_release);
              wake_session(sess.get());
            });
      }
    }
    // Step barrier: my transfers acked (each ack IS the tiny per-put
    // control frame), my expected receives landed, and my peers' pulls
    // of this step's data served.
    while (rc == 0) {
      if ((rc = failed()) != 0) {
        break;
      }
      bool sends_done =
          rs.outstanding.load(std::memory_order_acquire) == 0;
      bool recvs_done;
      bool serves_done;
      uint32_t v;
      {
        std::lock_guard<std::mutex> g(rs.sess->mu);
        recvs_done = rs.sess->arrived_bytes[s] >= expected[s];
        serves_done = rs.sess->served_bytes[s] >= expected_serve[s];
        // Acquire pairs with wake_session's release bump.
        v = rs.sess->changed.value.load(std::memory_order_acquire);
      }
      if (sends_done && recvs_done && serves_done) {
        break;
      }
      if (monotonic_time_us() > deadline) {
        rc = ETIMEDOUT;
        fail(ETIMEDOUT, "step " + std::to_string(s) + " timed out");
        break;
      }
      rs.sess->changed.wait(v, monotonic_time_us() + 20 * 1000);
    }
    if (rc == 0) {
      steps_done += 1;
      coll_vars().steps_total << 1;
      record_coll_step(plan.op, static_cast<uint32_t>(s), step_bytes);
      coll_vars().step_lat(plan.op)
          << (monotonic_time_us() - step_start);
      {
        // Contexts of a completed step are dead weight; reap them so a
        // many-step schedule's memory stays bounded by one step.
        std::lock_guard<std::mutex> g(rs.mu);
        rs.ctxs.clear();
      }
    }
  }

  if (rc != 0) {
    coll_vars().aborts_total << 1;
    {
      std::lock_guard<std::mutex> g(rs.mu);
      LOG(Warning) << "coll run failed rank=" << my_rank_ << " op="
                   << coll_op_name(plan.op) << " rc=" << rc << " why="
                   << rs.fail_why << " abort_why=" << rs.sess->abort_why;
    }
    // Cancel the still-in-flight chunks, then drain them: the contexts
    // (and the caller's buffers) must not be touched by a late
    // completion after run() returns.
    {
      std::lock_guard<std::mutex> g(rs.mu);
      for (auto& c : rs.ctxs) {
        StartCancel(c->cntl.call_id());
      }
    }
    while (rs.outstanding.load(std::memory_order_acquire) != 0) {
      const uint32_t v =
          // Acquire pairs with the done closures' release decrement.
          rs.sess->changed.value.load(std::memory_order_acquire);
      if (rs.outstanding.load(std::memory_order_acquire) != 0) {
        rs.sess->changed.wait(v, monotonic_time_us() + 50 * 1000);
      }
    }
    // Tell the group: the step failed for everyone (whole-or-nothing).
    CollPutWire w;
    memset(&w, 0, sizeof(w));
    w.group_id = group_id_;
    w.run_seq = run_seq;
    w.op = static_cast<uint32_t>(plan.op);
    w.src_rank = my_rank_;
    w.step = steps_done;
    w.flags = static_cast<uint32_t>(rc);
    for (size_t r = 0; r < chans_.size(); ++r) {
      if (chans_[r] == nullptr) {
        continue;
      }
      w.dst_rank = static_cast<uint32_t>(r);  // per-peer session key
      IOBuf abort_req;
      abort_req.append(&w, sizeof(w));
      Controller cntl;
      cntl.set_timeout_ms(std::min<int64_t>(2000, opts_.timeout_ms));
      IOBuf resp;
      chans_[r]->CallMethod(kCollAbortMethod, abort_req, &resp, &cntl);
      // Best effort: an unreachable peer fails its own step anyway.
    }
  } else {
    for (const CollTransfer& t : plan.final_copies) {
      if (t.src == my_rank_) {
        memcpy(static_cast<char*>(recvbuf) + t.dst_off, acc + t.src_off,
               t.len);
      }
    }
  }
  unregister_session(rs.sess);
  return rc;
}

int GroupChannel::all_gather(const void* sendbuf, uint64_t shard_bytes,
                             void* recvbuf, uint64_t recv_len) {
  return run(plan_all_gather(nmembers(), shard_bytes), sendbuf, shard_bytes,
             recvbuf, recv_len);
}

int GroupChannel::reduce_scatter(void* sendbuf, uint64_t send_len,
                                 void* recvbuf, uint64_t shard_bytes) {
  return run(plan_reduce_scatter(nmembers(), shard_bytes), sendbuf,
             send_len, recvbuf, shard_bytes);
}

int GroupChannel::all_to_all(const void* sendbuf, uint64_t send_len,
                             void* recvbuf, uint64_t recv_len) {
  // A remainder would silently drop the tail bytes (shard floors).
  if (nmembers() == 0 || send_len % nmembers() != 0) {
    return kECollMismatch;
  }
  return run(plan_all_to_all(nmembers(), send_len / nmembers()), sendbuf,
             send_len, recvbuf, recv_len);
}

int GroupChannel::reshard(const Sharding& src, const Sharding& dst,
                          const void* sendbuf, uint64_t send_len,
                          void* recvbuf, uint64_t recv_len,
                          uint64_t run_seq) {
  if (!sharding_valid(src, nmembers()) || !sharding_valid(dst, nmembers()) ||
      src.total != dst.total) {
    return kECollMismatch;
  }
  return run(plan_reshard(src, dst, nmembers()), sendbuf, send_len,
             recvbuf, recv_len, run_seq);
}

}  // namespace trpc
