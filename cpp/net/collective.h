// Collectives as RPC — group put schedules over the RMA fabric +
// array-resharding service (ISSUE 13 tentpole).
//
// No brpc parity: the reference stops at point-to-point channels.  This
// layer expresses all-gather / reduce-scatter / all-to-all — and generic
// array redistribution between arbitrary shardings — as *planned sets of
// one-sided RMA puts* over the shm/ICI mesh: every transfer in a
// TransferSchedule is a Coll.Put RPC whose MB-scale body rides the PR 10
// one-sided plane (multi-rail chunked puts into the peer's registered
// window, completion-bitmap + per-chunk CRC verification), and the RPC
// response is the tiny per-put control/ack frame.  Chunking follows T3
// (arXiv 2401.16677): each transfer is cut into trpc_coll_chunk_bytes
// chunks issued trpc_coll_inflight deep, so member i's step k+1 puts
// overlap member j's step k verification — there is no global barrier,
// only the data dependencies the ring schedules impose.  The resharding
// planner applies the portable-collectives decomposition of
// "Memory-efficient array redistribution" (arXiv 2112.01075): the
// redistribution factors into a put set that moves ONLY the bytes whose
// owner changes, reusing locally-resident ranges instead of re-fetching
// them — strictly fewer bytes than a naive full-exchange whenever the
// shardings overlap.
//
// Model:
//  - A GROUP is an ordered member list (explicit, or snapshotted from a
//    naming:// view at Init: members sorted by address so every process
//    derives the same rank order; kEDraining members have withdrawn and
//    are excluded by construction).  The naming VERSION is part of the
//    snapshot: an epoch change mid-schedule fails the current step
//    whole-or-nothing (kECollEpoch) — membership never changes under a
//    running schedule.
//  - A TransferSchedule is compiled deterministically from (op, nmembers,
//    shard bytes) — or from source/target shardings for reshard — so
//    every member compiles the identical plan and no coordinator exists.
//    Steps are the unit of fault atomicity: a dropped/corrupted chunk
//    (whole-or-nothing per put, inherited from the RMA/stripe planes)
//    fails that step for the WHOLE group — the executor aborts its peers
//    (Coll.Abort) and run() fails; a failed run's recv/accumulator
//    buffers are undefined-by-contract, and no step that REPORTED
//    success ever contains torn bytes (a shard is complete only when
//    every chunk landed whole).
//  - Execution is symmetric: every member calls run() with its rank's
//    buffers.  Receives land through the Coll.Put handler, which places
//    each chunk at its offset in the registered destination buffer (or
//    element-wise u32-adds it, for reduce steps) and wakes the local
//    executor's per-step countdown.
//
// The resharding *service* (Reshard.Plan / Reshard.Execute) attaches to
// any Server like the KV registry.  Plan is stateless: shardings in,
// {bytes_moved, bytes_reused, naive_bytes, steps} out.  Execute turns the
// PR 11 KV registry into group-transfer machinery: each member's source
// shard is addressed as a published KV block (src_block_base + rank); the
// handler pins the block's registered pages, allocates a fresh
// exportable region for its target shard, runs the planned schedule over
// the fabric with its peers, and re-publishes the result as
// dst_block_base + rank — a coordinator fans personalized Execute
// requests to the members and the array is resharded in place on the
// fleet.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "base/iobuf.h"

namespace trpc {

class Channel;
class Server;

// Error codes, continuing the 2101..2112 family (kvstore.h, naming.h).
// kECollAbort: a peer failed its part of the step (or the local step
// timed out) — the whole step failed, the run is dead.  kECollEpoch: the
// group's naming view changed mid-schedule; recompile the group and
// re-run.  kECollMismatch: buffer sizes / shardings do not fit the plan.
constexpr int kECollAbort = 2121;
constexpr int kECollEpoch = 2122;
constexpr int kECollMismatch = 2123;

// Method names (tstd, served by coll_attach).  Copy transfers are
// PULL-based (Coll.Get): the destination issues the RPC with its
// registered buffer slice as the landing target, so the serving member
// puts the bytes straight into the getter's memory through the
// direct-landing plane — ONE multi-rail memcpy end to end.  Reduce
// transfers stay PUSH-based (Coll.Put): the receiver's handler folds
// the payload into its accumulator.
inline constexpr const char* kCollPutMethod = "Coll.Put";
inline constexpr const char* kCollGetMethod = "Coll.Get";
inline constexpr const char* kCollAbortMethod = "Coll.Abort";
inline constexpr const char* kReshardPlanMethod = "Reshard.Plan";
inline constexpr const char* kReshardExecuteMethod = "Reshard.Execute";

// Collective ops (also the kCollStep timeline `b` op tags, b = op<<56 |
// step bytes; mirrored by observe.py TIMELINE_COLL_OPS and
// tools/trace_stitch.py).
enum class CollOp : uint32_t {
  kAllGather = 1,
  kReduceScatter = 2,
  kAllToAll = 3,
  kReshard = 4,
};
const char* coll_op_name(CollOp op);

// ---- plans ---------------------------------------------------------------

// One planned put: `src` rank writes `len` bytes read from its local
// buffer at `src_off` into rank `dst`'s destination buffer at `dst_off`.
// src_from_recv: the bytes are read from the RECEIVE/accumulator buffer
// (ring forwarding) instead of the send buffer.  reduce: the receiver
// element-wise u32-adds instead of copying.
struct CollTransfer {
  uint32_t src = 0;
  uint32_t dst = 0;
  uint64_t src_off = 0;
  uint64_t dst_off = 0;
  uint64_t len = 0;
  bool src_from_recv = false;
  bool reduce = false;
};

// One schedule step: the unit of whole-or-nothing fault semantics.  A
// member may proceed to step k+1 only when its step-k sends are acked
// AND its step-k receives landed (the data dependency the ring imposes).
struct CollStep {
  std::vector<CollTransfer> puts;
};

struct TransferSchedule {
  CollOp op = CollOp::kAllGather;
  uint32_t nmembers = 0;
  uint64_t shard_bytes = 0;  // per-member shard size (0 for reshard)
  std::vector<CollStep> steps;
  // Local memcpys (src rank == dst rank): executed in place, never sent.
  std::vector<CollTransfer> local_copies;
  // Local memcpys applied AFTER the last step (reduce_scatter moves the
  // fully-reduced chunk from the accumulator into recvbuf here).
  std::vector<CollTransfer> final_copies;
  // Bytes the schedule moves over the fabric (sum of cross-member puts).
  uint64_t bytes_moved() const;
  // Bytes reused in place (local copies — the 2112.01075 win).
  uint64_t bytes_reused() const;
};

// ---- input dependencies (overlap-aware execution) ------------------------

// The send-buffer range a transfer READS, compiled from the plan: with a
// readiness map attached (see run() `ready`), the transfer may fire as
// soon as this range is stamped instead of waiting for the whole-buffer
// barrier.  len == 0 means no send-buffer input (the transfer reads the
// receive/accumulator buffer — ring forwarding — whose readiness the
// step barrier already orders).
struct CollDep {
  uint64_t off = 0;
  uint64_t len = 0;
};

// Input dependency of one transfer as executed by rank `src`: its
// send-buffer source range unless src_from_recv.
CollDep transfer_input_dep(const CollTransfer& t);

// Max send-buffer extent (off+len) rank `rank` reads anywhere in the
// plan — the range the barrier path waits on when a readiness map is
// attached with overlap disabled (byte-identical semantics, single
// wait).  0 when the rank never reads its send buffer.
uint64_t plan_producer_extent(const TransferSchedule& plan, uint32_t rank);

// Deterministic ring/pairwise planners — every member compiles the same
// plan from the same arguments.
//   all_gather:     send = shard, recv = n*shard; n-1 ring steps.
//   reduce_scatter: send = n*shard (MUTATED: it is the accumulator),
//                   recv = shard; element type u32, op = add.
//   all_to_all:     send = n*shard (block j for rank j), recv = n*shard
//                   (block i from rank i); n-1 pairwise rounds.
TransferSchedule plan_all_gather(uint32_t nmembers, uint64_t shard_bytes);
TransferSchedule plan_reduce_scatter(uint32_t nmembers,
                                     uint64_t shard_bytes);
TransferSchedule plan_all_to_all(uint32_t nmembers, uint64_t shard_bytes);

// ---- resharding ----------------------------------------------------------

// 1-D sharding descriptor: `total` global bytes covered by disjoint
// ranges, each owned by one member rank.  A rank's LOCAL buffer is its
// ranges concatenated in ascending global offset.
struct ShardRange {
  uint32_t rank = 0;
  uint64_t off = 0;
  uint64_t len = 0;
};
struct Sharding {
  uint64_t total = 0;
  std::vector<ShardRange> ranges;
};
// Validates coverage: ranges sorted+disjoint, covering [0, total), every
// rank < nmembers.
bool sharding_valid(const Sharding& s, uint32_t nmembers);
// Bytes of `rank`'s local buffer under `s`.
uint64_t sharding_local_bytes(const Sharding& s, uint32_t rank);

// Plans the minimal put set moving src-sharded data into dst's layout:
// bytes whose owner does not change become local_copies (reused), the
// rest become puts bucketed into (dst-src) mod n rounds so per-step
// fan-in is bounded.  Offsets in the transfers are LOCAL buffer offsets
// (send = src layout, recv = dst layout).
TransferSchedule plan_reshard(const Sharding& src, const Sharding& dst,
                              uint32_t nmembers);
// The naive full-exchange baseline the plan must beat whenever the
// shardings overlap: every member ships its whole source shard to every
// other member (the all-gather-then-slice strawman).
uint64_t reshard_naive_bytes(const Sharding& src, uint32_t nmembers);

// ---- group ---------------------------------------------------------------

// Channels to a fixed member snapshot.  NOT thread-safe for concurrent
// run() calls on the same instance; every member must issue the same
// sequence of collectives (run_seq ties the wire to the call order).
class GroupChannel {
 public:
  struct Options {
    int64_t timeout_ms = 30000;  // per-put RPC budget AND step budget
    bool use_shm = true;         // shm rings (one-sided puts) to peers
  };

  ~GroupChannel();
  // Explicit member list.  `members[my_rank]` is this process's address;
  // all members must pass the SAME ordered list.  Returns 0 on success.
  int Init(const std::vector<std::string>& members, uint32_t my_rank,
           const Options* opts = nullptr);
  // Snapshot a naming:// view ("naming://registry_host:port/service"):
  // resolves the live member set (drained members have withdrawn and are
  // absent), sorts by address for a deterministic rank order, and
  // records the view VERSION — any later change fails the running step
  // kECollEpoch.  `self_addr` must be a member.  Returns 0 on success.
  int InitNaming(const std::string& naming_url, const std::string& self_addr,
                 const Options* opts = nullptr);

  // Runs one collective.  Buffer contracts per op (see the planners):
  // reduce_scatter MUTATES sendbuf (it is the ring accumulator).  The
  // caller owns both buffers and must keep them alive through the call;
  // a FAILED run leaves recvbuf (and, for reduce, sendbuf) undefined —
  // free or refill before reuse, exactly the RmaBuffer failed-call
  // contract.  run_seq must advance identically on every member; pass 0
  // to use the group's internal call counter.  Returns 0, kECollAbort,
  // kECollEpoch, kECollMismatch, or a transport errno.
  //
  // `ready` (optional): an rma_ready_create handle over THIS member's
  // sendbuf.  The caller stamps ranges as it fills them; transfers whose
  // compiled input dependency (transfer_input_dep) is stamped fire
  // immediately.  With trpc_coll_overlap off the executor instead waits
  // once for the full producer extent before executing the unchanged
  // barrier path — byte-identical results either way.  A producer that
  // never stamps trips the step deadline (whole-or-nothing abort), never
  // a wedge.  0 = no readiness gating (legacy barrier semantics).
  int run(const TransferSchedule& plan, const void* sendbuf,
          uint64_t send_len, void* recvbuf, uint64_t recv_len,
          uint64_t run_seq = 0, uint64_t ready = 0);

  // Convenience wrappers: compile + run.
  int all_gather(const void* sendbuf, uint64_t shard_bytes, void* recvbuf,
                 uint64_t recv_len);
  int reduce_scatter(void* sendbuf, uint64_t send_len, void* recvbuf,
                     uint64_t shard_bytes);
  int all_to_all(const void* sendbuf, uint64_t send_len, void* recvbuf,
                 uint64_t recv_len);
  int reshard(const Sharding& src, const Sharding& dst, const void* sendbuf,
              uint64_t send_len, void* recvbuf, uint64_t recv_len,
              uint64_t run_seq = 0);

  uint32_t my_rank() const { return my_rank_; }
  uint32_t nmembers() const { return static_cast<uint32_t>(members_.size()); }
  const std::vector<std::string>& members() const { return members_; }
  uint64_t group_id() const { return group_id_; }
  uint64_t naming_version() const { return naming_version_; }

 private:
  int init_channels(const Options* opts);
  // Naming-backed groups: re-resolves the view and fails (kECollEpoch)
  // when the version moved.  Explicit groups always pass.
  int check_epoch();

  std::vector<std::string> members_;
  uint32_t my_rank_ = 0;
  uint64_t group_id_ = 0;
  Options opts_;
  std::vector<std::unique_ptr<Channel>> chans_;  // [rank]; null for self
  // Naming snapshot (empty registry addr = explicit group).
  std::string naming_registry_;
  std::string naming_service_;
  std::unique_ptr<Channel> naming_ch_;
  uint64_t naming_version_ = 0;
  uint64_t run_counter_ = 0;
};

// Attaches the native handlers (Coll.Put, Coll.Abort, Reshard.Plan,
// Reshard.Execute) to a not-yet-started server.  Any member of any group
// must serve this; Reshard.Plan may also run on a node that stores
// nothing.  Returns 0, or -1 when a registration was refused.
int coll_attach(Server* s);

// Flag registration (idempotent): trpc_coll_chunk_bytes,
// trpc_coll_inflight, trpc_coll_rendezvous_ms,
// trpc_coll_ready_granularity_bytes, trpc_coll_overlap — the capi calls
// it so /flags sees the knobs before first traffic.
void coll_ensure_registered();

// Current trpc_coll_ready_granularity_bytes value (the default chunk
// granularity for readiness maps created through the C API).
uint64_t coll_ready_default_granularity();

// ---- wire ----------------------------------------------------------------

// Coll.Put / Coll.Abort header (fixed little-endian, 80 bytes; mirrored
// by brpc_tpu/rpc/collective.py _PUT_WIRE — coll-wire marker).  The put
// payload (len bytes) follows the header in the request body, so the
// whole body rides the one-sided plane when it clears the stripe
// threshold.  Abort sends the header alone (step = failing step, flags =
// error code).
// Shared by Coll.Put (push: header + payload in the body), Coll.Get
// (pull: header only — shard_off is the SOURCE-buffer offset to serve,
// the response body is the bytes) and Coll.Abort (header only, flags =
// error code).
struct CollPutWire {
  uint64_t group_id;
  uint64_t run_seq;
  uint32_t op;
  uint32_t src_rank;
  uint32_t step;
  uint32_t nchunks;    // chunks in this transfer (shard)
  uint32_t chunk;      // this chunk's index within the transfer
  uint32_t flags;      // bit 0: reduce-add-u32; bit 1 (Get): serve from
                       // the recv/forwarding buffer; Abort: error code
  uint64_t dst_off;    // destination-buffer offset of THIS chunk
  uint64_t len;        // payload bytes
  uint64_t shard_off;  // Put: dst offset of the whole transfer;
                       // Get: SOURCE-buffer offset to serve from
  uint64_t shard_len;  // bytes of the whole transfer
  // Sessions key on (group, run, rank): one process may host SEVERAL
  // members (in-process groups in tests/bench), and the serving handler
  // cannot tell which local member a connection belongs to — the wire
  // says so.  Put/Abort address dst_rank's session; Get addresses
  // src_rank's (the member being read).
  uint32_t dst_rank;
  uint32_t reserved;
};
static_assert(sizeof(CollPutWire) == 80, "CollPutWire is wire format");
constexpr uint32_t kCollFlagReduce = 1u << 0;
constexpr uint32_t kCollFlagFromRecv = 1u << 1;

// Reshard.Plan / Reshard.Execute header (fixed little-endian, 64 bytes;
// mirrored by brpc_tpu/rpc/collective.py _RESHARD_WIRE — coll-wire
// marker).  Followed by nmembers 64-byte address rows (Execute only;
// Plan sets nmembers to the rank count and sends no rows), then
// (nsrc + ndst) ShardRangeWire rows.  Plan responds with a
// ReshardPlanWire; Execute responds with {u64 dst_len, u64 generation}.
struct ReshardReqWire {
  uint64_t run_id;
  uint64_t src_block_base;  // Execute: kv block id of rank r's source
  uint64_t dst_block_base;  // Execute: block id to publish the result as
  uint64_t total;           // global array bytes
  uint32_t my_rank;         // Execute: the RECEIVER's rank (personalized)
  uint32_t nmembers;
  uint32_t nsrc;
  uint32_t ndst;
  uint32_t use_shm;
  uint32_t timeout_ms;
  uint64_t reserved;
};
static_assert(sizeof(ReshardReqWire) == 64, "ReshardReqWire is wire format");

struct ShardRangeWire {
  uint32_t rank;
  uint32_t reserved;
  uint64_t off;
  uint64_t len;
};
static_assert(sizeof(ShardRangeWire) == 24, "ShardRangeWire is wire format");

// Reshard.Plan response (fixed little-endian, 40 bytes; coll-wire
// marker, mirrored by collective.py _PLAN_WIRE).
struct ReshardPlanWire {
  uint64_t bytes_moved;
  uint64_t bytes_reused;
  uint64_t naive_bytes;
  uint32_t steps;
  uint32_t transfers;
  uint64_t reserved;
};
static_assert(sizeof(ReshardPlanWire) == 40, "ReshardPlanWire is wire format");

// Test/metrics support: receive sessions currently registered in this
// process (0 when no run is in flight — cancel/abort quiescence).
size_t coll_sessions_live();

}  // namespace trpc
