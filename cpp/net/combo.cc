#include "net/combo.h"

#include <errno.h>

#include <algorithm>

#include "base/rand.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/sync.h"

namespace trpc {

namespace {

class PlainSub final : public SubChannel {
 public:
  explicit PlainSub(std::shared_ptr<Channel> ch) : ch_(std::move(ch)) {}
  void Call(const std::string& method, const IOBuf& request, IOBuf* response,
            Controller* cntl) override {
    ch_->CallMethod(method, request, response, cntl);
  }

 private:
  std::shared_ptr<Channel> ch_;
};

class ClusterSub final : public SubChannel {
 public:
  explicit ClusterSub(std::shared_ptr<ClusterChannel> ch)
      : ch_(std::move(ch)) {}
  void Call(const std::string& method, const IOBuf& request, IOBuf* response,
            Controller* cntl) override {
    ch_->CallMethod(method, request, response, cntl);
  }

 private:
  std::shared_ptr<ClusterChannel> ch_;
};

// One fan-out sub-call, run in its own fiber (sub-done aggregation parity,
// parallel_channel.cpp:88-153 — ours is a shared ctx + countdown).  The ctx
// (including the latch) is shared_ptr-held by every fiber so the LAST
// signaler can still be inside the latch when the caller's frame moves on.
// Fibers write only their own cntls[i]/responses[i] slot; success flags are
// derived from the controllers AFTER the join (no concurrent bit-vector
// writes).
struct FanoutCtx {
  explicit FanoutCtx(int n) : latch(n) {
    responses.resize(n);
    cntls.resize(n);
  }
  std::vector<std::shared_ptr<SubChannel>> subs;
  std::string method;
  std::vector<IOBuf> requests;
  std::vector<IOBuf> responses;
  std::vector<Controller> cntls;
  std::vector<bool> oks;  // filled after the join
  CountdownEvent latch;
};

struct FanoutArg {
  std::shared_ptr<FanoutCtx> ctx;
  int index;
};

void fanout_fiber(void* p) {
  std::unique_ptr<FanoutArg> arg(static_cast<FanoutArg*>(p));
  FanoutCtx* ctx = arg->ctx.get();
  const int i = arg->index;
  ctx->subs[i]->Call(ctx->method, ctx->requests[i], &ctx->responses[i],
                     &ctx->cntls[i]);
  ctx->latch.signal();
}

void run_fanout(const std::shared_ptr<FanoutCtx>& ctx) {
  const int n = static_cast<int>(ctx->subs.size());
  for (int i = 0; i < n; ++i) {
    auto* arg = new FanoutArg{ctx, i};
    if (fiber_start(nullptr, fanout_fiber, arg, 0) != 0) {
      // Spawn failure must not hang the join (fiber_start does not take
      // ownership of arg on failure).
      delete arg;
      ctx->cntls[i].SetFailed(EAGAIN, "fiber_start failed");
      ctx->latch.signal();
    }
  }
  ctx->latch.wait(-1);
  ctx->oks.resize(n);
  for (int i = 0; i < n; ++i) {
    ctx->oks[i] = !ctx->cntls[i].Failed();
  }
}

void concat_merger(const std::vector<IOBuf>& subs, const std::vector<bool>& oks,
                   IOBuf* merged) {
  for (size_t i = 0; i < subs.size(); ++i) {
    if (oks[i]) {
      merged->append(subs[i]);
    }
  }
}

}  // namespace

std::shared_ptr<SubChannel> make_sub_channel(std::shared_ptr<Channel> ch) {
  return std::make_shared<PlainSub>(std::move(ch));
}

std::shared_ptr<SubChannel> make_sub_channel(
    std::shared_ptr<ClusterChannel> ch) {
  return std::make_shared<ClusterSub>(std::move(ch));
}

void ParallelChannel::CallMethod(const std::string& method,
                                 const IOBuf& request, IOBuf* response,
                                 Controller* cntl, const Options* opts) {
  if (subs_.empty()) {
    cntl->SetFailed(ENOENT, "no sub channels");
    return;
  }
  fiber_init(0);
  Options defaults;
  const Options& o = opts != nullptr ? *opts : defaults;

  auto ctx = std::make_shared<FanoutCtx>(static_cast<int>(subs_.size()));
  ctx->subs = subs_;
  ctx->method = method;
  ctx->requests.reserve(subs_.size());
  for (size_t i = 0; i < subs_.size(); ++i) {
    ctx->requests.push_back(o.mapper
                                ? o.mapper(static_cast<int>(i), request)
                                : request);  // broadcast shares blocks
    ctx->cntls[i].set_timeout_ms(cntl->timeout_ms());
    ctx->cntls[i].request_attachment() = cntl->request_attachment();
  }
  run_fanout(ctx);

  int failures = 0;
  for (bool ok : ctx->oks) {
    failures += !ok;
  }
  const int fail_limit = o.fail_limit < 0 ? 0 : o.fail_limit;
  if (failures > fail_limit) {
    // Report the first failure's code (fail_limit semantics).
    for (size_t i = 0; i < ctx->oks.size(); ++i) {
      if (!ctx->oks[i]) {
        cntl->SetFailed(ctx->cntls[i].error_code(),
                        "parallel: " + std::to_string(failures) + "/" +
                            std::to_string(subs_.size()) + " subs failed: " +
                            ctx->cntls[i].error_text());
        return;
      }
    }
  }
  if (o.merger) {
    o.merger(ctx->responses, ctx->oks, response);
  } else {
    concat_merger(ctx->responses, ctx->oks, response);
  }
}

void SelectiveChannel::CallMethod(const std::string& method,
                                  const IOBuf& request, IOBuf* response,
                                  Controller* cntl, int max_failover) {
  if (subs_.empty()) {
    cntl->SetFailed(ENOENT, "no sub channels");
    return;
  }
  const size_t start = next_.fetch_add(1, std::memory_order_relaxed);
  const int attempts =
      std::min<int>(1 + max_failover, static_cast<int>(subs_.size()));
  IOBuf attachment = cntl->request_attachment();  // survive per-try Reset
  for (int a = 0; a < attempts; ++a) {
    cntl->Reset();
    cntl->request_attachment() = attachment;
    response->clear();
    subs_[(start + a) % subs_.size()]->Call(method, request, response, cntl);
    if (!cntl->Failed()) {
      return;
    }
  }
}

namespace {

// Shared partition fanout: shards `request` over `subs` (all-or-nothing)
// and merges — the body of PartitionChannel::CallMethod, reused per
// scheme by DynamicPartitionChannel.
void partition_fanout(const std::vector<std::shared_ptr<SubChannel>>& subs,
                      const std::string& method, const IOBuf& request,
                      IOBuf* response, Controller* cntl,
                      const PartitionChannel::Partitioner& partitioner,
                      const ParallelChannel::ResponseMerger& merger) {
  if (subs.empty()) {
    cntl->SetFailed(ENOENT, "no partitions");
    return;
  }
  if (!partitioner) {
    cntl->SetFailed(EINVAL, "null partitioner");
    return;
  }
  fiber_init(0);
  std::vector<IOBuf> parts = partitioner(request, subs.size());
  if (parts.size() != subs.size()) {
    cntl->SetFailed(EINVAL, "partitioner returned wrong count");
    return;
  }
  auto ctx = std::make_shared<FanoutCtx>(static_cast<int>(subs.size()));
  ctx->subs = subs;
  ctx->method = method;
  ctx->requests = std::move(parts);
  for (size_t i = 0; i < subs.size(); ++i) {
    ctx->cntls[i].set_timeout_ms(cntl->timeout_ms());
    ctx->cntls[i].request_attachment() = cntl->request_attachment();
  }
  run_fanout(ctx);
  for (size_t i = 0; i < ctx->oks.size(); ++i) {
    if (!ctx->oks[i]) {  // partitions are all-or-nothing
      cntl->SetFailed(ctx->cntls[i].error_code(),
                      "partition " + std::to_string(i) + " failed: " +
                          ctx->cntls[i].error_text());
      return;
    }
  }
  if (merger) {
    merger(ctx->responses, ctx->oks, response);
  } else {
    for (const IOBuf& r : ctx->responses) {
      response->append(r);
    }
  }
}

}  // namespace

void PartitionChannel::CallMethod(const std::string& method,
                                  const IOBuf& request, IOBuf* response,
                                  Controller* cntl, Partitioner partitioner,
                                  ParallelChannel::ResponseMerger merger) {
  partition_fanout(subs_, method, request, response, cntl, partitioner,
                   merger);
}

int DynamicPartitionChannel::add_scheme(
    std::vector<std::shared_ptr<SubChannel>> partitions) {
  auto s = std::make_unique<Scheme>();
  s->parts = std::move(partitions);
  schemes_.push_back(std::move(s));
  return static_cast<int>(schemes_.size()) - 1;
}

int64_t DynamicPartitionChannel::weight_of(const Scheme& s) const {
  // Capacity prior: a 4-way scheme nominally serves 2x a 2-way one
  // (partition_channel.h:136 capacity semantics).  Quality correction:
  // latency relative to the best-performing scheme, divided by relative
  // in-flight load, quartered per consecutive failed fanout.
  constexpr int64_t kQualityOne = 1 << 16;
  const int64_t cap = static_cast<int64_t>(s.parts.size());
  const int64_t lat = s.ewma_us.load(std::memory_order_relaxed);
  int64_t quality = kQualityOne;  // untried schemes enter at parity
  if (lat > 0) {
    int64_t best = lat;
    for (const auto& other : schemes_) {
      const int64_t l = other->ewma_us.load(std::memory_order_relaxed);
      if (l > 0) {
        best = std::min(best, l);
      }
    }
    quality = kQualityOne * best / lat;
  }
  const int64_t load =
      1 + s.inflight.load(std::memory_order_relaxed) / std::max<int64_t>(
                                                           cap, 1);
  int64_t w = cap * quality / load;
  w >>= std::min(2 * s.fails.load(std::memory_order_relaxed), 30);
  return std::max<int64_t>(w, 1);
}

int64_t DynamicPartitionChannel::scheme_weight(int index) const {
  if (index < 0 || static_cast<size_t>(index) >= schemes_.size()) {
    return 0;
  }
  return weight_of(*schemes_[index]);
}

void DynamicPartitionChannel::CallMethod(
    const std::string& method, const IOBuf& request, IOBuf* response,
    Controller* cntl, PartitionChannel::Partitioner partitioner,
    ParallelChannel::ResponseMerger merger) {
  if (schemes_.empty()) {
    cntl->SetFailed(ENOENT, "no partition schemes");
    return;
  }
  // Capacity x quality weighted random scheme pick.
  std::vector<int64_t> weights(schemes_.size());
  for (size_t i = 0; i < schemes_.size(); ++i) {
    weights[i] = weight_of(*schemes_[i]);
  }
  Scheme& s = *schemes_[weighted_pick(weights.data(), weights.size())];
  s.inflight.fetch_add(1, std::memory_order_relaxed);
  const int64_t t0 = monotonic_time_us();
  partition_fanout(s.parts, method, request, response, cntl, partitioner,
                   merger);
  const int64_t lat = monotonic_time_us() - t0;
  s.inflight.fetch_sub(1, std::memory_order_relaxed);
  if (cntl->Failed()) {
    s.fails.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  s.fails.store(0, std::memory_order_relaxed);
  s.ewma_us.store(asym_ewma(s.ewma_us.load(std::memory_order_relaxed), lat),
                  std::memory_order_relaxed);
}

}  // namespace trpc
