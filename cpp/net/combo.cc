#include "net/combo.h"

#include <errno.h>

#include <algorithm>

#include "fiber/fiber.h"
#include "fiber/sync.h"

namespace trpc {

namespace {

class PlainSub final : public SubChannel {
 public:
  explicit PlainSub(std::shared_ptr<Channel> ch) : ch_(std::move(ch)) {}
  void Call(const std::string& method, const IOBuf& request, IOBuf* response,
            Controller* cntl) override {
    ch_->CallMethod(method, request, response, cntl);
  }

 private:
  std::shared_ptr<Channel> ch_;
};

class ClusterSub final : public SubChannel {
 public:
  explicit ClusterSub(std::shared_ptr<ClusterChannel> ch)
      : ch_(std::move(ch)) {}
  void Call(const std::string& method, const IOBuf& request, IOBuf* response,
            Controller* cntl) override {
    ch_->CallMethod(method, request, response, cntl);
  }

 private:
  std::shared_ptr<ClusterChannel> ch_;
};

// One fan-out sub-call, run in its own fiber (sub-done aggregation parity,
// parallel_channel.cpp:88-153 — ours is a shared ctx + countdown).  The ctx
// (including the latch) is shared_ptr-held by every fiber so the LAST
// signaler can still be inside the latch when the caller's frame moves on.
// Fibers write only their own cntls[i]/responses[i] slot; success flags are
// derived from the controllers AFTER the join (no concurrent bit-vector
// writes).
struct FanoutCtx {
  explicit FanoutCtx(int n) : latch(n) {
    responses.resize(n);
    cntls.resize(n);
  }
  std::vector<std::shared_ptr<SubChannel>> subs;
  std::string method;
  std::vector<IOBuf> requests;
  std::vector<IOBuf> responses;
  std::vector<Controller> cntls;
  std::vector<bool> oks;  // filled after the join
  CountdownEvent latch;
};

struct FanoutArg {
  std::shared_ptr<FanoutCtx> ctx;
  int index;
};

void fanout_fiber(void* p) {
  std::unique_ptr<FanoutArg> arg(static_cast<FanoutArg*>(p));
  FanoutCtx* ctx = arg->ctx.get();
  const int i = arg->index;
  ctx->subs[i]->Call(ctx->method, ctx->requests[i], &ctx->responses[i],
                     &ctx->cntls[i]);
  ctx->latch.signal();
}

void run_fanout(const std::shared_ptr<FanoutCtx>& ctx) {
  const int n = static_cast<int>(ctx->subs.size());
  for (int i = 0; i < n; ++i) {
    auto* arg = new FanoutArg{ctx, i};
    if (fiber_start(nullptr, fanout_fiber, arg, 0) != 0) {
      // Spawn failure must not hang the join (fiber_start does not take
      // ownership of arg on failure).
      delete arg;
      ctx->cntls[i].SetFailed(EAGAIN, "fiber_start failed");
      ctx->latch.signal();
    }
  }
  ctx->latch.wait(-1);
  ctx->oks.resize(n);
  for (int i = 0; i < n; ++i) {
    ctx->oks[i] = !ctx->cntls[i].Failed();
  }
}

void concat_merger(const std::vector<IOBuf>& subs, const std::vector<bool>& oks,
                   IOBuf* merged) {
  for (size_t i = 0; i < subs.size(); ++i) {
    if (oks[i]) {
      merged->append(subs[i]);
    }
  }
}

}  // namespace

std::shared_ptr<SubChannel> make_sub_channel(std::shared_ptr<Channel> ch) {
  return std::make_shared<PlainSub>(std::move(ch));
}

std::shared_ptr<SubChannel> make_sub_channel(
    std::shared_ptr<ClusterChannel> ch) {
  return std::make_shared<ClusterSub>(std::move(ch));
}

void ParallelChannel::CallMethod(const std::string& method,
                                 const IOBuf& request, IOBuf* response,
                                 Controller* cntl, const Options* opts) {
  if (subs_.empty()) {
    cntl->SetFailed(ENOENT, "no sub channels");
    return;
  }
  fiber_init(0);
  Options defaults;
  const Options& o = opts != nullptr ? *opts : defaults;

  auto ctx = std::make_shared<FanoutCtx>(static_cast<int>(subs_.size()));
  ctx->subs = subs_;
  ctx->method = method;
  ctx->requests.reserve(subs_.size());
  for (size_t i = 0; i < subs_.size(); ++i) {
    ctx->requests.push_back(o.mapper
                                ? o.mapper(static_cast<int>(i), request)
                                : request);  // broadcast shares blocks
    ctx->cntls[i].set_timeout_ms(cntl->timeout_ms());
    ctx->cntls[i].request_attachment() = cntl->request_attachment();
  }
  run_fanout(ctx);

  int failures = 0;
  for (bool ok : ctx->oks) {
    failures += !ok;
  }
  const int fail_limit = o.fail_limit < 0 ? 0 : o.fail_limit;
  if (failures > fail_limit) {
    // Report the first failure's code (fail_limit semantics).
    for (size_t i = 0; i < ctx->oks.size(); ++i) {
      if (!ctx->oks[i]) {
        cntl->SetFailed(ctx->cntls[i].error_code(),
                        "parallel: " + std::to_string(failures) + "/" +
                            std::to_string(subs_.size()) + " subs failed: " +
                            ctx->cntls[i].error_text());
        return;
      }
    }
  }
  if (o.merger) {
    o.merger(ctx->responses, ctx->oks, response);
  } else {
    concat_merger(ctx->responses, ctx->oks, response);
  }
}

void SelectiveChannel::CallMethod(const std::string& method,
                                  const IOBuf& request, IOBuf* response,
                                  Controller* cntl, int max_failover) {
  if (subs_.empty()) {
    cntl->SetFailed(ENOENT, "no sub channels");
    return;
  }
  const size_t start = next_.fetch_add(1, std::memory_order_relaxed);
  const int attempts =
      std::min<int>(1 + max_failover, static_cast<int>(subs_.size()));
  IOBuf attachment = cntl->request_attachment();  // survive per-try Reset
  for (int a = 0; a < attempts; ++a) {
    cntl->Reset();
    cntl->request_attachment() = attachment;
    response->clear();
    subs_[(start + a) % subs_.size()]->Call(method, request, response, cntl);
    if (!cntl->Failed()) {
      return;
    }
  }
}

void PartitionChannel::CallMethod(const std::string& method,
                                  const IOBuf& request, IOBuf* response,
                                  Controller* cntl, Partitioner partitioner,
                                  ParallelChannel::ResponseMerger merger) {
  if (subs_.empty()) {
    cntl->SetFailed(ENOENT, "no partitions");
    return;
  }
  if (!partitioner) {
    cntl->SetFailed(EINVAL, "null partitioner");
    return;
  }
  fiber_init(0);
  std::vector<IOBuf> parts = partitioner(request, subs_.size());
  if (parts.size() != subs_.size()) {
    cntl->SetFailed(EINVAL, "partitioner returned wrong count");
    return;
  }
  auto ctx = std::make_shared<FanoutCtx>(static_cast<int>(subs_.size()));
  ctx->subs = subs_;
  ctx->method = method;
  ctx->requests = std::move(parts);
  for (size_t i = 0; i < subs_.size(); ++i) {
    ctx->cntls[i].set_timeout_ms(cntl->timeout_ms());
    ctx->cntls[i].request_attachment() = cntl->request_attachment();
  }
  run_fanout(ctx);
  for (size_t i = 0; i < ctx->oks.size(); ++i) {
    if (!ctx->oks[i]) {  // partitions are all-or-nothing
      cntl->SetFailed(ctx->cntls[i].error_code(),
                      "partition " + std::to_string(i) + " failed: " +
                          ctx->cntls[i].error_text());
      return;
    }
  }
  if (merger) {
    merger(ctx->responses, ctx->oks, response);
  } else {
    for (const IOBuf& r : ctx->responses) {
      response->append(r);
    }
  }
}

}  // namespace trpc
