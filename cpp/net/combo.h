// Combo channels — declarative scatter/gather over sub-channels.
//
// Parity (SURVEY.md §2.4): ParallelChannel
// (/root/reference/src/brpc/parallel_channel.h:202 with CallMapper :102 and
// ResponseMerger :141, fail_limit semantics), SelectiveChannel
// (selective_channel.h:52 — LB over heterogeneous sub-channels with
// failover), PartitionChannel (partition_channel.h:75 — shard one logical
// request across partitions).  The TPU-native twins lower these onto XLA
// collectives (brpc_tpu/channels/combo.py); this is the host-side form for
// byte-payload RPCs.
#pragma once

#include <atomic>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "net/cluster.h"
#include "net/controller.h"

namespace trpc {

// Sub-call abstraction: anything that can CallMethod (Channel or
// ClusterChannel) — heterogeneous subs are the SelectiveChannel use case.
class SubChannel {
 public:
  virtual ~SubChannel() = default;
  virtual void Call(const std::string& method, const IOBuf& request,
                    IOBuf* response, Controller* cntl) = 0;
};

std::shared_ptr<SubChannel> make_sub_channel(std::shared_ptr<Channel> ch);
std::shared_ptr<SubChannel> make_sub_channel(std::shared_ptr<ClusterChannel> ch);

class ParallelChannel {
 public:
  // Maps the logical request to sub-call i's request (null = broadcast).
  using CallMapper = std::function<IOBuf(int index, const IOBuf& request)>;
  // Merges sub-responses (failed subs have empty slots; check oks).
  using ResponseMerger = std::function<void(
      const std::vector<IOBuf>& sub_responses, const std::vector<bool>& oks,
      IOBuf* merged)>;

  struct Options {
    int fail_limit = -1;  // -1 = all subs must succeed
    CallMapper mapper;
    ResponseMerger merger;  // default: concatenate successful responses
  };

  void add_sub_channel(std::shared_ptr<SubChannel> sub) {
    subs_.push_back(std::move(sub));
  }
  size_t sub_count() const { return subs_.size(); }

  // Fans out to every sub concurrently, waits for all, merges.
  // cntl fails when failures > fail_limit (parallel_channel fail_limit
  // semantics: the call succeeds while at most fail_limit subs fail).
  void CallMethod(const std::string& method, const IOBuf& request,
                  IOBuf* response, Controller* cntl,
                  const Options* opts = nullptr);

 private:
  std::vector<std::shared_ptr<SubChannel>> subs_;
};

// LB over heterogeneous sub-channels with failover to the next sub.
class SelectiveChannel {
 public:
  void add_sub_channel(std::shared_ptr<SubChannel> sub) {
    subs_.push_back(std::move(sub));
  }
  void CallMethod(const std::string& method, const IOBuf& request,
                  IOBuf* response, Controller* cntl, int max_failover = 1);

 private:
  std::vector<std::shared_ptr<SubChannel>> subs_;
  std::atomic<uint64_t> next_{0};
};

// Shards one logical request across partition sub-channels.
class PartitionChannel {
 public:
  // Splits the request into one IOBuf per partition.
  using Partitioner = std::function<std::vector<IOBuf>(
      const IOBuf& request, size_t num_partitions)>;

  void add_partition(std::shared_ptr<SubChannel> sub) {
    subs_.push_back(std::move(sub));
  }
  // All partitions must succeed; responses concatenate in partition order
  // unless `merger` is given.
  void CallMethod(const std::string& method, const IOBuf& request,
                  IOBuf* response, Controller* cntl, Partitioner partitioner,
                  ParallelChannel::ResponseMerger merger = nullptr);

 private:
  std::vector<std::shared_ptr<SubChannel>> subs_;
};

// Several partition SCHEMES of the same logical service coexisting (a
// 2-way and a 4-way deployment during a resharding migration): each call
// picks one scheme and shards across it.  Parity:
// DynamicPartitionChannel (partition_channel.h:136), which weighs
// schemes by server capacity — here the capacity prior (partition count)
// is CLOSED-LOOP corrected by observed per-scheme latency, in-flight
// load and errors, so a scheme that underperforms its nominal capacity
// sheds traffic live and re-earns it on recovery (the TPU twin is
// brpc_tpu/channels/combo.py DynamicPartitionChannel).
class DynamicPartitionChannel {
 public:
  // Adds one scheme (its shard sub-channels, in partition order).
  // Returns the scheme index.
  int add_scheme(std::vector<std::shared_ptr<SubChannel>> partitions);
  size_t scheme_count() const { return schemes_.size(); }

  // Capacity×quality-weighted scheme pick, then a PartitionChannel-style
  // fanout over the chosen scheme.
  void CallMethod(const std::string& method, const IOBuf& request,
                  IOBuf* response, Controller* cntl,
                  PartitionChannel::Partitioner partitioner,
                  ParallelChannel::ResponseMerger merger = nullptr);

  // Live effective weight of one scheme (observability / tests).
  int64_t scheme_weight(int index) const;

 private:
  struct Scheme {
    std::vector<std::shared_ptr<SubChannel>> parts;
    std::atomic<int64_t> ewma_us{0};   // smoothed whole-fanout latency
    std::atomic<int64_t> inflight{0};
    std::atomic<int> fails{0};         // consecutive failed fanouts
  };
  int64_t weight_of(const Scheme& s) const;

  std::vector<std::unique_ptr<Scheme>> schemes_;
};

}  // namespace trpc
