// Concurrency limiters — per-method admission control.
//
// Parity: brpc's ConcurrencyLimiter extension (/root/reference/src/brpc/
// concurrency_limiter.h; policy/auto_concurrency_limiter.cpp) with its
// "constant" and "auto" policies and MethodStatus gating
// (details/method_status.h).  "auto" is a condensed AIMD on latency: the
// limit grows additively while latency stays near the no-load EMA and
// backs off multiplicatively when it inflates.
#pragma once

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <memory>
#include <string>
#include <utility>

namespace trpc {

// Rejected-by-limiter error code (parity: brpc::ELIMIT).
constexpr int kELimit = 2004;
// Shed by per-tenant admission control (net/qos.h) — deliberately
// DISTINCT from kELimit: kELimit means "this method is at its bound,
// try again here later"; kEOverloaded means "this server is shedding
// your tenant's load — fail over NOW".  The cluster client treats it as
// a node failure (immediate retry on a different node + quarantine
// backoff), and health probes treat it as proof of life.
constexpr int kEOverloaded = 2005;
// Answered by a server that entered graceful drain (Server::Drain): the
// node is HEALTHY but leaving the fleet.  Distinct from kEOverloaded on
// purpose — the cluster client fails over immediately like a shed, but
// does NOT feed the circuit breaker (quarantining a deliberately-leaving
// node would poison its successor, which revives on the same endpoint
// moments later after the hot-restart listener handoff).
constexpr int kEDraining = 2006;

class ConcurrencyLimiter {
 public:
  virtual ~ConcurrencyLimiter() = default;
  // True = admitted (caller MUST later call on_response exactly once).
  virtual bool on_request() = 0;
  virtual void on_response(int64_t latency_us, bool error) = 0;
  virtual int64_t current_limit() const = 0;

  // spec: "" (unlimited → nullptr), "<N>" (constant), "auto".
  static std::unique_ptr<ConcurrencyLimiter> create(const std::string& spec);
};

class ConstantLimiter final : public ConcurrencyLimiter {
 public:
  explicit ConstantLimiter(int64_t limit) : limit_(limit) {}

  bool on_request() override {
    const int64_t limit = limit_.load(std::memory_order_acquire);
    if (inflight_.fetch_add(1, std::memory_order_acq_rel) >= limit) {
      inflight_.fetch_sub(1, std::memory_order_acq_rel);
      return false;
    }
    return true;
  }

  void on_response(int64_t, bool) override {
    inflight_.fetch_sub(1, std::memory_order_acq_rel);
  }

  int64_t current_limit() const override {
    return limit_.load(std::memory_order_acquire);
  }
  // Runtime retarget (a /flags flip lands here; in-flight admissions are
  // unaffected, the new bound gates subsequent requests).
  void set_limit(int64_t limit) {
    limit_.store(limit, std::memory_order_release);
  }

 private:
  std::atomic<int64_t> limit_;
  std::atomic<int64_t> inflight_{0};
};

class AutoLimiter final : public ConcurrencyLimiter {
 public:
  bool on_request() override {
    const int64_t limit = limit_.load(std::memory_order_acquire);
    if (inflight_.fetch_add(1, std::memory_order_acq_rel) >= limit) {
      inflight_.fetch_sub(1, std::memory_order_acq_rel);
      return false;
    }
    return true;
  }

  void on_response(int64_t latency_us, bool error) override {
    const int64_t inflight_now =
        inflight_.fetch_sub(1, std::memory_order_acq_rel);
    if (error || latency_us <= 0) {
      return;
    }
    // No-load latency: EMA sampled while the method is nearly idle.
    if (inflight_now <= 2) {
      int64_t noload = noload_us_.load(std::memory_order_relaxed);
      noload = noload == 0 ? latency_us : (noload * 7 + latency_us) / 8;
      noload_us_.store(noload, std::memory_order_relaxed);
    }
    int64_t peak = peak_inflight_.load(std::memory_order_relaxed);
    while (inflight_now > peak &&
           !peak_inflight_.compare_exchange_weak(
               peak, inflight_now, std::memory_order_relaxed)) {
    }
    latency_sum_us_.fetch_add(latency_us, std::memory_order_relaxed);
    const int64_t n = samples_.fetch_add(1, std::memory_order_acq_rel) + 1;
    if (n < kInterval) {
      return;
    }
    // One adjuster per interval (the CAS winner).
    int64_t expect = n;
    if (!samples_.compare_exchange_strong(expect, 0,
                                          std::memory_order_acq_rel)) {
      return;
    }
    const int64_t avg =
        latency_sum_us_.exchange(0, std::memory_order_acq_rel) / n;
    const int64_t interval_peak =
        peak_inflight_.exchange(0, std::memory_order_acq_rel);
    const int64_t noload = noload_us_.load(std::memory_order_relaxed);
    int64_t limit = limit_.load(std::memory_order_relaxed);
    if (noload == 0 || avg <= noload + noload / 2) {
      // Additive increase ONLY while the limit is actually being exercised;
      // an idle-but-healthy method must not inflate the limit until it can
      // never bind under a later overload.
      if (interval_peak >= limit - limit / 4) {
        limit += 4;
      }
    } else {
      limit = limit * 9 / 10;  // multiplicative decrease once it inflates
    }
    limit_.store(std::max<int64_t>(limit, kMinLimit),
                 std::memory_order_release);
  }

  int64_t current_limit() const override {
    return limit_.load(std::memory_order_acquire);
  }

 private:
  static constexpr int64_t kInterval = 64;  // responses per adjustment
  static constexpr int64_t kMinLimit = 4;
  std::atomic<int64_t> limit_{64};
  std::atomic<int64_t> inflight_{0};
  std::atomic<int64_t> peak_inflight_{0};
  std::atomic<int64_t> noload_us_{0};
  std::atomic<int64_t> latency_sum_us_{0};
  std::atomic<int64_t> samples_{0};
};

// Third limiter kind (parity: policy/timeout_concurrency_limiter.h):
// admits a request only while the QUEUEING estimate — in-flight depth x
// recent average latency — still fits the configured timeout budget, so
// requests that would blow their deadline anyway are rejected up front
// instead of wasting a slot timing out.  Condensed: the reference keeps a
// sampling window + adjusted average; ours keeps an EMA that errors
// (timeouts) also feed, which is what inflates the estimate under
// overload and closes the gate.
class TimeoutLimiter final : public ConcurrencyLimiter {
 public:
  explicit TimeoutLimiter(int64_t timeout_ms)
      : timeout_us_(timeout_ms * 1000) {}

  bool on_request() override {
    const int64_t avg = avg_latency_us_.load(std::memory_order_acquire);
    const int64_t depth =
        inflight_.fetch_add(1, std::memory_order_acq_rel) + 1;
    // depth 1 always admits: the estimate gates QUEUEING delay, and a
    // lone request has no queue — otherwise a latency spike above the
    // budget would close the gate permanently (nothing left running to
    // decay the EMA).
    if (depth > 1 && avg > 0 && depth * avg > timeout_us_) {
      inflight_.fetch_sub(1, std::memory_order_acq_rel);
      return false;
    }
    return true;
  }

  void on_response(int64_t latency_us, bool /*error*/) override {
    // Errors sample too: a wave of timeouts must RAISE the estimate.
    inflight_.fetch_sub(1, std::memory_order_acq_rel);
    if (latency_us <= 0) {
      return;
    }
    // CAS loop, not load/compute/store: concurrent completions would
    // otherwise overwrite each other's samples, and the estimate lags
    // exactly under the overload this limiter is meant to gate
    // (ADVICE r5).
    int64_t avg = avg_latency_us_.load(std::memory_order_relaxed);
    int64_t next;
    do {
      next = avg == 0 ? latency_us : (avg * 7 + latency_us) / 8;
    } while (!avg_latency_us_.compare_exchange_weak(
        avg, next, std::memory_order_relaxed));
  }

  int64_t current_limit() const override {
    const int64_t avg = avg_latency_us_.load(std::memory_order_acquire);
    return avg > 0 ? std::max<int64_t>(1, timeout_us_ / avg)
                   : INT64_MAX;  // no samples yet: unbounded
  }

 private:
  const int64_t timeout_us_;
  std::atomic<int64_t> inflight_{0};
  std::atomic<int64_t> avg_latency_us_{0};
};

// Returns {ok, limiter}: ok=false means the spec was unparseable (distinct
// from ""/unlimited so callers can reject typos instead of silently
// removing a limit).
inline std::pair<bool, std::unique_ptr<ConcurrencyLimiter>>
parse_concurrency_spec(const std::string& spec) {
  if (spec.empty()) {
    return {true, nullptr};
  }
  if (spec == "auto") {
    return {true, std::make_unique<AutoLimiter>()};
  }
  if (spec.rfind("timeout:", 0) == 0) {
    char* end = nullptr;
    const long ms = strtol(spec.c_str() + 8, &end, 10);
    if (end == spec.c_str() + 8 || *end != '\0' || ms <= 0) {
      return {false, nullptr};
    }
    return {true, std::make_unique<TimeoutLimiter>(ms)};
  }
  char* end = nullptr;
  const long n = strtol(spec.c_str(), &end, 10);
  if (end == spec.c_str() || *end != '\0' || n <= 0) {
    return {false, nullptr};
  }
  return {true, std::make_unique<ConstantLimiter>(n)};
}

inline std::unique_ptr<ConcurrencyLimiter> ConcurrencyLimiter::create(
    const std::string& spec) {
  return parse_concurrency_spec(spec).second;
}

}  // namespace trpc
