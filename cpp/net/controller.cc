// Controller out-of-line bits: cancellation + the deadline plane.
//
// Parity: /root/reference/src/brpc/controller.h:717 `StartCancel()` and
// :983 `StartCancel(CallId)` — the reference routes both through
// bthread_id_error(ECANCELED); ours routes through the equivalent
// versioned-fid error path (fiber/fid.h), which wakes sync joiners,
// cancels the timeout timer and runs the async done exactly once via
// complete_locked_call (net/channel.cc).  Beyond the reference, a cancel
// also ships a kCancel control frame to the server (net/deadline.h), so
// downstream work the handler started is abandoned instead of running to
// completion — the cascading half brpc never had.
#include "net/controller.h"

#include <errno.h>

#include "base/time.h"
#include "net/deadline.h"
#include "net/socket.h"

namespace trpc {

void StartCancel(fid_t cid) {
  if (cid == 0) {
    return;
  }
  // Best-effort cascading cancel: while the call is still live, read its
  // connection under the fid lock and queue the kCancel frame BEFORE the
  // local error completes the call (completion may recycle pooled
  // sockets).  A call that completed in the meantime skips the frame —
  // and a frame racing the response on the server is a harmless registry
  // miss.  h2 calls have their own stream-level cancel
  // (complete_locked_call); only tstd connections speak kCancel.
  void* data = nullptr;
  if (fid_lock(cid, &data) == 0) {
    auto* cntl = static_cast<Controller*>(data);
    const uint64_t sid =
        cntl->call().h2_stream == 0 ? cntl->call().socket_id : 0;
    fid_unlock(cid);
    if (sid != 0) {
      send_cancel_frame(sid, cid);
    }
  }
  // EINVAL (already completed / never existed) is the documented
  // harmless case; fid versioning makes double-cancel safe too.
  fid_error(cid, ECANCELED);
}

void Controller::StartCancel() { trpc::StartCancel(call_.cid); }

bool Controller::IsCanceled() const {
  if (call_.cancel_scope != nullptr && call_.cancel_scope->cancelled()) {
    return true;  // explicit kCancel fan-out beat the socket poll
  }
  if (call_.socket_id == 0) {
    return false;
  }
  SocketRef s(Socket::Address(call_.socket_id));
  return !s || s->Failed();
}

int64_t Controller::remaining_us() const {
  return deadline_remaining_us(deadline_abs_us_);
}

}  // namespace trpc
