// Controller out-of-line bits: cancellation.
//
// Parity: /root/reference/src/brpc/controller.h:717 `StartCancel()` and
// :983 `StartCancel(CallId)` — the reference routes both through
// bthread_id_error(ECANCELED); ours routes through the equivalent
// versioned-fid error path (fiber/fid.h), which wakes sync joiners,
// cancels the timeout timer and runs the async done exactly once via
// complete_locked_call (net/channel.cc).
#include "net/controller.h"

#include <errno.h>

#include "net/socket.h"

namespace trpc {

void StartCancel(fid_t cid) {
  if (cid != 0) {
    // EINVAL (already completed / never existed) is the documented
    // harmless case; fid versioning makes double-cancel safe too.
    fid_error(cid, ECANCELED);
  }
}

void Controller::StartCancel() { trpc::StartCancel(call_.cid); }

bool Controller::IsCanceled() const {
  if (call_.socket_id == 0) {
    return false;
  }
  SocketRef s(Socket::Address(call_.socket_id));
  return !s || s->Failed();
}

}  // namespace trpc
