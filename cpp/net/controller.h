// Controller — per-RPC state visible to user code on both sides.
//
// Parity: brpc::Controller (/root/reference/src/brpc/controller.h) condensed:
// error state, timeout, attachment, correlation id.  The client call
// lifecycle (response/timeout/failure racing) serializes on the fid the
// controller owns, mirroring the bthread_id protocol in controller.cpp:611.
#pragma once

#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <utility>
#include <vector>

#include "base/iobuf.h"
#include "fiber/fid.h"
#include "net/data_pool.h"

namespace trpc {

using Closure = std::function<void()>;

class ProgressiveAttachment;  // net/progressive.h
class ProgressiveReader;
class CancelScope;  // net/deadline.h

class Controller {
 public:
  // -- status ----------------------------------------------------------
  bool Failed() const { return error_code_ != 0; }
  int error_code() const { return error_code_; }
  const std::string& error_text() const { return error_text_; }
  void SetFailed(int code, const std::string& text) {
    error_code_ = code;
    error_text_ = text;
  }
  void Reset() {
    error_code_ = 0;
    error_text_.clear();
    request_attachment_.clear();
    response_attachment_.clear();
  }

  // -- knobs (client) --------------------------------------------------
  // timeout_ms is kUnsetTimeoutMs until the caller sets it; channels then
  // substitute their own Options::timeout_ms. An explicit 0 disables the
  // timer. A reachable legal value (like 1000) must NOT be the sentinel or
  // callers could never ask for it explicitly.
  static constexpr int64_t kUnsetTimeoutMs = -1;
  void set_timeout_ms(int64_t ms) { timeout_ms_ = ms; }
  int64_t timeout_ms() const { return timeout_ms_; }
  // The caller's timeout if set, else the channel's default.
  int64_t timeout_ms_or(int64_t dflt) const {
    return timeout_ms_ != kUnsetTimeoutMs ? timeout_ms_ : dflt;
  }

  // Compression of the request body (client) / response body (server),
  // negotiated in the meta (gzip_compress.* parity).  Attachments stay
  // raw, like the reference.
  void set_request_compress_type(uint8_t t) { req_compress_ = t; }
  uint8_t request_compress_type() const { return req_compress_; }
  void set_response_compress_type(uint8_t t) { resp_compress_ = t; }
  uint8_t response_compress_type() const { return resp_compress_; }
  // crc32c over the on-wire payload, verified by the receiving parser.
  void set_enable_checksum(bool on) { checksum_ = on; }
  bool checksum_enabled() const { return checksum_; }

  // -- QoS tag (net/qos.h) ---------------------------------------------
  // Client: per-call override of the channel's default tenant/priority
  // (set BEFORE CallMethod; rides the request meta's qos tail group).
  // Server: the arriving request's tag, readable in the handler.
  // Tenant names are capped at 64 bytes (wire decoder limit) — longer
  // ones are truncated at send.  Priority 0 is the highest lane.
  void set_qos(const std::string& tenant, uint8_t priority) {
    qos_tenant_ = tenant.size() > 64 ? tenant.substr(0, 64) : tenant;
    qos_priority_ = priority;
    qos_set_ = true;
  }
  bool qos_set() const { return qos_set_; }
  const std::string& qos_tenant() const { return qos_tenant_; }
  uint8_t qos_priority() const { return qos_priority_; }

  // Payload carried outside the main body (parity: attachment in
  // baidu_std; rides the same frame after the response body).
  IOBuf& request_attachment() { return request_attachment_; }
  IOBuf& response_attachment() { return response_attachment_; }

  int64_t latency_us() const { return latency_us_; }
  const std::string& method() const { return method_; }

  // -- cancellation ------------------------------------------------------
  // Parity: reference controller.h:717 StartCancel() / :983 free-function
  // StartCancel(CallId).  Rides the versioned-fid error path: the call
  // completes with ECANCELED exactly once, racing responses/timeouts
  // serialize on the fid, and a cancel after completion is a harmless
  // no-op (stale version).  Never blocks on the network.
  fid_t call_id() const { return call_.cid; }
  void StartCancel();

  // -- deadline plane (net/deadline.h) -----------------------------------
  // Server side: the request's absolute monotonic deadline, anchored at
  // arrival from the wire's remaining-budget stamp (0 = the caller set
  // no deadline).  Handlers poll remaining_us() to right-size or
  // abandon work; long transfer loops check it between chunks.
  void set_deadline_abs_us(int64_t abs_us) { deadline_abs_us_ = abs_us; }
  int64_t deadline_abs_us() const { return deadline_abs_us_; }
  // Remaining budget in µs: INT64_MAX when no deadline, 0 when already
  // past (never negative — callers compare against work estimates).
  int64_t remaining_us() const;
  // Server side: has the client gone away (socket failed/closed)?  A long
  // handler polls this to abandon work nobody will receive
  // (controller.h:308 IsCanceled parity).
  bool IsCanceled() const;

  // Async-completion hook (batch pipeline): a done closure marked
  // inline-safe is BOUNDED FRAMEWORK WORK (memcpy + atomic push + wake,
  // never parks, never runs user code) and may execute directly on a
  // connection's dispatch fiber instead of costing a completion-fiber
  // spawn per call (net/channel.cc complete_locked_call).  Default off:
  // arbitrary user dones must not stall everything behind them on the
  // connection.
  void set_done_inline_safe(bool on) { done_inline_safe_ = on; }
  bool done_inline_safe() const { return done_inline_safe_; }

  // -- progressive bodies (net/progressive.h) --------------------------
  // Server handler (HTTP serving): the response body will be streamed
  // incrementally; done() flushes headers (chunked) and the returned
  // attachment keeps writing from any fiber until close().
  std::shared_ptr<ProgressiveAttachment> CreateProgressiveAttachment();
  const std::shared_ptr<ProgressiveAttachment>& progressive_attachment()
      const {
    return progressive_;
  }
  // Client (h2): response DATA is delivered to `r` piece by piece
  // instead of accumulating; `r` must outlive the call and gets exactly
  // one on_done.
  void ReadProgressively(ProgressiveReader* r) { call_.preader = r; }

  // -- internal (framework) --------------------------------------------
  struct CallState {
    fid_t cid = 0;
    uint64_t timeout_timer = 0;
    void* span = nullptr;  // rpcz client Span (owned until submit)
    // Connection ownership for pooled/short calls (socket_map.h): the
    // completion path gives pooled sockets back / closes short ones.
    uint8_t conn_type = 0;      // ConnectionType
    const void* conn_auth = nullptr;  // pool key half (Authenticator*)
    IOBuf* response = nullptr;
    Closure done;
    int64_t start_us = 0;
    uint64_t socket_id = 0;
    // Streaming piggyback (net/stream.h): client-offered / request-carried /
    // server-accepted stream ids.
    uint64_t offered_stream = 0;
    uint64_t peer_stream = 0;
    uint64_t peer_stream_window = 0;
    uint64_t accepted_stream = 0;
    // Batch establishment (StreamIds parity): offers/acceptances beyond
    // the first, index-aligned through the meta's extra_streams tail.
    std::vector<uint64_t> extra_offered;
    std::vector<std::pair<uint64_t, uint64_t>> extra_peer;  // (sid, window)
    std::vector<uint64_t> extra_accepted;
    // h2/grpc calls: the stream id issued for this call, so a failed call
    // (timeout) can cancel its client-side stream state (h2_client.h).
    uint32_t h2_stream = 0;
    // Progressive response consumer (net/progressive.h; h2 client).
    ProgressiveReader* preader = nullptr;
    // Session-local data (net/data_pool.h): the server's pool and the
    // object lazily borrowed for this request.
    SimpleDataPool* sl_pool = nullptr;
    void* sl_data = nullptr;
    // Large-message striping (net/stripe.h).  Client: a caller-owned
    // response landing buffer (batch plane) — registered under the cid
    // so striped response chunks memcpy straight into it; unregistered
    // (with a lander drain) in complete_locked_call before the fid can
    // recycle.  Server: the rails the striped REQUEST arrived over, so
    // the response stripes back across the same connections.
    void* land_buf = nullptr;
    size_t land_cap = 0;
    bool land_registered = false;
    // One-sided RMA (net/rma.h), server side: the request's advertised
    // response-landing region — when set (and the connection has an rma
    // session) the response is PUT straight into the caller's registered
    // buffer instead of riding frames back.
    uint64_t rma_resp_rkey = 0;
    uint64_t rma_resp_max = 0;
    uint64_t rma_resp_off = 0;
    std::vector<uint64_t> stripe_rails;
    // Cancellation scope of a DISPATCHED server request (net/deadline.h):
    // co-owned with the cancel registry so the response path (which may
    // run rma_try_send long after the handler fiber exited) can still
    // poll it between chunks.  Null on the client side and on requests
    // shed before dispatch.
    std::shared_ptr<CancelScope> cancel_scope;
  };
  CallState& call() { return call_; }

  // Pooled per-request scratch object, created by the server's
  // session_local_data_factory (simple_data_pool parity).  Null when no
  // factory is installed.  Returned to the pool after the response.
  void* session_local_data() {
    if (call_.sl_data == nullptr && call_.sl_pool != nullptr) {
      call_.sl_data = call_.sl_pool->Borrow();
    }
    return call_.sl_data;
  }

  void set_method(const std::string& m) { method_ = m; }
  void set_latency_us(int64_t us) { latency_us_ = us; }

 private:
  int error_code_ = 0;
  std::string error_text_;
  std::string method_;
  int64_t timeout_ms_ = kUnsetTimeoutMs;
  uint8_t req_compress_ = 0;
  uint8_t resp_compress_ = 0;
  bool checksum_ = false;
  bool done_inline_safe_ = false;
  bool qos_set_ = false;
  int64_t deadline_abs_us_ = 0;
  uint8_t qos_priority_ = 0;
  std::string qos_tenant_;
  int64_t latency_us_ = 0;
  IOBuf request_attachment_;
  IOBuf response_attachment_;
  std::shared_ptr<ProgressiveAttachment> progressive_;
  CallState call_;
};

// Cancels the call identified by `cid` (Controller::call_id(), safe to
// stash and invoke from any thread/fiber, even after the call completed —
// the versioned fid makes a stale cancel a no-op).
void StartCancel(fid_t cid);

}  // namespace trpc
