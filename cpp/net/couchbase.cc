#include "net/couchbase.h"

#include <zlib.h>

namespace trpc {

uint16_t couchbase_vbucket_of(const std::string& key, int n_vbuckets) {
  const uint32_t crc = static_cast<uint32_t>(
      crc32(0, reinterpret_cast<const Bytef*>(key.data()),
            static_cast<uInt>(key.size())));
  return static_cast<uint16_t>((crc >> 16) & (n_vbuckets - 1));
}

int CouchbaseClient::Init(const std::vector<std::string>& nodes,
                          const Options* opts) {
  if (nodes.empty()) {
    return -1;
  }
  if (opts != nullptr) {
    opts_ = *opts;
  }
  if (opts_.n_vbuckets <= 0 ||
      (opts_.n_vbuckets & (opts_.n_vbuckets - 1)) != 0) {
    return -1;
  }
  nodes_ = nodes;
  map_.resize(opts_.n_vbuckets);
  for (int vb = 0; vb < opts_.n_vbuckets; ++vb) {
    map_[vb] = vb % static_cast<int>(nodes_.size());
  }
  return 0;
}

int CouchbaseClient::set_vbucket_map(const std::vector<int>& map) {
  if (static_cast<int>(map.size()) != opts_.n_vbuckets) {
    return -1;
  }
  for (int idx : map) {
    if (idx < 0 || idx >= static_cast<int>(nodes_.size())) {
      return -1;
    }
  }
  LockGuard<FiberMutex> g(mu_);
  map_ = map;
  return 0;
}

int CouchbaseClient::vbucket_node(int vb) {
  LockGuard<FiberMutex> g(mu_);
  return (vb >= 0 && vb < static_cast<int>(map_.size())) ? map_[vb] : -1;
}

MemcacheClient* CouchbaseClient::client_at(size_t node_idx) {
  // Callers hold mu_.
  auto it = pool_.find(node_idx);
  if (it != pool_.end()) {
    return it->second.get();
  }
  auto cli = std::make_unique<MemcacheClient>();
  MemcacheClient::Options copts;
  copts.timeout_ms = opts_.timeout_ms;
  if (cli->Init(nodes_[node_idx], &copts) != 0) {
    return nullptr;
  }
  return pool_.emplace(node_idx, std::move(cli)).first->second.get();
}

McResult CouchbaseClient::route(McCommand cmd) {
  cmd.vbucket = couchbase_vbucket_of(cmd.key, opts_.n_vbuckets);
  size_t first;
  {
    LockGuard<FiberMutex> g(mu_);
    first = static_cast<size_t>(map_[cmd.vbucket]);
  }
  McResult last;
  for (size_t probe = 0; probe < nodes_.size(); ++probe) {
    const size_t idx = (first + probe) % nodes_.size();
    MemcacheClient* cli;
    {
      LockGuard<FiberMutex> g(mu_);
      cli = client_at(idx);
    }
    if (cli == nullptr) {
      last.status = McStatus::kRemoteError;
      last.value = "cannot reach " + nodes_[idx];
      continue;
    }
    last = cli->batch({cmd}).front();
    if (last.status == McStatus::kNotMyVbucket ||
        last.status == McStatus::kRemoteError) {
      // Declined or unreachable: neither is ownership — keep probing
      // (a transport error from a stale/non-owning node must not stop
      // the search before a reachable owner is tried, and must never
      // be written into the map).
      continue;
    }
    if (probe != 0) {
      LockGuard<FiberMutex> g(mu_);
      map_[cmd.vbucket] = static_cast<int>(idx);  // learned ownership
    }
    return last;
  }
  return last;  // every node declined or was unreachable
}

McResult CouchbaseClient::Get(const std::string& key) {
  McCommand c;
  c.op = McOp::kGet;
  c.key = key;
  return route(std::move(c));
}

McResult CouchbaseClient::Set(const std::string& key,
                              const std::string& value, uint32_t flags,
                              uint32_t exptime, uint64_t cas) {
  McCommand c;
  c.op = McOp::kSet;
  c.key = key;
  c.value = value;
  c.flags = flags;
  c.exptime = exptime;
  c.cas = cas;
  return route(std::move(c));
}

McResult CouchbaseClient::Delete(const std::string& key) {
  McCommand c;
  c.op = McOp::kDelete;
  c.key = key;
  return route(std::move(c));
}

McResult CouchbaseClient::Increment(const std::string& key,
                                    uint64_t delta, uint64_t initial) {
  McCommand c;
  c.op = McOp::kIncrement;
  c.key = key;
  c.delta = delta;
  c.initial = initial;
  return route(std::move(c));
}

}  // namespace trpc
