// Couchbase client: vbucket-aware routing over the memcache binary
// substrate.
//
// Parity: /root/reference/src/brpc/couchbase.* +
// policy/couchbase_protocol.* (~3.3k LoC, fork extension) — data ops are
// memcache binary frames carrying a vbucket id in the header; the
// client hashes keys to vbuckets (CRC32 >> 16, masked), routes each op
// to the node the vBucketMap assigns, and on NOT_MY_VBUCKET (0x0007)
// probes the other nodes and repairs the map entry (the reference
// re-pulls the whole config; single-entry learning is the same
// convergence without a config channel, which needs live cluster
// infra this environment cannot reach).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "fiber/sync.h"
#include "net/memcache.h"

namespace trpc {

// vbucket of `key` under an n_vbuckets (power of two) map: standard
// couchbase hash, IEEE CRC32 of the key, upper half, masked.
uint16_t couchbase_vbucket_of(const std::string& key, int n_vbuckets);

class CouchbaseClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
    int n_vbuckets = 1024;  // must be a power of two
  };

  // `nodes` are "host:port" data nodes.  The initial vBucketMap is
  // vb→nodes[vb % n] (tests and static deployments); real deployments
  // install the cluster's map via set_vbucket_map.
  int Init(const std::vector<std::string>& nodes,
           const Options* opts = nullptr);

  // Installs a full vb→node-index map (size must equal n_vbuckets,
  // entries index `nodes`).  Returns 0 on success.
  int set_vbucket_map(const std::vector<int>& map);

  // Current node index of `vb` (diagnostics/tests).
  int vbucket_node(int vb);

  McResult Get(const std::string& key);
  McResult Set(const std::string& key, const std::string& value,
               uint32_t flags = 0, uint32_t exptime = 0, uint64_t cas = 0);
  McResult Delete(const std::string& key);
  McResult Increment(const std::string& key, uint64_t delta,
                     uint64_t initial = 0);

 private:
  // Routes one keyed command: map-assigned node first, then linear
  // probe of the rest on NOT_MY_VBUCKET, repairing the map on success.
  McResult route(McCommand cmd);
  MemcacheClient* client_at(size_t node_idx);

  Options opts_;
  std::vector<std::string> nodes_;
  FiberMutex mu_;  // guards map_ and pool_
  std::vector<int> map_;
  std::map<size_t, std::unique_ptr<MemcacheClient>> pool_;
};

}  // namespace trpc
