// Session-local data pooling (reference: simple_data_pool.{h,cpp} +
// data_factory.h; ServerOptions::session_local_data_factory →
// Controller::session_local_data()).  Expensive per-request scratch
// objects (parsers, arenas, model states) are created once and recycled
// across requests instead of constructed per call.
#pragma once

#include <cstddef>
#include <mutex>
#include <vector>

namespace trpc {

class DataFactory {
 public:
  virtual ~DataFactory() = default;
  virtual void* CreateData() = 0;
  virtual void DestroyData(void* d) = 0;
  // Called before an object is handed out again; default keeps state
  // (matching the reference, where reuse-with-state is the point).
  virtual void ResetData(void* d) { (void)d; }
};

class SimpleDataPool {
 public:
  explicit SimpleDataPool(DataFactory* factory) : factory_(factory) {}
  ~SimpleDataPool() {
    for (void* d : free_) {
      factory_->DestroyData(d);
    }
  }

  // Pre-creates `n` objects (ServerOptions::reserved_session_local_data
  // parity) so first requests skip CreateData.
  void Reserve(size_t n) {
    std::lock_guard<std::mutex> g(mu_);
    while (free_.size() < n) {
      void* d = factory_->CreateData();
      if (d == nullptr) {
        return;
      }
      ++created_;
      free_.push_back(d);
    }
  }

  void* Borrow() {
    {
      std::lock_guard<std::mutex> g(mu_);
      if (!free_.empty()) {
        void* d = free_.back();
        free_.pop_back();
        factory_->ResetData(d);
        return d;
      }
    }
    void* d = factory_->CreateData();
    if (d != nullptr) {
      std::lock_guard<std::mutex> g(mu_);
      ++created_;
    }
    return d;
  }

  void Return(void* d) {
    if (d == nullptr) {
      return;
    }
    std::lock_guard<std::mutex> g(mu_);
    free_.push_back(d);
  }

  size_t created() const {
    std::lock_guard<std::mutex> g(mu_);
    return created_;
  }
  size_t free_count() const {
    std::lock_guard<std::mutex> g(mu_);
    return free_.size();
  }

 private:
  DataFactory* factory_;
  mutable std::mutex mu_;
  std::vector<void*> free_;
  size_t created_ = 0;
};

}  // namespace trpc
