#include "net/deadline.h"

#include <deque>
#include <unordered_map>
#include <unordered_set>

#include "base/flags.h"
#include "net/controller.h"
#include "net/protocol.h"
#include "net/socket.h"
#include "fiber/scheduler.h"

namespace trpc {

// ---- CancelScope ---------------------------------------------------------

void CancelScope::Cancel() {
  // Release on the flag: a loop that observes cancelled() also observes
  // everything the canceller wrote before triggering.  The exchange
  // makes the fan-out exactly-once under racing triggers (kCancel frame
  // vs. a poller).
  if (cancelled_.exchange(true, std::memory_order_acq_rel)) {
    return;
  }
  std::vector<fid_t> calls;
  std::vector<std::function<void()>> hooks;
  {
    std::lock_guard<std::mutex> g(mu_);
    calls.swap(calls_);
    hooks.swap(hooks_);
  }
  deadline_vars().cancel_fanout_total << 1;
  for (fid_t cid : calls) {
    StartCancel(cid);  // versioned fid: stale/completed calls no-op
  }
  for (auto& hook : hooks) {
    hook();
  }
}

bool CancelScope::triggered(int64_t now_us) const {
  if (cancelled()) {
    return true;
  }
  if (deadline_us != 0 &&
      (now_us != 0 ? now_us : monotonic_time_us()) >= deadline_us) {
    return true;
  }
  if (socket != 0) {
    SocketRef s(Socket::Address(socket));
    if (!s || s->Failed()) {
      return true;  // the caller's connection died: nobody wants this work
    }
  }
  return false;
}

void CancelScope::add_call(fid_t cid) {
  if (cid == 0) {
    return;
  }
  {
    std::lock_guard<std::mutex> g(mu_);
    if (!cancelled()) {
      // Bound the record: a request issuing thousands of downstream
      // calls keeps only the newest window — older ones have almost
      // certainly completed, and a stale fid cancel is a no-op anyway.
      if (calls_.size() >= 1024) {
        calls_.erase(calls_.begin(), calls_.begin() + 512);
      }
      calls_.push_back(cid);
      return;
    }
  }
  StartCancel(cid);  // late registration after the trigger: cancel now
}

void CancelScope::add_hook(std::function<void()> hook) {
  {
    std::lock_guard<std::mutex> g(mu_);
    if (!cancelled()) {
      hooks_.push_back(std::move(hook));
      return;
    }
  }
  hook();
}

// ---- ambient propagation -------------------------------------------------

namespace {

// Off-fiber fallback (ctypes callers on Python pthreads, like the
// ambient trace context in net/span.cc).
thread_local int64_t tls_ambient_deadline = 0;
thread_local CancelScope* tls_ambient_cancel = nullptr;

}  // namespace

void set_ambient_deadline(int64_t abs_us) {
  Worker* w = tls_worker;
  if (w != nullptr && w->current() != nullptr) {
    // Relaxed: own-fiber context write (see scheduler.h ambient_deadline).
    w->current()->ambient_deadline.store(abs_us, std::memory_order_relaxed);
  } else {
    tls_ambient_deadline = abs_us;
  }
}

int64_t ambient_deadline() {
  Worker* w = tls_worker;
  if (w != nullptr && w->current() != nullptr) {
    // Relaxed: own-fiber context read (see scheduler.h ambient_deadline).
    return w->current()->ambient_deadline.load(std::memory_order_relaxed);
  }
  return tls_ambient_deadline;
}

void set_ambient_cancel(CancelScope* scope) {
  Worker* w = tls_worker;
  if (w != nullptr && w->current() != nullptr) {
    // Relaxed: own-fiber context write (see scheduler.h ambient_cancel).
    w->current()->ambient_cancel.store(scope, std::memory_order_relaxed);
  } else {
    tls_ambient_cancel = scope;
  }
}

CancelScope* ambient_cancel() {
  Worker* w = tls_worker;
  if (w != nullptr && w->current() != nullptr) {
    // Relaxed: own-fiber context read (see scheduler.h ambient_cancel).
    return static_cast<CancelScope*>(
        w->current()->ambient_cancel.load(std::memory_order_relaxed));
  }
  return tls_ambient_cancel;
}

// ---- registry ------------------------------------------------------------

namespace {

// Sharded by (socket, cid) so the per-request register/unregister pair
// never funnels the whole server through one mutex.  Leaked statics:
// runtime registries outlive static destruction order.
constexpr size_t kCancelShards = 16;

struct CancelKey {
  uint64_t socket;
  uint64_t cid;
  bool operator==(const CancelKey& o) const {
    return socket == o.socket && cid == o.cid;
  }
};

struct CancelKeyHash {
  size_t operator()(const CancelKey& k) const {
    // splitmix-style fold: sockets are dense ids, cids dense counters —
    // xor alone would collide systematically.
    uint64_t x = k.socket * 0x9e3779b97f4a7c15ull ^ k.cid;
    x ^= x >> 30;
    x *= 0xbf58476d1ce4e5b9ull;
    x ^= x >> 27;
    return static_cast<size_t>(x);
  }
};

struct CancelShard {
  std::mutex mu;
  std::unordered_map<CancelKey, std::shared_ptr<CancelScope>, CancelKeyHash>
      map;
  // Cancels that arrived BEFORE their request dispatched (still queued
  // in a QoS lane / dispatch backlog): cancel_register consumes the
  // tombstone and sheds the request.  FIFO-capped — an evicted
  // tombstone degrades to the old execute-anyway behavior, never leaks.
  std::unordered_set<CancelKey, CancelKeyHash> tombs;
  std::deque<CancelKey> tomb_order;
};

constexpr size_t kTombCapPerShard = 512;

CancelShard* cancel_shards() {
  static CancelShard* s = new CancelShard[kCancelShards];
  return s;
}

CancelShard& shard_for(uint64_t socket, uint64_t cid) {
  return cancel_shards()[CancelKeyHash{}({socket, cid}) % kCancelShards];
}

}  // namespace

bool cancel_register(uint64_t socket, uint64_t cid,
                     std::shared_ptr<CancelScope> scope) {
  CancelShard& sh = shard_for(socket, cid);
  const CancelKey key{socket, cid};
  std::lock_guard<std::mutex> g(sh.mu);
  auto tomb = sh.tombs.find(key);
  if (tomb != sh.tombs.end()) {
    // The cancel raced ahead of dispatch: consume the tombstone, shed.
    sh.tombs.erase(tomb);
    for (auto it = sh.tomb_order.begin(); it != sh.tomb_order.end(); ++it) {
      if (*it == key) {
        sh.tomb_order.erase(it);
        break;
      }
    }
    return false;
  }
  sh.map[key] = std::move(scope);
  return true;
}

void cancel_unregister(uint64_t socket, uint64_t cid) {
  CancelShard& sh = shard_for(socket, cid);
  std::lock_guard<std::mutex> g(sh.mu);
  sh.map.erase(CancelKey{socket, cid});
}

bool cancel_fire(uint64_t socket, uint64_t cid) {
  std::shared_ptr<CancelScope> scope;
  {
    CancelShard& sh = shard_for(socket, cid);
    const CancelKey key{socket, cid};
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.map.find(key);
    if (it == sh.map.end()) {
      // Not dispatched (yet): leave a tombstone so a still-queued
      // request sheds at registration.  Already-completed calls never
      // see it (versioned cids are not reused) — it just ages out.
      if (sh.tombs.insert(key).second) {
        sh.tomb_order.push_back(key);
        if (sh.tomb_order.size() > kTombCapPerShard) {
          sh.tombs.erase(sh.tomb_order.front());
          sh.tomb_order.pop_front();
        }
      }
      return false;
    }
    scope = it->second;
  }
  // Fan-out OUTSIDE the shard mutex: StartCancel may complete a call
  // inline, and that completion path must never need this shard.
  scope->Cancel();
  return true;
}

size_t cancel_registered() {
  size_t n = 0;
  for (size_t i = 0; i < kCancelShards; ++i) {
    std::lock_guard<std::mutex> g(cancel_shards()[i].mu);
    n += cancel_shards()[i].map.size();
  }
  return n;
}

void send_cancel_frame(uint64_t sid, uint64_t cid) {
  if (sid == 0 || cid == 0) {
    return;
  }
  SocketRef s(Socket::Address(sid));
  if (!s || s->Failed()) {
    return;  // connection already gone: its death cancels server-side
  }
  RpcMeta meta;
  meta.type = RpcMeta::kCancel;
  meta.correlation_id = cid;
  IOBuf frame;
  tstd_pack(&frame, meta, IOBuf());
  s->Write(std::move(frame));
}

// ---- flags ---------------------------------------------------------------

namespace {

Flag* wire_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_bool(
        "trpc_deadline_wire", true,
        "stamp meta tail-group 7 (remaining deadline budget, µs) on "
        "outbound tstd requests from min(Controller timeout, ambient "
        "deadline); off = byte-identical pre-deadline-plane frames and "
        "no server-side budget enforcement");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
    }
    return flag;
  }();
  return f;
}

Flag* retry_budget_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_cluster_retry_budget_pct", 0,
        "cluster retry token bucket: each primary attempt earns pct/100 "
        "of a retry token, each retry or hedge spends one ([0, 100]; "
        "0 = unlimited, the pre-budget behavior; ~10 bounds retry-storm "
        "amplification to ~1.1x under total downstream failure)");
    if (flag != nullptr) {
      flag->set_int_range(0, 100);
    }
    return flag;
  }();
  return f;
}

// Eager definitions so /flags?setvalue (and tests) can set them before
// first traffic.
[[maybe_unused]] Flag* const g_wire_flag_eager = wire_flag();
[[maybe_unused]] Flag* const g_retry_budget_flag_eager = retry_budget_flag();

}  // namespace

bool deadline_wire_enabled() { return wire_flag()->bool_value(); }

int64_t cluster_retry_budget_pct() {
  return retry_budget_flag()->int64_value();
}

void deadline_ensure_registered() {
  wire_flag();
  retry_budget_flag();
  deadline_vars();
}

// ---- vars ----------------------------------------------------------------

DeadlineVars::DeadlineVars() {
  shed_total.expose(
      "deadline_expired_shed_total",
      "requests shed before handler dispatch because their propagated "
      "budget (meta tail-group 7) had already expired on arrival or "
      "while queued (kEDeadlineExpired)");
  stamped_total.expose(
      "deadline_stamped_total",
      "outbound requests that carried a remaining-budget stamp in meta "
      "tail-group 7");
  client_expired_total.expose(
      "deadline_client_expired_total",
      "calls failed locally (kEDeadlineExpired) because the ambient "
      "budget was exhausted before the request could be sent");
  cancel_fanout_total.expose(
      "deadline_cancel_fanout_total",
      "cancel scopes triggered (client kCancel frame, dead connection, "
      "or expired budget) that fanned out to downstream calls and "
      "in-flight transfers");
  tombstone_shed.expose(
      "deadline_cancel_tombstone_shed_total",
      "requests shed at dispatch because their kCancel control frame "
      "raced ahead of them (cancelled while still queued in a QoS lane "
      "or dispatch backlog)");
  cancel_saved_bytes.expose(
      "deadline_cancel_saved_bytes",
      "payload bytes NOT written by one-sided/striped transfer loops "
      "because the request was cancelled or its budget expired "
      "mid-transfer (wasted work avoided by cascading cancellation)");
  retry_suppressed.expose(
      "cluster_retry_suppressed_total",
      "cluster retries suppressed by the trpc_cluster_retry_budget_pct "
      "token bucket (retry-storm governor)");
  hedge_suppressed.expose(
      "cluster_hedge_suppressed_total",
      "cluster hedges suppressed because the retry budget was empty or "
      "the remaining deadline could not cover a fresh attempt "
      "(observed p50)");
}

DeadlineVars& deadline_vars() {
  static DeadlineVars* v = new DeadlineVars();
  return *v;
}

}  // namespace trpc
