// Deadline & cancellation plane — end-to-end budgets, cascading cancel,
// and the cluster retry budget's shared state.
//
// No direct brpc parity: the reference propagates nothing past the first
// hop (a brpc server never learns the caller's remaining budget, and a
// dead caller leaves downstream work running to completion).  This plane
// closes that gap three ways:
//
//  1. WIRE — meta tail-group 7 `(deadline)` carries the caller's
//     *remaining* budget in µs (relative, so clock skew between hosts is
//     irrelevant; zero bytes on the wire when unset).  Channels stamp it
//     from min(Controller::timeout_ms, ambient deadline) at send; a
//     proxied call therefore re-stamps budget-minus-elapsed at every hop
//     automatically, exactly like the rpcz trace context rides ambient
//     fiber state (net/span.h).
//
//  2. SERVER ENFORCEMENT — the parse path stamps the request's arrival
//     time; requests whose budget expired while in flight or queued in a
//     QoS lane are shed BEFORE handler dispatch with the distinct
//     kEDeadlineExpired status (the cluster client stops the attempt
//     chain on it: retrying a dead budget is pure waste).  Handlers read
//     Controller::remaining_us(), and long-running transfer loops
//     (stripe rails, one-sided RMA chunk writers, collective steps)
//     check a DeadlineToken between chunks and abort whole-or-nothing
//     through the existing fault semantics.
//
//  3. CASCADING CANCELLATION — every dispatched request owns a
//     CancelScope registered under (connection, correlation id).  A
//     kCancel control frame (client StartCancel), or the scope's
//     triggered() poll observing a dead connection / expired budget,
//     fans the cancel out to every downstream call the handler issued
//     (registered via the ambient scope) and aborts in-flight one-sided
//     transfers between chunks — a dead caller's work stops within one
//     chunk budget instead of running to completion.
//
// The retry budget itself (SRE-style token bucket, ~10% of primary
// traffic) lives in net/cluster.cc; this header owns its flag +
// counters so the whole deadline plane's observability sits in one
// place.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <vector>

#include "base/time.h"
#include "fiber/fid.h"
#include "stat/reducer.h"

namespace trpc {

// Continues the 2004..2006 (kELimit/kEOverloaded/kEDraining) family in
// concurrency_limiter.h.  kEDeadlineExpired: the caller's propagated
// budget ran out before (or while) this node could do the work.  The
// cluster client treats it as NON-RETRIABLE for the same attempt chain
// — the budget is just as dead on every other node — and Python
// surfaces it as the typed DeadlineExpiredError.
constexpr int kEDeadlineExpired = 2007;

// Per-request cancellation scope (server side).  Owned by shared_ptr:
// the registry, the request's Controller and the dispatch fiber co-own
// it, so a cancel frame racing request completion can never touch a
// freed scope.  Downstream calls registered here are cancelled via the
// versioned-fid error path, so a stale registration (call already
// completed) is a harmless no-op and completion never needs to
// unregister.
class CancelScope {
 public:
  // Idempotent trigger: fans StartCancel out to every registered
  // downstream call and runs the abort hooks exactly once.
  void Cancel();
  bool cancelled() const {
    // Acquire: pairs with Cancel()'s release store so a loop observing
    // the flag also observes any state the canceller wrote before it.
    return cancelled_.load(std::memory_order_acquire);
  }
  // Full trigger poll for long-running loops: cancelled, the request's
  // connection died, or the request's budget expired.  `now_us` 0 reads
  // the clock.
  bool triggered(int64_t now_us = 0) const;

  // Registers an in-flight downstream call / an abort hook (hooks abort
  // non-call work: RMA sessions, collective schedules).  Registration
  // after Cancel() fires immediately — a handler that keeps issuing
  // downstream work after its caller died has that work cancelled too.
  void add_call(fid_t cid);
  void add_hook(std::function<void()> hook);

  // Bound state, written once at registration (before the scope is
  // published to the registry).
  uint64_t socket = 0;        // request connection; its death = cancel
  int64_t deadline_us = 0;    // absolute monotonic; 0 = none

 private:
  std::atomic<bool> cancelled_{false};
  std::mutex mu_;
  std::vector<fid_t> calls_;
  std::vector<std::function<void()>> hooks_;
};

// ---- ambient propagation (like the rpcz trace context) -------------------

// Absolute monotonic deadline of the request the current fiber (or, off
// fiber, the current pthread) is serving; 0 = none.  Channels fold it
// into every outbound call's stamped budget, so the budget decrements
// by elapsed time at every hop without anyone passing it explicitly.
void set_ambient_deadline(int64_t abs_us);  // 0 clears
int64_t ambient_deadline();

// The serving request's cancel scope.  Raw pointer by design: it is
// only ever read synchronously inside the handler extent, where the
// dispatch fiber's shared_ptr keeps the scope alive (same lifetime
// argument as the ambient span).  Cleared by the dispatch fiber's guard
// on every exit path.
void set_ambient_cancel(CancelScope* scope);  // nullptr clears
CancelScope* ambient_cancel();

// Remaining budget of an absolute deadline (INT64_MAX when abs_us == 0,
// 0 when already past).
inline int64_t deadline_remaining_us(int64_t abs_us) {
  if (abs_us == 0) {
    return INT64_MAX;
  }
  const int64_t rem = abs_us - monotonic_time_us();
  return rem > 0 ? rem : 0;
}

// Abort predicate checked between chunks by the long-running transfer
// loops (rma rails, stripe sender, collective steps).  Both fields are
// borrowed: the scope must outlive the loop (the caller holds the
// owning shared_ptr across it).
struct DeadlineToken {
  const CancelScope* scope = nullptr;
  int64_t deadline_us = 0;  // absolute monotonic; 0 = none
  bool aborted(int64_t now_us = 0) const {
    if (scope != nullptr && scope->triggered(now_us)) {
      return true;
    }
    if (deadline_us != 0) {
      return (now_us != 0 ? now_us : monotonic_time_us()) >= deadline_us;
    }
    return false;
  }
};

// ---- (connection, correlation id) → scope registry -----------------------

// Sharded registry the kCancel control frame resolves through.  One
// entry per DISPATCHED request (shed/early-error requests never own
// work worth cancelling); unregistered by the response path.
//
// Returns false when a cancel TOMBSTONE for (socket, cid) was pending:
// the kCancel frame raced ahead of dispatch (request still queued in a
// QoS lane / dispatch backlog when it arrived) — the caller must shed
// the request as cancelled instead of executing work nobody wants.
// The scope is NOT registered in that case.
bool cancel_register(uint64_t socket, uint64_t cid,
                     std::shared_ptr<CancelScope> scope);
void cancel_unregister(uint64_t socket, uint64_t cid);
// Fires the scope registered under (socket, cid), if any.  Returns true
// when one was found (counted by deadline_cancel_fanout_total).  A miss
// leaves a bounded TOMBSTONE instead: the request may still be queued
// (QoS lane, dispatch backlog) — when it finally reaches
// cancel_register, it is shed as cancelled.  Versioned correlation ids
// make a tombstone for an already-completed call harmless (the id is
// never reused), and the per-shard cap bounds the memory.
bool cancel_fire(uint64_t socket, uint64_t cid);
// Live registrations (tests: must drain to 0 with no traffic in flight).
size_t cancel_registered();

// Queues a kCancel control frame for `cid` on `sid` (fire-and-forget;
// no-op when the socket is gone).  Shared by Controller::StartCancel and
// the free StartCancel(fid_t).
void send_cancel_frame(uint64_t sid, uint64_t cid);

// ---- flags ---------------------------------------------------------------

// trpc_deadline_wire (default true): stamp tail-group 7 from the
// effective timeout / ambient budget.  Off = byte-identical pre-plane
// frames (the byte-identity guard's lever).
bool deadline_wire_enabled();
// trpc_cluster_retry_budget_pct (default 0 = unlimited): SRE-style
// retry token bucket — each primary attempt earns pct/100 of a retry
// token, each retry or hedge spends one.  ~10 is the recommended
// production value; the default keeps existing retry semantics intact.
int64_t cluster_retry_budget_pct();
// Idempotent flag/var registration (the capi calls it so /flags sees
// the knobs before first traffic).
void deadline_ensure_registered();

// ---- vars ----------------------------------------------------------------

struct DeadlineVars {
  Adder shed_total;            // deadline_expired_shed_total
  Adder stamped_total;         // deadline_stamped_total
  Adder client_expired_total;  // deadline_client_expired_total
  Adder cancel_fanout_total;   // deadline_cancel_fanout_total
  Adder cancel_saved_bytes;    // deadline_cancel_saved_bytes
  Adder tombstone_shed;        // deadline_cancel_tombstone_shed_total
  Adder retry_suppressed;      // cluster_retry_suppressed_total
  Adder hedge_suppressed;      // cluster_hedge_suppressed_total
  DeadlineVars();
};
DeadlineVars& deadline_vars();

}  // namespace trpc
