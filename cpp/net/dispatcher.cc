#include "net/dispatcher.h"

#include <pthread.h>
#include <sys/epoll.h>

#include "base/logging.h"
#include "net/socket.h"

namespace trpc {

EventDispatcher* EventDispatcher::instance() {
  // Deliberately leaked: detached threads outlive static destruction.
  static EventDispatcher* d = new EventDispatcher();
  return d;
}

EventDispatcher::EventDispatcher() {
  epfd_ = epoll_create1(EPOLL_CLOEXEC);
  CHECK(epfd_ >= 0);
  pthread_t tid;
  pthread_create(
      &tid, nullptr,
      [](void* self) -> void* {
        static_cast<EventDispatcher*>(self)->run();
        return nullptr;
      },
      this);
  pthread_detach(tid);
}

int EventDispatcher::add(int fd, uint64_t socket_id) {
  epoll_event ev = {};
  ev.events = EPOLLIN | EPOLLOUT | EPOLLET | EPOLLRDHUP;
  ev.data.u64 = socket_id;
  return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
}

int EventDispatcher::remove(int fd) {
  return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

void EventDispatcher::run() {
  constexpr int kMaxEvents = 64;
  epoll_event events[kMaxEvents];
  while (true) {
    const int n = epoll_wait(epfd_, events, kMaxEvents, -1);
    for (int i = 0; i < n; ++i) {
      Socket* s = Socket::Address(events[i].data.u64);
      if (s == nullptr) {
        continue;  // stale event on a recycled slot
      }
      if (events[i].events & (EPOLLOUT)) {
        s->on_output_event();
      }
      if (events[i].events & (EPOLLIN | EPOLLRDHUP | EPOLLERR | EPOLLHUP)) {
        s->on_input_event();
      }
      s->Dereference();
    }
  }
}

}  // namespace trpc
