#include "net/dispatcher.h"

#include <pthread.h>
#include <stdlib.h>
#include <sys/epoll.h>

#include "base/flags.h"
#include "base/logging.h"
#include "net/socket.h"

namespace trpc {

namespace {

Flag* dispatchers_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_event_dispatchers", 1,
        "epoll event loops fds are hash-sharded across (latched at the "
        "first socket registration; raise BEFORE any traffic)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long n = strtol(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 1 &&
               n <= EventDispatcher::kMaxDispatchers;
      });
    }
    return flag;
  }();
  return f;
}

[[maybe_unused]] Flag* const g_dispatchers_eager = dispatchers_flag();

}  // namespace

int EventDispatcher::count() {
  // Latched once: a later flag flip must not strand registered fds on
  // loops that for_fd would no longer pick for them.
  static const int n = [] {
    const int64_t v = dispatchers_flag()->int64_value();
    return v >= 1 && v <= kMaxDispatchers ? static_cast<int>(v) : 1;
  }();
  return n;
}

EventDispatcher* EventDispatcher::for_fd(int fd) {
  // Deliberately leaked: detached threads outlive static destruction.
  static EventDispatcher* const* loops = [] {
    auto** all = new EventDispatcher*[kMaxDispatchers];
    for (int i = 0; i < count(); ++i) {
      all[i] = new EventDispatcher();
    }
    return const_cast<EventDispatcher* const*>(all);
  }();
  const int n = count();
  return loops[fd >= 0 ? fd % n : 0];
}

EventDispatcher::EventDispatcher() {
  epfd_ = epoll_create1(EPOLL_CLOEXEC);
  CHECK(epfd_ >= 0);
  pthread_t tid;
  pthread_create(
      &tid, nullptr,
      [](void* self) -> void* {
        static_cast<EventDispatcher*>(self)->run();
        return nullptr;
      },
      this);
  pthread_detach(tid);
}

int EventDispatcher::add(int fd, uint64_t socket_id) {
  epoll_event ev = {};
  ev.events = EPOLLIN | EPOLLOUT | EPOLLET | EPOLLRDHUP;
  ev.data.u64 = socket_id;
  return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
}

int EventDispatcher::remove(int fd) {
  return epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
}

void EventDispatcher::run() {
  constexpr int kMaxEvents = 64;
  epoll_event events[kMaxEvents];
  while (true) {
    const int n = epoll_wait(epfd_, events, kMaxEvents, -1);
    for (int i = 0; i < n; ++i) {
      Socket* s = Socket::Address(events[i].data.u64);
      if (s == nullptr) {
        continue;  // stale event on a recycled slot
      }
      if (events[i].events & (EPOLLOUT)) {
        s->on_output_event();
      }
      if (events[i].events & (EPOLLIN | EPOLLRDHUP | EPOLLERR | EPOLLHUP)) {
        s->on_input_event();
      }
      s->Dereference();
    }
  }
}

}  // namespace trpc
