// EventDispatcher — the epoll-ET loop feeding sockets.
//
// Parity: brpc EventDispatcher (/root/reference/src/brpc/event_dispatcher.h:
// 96-197; Run loop event_dispatcher_epoll.cpp:207-213).  The epoll payload
// is the versioned SocketId, never a pointer, so stale events on recycled
// slots are dropped by the version check in Socket::Address — the same
// armor as the reference's IOEventDataId.  Re-designed: the loop runs in a
// dedicated pthread (the reference runs it in a bthread) since our fibers
// park on Events, not fds.
#pragma once

#include <cstdint>

namespace trpc {

class EventDispatcher {
 public:
  static EventDispatcher* instance();

  // Registers fd for edge-triggered IN|OUT with the given versioned id.
  int add(int fd, uint64_t socket_id);
  int remove(int fd);

 private:
  EventDispatcher();
  void run();
  int epfd_ = -1;
};

}  // namespace trpc
