// EventDispatcher — the epoll-ET loop feeding sockets.
//
// Parity: brpc EventDispatcher (/root/reference/src/brpc/event_dispatcher.h:
// 96-197; Run loop event_dispatcher_epoll.cpp:207-213; the reference runs
// -event_dispatcher_num loops and hashes fds across them,
// event_dispatcher.cpp:113).  The epoll payload is the versioned SocketId,
// never a pointer, so stale events on recycled slots are dropped by the
// version check in Socket::Address — the same armor as the reference's
// IOEventDataId.  Re-designed: each loop runs in a dedicated pthread (the
// reference runs it in a bthread) since our fibers park on Events, not fds.
//
// Sharding: trpc_event_dispatchers (latched at first use, 1..kMaxDispatchers)
// epoll loops; a socket's fd hashes to its loop via for_fd, so add/remove
// for one fd always land on the same epoll set.  One loop (the default)
// keeps the pre-sharding behavior bit-for-bit.
#pragma once

#include <cstdint>

namespace trpc {

class EventDispatcher {
 public:
  static constexpr int kMaxDispatchers = 8;

  // The dispatcher responsible for `fd` (fd-hash over the latched count).
  static EventDispatcher* for_fd(int fd);
  // Dispatcher count latched from trpc_event_dispatchers at first use.
  static int count();

  // Registers fd for edge-triggered IN|OUT with the given versioned id.
  int add(int fd, uint64_t socket_id);
  int remove(int fd);

 private:
  EventDispatcher();
  void run();
  int epfd_ = -1;
};

}  // namespace trpc
