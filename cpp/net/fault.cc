#include "net/fault.h"

#include <errno.h>
#include <stdio.h>
#include <stdlib.h>

#include <algorithm>
#include <map>

#include "base/flags.h"
#include "base/iobuf.h"
#include "base/logging.h"
#include "net/socket.h"
#include "net/transport.h"

namespace trpc {

void fiber_sleep_us(int64_t us);  // fiber/fiber.h (avoid the heavy include)

const char* fault_point_name(FaultPoint p) {
  switch (p) {
    case FaultPoint::kTx:
      return "tx";
    case FaultPoint::kRx:
      return "rx";
    case FaultPoint::kConnect:
      return "connect";
    case FaultPoint::kDispatch:
      return "dispatch";
    case FaultPoint::kAccept:
      return "accept";
  }
  return "?";
}

const char* fault_kind_name(FaultKind k) {
  switch (k) {
    case FaultKind::kNone:
      return "none";
    case FaultKind::kDrop:
      return "drop";
    case FaultKind::kCorrupt:
      return "corrupt";
    case FaultKind::kTrunc:
      return "trunc";
    case FaultKind::kPartial:
      return "partial";
    case FaultKind::kReset:
      return "reset";
    case FaultKind::kRefuse:
      return "refuse";
    case FaultKind::kDelay:
      return "delay";
    case FaultKind::kSvrDelay:
      return "svr_delay";
    case FaultKind::kSvrError:
      return "svr_error";
    case FaultKind::kSvrReject:
      return "svr_reject";
  }
  return "?";
}

namespace {

// splitmix64: the decision PRNG.  Stateless — verdict i is a pure
// function of (seed, i), which is what makes replay exact regardless of
// thread interleaving (concurrency can reorder which OPERATION gets
// index i, never what index i decides).
uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

double unit_interval(uint64_t r) {
  return static_cast<double>(r >> 11) * (1.0 / 9007199254740992.0);  // 2^-53
}

// "P" or "P:EXTRA" → probability (+ optional int64 parameter).
bool parse_prob(const std::string& v, double* p, int64_t* extra) {
  const size_t colon = v.find(':');
  char* end = nullptr;
  const std::string head = v.substr(0, colon);
  *p = strtod(head.c_str(), &end);
  // !(>= && <=) rather than (< || >): NaN fails every comparison, and a
  // NaN probability would install an "active" schedule that can never
  // fire — the silent no-op this parser exists to reject.
  if (end == head.c_str() || *end != '\0' || !(*p >= 0.0 && *p <= 1.0)) {
    return false;
  }
  if (colon == std::string::npos) {
    return extra == nullptr;  // kinds that need EXTRA must get one
  }
  if (extra == nullptr) {
    return false;
  }
  const std::string tail = v.substr(colon + 1);
  *extra = strtoll(tail.c_str(), &end, 10);
  return end != tail.c_str() && *end == '\0' && *extra >= 0;
}

bool parse_u64(const std::string& v, uint64_t* out) {
  char* end = nullptr;
  *out = strtoull(v.c_str(), &end, 10);
  return end != v.c_str() && *end == '\0';
}

}  // namespace

bool FaultSchedule::parse(const std::string& spec, FaultSchedule* out) {
  *out = FaultSchedule();
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t end = spec.find_first_of(";,", pos);
    if (end == std::string::npos) {
      end = spec.size();
    }
    std::string field = spec.substr(pos, end - pos);
    pos = end + 1;
    // Trim surrounding whitespace.
    const size_t b = field.find_first_not_of(" \t");
    const size_t e = field.find_last_not_of(" \t");
    if (b == std::string::npos) {
      continue;
    }
    field = field.substr(b, e - b + 1);
    const size_t eq = field.find('=');
    if (eq == std::string::npos) {
      return false;
    }
    const std::string key = field.substr(0, eq);
    const std::string val = field.substr(eq + 1);
    int64_t extra = 0;
    bool ok = true;
    if (key == "seed") {
      ok = parse_u64(val, &out->seed);
    } else if (key == "peer") {
      ok = hostname2endpoint(val.c_str(), &out->peer) == 0;
      out->has_peer = ok;
    } else if (key == "after") {
      ok = parse_u64(val, &out->after);
    } else if (key == "max") {
      ok = parse_u64(val, &out->max_faults);
    } else if (key == "drop") {
      ok = parse_prob(val, &out->drop, nullptr);
    } else if (key == "corrupt") {
      ok = parse_prob(val, &out->corrupt, nullptr);
    } else if (key == "trunc") {
      ok = parse_prob(val, &out->trunc, nullptr);
    } else if (key == "partial") {
      ok = parse_prob(val, &out->partial, nullptr);
    } else if (key == "reset") {
      ok = parse_prob(val, &out->reset, nullptr);
    } else if (key == "refuse") {
      ok = parse_prob(val, &out->refuse, nullptr);
    } else if (key == "delay") {
      ok = parse_prob(val, &out->delay, &extra);
      out->delay_ms = extra;
    } else if (key == "svr_delay") {
      ok = parse_prob(val, &out->svr_delay, &extra);
      out->svr_delay_ms = extra;
    } else if (key == "svr_error") {
      ok = parse_prob(val, &out->svr_error, &extra) && extra > 0;
      out->svr_error_code = static_cast<int>(extra);
    } else if (key == "svr_reject") {
      ok = parse_prob(val, &out->svr_reject, nullptr);
    } else {
      return false;  // unknown key: reject, never silently no-op
    }
    if (!ok) {
      return false;
    }
  }
  return true;
}

std::string FaultSchedule::to_string() const {
  char buf[64];
  std::string s = "seed=" + std::to_string(seed);
  if (has_peer) {
    s += ";peer=" + endpoint2str(peer);
  }
  if (after != 0) {
    s += ";after=" + std::to_string(after);
  }
  if (max_faults != 0) {
    s += ";max=" + std::to_string(max_faults);
  }
  const auto prob = [&s, &buf](const char* k, double p) {
    if (p > 0) {
      snprintf(buf, sizeof(buf), ";%s=%g", k, p);
      s += buf;
    }
  };
  prob("drop", drop);
  prob("corrupt", corrupt);
  prob("trunc", trunc);
  prob("partial", partial);
  prob("reset", reset);
  prob("refuse", refuse);
  if (delay > 0) {
    snprintf(buf, sizeof(buf), ";delay=%g:%lld", delay,
             static_cast<long long>(delay_ms));
    s += buf;
  }
  if (svr_delay > 0) {
    snprintf(buf, sizeof(buf), ";svr_delay=%g:%lld", svr_delay,
             static_cast<long long>(svr_delay_ms));
    s += buf;
  }
  if (svr_error > 0) {
    snprintf(buf, sizeof(buf), ";svr_error=%g:%d", svr_error,
             svr_error_code);
    s += buf;
  }
  prob("svr_reject", svr_reject);
  return s;
}

// ---- FaultActor ----------------------------------------------------------

namespace {

// Scope check: a spec whose fields can never fire on this actor's fault
// points must be rejected loudly, not installed as a silent no-op.
bool schedule_in_scope(const FaultSchedule& s, FaultScope scope) {
  const bool has_transport = s.drop > 0 || s.corrupt > 0 || s.trunc > 0 ||
                             s.partial > 0 || s.reset > 0 ||
                             s.refuse > 0 || s.delay > 0;
  const bool has_server =
      s.svr_delay > 0 || s.svr_error > 0 || s.svr_reject > 0;
  switch (scope) {
    case FaultScope::kTransport:
      return !has_server;
    case FaultScope::kServer:
      return !has_transport;
    case FaultScope::kAny:
      break;
  }
  return true;
}

}  // namespace

bool FaultActor::parse_ok(const std::string& spec) const {
  if (spec.empty()) {
    return true;
  }
  FaultSchedule s;
  return FaultSchedule::parse(spec, &s) && schedule_in_scope(s, scope_);
}

int FaultActor::set(const std::string& spec) {
  std::shared_ptr<const FaultSchedule> fresh;
  if (!spec.empty()) {
    auto parsed = std::make_shared<FaultSchedule>();
    if (!FaultSchedule::parse(spec, parsed.get()) ||
        !schedule_in_scope(*parsed, scope_)) {
      return -1;
    }
    fresh = std::move(parsed);
  }
  {
    std::lock_guard<std::mutex> g(mu_);
    schedule_ = fresh;
  }
  reset_counters();
  active_.store(fresh != nullptr, std::memory_order_release);
  return 0;
}

std::string FaultActor::spec() const {
  auto s = snapshot();
  return s != nullptr ? s->to_string() : std::string();
}

std::shared_ptr<const FaultSchedule> FaultActor::snapshot() const {
  std::lock_guard<std::mutex> g(mu_);
  return schedule_;
}

void FaultActor::reset_counters() {
  counter_.store(0, std::memory_order_relaxed);
  injected_.store(0, std::memory_order_relaxed);
  jitter_counter_.store(0, std::memory_order_relaxed);
  std::lock_guard<std::mutex> g(log_mu_);
  log_.clear();
  log_head_ = 0;
}

uint64_t FaultActor::jitter_draw() {
  // Relaxed: the index only needs uniqueness within the stream; the
  // (index -> value) mapping is the pure splitmix64 function.
  const uint64_t i = jitter_counter_.fetch_add(1, std::memory_order_relaxed);
  auto sched = snapshot();
  const uint64_t seed = sched != nullptr ? sched->seed : 1;
  // Offset namespace (~0x6a77) keeps the jitter stream disjoint from the
  // decision stream even under the same seed and colliding indices.
  return mix64(seed ^ 0x6a77000000000000ull ^
               (i + 1) * 0x9e3779b97f4a7c15ull);
}

FaultDecision FaultActor::decide(FaultPoint point, const EndPoint& peer) {
  FaultDecision d;
  if (!active()) {
    return d;
  }
  auto sched = snapshot();
  if (sched == nullptr) {
    return d;
  }
  if (sched->has_peer && !(sched->peer == peer)) {
    return d;
  }
  d.index = counter_.fetch_add(1, std::memory_order_relaxed);
  if (d.index < sched->after) {
    return d;
  }
  if (sched->max_faults != 0 &&
      injected_.load(std::memory_order_relaxed) >= sched->max_faults) {
    return d;
  }
  d.rand = mix64(sched->seed ^ (d.index + 1) * 0x9e3779b97f4a7c15ull);
  const double u = unit_interval(d.rand);
  // Per-point kinds in fixed precedence; cumulative thresholds so at most
  // one fires per decision.
  double cum = 0;
  const auto hit = [&cum, u](double p) {
    if (p <= 0) {
      return false;
    }
    cum += p;
    return u < cum;
  };
  switch (point) {
    case FaultPoint::kTx:
      if (hit(sched->reset)) {
        d.kind = FaultKind::kReset;
      } else if (hit(sched->drop)) {
        d.kind = FaultKind::kDrop;
      } else if (hit(sched->trunc)) {
        d.kind = FaultKind::kTrunc;
      } else if (hit(sched->corrupt)) {
        d.kind = FaultKind::kCorrupt;
      } else if (hit(sched->partial)) {
        d.kind = FaultKind::kPartial;
      }
      break;
    case FaultPoint::kRx:
      if (hit(sched->reset)) {
        d.kind = FaultKind::kReset;
      } else if (hit(sched->trunc)) {
        d.kind = FaultKind::kTrunc;
      } else if (hit(sched->corrupt)) {
        d.kind = FaultKind::kCorrupt;
      } else if (hit(sched->delay)) {
        d.kind = FaultKind::kDelay;
        d.delay_ms = sched->delay_ms;
      }
      break;
    case FaultPoint::kConnect:
      if (hit(sched->refuse)) {
        d.kind = FaultKind::kRefuse;
      }
      break;
    case FaultPoint::kDispatch:
      if (hit(sched->svr_error)) {
        d.kind = FaultKind::kSvrError;
        d.error_code = sched->svr_error_code;
      } else if (hit(sched->svr_delay)) {
        d.kind = FaultKind::kSvrDelay;
        d.delay_ms = sched->svr_delay_ms;
      }
      break;
    case FaultPoint::kAccept:
      if (hit(sched->svr_reject)) {
        d.kind = FaultKind::kSvrReject;
      }
      break;
  }
  if (d.kind != FaultKind::kNone) {
    // max= is a hard blast-radius bound even under concurrent decisions:
    // RESERVE a slot (fetch_add-then-check), don't check-then-add — the
    // early read above is only a fast-path skip.
    if (sched->max_faults != 0 &&
        injected_.fetch_add(1, std::memory_order_relaxed) >=
            sched->max_faults) {
      injected_.fetch_sub(1, std::memory_order_relaxed);
      d.kind = FaultKind::kNone;
      d.delay_ms = 0;
      d.error_code = 0;
      return d;
    }
    if (sched->max_faults == 0) {
      injected_.fetch_add(1, std::memory_order_relaxed);
    }
    std::lock_guard<std::mutex> g(log_mu_);
    if (log_.size() < kLogCap) {
      log_.push_back({d.index, point, d.kind});
    } else {
      log_[log_head_] = {d.index, point, d.kind};
      log_head_ = (log_head_ + 1) % kLogCap;
    }
  }
  return d;
}

std::string FaultActor::log_text(size_t max_rows) const {
  std::lock_guard<std::mutex> g(log_mu_);
  std::string out;
  const size_t n = log_.size();
  const size_t take = std::min(n, max_rows);
  char line[64];
  for (size_t i = n - take; i < n; ++i) {
    const LogEntry& e = log_[(log_head_ + i) % std::max<size_t>(n, 1)];
    snprintf(line, sizeof(line), "#%llu %s %s\n",
             static_cast<unsigned long long>(e.index),
             fault_point_name(e.point), fault_kind_name(e.kind));
    out += line;
  }
  return out;
}

FaultActor& FaultActor::global() {
  static FaultActor* a = new FaultActor(FaultScope::kTransport);
  return *a;
}

// ---- FaultTransport ------------------------------------------------------

namespace {

class FaultTransport final : public Transport {
 public:
  explicit FaultTransport(Transport* inner) : inner_(inner) {}
  Transport* inner() const { return inner_; }

  ssize_t cut_from_iobuf(Socket* s, IOBuf* from) override {
    FaultActor& a = FaultActor::global();
    if (!a.active() || from->empty()) {
      return inner_->cut_from_iobuf(s, from);
    }
    const FaultDecision d = a.decide(FaultPoint::kTx, s->remote());
    switch (d.kind) {
      case FaultKind::kReset:
        errno = ECONNRESET;
        return -1;
      case FaultKind::kDrop: {
        // The bytes vanish on the wire but look sent: the caller observes
        // a stuck peer (timeout path), not a local error.
        const size_t n = from->size();
        from->clear();
        return static_cast<ssize_t>(n);
      }
      case FaultKind::kTrunc: {
        // Deliver a prefix, discard the tail of what was queued.  The
        // receiver sees a frame that never completes (or misframed
        // follow-on bytes) — its parser must time out or reject, never
        // accept a short payload.
        IOBuf head;
        from->cutn(&head, from->size() / 2 + 1);
        from->clear();
        *from = std::move(head);
        return inner_->cut_from_iobuf(s, from);
      }
      case FaultKind::kCorrupt: {
        // Flatten-copy then flip one byte: queued blocks may be shared
        // zero-copy with the caller's request buffer, which must never
        // be scribbled.
        std::string flat = from->to_string();
        flat[d.rand % flat.size()] ^= 0x01;
        from->clear();
        from->append(flat);
        return inner_->cut_from_iobuf(s, from);
      }
      case FaultKind::kPartial: {
        // Only a short prefix moves this round; the rest is re-queued so
        // KeepWrite exercises its resumption path.
        IOBuf head;
        const size_t k =
            1 + static_cast<size_t>(d.rand % (from->size() / 2 + 1));
        from->cutn(&head, k);
        const ssize_t rc = inner_->cut_from_iobuf(s, &head);
        head.append(std::move(*from));
        *from = std::move(head);
        return rc;
      }
      default:
        return inner_->cut_from_iobuf(s, from);
    }
  }

  ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) override {
    FaultActor& a = FaultActor::global();
    if (!a.active()) {
      return inner_->append_to_iobuf(s, to, max);
    }
    // Read FIRST, decide only when bytes actually arrived: the messenger
    // drains until EAGAIN, and letting empty reads consume decision
    // indices would make the seed-replay sequence depend on kernel
    // chunking instead of on the byte stream.
    IOBuf tmp;
    const ssize_t rc = inner_->append_to_iobuf(s, &tmp, max);
    if (rc <= 0) {
      return rc;
    }
    const FaultDecision d = a.decide(FaultPoint::kRx, s->remote());
    switch (d.kind) {
      case FaultKind::kReset:
        errno = ECONNRESET;
        return -1;
      case FaultKind::kDelay:
        // Park the read fiber: bytes arrive late, connection stays up.
        fiber_sleep_us(d.delay_ms * 1000);
        to->append(std::move(tmp));
        return rc;
      case FaultKind::kTrunc: {
        // Never return 0 here: rc > 0 bytes were consumed from the
        // kernel, and 0 means EAGAIN to the messenger — under ET epoll
        // that would stall the drain loop, not truncate the stream.
        const size_t keep = std::max<size_t>(1, tmp.size() / 2);
        IOBuf head;
        tmp.cutn(&head, keep);
        to->append(std::move(head));
        return static_cast<ssize_t>(keep);
      }
      case FaultKind::kCorrupt: {
        std::string flat = tmp.to_string();
        flat[d.rand % flat.size()] ^= 0x01;
        to->append(flat);
        return rc;
      }
      default:
        to->append(std::move(tmp));
        return rc;
    }
  }

  int connect(Socket* s) override {
    FaultActor& a = FaultActor::global();
    if (a.active() &&
        a.decide(FaultPoint::kConnect, s->remote()).kind ==
            FaultKind::kRefuse) {
      errno = ECONNREFUSED;
      return -1;
    }
    return inner_->connect(s);
  }

  // Doorbells pass straight through: faults act on bytes, not on the
  // publish step (swallowing a flush would wedge ring transports, which
  // is a hang, not an injected fault).
  void flush(Socket* s) override { inner_->flush(s); }

  // One-sided capability passes through untouched: rma chunk writes
  // consult the global actor themselves (net/rma.cc rail_run, kTx
  // decisions), and the control frame rides the wrapped byte plane —
  // so drop/trunc/delay compose on both halves of an rma transfer.
  RmaSession* rma(Socket* s) override { return inner_->rma(s); }

  bool fd_based() const override { return inner_->fd_based(); }
  const char* name() const override { return inner_->name(); }

 private:
  Transport* const inner_;
};

}  // namespace

Transport* fault_wrap(Transport* inner) {
  if (inner == nullptr || dynamic_cast<FaultTransport*>(inner) != nullptr) {
    return inner;
  }
  static std::mutex* mu = new std::mutex();
  static auto* cache = new std::map<Transport*, Transport*>();
  std::lock_guard<std::mutex> g(*mu);
  auto it = cache->find(inner);
  if (it == cache->end()) {
    it = cache->emplace(inner, new FaultTransport(inner)).first;
  }
  return it->second;
}

Transport* fault_unwrap(Transport* t) {
  auto* f = dynamic_cast<FaultTransport*>(t);
  return f != nullptr ? f->inner() : t;
}

// ---- flag plumbing -------------------------------------------------------

void fault_register_flag() {
  static Flag* flag = [] {
    Flag* f = Flag::define_string(
        "fault_schedule", "",
        "transport fault-injection schedule (net/fault.h grammar; empty = "
        "off)");
    if (f != nullptr) {
      f->set_validator([](const std::string& v) {
        return FaultActor::global().parse_ok(v);
      });
      f->on_update([](Flag* self) {
        FaultActor::global().set(self->string_value());
      });
    }
    return f;
  }();
  (void)flag;
}

namespace {
// Registered at load so /flags lists it before any /faults request.
const bool g_fault_flag_registered = (fault_register_flag(), true);
}  // namespace

}  // namespace trpc
