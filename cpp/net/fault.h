// Deterministic fault injection — seeded, schedule-driven chaos for the
// transport plane and the server dispatch path.
//
// Motivation ("RPC Considered Harmful", PAPERS.md): what decides whether an
// RPC stack survives distributed ML workloads is its behavior under
// transport-level failure, not its API shape.  The retry / hedging /
// circuit-breaker / health-check machinery in net/cluster.* therefore gets
// a first-class adversary: a FaultTransport decorator that wraps ANY
// Transport (tcp, tls, shm_ring, ici) and injects faults drawn from a
// seeded PRNG, plus server-side fault points (delayed dispatch, forced
// error codes, reject-at-accept) consulted in server.cc.
//
// Determinism: every fault point evaluation consumes one index from an
// atomic counter and derives its verdict as splitmix64(seed, index) — the
// (index → decision) mapping is a pure function of the schedule, so a
// given seed replays the identical fault sequence (the chaos soak's replay
// assertion).  Injected faults are recorded in a bounded event log.
//
// Schedule grammar (';' or ',' separated key[=value] fields, all optional):
//   seed=N          PRNG seed (default 1)
//   peer=ip:port    only sockets whose remote matches (default: all)
//   after=N         pass through the first N decisions (warmup)
//   max=N           inject at most N faults, then pass through
//   drop=P          tx: silently discard the queued bytes ("sent" to /dev/null)
//   corrupt=P       tx+rx: flip one byte of the moved payload
//   trunc=P         tx+rx: deliver only a prefix, discard the tail
//   partial=P       tx: write only a small prefix this round (exercises
//                   KeepWrite resumption / partial-write handling)
//   reset=P         tx+rx: fail the operation with ECONNRESET
//   refuse=P        connect: fail with ECONNREFUSED
//   delay=P:MS      rx: park the read fiber MS ms before delivering
//   svr_delay=P:MS  server: sleep MS ms before dispatching the handler
//   svr_error=P:E   server: answer with error code E instead of dispatching
//   svr_reject=P    server: close freshly accepted connections
// P is a probability in [0,1].  Probabilities are evaluated per fault
// point in a fixed precedence order; at most one fault fires per decision.
// Scoping: drop..delay belong on the GLOBAL transport actor, svr_* on a
// Server's private actor (Server::SetFaults / /faults?server=); a scoped
// actor rejects fields it could never fire (see FaultScope).
//
// Control planes (all runtime, no rebuild):
//   - flag "fault_schedule"      (base/flags.h; /flags/fault_schedule?setvalue=)
//   - builtin "/faults" endpoint (net/builtin.cc; ?set= ?server= ?reset=)
//   - C ABI trpc_fault_*        (capi/rpc_capi.cc → brpc_tpu/rpc/fault.py)
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "base/endpoint.h"

namespace trpc {

class Transport;
class Socket;

enum class FaultPoint : uint8_t {
  kTx = 0,       // Transport::cut_from_iobuf
  kRx,           // Transport::append_to_iobuf
  kConnect,      // Transport::connect
  kDispatch,     // server request dispatch (tstd_process_request)
  kAccept,       // server accept loop
};

enum class FaultKind : uint8_t {
  kNone = 0,
  kDrop,
  kCorrupt,
  kTrunc,
  kPartial,
  kReset,
  kRefuse,
  kDelay,
  kSvrDelay,
  kSvrError,
  kSvrReject,
};

const char* fault_point_name(FaultPoint p);
const char* fault_kind_name(FaultKind k);

// Parsed schedule (immutable once installed; see FaultActor::set).
struct FaultSchedule {
  uint64_t seed = 1;
  bool has_peer = false;
  EndPoint peer;
  uint64_t after = 0;
  uint64_t max_faults = 0;  // 0 = unlimited
  double drop = 0, corrupt = 0, trunc = 0, partial = 0, reset = 0,
         refuse = 0;
  double delay = 0;
  int64_t delay_ms = 0;
  double svr_delay = 0;
  int64_t svr_delay_ms = 0;
  double svr_error = 0;
  int svr_error_code = 0;
  double svr_reject = 0;

  // Parses `spec` (grammar above).  Returns false on any unknown key or
  // malformed value — a typo'd schedule must not silently mean "no
  // faults" (same contract as parse_concurrency_spec).
  static bool parse(const std::string& spec, FaultSchedule* out);
  std::string to_string() const;  // canonical re-rendering
};

// One fault-point verdict.  `rand` is the decision's raw draw — fault
// implementations reuse it for sub-choices (byte offset, prefix length)
// so those stay seed-deterministic too.
struct FaultDecision {
  FaultKind kind = FaultKind::kNone;
  int64_t delay_ms = 0;
  int error_code = 0;
  uint64_t index = 0;
  uint64_t rand = 0;
};

// Which fault-point family an actor serves.  Installing a spec whose
// only active kinds belong to the OTHER scope would silently inject
// nothing — exactly the "typo'd schedule must never silently mean no
// faults" failure — so scoped actors reject mis-scoped fields loudly.
enum class FaultScope : uint8_t {
  kAny = 0,        // unit tests / embedders driving decide() directly
  kTransport,      // kTx/kRx/kConnect: drop..refuse/delay only
  kServer,         // kDispatch/kAccept: svr_* only
};

// A schedule + its decision counter + injected-fault log.  One global
// instance drives every FaultTransport; each Server owns a private one
// for its dispatch/accept points (so one node of an in-process cluster
// can fail while its siblings stay clean).
class FaultActor {
 public:
  explicit FaultActor(FaultScope scope = FaultScope::kAny)
      : scope_(scope) {}

  // Installs a schedule ("" disables).  Returns 0, or -1 on parse error
  // OR a field outside this actor's scope (previous schedule kept).
  // Resets the decision counter and log — installing a schedule starts a
  // fresh deterministic sequence.
  int set(const std::string& spec);
  std::string spec() const;
  // Parse + scope pre-check without installing (the /faults endpoint
  // validates both specs before applying either).
  bool parse_ok(const std::string& spec) const;

  // Fast inactive check (one relaxed load) for hot paths.
  bool active() const { return active_.load(std::memory_order_acquire); }

  // Draws the verdict for one fault-point evaluation.  kNone when
  // inactive, the peer filter excludes `peer`, the warmup/max bounds
  // apply, or the dice say pass.
  FaultDecision decide(FaultPoint point, const EndPoint& peer);

  // Restarts the deterministic sequence: counter to zero, log cleared
  // (schedule kept).  The seed-replay test is: set → run → log_text →
  // reset_counters → run → log_text, expecting identical text.
  void reset_counters();

  uint64_t decisions() const {
    return counter_.load(std::memory_order_relaxed);
  }
  uint64_t injected() const {
    return injected_.load(std::memory_order_relaxed);
  }
  // "#<index> <point> <kind>" per injected fault, oldest first.  The
  // default renders everything the ring retains (kLogCap entries; older
  // ones fall off the ring itself).
  std::string log_text(size_t max_rows = 512) const;

  // The process-wide transport-plane actor.
  static FaultActor& global();

  // Seed-deterministic SIDE stream for backoff jitter (cluster
  // quarantine decorrelation): splitmix64(seed, jitter_index++) off a
  // counter SEPARATE from the decision counter, so drawing jitter never
  // perturbs which fault index a transport operation lands on — chaos
  // replays stay byte-identical while the jitter sequence itself replays
  // under the same seed.  Uses the installed schedule's seed (1 when no
  // schedule is active).
  uint64_t jitter_draw();

 private:
  std::shared_ptr<const FaultSchedule> snapshot() const;

  const FaultScope scope_ = FaultScope::kAny;
  mutable std::mutex mu_;
  std::shared_ptr<const FaultSchedule> schedule_;
  std::atomic<bool> active_{false};
  std::atomic<uint64_t> counter_{0};
  std::atomic<uint64_t> injected_{0};
  std::atomic<uint64_t> jitter_counter_{0};

  struct LogEntry {
    uint64_t index;
    FaultPoint point;
    FaultKind kind;
  };
  static constexpr size_t kLogCap = 512;
  mutable std::mutex log_mu_;
  std::vector<LogEntry> log_;
  size_t log_head_ = 0;  // ring cursor once log_ reaches kLogCap
};

// Returns the (cached, process-lifetime) FaultTransport decorating
// `inner`.  Idempotent: wrapping a wrapper returns it unchanged.  The
// decorator forwards name()/fd_based() so observable transport identity
// ("tcp", "shm_ring") is unchanged; when the global actor is inactive the
// overhead is one virtual hop + one atomic load.
Transport* fault_wrap(Transport* inner);

// The wrapped transport's inner instance (t itself when not a wrapper).
Transport* fault_unwrap(Transport* t);

// Registers the "fault_schedule" flag (idempotent); called from static
// init in fault.cc and from ensure_runtime_flags in the C ABI so a fresh
// process sees the flag before first use.
void fault_register_flag();

}  // namespace trpc
