#include "net/flv.h"

namespace trpc {

namespace {

constexpr size_t kMaxTag = 32u << 20;

void put24(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 16));
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v));
}

void put32(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 24));
  put24(out, v);
}

uint32_t get24(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 16) |
         (static_cast<uint32_t>(p[1]) << 8) | p[2];
}

}  // namespace

void flv_write_header(bool has_audio, bool has_video, std::string* out) {
  out->append("FLV", 3);
  out->push_back(1);  // version
  out->push_back(static_cast<char>((has_audio ? 4 : 0) |
                                   (has_video ? 1 : 0)));
  put32(out, 9);  // header size
  put32(out, 0);  // prev_tag_size of the non-existent tag before
}

bool flv_write_tag(uint8_t type, uint32_t timestamp,
                   const std::string& data, std::string* out) {
  if (data.size() > 0xffffff) {
    // RTMP admits messages of exactly 16MiB; FLV's size field cannot
    // represent them — refuse instead of writing a corrupt tag.
    return false;
  }
  out->push_back(static_cast<char>(type));
  put24(out, static_cast<uint32_t>(data.size()));
  put24(out, timestamp & 0xffffff);
  out->push_back(static_cast<char>(timestamp >> 24));  // extension
  put24(out, 0);  // stream id
  out->append(data);
  put32(out, static_cast<uint32_t>(11 + data.size()));
  return true;
}

bool flv_write_message(const RtmpMessage& msg, std::string* out) {
  if (msg.type != static_cast<uint8_t>(RtmpMsgType::kAudio) &&
      msg.type != static_cast<uint8_t>(RtmpMsgType::kVideo) &&
      msg.type != static_cast<uint8_t>(RtmpMsgType::kDataAmf0)) {
    return false;
  }
  return flv_write_tag(msg.type, msg.timestamp, msg.payload, out);
}

int flv_read_header(const std::string& in, size_t* pos, bool* has_audio,
                    bool* has_video) {
  if (in.size() - *pos < 13) {
    return 0;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(in.data()) + *pos;
  if (p[0] != 'F' || p[1] != 'L' || p[2] != 'V' || p[3] != 1) {
    return -1;
  }
  const uint32_t header_size = (static_cast<uint32_t>(p[5]) << 24) |
                               get24(p + 6);
  if (header_size < 9 || header_size > 64) {
    return -1;
  }
  if (in.size() - *pos < header_size + 4) {
    return 0;
  }
  *has_audio = (p[4] & 4) != 0;
  *has_video = (p[4] & 1) != 0;
  *pos += header_size + 4;  // header + first prev_tag_size
  return 1;
}

int flv_read_tag(const std::string& in, size_t* pos, FlvTag* out) {
  if (in.size() - *pos < 11) {
    return 0;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(in.data()) + *pos;
  const uint32_t size = get24(p + 1);
  if (size > kMaxTag) {
    return -1;
  }
  if (in.size() - *pos < 11 + size + 4) {
    return 0;
  }
  out->type = p[0];
  out->timestamp = get24(p + 4) | (static_cast<uint32_t>(p[7]) << 24);
  if (get24(p + 8) != 0) {  // stream id is always 0 in files
    return -1;
  }
  out->data.assign(in, *pos + 11, size);
  const uint8_t* back = p + 11 + size;
  const uint32_t prev = (static_cast<uint32_t>(back[0]) << 24) |
                        get24(back + 1);
  if (prev != 11 + size) {
    return -1;
  }
  *pos += 11 + size + 4;
  return 1;
}

}  // namespace trpc
