// FLV container: mux RTMP media messages into an FLV byte stream and
// demux one back.
//
// Parity: the reference's FLV reader/writer ride inside rtmp.cpp
// (RtmpFLVWriter etc.) and policy/rtmp_protocol.cpp serves /flv
// streams.  Format (public Adobe spec): 9-byte header "FLV" ver=1
// flags(audio|video) header_size=9, then repeated [prev_tag_size u32]
// [tag: type u8, data_size u24, timestamp u24 + ts_ext u8, stream_id
// u24(0), data].  Tag types match RTMP message types (8 audio, 9
// video, 18 script data), which is what makes the relay → FLV file
// path a straight re-framing.
#pragma once

#include <cstdint>
#include <string>

#include "net/rtmp.h"

namespace trpc {

struct FlvTag {
  uint8_t type = 0;  // 8 audio / 9 video / 18 script data
  uint32_t timestamp = 0;
  std::string data;
};

// Appends the 9-byte file header + the first prev_tag_size(0).
void flv_write_header(bool has_audio, bool has_video, std::string* out);

// Appends one tag + its trailing prev_tag_size.  False (no write) when
// data exceeds the format's 24-bit size field.
bool flv_write_tag(uint8_t type, uint32_t timestamp,
                   const std::string& data, std::string* out);

// Appends an RTMP message as a tag; ignores non-media types (returns
// false).  Feed this from an RtmpService media observer to record a
// live stream as FLV.
bool flv_write_message(const RtmpMessage& msg, std::string* out);

// Resumable readers: 1 ok (advances *pos) / 0 need more / -1 malformed.
int flv_read_header(const std::string& in, size_t* pos, bool* has_audio,
                    bool* has_video);
int flv_read_tag(const std::string& in, size_t* pos, FlvTag* out);

}  // namespace trpc
