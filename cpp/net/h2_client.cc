#include "net/h2_client.h"

#include <algorithm>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include <errno.h>

#include "base/logging.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/h2_frames.h"
#include "net/hpack.h"
#include "net/progressive.h"
#include "net/protocol.h"

namespace trpc {

namespace {

using namespace h2;

// One in-flight request stream (client view).
struct H2CliStream {
  uint64_t cid = 0;          // the call this stream answers
  HeaderList headers;        // response headers (+trailers, appended)
  std::string header_block;  // fragments until END_HEADERS
  IOBuf body;
  int32_t send_window = kDefaultWindow;  // peer's grant for our DATA
  // Request bytes awaiting window — an IOBuf so window-limited drains
  // cut chunks by reference instead of memmoving a string tail.
  IOBuf pending_data;
  bool pending_end = false;
  bool request_done = false;  // our END_STREAM has been sent
  bool response_end = false;  // peer's END_STREAM seen (may precede
                              // END_HEADERS when trailers span frames)
  // Progressive consumption (net/progressive.h): DATA frames go to the
  // reader as they arrive instead of accumulating in `body`.
  ProgressiveReader* reader = nullptr;
};

// Per-connection client state, hung on Socket::parse_state.
struct H2CliConn {
  bool preface_sent = false;
  HpackEncoder encoder;
  HpackDecoder decoder;
  std::mutex mu;  // issue path vs parse path (different fibers)
  std::map<uint32_t, H2CliStream> streams;
  uint32_t next_stream_id = 1;  // client streams are odd
  uint32_t continuation_stream = 0;
  int32_t conn_send_window = kDefaultWindow;
  int32_t peer_initial_window = kDefaultWindow;
  uint32_t peer_max_frame = kMaxFrameSize;
};

const char kH2CliStateTag = 0;  // parse_state owner tag

H2CliConn* conn_of(Socket* s) {
  if (s->parse_state == nullptr || s->parse_state_owner != &kH2CliStateTag) {
    s->parse_state = std::make_shared<H2CliConn>();
    s->parse_state_owner = &kH2CliStateTag;
  }
  return static_cast<H2CliConn*>(s->parse_state.get());
}

void send_frames(SocketId sid, std::string&& bytes) {
  SocketRef s(Socket::Address(sid));
  if (s) {
    IOBuf out;
    out.append(bytes);
    s->Write(std::move(out));
  }
}

void send_wire(SocketId sid, IOBuf&& wire) {
  if (wire.empty()) {
    return;
  }
  SocketRef s(Socket::Address(sid));
  if (s) {
    s->Write(std::move(wire));
  }
}

// Appends as much of the stream's pending request DATA as the windows
// allow to *wire (chunks are CUT by reference, not copied).  Call with
// conn->mu held.
void flush_pending_locked(H2CliConn* c, uint32_t stream_id, H2CliStream* st,
                          IOBuf* wire) {
  while (!st->pending_data.empty() && st->send_window > 0 &&
         c->conn_send_window > 0) {
    const uint32_t chunk = std::min<uint32_t>(
        {static_cast<uint32_t>(st->pending_data.size()),
         static_cast<uint32_t>(st->send_window),
         static_cast<uint32_t>(c->conn_send_window), c->peer_max_frame});
    const bool last = chunk == st->pending_data.size() && st->pending_end;
    wire->append(frame_header(chunk, kData, last ? kEndStream : 0,
                              stream_id));
    IOBuf part;
    st->pending_data.cutn(&part, chunk);
    wire->append(std::move(part));
    st->send_window -= static_cast<int32_t>(chunk);
    c->conn_send_window -= static_cast<int32_t>(chunk);
    if (last) {
      st->request_done = true;
    }
  }
}

// Builds the response InputMessage for a completed (END_STREAM) stream and
// erases it.  Call with conn->mu held.
void complete_stream_locked(H2CliConn* c, uint32_t stream_id,
                            H2CliStream* st, InputMessage* out) {
  out->meta.type = RpcMeta::kResponse;
  out->meta.correlation_id = st->cid;
  out->meta.stream_id = stream_id;
  out->ctx = std::make_shared<HeaderList>(std::move(st->headers));
  out->payload = std::move(st->body);
  c->streams.erase(stream_id);
}

// ---- frame parsing (server → client direction) ---------------------------

ParseError h2c_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr) {
    return ParseError::kTryOtherProtocol;  // needs connection state
  }
  if (source->empty()) {
    return ParseError::kNotEnoughData;
  }
  H2CliConn* c = conn_of(sock);
  std::unique_lock<std::mutex> g(c->mu);
  while (true) {
    uint8_t head[kFrameHeaderLen];
    if (source->copy_to(head, kFrameHeaderLen) < kFrameHeaderLen) {
      return ParseError::kNotEnoughData;
    }
    const uint32_t len = get_u24(head);
    const uint8_t type = head[3];
    const uint8_t flags = head[4];
    const uint32_t stream_id = get_u31(head + 5);
    if (len > kMaxFrameSize) {
      return ParseError::kCorrupted;
    }
    if (source->size() < kFrameHeaderLen + len) {
      return ParseError::kNotEnoughData;
    }
    source->pop_front(kFrameHeaderLen);
    std::string payload;
    payload.resize(len);
    source->copy_to(payload.data(), len);
    source->pop_front(len);
    const uint8_t* p = reinterpret_cast<const uint8_t*>(payload.data());

    if (c->continuation_stream != 0 &&
        (type != kContinuation || stream_id != c->continuation_stream)) {
      return ParseError::kCorrupted;  // CONTINUATION barrier
    }

    switch (type) {
      case kSettings: {
        if (stream_id != 0 || (len % 6 != 0 && (flags & kAck) == 0)) {
          return ParseError::kCorrupted;
        }
        if (flags & kAck) {
          break;
        }
        IOBuf wire;
        for (uint32_t off = 0; off + 6 <= len; off += 6) {
          const uint16_t id = static_cast<uint16_t>(p[off]) << 8 | p[off + 1];
          const uint32_t val = (static_cast<uint32_t>(p[off + 2]) << 24) |
                               (static_cast<uint32_t>(p[off + 3]) << 16) |
                               (static_cast<uint32_t>(p[off + 4]) << 8) |
                               p[off + 5];
          if (id == 0x1) {  // HEADER_TABLE_SIZE (the peer's decoder)
            c->encoder.set_max_size(val);
          } else if (id == 0x5) {  // MAX_FRAME_SIZE
            if (val >= 16384 && val <= 1 << 24) {
              c->peer_max_frame = std::min<uint32_t>(val, 1 << 20);
            }
          } else if (id == 0x4) {  // INITIAL_WINDOW_SIZE
            if (val > 0x7fffffffu) {
              return ParseError::kCorrupted;
            }
            const int32_t delta =
                static_cast<int32_t>(val) - c->peer_initial_window;
            c->peer_initial_window = static_cast<int32_t>(val);
            for (auto& [sid2, st] : c->streams) {
              if (delta > 0 && st.send_window > INT32_MAX - delta) {
                return ParseError::kCorrupted;  // RFC 9113 §6.9.2
              }
              st.send_window += delta;
              flush_pending_locked(c, sid2, &st, &wire);
            }
          }
        }
        wire.append(frame_header(0, kSettings, kAck, 0));
        send_wire(sock->id(), std::move(wire));
        break;
      }
      case kPing: {
        if (len != 8 || stream_id != 0) {
          return ParseError::kCorrupted;
        }
        if ((flags & kAck) == 0) {
          send_frames(sock->id(), frame_header(8, kPing, kAck, 0) + payload);
        }
        break;
      }
      case kWindowUpdate: {
        if (len != 4) {
          return ParseError::kCorrupted;
        }
        const uint32_t inc = get_u31(p);
        if (inc == 0) {
          return ParseError::kCorrupted;
        }
        IOBuf wire;
        if (stream_id == 0) {
          if (c->conn_send_window > INT32_MAX - static_cast<int32_t>(inc)) {
            return ParseError::kCorrupted;  // RFC 9113 §6.9.1 overflow
          }
          c->conn_send_window += static_cast<int32_t>(inc);
          for (auto& [sid2, st] : c->streams) {
            if (c->conn_send_window <= 0) {
              break;
            }
            flush_pending_locked(c, sid2, &st, &wire);
          }
        } else {
          auto it = c->streams.find(stream_id);
          if (it != c->streams.end()) {
            if (it->second.send_window >
                INT32_MAX - static_cast<int32_t>(inc)) {
              return ParseError::kCorrupted;
            }
            it->second.send_window += static_cast<int32_t>(inc);
            flush_pending_locked(c, stream_id, &it->second, &wire);
          }
        }
        send_wire(sock->id(), std::move(wire));
        break;
      }
      case kRstStream: {
        if (len != 4 || stream_id == 0) {
          return ParseError::kCorrupted;
        }
        auto it = c->streams.find(stream_id);
        if (it != c->streams.end()) {
          // Surface as an errored response so the call fails promptly
          // instead of waiting out its timeout.
          complete_stream_locked(c, stream_id, &it->second, out);
          out->meta.error_code = ECONNRESET;
          out->meta.error_text =
              "h2 stream reset by peer (code " +
              std::to_string(get_u31(p)) + ")";
          return ParseError::kOk;
        }
        break;
      }
      case kGoaway:
        // Streams above last_stream_id will never complete; the server
        // closes the connection when done and socket failure wakes the
        // rest.  Consume.
        break;
      case kPriority:
      case kPushPromise:
        break;  // we never enable push; priority is advisory
      case kHeaders:
      case kContinuation: {
        if (stream_id == 0) {
          return ParseError::kCorrupted;
        }
        const uint8_t* frag = p;
        uint32_t frag_len = len;
        bool end_stream = false;
        if (type == kHeaders) {
          uint32_t pad = 0;
          if (flags & kPadded) {
            if (frag_len < 1) {
              return ParseError::kCorrupted;
            }
            pad = *frag;
            ++frag;
            --frag_len;
          }
          if (flags & kPriorityFlag) {
            if (frag_len < 5) {
              return ParseError::kCorrupted;
            }
            frag += 5;
            frag_len -= 5;
          }
          if (pad > frag_len) {
            return ParseError::kCorrupted;
          }
          frag_len -= pad;
          end_stream = (flags & kEndStream) != 0;
        }
        if (type == kContinuation && c->continuation_stream != stream_id) {
          return ParseError::kCorrupted;  // RFC 7540 §6.10
        }
        auto it = c->streams.find(stream_id);
        if (it == c->streams.end()) {
          // Response on a stream we never opened (or already completed):
          // connection state is corrupt.
          return ParseError::kCorrupted;
        }
        H2CliStream& st = it->second;
        if (end_stream) {
          st.pending_end = false;  // no point sending more request bytes
          st.pending_data.clear();
          st.response_end = true;  // persists across CONTINUATIONs
        }
        st.header_block.append(reinterpret_cast<const char*>(frag),
                               frag_len);
        if (st.header_block.size() > 256 * 1024) {
          return ParseError::kCorrupted;
        }
        if ((flags & kEndHeaders) == 0) {
          c->continuation_stream = stream_id;
          break;
        }
        c->continuation_stream = 0;
        if (!c->decoder.decode(
                reinterpret_cast<const uint8_t*>(st.header_block.data()),
                st.header_block.size(), &st.headers)) {
          return ParseError::kCorrupted;
        }
        st.header_block.clear();
        if (st.response_end) {
          complete_stream_locked(c, stream_id, &st, out);
          return ParseError::kOk;
        }
        break;
      }
      case kData: {
        if (stream_id == 0) {
          return ParseError::kCorrupted;
        }
        auto it = c->streams.find(stream_id);
        const uint8_t* d = p;
        uint32_t dlen = len;
        if (flags & kPadded) {
          if (dlen < 1 || d[0] > dlen - 1) {
            return ParseError::kCorrupted;
          }
          dlen -= d[0] + 1;
          ++d;
        }
        // Replenish receive windows regardless (credit must not leak).
        if (len > 0) {
          std::string wu;
          put_u32(&wu, len);
          std::string frames = frame_header(4, kWindowUpdate, 0, 0) + wu;
          if (it != c->streams.end()) {
            std::string wu2;
            put_u32(&wu2, len);
            frames += frame_header(4, kWindowUpdate, 0, stream_id) + wu2;
          }
          send_frames(sock->id(), std::move(frames));
        }
        if (it == c->streams.end()) {
          break;  // stale stream (reset/completed): discard
        }
        H2CliStream& st = it->second;
        if (st.reader != nullptr) {
          // Progressive: hand the piece over OUTSIDE the conn lock but
          // UNDER the call's fid lock — a concurrent timeout completing
          // the call fires on_done (after which the user may destroy the
          // reader), so delivery and completion must serialize to keep
          // the "no on_part after on_done" contract.  on_part must not
          // issue sync calls on THIS connection (it runs in its read
          // fiber).
          const uint64_t cid = st.cid;
          const bool end = (flags & kEndStream) != 0;
          IOBuf piece;
          piece.append(d, dlen);
          g.unlock();
          bool cont = true;
          bool call_alive = true;
          {
            void* data = nullptr;
            if (fid_lock(cid, &data) != 0) {
              call_alive = false;  // completed (timed out): stop
            } else {
              auto* cntl = static_cast<Controller*>(data);
              ProgressiveReader* r = cntl->call().preader;
              if (r != nullptr && dlen > 0) {
                cont = r->on_part(piece);
              }
              fid_unlock(cid);
            }
          }
          g.lock();
          auto it2 = c->streams.find(stream_id);
          if (it2 == c->streams.end()) {
            break;
          }
          if (!call_alive || !cont) {  // dead call / consumer abort
            c->streams.erase(it2);
            std::string rst;
            put_u32(&rst, 0x8);  // CANCEL
            send_frames(sock->id(),
                        frame_header(4, kRstStream, 0, stream_id) + rst);
            if (!call_alive) {
              break;  // nothing left to complete
            }
            out->meta.type = RpcMeta::kResponse;
            out->meta.correlation_id = cid;
            out->meta.error_code = ECANCELED;
            out->meta.error_text = "progressive reader aborted";
            return ParseError::kOk;
          }
          if (end) {
            complete_stream_locked(c, stream_id, &it2->second, out);
            return ParseError::kOk;
          }
          break;
        }
        st.body.append(d, dlen);
        if (st.body.size() > (1ull << 30)) {
          return ParseError::kCorrupted;
        }
        if (flags & kEndStream) {
          complete_stream_locked(c, stream_id, &st, out);
          return ParseError::kOk;
        }
        break;
      }
      default:
        break;  // unknown frame types are ignored (RFC 7540 §4.1)
    }
    if (source->empty()) {
      return ParseError::kNotEnoughData;
    }
  }
}

// ---- response processing -------------------------------------------------

void h2c_process_response(InputMessage&& msg) {
  const fid_t cid = msg.meta.correlation_id;
  void* data = nullptr;
  if (fid_lock(cid, &data) != 0) {
    return;  // stale (timed out): harmless
  }
  Controller* cntl = static_cast<Controller*>(data);
  if (msg.meta.error_code != 0) {  // RST_STREAM path
    cntl->SetFailed(msg.meta.error_code, msg.meta.error_text);
    complete_locked_call(cid, cntl);
    return;
  }
  auto headers = std::static_pointer_cast<HeaderList>(msg.ctx);
  const std::string* status = find_header(*headers, ":status");
  const std::string* grpc_status = find_header(*headers, "grpc-status");
  const std::string* ct = find_header(*headers, "content-type");
  const bool grpc =
      grpc_status != nullptr ||
      (ct != nullptr && ct->rfind("application/grpc", 0) == 0);
  if (grpc) {
    const int gs = grpc_status != nullptr ? atoi(grpc_status->c_str()) : 2;
    if (gs != 0) {
      const std::string* gm = find_header(*headers, "grpc-message");
      cntl->SetFailed(EREMOTE, gm != nullptr
                                   ? *gm
                                   : "grpc-status " + std::to_string(gs));
      complete_locked_call(cid, cntl);
      return;
    }
    IOBuf unframed;
    if (msg.payload.size() > 0 && !grpc_unframe(msg.payload, &unframed)) {
      cntl->SetFailed(EBADMSG, "bad grpc response framing");
      complete_locked_call(cid, cntl);
      return;
    }
    if (cntl->call().response != nullptr) {
      *cntl->call().response = std::move(unframed);
    }
    complete_locked_call(cid, cntl);
    return;
  }
  if (status == nullptr || *status != "200") {
    cntl->SetFailed(EREMOTE,
                    "http status " + (status != nullptr ? *status : "?") +
                        ": " + msg.payload.to_string().substr(0, 200));
    complete_locked_call(cid, cntl);
    return;
  }
  if (cntl->call().response != nullptr) {
    *cntl->call().response = std::move(msg.payload);
  }
  complete_locked_call(cid, cntl);
}

void h2c_process_request(InputMessage&&) {
  // Client side only: servers never arrive here (sockets are pre-pinned).
}

}  // namespace

int h2_client_protocol_index() {
  static const int index = [] {
    Protocol p = {"h2c", h2c_parse, h2c_process_request,
                  h2c_process_response,
                  /*process_in_order=*/false};
    return register_protocol(p);
  }();
  return index;
}

void h2_client_bind(SocketId sid) {
  SocketRef s(Socket::Address(sid));
  if (s) {
    s->pinned_protocol = h2_client_protocol_index();
    conn_of(s.get());  // install state while single-threaded
  }
}

int h2_client_issue(SocketId sid, uint64_t cid, const std::string& method,
                    const IOBuf& request, bool grpc,
                    const std::string& authority,
                    const std::string& auth_header,
                    uint32_t* stream_id_out, ProgressiveReader* reader) {
  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  H2CliConn* c = conn_of(s.get());
  std::lock_guard<std::mutex> g(c->mu);
  IOBuf wire;
  if (!c->preface_sent) {
    c->preface_sent = true;
    std::string pre(kPreface, kPrefaceLen);
    std::string settings;
    settings.append("\x00\x05", 2);  // MAX_FRAME_SIZE
    put_u32(&settings, kMaxFrameSize);
    settings.append("\x00\x04", 2);  // INITIAL_WINDOW_SIZE
    put_u32(&settings, kRecvWindow);
    pre += frame_header(static_cast<uint32_t>(settings.size()), kSettings,
                        0, 0) +
           settings;
    std::string wu;  // grow the connection-level receive window too
    put_u32(&wu, kRecvWindow - kDefaultWindow);
    pre += frame_header(4, kWindowUpdate, 0, 0) + wu;
    wire.append(pre);
  }
  const uint32_t stream_id = c->next_stream_id;
  c->next_stream_id += 2;
  H2CliStream& st = c->streams[stream_id];
  st.cid = cid;
  st.send_window = c->peer_initial_window;
  st.reader = reader;
  if (stream_id_out != nullptr) {
    *stream_id_out = stream_id;
  }

  std::string path = "/" + method;
  if (grpc) {
    // gRPC paths are /package.Service/Method: the LAST dot splits the
    // service from the method ("pkg.Svc.Method" → "/pkg.Svc/Method").
    const size_t dot = path.rfind('.');
    if (dot != std::string::npos) {
      path[dot] = '/';
    }
  }
  HeaderList req_headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", path},
      {":authority", authority},
  };
  if (grpc) {
    req_headers.push_back({"content-type", "application/grpc"});
    req_headers.push_back({"te", "trailers"});
  }
  if (!auth_header.empty()) {
    req_headers.push_back({"authorization", auth_header});
  }
  std::string block;
  c->encoder.encode(req_headers, &block);

  IOBuf body = request;  // zero-copy share
  if (grpc) {
    std::string prefix;
    prefix.push_back(0);  // uncompressed
    put_u32(&prefix, static_cast<uint32_t>(body.size()));
    IOBuf framed;
    framed.append(prefix);
    framed.append(std::move(body));
    body = std::move(framed);
  }
  if (body.empty()) {
    wire.append(frame_header(static_cast<uint32_t>(block.size()), kHeaders,
                             kEndHeaders | kEndStream, stream_id) +
                block);
    st.request_done = true;
  } else {
    wire.append(frame_header(static_cast<uint32_t>(block.size()), kHeaders,
                             kEndHeaders, stream_id) +
                block);
    st.pending_data = std::move(body);
    st.pending_end = true;
    flush_pending_locked(c, stream_id, &st, &wire);
  }
  return s->Write(std::move(wire)) == 0 ? 0 : -1;
}

void h2_client_cancel(SocketId sid, uint32_t stream_id) {
  SocketRef s(Socket::Address(sid));
  if (!s || s->parse_state_owner != &kH2CliStateTag) {
    return;
  }
  auto* c = static_cast<H2CliConn*>(s->parse_state.get());
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) {
    return;  // already completed normally
  }
  c->streams.erase(it);
  std::string rst;
  put_u32(&rst, 0x8);  // CANCEL
  send_frames(sid, frame_header(4, kRstStream, 0, stream_id) + rst);
}

}  // namespace trpc
