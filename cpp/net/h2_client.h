// HTTP/2 + gRPC client side.
//
// Parity: the reference's PackH2Request / H2UnsentRequest machinery
// (/root/reference/src/brpc/policy/http2_rpc_protocol.cpp:1793): client
// connection preface, stream-id allocation, HPACK-encoded request headers,
// flow-control-aware DATA, and trailer (grpc-status) handling.  Channel
// routes calls here when Options::protocol is "h2" or "grpc"; responses
// come back through the protocol registry like tstd's, correlated by a
// per-connection stream-id → call-id map instead of a wire correlation id.
#pragma once

#include <string>

#include "base/iobuf.h"
#include "net/socket.h"

namespace trpc {

class ProgressiveReader;  // net/progressive.h

// Registers the client-side h2 protocol (idempotent) and returns its
// registry index — client sockets are PRE-pinned to it: the client knows
// what it speaks, and the server's first bytes (a SETTINGS frame) carry
// no distinctive magic for probing.
int h2_client_protocol_index();

// Binds a fresh client socket to the h2 client protocol: pins the
// protocol index and installs the per-connection state.  Must run once
// BEFORE concurrent h2_client_issue calls can race (Channel does it under
// its socket mutex right after creating the connection).
void h2_client_bind(SocketId sid);

// Issues one request on an h2 client connection: writes the connection
// preface + SETTINGS on first use, allocates the next odd stream id,
// HPACK-encodes the request headers and sends DATA as the peer's flow
// windows allow (the remainder is queued and drains on WINDOW_UPDATE).
// `grpc` selects gRPC path form (/pkg.Svc/Method), content-type and
// message framing; `auth_header` rides as "authorization" when non-empty.
// `*stream_id_out` receives the allocated stream id (for cancel on call
// failure).  Returns 0 when the frames were queued to the socket.
int h2_client_issue(SocketId sid, uint64_t cid, const std::string& method,
                    const IOBuf& request, bool grpc,
                    const std::string& authority,
                    const std::string& auth_header,
                    uint32_t* stream_id_out = nullptr,
                    ProgressiveReader* reader = nullptr);

// Drops a stream whose call completed without a response (timeout /
// local failure): erases the client-side state — otherwise dead streams
// and their queued request bytes accumulate for the connection's
// lifetime — and tells the server via RST_STREAM(CANCEL).
void h2_client_cancel(SocketId sid, uint32_t stream_id);

}  // namespace trpc
