// HTTP/2 wire primitives shared by the server (h2_protocol.cc) and client
// (h2_client.cc) halves: frame-header build/read helpers, the RFC 7540
// frame-type/flag constants, and the gRPC length-prefixed message framing
// (the values are RFC constants; the connection state machines on either
// side are separate by design — server parses requests, client parses
// responses).
#pragma once

#include <cstdint>
#include <string>

#include "base/iobuf.h"
#include "net/hpack.h"

namespace trpc {
namespace h2 {

constexpr char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kPrefaceLen = 24;
constexpr uint32_t kFrameHeaderLen = 9;
constexpr uint32_t kMaxFrameSize = 16384;  // our advertised max
constexpr uint32_t kDefaultWindow = 65535;
constexpr uint32_t kRecvWindow = 1 << 20;  // what we grant peers
constexpr uint32_t kRefusedStream = 0x7;   // RST_STREAM error code

enum FrameType : uint8_t {
  kData = 0x0,
  kHeaders = 0x1,
  kPriority = 0x2,
  kRstStream = 0x3,
  kSettings = 0x4,
  kPushPromise = 0x5,
  kPing = 0x6,
  kGoaway = 0x7,
  kWindowUpdate = 0x8,
  kContinuation = 0x9,
};

enum Flags : uint8_t {
  kEndStream = 0x1,
  kEndHeaders = 0x4,
  kPadded = 0x8,
  kPriorityFlag = 0x20,
  kAck = 0x1,
};

inline void put_u24(std::string* s, uint32_t v) {
  s->push_back(static_cast<char>(v >> 16));
  s->push_back(static_cast<char>(v >> 8));
  s->push_back(static_cast<char>(v));
}

inline void put_u32(std::string* s, uint32_t v) {
  s->push_back(static_cast<char>(v >> 24));
  s->push_back(static_cast<char>(v >> 16));
  s->push_back(static_cast<char>(v >> 8));
  s->push_back(static_cast<char>(v));
}

inline uint32_t get_u24(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 16) |
         (static_cast<uint32_t>(p[1]) << 8) | p[2];
}

inline uint32_t get_u31(const uint8_t* p) {
  return ((static_cast<uint32_t>(p[0]) & 0x7f) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | p[3];
}

inline std::string frame_header(uint32_t len, uint8_t type, uint8_t flags,
                                uint32_t stream_id) {
  std::string h;
  put_u24(&h, len);
  h.push_back(static_cast<char>(type));
  h.push_back(static_cast<char>(flags));
  put_u32(&h, stream_id);
  return h;
}

// gRPC length-prefixed message framing (details/grpc.* parity).
inline std::string grpc_frame(const std::string& msg) {
  std::string out;
  out.push_back(0);  // uncompressed
  put_u32(&out, static_cast<uint32_t>(msg.size()));
  out += msg;
  return out;
}

inline bool grpc_unframe(const IOBuf& body, IOBuf* msg) {
  if (body.size() < 5) {
    return false;
  }
  uint8_t head[5];
  body.copy_to(head, 5);
  if (head[0] != 0) {
    return false;  // compressed grpc messages unsupported (negotiated off)
  }
  const uint32_t len = (static_cast<uint32_t>(head[1]) << 24) |
                       (static_cast<uint32_t>(head[2]) << 16) |
                       (static_cast<uint32_t>(head[3]) << 8) | head[4];
  if (body.size() < 5ull + len) {
    return false;
  }
  IOBuf tmp = body;
  tmp.pop_front(5);
  tmp.cutn(msg, len);
  return true;
}

inline const std::string* find_header(const HeaderList& h,
                                      const char* name) {
  for (const auto& [k, v] : h) {
    if (k == name) {
      return &v;
    }
  }
  return nullptr;
}

}  // namespace h2
}  // namespace trpc
