#include "net/h2_protocol.h"

#include <algorithm>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/sync.h"
#include "net/h2_frames.h"
#include "net/hpack.h"
#include "net/http_protocol.h"
#include "net/server.h"
#include "net/socket.h"

namespace trpc {

namespace {

using namespace h2;  // frame constants/helpers shared with h2_client.cc

constexpr uint32_t kMaxConcurrentStreams = 256;  // advertised in SETTINGS

// One in-progress request stream.
struct H2Stream {
  HeaderList headers;
  std::string header_block;  // fragments until END_HEADERS
  IOBuf body;
  bool headers_done = false;
  int32_t send_window = kDefaultWindow;  // peer's grant for our DATA
  // Response bytes still waiting for window (flow-controlled remainder,
  // an IOBuf so drains cut chunks by reference instead of memmoving a
  // string tail), and — for gRPC — the trailer HEADERS that may only
  // follow the LAST DATA frame (status rides the trailers, so ordering
  // is correctness).
  IOBuf pending_data;
  bool pending_end = false;
  // gRPC trailers waiting behind the data: kept as HEADERS (not a
  // pre-encoded block) and HPACK-encoded at TRANSMISSION time — the
  // stateful encoder's table mutations must hit the wire in encode
  // order, and a deferred pre-encoded block would let a later stream's
  // headers overtake its inserts.
  HeaderList trailer_headers;
  bool has_trailers = false;
};

// Per-connection h2 state, hung on Socket::parse_state.
struct H2Conn {
  bool preface_done = false;
  HpackDecoder decoder;
  HpackEncoder encoder;
  std::mutex mu;  // response path vs parse path (different fibers)
  std::map<uint32_t, H2Stream> streams;
  uint32_t continuation_stream = 0;  // nonzero while CONTINUATIONs expected
  // Stream being refused over MAX_CONCURRENT_STREAMS: its header block is
  // still HPACK-decoded (the dynamic table is connection state — skipping
  // a block would desync every later stream) but into a throwaway list,
  // then RST_STREAM(REFUSED_STREAM) instead of tearing the connection down.
  uint32_t refusing_stream = 0;
  H2Stream refused_scratch;  // header-block accumulator for refused streams
  // Highest client stream id ever opened or refused: frames on an unknown
  // id at or below this belong to a closed/refused stream, not a new one.
  uint32_t max_stream_id = 0;
  int32_t conn_send_window = kDefaultWindow;
  // Peer's SETTINGS_INITIAL_WINDOW_SIZE: seeds NEW streams; a repeated
  // SETTINGS adjusts open streams by the delta from the PREVIOUS value.
  int32_t peer_initial_window = kDefaultWindow;
  uint32_t peer_max_frame = kMaxFrameSize;
};

const char kH2StateTag = 0;  // address used as the parse_state owner tag

H2Conn* conn_of(Socket* s) {
  if (s->parse_state == nullptr || s->parse_state_owner != &kH2StateTag) {
    s->parse_state = std::make_shared<H2Conn>();
    s->parse_state_owner = &kH2StateTag;
  }
  return static_cast<H2Conn*>(s->parse_state.get());
}

void send_frames(SocketId sid, std::string&& bytes) {
  SocketRef s(Socket::Address(sid));
  if (s) {
    IOBuf out;
    out.append(bytes);
    s->Write(std::move(out));
  }
}

// Writes as much of the stream's pending response DATA as the windows
// allow (chunks are CUT by reference, not copied).  Call with conn->mu
// held.
void flush_pending_locked(H2Conn* c, SocketId sid, uint32_t stream_id,
                          H2Stream* st) {
  IOBuf out;
  while (!st->pending_data.empty() && st->send_window > 0 &&
         c->conn_send_window > 0) {
    const uint32_t chunk = std::min<uint32_t>(
        {static_cast<uint32_t>(st->pending_data.size()),
         static_cast<uint32_t>(st->send_window),
         static_cast<uint32_t>(c->conn_send_window), c->peer_max_frame});
    const bool last = chunk == st->pending_data.size() && st->pending_end;
    out.append(frame_header(chunk, kData, last ? kEndStream : 0,
                            stream_id));
    IOBuf part;
    st->pending_data.cutn(&part, chunk);
    out.append(std::move(part));
    st->send_window -= static_cast<int32_t>(chunk);
    c->conn_send_window -= static_cast<int32_t>(chunk);
  }
  const bool done = st->pending_data.empty();
  if (done && st->has_trailers) {
    // Encode NOW, inside the same critical section as the write: wire
    // order must equal encoder-table mutation order.
    std::string tblock;
    c->encoder.encode(st->trailer_headers, &tblock);
    out.append(frame_header(static_cast<uint32_t>(tblock.size()), kHeaders,
                            kEndHeaders | kEndStream, stream_id) +
               tblock);
    st->has_trailers = false;
  }
  if (!out.empty()) {
    SocketRef s(Socket::Address(sid));
    if (s) {
      s->Write(std::move(out));
    }
  }
  if (done) {
    c->streams.erase(stream_id);
  }
}

// Response writer: HEADERS (+DATA, window-limited) (+gRPC trailers).
void h2_respond(SocketId sid, uint32_t stream_id, int status,
                const std::string& content_type, const std::string& body,
                bool grpc, int grpc_status, const std::string& grpc_msg) {
  SocketRef sref(Socket::Address(sid));
  if (!sref) {
    return;
  }
  H2Conn* c = conn_of(sref.get());
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) {
    return;  // reset by the peer meanwhile
  }
  H2Stream* st = &it->second;

  HeaderList resp_headers = {
      {":status", std::to_string(status)},
      {"content-type", content_type},
  };
  std::string block;
  c->encoder.encode(resp_headers, &block);
  std::string out =
      frame_header(static_cast<uint32_t>(block.size()), kHeaders,
                   kEndHeaders, stream_id) +
      block;

  std::string payload = grpc ? grpc_frame(body) : body;
  if (grpc) {
    // Trailers carry the status and may only follow the LAST DATA frame:
    // queue them behind the (window-limited) data so a big response
    // cannot see END_STREAM before its bytes.
    st->pending_data.clear();
    st->pending_data.append(payload);
    st->pending_end = false;
    st->trailer_headers = {
        {"grpc-status", std::to_string(grpc_status)},
    };
    if (!grpc_msg.empty()) {
      st->trailer_headers.push_back({"grpc-message", grpc_msg});
    }
    st->has_trailers = true;
    send_frames(sid, std::move(out));
    flush_pending_locked(c, sid, stream_id, st);
    return;
  }
  st->pending_data.clear();
  st->pending_data.append(payload);
  st->pending_end = true;
  if (st->pending_data.empty()) {
    // Header-only response: END_STREAM rides the HEADERS frame.
    out = frame_header(static_cast<uint32_t>(block.size()), kHeaders,
                       kEndHeaders | kEndStream, stream_id) +
          block;
    send_frames(sid, std::move(out));
    c->streams.erase(stream_id);
    return;
  }
  send_frames(sid, std::move(out));
  flush_pending_locked(c, sid, stream_id, st);
}

// ---- frame parsing -------------------------------------------------------

bool looks_like_h2(const IOBuf& buf) {
  char start[kPrefaceLen] = {};
  const size_t n = buf.copy_to(start, sizeof(start));
  return memcmp(start, kPreface, std::min(n, kPrefaceLen)) == 0;
}

ParseError h2_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr) {
    return ParseError::kTryOtherProtocol;  // h2 needs connection state
  }
  if (source->empty()) {
    return ParseError::kNotEnoughData;
  }
  // During probing (not yet pinned), this connection is ours iff we
  // already claimed it on an earlier round (preface consumed, state
  // tagged) or the preface is on the wire now.
  const bool claimed = sock->parse_state_owner == &kH2StateTag;
  if (sock->pinned_protocol < 0 && !claimed) {
    if (!looks_like_h2(*source)) {
      return ParseError::kTryOtherProtocol;
    }
    if (source->size() < kPrefaceLen) {
      return ParseError::kNotEnoughData;
    }
  }
  H2Conn* c = conn_of(sock);
  std::lock_guard<std::mutex> g(c->mu);
  if (!c->preface_done) {
    source->pop_front(kPrefaceLen);
    c->preface_done = true;
    // Our SETTINGS: max frame size + a big connection receive window.
    std::string settings;
    std::string payload;
    payload.append("\x00\x05", 2);  // MAX_FRAME_SIZE
    put_u32(&payload, kMaxFrameSize);
    payload.append("\x00\x04", 2);  // INITIAL_WINDOW_SIZE
    put_u32(&payload, kRecvWindow);
    payload.append("\x00\x03", 2);  // MAX_CONCURRENT_STREAMS
    put_u32(&payload, kMaxConcurrentStreams);
    settings += frame_header(static_cast<uint32_t>(payload.size()),
                             kSettings, 0, 0) +
                payload;
    // Grow the connection-level receive window too.
    std::string wu;
    put_u32(&wu, kRecvWindow - kDefaultWindow);
    settings += frame_header(4, kWindowUpdate, 0, 0) + wu;
    send_frames(sock->id(), std::move(settings));
  }

  while (true) {
    uint8_t head[kFrameHeaderLen];
    if (source->copy_to(head, kFrameHeaderLen) < kFrameHeaderLen) {
      return ParseError::kNotEnoughData;
    }
    const uint32_t len = get_u24(head);
    const uint8_t type = head[3];
    const uint8_t flags = head[4];
    const uint32_t stream_id = get_u31(head + 5);
    if (len > kMaxFrameSize) {
      return ParseError::kCorrupted;  // exceeds our advertised limit
    }
    if (source->size() < kFrameHeaderLen + len) {
      return ParseError::kNotEnoughData;
    }
    source->pop_front(kFrameHeaderLen);
    std::string payload;
    payload.resize(len);
    source->copy_to(payload.data(), len);
    source->pop_front(len);
    const uint8_t* p = reinterpret_cast<const uint8_t*>(payload.data());

    // A CONTINUATION barrier: nothing else may interleave.
    if (c->continuation_stream != 0 &&
        (type != kContinuation || stream_id != c->continuation_stream)) {
      return ParseError::kCorrupted;
    }

    switch (type) {
      case kSettings: {
        if (stream_id != 0 || (len % 6 != 0 && (flags & kAck) == 0)) {
          return ParseError::kCorrupted;
        }
        if (flags & kAck) {
          break;
        }
        for (uint32_t off = 0; off + 6 <= len; off += 6) {
          const uint16_t id = static_cast<uint16_t>(p[off]) << 8 | p[off + 1];
          const uint32_t val = (static_cast<uint32_t>(p[off + 2]) << 24) |
                               (static_cast<uint32_t>(p[off + 3]) << 16) |
                               (static_cast<uint32_t>(p[off + 4]) << 8) |
                               p[off + 5];
          if (id == 0x1) {  // HEADER_TABLE_SIZE (the peer's decoder)
            c->encoder.set_max_size(val);
          } else if (id == 0x5) {  // MAX_FRAME_SIZE
            if (val >= 16384 && val <= 1 << 24) {
              c->peer_max_frame = std::min<uint32_t>(val, 1 << 20);
            }
          } else if (id == 0x4) {  // INITIAL_WINDOW_SIZE
            if (val > 0x7fffffffu) {
              return ParseError::kCorrupted;
            }
            const int32_t delta =
                static_cast<int32_t>(val) - c->peer_initial_window;
            c->peer_initial_window = static_cast<int32_t>(val);
            std::vector<uint32_t> stalled;
            for (auto& [sid2, st] : c->streams) {
              if (delta > 0 && st.send_window > INT32_MAX - delta) {
                return ParseError::kCorrupted;  // RFC 9113 §6.9.2 overflow
              }
              st.send_window += delta;
              if (delta > 0 && !st.pending_data.empty()) {
                stalled.push_back(sid2);
              }
            }
            // A raised initial window must RESUME stalled responses
            // (RFC 9113 §6.9.2): no per-stream WINDOW_UPDATE is coming
            // for a window that never emptied from the peer's view.
            // flush erases completed streams — iterate collected ids.
            for (uint32_t sid2 : stalled) {
              auto it2 = c->streams.find(sid2);
              if (it2 != c->streams.end()) {
                flush_pending_locked(c, sock->id(), sid2, &it2->second);
              }
            }
          }
        }
        send_frames(sock->id(), frame_header(0, kSettings, kAck, 0));
        break;
      }
      case kPing: {
        if (len != 8 || stream_id != 0) {
          return ParseError::kCorrupted;
        }
        if ((flags & kAck) == 0) {
          send_frames(sock->id(),
                      frame_header(8, kPing, kAck, 0) + payload);
        }
        break;
      }
      case kWindowUpdate: {
        if (len != 4) {
          return ParseError::kCorrupted;
        }
        const uint32_t inc = get_u31(p);
        if (inc == 0) {
          return ParseError::kCorrupted;
        }
        if (stream_id == 0) {
          if (c->conn_send_window > INT32_MAX - static_cast<int32_t>(inc)) {
            return ParseError::kCorrupted;  // RFC 9113 §6.9.1 overflow
          }
          c->conn_send_window += static_cast<int32_t>(inc);
          // A bigger connection window can unblock streams stalled on it
          // ALONE (their per-stream window never emptied, so no per-stream
          // WINDOW_UPDATE is coming to resume them).  flush erases
          // completed streams, so collect ids before touching the map.
          std::vector<uint32_t> stalled;
          for (auto& [sid2, st2] : c->streams) {
            if (!st2.pending_data.empty()) {
              stalled.push_back(sid2);
            }
          }
          for (uint32_t sid2 : stalled) {
            if (c->conn_send_window <= 0) {
              break;
            }
            auto it2 = c->streams.find(sid2);
            if (it2 != c->streams.end()) {
              flush_pending_locked(c, sock->id(), sid2, &it2->second);
            }
          }
        } else {
          auto it = c->streams.find(stream_id);
          if (it != c->streams.end()) {
            if (it->second.send_window >
                INT32_MAX - static_cast<int32_t>(inc)) {
              return ParseError::kCorrupted;  // per-stream window overflow
            }
            it->second.send_window += static_cast<int32_t>(inc);
            flush_pending_locked(c, sock->id(), stream_id, &it->second);
          }
        }
        break;
      }
      case kRstStream: {
        if (len != 4 || stream_id == 0) {
          return ParseError::kCorrupted;
        }
        c->streams.erase(stream_id);
        break;
      }
      case kGoaway:
        // Graceful shutdown: in-flight streams finish; the peer closes
        // the connection when done (EOF path), so just consume it.
        break;
      case kPriority:
      case kPushPromise:
        break;  // ignored (we never accept pushes; priority is advisory)
      case kHeaders:
      case kContinuation: {
        if (stream_id == 0) {
          return ParseError::kCorrupted;
        }
        const uint8_t* frag = p;
        uint32_t frag_len = len;
        if (type == kHeaders) {
          uint32_t pad = 0;
          if (flags & kPadded) {
            if (frag_len < 1) {
              return ParseError::kCorrupted;
            }
            pad = *frag;
            ++frag;
            --frag_len;
          }
          if (flags & kPriorityFlag) {
            if (frag_len < 5) {
              return ParseError::kCorrupted;
            }
            frag += 5;
            frag_len -= 5;
          }
          if (pad > frag_len) {
            return ParseError::kCorrupted;
          }
          frag_len -= pad;
        }
        // CONTINUATION is only legal while a header block is open on this
        // stream (RFC 7540 §6.10); a bare one must not create stream state.
        if (type == kContinuation && c->continuation_stream != stream_id) {
          return ParseError::kCorrupted;
        }
        const bool known = c->streams.count(stream_id) != 0;
        // A stream over the advertised MAX_CONCURRENT_STREAMS — or on a
        // stale id (closed/refused earlier) — is refused with
        // RST_STREAM/REFUSED_STREAM instead of tearing down the whole
        // connection.  Its header block still passes through the shared
        // machinery below (accumulate, cap, HPACK-decode) because the
        // HPACK dynamic table is connection state: skipping a block would
        // desync every later stream.  Only the destination differs: a
        // scratch stream whose decoded headers are discarded.
        const bool refused =
            !known && (c->refusing_stream == stream_id ||
                       stream_id <= c->max_stream_id ||
                       c->streams.size() >= kMaxConcurrentStreams);
        H2Stream* st;
        if (refused) {
          c->refusing_stream = stream_id;
          c->max_stream_id = std::max(c->max_stream_id, stream_id);
          st = &c->refused_scratch;
        } else {
          if (!known) {
            c->streams[stream_id].send_window = c->peer_initial_window;
            c->max_stream_id = std::max(c->max_stream_id, stream_id);
          }
          st = &c->streams[stream_id];
          if (type == kHeaders && (flags & kEndStream)) {
            st->headers_done = true;  // no (more) body coming
          }
        }
        st->header_block.append(reinterpret_cast<const char*>(frag),
                                frag_len);
        if (st->header_block.size() > 256 * 1024) {
          return ParseError::kCorrupted;
        }
        if ((flags & kEndHeaders) == 0) {
          c->continuation_stream = stream_id;
          break;
        }
        c->continuation_stream = 0;
        if (!c->decoder.decode(
                reinterpret_cast<const uint8_t*>(st->header_block.data()),
                st->header_block.size(), &st->headers)) {
          return ParseError::kCorrupted;
        }
        st->header_block.clear();
        if (refused) {
          st->headers.clear();
          c->refusing_stream = 0;
          std::string rst;
          put_u32(&rst, kRefusedStream);
          send_frames(sock->id(),
                      frame_header(4, kRstStream, 0, stream_id) + rst);
          break;
        }
        if (st->headers_done) {  // END_STREAM rode the HEADERS
          out->meta.type = RpcMeta::kRequest;
          out->meta.stream_id = stream_id;
          // Trailing HEADERS after DATA (legal HTTP/2): the decoder
          // appended the trailer fields to st->headers, and the body
          // accumulated so far must ride along.
          out->ctx = std::make_shared<HeaderList>(std::move(st->headers));
          out->payload = std::move(st->body);
          st->headers.clear();
          st->body.clear();
          return ParseError::kOk;
        }
        break;
      }
      case kData: {
        if (stream_id == 0) {
          return ParseError::kCorrupted;
        }
        auto it = c->streams.find(stream_id);
        if (it == c->streams.end()) {
          // Reset stream: discard the bytes but still replenish the
          // CONNECTION window, or the credit leaks away forever.
          if (len > 0) {
            std::string wu;
            put_u32(&wu, len);
            send_frames(sock->id(),
                        frame_header(4, kWindowUpdate, 0, 0) + wu);
          }
          break;
        }
        H2Stream& st = it->second;
        const uint8_t* d = p;
        uint32_t dlen = len;
        if (flags & kPadded) {
          if (dlen < 1 || d[0] > dlen - 1) {
            return ParseError::kCorrupted;
          }
          dlen -= d[0] + 1;
          ++d;
        }
        st.body.append(d, dlen);
        if (st.body.size() > (1ull << 30)) {
          return ParseError::kCorrupted;
        }
        // Replenish receive windows as we consume (credit flow control).
        if (len > 0) {
          std::string wu;
          put_u32(&wu, len);
          std::string frames = frame_header(4, kWindowUpdate, 0, 0) + wu;
          std::string wu2;
          put_u32(&wu2, len);
          frames +=
              frame_header(4, kWindowUpdate, 0, stream_id) + wu2;
          send_frames(sock->id(), std::move(frames));
        }
        if (flags & kEndStream) {
          out->meta.type = RpcMeta::kRequest;
          out->meta.stream_id = stream_id;
          out->ctx = std::make_shared<HeaderList>(std::move(st.headers));
          out->payload = std::move(st.body);
          st.headers.clear();
          st.body.clear();
          return ParseError::kOk;
        }
        break;
      }
      default:
        break;  // unknown frame types are ignored (RFC 7540 §4.1)
    }
    if (source->empty()) {
      return ParseError::kNotEnoughData;
    }
  }
}

// ---- request processing --------------------------------------------------

void h2_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto headers = std::static_pointer_cast<HeaderList>(msg.ctx);
  const uint32_t stream_id = static_cast<uint32_t>(msg.meta.stream_id);
  const std::string* path = find_header(*headers, ":path");
  const std::string* ct = find_header(*headers, "content-type");
  if (srv != nullptr && srv->authenticator() != nullptr &&
      !sock->auth_ok.load(std::memory_order_acquire)) {
    // h2 clients carry no kAuth frame; the credential rides the
    // "authorization" header instead (our h2 client sends it on every
    // request until the connection is marked).
    const std::string* cred = find_header(*headers, "authorization");
    if (cred != nullptr &&
        srv->authenticator()->verify_credential(*cred, sock->remote()) ==
            0) {
      sock->auth_ok.store(true, std::memory_order_release);
    }
  }
  if (srv != nullptr && srv->authenticator() != nullptr &&
      !sock->auth_ok.load(std::memory_order_acquire) &&
      (path == nullptr || *path != "/health")) {
    // Same-port auth gate as HTTP/1.
    h2_respond(msg.socket, static_cast<uint32_t>(msg.meta.stream_id), 403,
               "text/plain", "connection not authenticated\n", false, 16,
               "unauthenticated");
    return;
  }
  const bool grpc = ct != nullptr && ct->rfind("application/grpc", 0) == 0;
  const std::string resp_ct =
      grpc ? (ct != nullptr ? *ct : "application/grpc") : "text/plain";
  if (path == nullptr || srv == nullptr) {
    h2_respond(msg.socket, stream_id, 400, "text/plain", "bad request\n",
               grpc, 13, "missing :path");
    return;
  }
  // Strip any query for dispatch; reuse the HTTP/1 query machinery.
  HttpRequest req;
  const size_t q = path->find('?');
  req.path = q == std::string::npos ? *path : path->substr(0, q);
  if (q != std::string::npos) {
    req.query_string = path->substr(q + 1);
    parse_query_string(req.query_string, &req.queries);
  }
  const std::string* verb = find_header(*headers, ":method");
  req.verb = verb != nullptr ? *verb : "GET";

  // Interceptor gate — covers builtins too; /health stays open.
  {
    int ec = 0;
    std::string et;
    if (req.path != "/health" &&
        !srv->accept_request(req.path, sock->remote(), &ec, &et)) {
      // gRPC's status space is its own: PERMISSION_DENIED with the
      // caller's code folded into the message; plain h2 gets 403.
      h2_respond(msg.socket, stream_id, grpc ? 200 : 403, resp_ct,
                 grpc ? "" : "error " + std::to_string(ec) + ": " + et +
                                "\n",
                 grpc, 7, "error " + std::to_string(ec) + ": " + et);
      return;
    }
  }

  // 1. Builtin endpoints (same table as HTTP/1).
  std::string body;
  std::string ctype = "text/plain";
  int status = 200;
  if (!grpc && builtin_http_dispatch(srv, req, msg.payload, &status, &body, &ctype)) {
    h2_respond(msg.socket, stream_id, status, ctype, body, false, 0, "");
    return;
  }
  // 2. Restful, then /Service.Method (gRPC uses /Service/Method).
  std::string rpc_name;
  const Server::MethodProperty* prop = srv->find_restful(req.path, &rpc_name);
  if (prop == nullptr) {
    rpc_name = req.path.empty() ? "" : req.path.substr(1);
    if (grpc) {
      const size_t slash = rpc_name.find('/');
      if (slash != std::string::npos) {
        rpc_name[slash] = '.';  // grpc path form → method registry form
      }
    }
    prop = srv->find_method(rpc_name);
  }
  if (prop == nullptr) {
    h2_respond(msg.socket, stream_id, grpc ? 200 : 404, resp_ct, "", grpc,
               12, "unimplemented: " + rpc_name);
    return;
  }
  std::shared_ptr<ConcurrencyLimiter> limiter = prop->limiter;
  if (limiter != nullptr && !limiter->on_request()) {
    h2_respond(msg.socket, stream_id, grpc ? 200 : 503, resp_ct, "", grpc,
               8, "resource exhausted");
    return;
  }

  IOBuf request;
  if (grpc) {
    if (msg.payload.size() > 0 && !grpc_unframe(msg.payload, &request)) {
      if (limiter != nullptr) {
        limiter->on_response(0, true);
      }
      h2_respond(msg.socket, stream_id, 200, resp_ct, "", true, 13,
                 "bad grpc framing");
      return;
    }
  } else {
    request = std::move(msg.payload);
  }

  auto* cntl = new Controller();
  cntl->set_method(rpc_name);
  cntl->call().sl_pool = srv->session_data_pool();
  auto* response = new IOBuf();
  const SocketId sid = msg.socket;
  const int64_t start_us = monotonic_time_us();
  std::shared_ptr<LatencyRecorder> lat = prop->latency;
  srv->in_flight.fetch_add(1, std::memory_order_acq_rel);
  auto latch = std::make_shared<CountdownEvent>(1);
  Closure done = [sid, stream_id, cntl, response, srv, lat, start_us, latch,
                  limiter, grpc, resp_ct] {
    if (limiter != nullptr) {
      limiter->on_response(monotonic_time_us() - start_us, cntl->Failed());
    }
    if (cntl->Failed()) {
      h2_respond(sid, stream_id, grpc ? 200 : 500, resp_ct,
                 grpc ? "" : cntl->error_text() + "\n", grpc, 2,
                 cntl->error_text());
    } else {
      h2_respond(sid, stream_id, 200,
                 grpc ? resp_ct : "application/octet-stream",
                 response->to_string(), grpc, 0, "");
    }
    if (lat != nullptr) {
      *lat << (monotonic_time_us() - start_us);
    }
    delete response;
    if (cntl->call().sl_data != nullptr) {
      cntl->call().sl_pool->Return(cntl->call().sl_data);
    }
    delete cntl;
    srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    srv->in_flight.fetch_sub(1, std::memory_order_acq_rel);
    latch->signal();
  };
  prop->handler(cntl, request, response, std::move(done));
  latch->wait(-1);
}

void h2_process_response(InputMessage&&) {
  // Server-side only (the RPC client speaks tstd).
}

}  // namespace

void register_h2_protocol() {
  static int once = [] {
    Protocol p = {"h2", h2_parse, h2_process_request, h2_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

}  // namespace trpc
