// HTTP/2 + gRPC serving protocol.
//
// Parity: the reference's h2 stack (/root/reference/src/brpc/policy/
// http2_rpc_protocol.cpp + details/hpack.* + details/grpc.*, ~3,800 LoC).
// Redesigned condensed, server-side: connection preface pinning, frame
// parsing (SETTINGS/PING/HEADERS+CONTINUATION/DATA/WINDOW_UPDATE/
// RST_STREAM/GOAWAY), HPACK header blocks (net/hpack.h), credit-window
// flow control on BOTH directions (receive windows replenished after
// delivery; response DATA honors the peer's connection+stream windows,
// queueing the remainder until WINDOW_UPDATE — the same
// bounded-window/KeepWrite interaction the RDMA endpoint has), and gRPC
// message framing + trailers for application/grpc requests.  Requests
// dispatch exactly like HTTP/1.x: builtin endpoints, restful map, then
// /Service.Method.
#pragma once

namespace trpc {

// Registers the h2 protocol (idempotent).  Server::Start calls this.
void register_h2_protocol();

}  // namespace trpc
