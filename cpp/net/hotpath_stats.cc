#include "net/hotpath_stats.h"

#include "fiber/fiber.h"
#include "stat/variable.h"

namespace trpc {

namespace {

// Bulk-wake counters live in the fiber layer (scheduler.cc) because net/
// sits above fiber/; they surface here as pull-based vars so every
// hot-path series shares one /vars namespace.
struct BulkWakeVars {
  PassiveStatus<long> batches{[] {
    uint64_t b = 0, f = 0, m = 0;
    fiber_bulk_wake_stats(&b, &f, &m);
    return static_cast<long>(b);
  }};
  PassiveStatus<long> fibers{[] {
    uint64_t b = 0, f = 0, m = 0;
    fiber_bulk_wake_stats(&b, &f, &m);
    return static_cast<long>(f);
  }};
  PassiveStatus<long> max{[] {
    uint64_t b = 0, f = 0, m = 0;
    fiber_bulk_wake_stats(&b, &f, &m);
    return static_cast<long>(m);
  }};
};

}  // namespace

HotPathVars::HotPathVars() {
  write_coalesce_drains.expose(
      "socket_write_coalesce_drains",
      "write-queue drain sweeps (one coalesced writev each)");
  write_coalesce_nodes.expose(
      "socket_write_coalesce_nodes",
      "queued Writes absorbed into coalesced drains");
  write_coalesce_max.expose(
      "socket_write_coalesce_max",
      "high-water queued Writes absorbed by one drain");
  write_coalesce_batch.expose(
      "socket_write_coalesce_batch",
      "coalesced-drain batch size (1-in-16 sampled)");
  inline_write_attempts.expose(
      "socket_inline_write_attempts",
      "Socket::Write calls that tried the wait-free inline flush");
  inline_write_hits.expose(
      "socket_inline_write_hits",
      "inline flushes that drained the whole queue on the caller");
  dispatch_batches.expose(
      "messenger_dispatch_batches",
      "readable sweeps that cut at least one message");
  dispatch_msgs.expose("messenger_dispatch_messages",
                       "messages cut from readable sweeps");
  dispatch_inline.expose(
      "messenger_dispatch_inline",
      "messages run inline on the dispatch fiber (first-of-batch)");
  dispatch_max.expose("messenger_dispatch_max",
                      "high-water messages cut in one readable sweep");
  dispatch_batch.expose("messenger_dispatch_batch",
                        "dispatch batch size (1-in-16 sampled)");
  probe_rounds.expose("messenger_probe_rounds",
                      "full multi-protocol probe sweeps");
  probe_stall_skips.expose(
      "messenger_probe_stall_skips",
      "probe sweeps elided by the per-socket prefix-length memo");
  stripe_tx_chunks.expose(
      "stripe_tx_chunks",
      "large-message stripe chunk frames sent (heads included)");
  stripe_rx_chunks.expose(
      "stripe_rx_chunks",
      "large-message stripe chunk frames received (heads included)");
  stripe_tx_bytes.expose(
      "stripe_tx_bytes",
      "striped payload bytes sent (whole message bodies; the tuner's "
      "sender-side throughput signal for the stripe knobs)");
  stripe_rx_bytes.expose(
      "stripe_rx_bytes",
      "striped payload bytes landed at receivers (per-chunk sizes; the "
      "tuner's hill-climb target for stripe chunk/rail geometry)");
  stripe_reassembled.expose(
      "stripe_reassembled",
      "striped messages fully reassembled and dispatched");
  stripe_expired.expose(
      "stripe_expired",
      "stripe reassemblies dropped by timeout or abandonment");
  cut_budget_yields.expose(
      "messenger_cut_budget_yields",
      "read sweeps that yielded their worker after exhausting the "
      "per-sweep cut budget (bulk transfers sharing with small RPCs)");
  rma_tx_msgs.expose("rma_tx_msgs",
                     "one-sided transfers sent (control frames queued "
                     "after the last chunk write landed)");
  rma_tx_chunks.expose(
      "rma_tx_chunks",
      "chunks written one-sided into peer registered regions");
  rma_tx_bytes.expose("rma_tx_bytes",
                      "payload bytes moved by one-sided writes (no "
                      "ring/socket copy)");
  rma_rx_msgs.expose("rma_rx_msgs",
                     "rma control frames resolved into complete "
                     "payloads and dispatched");
  rma_window_full.expose(
      "rma_window_full",
      "one-sided sends that fell back to the striped copy path because "
      "no window span was free");
  rma_rejected.expose(
      "rma_rejected",
      "rma control frames dropped whole (incomplete completion bitmap, "
      "bad bounds, or an unknown/unbound region)");
}

HotPathVars& hotpath_vars() {
  // Leaked with the registry: worker threads outlive static destruction.
  static HotPathVars* v = new HotPathVars();
  return *v;
}

void expose_hotpath_variables() {
  hotpath_vars();
  static BulkWakeVars* bw = [] {
    auto* b = new BulkWakeVars();
    b->batches.expose("fiber_bulk_wake_batches",
                      "ready_to_run_batch publications (one ParkingLot "
                      "signal per batch)");
    b->fibers.expose("fiber_bulk_wake_fibers",
                     "fibers published through the bulk-wake path");
    b->max.expose("fiber_bulk_wake_max",
                  "largest single bulk-wake batch observed");
    return b;
  }();
  (void)bw;
}

}  // namespace trpc
