#include "net/hotpath_stats.h"

#include "fiber/fiber.h"
#include "stat/variable.h"

namespace trpc {

namespace {

// Bulk-wake counters live in the fiber layer (scheduler.cc) because net/
// sits above fiber/; they surface here as pull-based vars so every
// hot-path series shares one /vars namespace.
struct BulkWakeVars {
  PassiveStatus<long> batches{[] {
    uint64_t b = 0, f = 0, m = 0;
    fiber_bulk_wake_stats(&b, &f, &m);
    return static_cast<long>(b);
  }};
  PassiveStatus<long> fibers{[] {
    uint64_t b = 0, f = 0, m = 0;
    fiber_bulk_wake_stats(&b, &f, &m);
    return static_cast<long>(f);
  }};
  PassiveStatus<long> max{[] {
    uint64_t b = 0, f = 0, m = 0;
    fiber_bulk_wake_stats(&b, &f, &m);
    return static_cast<long>(m);
  }};
};

}  // namespace

HotPathVars::HotPathVars() {
  write_coalesce_drains.expose("socket_write_coalesce_drains");
  write_coalesce_nodes.expose("socket_write_coalesce_nodes");
  write_coalesce_max.expose("socket_write_coalesce_max");
  write_coalesce_batch.expose("socket_write_coalesce_batch");
  inline_write_attempts.expose("socket_inline_write_attempts");
  inline_write_hits.expose("socket_inline_write_hits");
  dispatch_batches.expose("messenger_dispatch_batches");
  dispatch_msgs.expose("messenger_dispatch_messages");
  dispatch_inline.expose("messenger_dispatch_inline");
  dispatch_max.expose("messenger_dispatch_max");
  dispatch_batch.expose("messenger_dispatch_batch");
  probe_rounds.expose("messenger_probe_rounds");
  probe_stall_skips.expose("messenger_probe_stall_skips");
}

HotPathVars& hotpath_vars() {
  // Leaked with the registry: worker threads outlive static destruction.
  static HotPathVars* v = new HotPathVars();
  return *v;
}

void expose_hotpath_variables() {
  hotpath_vars();
  static BulkWakeVars* bw = [] {
    auto* b = new BulkWakeVars();
    b->batches.expose("fiber_bulk_wake_batches");
    b->fibers.expose("fiber_bulk_wake_fibers");
    b->max.expose("fiber_bulk_wake_max");
    return b;
  }();
  (void)bw;
}

}  // namespace trpc
