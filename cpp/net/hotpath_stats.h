// Hot-path instrumentation for the small-RPC fast path: write coalescing,
// inline writes, batched message dispatch and bulk fiber wakeups.
//
// Parity: the reference instruments the same seams with bvars
// (socket.cpp's "connection_count"-family and input_messenger's batch
// counters); here one struct owns every hot-path var so the builtin /vars
// endpoint shows the whole picture at once.  Counters are thread-local-
// combining Adders (one relaxed CAS per event); the batch-size histograms
// go through LatencyRecorder (octave percentile sketch) on a 1-in-16
// sample so the recorder mutex stays off the hot path.
#pragma once

#include <cstdint>

#include "stat/latency_recorder.h"
#include "stat/reducer.h"

namespace trpc {

struct HotPathVars {
  // Write side: one "drain" = one KeepWrite/inline sweep of the MPSC
  // write queue into a single coalesced buffer (→ one writev/doorbell).
  Adder write_coalesce_drains;
  Adder write_coalesce_nodes;   // queued Writes absorbed by those drains
  Maxer write_coalesce_max;     // high-water nodes in one drain
  LatencyRecorder write_coalesce_batch;  // sampled batch-size quantiles

  // Inline-write fast path: Socket::Write flushed the whole queue on the
  // caller, no KeepWrite fiber, no wakeup.  hit/attempt = how often the
  // small-RPC path stays wait-free.
  Adder inline_write_attempts;
  Adder inline_write_hits;

  // Read side: one "batch" = the messages cut from one readable sweep;
  // the first runs inline on the dispatch fiber, the rest bulk-enqueue.
  Adder dispatch_batches;
  Adder dispatch_msgs;
  Adder dispatch_inline;        // messages run inline (first-of-batch)
  Maxer dispatch_max;
  LatencyRecorder dispatch_batch;  // sampled batch-size quantiles

  // Protocol probing: rounds = full multi-protocol probe sweeps,
  // stall_skips = sweeps elided because no new bytes arrived since the
  // last inconclusive probe (the per-socket prefix-length memo).
  Adder probe_rounds;
  Adder probe_stall_skips;

  // Large-message striping (net/stripe.h).  All four stay EXACTLY zero
  // on sub-threshold traffic — that invariant is what proves small RPCs
  // bypass the stripe layer entirely.
  Adder stripe_tx_chunks;    // chunk frames sent (head included)
  Adder stripe_rx_chunks;    // chunk frames received (head included)
  Adder stripe_tx_bytes;     // striped payload bytes sent (whole bodies)
  Adder stripe_rx_bytes;     // striped payload bytes landed (chunk sizes)
  Adder stripe_reassembled;  // messages fully reassembled and dispatched
  Adder stripe_expired;      // reassemblies dropped by timeout/abandon

  // Read sweeps that yielded mid-drain (trpc_messenger_cut_budget): how
  // often a bulk transfer handed its worker back to small-RPC dispatch.
  Adder cut_budget_yields;

  // One-sided RMA plane (net/rma.h).  Like the stripe vars, every one
  // of these stays EXACTLY zero on sub-threshold traffic — the proof
  // that small RPCs never touch the rma layer.
  Adder rma_tx_msgs;      // one-sided transfers sent (control frames)
  Adder rma_tx_chunks;    // chunks written into peer regions
  Adder rma_tx_bytes;     // payload bytes moved one-sided
  Adder rma_rx_msgs;      // control frames resolved and dispatched
  Adder rma_window_full;  // sends that fell back (no window span free)
  Adder rma_rejected;     // control frames dropped whole (incomplete
                          // bitmap, bad bounds, unknown region)

  HotPathVars();
};

// Process-wide instance (registered in /vars on first use).
HotPathVars& hotpath_vars();

// Idempotent: force registration so /vars shows the zeroed series even
// before traffic (called from Server::Start like the process vars).
void expose_hotpath_variables();

// 1-in-N sampling helper for the histogram recorders (TLS counter).
inline bool hotpath_sample16() {
  static thread_local uint32_t n = 0;
  return (++n & 15u) == 0;
}

}  // namespace trpc
