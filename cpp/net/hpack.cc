#include "net/hpack.h"

#include <algorithm>

#include <cstring>

namespace trpc {

namespace {

#include "net/hpack_huffman.inc"

// RFC 7541 Appendix A static table (1-based).
struct StaticEntry {
  const char* name;
  const char* value;
};
const StaticEntry kStatic[] = {
    {"", ""},  // index 0 unused
    {":authority", ""},
    {":method", "GET"},
    {":method", "POST"},
    {":path", "/"},
    {":path", "/index.html"},
    {":scheme", "http"},
    {":scheme", "https"},
    {":status", "200"},
    {":status", "204"},
    {":status", "206"},
    {":status", "304"},
    {":status", "400"},
    {":status", "404"},
    {":status", "500"},
    {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"},
    {"accept-language", ""},
    {"accept-ranges", ""},
    {"accept", ""},
    {"access-control-allow-origin", ""},
    {"age", ""},
    {"allow", ""},
    {"authorization", ""},
    {"cache-control", ""},
    {"content-disposition", ""},
    {"content-encoding", ""},
    {"content-language", ""},
    {"content-length", ""},
    {"content-location", ""},
    {"content-range", ""},
    {"content-type", ""},
    {"cookie", ""},
    {"date", ""},
    {"etag", ""},
    {"expect", ""},
    {"expires", ""},
    {"from", ""},
    {"host", ""},
    {"if-match", ""},
    {"if-modified-since", ""},
    {"if-none-match", ""},
    {"if-range", ""},
    {"if-unmodified-since", ""},
    {"last-modified", ""},
    {"link", ""},
    {"location", ""},
    {"max-forwards", ""},
    {"proxy-authenticate", ""},
    {"proxy-authorization", ""},
    {"range", ""},
    {"referer", ""},
    {"refresh", ""},
    {"retry-after", ""},
    {"server", ""},
    {"set-cookie", ""},
    {"strict-transport-security", ""},
    {"transfer-encoding", ""},
    {"user-agent", ""},
    {"vary", ""},
    {"via", ""},
    {"www-authenticate", ""},
};
constexpr uint64_t kStaticCount = 61;

constexpr size_t kEntryOverhead = 32;  // RFC 7541 §4.1
constexpr size_t kMaxHeaderBytes = 256 * 1024;  // decoded-size bomb guard

}  // namespace

bool hpack_decode_int(const uint8_t** p, const uint8_t* end, int prefix_bits,
                      uint64_t* out) {
  if (*p >= end) {
    return false;
  }
  const uint64_t mask = (1u << prefix_bits) - 1;
  uint64_t v = **p & mask;
  ++*p;
  if (v < mask) {
    *out = v;
    return true;
  }
  uint64_t shift = 0;
  while (*p < end) {
    const uint8_t b = **p;
    ++*p;
    v += static_cast<uint64_t>(b & 0x7f) << shift;
    if (shift > 56 || v > (1ull << 62)) {
      return false;  // unbounded varint
    }
    if ((b & 0x80) == 0) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;  // truncated
}

void hpack_encode_int(uint64_t v, int prefix_bits, uint8_t first_byte_flags,
                      std::string* out) {
  const uint64_t mask = (1u << prefix_bits) - 1;
  if (v < mask) {
    out->push_back(static_cast<char>(first_byte_flags | v));
    return;
  }
  out->push_back(static_cast<char>(first_byte_flags | mask));
  v -= mask;
  while (v >= 0x80) {
    out->push_back(static_cast<char>(0x80 | (v & 0x7f)));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

bool hpack_huffman_decode(const uint8_t* data, size_t len,
                          std::string* out) {
  // Canonical decoding: accumulate bits msb-first; at each code length
  // with assigned symbols, test whether the accumulated code falls in
  // that length's [min, min+count) range.
  uint32_t code = 0;
  int bits = 0;
  size_t len_idx = 0;  // next candidate row in kHuffLens
  for (size_t i = 0; i < len; ++i) {
    for (int b = 7; b >= 0; --b) {
      code = (code << 1) | ((data[i] >> b) & 1);
      ++bits;
      while (len_idx < sizeof(kHuffLens) / sizeof(kHuffLens[0]) &&
             kHuffLens[len_idx].bits < bits) {
        ++len_idx;
      }
      if (len_idx >= sizeof(kHuffLens) / sizeof(kHuffLens[0])) {
        return false;  // longer than any code: invalid
      }
      const HuffLen& row = kHuffLens[len_idx];
      if (row.bits == bits && code >= row.min_code &&
          code < row.min_code + row.count) {
        const uint16_t sym = kHuffSyms[row.first_sym_idx +
                                       (code - row.min_code)];
        if (sym == 256) {
          return false;  // EOS inside the stream is a coding error
        }
        out->push_back(static_cast<char>(sym));
        if (out->size() > kMaxHeaderBytes) {
          return false;
        }
        code = 0;
        bits = 0;
        len_idx = 0;
      }
    }
  }
  // Padding must be the EOS prefix: all ones, shorter than a byte.
  if (bits >= 8) {
    return false;
  }
  return code == (1u << bits) - 1;
}

namespace {

// Reads a §5.2 string literal (optionally huffman-coded).
bool read_string(const uint8_t** p, const uint8_t* end, std::string* out) {
  if (*p >= end) {
    return false;
  }
  const bool huff = (**p & 0x80) != 0;
  uint64_t len = 0;
  if (!hpack_decode_int(p, end, 7, &len)) {
    return false;
  }
  if (len > static_cast<uint64_t>(end - *p) || len > kMaxHeaderBytes) {
    return false;
  }
  if (huff) {
    if (!hpack_huffman_decode(*p, len, out)) {
      return false;
    }
  } else {
    out->assign(reinterpret_cast<const char*>(*p), len);
  }
  *p += len;
  return true;
}

}  // namespace

void HpackDynTable::evict_to(size_t limit) {
  while (bytes > limit && !entries.empty()) {
    bytes -= entries.back().first.size() + entries.back().second.size() +
             kEntryOverhead;
    entries.pop_back();
  }
}

void HpackDynTable::insert(const std::string& name,
                           const std::string& value, size_t max_size) {
  const size_t sz = name.size() + value.size() + kEntryOverhead;
  if (sz > max_size) {  // larger than the table: empties it (§4.4)
    evict_to(0);
    return;
  }
  evict_to(max_size - sz);
  entries.insert(entries.begin(), {name, value});
  bytes += sz;
}

size_t HpackDynTable::find(const std::string& name,
                           const std::string& value) const {
  for (size_t i = 0; i < entries.size(); ++i) {
    if (entries[i].first == name && entries[i].second == value) {
      return i;
    }
  }
  return SIZE_MAX;
}

bool HpackDecoder::lookup(uint64_t index, std::string* name,
                          std::string* value) const {
  if (index == 0) {
    return false;
  }
  if (index <= kStaticCount) {
    *name = kStatic[index].name;
    *value = kStatic[index].value;
    return true;
  }
  const uint64_t d = index - kStaticCount - 1;
  if (d >= table_.entries.size()) {
    return false;
  }
  *name = table_.entries[d].first;
  *value = table_.entries[d].second;
  return true;
}

bool HpackDecoder::decode(const uint8_t* data, size_t len,
                          HeaderList* out) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  size_t total = 0;
  while (p < end) {
    const uint8_t b = *p;
    if (b & 0x80) {  // §6.1 indexed
      uint64_t index = 0;
      if (!hpack_decode_int(&p, end, 7, &index)) {
        return false;
      }
      std::string name;
      std::string value;
      if (!lookup(index, &name, &value)) {
        return false;
      }
      total += name.size() + value.size();
      out->emplace_back(std::move(name), std::move(value));
    } else if (b & 0x40) {  // §6.2.1 literal with incremental indexing
      uint64_t index = 0;
      if (!hpack_decode_int(&p, end, 6, &index)) {
        return false;
      }
      std::string name;
      std::string value;
      if (index != 0) {
        std::string unused;
        if (!lookup(index, &name, &unused)) {
          return false;
        }
      } else if (!read_string(&p, end, &name)) {
        return false;
      }
      if (!read_string(&p, end, &value)) {
        return false;
      }
      table_.insert(name, value, max_size_);
      total += name.size() + value.size();
      out->emplace_back(std::move(name), std::move(value));
    } else if (b & 0x20) {  // §6.3 dynamic table size update
      uint64_t sz = 0;
      if (!hpack_decode_int(&p, end, 5, &sz)) {
        return false;
      }
      if (sz > settings_cap_) {
        return false;  // must not exceed the SETTINGS ceiling
      }
      max_size_ = static_cast<uint32_t>(sz);
      table_.evict_to(max_size_);
    } else {  // §6.2.2/§6.2.3 literal without indexing / never indexed
      uint64_t index = 0;
      if (!hpack_decode_int(&p, end, 4, &index)) {
        return false;
      }
      std::string name;
      std::string value;
      if (index != 0) {
        std::string unused;
        if (!lookup(index, &name, &unused)) {
          return false;
        }
      } else if (!read_string(&p, end, &name)) {
        return false;
      }
      if (!read_string(&p, end, &value)) {
        return false;
      }
      total += name.size() + value.size();
      out->emplace_back(std::move(name), std::move(value));
    }
    if (total > kMaxHeaderBytes) {
      return false;
    }
  }
  return true;
}

void HpackEncoder::set_max_size(uint32_t peer_max) {
  // Never grow past our own 4096 budget; shrink to the peer's limit and
  // open the next block with the §6.3 size update it must observe.
  const uint32_t next = std::min<uint32_t>(peer_max, 4096);
  if (next == max_size_) {
    return;
  }
  max_size_ = next;
  table_.evict_to(max_size_);
  pending_size_update_ = true;
}

void HpackEncoder::encode(const HeaderList& headers, std::string* out) {
  if (pending_size_update_) {
    hpack_encode_int(max_size_, 5, 0x20, out);  // §6.3
    pending_size_update_ = false;
  }
  for (const auto& [name, value] : headers) {
    // Exact static match → one indexed byte.
    uint64_t exact = 0;
    uint64_t name_only = 0;
    for (uint64_t i = 1; i <= kStaticCount; ++i) {
      if (name == kStatic[i].name) {
        if (name_only == 0) {
          name_only = i;
        }
        if (value == kStatic[i].value) {
          exact = i;
          break;
        }
      }
    }
    if (exact == 0) {
      const size_t d = table_.find(name, value);
      if (d != SIZE_MAX) {
        exact = kStaticCount + d + 1;  // HPACK numbering: newest first
      }
    }
    if (exact != 0) {
      hpack_encode_int(exact, 7, 0x80, out);
      continue;
    }
    const size_t entry_sz = name.size() + value.size() + kEntryOverhead;
    if (entry_sz > max_size_ / 2) {
      // Oversized: indexing would evict the whole table for one entry.
      // Literal WITHOUT indexing (§6.2.2), indexed name when available.
      hpack_encode_int(name_only, 4, 0x00, out);
      if (name_only == 0) {
        hpack_encode_int(name.size(), 7, 0x00, out);
        out->append(name);
      }
      hpack_encode_int(value.size(), 7, 0x00, out);
      out->append(value);
      continue;
    }
    // Literal WITH incremental indexing (§6.2.1): the peer's decoder
    // inserts exactly what we insert, so later blocks can reference it.
    hpack_encode_int(name_only, 6, 0x40, out);
    if (name_only == 0) {
      hpack_encode_int(name.size(), 7, 0x00, out);
      out->append(name);
    }
    hpack_encode_int(value.size(), 7, 0x00, out);
    out->append(value);
    table_.insert(name, value, max_size_);
  }
}

}  // namespace trpc
