// HPACK — HTTP/2 header compression (RFC 7541).
//
// Parity: the reference's hpack.cpp/hpack-static-table.h
// (/root/reference/src/brpc/details/hpack.cpp — ~1,700 LoC with a
// node-tree Huffman decoder).  Redesigned condensed: canonical-Huffman
// decoding by bit-length groups (the RFC code assignment is canonical, so
// per-length [min_code, max_code] ranges + a symbol array replace the
// tree entirely), one dynamic table with RFC size accounting, and an
// encoder that emits never-indexed literals (legal and simple — peers
// still send us fully indexed/huffman forms, which we decode).
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace trpc {

using HeaderList = std::vector<std::pair<std::string, std::string>>;

class HpackDecoder {
 public:
  explicit HpackDecoder(uint32_t max_dynamic_size = 4096)
      : max_size_(max_dynamic_size) {}

  // Decodes one complete header block; false on any malformed input
  // (connection error per RFC 7540 §4.3).
  bool decode(const uint8_t* data, size_t len, HeaderList* out);

  size_t dynamic_size() const { return dyn_bytes_; }

 private:
  bool lookup(uint64_t index, std::string* name, std::string* value) const;
  void insert(const std::string& name, const std::string& value);
  void evict_to(size_t limit);

  uint32_t max_size_;
  uint32_t settings_cap_ = 4096;  // ceiling for table-size updates
  std::vector<std::pair<std::string, std::string>> dynamic_;  // newest front
  size_t dyn_bytes_ = 0;
};

class HpackEncoder {
 public:
  // Appends one header block for `headers` to *out (static-table indexed
  // where an exact match exists; literal-never-indexed otherwise).
  void encode(const HeaderList& headers, std::string* out);
};

// Exposed for tests: RFC 7541 §5.1 prefix integers and §5.2 huffman.
bool hpack_decode_int(const uint8_t** p, const uint8_t* end, int prefix_bits,
                      uint64_t* out);
void hpack_encode_int(uint64_t v, int prefix_bits, uint8_t first_byte_flags,
                      std::string* out);
bool hpack_huffman_decode(const uint8_t* data, size_t len, std::string* out);

}  // namespace trpc
