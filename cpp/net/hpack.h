// HPACK — HTTP/2 header compression (RFC 7541).
//
// Parity: the reference's hpack.cpp/hpack-static-table.h
// (/root/reference/src/brpc/details/hpack.cpp — ~1,700 LoC with a
// node-tree Huffman decoder).  Redesigned condensed: canonical-Huffman
// decoding by bit-length groups (the RFC code assignment is canonical, so
// per-length [min_code, max_code] ranges + a symbol array replace the
// tree entirely), one dynamic table with RFC size accounting, and an
// encoder with incremental indexing over its own dynamic table (repeated
// metadata — gRPC paths, authorities, custom headers — shrinks to one
// index byte per later block, details/hpack.cpp parity).
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace trpc {

using HeaderList = std::vector<std::pair<std::string, std::string>>;

// The RFC 7541 §4.1 dynamic table (newest-first, 32-byte per-entry
// overhead), shared by decoder and encoder so the size-accounting rules
// exist exactly once.
struct HpackDynTable {
  void evict_to(size_t limit);
  // §4.4 included: an entry larger than the whole table empties it.
  void insert(const std::string& name, const std::string& value,
              size_t max_size);
  // 0-based position of an exact match, or SIZE_MAX.
  size_t find(const std::string& name, const std::string& value) const;

  std::vector<std::pair<std::string, std::string>> entries;
  size_t bytes = 0;
};

class HpackDecoder {
 public:
  explicit HpackDecoder(uint32_t max_dynamic_size = 4096)
      : max_size_(max_dynamic_size) {}

  // Decodes one complete header block; false on any malformed input
  // (connection error per RFC 7540 §4.3).
  bool decode(const uint8_t* data, size_t len, HeaderList* out);

  size_t dynamic_size() const { return table_.bytes; }

 private:
  bool lookup(uint64_t index, std::string* name, std::string* value) const;

  uint32_t max_size_;
  uint32_t settings_cap_ = 4096;  // ceiling for table-size updates
  HpackDynTable table_;
};

class HpackEncoder {
 public:
  explicit HpackEncoder(uint32_t max_dynamic_size = 4096)
      : max_size_(max_dynamic_size) {}

  // Appends one header block for `headers` to *out: static/dynamic exact
  // matches emit one index; everything else is a literal WITH incremental
  // indexing (§6.2.1), entering the encoder's table — which mirrors, by
  // construction, the table the peer's decoder maintains — so repeats in
  // later blocks shrink to an index.  Oversized entries (> half the
  // table) are never indexed: they would evict everything for one entry.
  void encode(const HeaderList& headers, std::string* out);

  // Bounds the encoder's table by the peer decoder's advertised
  // SETTINGS_HEADER_TABLE_SIZE (RFC 7541 §4.2): shrinks immediately and
  // schedules the §6.3 size update the next block must open with.
  void set_max_size(uint32_t peer_max);

  size_t dynamic_size() const { return table_.bytes; }

 private:
  uint32_t max_size_;
  bool pending_size_update_ = false;
  HpackDynTable table_;
};

// Exposed for tests: RFC 7541 §5.1 prefix integers and §5.2 huffman.
bool hpack_decode_int(const uint8_t** p, const uint8_t* end, int prefix_bits,
                      uint64_t* out);
void hpack_encode_int(uint64_t v, int prefix_bits, uint8_t first_byte_flags,
                      std::string* out);
bool hpack_huffman_decode(const uint8_t* data, size_t len, std::string* out);

}  // namespace trpc
