#include "net/http_client.h"

#include <algorithm>
#include <deque>
#include <memory>
#include <mutex>

#include "base/time.h"
#include "fiber/fiber.h"
#include "net/messenger.h"
#include "net/protocol.h"

namespace trpc {

namespace {

// One in-flight request awaiting its FIFO slot's response.  head_only
// tracks HEAD requests, whose responses carry headers but no body
// whatever Content-Length says.
struct HttpWaiter {
  CountdownEvent ev{1};
  bool head_only = false;
  HttpResult result;
};

struct HttpCliConn {
  std::mutex mu;  // queue order must match wire order
  std::deque<std::shared_ptr<HttpWaiter>> pending;
  // Resumable chunked-body scan state for the response being parsed.
  std::shared_ptr<void> chunk_state;
};

const char kHttpCliTag = 0;

HttpCliConn* cli_conn_of(Socket* s) {
  return proto_conn_of<HttpCliConn>(s, &kHttpCliTag);
}

ParseError httpc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;  // client sockets are pre-pinned
  }
  HttpCliConn* c = cli_conn_of(sock);
  bool head_only = false;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (!c->pending.empty()) {
      head_only = c->pending.front()->head_only;
    }
  }
  while (true) {
    auto resp = std::make_shared<std::pair<HttpResponse, IOBuf>>();
    const ParseError rc = http_parse_response(
        source, &resp->first, &resp->second, &c->chunk_state, head_only);
    if (rc != ParseError::kOk) {
      return rc;
    }
    if (resp->first.status < 200) {
      // 1xx interim (100 Continue, 103 Early Hints): NOT the final
      // response — swallow it (a loop, not recursion: a server
      // streaming thousands of interims must not grow the stack) so
      // the FIFO stays aligned with the request the real response
      // answers.
      if (source->empty()) {
        return ParseError::kNotEnoughData;
      }
      continue;
    }
    out->meta.type = RpcMeta::kResponse;
    out->ctx = std::move(resp);
    out->socket = sock->id();
    return ParseError::kOk;
  }
}

void httpc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto resp =
      std::static_pointer_cast<std::pair<HttpResponse, IOBuf>>(msg.ctx);
  HttpCliConn* c = cli_conn_of(sock.get());
  std::shared_ptr<HttpWaiter> w;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->pending.empty()) {
      return;  // unsolicited response: drop
    }
    w = std::move(c->pending.front());
    c->pending.pop_front();
  }
  w->result.ok = true;
  w->result.status = resp->first.status;
  w->result.reason = std::move(resp->first.reason);
  w->result.headers = std::move(resp->first.headers);
  w->result.body = resp->second.to_string();
  const bool close_me = !resp->first.keep_alive;
  w->ev.signal();
  if (close_me) {
    sock->SetFailed(ESHUTDOWN);  // server said Connection: close
  }
}

void httpc_process_request(InputMessage&&) {}

int httpc_protocol_index() {
  static const int index = [] {
    Protocol p = {"httpc", httpc_parse, httpc_process_request,
                  httpc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

}  // namespace

const std::string* HttpResult::header(const std::string& name) const {
  return http_find_header(headers, name);
}

HttpClient::~HttpClient() {
  csock_.Shutdown();
}

int HttpClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  httpc_protocol_index();
  std::string target = addr;
  bool https = false;
  if (target.rfind("http://", 0) == 0) {
    target = target.substr(7);
  } else if (target.rfind("https://", 0) == 0) {
    target = target.substr(8);
    https = true;
  }
  const size_t slash = target.find('/');
  if (slash != std::string::npos && target.rfind("unix:", 0) != 0) {
    target.resize(slash);  // strip any path; calls pass paths explicitly
  }
  host_ = target;
  if (https) {
    // Port detection must ignore colons INSIDE a bracketed IPv6 literal:
    // only a colon after the last ']' (or any colon when unbracketed)
    // counts as host:port.
    const size_t bracket = target.rfind(']');
    const size_t colon = target.rfind(':');
    const bool has_port =
        colon != std::string::npos &&
        (bracket == std::string::npos || colon > bracket);
    std::string host_only =
        has_port ? target.substr(0, colon) : target;
    if (csock_.EnableTls("\x08http/1.1", host_only) != 0) {
      return -1;  // https requested but libssl unavailable: fail loudly
    }
    if (!has_port) {
      target += ":443";  // scheme default
    }
  }
  return csock_.Init(target);
}

HttpResult HttpClient::Do(
    const std::string& verb, const std::string& path,
    const std::vector<std::pair<std::string, std::string>>& extra_headers,
    const std::string& body) {
  HttpResult fail;
  auto w = std::make_shared<HttpWaiter>();
  w->head_only = http_ci_equal(verb, "HEAD");

  std::string wire = verb + " " + path + " HTTP/1.1\r\nHost: " + host_ +
                     "\r\n";
  for (const auto& [k, v] : extra_headers) {
    wire += k + ": " + v + "\r\n";
  }
  if (!body.empty() || http_ci_equal(verb, "POST") || http_ci_equal(verb, "PUT")) {
    wire += "Content-Length: " + std::to_string(body.size()) + "\r\n";
  }
  wire += "\r\n";
  wire += body;

  SocketId sid = 0;
  {
    LockGuard<FiberMutex> g(sock_mu_);
    auto install = [](Socket* fresh) -> int {
      cli_conn_of(fresh);  // install state while single-threaded
      return 0;
    };
    if (csock_.ensure(httpc_protocol_index(), install, &sid) != 0) {
      fail.error = "cannot reach " + host_;
      return fail;
    }
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    fail.error = "connection failed";
    return fail;
  }
  HttpCliConn* c = cli_conn_of(s.get());
  {
    // Queue order must equal wire order: both under one lock.
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.push_back(w);
    IOBuf frame;
    frame.append(wire);
    if (s->Write(std::move(frame)) != 0) {
      c->pending.pop_back();
      fail.error = "write failed";
      return fail;
    }
  }
  if (w->ev.wait(monotonic_time_us() + opts_.timeout_ms * 1000) != 0) {
    fail.error = "timeout";
    return fail;
  }
  return std::move(w->result);
}

HttpResult HttpClient::Get(const std::string& path) {
  return Do("GET", path, {}, "");
}

HttpResult HttpClient::Head(const std::string& path) {
  return Do("HEAD", path, {}, "");
}

HttpResult HttpClient::Post(const std::string& path,
                            const std::string& content_type,
                            const std::string& body) {
  return Do("POST", path, {{"Content-Type", content_type}}, body);
}

}  // namespace trpc
