// HTTP/1.1 client over the fiber runtime — keep-alive, pipelined FIFO
// correlation, chunked responses.
//
// Parity: the reference issues HTTP calls through Channel with an
// http:// URL (policy/http_rpc_protocol.cpp client half + Controller's
// http_request accessors).  Condensed per-protocol-client form (the
// RedisClient idiom): one lazily-connected pinned socket, requests
// written in order, responses popped FIFO — HTTP/1.1's ordering
// guarantee is exactly the pipelined_count contract.
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

#include "fiber/sync.h"
#include "net/http_message.h"
#include "net/proto_client.h"

namespace trpc {

struct HttpResult {
  bool ok = false;       // transport-level success (any status counts)
  std::string error;     // transport failure text when !ok
  int status = 0;
  std::string reason;
  std::vector<std::pair<std::string, std::string>> headers;
  std::string body;

  // nullptr when absent; case-insensitive.
  const std::string* header(const std::string& name) const;
};

class HttpClient {
 public:
  struct Options {
    int64_t timeout_ms = 2000;
  };

  ~HttpClient();
  // "host:port", "http://host:port", or "unix:/path".
  int Init(const std::string& addr, const Options* opts = nullptr);

  HttpResult Get(const std::string& path);
  HttpResult Post(const std::string& path, const std::string& content_type,
                  const std::string& body);
  HttpResult Head(const std::string& path);
  // Full form: extra headers ride verbatim (Host/Content-Length added).
  HttpResult Do(const std::string& verb, const std::string& path,
                const std::vector<std::pair<std::string, std::string>>&
                    extra_headers,
                const std::string& body);

 private:
  Options opts_;
  std::string host_;  // Host header value
  FiberMutex sock_mu_;
  ClientSocket csock_;
};

}  // namespace trpc
