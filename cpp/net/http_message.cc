#include "net/http_message.h"

#include <cstring>

#include <algorithm>

namespace trpc {

namespace {

constexpr size_t kMaxHeaderBytes = 64 * 1024;
constexpr uint64_t kMaxBody = 1ull << 30;  // 1 GB

bool ci_equal(const std::string& a, const char* b) {
  const size_t n = strlen(b);
  if (a.size() != n) {
    return false;
  }
  for (size_t i = 0; i < n; ++i) {
    if (tolower(static_cast<unsigned char>(a[i])) !=
        tolower(static_cast<unsigned char>(b[i]))) {
      return false;
    }
  }
  return true;
}

bool ci_contains(const std::string& haystack, const char* needle) {
  std::string lower = haystack;
  for (char& c : lower) {
    c = static_cast<char>(tolower(static_cast<unsigned char>(c)));
  }
  return lower.find(needle) != std::string::npos;
}

std::string trim_ows(const std::string& s) {
  size_t b = 0;
  size_t e = s.size();
  while (b < e && (s[b] == ' ' || s[b] == '\t')) {
    ++b;
  }
  while (e > b && (s[e - 1] == ' ' || s[e - 1] == '\t')) {
    --e;
  }
  return s.substr(b, e - b);
}

int hex_val(char c) {
  if (c >= '0' && c <= '9') {
    return c - '0';
  }
  if (c >= 'a' && c <= 'f') {
    return c - 'a' + 10;
  }
  if (c >= 'A' && c <= 'F') {
    return c - 'A' + 10;
  }
  return -1;
}

constexpr size_t kMaxTrailerBytes = 16 * 1024;

// Resumable chunked-body scan.  st->pos is the absolute offset of the
// validated frontier in the connection's input buffer: completed chunks
// are moved into st->body and the frontier advances, so a retry with more
// bytes only re-copies the unvalidated tail (a partial line or partial
// chunk), never the whole buffer — no O(n^2) rescans.
struct ChunkedState {
  size_t pos = 0;        // absolute offset of the validated frontier
  std::string body;      // de-chunked payload so far
  bool in_trailers = false;
  size_t trailer_bytes = 0;  // completed trailer-line bytes (capped)
};

ParseError parse_chunked(const IOBuf& source, ChunkedState* st,
                         IOBuf* body, size_t* consumed) {
  // ONE copy of the unvalidated tail per parse attempt; the loop below
  // scans every chunk inside this window via `off` (window-relative
  // frontier) — copying inside the loop would be O(bytes x chunks).
  std::string tail;
  tail.resize(source.size() - st->pos);
  source.copy_to(tail.data(), tail.size(), st->pos);
  size_t off = 0;

  while (true) {
    if (st->in_trailers) {
      // Trailer section: zero or more (ignored) header lines, then CRLF.
      // Bounded so an endless trailer stream cannot grow the buffer
      // forever.
      while (true) {
        const size_t t_end = tail.find("\r\n", off);
        if (t_end == std::string::npos) {
          st->pos += off;  // completed trailer lines are consumed
          if (st->trailer_bytes + (tail.size() - off) > kMaxTrailerBytes) {
            return ParseError::kCorrupted;
          }
          return ParseError::kNotEnoughData;
        }
        if (t_end == off) {  // empty line closes the message
          st->pos += off + 2;
          body->append(st->body);
          *consumed = st->pos;
          return ParseError::kOk;
        }
        st->trailer_bytes += t_end + 2 - off;
        if (st->trailer_bytes > kMaxTrailerBytes) {
          return ParseError::kCorrupted;
        }
        off = t_end + 2;
      }
    }

    // chunk-size line: hex [; extensions] CRLF
    const size_t line_end = tail.find("\r\n", off);
    if (line_end == std::string::npos) {
      st->pos += off;
      return tail.size() - off > 64
                 ? ParseError::kCorrupted  // absurd size line
                 : ParseError::kNotEnoughData;
    }
    uint64_t size = 0;
    size_t i = off;
    bool any = false;
    for (; i < line_end; ++i) {
      const int v = hex_val(tail[i]);
      if (v < 0) {
        break;  // extensions start (';') or garbage
      }
      any = true;
      size = size * 16 + static_cast<uint64_t>(v);
      if (size > kMaxBody) {
        return ParseError::kCorrupted;
      }
    }
    if (!any || (i < line_end && tail[i] != ';')) {
      return ParseError::kCorrupted;
    }
    if (size == 0) {
      off = line_end + 2;
      st->in_trailers = true;
      continue;
    }
    if (st->body.size() + size > kMaxBody) {
      return ParseError::kCorrupted;
    }
    const size_t data_off = line_end + 2;
    if (data_off + size + 2 > tail.size()) {
      // Frontier stays at the size line until the whole chunk (+CRLF) is
      // visible; the next attempt's copied tail is bounded by one chunk.
      st->pos += off;
      return ParseError::kNotEnoughData;
    }
    if (tail[data_off + size] != '\r' || tail[data_off + size + 1] != '\n') {
      return ParseError::kCorrupted;
    }
    st->body.append(tail, data_off, size);
    off = data_off + size + 2;
  }
}


}  // namespace

bool http_ci_equal(const std::string& a, const std::string& b) {
  return ci_equal(a, b.c_str());
}

const std::string* http_find_header(
    const std::vector<std::pair<std::string, std::string>>& headers,
    const std::string& name) {
  for (const auto& [k, v] : headers) {
    if (ci_equal(k, name.c_str())) {
      return &v;
    }
  }
  return nullptr;
}

const std::string* HttpRequest::header(const std::string& name) const {
  return http_find_header(headers, name);
}

const std::string* HttpRequest::query(const std::string& name) const {
  for (const auto& [k, v] : queries) {
    if (k == name) {
      return &v;
    }
  }
  return nullptr;
}

bool percent_decode(const std::string& in, std::string* out, bool for_query) {
  out->clear();
  out->reserve(in.size());
  for (size_t i = 0; i < in.size(); ++i) {
    const char c = in[i];
    if (c == '%') {
      if (i + 2 >= in.size()) {
        return false;
      }
      const int hi = hex_val(in[i + 1]);
      const int lo = hex_val(in[i + 2]);
      if (hi < 0 || lo < 0) {
        return false;
      }
      out->push_back(static_cast<char>(hi * 16 + lo));
      i += 2;
    } else if (for_query && c == '+') {
      out->push_back(' ');
    } else {
      out->push_back(c);
    }
  }
  return true;
}

void parse_query_string(
    const std::string& qs,
    std::vector<std::pair<std::string, std::string>>* out) {
  size_t pos = 0;
  while (pos <= qs.size()) {
    size_t amp = qs.find('&', pos);
    if (amp == std::string::npos) {
      amp = qs.size();
    }
    const std::string pair = qs.substr(pos, amp - pos);
    pos = amp + 1;
    if (pair.empty()) {
      if (amp == qs.size()) {
        break;
      }
      continue;
    }
    const size_t eq = pair.find('=');
    std::string k;
    std::string v;
    const bool ok =
        eq == std::string::npos
            ? percent_decode(pair, &k, true)
            : percent_decode(pair.substr(0, eq), &k, true) &&
                  percent_decode(pair.substr(eq + 1), &v, true);
    if (ok && !k.empty()) {
      out->emplace_back(std::move(k), std::move(v));
    }
    if (amp == qs.size()) {
      break;
    }
  }
}


// Shared header-block scan for BOTH directions.  Every smuggling-
// hardening rule lives here exactly once: no whitespace before the
// colon, a single non-list numeric Content-Length, Transfer-Encoding
// exactly "chunked", CL+TE rejected by the callers.
struct HeaderScan {
  std::vector<std::pair<std::string, std::string>> headers;
  bool chunked = false;
  bool have_content_length = false;
  uint64_t content_len = 0;
  int keep_alive = -1;  // -1 header absent, 0 close, 1 keep-alive
};

bool parse_header_block(const std::string& window, size_t pos,
                        size_t hdr_end, HeaderScan* out) {
  while (pos < hdr_end + 2) {
    size_t eol = window.find("\r\n", pos);
    if (eol == std::string::npos || eol > hdr_end) {
      eol = hdr_end;
    }
    const std::string hline = window.substr(pos, eol - pos);
    pos = eol + 2;
    if (hline.empty()) {
      break;
    }
    const size_t colon = hline.find(':');
    if (colon == std::string::npos || colon == 0) {
      return false;  // a header line without a name
    }
    std::string name = hline.substr(0, colon);
    // RFC 7230 §3.2.4: whitespace between field-name and colon must be
    // rejected — "Content-Length :" would otherwise dodge the framing
    // logic while a fronting proxy honors it (request smuggling).
    if (name.back() == ' ' || name.back() == '\t') {
      return false;
    }
    std::string value = trim_ows(hline.substr(colon + 1));
    if (ci_equal(name, "content-length")) {
      // Duplicate or list-valued Content-Length desyncs framing: reject
      // outright rather than trusting either copy (request smuggling).
      // 1*DIGIT only (RFC 7230): strtoull's leading '+'/whitespace
      // tolerance is a smuggling desync vector behind stricter proxies.
      if (out->have_content_length ||
          value.find(',') != std::string::npos || value.empty() ||
          value[0] < '0' || value[0] > '9') {
        return false;
      }
      char* end = nullptr;
      out->content_len = strtoull(value.c_str(), &end, 10);
      if (end == value.c_str() || *end != '\0' ||
          out->content_len > kMaxBody) {
        return false;
      }
      out->have_content_length = true;
    } else if (ci_equal(name, "transfer-encoding")) {
      // Only the exact value "chunked" (already OWS-trimmed).  A
      // substring match would accept "chunked, gzip" — where the body
      // framing is gzip-of-chunks — as plain chunked (a desync vector
      // behind proxies honoring the full coding list), and
      // "gzip, chunked" would hand still-compressed bytes up.
      if (!ci_equal(value, "chunked")) {
        return false;
      }
      out->chunked = true;
    } else if (ci_equal(name, "connection")) {
      if (ci_contains(value, "close")) {
        out->keep_alive = 0;
      } else if (ci_contains(value, "keep-alive")) {
        out->keep_alive = 1;
      }
    }
    out->headers.emplace_back(std::move(name), std::move(value));
  }
  return true;
}

// Shared body framing: chunked (resumable via `state`) or
// Content-Length.  The no-framing case stays with the callers (request:
// empty body; response: unsupported read-until-close).
ParseError parse_framed_body(IOBuf* source, size_t body_off, bool chunked,
                             uint64_t content_len, IOBuf* body,
                             std::shared_ptr<void>* state) {
  if (chunked) {
    std::shared_ptr<ChunkedState> st;
    if (state != nullptr && *state != nullptr) {
      st = std::static_pointer_cast<ChunkedState>(*state);
    } else {
      st = std::make_shared<ChunkedState>();
      st->pos = body_off;
      if (state != nullptr) {
        *state = st;
      }
    }
    size_t consumed = 0;
    const ParseError rc = parse_chunked(*source, st.get(), body, &consumed);
    if (rc == ParseError::kOk) {
      if (state != nullptr) {
        state->reset();
      }
      source->pop_front(consumed);
    } else if (rc == ParseError::kCorrupted && state != nullptr) {
      state->reset();
    }
    return rc;
  }
  const uint64_t total = static_cast<uint64_t>(body_off) + content_len;
  if (source->size() < total) {
    return ParseError::kNotEnoughData;
  }
  source->pop_front(body_off);
  source->cutn(body, content_len);
  return ParseError::kOk;
}

ParseError http_parse_request(IOBuf* source, HttpRequest* req, IOBuf* body,
                              std::shared_ptr<void>* state) {
  // Header window only — the non-chunked body is cut straight from the
  // IOBuf without ever being copied here (a 1GB upload must not be
  // re-copied on every parse retry).
  const size_t scan = std::min(source->size(), kMaxHeaderBytes);
  std::string window;
  window.resize(scan);
  source->copy_to(window.data(), window.size());

  const size_t hdr_end = window.find("\r\n\r\n");
  if (hdr_end == std::string::npos) {
    return scan >= kMaxHeaderBytes ? ParseError::kCorrupted
                                   : ParseError::kNotEnoughData;
  }
  if (hdr_end + 4 > kMaxHeaderBytes) {
    return ParseError::kCorrupted;
  }

  // ---- request line ----------------------------------------------------
  const size_t line_end = window.find("\r\n");
  const std::string line = window.substr(0, line_end);
  const size_t sp1 = line.find(' ');
  const size_t sp2 = line.rfind(' ');
  if (sp1 == std::string::npos || sp2 == sp1) {
    return ParseError::kCorrupted;
  }
  req->verb = line.substr(0, sp1);
  const std::string version = line.substr(sp2 + 1);
  if (version.rfind("HTTP/", 0) != 0) {
    return ParseError::kCorrupted;
  }
  req->http_1_0 = version == "HTTP/1.0";
  std::string target = line.substr(sp1 + 1, sp2 - sp1 - 1);
  const size_t frag = target.find('#');
  if (frag != std::string::npos) {
    target.resize(frag);
  }
  const size_t qmark = target.find('?');
  std::string raw_path = target;
  if (qmark != std::string::npos) {
    raw_path = target.substr(0, qmark);
    req->query_string = target.substr(qmark + 1);
    parse_query_string(req->query_string, &req->queries);
  }
  if (!percent_decode(raw_path, &req->path, false)) {
    return ParseError::kCorrupted;
  }

  // ---- headers (shared scan) ---------------------------------------------
  HeaderScan hs;
  if (!parse_header_block(window, line_end + 2, hdr_end, &hs)) {
    return ParseError::kCorrupted;
  }
  req->headers = std::move(hs.headers);
  req->chunked = hs.chunked;
  if (hs.keep_alive >= 0) {
    req->keep_alive = hs.keep_alive != 0;
  } else if (req->http_1_0) {
    req->keep_alive = false;
  }
  // A message with BOTH is a smuggling vector: reject (RFC 7230 §3.3.3).
  if (req->chunked && hs.have_content_length) {
    return ParseError::kCorrupted;
  }

  // ---- body (no framing headers = no body, for requests) -----------------
  return parse_framed_body(source, hdr_end + 4, req->chunked,
                           hs.have_content_length ? hs.content_len : 0,
                           body, state);
}

const std::string* HttpResponse::header(const std::string& name) const {
  return http_find_header(headers, name);
}

ParseError http_parse_response(IOBuf* source, HttpResponse* resp,
                               IOBuf* body, std::shared_ptr<void>* state,
                               bool head_only) {
  const size_t scan = std::min(source->size(), kMaxHeaderBytes);
  std::string window;
  window.resize(scan);
  source->copy_to(window.data(), window.size());

  const size_t hdr_end = window.find("\r\n\r\n");
  if (hdr_end == std::string::npos) {
    return scan >= kMaxHeaderBytes ? ParseError::kCorrupted
                                   : ParseError::kNotEnoughData;
  }
  if (hdr_end + 4 > kMaxHeaderBytes) {
    return ParseError::kCorrupted;
  }

  // ---- status line -------------------------------------------------------
  const size_t line_end = window.find("\r\n");
  const std::string line = window.substr(0, line_end);
  if (line.rfind("HTTP/1.", 0) != 0 || line.size() < 12) {
    return ParseError::kCorrupted;
  }
  resp->http_1_0 = line[7] == '0';
  if (line[8] != ' ' || line[9] < '1' || line[9] > '5' ||
      line[10] < '0' || line[10] > '9' || line[11] < '0' ||
      line[11] > '9') {
    return ParseError::kCorrupted;
  }
  if (line.size() > 12 && line[12] != ' ') {
    return ParseError::kCorrupted;  // "HTTP/1.1 2004" / "200X" forms
  }
  resp->status = (line[9] - '0') * 100 + (line[10] - '0') * 10 +
                 (line[11] - '0');
  resp->reason = line.size() > 13 ? line.substr(13) : std::string();

  // ---- headers (the shared smuggling-strict scan) -------------------------
  HeaderScan hs;
  if (!parse_header_block(window, line_end + 2, hdr_end, &hs)) {
    return ParseError::kCorrupted;
  }
  resp->headers = std::move(hs.headers);
  resp->chunked = hs.chunked;
  resp->keep_alive =
      hs.keep_alive >= 0 ? hs.keep_alive != 0 : !resp->http_1_0;
  if (resp->chunked && hs.have_content_length) {
    return ParseError::kCorrupted;
  }

  // ---- body --------------------------------------------------------------
  const size_t body_off = hdr_end + 4;
  const bool bodyless = head_only || resp->status == 204 ||
                        resp->status == 304 ||
                        (resp->status >= 100 && resp->status < 200);
  if (bodyless) {
    source->pop_front(body_off);
    return ParseError::kOk;
  }
  if (!resp->chunked && !hs.have_content_length) {
    // Read-until-close framing: out of scope (see header).
    return ParseError::kCorrupted;
  }
  return parse_framed_body(source, body_off, resp->chunked,
                           hs.content_len, body, state);
}

std::string http_status_line(int status) {
  const char* reason = "OK";
  switch (status) {
    case 200: reason = "OK"; break;
    case 204: reason = "No Content"; break;
    case 400: reason = "Bad Request"; break;
    case 403: reason = "Forbidden"; break;
    case 404: reason = "Not Found"; break;
    case 405: reason = "Method Not Allowed"; break;
    case 500: reason = "Internal Server Error"; break;
    case 501: reason = "Not Implemented"; break;
    case 503: reason = "Service Unavailable"; break;
    default: reason = "Unknown"; break;
  }
  return "HTTP/1.1 " + std::to_string(status) + " " + reason;
}

}  // namespace trpc
