// HTTP/1.x message parsing — request lines, headers, chunked bodies, URIs.
//
// Parity: the reference's HTTP front (/root/reference/src/brpc/details/
// http_message.*, http_parser.* (vendored node parser), uri.*,
// http_header.*, ~6,500 LoC with transcoding).  Redesigned condensed: a
// re-scanning parser over the accumulating input buffer (the InputMessenger
// retries parse as bytes arrive, so per-connection parser state is
// unnecessary), strict on the invariants that desync framing — duplicate
// Content-Length, malformed chunk sizes, header caps — and tolerant
// elsewhere.  Unit-testable from raw bytes without sockets (the reference's
// protocol-unit style, test/brpc_http_parser_unittest.cpp).
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <utility>
#include <vector>

#include "base/iobuf.h"
#include "net/protocol.h"

namespace trpc {

struct HttpRequest {
  std::string verb;          // GET / POST / ...
  std::string path;          // percent-decoded, query stripped
  std::string query_string;  // raw (undecoded) query part
  bool http_1_0 = false;
  bool keep_alive = true;    // Connection semantics (1.0 defaults close)
  bool chunked = false;      // body arrived chunked
  // Original-case names; lookup is case-insensitive.
  std::vector<std::pair<std::string, std::string>> headers;
  std::vector<std::pair<std::string, std::string>> queries;  // decoded

  // nullptr when absent; case-insensitive on name.
  const std::string* header(const std::string& name) const;
  // nullptr when absent ("?k" alone yields an empty value, not nullptr).
  const std::string* query(const std::string& name) const;
};

// Cuts ONE complete request off `source` into *req + *body.
// kNotEnoughData leaves `source` untouched.  `state` (may be null) lets
// chunked bodies resume scanning where the previous attempt stopped
// instead of re-walking the whole buffer on every retry; callers pass the
// same slot across retries (Socket::parse_state) and it is reset when a
// message completes or fails.
ParseError http_parse_request(IOBuf* source, HttpRequest* req, IOBuf* body,
                              std::shared_ptr<void>* state = nullptr);

// Case-insensitive ASCII compare / header lookup — THE header-matching
// semantics, shared by both directions and the HTTP client.
bool http_ci_equal(const std::string& a, const std::string& b);
const std::string* http_find_header(
    const std::vector<std::pair<std::string, std::string>>& headers,
    const std::string& name);

// Percent-decodes `in` ('+' becomes space when for_query).  Returns false
// on malformed escapes (which a strict parser rejects).
bool percent_decode(const std::string& in, std::string* out, bool for_query);

// Splits "a=1&b=%20c" into decoded pairs (malformed pairs are skipped).
void parse_query_string(
    const std::string& qs,
    std::vector<std::pair<std::string, std::string>>* out);

// Response head for the given status; body appended by the caller.
std::string http_status_line(int status);

// ---- client direction ----------------------------------------------------

struct HttpResponse {
  int status = 0;
  std::string reason;
  bool http_1_0 = false;
  bool keep_alive = true;
  bool chunked = false;
  std::vector<std::pair<std::string, std::string>> headers;

  const std::string* header(const std::string& name) const;
};

// Cuts ONE complete response off `source` (status line, headers, body by
// Content-Length / chunked / bodyless-status rules).  Same contract and
// resumable-chunked `state` slot as http_parse_request.  `head_only`
// marks a HEAD-request response (headers only, whatever Content-Length
// claims).  Read-until-close framing (no CL, no TE on an HTTP/1.0-style
// response) is reported as kCorrupted — this client speaks 1.1 and every
// modern server frames explicitly.
ParseError http_parse_response(IOBuf* source, HttpResponse* resp,
                               IOBuf* body,
                               std::shared_ptr<void>* state = nullptr,
                               bool head_only = false);

}  // namespace trpc
