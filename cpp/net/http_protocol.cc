#include "net/http_protocol.h"

#include <cstring>
#include <string>

#include <memory>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/sync.h"
#include "net/server.h"
#include "net/socket.h"

namespace trpc {

namespace {

constexpr size_t kMaxHeaderBytes = 64 * 1024;

bool looks_like_http(const IOBuf& buf) {
  char start[8] = {};
  const size_t n = buf.copy_to(start, sizeof(start));
  static const char* kMethods[] = {"GET ",    "POST ",  "PUT ",
                                   "DELETE ", "HEAD ",  "OPTIONS ",
                                   "PATCH "};
  for (const char* m : kMethods) {
    // Prefix match on however many bytes we have: "G" alone must count as
    // possibly-HTTP so the messenger waits instead of killing the socket.
    const size_t l = std::min(n, strlen(m));
    if (l > 0 && memcmp(start, m, l) == 0) {
      return true;
    }
  }
  return false;
}

// InputMessage reuse for HTTP: meta.method carries "VERB PATH"; payload is
// the body.
ParseError http_parse(IOBuf* source, InputMessage* out) {
  if (source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (!looks_like_http(*source)) {
    return ParseError::kTryOtherProtocol;
  }
  const size_t scan = std::min(source->size(), kMaxHeaderBytes);
  std::string head;
  head.resize(scan);
  source->copy_to(head.data(), scan);
  const size_t hdr_end = head.find("\r\n\r\n");
  if (hdr_end == std::string::npos) {
    return scan >= kMaxHeaderBytes ? ParseError::kCorrupted
                                   : ParseError::kNotEnoughData;
  }
  // Request line.
  const size_t line_end = head.find("\r\n");
  const std::string line = head.substr(0, line_end);
  const size_t sp1 = line.find(' ');
  const size_t sp2 = line.rfind(' ');
  if (sp1 == std::string::npos || sp2 <= sp1) {
    return ParseError::kCorrupted;
  }
  const std::string verb = line.substr(0, sp1);
  const std::string path = line.substr(sp1 + 1, sp2 - sp1 - 1);
  // Content-Length: matched as a header NAME (leading "\r\n"), never as a
  // substring of another header or the request line; capped so a hostile
  // value can neither wrap the total nor buffer unboundedly.
  constexpr uint64_t kMaxBody = 1ull << 30;  // 1 GB
  uint64_t content_len = 0;
  {
    std::string lower = head.substr(0, hdr_end + 2);
    for (char& c : lower) {
      c = static_cast<char>(tolower(c));
    }
    const size_t pos = lower.find("\r\ncontent-length:");
    if (pos != std::string::npos) {
      char* end = nullptr;
      content_len = strtoull(lower.c_str() + pos + 17, &end, 10);
      if (content_len > kMaxBody) {
        return ParseError::kCorrupted;
      }
    }
  }
  const uint64_t total = static_cast<uint64_t>(hdr_end) + 4 + content_len;
  if (source->size() < total) {
    return ParseError::kNotEnoughData;
  }
  source->pop_front(hdr_end + 4);
  source->cutn(&out->payload, content_len);
  out->meta.type = RpcMeta::kRequest;
  out->meta.method = verb + " " + path;
  return ParseError::kOk;
}

void http_respond(SocketId sid, int status, const std::string& reason,
                  const std::string& content_type, const std::string& body) {
  std::string head = "HTTP/1.1 " + std::to_string(status) + " " + reason +
                     "\r\nContent-Type: " + content_type +
                     "\r\nContent-Length: " + std::to_string(body.size()) +
                     "\r\nConnection: keep-alive\r\n\r\n";
  IOBuf out;
  out.append(head);
  out.append(body);
  SocketRef s(Socket::Address(sid));
  if (s) {
    s->Write(std::move(out));
  }
}

void http_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  const size_t sp = msg.meta.method.find(' ');
  std::string path = msg.meta.method.substr(sp + 1);
  const size_t q = path.find('?');
  if (q != std::string::npos) {
    path = path.substr(0, q);
  }
  std::string body, ctype = "text/plain";
  if (srv != nullptr && builtin_http_dispatch(srv, path, &body, &ctype)) {
    http_respond(msg.socket, 200, "OK", ctype, body);
    return;
  }
  // RPC-over-HTTP: POST /Service.Method with the request payload as body
  // (parity: brpc's http access to pb services).
  const std::string rpc_name = path.empty() ? "" : path.substr(1);
  const Server::MethodProperty* prop =
      srv != nullptr ? srv->find_method(rpc_name) : nullptr;
  if (prop == nullptr) {
    http_respond(msg.socket, 404, "Not Found", "text/plain",
                 "no such path or method: " + path + "\n");
    return;
  }
  // Admission gate — same limiter instance as the tstd path, so the
  // configured per-method limit holds regardless of serving protocol.
  std::shared_ptr<ConcurrencyLimiter> limiter = prop->limiter;
  if (limiter != nullptr && !limiter->on_request()) {
    http_respond(msg.socket, 503, "Service Unavailable", "text/plain",
                 "rejected by concurrency limiter\n");
    return;
  }
  auto* cntl = new Controller();
  cntl->set_method(rpc_name);
  auto* response = new IOBuf();
  const SocketId sid = msg.socket;
  const int64_t start_us = monotonic_time_us();
  std::shared_ptr<LatencyRecorder> lat = prop->latency;
  // HTTP/1.1 has no correlation id: responses must leave in request order.
  // The read fiber parks on this latch until done() fires, so even an
  // asynchronous handler cannot let a later pipelined response overtake.
  srv->in_flight.fetch_add(1, std::memory_order_acq_rel);
  auto latch = std::make_shared<CountdownEvent>(1);
  Closure done = [sid, cntl, response, srv, lat, start_us, latch, limiter] {
    if (limiter != nullptr) {
      limiter->on_response(monotonic_time_us() - start_us, cntl->Failed());
    }
    if (cntl->Failed()) {
      http_respond(sid, 500, "Internal Server Error", "text/plain",
                   cntl->error_text() + "\n");
    } else {
      http_respond(sid, 200, "OK", "application/octet-stream",
                   response->to_string());
    }
    if (lat != nullptr) {
      *lat << (monotonic_time_us() - start_us);
    }
    delete response;
    delete cntl;
    srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    srv->in_flight.fetch_sub(1, std::memory_order_acq_rel);
    latch->signal();
  };
  prop->handler(cntl, msg.payload, response, std::move(done));
  latch->wait(-1);
}

void http_process_response(InputMessage&&) {
  // Server-side only for now; the RPC client speaks tstd.
}

}  // namespace

void register_http_protocol() {
  static int once = [] {
    Protocol p = {"http", http_parse, http_process_request,
                  http_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

}  // namespace trpc
