#include "net/http_protocol.h"
#include "net/progressive.h"

#include <cstring>
#include <memory>
#include <string>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/sync.h"
#include "net/http_message.h"
#include "net/server.h"
#include "net/socket.h"

namespace trpc {

namespace {

bool looks_like_http(const IOBuf& buf) {
  char start[8] = {};
  const size_t n = buf.copy_to(start, sizeof(start));
  static const char* kMethods[] = {"GET ",    "POST ",  "PUT ",
                                   "DELETE ", "HEAD ",  "OPTIONS ",
                                   "PATCH "};
  for (const char* m : kMethods) {
    // Prefix match on however many bytes we have: "G" alone must count as
    // possibly-HTTP so the messenger waits instead of killing the socket.
    const size_t l = std::min(n, strlen(m));
    if (l > 0 && memcmp(start, m, l) == 0) {
      return true;
    }
  }
  return false;
}

const char kHttpStateTag = 0;  // parse_state owner tag (see socket.h)

ParseError http_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (!looks_like_http(*source)) {
    return ParseError::kTryOtherProtocol;
  }
  std::shared_ptr<void>* state = nullptr;
  if (sock != nullptr) {
    if (sock->parse_state_owner != &kHttpStateTag &&
        sock->parse_state != nullptr) {
      // Another protocol keeps in-flight state on this connection (e.g.
      // the rtmp handshake machine, which spans several probe rounds
      // before its first complete message pins the socket).  Destroying
      // it from a PROBE would corrupt that protocol mid-parse — and a
      // connection someone else has state on is not HTTP anyway.
      return ParseError::kTryOtherProtocol;
    }
    state = &sock->parse_state;
  }
  auto req = std::make_shared<HttpRequest>();
  const ParseError rc =
      http_parse_request(source, req.get(), &out->payload, state);
  if (sock != nullptr) {
    sock->parse_state_owner =
        sock->parse_state != nullptr ? &kHttpStateTag : nullptr;
  }
  if (rc != ParseError::kOk) {
    return rc;
  }
  out->meta.type = RpcMeta::kRequest;
  out->meta.method = req->verb + " " + req->path;
  out->ctx = std::move(req);
  return ParseError::kOk;
}

// One header-block assembler for every response form; `framing` is the
// body-framing header ("Content-Length: N" / "Transfer-Encoding:
// chunked").
std::string http_head(int status, const std::string& content_type,
                      const std::string& framing, bool keep_alive) {
  return http_status_line(status) + "\r\nContent-Type: " + content_type +
         "\r\n" + framing +
         (keep_alive ? "\r\nConnection: keep-alive\r\n\r\n"
                     : "\r\nConnection: close\r\n\r\n");
}

// Response write; honors HEAD (headers only) and Connection semantics
// (keep-alive by default, flush-then-close on `close`).
void http_respond(SocketId sid, const HttpRequest& req, int status,
                  const std::string& content_type, const std::string& body) {
  std::string head =
      http_head(status, content_type,
                "Content-Length: " + std::to_string(body.size()),
                req.keep_alive);
  IOBuf out;
  out.append(head);
  if (req.verb != "HEAD") {
    out.append(body);
  }
  SocketRef s(Socket::Address(sid));
  if (s) {
    // close_after rides the write node: the socket fails itself only once
    // THIS response has flushed, immune to races with earlier drains.
    s->Write(std::move(out), /*close_after=*/!req.keep_alive);
  }
}

void http_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto req = std::static_pointer_cast<HttpRequest>(msg.ctx);
  CHECK(req != nullptr);
  // An installed authenticator gates EVERY serving protocol: HTTP/h2
  // clients cannot present a kAuth credential, so only the liveness
  // probe stays open (otherwise auth would be bypassable by speaking a
  // different protocol to the same port).
  if (srv != nullptr && srv->authenticator() != nullptr &&
      !sock->auth_ok.load(std::memory_order_acquire) &&
      req->path != "/health") {
    http_respond(msg.socket, *req, 403, "text/plain",
                 "connection not authenticated\n");
    return;
  }

  // Interceptor gate — BEFORE builtin dispatch too (an access policy
  // must cover the observability pages; /health stays open like auth).
  if (srv != nullptr && req->path != "/health") {
    int ec = 0;
    std::string et;
    if (!srv->accept_request(req->path, sock->remote(), &ec, &et)) {
      http_respond(msg.socket, *req, 403, "text/plain",
                   "error " + std::to_string(ec) + ": " + et + "\n");
      return;
    }
  }

  // 1. Builtin observability endpoints.
  std::string body;
  std::string ctype = "text/plain";
  int status = 200;
  if (srv != nullptr &&
      builtin_http_dispatch(srv, *req, msg.payload, &status, &body, &ctype)) {
    http_respond(msg.socket, *req, status, ctype, body);
    return;
  }

  // 2. Restful patterns, then direct /Service.Method access (parity:
  //    RestfulMap + http access to pb services).
  const Server::MethodProperty* prop = nullptr;
  std::string rpc_name;
  if (srv != nullptr) {
    prop = srv->find_restful(req->path, &rpc_name);
    if (prop == nullptr) {
      rpc_name = req->path.empty() ? "" : req->path.substr(1);
      prop = srv->find_method(rpc_name);
    }
  }
  if (prop == nullptr) {
    http_respond(msg.socket, *req, 404, "text/plain",
                 "no such path or method: " + req->path + "\n");
    return;
  }
  // Admission gate — same limiter instance as the tstd path, so the
  // configured per-method limit holds regardless of serving protocol.
  std::shared_ptr<ConcurrencyLimiter> limiter = prop->limiter;
  if (limiter != nullptr && !limiter->on_request()) {
    http_respond(msg.socket, *req, 503, "text/plain",
                 "rejected by concurrency limiter\n");
    return;
  }

  auto* cntl = new Controller();
  cntl->set_method(rpc_name);
  cntl->call().sl_pool = srv->session_data_pool();
  auto* response = new IOBuf();
  const SocketId sid = msg.socket;
  const int64_t start_us = monotonic_time_us();
  std::shared_ptr<LatencyRecorder> lat = prop->latency;
  // HTTP/1.1 has no correlation id: responses must leave in request order.
  // The read fiber parks on this latch until done() fires, so even an
  // asynchronous handler cannot let a later pipelined response overtake.
  srv->in_flight.fetch_add(1, std::memory_order_acq_rel);
  auto latch = std::make_shared<CountdownEvent>(1);
  Closure done = [sid, req, cntl, response, srv, lat, start_us, latch,
                  limiter] {
    if (limiter != nullptr) {
      limiter->on_response(monotonic_time_us() - start_us, cntl->Failed());
    }
    bool ordering_released = false;
    if (cntl->Failed()) {
      http_respond(sid, *req, 500, "text/plain", cntl->error_text() + "\n");
    } else if (cntl->progressive_attachment() != nullptr) {
      // Progressive body: flush the headers (chunked) now; the handler
      // keeps Write()ing the attachment from any fiber.  The connection's
      // response ordering (the latch the read fiber parks on) is released
      // only when the attachment CLOSES — HTTP/1.1 responses cannot
      // interleave, so a pipelined request must wait out the stream.
      std::shared_ptr<ProgressiveAttachment> pa =
          cntl->progressive_attachment();
      IOBuf out;
      out.append(http_head(200, "application/octet-stream",
                           "Transfer-Encoding: chunked", req->keep_alive));
      if (req->verb == "HEAD") {
        // Headers only; the attachment's body is discarded (http_respond
        // parity) and the ordering latch releases normally below.
        pa->abandon();
        SocketRef s(Socket::Address(sid));
        if (s) {
          s->Write(std::move(out), /*close_after=*/!req->keep_alive);
        }
      } else {
        // bind() writes the headers itself, under the attachment's lock:
        // the socket publishes only after them, and the latch is owned
        // by the attachment until it closes.
        pa->bind(sid, req->keep_alive, latch, std::move(out));
        ordering_released = true;
      }
    } else {
      http_respond(sid, *req, 200, "application/octet-stream",
                   response->to_string());
    }
    if (lat != nullptr) {
      *lat << (monotonic_time_us() - start_us);
    }
    delete response;
    if (cntl->call().sl_data != nullptr) {
      cntl->call().sl_pool->Return(cntl->call().sl_data);
    }
    delete cntl;
    srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    srv->in_flight.fetch_sub(1, std::memory_order_acq_rel);
    if (!ordering_released) {
      latch->signal();
    }
  };
  prop->handler(cntl, msg.payload, response, std::move(done));
  latch->wait(-1);
}

void http_process_response(InputMessage&&) {
  // Server-side only for now; the RPC client speaks tstd.
}

}  // namespace

void register_http_protocol() {
  static int once = [] {
    Protocol p = {"http", http_parse, http_process_request,
                  http_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

}  // namespace trpc
