// HTTP/1.x serving protocol — builtin services + RPC-over-HTTP + restful.
//
// Parity: brpc's http support (/root/reference/src/brpc/policy/
// http_rpc_protocol.cpp + builtin services server.cpp:501-604): the same
// port serves RPC framing AND HTTP — the messenger tries protocols in
// registration order and pins the match (input_messenger.cpp:83).
// Request parsing (chunked bodies, URIs, percent-decoding) lives in
// net/http_message.*; this layer routes: builtin endpoints, restful
// patterns (Server::MapRestful), then POST /Service.Method RPC access.
#pragma once

#include <string>

#include "net/http_message.h"
#include "net/protocol.h"

namespace trpc {

// Registers the HTTP protocol (idempotent).  Server::Start calls this.
void register_http_protocol();

// Builtin service dispatch (/vars, /status, /flags, ...).  Returns true
// when the path is a builtin; fills status/body/content_type.
class Server;
bool builtin_http_dispatch(Server* srv, const HttpRequest& req,
                           const IOBuf& payload, int* status,
                           std::string* body, std::string* content_type);

}  // namespace trpc
