// Minimal HTTP/1.1 server-side protocol.
//
// Parity: brpc's http support (/root/reference/src/brpc/policy/
// http_rpc_protocol.cpp + builtin services server.cpp:501-604): the same
// port serves RPC framing AND HTTP — the messenger tries protocols in
// registration order and pins the match (input_messenger.cpp:83).
// Re-designed minimal: request-line + headers + Content-Length bodies;
// keep-alive; no chunked/h2 yet.
#pragma once

#include "net/protocol.h"

namespace trpc {

// Registers the HTTP protocol (idempotent).  Server::Start calls this.
void register_http_protocol();

// Builtin service dispatch: returns true if `path` was handled.
class Server;
bool builtin_http_dispatch(Server* srv, const std::string& path,
                           std::string* body, std::string* content_type);

}  // namespace trpc
