#include "net/ici_transport.h"

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <vector>

#include "base/device_arena.h"
#include "base/logging.h"
#include "base/rand.h"
#include "base/time.h"
#include "net/rma.h"

namespace trpc {

namespace {

constexpr uint32_t kIciMaxSlots = 1024;
constexpr uint32_t kIciMaxSlabs = 64;  // per side
constexpr uint32_t kSlabNameLen = 48;
// Bumped from "...2T": the segment grew the per-side rma window rkey
// words (net/rma.h) — a mixed-version pair must fail the handshake.
constexpr uint64_t kIciMagic = 0x5452504943493354ull;  // "TRPICI3T"

// ---- ring geometry (client proposes, server validates) ------------------

struct Geometry {
  uint32_t block_size = 64 * 1024;
  uint32_t slots = 16;
  // Receive-pool cap per direction (block_pool growth bound): the biggest
  // message a connection can carry is ≈ (max_blocks - slots) × block_size,
  // because a frame's blocks stay pinned until it parses whole.
  uint32_t max_blocks = 1024;
};

std::mutex& geom_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
Geometry& geom() {
  static Geometry* g = new Geometry();
  return *g;
}

bool geometry_valid(uint32_t block_size, uint32_t slots,
                    uint32_t max_blocks) {
  return block_size >= 4096 && block_size <= 4 * 1024 * 1024 &&
         slots >= 2 && slots <= kIciMaxSlots &&
         (slots & (slots - 1)) == 0 && max_blocks >= slots &&
         max_blocks <= kIciMaxSlabs * slots &&
         static_cast<uint64_t>(block_size) * slots <= 256ull * 1024 * 1024;
}

// ---- slab registration seam ---------------------------------------------

struct Registrar {
  int (*reg)(void*, size_t, void*, uint64_t*) = nullptr;
  void (*unreg)(void*, size_t, void*, uint64_t) = nullptr;
  void* ctx = nullptr;
};
std::mutex& reg_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
Registrar& registrar() {
  static Registrar* r = new Registrar();
  return *r;
}
std::atomic<size_t>& registered_slabs() {
  static std::atomic<size_t>* n = new std::atomic<size_t>(0);
  return *n;
}

// Trampolines DeviceArena registration through the swappable registrar.
int slab_register_tramp(void* base, size_t len, void*, uint64_t* handle) {
  Registrar r;
  {
    std::lock_guard<std::mutex> g(reg_mu());
    r = registrar();
  }
  if (r.reg != nullptr && r.reg(base, len, r.ctx, handle) != 0) {
    return -1;
  }
  if (r.reg == nullptr) {
    *handle = registered_slabs().load(std::memory_order_relaxed);
  }
  registered_slabs().fetch_add(1, std::memory_order_relaxed);
  return 0;
}
void slab_unregister_tramp(void* base, size_t len, void*, uint64_t handle) {
  Registrar r;
  {
    std::lock_guard<std::mutex> g(reg_mu());
    r = registrar();
  }
  if (r.unreg != nullptr) {
    r.unreg(base, len, r.ctx, handle);
  }
  registered_slabs().fetch_sub(1, std::memory_order_relaxed);
}

// ---- sender-owned staging slabs ------------------------------------------
// Registered, shm-published payload memory.  Descriptor meta encoding:
// bit 63 = sender-owned; bits 40..59 = slab ordinal; bits 0..39 = offset.
// Normal (posted-block) metas are (slab<<32)|offset with slab < 64, so
// bit 63 is never set on them.

constexpr uint64_t kStageBit = 1ull << 63;
constexpr uint64_t kStageOffsetMask = (1ull << 40) - 1;

inline uint64_t stage_meta(uint32_t ordinal, uint64_t offset) {
  return kStageBit | (static_cast<uint64_t>(ordinal) << 40) |
         (offset & kStageOffsetMask);
}

// One mapped staging slab, REF-COUNTED: the registry (local slabs) and
// every conn cache / wrapped-range consumer (RxStageCtx) co-own it, so
// neither a dying connection nor ici_staging_free can munmap under a
// live reader; the memory unmaps when the LAST reference drops.
struct StageMapping {
  char* base = nullptr;
  size_t len = 0;
  bool owned = false;  // false: alias of another mapping (never unmapped)
  ~StageMapping() {
    if (base != nullptr && owned) {
      munmap(base, len);
    }
  }
};

struct StagingSlab {
  std::shared_ptr<StageMapping> mapping;  // owned=true
  uint32_t ordinal = 0;
  uint64_t reg_handle = 0;
  std::string name;
};

std::mutex& stage_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::vector<StagingSlab>& stage_slabs() {
  static auto* v = new std::vector<StagingSlab>();
  return *v;
}
std::atomic<uint64_t>& zc_wrs_total() {
  static auto* n = new std::atomic<uint64_t>(0);
  return *n;
}
std::atomic<uint64_t>& zc_bytes_total() {
  static auto* n = new std::atomic<uint64_t>(0);
  return *n;
}

std::string stage_shm_name(int pid, uint32_t ordinal) {
  char name[64];
  snprintf(name, sizeof(name), "/trpc_stage_%d_%u", pid, ordinal);
  return name;
}

// Is [p, p+len) inside one of THIS process's staging slabs?  Fills
// *ordinal/*offset when so.  Linear scan: slab count is tiny and this
// only runs once per multi-KB WR.
bool staging_of(const char* p, size_t len, uint32_t* ordinal,
                uint64_t* offset) {
  std::lock_guard<std::mutex> g(stage_mu());
  for (const StagingSlab& s : stage_slabs()) {
    char* base = s.mapping != nullptr ? s.mapping->base : nullptr;
    if (base != nullptr && p >= base && p + len <= base + s.mapping->len) {
      *ordinal = s.ordinal;
      *offset = static_cast<uint64_t>(p - base);
      return true;
    }
  }
  return false;
}

// ---- shared control segment ---------------------------------------------

// One one-way DMA lane.  The RECEIVER posts recv blocks — (slab,offset)
// descriptors into its own registered slabs, the lkey analogue — to
// post_ring; the SENDER claims them strictly in order, DMAs payload into
// the peer slab, and publishes a {meta,len} descriptor.  The receiver
// bumps desc_consumed once it owns the data; that is the sender's send
// completion (sbuf release point).  Cursors are free-running uint64s.
struct IciDesc {
  uint64_t meta;  // slab_id<<32 | offset  (echoes the claimed post entry)
  uint32_t len;
  uint32_t pad;
};

struct IciDir {
  alignas(64) std::atomic<uint64_t> post_head;      // receiver bumps
  alignas(64) std::atomic<uint64_t> desc_head;      // sender bumps
  alignas(64) std::atomic<uint64_t> desc_consumed;  // receiver bumps
  alignas(64) uint64_t post_ring[kIciMaxSlots];     // (slab,offset) metas
  IciDesc desc_ring[kIciMaxSlots];
};

// Each side's receive pool is a set of uniformly-sized registered slabs,
// published by name so the peer can map them lazily (block_pool growth:
// new slabs appear while the connection runs).
struct SlabTable {
  std::atomic<uint32_t> count;
  char names[kIciMaxSlabs][kSlabNameLen];
};

struct IciSegment {
  uint64_t magic;
  uint32_t block_size;
  uint32_t slots;
  uint32_t max_blocks;
  uint32_t pad;
  std::atomic<int32_t> client_pid;
  std::atomic<int32_t> server_pid;
  std::atomic<uint64_t> client_beat;
  std::atomic<uint64_t> server_beat;
  // One-sided plane (net/rma.h): each side publishes its registered
  // receive window's rkey (release; 0 while absent/disabled).  Large
  // copy-mode bodies are then WRITTEN into the peer window by parallel
  // rail fibers instead of serializing through the poller's ring DMA.
  std::atomic<uint64_t> client_rma_rkey;
  std::atomic<uint64_t> server_rma_rkey;
  SlabTable client_slabs;  // client's receive pool (server DMAs into these)
  SlabTable server_slabs;
  IciDir c2s;  // client sends, server receives
  IciDir s2c;
};

void* map_shm(const char* name, size_t len) {
  const int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) {
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < len) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return mem == MAP_FAILED ? nullptr : mem;
}

}  // namespace

// ---- connection state ----------------------------------------------------

// Receive-pool keepalive: slabs must outlive every IOBuf block wrapped over
// them, even after the connection is gone (a consumer may sit on received
// bytes indefinitely).  Deleter contexts share ownership.
struct IciRx {
  std::unique_ptr<DeviceArena> arena;
  std::atomic<uint64_t> wrapped{0};  // blocks held by consumers
};

void ici_conn_release_name(const std::string& name);

struct IciConn {
  IciSegment* seg = nullptr;
  std::string name;
  bool is_client = false;
  bool creator = false;
  bool unlink_on_close = false;
  uint32_t block_size = 0;
  uint32_t slots = 0;
  uint32_t max_blocks = 0;

  // My receive pool + the FIFO of blocks currently posted (post entries
  // are claimed by the sender strictly in order, so descriptor n resolves
  // to the n-th posted block).
  std::shared_ptr<IciRx> rx;
  std::deque<Block*> posted_fifo;  // poller-owned
  uint32_t repost_deficit = 0;     // posts deferred on pool exhaustion

  // Peer receive slabs mapped as DMA targets (lazily, as the peer's pool
  // grows).  Poller-owned.
  std::vector<char*> tx_slabs;
  size_t tx_slab_len = 0;

  // Local send queue: the writer fiber posts WRs (each ≤ block_size bytes
  // of IOBuf refs, uncopied); the poller is the DMA engine.  SPSC.
  // sq_meta parallels sq: 0 = copy-mode WR; a kStageBit-tagged value =
  // sender-owned zero-copy WR (the whole payload in one descriptor).
  std::vector<IOBuf> sq;
  std::vector<uint64_t> sq_meta;
  alignas(64) std::atomic<uint64_t> sq_head{0};  // writer bumps
  // Staged (unpublished) sq_head, owned by the socket's single writer
  // role; UINT64_MAX = nothing staged.  cut_from_iobuf posts WRs here and
  // Transport::flush publishes once per drain — the poller (DMA engine)
  // sees one doorbell per KeepWrite sweep instead of one per WR.
  uint64_t sq_staged = UINT64_MAX;
  alignas(64) std::atomic<uint64_t> sq_tail{0};  // poller bumps
  // DMA'd-but-uncompleted source refs, indexed by descriptor slot
  // (_sbuf parity: released only when the peer's desc_consumed passes).
  std::vector<IOBuf> sbuf;
  uint64_t sbuf_released = 0;  // poller-local completion cursor
  uint64_t post_tail = 0;      // poller-local posted-credit cursor

  // Receive staging the read fiber drains (poller appends wrapped blocks).
  std::mutex rx_mu;
  IOBuf rx_pending;
  uint64_t rx_desc_tail = 0;  // poller-local: descriptors wrapped
  uint64_t rx_ack = 0;        // poller-local: desc_consumed published
  // Copy-mode descriptors received == posted entries the PEER has claimed
  // (it claims strictly in order, one per copy-mode WR).  This — not
  // desc_head — bounds post-ring slot reuse: sender-owned (zero-copy)
  // descriptors advance desc_head WITHOUT claiming a post, so a stream
  // mixing the two (striped chunks: tiny copy-mode header + zero-copy
  // payload each) drifts desc_head arbitrarily far past post_head, and
  // the old `post_head - desc_head >= slots` guard underflowed and
  // wedged posting permanently.
  uint64_t posts_claimed_by_peer = 0;  // poller-local
  // Deferred-ack flags, desc index & mask.  Copy-mode descs release at
  // wrap time; sender-owned descs release when the consumer's last IOBuf
  // ref drops (any thread — hence atomics + shared_ptr lifetime).
  std::shared_ptr<std::array<std::atomic<uint8_t>, kIciMaxSlots>>
      rx_released =
          std::make_shared<std::array<std::atomic<uint8_t>, kIciMaxSlots>>();
  // One-sided session (net/rma.h): local window + peer window resolve.
  std::shared_ptr<RmaSession> rma;

  // Peer staging slabs mapped on first reference (poller-owned map of
  // REF-COUNTED StageMapping).  Consumers of wrapped ranges co-own the
  // mapping through their RxStageCtx, so neither a dying connection nor
  // ici_staging_free can munmap under them; loopback entries SHARE the
  // registry's own mapping object.
  std::map<uint32_t, std::shared_ptr<StageMapping>> stage_maps;

  // Stats.
  std::atomic<uint64_t> tx_wrs{0}, rx_wrs{0}, tx_bytes{0}, rx_bytes{0};
  std::atomic<uint64_t> window_exhausted{0};
  std::atomic<uint64_t> tx_zc_wrs{0}, tx_zc_bytes{0}, rx_zc_wrs{0};

  IciDir& tx_dir() { return is_client ? seg->c2s : seg->s2c; }
  IciDir& rx_dir() { return is_client ? seg->s2c : seg->c2s; }
  SlabTable& my_slabs() {
    return is_client ? seg->client_slabs : seg->server_slabs;
  }
  SlabTable& peer_slabs() {
    return is_client ? seg->server_slabs : seg->client_slabs;
  }
  int32_t peer_pid() const {
    return (is_client ? seg->server_pid : seg->client_pid)
        .load(std::memory_order_acquire);
  }
  uint64_t peer_beat() const {
    return (is_client ? seg->server_beat : seg->client_beat)
        .load(std::memory_order_acquire);
  }
  void bump_self_beat() {
    (is_client ? seg->client_beat : seg->server_beat)
        .fetch_add(1, std::memory_order_acq_rel);
  }

  ~IciConn() {
    sq.clear();     // drop queued source refs (SetFailed mid-transfer)
    sbuf.clear();   // drop deferred in-flight refs
    {
      std::lock_guard<std::mutex> g(rx_mu);
      rx_pending.clear();
    }
    for (Block* b : posted_fifo) {
      b->release();
    }
    for (char* m : tx_slabs) {
      if (m != nullptr) {
        munmap(m, tx_slab_len);
      }
    }
    stage_maps.clear();  // mappings with live consumer refs survive
    if (seg != nullptr) {
      munmap(seg, sizeof(IciSegment));
    }
    if (creator || unlink_on_close) {
      shm_unlink(name.c_str());
    }
    if (!creator) {
      ici_conn_release_name(name);
    }
  }
};

namespace {

// Deleter context for a wrapped recv block: returns the block to the pool
// when the consumer drops the last reference.  Holds the pool alive
// independently of the connection.
struct RxBlockCtx {
  std::shared_ptr<IciRx> rx;
  Block* block;
};

void rx_block_deleter(void*, void* vctx) {
  auto* ctx = static_cast<RxBlockCtx*>(vctx);
  ctx->rx->wrapped.fetch_sub(1, std::memory_order_relaxed);
  ctx->block->release();  // back to the arena free list
  delete ctx;
}

// Deleter context for a wrapped SENDER-OWNED range: acking the descriptor
// (flipping its released flag) is deferred to the moment the consumer's
// last reference drops — the sender must not reuse its staging bytes
// earlier.  Holds the flag array AND the slab mapping alive independently
// of the connection.
struct RxStageCtx {
  std::shared_ptr<std::array<std::atomic<uint8_t>, kIciMaxSlots>> released;
  std::shared_ptr<StageMapping> mapping;  // co-owns the slab memory
  uint32_t slot;
};

void rx_stage_deleter(void*, void* vctx) {
  auto* ctx = static_cast<RxStageCtx*>(vctx);
  ctx->released->at(ctx->slot).store(1, std::memory_order_release);
  delete ctx;
}

// Maps a REMOTE peer's staging slab READ-ONLY: the receiver only ever
// reads published ranges, and a receiver-side bug scribbling the sender's
// registered payload memory would corrupt frames the sender believes are
// immutably in flight (ADVICE r5).  Only the loopback branch — where the
// "peer" slab IS our own registry mapping — stays writable.
std::shared_ptr<StageMapping> map_peer_stage(const std::string& name) {
  const int fd = shm_open(name.c_str(), O_RDONLY, 0600);
  if (fd < 0) {
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    return nullptr;
  }
  auto m = std::make_shared<StageMapping>();
  m->base = static_cast<char*>(mem);
  m->len = static_cast<size_t>(st.st_size);
  m->owned = true;
  return m;
}

// Maps the peer's staging slab `ordinal` on first reference (bounded to
// keep a hostile peer from exhausting mappings); validates the range.
// On success fills *mapping (the ref-counted holder; RxStageCtx co-owns
// it so consumers outlive the connection).
char* resolve_stage_source(IciConn& c, uint32_t ordinal, uint64_t offset,
                           uint32_t len,
                           std::shared_ptr<StageMapping>* mapping) {
  auto it = c.stage_maps.find(ordinal);
  if (it == c.stage_maps.end()) {
    if (c.stage_maps.size() >= 1024) {
      return nullptr;
    }
    const int32_t pid = c.peer_pid();
    if (pid == 0) {
      return nullptr;
    }
    std::shared_ptr<StageMapping> m;
    if (pid == getpid()) {
      // Loopback: the peer's staging slab IS ours — SHARE the registry's
      // mapping object (same virtual address; the shared refcount also
      // defers ici_staging_free's munmap past every consumer), which
      // lets an echo response ride the zero-copy path back out too.
      std::lock_guard<std::mutex> g(stage_mu());
      for (const StagingSlab& s : stage_slabs()) {
        if (s.ordinal == ordinal) {
          m = s.mapping;
          break;
        }
      }
      if (m == nullptr) {
        return nullptr;
      }
    } else {
      m = map_peer_stage(stage_shm_name(pid, ordinal));
      if (m == nullptr) {
        return nullptr;
      }
    }
    it = c.stage_maps.emplace(ordinal, std::move(m)).first;
  }
  if (len == 0 || offset + len > it->second->len) {
    return nullptr;
  }
  *mapping = it->second;
  return it->second->base + offset;
}

// Publishes a freshly-grown slab's shm name so the peer can map it.
// Returns false when the slab table is full/invalid.
bool publish_slabs(IciConn& c) {
  SlabTable& t = c.my_slabs();
  const size_t have = c.rx->arena->slab_count();
  uint32_t published = t.count.load(std::memory_order_relaxed);
  while (published < have) {
    if (published >= kIciMaxSlabs) {
      return false;
    }
    const std::string name = c.rx->arena->slab_shm_name(published);
    if (name.empty() || name.size() >= kSlabNameLen) {
      return false;
    }
    snprintf(t.names[published], kSlabNameLen, "%s", name.c_str());
    ++published;
    t.count.store(published, std::memory_order_release);
  }
  return true;
}

// Allocates and posts one recv block; false when the pool is at its cap
// (post deferred — pool-exhaustion backpressure), the post ring is full,
// or the pool is broken.  Ring-fullness bound: the sender claims post
// entries strictly in order, one per COPY-MODE descriptor it publishes
// (zero-copy descriptors claim nothing), so entries it may not have
// claimed yet number post_head - posts_claimed_by_peer; reusing a slot
// before the sender claimed it would tear the window.
bool post_one_block(IciConn& c, bool* fatal) {
  IciDir& my_rxd = c.rx_dir();
  if (my_rxd.post_head.load(std::memory_order_relaxed) -
          c.posts_claimed_by_peer >=
      c.slots) {
    return false;
  }
  if (c.rx->arena->blocks_in_use() >= c.max_blocks) {
    return false;
  }
  Block* b = c.rx->arena->allocate(c.block_size);
  if (b == nullptr) {
    *fatal = true;
    return false;
  }
  if (!publish_slabs(c)) {
    b->release();
    *fatal = true;
    return false;
  }
  IciDir& rxd = c.rx_dir();
  const uint64_t head = rxd.post_head.load(std::memory_order_relaxed);
  rxd.post_ring[head & (c.slots - 1)] = b->user_meta;
  c.posted_fifo.push_back(b);
  rxd.post_head.store(head + 1, std::memory_order_release);
  return true;
}

// Resolves a (slab,offset) meta to a DMA target inside the peer's pool,
// mapping newly-published slabs on first use.  nullptr = invalid/hostile.
char* resolve_tx_target(IciConn& c, uint64_t meta, uint32_t len) {
  const uint32_t slab_id = static_cast<uint32_t>(meta >> 32);
  const uint32_t offset = static_cast<uint32_t>(meta);
  if (slab_id >= kIciMaxSlabs || offset % c.block_size != 0 ||
      static_cast<size_t>(offset) + len > c.tx_slab_len) {
    return nullptr;
  }
  SlabTable& t = c.peer_slabs();
  while (c.tx_slabs.size() <= slab_id) {
    const uint32_t published = t.count.load(std::memory_order_acquire);
    const size_t next = c.tx_slabs.size();
    if (next >= published) {
      return nullptr;  // descriptor references an unpublished slab
    }
    char name[kSlabNameLen];
    memcpy(name, t.names[next], kSlabNameLen);
    name[kSlabNameLen - 1] = '\0';
    if (strncmp(name, "/trpc_arena_", 12) != 0) {
      return nullptr;
    }
    void* mem = map_shm(name, c.tx_slab_len);
    if (mem == nullptr) {
      return nullptr;
    }
    c.tx_slabs.push_back(static_cast<char*>(mem));
  }
  return c.tx_slabs[slab_id] + offset;
}

// ---- completion poller (PollCq / rdma_use_polling parity) ----------------

struct PolledConn {
  std::weak_ptr<IciConn> conn;
  SocketId socket = 0;
  int64_t created_us = 0;
  int64_t last_liveness_us = 0;
  uint64_t last_peer_beat = 0;
  int64_t peer_beat_changed_us = 0;
  bool remove = false;  // poller-thread-only: marked dead this pass
};

class IciPoller {
 public:
  static IciPoller* instance() {
    static IciPoller* p = new IciPoller();  // leaked: thread outlives statics
    return p;
  }

  void add(std::shared_ptr<IciConn> conn, SocketId socket) {
    auto pc = std::make_shared<PolledConn>();
    pc->conn = conn;
    pc->socket = socket;
    pc->created_us = monotonic_time_us();
    std::lock_guard<std::mutex> g(mu_);
    conns_.push_back(std::move(pc));
  }

 private:
  IciPoller() {
    pthread_t tid;
    pthread_create(
        &tid, nullptr,
        [](void* self) -> void* {
          static_cast<IciPoller*>(self)->run();
          return nullptr;
        },
        this);
    pthread_detach(tid);
  }

  // One pass over one connection; returns true if anything moved.  *dead
  // is set when the shared rings hold values only a corrupted or hostile
  // peer could have written — the socket is then failed rather than spun
  // on.
  bool service(IciConn& c, bool* rx_edge, bool* tx_edge, bool* dead) {
    const uint32_t mask = c.slots - 1;
    bool moved = false;

    // 1. RX: wrap freshly published descriptors zero-copy and hand them to
    // the read path.  Bumping desc_consumed IS the peer's send completion;
    // a fresh block is posted in the consumed one's place immediately
    // (block_pool re-post semantics — the pool, not the ring, is the
    // backpressure bound).
    IciDir& rxd = c.rx_dir();
    const uint64_t rx_head = rxd.desc_head.load(std::memory_order_acquire);
    // desc_head is peer-writable: legitimately it never runs more than
    // `slots` past our ack cursor (the sender's own window check).  A
    // hostile overrun must die HERE — stage-mode descs skip the
    // posted_fifo check that used to bound copy-mode overruns, so without
    // this the drain loop is an unbounded-work/OOM primitive.
    if (rx_head - c.rx_ack > c.slots) {
      *dead = true;
      return moved;
    }
    if (rx_head != c.rx_desc_tail) {
      std::lock_guard<std::mutex> g(c.rx_mu);
      while (c.rx_desc_tail != rx_head) {
        const IciDesc d = rxd.desc_ring[c.rx_desc_tail & mask];
        const uint32_t slot = static_cast<uint32_t>(c.rx_desc_tail & mask);
        if (d.meta & kStageBit) {
          // Sender-owned range: wrap the peer's staging bytes zero-copy;
          // the descriptor acks (released flag) only when the consumer's
          // last reference drops.
          const uint32_t ord =
              static_cast<uint32_t>((d.meta >> 40) & 0xFFFFF);
          std::shared_ptr<StageMapping> mapping;
          char* src = resolve_stage_source(
              c, ord, d.meta & kStageOffsetMask, d.len, &mapping);
          if (src == nullptr) {
            *dead = true;
            return moved;
          }
          if (c.rx_desc_tail - c.rx_ack >= c.slots / 2) {
            // Backlog valve: acks are strictly in-order, so a frame whose
            // refs only drop once it is COMPLETE must never need more
            // deferred-ack descriptors than the window holds.  Past half
            // the window, copy-and-ack keeps the stream moving (zero-copy
            // degrades, correctness doesn't).
            c.rx_pending.append(src, d.len);
            c.rx_released->at(slot).store(1, std::memory_order_release);
          } else {
            auto* ctx =
                new RxStageCtx{c.rx_released, std::move(mapping), slot};
            c.rx_pending.append_user_data(src, d.len, &rx_stage_deleter,
                                          ctx, d.meta);
            c.rx_zc_wrs.fetch_add(1, std::memory_order_relaxed);
          }
        } else {
          if (c.posted_fifo.empty() || d.len > c.block_size) {
            *dead = true;
            return moved;
          }
          Block* b = c.posted_fifo.front();
          if (d.meta != b->user_meta) {
            *dead = true;  // descriptor does not match the claimed post
            return moved;
          }
          c.posted_fifo.pop_front();
          ++c.posts_claimed_by_peer;  // the post-ring slot reuse bound
          auto* ctx = new RxBlockCtx{c.rx, b};
          c.rx->wrapped.fetch_add(1, std::memory_order_relaxed);
          c.rx_pending.append_user_data(b->data, d.len, &rx_block_deleter,
                                        ctx, b->user_meta);
          // Copy-mode descs ack at wrap (block reuse is governed by the
          // pool re-post cycle, as before).
          c.rx_released->at(slot).store(1, std::memory_order_release);
          bool fatal = false;
          if (!post_one_block(c, &fatal)) {
            if (fatal) {
              *dead = true;
              return moved;
            }
            ++c.repost_deficit;  // pool exhausted; retry when blocks return
          }
        }
        c.rx_wrs.fetch_add(1, std::memory_order_relaxed);
        c.rx_bytes.fetch_add(d.len, std::memory_order_relaxed);
        ++c.rx_desc_tail;
      }
      *rx_edge = true;
      moved = true;
    }
    // Publish desc_consumed over the contiguous released prefix.  Acks
    // are strictly in-order: a held sender-owned range stalls later acks
    // (and thus the sender's window) — end-to-end backpressure.
    while (c.rx_ack < c.rx_desc_tail &&
           c.rx_released->at(c.rx_ack & mask).load(
               std::memory_order_acquire) != 0) {
      c.rx_released->at(c.rx_ack & mask).store(0, std::memory_order_relaxed);
      ++c.rx_ack;
      rxd.desc_consumed.store(c.rx_ack, std::memory_order_release);
      moved = true;
    }

    // 1b. Clear deferred posts once consumers return blocks to the pool.
    while (c.repost_deficit > 0) {
      bool fatal = false;
      if (!post_one_block(c, &fatal)) {
        if (fatal) {
          *dead = true;
          return moved;
        }
        break;
      }
      --c.repost_deficit;
      moved = true;
    }

    // 2. TX completions: the peer consumed descriptors → release the
    // deferred source refs (_sbuf) for those WRs.
    IciDir& txd = c.tx_dir();
    const uint64_t consumed =
        txd.desc_consumed.load(std::memory_order_acquire);
    // desc_consumed is peer-writable shared memory.  Legitimately it trails
    // our published desc_head, which itself never runs more than `slots`
    // ahead of sbuf_released — so a gap beyond `slots` is a value only a
    // corrupt or hostile peer could have stored, and draining toward it
    // would wedge the poller (and every other connection) in this loop.
    if (consumed - c.sbuf_released > c.slots) {
      *dead = true;
      return moved;
    }
    while (c.sbuf_released < consumed) {
      c.sbuf[c.sbuf_released & mask].clear();
      ++c.sbuf_released;
      moved = true;
    }

    // 3. TX DMA engine: drain the send queue while the window is open.
    // Copy-mode WRs need a posted peer block (credit) AND a descriptor
    // slot; sender-owned WRs need only the descriptor slot (their bytes
    // already live in a registered staging slab the peer maps directly).
    const uint64_t sq_head = c.sq_head.load(std::memory_order_acquire);
    uint64_t sq_tail = c.sq_tail.load(std::memory_order_relaxed);
    if (sq_tail != sq_head) {
      const uint64_t post_head =
          txd.post_head.load(std::memory_order_acquire);
      uint64_t desc_head = txd.desc_head.load(std::memory_order_relaxed);
      while (sq_tail != sq_head && desc_head - consumed < c.slots) {
        IOBuf& wr = c.sq[sq_tail & mask];
        const uint64_t wr_meta = c.sq_meta[sq_tail & mask];
        const uint32_t len = static_cast<uint32_t>(wr.size());
        if (wr_meta & kStageBit) {
          // Zero-copy publish: descriptor names our staging slab range.
          IciDesc& slot = txd.desc_ring[desc_head & mask];
          slot.meta = wr_meta;
          slot.len = len;
          c.sbuf[desc_head & mask] = std::move(wr);
          ++desc_head;
          txd.desc_head.store(desc_head, std::memory_order_release);
          c.tx_zc_wrs.fetch_add(1, std::memory_order_relaxed);
          c.tx_zc_bytes.fetch_add(len, std::memory_order_relaxed);
          zc_wrs_total().fetch_add(1, std::memory_order_relaxed);
          zc_bytes_total().fetch_add(len, std::memory_order_relaxed);
        } else {
          if (c.post_tail == post_head) {
            break;  // no posted-block credit for a copy-mode WR
          }
          const uint64_t target_meta = txd.post_ring[c.post_tail & mask];
          char* dst = resolve_tx_target(c, target_meta, len);
          if (dst == nullptr) {
            *dead = true;
            return moved;
          }
          // The DMA: gather the WR's refs into the peer's posted block.
          size_t off = 0;
          for (size_t i = 0; i < wr.block_count(); ++i) {
            const IOBuf::BlockRef& ref = wr.ref_at(i);
            memcpy(dst + off, ref.block->data + ref.offset, ref.length);
            off += ref.length;
          }
          // Publish the descriptor; hold the source refs until completion.
          IciDesc& slot = txd.desc_ring[desc_head & mask];
          slot.meta = target_meta;
          slot.len = len;
          c.sbuf[desc_head & mask] = std::move(wr);
          ++desc_head;
          txd.desc_head.store(desc_head, std::memory_order_release);
          ++c.post_tail;
        }
        ++sq_tail;
        c.tx_wrs.fetch_add(1, std::memory_order_relaxed);
        c.tx_bytes.fetch_add(len, std::memory_order_relaxed);
      }
      if (sq_tail != c.sq_tail.load(std::memory_order_relaxed)) {
        c.sq_tail.store(sq_tail, std::memory_order_release);
        *tx_edge = true;  // SQ space freed → wake a parked writer
        moved = true;
      }
    }
    return moved;
  }

  void run() {
    int idle_spins = 0;
    std::vector<std::shared_ptr<PolledConn>> snap;
    while (true) {
      bool any = false;
      bool pruned = false;
      // Snapshot under the lock; service OUTSIDE it.  The bulk memcpy
      // "DMA" (up to slots×block_size per pass) and SetFailed/on_input
      // dispatch would otherwise add head-of-line latency to every other
      // connection and block add() (new handshakes) for the duration.
      // PolledConn fields are poller-thread-only, so mutating them on the
      // snapshot is safe; add() only ever appends fresh entries.
      snap.clear();
      {
        std::lock_guard<std::mutex> g(mu_);
        snap.assign(conns_.begin(), conns_.end());
      }
      const int64_t now_us = monotonic_time_us();
      for (auto& pcp : snap) {
        PolledConn& pc = *pcp;
        std::shared_ptr<IciConn> conn = pc.conn.lock();
        if (conn == nullptr) {
          pc.remove = true;
          pruned = true;
          continue;
        }
        bool rx_edge = false, tx_edge = false, dead = false;
        if (service(*conn, &rx_edge, &tx_edge, &dead)) {
          any = true;
        }
        if (dead) {
          LOG(Warning) << "ici rings corrupt (" << conn->name
                       << "); failing socket";
          conn->unlink_on_close = true;
          SocketRef s(Socket::Address(pc.socket));
          if (s) {
            s->SetFailed(EPROTO);
          }
          pc.remove = true;
          pruned = true;
          continue;
        }
        if (rx_edge || tx_edge) {
          SocketRef s(Socket::Address(pc.socket));
          if (s) {
            if (rx_edge) {
              s->on_input_event();
            }
            if (tx_edge) {
              s->on_output_event();
            }
          } else if (conn->rx_pending.size() > 0 && rx_edge) {
            // Socket gone: nobody will ever drain; drop the entry.
            pc.remove = true;
            pruned = true;
            continue;
          }
        }
        // Liveness (rate-limited ~1/s): reap on verified exit, a 30s
        // heartbeat stall, or a peer that never arrived.
        if (now_us - pc.last_liveness_us > 1000 * 1000) {
          pc.last_liveness_us = now_us;
          conn->bump_self_beat();
          const uint64_t beat = conn->peer_beat();
          if (beat != pc.last_peer_beat || pc.peer_beat_changed_us == 0) {
            pc.last_peer_beat = beat;
            pc.peer_beat_changed_us = now_us;
          }
          const int32_t peer = conn->peer_pid();
          const bool no_pid =
              peer == 0 && now_us - pc.created_us > 30 * 1000 * 1000;
          const bool dead_pid =
              peer != 0 && kill(static_cast<pid_t>(peer), 0) != 0 &&
              errno == ESRCH;
          const bool stalled =
              now_us - pc.peer_beat_changed_us > 30 * 1000 * 1000;
          if (no_pid || dead_pid || stalled) {
            LOG(Warning) << "ici peer lost (" << conn->name << ", pid "
                         << peer << "); reaping";
            conn->unlink_on_close = true;
            SocketRef deads(Socket::Address(pc.socket));
            if (deads) {
              deads->SetFailed(no_pid ? ETIMEDOUT : ECONNRESET);
            }
            pc.remove = true;
            pruned = true;
          }
        }
      }
      if (pruned) {
        std::lock_guard<std::mutex> g(mu_);
        conns_.erase(
            std::remove_if(conns_.begin(), conns_.end(),
                           [](const std::shared_ptr<PolledConn>& p) {
                             return p->remove;
                           }),
            conns_.end());
      }
      if (any) {
        idle_spins = 0;
        continue;
      }
      if (++idle_spins < 1000) {
        sched_yield();
      } else {
        usleep(100);
      }
    }
  }

  std::mutex mu_;
  std::vector<std::shared_ptr<PolledConn>> conns_;
};

// ---- the Transport -------------------------------------------------------

class IciRingTransport final : public Transport {
 public:
  // Post ≤block_size WRs into the SQ without copying; the poller is the
  // DMA engine.  Returns 0 (EAGAIN) when the SQ is full — KeepWrite then
  // parks on the writable Event and the poller wakes it on completion.
  ssize_t cut_from_iobuf(Socket* s, IOBuf* from) override {
    auto* c = static_cast<IciConn*>(s->transport_ctx);
    if (c == nullptr) {
      errno = ENOTCONN;
      return -1;
    }
    const uint32_t mask = c->slots - 1;
    if (c->sq_staged == UINT64_MAX) {
      c->sq_staged = c->sq_head.load(std::memory_order_relaxed);
    }
    size_t total = 0;
    while (!from->empty()) {
      const uint64_t head = c->sq_staged;
      if (head - c->sq_tail.load(std::memory_order_acquire) >= c->slots) {
        c->window_exhausted.fetch_add(1, std::memory_order_relaxed);
        break;
      }
      IOBuf& wr = c->sq[head & mask];
      uint64_t meta = 0;
      // Zero-copy fast path: a front ref living inside one of OUR
      // registered staging slabs ships as a single sender-owned
      // descriptor (whole ref, not block_size chunks) with no ring DMA.
      // The user_deleter pre-filter keeps ordinary arena blocks off the
      // registry mutex.
      const IOBuf::BlockRef& r0 = from->ref_at(0);
      uint32_t ord = 0;
      uint64_t off = 0;
      if (r0.length >= 4096 && r0.block->user_deleter != nullptr &&
          staging_of(r0.block->data + r0.offset, r0.length, &ord, &off)) {
        total += from->cutn(&wr, r0.length);
        // Coalesce CONTIGUOUS staging refs into this one descriptor: a
        // parser that sliced a big staged payload into read-chunk pieces
        // (consecutive refs of one slab range) must not fan out into
        // per-piece descriptors — descs are acked in order only when the
        // whole frame's refs drop, so a frame needing more descs than
        // the ring has slots would deadlock the window (r5: 16MB+ echo
        // responses arrived as 512KB slices).
        uint64_t end = off + r0.length;
        while (!from->empty() && wr.size() < (1ull << 31)) {
          const IOBuf::BlockRef& rn = from->ref_at(0);
          uint32_t ord2 = 0;
          uint64_t off2 = 0;
          if (rn.block->user_deleter == nullptr ||
              !staging_of(rn.block->data + rn.offset, rn.length, &ord2,
                          &off2) ||
              ord2 != ord || off2 != end ||
              // Descriptor lengths publish as uint32 (slot.len below):
              // growing past UINT32_MAX would silently truncate at the
              // static_cast and corrupt >4GiB staged frames — the tail
              // refs start a fresh WR instead (ADVICE r5).
              !ici_desc_len_fits(wr.size(), rn.length)) {
            break;
          }
          total += from->cutn(&wr, rn.length);
          end += rn.length;
        }
        meta = stage_meta(ord, off);
      } else {
        // Align the cut so a staging ref BEHIND a small header ref stays
        // whole for the next iteration's zero-copy publish, instead of
        // having its front chopped into this copy-mode WR.
        size_t n = c->block_size;
        if (r0.length < c->block_size && from->block_count() > 1) {
          const IOBuf::BlockRef& r1 = from->ref_at(1);
          uint32_t o2 = 0;
          uint64_t f2 = 0;
          if (r1.length >= 4096 && r1.block->user_deleter != nullptr &&
              staging_of(r1.block->data + r1.offset, r1.length, &o2, &f2)) {
            n = r0.length;
          }
        }
        total += from->cutn(&wr, n);
      }
      c->sq_meta[head & mask] = meta;
      c->sq_staged = head + 1;
    }
    return static_cast<ssize_t>(total);
  }

  void flush(Socket* s) override {
    auto* c = static_cast<IciConn*>(s->transport_ctx);
    if (c == nullptr || c->sq_staged == UINT64_MAX) {
      return;
    }
    c->sq_head.store(c->sq_staged, std::memory_order_release);
    c->sq_staged = UINT64_MAX;
  }

  ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) override {
    auto* c = static_cast<IciConn*>(s->transport_ctx);
    if (c == nullptr) {
      errno = ENOTCONN;
      return -1;
    }
    std::lock_guard<std::mutex> g(c->rx_mu);
    return static_cast<ssize_t>(c->rx_pending.cutn(to, max));
  }

  int connect(Socket*) override { return 0; }  // established at handshake
  bool fd_based() const override { return false; }
  const char* name() const override { return "ici_ring"; }

  // One-sided capability: the connection's window session (nullptr when
  // trpc_rma_window_bytes was 0 at establishment).
  RmaSession* rma(Socket* s) override {
    auto* c = static_cast<IciConn*>(s->transport_ctx);
    return c != nullptr ? c->rma.get() : nullptr;
  }
};

IciRingTransport* ici_transport() {
  static IciRingTransport t;
  return &t;
}

// One consumer per segment name, ever (duplicate open = two readers on one
// SPSC lane).
std::mutex& open_names_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::vector<std::string>& open_names() {
  static auto* v = new std::vector<std::string>();
  return *v;
}

// Builds one side's receive pool and posts the initial window.
bool build_rx_side(IciConn& c) {
  DeviceArena::Options aopts;
  aopts.block_size = c.block_size;
  aopts.blocks_per_slab = c.slots;
  aopts.shm_backed = true;
  aopts.register_slab = &slab_register_tramp;
  aopts.unregister_slab = &slab_unregister_tramp;
  c.rx = std::make_shared<IciRx>();
  c.rx->arena.reset(new DeviceArena(aopts));
  c.sq.resize(c.slots);
  c.sq_meta.assign(c.slots, 0);
  c.sbuf.resize(c.slots);
  c.tx_slab_len = static_cast<size_t>(c.block_size) * c.slots;
  for (uint32_t i = 0; i < c.slots; ++i) {
    bool fatal = false;
    if (!post_one_block(c, &fatal)) {
      return false;
    }
  }
  return true;
}

}  // namespace

void ici_conn_release_name(const std::string& name) {
  std::lock_guard<std::mutex> g(open_names_mu());
  auto& v = open_names();
  v.erase(std::remove(v.begin(), v.end(), name), v.end());
}

bool ici_set_ring_geometry(uint32_t block_size, uint32_t slots,
                           uint32_t max_blocks) {
  if (max_blocks == 0) {
    max_blocks = std::min<uint32_t>(1024, kIciMaxSlabs * slots);
  }
  std::lock_guard<std::mutex> g(geom_mu());
  if (!geometry_valid(block_size, slots, max_blocks)) {
    LOG(Warning) << "ici_set_ring_geometry rejected (block_size="
                 << block_size << " slots=" << slots
                 << " max_blocks=" << max_blocks << "); keeping previous";
    return false;
  }
  geom() = Geometry{block_size, slots, max_blocks};
  return true;
}

void ici_get_ring_geometry(uint32_t* block_size, uint32_t* slots,
                           uint32_t* max_blocks) {
  std::lock_guard<std::mutex> g(geom_mu());
  *block_size = geom().block_size;
  *slots = geom().slots;
  *max_blocks = geom().max_blocks;
}

void ici_set_slab_registrar(int (*reg)(void*, size_t, void*, uint64_t*),
                            void (*unreg)(void*, size_t, void*, uint64_t),
                            void* ctx) {
  std::lock_guard<std::mutex> g(reg_mu());
  registrar() = Registrar{reg, unreg, ctx};
}

void* ici_staging_alloc(size_t len, uint32_t* ordinal_out) {
  if (len == 0 || len > kStageOffsetMask) {
    return nullptr;
  }
  static std::atomic<uint32_t> next_ord{0};
  const uint32_t ord = next_ord.fetch_add(1, std::memory_order_relaxed);
  if (ord >= (1u << 20)) {
    return nullptr;  // meta encoding holds 20 ordinal bits
  }
  const std::string name = stage_shm_name(getpid(), ord);
  const int fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    return nullptr;
  }
  if (ftruncate(fd, static_cast<off_t>(len)) != 0) {
    close(fd);
    shm_unlink(name.c_str());
    return nullptr;
  }
  void* mem =
      mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name.c_str());
    return nullptr;
  }
  uint64_t handle = 0;
  if (slab_register_tramp(mem, len, nullptr, &handle) != 0) {
    munmap(mem, len);
    shm_unlink(name.c_str());
    return nullptr;
  }
  auto mapping = std::make_shared<StageMapping>();
  mapping->base = static_cast<char*>(mem);
  mapping->len = len;
  mapping->owned = true;
  std::lock_guard<std::mutex> g(stage_mu());
  stage_slabs().push_back(StagingSlab{std::move(mapping), ord, handle, name});
  if (ordinal_out != nullptr) {
    *ordinal_out = ord;
  }
  return mem;
}

void ici_staging_free(void* base) {
  StagingSlab victim;
  {
    std::lock_guard<std::mutex> g(stage_mu());
    auto& v = stage_slabs();
    auto it = std::find_if(v.begin(), v.end(), [base](const StagingSlab& s) {
      return s.mapping != nullptr && s.mapping->base == base;
    });
    if (it == v.end()) {
      return;
    }
    victim = std::move(*it);
    v.erase(it);
  }
  // Unregister + unlink NOW (the name and DMA registration are gone for
  // new users); the munmap itself is deferred by the mapping's refcount
  // until the last wrapped-range consumer drops (use-after-free guard).
  slab_unregister_tramp(victim.mapping->base, victim.mapping->len, nullptr,
                        victim.reg_handle);
  shm_unlink(victim.name.c_str());
}

void ici_zero_copy_counters(uint64_t* wrs, uint64_t* bytes) {
  if (wrs != nullptr) {
    *wrs = zc_wrs_total().load(std::memory_order_relaxed);
  }
  if (bytes != nullptr) {
    *bytes = zc_bytes_total().load(std::memory_order_relaxed);
  }
}

size_t ici_registered_slab_count() {
  return registered_slabs().load(std::memory_order_relaxed);
}

std::shared_ptr<IciConn> ici_conn_create(std::string* name_out) {
  Geometry g;
  {
    std::lock_guard<std::mutex> lk(geom_mu());
    g = geom();
  }
  char name[64];
  snprintf(name, sizeof(name), "/trpc_ici_%d_%llx", getpid(),
           static_cast<unsigned long long>(fast_rand()));
  const int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    return nullptr;
  }
  if (ftruncate(fd, sizeof(IciSegment)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, sizeof(IciSegment), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* seg = static_cast<IciSegment*>(mem);
  memset(static_cast<void*>(seg), 0, sizeof(IciSegment));
  seg->block_size = g.block_size;
  seg->slots = g.slots;
  seg->max_blocks = g.max_blocks;
  seg->client_pid.store(static_cast<int32_t>(getpid()),
                        std::memory_order_release);

  auto conn = std::make_shared<IciConn>();
  conn->seg = seg;
  conn->name = name;
  conn->is_client = true;
  conn->creator = true;
  conn->block_size = g.block_size;
  conn->slots = g.slots;
  conn->max_blocks = g.max_blocks;
  if (!build_rx_side(*conn)) {
    return nullptr;  // dtor unmaps + unlinks
  }
  conn->rma = rma_session_create();
  if (conn->rma != nullptr) {
    conn->rma->peer_rkey_slot = &seg->server_rma_rkey;
    // Release: the window region is fully built before the peer can
    // observe its rkey.
    seg->client_rma_rkey.store(conn->rma->local_rkey,
                               std::memory_order_release);
  }
  seg->magic = kIciMagic;  // last: publish a fully-built segment
  *name_out = name;
  return conn;
}

std::shared_ptr<IciConn> ici_conn_open(const std::string& name) {
  if (name.empty() || name[0] != '/' || name.rfind("/trpc_ici_", 0) != 0 ||
      name.size() > 60) {
    return nullptr;
  }
  {
    std::lock_guard<std::mutex> g(open_names_mu());
    auto& v = open_names();
    if (std::find(v.begin(), v.end(), name) != v.end()) {
      return nullptr;
    }
    v.push_back(name);
  }
  auto fail = [&name]() -> std::shared_ptr<IciConn> {
    ici_conn_release_name(name);
    return nullptr;
  };
  void* mem = map_shm(name.c_str(), sizeof(IciSegment));
  if (mem == nullptr) {
    return fail();
  }
  auto* seg = static_cast<IciSegment*>(mem);
  if (seg->magic != kIciMagic ||
      !geometry_valid(seg->block_size, seg->slots, seg->max_blocks)) {
    munmap(mem, sizeof(IciSegment));
    return fail();
  }
  auto conn = std::make_shared<IciConn>();
  conn->seg = seg;
  conn->name = name;
  conn->is_client = false;
  conn->block_size = seg->block_size;
  conn->slots = seg->slots;
  conn->max_blocks = seg->max_blocks;
  if (!build_rx_side(*conn)) {
    return nullptr;  // dtor unmaps + releases the name
  }
  conn->rma = rma_session_create();
  if (conn->rma != nullptr) {
    conn->rma->peer_rkey_slot = &seg->client_rma_rkey;
    // Release: pairs with the peer's acquire read at first rma send.
    seg->server_rma_rkey.store(conn->rma->local_rkey,
                               std::memory_order_release);
  }
  seg->server_pid.store(static_cast<int32_t>(getpid()),
                        std::memory_order_release);
  return conn;
}

int ici_socket_create(std::shared_ptr<IciConn> conn,
                      void (*on_readable)(SocketId, void*), void* user_data,
                      SocketId* out) {
  if (conn == nullptr) {
    return -1;
  }
  Socket::Options opts;
  opts.fd = -1;
  opts.mode = SocketMode::kIci;
  opts.on_readable = on_readable;
  opts.user_data = user_data;
  opts.transport = ici_transport();
  opts.transport_ctx_holder = conn;
  if (Socket::Create(opts, out) != 0) {
    return -1;
  }
  IciPoller::instance()->add(conn, *out);
  return 0;
}

IciConnStats ici_conn_stats(const IciConn& c) {
  IciConnStats s;
  s.tx_wrs = c.tx_wrs.load(std::memory_order_relaxed);
  s.rx_wrs = c.rx_wrs.load(std::memory_order_relaxed);
  s.tx_bytes = c.tx_bytes.load(std::memory_order_relaxed);
  s.rx_bytes = c.rx_bytes.load(std::memory_order_relaxed);
  s.window_exhausted = c.window_exhausted.load(std::memory_order_relaxed);
  auto& txd = const_cast<IciConn&>(c).tx_dir();
  s.sbuf_held = txd.desc_head.load(std::memory_order_acquire) -
                txd.desc_consumed.load(std::memory_order_acquire);
  s.rx_unposted = c.rx->wrapped.load(std::memory_order_relaxed);
  s.tx_zero_copy_wrs = c.tx_zc_wrs.load(std::memory_order_relaxed);
  s.tx_zero_copy_bytes = c.tx_zc_bytes.load(std::memory_order_relaxed);
  s.rx_zero_copy_wrs = c.rx_zc_wrs.load(std::memory_order_relaxed);
  s.slots = c.slots;
  s.block_size = c.block_size;
  return s;
}

void ici_conn_set_self_pid(IciConn& c, int32_t pid) {
  (c.is_client ? c.seg->client_pid : c.seg->server_pid)
      .store(pid, std::memory_order_release);
}

void ici_conn_corrupt_tx_consumed(IciConn& c, uint64_t value) {
  c.tx_dir().desc_consumed.store(value, std::memory_order_release);
}

bool ici_payload_prefers_descriptors(const IOBuf& body) {
  // Staging-backed bytes ship as sender-owned descriptors with ZERO
  // copies; an rma put would reintroduce one.  The user_deleter
  // pre-filter keeps ordinary arena blocks off the registry mutex (same
  // screen as cut_from_iobuf's zero-copy fast path).
  uint64_t staged = 0;
  const uint64_t total = body.size();
  for (size_t i = 0; i < body.block_count(); ++i) {
    const IOBuf::BlockRef& r = body.ref_at(i);
    uint32_t ord = 0;
    uint64_t off = 0;
    if (r.length >= 4096 && r.block->user_deleter != nullptr &&
        staging_of(r.block->data + r.offset, r.length, &ord, &off)) {
      staged += r.length;
    }
  }
  return total != 0 && staged * 2 >= total;
}

std::string ici_test_stage_shm_name(int32_t pid, uint32_t ordinal) {
  return stage_shm_name(pid, ordinal);
}

char* ici_test_map_peer_stage(const std::string& shm_name, size_t* len_out) {
  auto m = map_peer_stage(shm_name);
  if (m == nullptr) {
    return nullptr;
  }
  if (len_out != nullptr) {
    *len_out = m->len;
  }
  // Detach: the caller owns the munmap (test-only path).
  char* base = m->base;
  m->owned = false;
  return base;
}

}  // namespace trpc
