// ICI DMA-ring transport — the device-interconnect endpoint behind
// SocketMode::kIci.
//
// Parity: the reference's RDMA endpoint machinery, re-designed for a TPU
// interconnect whose unit of transfer is a DMA into a registered
// staging window rather than a byte stream:
//   - posted receive blocks   (/root/reference/src/brpc/rdma/
//     rdma_endpoint.h:295-299 `_rbuf` fixed recv blocks)
//   - send/recv credit windows (`rdma_endpoint.h:292-328` —
//     _remote_rq_window_size / _sq_window_size; exhaustion returns EAGAIN to
//     the wait-free write queue so KeepWrite parks; completion wakes it)
//   - deferred source release  (`_sbuf`: send-side IOBuf refs held until the
//     completion for that WR, never freed at post time)
//   - a completion poller      (`rdma_endpoint.h:250` PollCq /
//     FLAGS_rdma_use_polling dedicated-poller mode)
//   - registered block memory  (rdma/block_pool.cpp taking over IOBuf
//     allocation; here base/device_arena.h slabs ARE the registered
//     windows, and descriptors carry (slab,offset) — the lkey analogue)
//   - TCP bootstrap handshake  (rdma_handshake-over-TCP: the client mints
//     the rings, ships their names in an ordinary RPC, both sides then run
//     fd-less sockets over the rings).
//
// TPU-native shape: one connection = two one-way DMA lanes.  Each side owns
// a DeviceArena slab as its RECEIVE window (registered once — the
// registration hook is where PJRT/libtpu pinning goes, see
// ici_set_slab_registrar) and posts its blocks to the peer.  A send is:
// claim a posted peer block (a credit), DMA the bytes into it, publish a
// {offset,len} descriptor.  The receiver wraps the block into the IOBuf
// zero-copy (meta = the block's lkey-analogue) and re-posts it only when
// the last IOBuf reference drops — backpressure is therefore end-to-end:
// a slow *consumer* (not just a slow reader) stalls the sender's window.
//
// Where this image cannot reach real device DMA, the slabs are shm/host
// staging memory and the "DMA engine" is the poller thread doing the copy —
// the machinery (windows, posted blocks, deferred release, completion wake)
// is identical; see tools/pjrt_probe.md for the committed probe of real
// device-pointer registration.
#pragma once

#include <cstdint>
#include <memory>
#include <string>

#include "net/socket.h"

namespace trpc {

struct IciConn;

// Client side: mint the control segment + receive window, post all recv
// blocks.  *name_out is the segment name to ship in the handshake RPC.
std::shared_ptr<IciConn> ici_conn_create(std::string* name_out);
// Server side: map a client-minted segment, build our receive window, post
// our blocks.  Validates geometry; nullptr on any mismatch.
std::shared_ptr<IciConn> ici_conn_open(const std::string& name);

// Builds the fd-less socket bound to `conn` and registers it with the
// completion poller.
int ici_socket_create(std::shared_ptr<IciConn> conn,
                      void (*on_readable)(SocketId, void*), void* user_data,
                      SocketId* out);

// The handshake method name Servers auto-register.
inline const char* kIciConnectMethod = "__ici.Connect";

// Ring geometry for NEW client connections (the client proposes, the server
// validates).  block_size: DMA granularity (clamped 4KB..4MB); slots: posted
// blocks per direction (power of two, 2..1024); max_blocks: receive-pool
// growth cap per direction (block_pool bound — the largest frame a
// connection can carry is ≈ (max_blocks - slots) × block_size; 0 = default
// 1024 capped at 64×slots).  Tests shrink this to force window exhaustion
// and pool backpressure; the bench widens it.  Returns false (keeping the
// previous geometry, with a warning log) when validation rejects the
// proposal, so callers can detect the no-op.
bool ici_set_ring_geometry(uint32_t block_size, uint32_t slots,
                           uint32_t max_blocks = 0);

// Reads the current proposal (save/restore around scoped overrides).
void ici_get_ring_geometry(uint32_t* block_size, uint32_t* slots,
                           uint32_t* max_blocks);

// ---- sender-owned zero-copy staging (block_pool takeover parity) --------
// A staging slab is shm-backed, registered through the same registrar seam
// as receive windows, and published under a process-derivable name, so ANY
// ici connection's peer can map it.  Payload bytes living in a staging
// slab are sent WITHOUT the ring DMA copy: the sender publishes a
// sender-owned descriptor {slab ordinal, offset, len} (one descriptor can
// carry the whole payload, not block_size chunks) and the receiver wraps
// the mapped bytes into its IOBuf zero-copy, acking the descriptor only
// when the last reference drops — end-to-end zero-copy with end-to-end
// backpressure.  The staging memory is the device→host DMA landing zone:
// a PJRT pinned-host backend registers it for real DMA via the seam.
// Returns the slab base (page-aligned) or nullptr; *ordinal_out names it
// on the wire.  The caller must not reuse a region until the RPCs that
// reference it completed (same contract as rdma send buffers).
void* ici_staging_alloc(size_t len, uint32_t* ordinal_out);
// Unmaps, unregisters and unlinks.  Safe only once no conn references it.
void ici_staging_free(void* base);
// Process-wide zero-copy send counters (bench/test assertions that the
// staging path really elided the ring copy).
void ici_zero_copy_counters(uint64_t* wrs, uint64_t* bytes);

// Slab registration seam (block_pool::RegisterMemory parity): invoked once
// per receive-window slab.  The default registrar records the slab in a
// process-local table (handle = ordinal).  A real device backend (PJRT
// pinned host memory) swaps itself in here.
void ici_set_slab_registrar(int (*reg)(void* base, size_t len, void* ctx,
                                       uint64_t* handle),
                            void (*unreg)(void* base, size_t len, void* ctx,
                                          uint64_t handle),
                            void* ctx);
// Number of slabs currently registered through the seam (probe/tests).
size_t ici_registered_slab_count();

// Introspection for tests and /vars.
struct IciConnStats {
  uint64_t tx_wrs = 0;           // descriptors published
  uint64_t rx_wrs = 0;           // descriptors consumed
  uint64_t tx_bytes = 0;
  uint64_t rx_bytes = 0;
  uint64_t window_exhausted = 0; // cut_from_iobuf hit a full window
  uint64_t sbuf_held = 0;        // send WRs DMA'd but not yet completed
  uint64_t rx_unposted = 0;      // recv blocks held by consumers (not posted)
  uint64_t tx_zero_copy_wrs = 0;   // sender-owned descriptors published
  uint64_t tx_zero_copy_bytes = 0; // bytes sent without the ring DMA copy
  uint64_t rx_zero_copy_wrs = 0;   // sender-owned descriptors wrapped
  uint32_t slots = 0;
  uint32_t block_size = 0;
};
IciConnStats ici_conn_stats(const IciConn& c);

// Overrides the pid this side published (liveness tests impersonate a
// crashed peer without a full client process).
void ici_conn_set_self_pid(IciConn& c, int32_t pid);

// Fault injection for tests: scribbles the peer-writable desc_consumed
// cursor on `c`'s TX direction, impersonating a hostile/corrupt peer.  The
// poller must fail the socket (EPROTO), not wedge draining toward it.
void ici_conn_corrupt_tx_consumed(IciConn& c, uint64_t value);

// Descriptor lengths publish as uint32: a coalesced zero-copy WR may only
// grow while the published length stays exact (the >4GiB truncation guard
// in cut_from_iobuf's staging coalesce loop; ADVICE r5).
constexpr bool ici_desc_len_fits(uint64_t cur_size, uint64_t add_len) {
  return cur_size + add_len <= 0xffffffffull;
}

// True when `body` should ride sender-owned zero-copy descriptors
// rather than the one-sided rma put path (net/rma.h): at least half its
// bytes already live in OUR registered staging slabs, so descriptors
// move them with ZERO copies — an rma put would add one.  Consulted by
// rma_try_send for SocketMode::kIci bodies.
bool ici_payload_prefers_descriptors(const IOBuf& body);

// Test hooks for the peer-staging mapping path (resolve_stage_source):
// the shm name a peer derives for (pid, ordinal), and the same READ-ONLY
// mapping a receiver makes of a remote peer's staging slab (regression:
// a receiver-side bug must not be able to scribble the sender's
// registered payload memory).  Caller munmaps base/len.
std::string ici_test_stage_shm_name(int32_t pid, uint32_t ordinal);
char* ici_test_map_peer_stage(const std::string& shm_name, size_t* len_out);

}  // namespace trpc
