#include "net/infer.h"

#include <atomic>
#include <deque>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "base/flags.h"
#include "base/logging.h"
#include "base/time.h"
#include "fiber/event.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/concurrency_limiter.h"
#include "net/controller.h"
#include "net/deadline.h"
#include "net/kvstore.h"
#include "net/qos.h"
#include "net/server.h"
#include "net/stream.h"
#include "stat/slo.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

// ---- flags ----------------------------------------------------------------

Flag* batch_max_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_infer_batch_max", 256,
        "continuous-batching decode slots: requests concurrently in the "
        "running batch, one token each per step ([1, 65536]); freed "
        "slots re-admit from the waiting queue the same step");
    if (flag != nullptr) {
      flag->set_int_range(1, 65536);
    }
    return flag;
  }();
  return f;
}

Flag* queue_max_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_infer_queue_max", 200000,
        "admitted-but-not-yet-decoding requests the scheduler will hold "
        "([0, 1000000]); past batch+queue, Infer.Submit sheds with "
        "kEOverloaded (2005) — each waiting request holds its accepted "
        "token stream open, so this bounds logical streams too");
    if (flag != nullptr) {
      flag->set_int_range(0, 1000000);
    }
    return flag;
  }();
  return f;
}

Flag* step_us_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_infer_step_us", 1000,
        "simulated batched forward-pass time per decode step, spent once "
        "per step for the WHOLE batch ([0, 10000000] µs, 0 = no model "
        "cost — drain mode); the knob bench sweeps to model TPOT");
    if (flag != nullptr) {
      flag->set_int_range(0, 10000000);
    }
    return flag;
  }();
  return f;
}

Flag* prefill_us_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_infer_prefill_us_per_token", 5,
        "simulated prefill compute per UNCACHED prompt token ([0, "
        "1000000] µs); prefix-cache-matched tokens skip this entirely — "
        "the measurable recompute the cache saves");
    if (flag != nullptr) {
      flag->set_int_range(0, 1000000);
    }
    return flag;
  }();
  return f;
}

Flag* max_new_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_infer_max_new_tokens", 256,
        "cap on generated tokens per request ([1, 65536]); a submit "
        "asking for more is clamped, and the effective cap is further "
        "clamped to the client's advertised stream window so one slow "
        "reader can never park the shared decode loop");
    if (flag != nullptr) {
      flag->set_int_range(1, 65536);
    }
    return flag;
  }();
  return f;
}

Flag* bytes_per_token_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_infer_bytes_per_token", 64,
        "simulated KV-cache bytes per prompt token ([1, 65536]); sizes "
        "the prefix blocks published after prefill and the "
        "bytes-recomputed/bytes-cached accounting");
    if (flag != nullptr) {
      flag->set_int_range(1, 65536);
    }
    return flag;
  }();
  return f;
}

uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

constexpr size_t kMaxChainBlocks = 64;

// ---- request --------------------------------------------------------------

enum FetchState { kFetchNone = 0, kFetchRunning = 1, kFetchDone = 2 };

struct InferReq {
  uint64_t id = 0;
  std::string tenant;
  StreamId sid = 0;
  int64_t arrival_us = 0;
  uint32_t max_new = 0;
  uint32_t emitted = 0;
  uint32_t nprompt = 0;
  uint32_t cached_tokens = 0;
  uint64_t prompt_hash = 0;
  std::vector<uint64_t> prompt;  // dropped once prefill publishes
  bool publish = true;
  bool decoding = false;  // prefill finished, counters transitioned
  int64_t ready_at_us = 0;
  int64_t first_token_us = 0;
  int64_t last_token_us = 0;
  std::shared_ptr<CancelScope> scope;
  std::atomic<bool> peer_closed{false};
  std::atomic<int> fetch_state{kFetchNone};
  // Matched blocks whose fetch failed for a non-cancel reason fall back
  // to recompute: the fetch fiber counts the tokens, the loop converts
  // them to prefill time once (fetch_state == kFetchDone).
  std::atomic<uint32_t> fallback_tokens{0};
  // Total bytes the prefix fetch plans to pull / has pulled — the delta
  // is what a mid-flight cancel credits to deadline_cancel_saved_bytes.
  uint64_t fetch_total_bytes = 0;
  std::atomic<uint64_t> fetch_done_bytes{0};
  std::vector<KvPrefixMeta> matched;  // one replica meta per matched depth
};

using ReqPtr = std::shared_ptr<InferReq>;

// Tracks detached prefix-fetch fibers so stop() can wait for them to
// retire.  Shared (not scheduler-owned): a retiring fiber touches ONLY
// this block after its decrement, so the scheduler may be freed the
// moment inflight hits zero even if the fiber hasn't returned yet.
struct FetchDrain {
  std::atomic<int64_t> inflight{0};
  Event ev;  // bumped on every retirement
};

}  // namespace

// ---- scheduler ------------------------------------------------------------

class InferScheduler {
 public:
  InferScheduler(Server* s, const InferOptions& opts)
      : srv_(s), opts_(opts) {}

  int start() {
    const int rc = srv_->RegisterMethod(
        "Infer.Submit",
        [this](Controller* cntl, const IOBuf& req, IOBuf* resp,
               Closure done) { submit(cntl, req, resp, std::move(done)); });
    if (rc != 0) {
      return rc;
    }
    if (fiber_start(&loop_fid_, &InferScheduler::loop_entry, this) != 0) {
      return -1;
    }
    loop_started_ = true;
    return 0;
  }

  void stop() {
    stop_.store(true, std::memory_order_release);
    wake();
    if (loop_started_) {
      fiber_join(loop_fid_);
    }
    // The loop's teardown cancelled every request scope, so in-flight
    // fetch fibers abort promptly — but they hold a raw scheduler
    // pointer and may still be inside CallMethod on fetch_ch_ or waking
    // work_ev_.  Wait for every one to retire before anything is freed.
    while (true) {
      const uint32_t snap =
          fetch_drain_->ev.value.load(std::memory_order_acquire);
      if (fetch_drain_->inflight.load(std::memory_order_acquire) == 0) {
        break;
      }
      fetch_drain_->ev.wait(snap, monotonic_time_us() + 50 * 1000);
    }
    std::lock_guard<std::mutex> g(fetch_ch_mu_);
    if (fetch_ch_ != nullptr) {
      delete fetch_ch_;
      fetch_ch_ = nullptr;
    }
  }

  size_t active() const { return active_n_.load(std::memory_order_acquire); }
  size_t waiting() const {
    return waiting_n_.load(std::memory_order_acquire);
  }
  int64_t streams_live() const {
    return streams_live_.load(std::memory_order_acquire);
  }
  int64_t streams_peak() const {
    return streams_peak_.load(std::memory_order_acquire);
  }
  std::string dump_json() const;

 private:
  static void loop_entry(void* arg) {
    static_cast<InferScheduler*>(arg)->loop();
  }

  void wake() {
    work_ev_.value.fetch_add(1, std::memory_order_release);
    work_ev_.wake_all();
  }

  void shed(Controller* cntl, const std::string& tenant) {
    infer_vars().shed_total << 1;
    auto gov = srv_->qos_governor();
    if (gov != nullptr) {
      for (const auto& e : gov->entries()) {
        if (e->name == tenant && e->shed != nullptr) {
          *e->shed << 1;
          break;
        }
      }
    }
    if (timeline::enabled()) {
      timeline::record(timeline::kTokenStep, 0,
                       (timeline::kTokenStepShed << 56) |
                           static_cast<uint64_t>(kEOverloaded));
    }
    cntl->SetFailed(kEOverloaded, "inference batch + queue saturated");
  }

  // Weighted-fair admission under pressure.  Caller holds mu_.
  bool over_share_locked(const std::string& tenant, int64_t cap) {
    int w = qos_tenant_weight(tenant);
    int total_w = 0;
    auto gov = srv_->qos_governor();
    if (gov != nullptr) {
      for (const auto& e : gov->entries()) {
        if (e->name == tenant) {
          w = e->weight;
        }
      }
    }
    bool self_seen = false;
    for (const auto& [name, live] : tenant_live_) {
      if (live <= 0) {
        continue;
      }
      int tw = qos_tenant_weight(name);
      if (gov != nullptr) {
        for (const auto& e : gov->entries()) {
          if (e->name == name) {
            tw = e->weight;
          }
        }
      }
      total_w += tw;
      if (name == tenant) {
        self_seen = true;
      }
    }
    if (!self_seen) {
      total_w += w;
    }
    int64_t share = total_w > 0 ? cap * w / total_w : cap;
    auto slo = srv_->slo_engine();
    if (slo != nullptr && slo->tenant_breached(tenant)) {
      // A tenant burning its error budget is already failing its SLO —
      // halving its share sheds its excess first so it stops dragging
      // the batch for tenants still inside theirs.
      share /= 2;
    }
    if (share < 1) {
      share = 1;
    }
    auto it = tenant_live_.find(tenant);
    const int64_t mine = it != tenant_live_.end() ? it->second : 0;
    return mine >= share;
  }

  void submit(Controller* cntl, const IOBuf& req, IOBuf* resp, Closure done);
  void loop();
  void admit_locked(std::vector<ReqPtr>* admitted);
  void begin_prefill(const ReqPtr& r, int64_t now);
  void fetch_blocks(const ReqPtr& r);
  void publish_blocks(const ReqPtr& r);
  bool step_request(const ReqPtr& r, int64_t now);
  void finish(const ReqPtr& r, bool cancelled);
  void release_slot(const std::string& tenant);

  Server* srv_;
  InferOptions opts_;

  mutable std::mutex mu_;
  std::deque<ReqPtr> waiting_;
  std::unordered_map<std::string, int64_t> tenant_live_;
  std::vector<ReqPtr> active_;  // loop-owned

  Event work_ev_;
  std::atomic<bool> stop_{false};
  fiber_t loop_fid_{};
  bool loop_started_ = false;

  std::atomic<uint64_t> next_id_{1};
  std::atomic<size_t> active_n_{0};
  std::atomic<size_t> waiting_n_{0};
  std::atomic<int64_t> streams_live_{0};
  std::atomic<int64_t> streams_peak_{0};

  std::mutex fetch_ch_mu_;
  Channel* fetch_ch_ = nullptr;
  std::shared_ptr<FetchDrain> fetch_drain_ = std::make_shared<FetchDrain>();
};

void InferScheduler::submit(Controller* cntl, const IOBuf& req, IOBuf* resp,
                            Closure done) {
  infer_vars().submitted_total << 1;
  InferSubmitWire w;
  if (req.size() < sizeof(w)) {
    cntl->SetFailed(EINVAL, "short Infer.Submit request");
    done();
    return;
  }
  req.copy_to(&w, sizeof(w));
  if (w.magic != kInferMagic ||
      req.size() < sizeof(w) + w.n_prompt_tokens * sizeof(uint64_t) ||
      w.n_prompt_tokens > 65536) {
    cntl->SetFailed(EINVAL, "bad Infer.Submit request");
    done();
    return;
  }
  if (cntl->call().peer_stream == 0) {
    cntl->SetFailed(EINVAL, "Infer.Submit must offer a token stream");
    done();
    return;
  }

  const std::string tenant = cntl->qos_tenant();
  const int64_t batch_max = batch_max_flag()->int64_value();
  const int64_t queue_max = queue_max_flag()->int64_value();
  const int64_t cap = batch_max + queue_max;
  {
    std::lock_guard<std::mutex> g(mu_);
    const int64_t live = streams_live_.load(std::memory_order_relaxed);
    if (live >= cap ||
        (live >= (cap + 1) / 2 && over_share_locked(tenant, cap))) {
      shed(cntl, tenant);
      done();
      return;
    }
    // Reserve the slot in the SAME critical section as the cap/share
    // check: N concurrent submits would otherwise all pass the check
    // before any increment lands, overshooting batch+queue and the
    // per-tenant shares.  Failure paths below release the reservation.
    tenant_live_[tenant] += 1;
    const int64_t now_live =
        streams_live_.fetch_add(1, std::memory_order_acq_rel) + 1;
    int64_t peak = streams_peak_.load(std::memory_order_relaxed);
    while (now_live > peak &&
           !streams_peak_.compare_exchange_weak(peak, now_live,
                                                std::memory_order_acq_rel)) {
    }
  }

  auto r = std::make_shared<InferReq>();
  r->id = next_id_.fetch_add(1, std::memory_order_relaxed);
  r->tenant = tenant;
  r->arrival_us = monotonic_time_us();
  r->nprompt = w.n_prompt_tokens;
  r->publish = (w.flags & kSubmitNoPublish) == 0;
  r->prompt.resize(w.n_prompt_tokens);
  if (w.n_prompt_tokens > 0) {
    req.copy_to(r->prompt.data(), w.n_prompt_tokens * sizeof(uint64_t),
                sizeof(w));
  }
  uint64_t h = 0x811c9dc5;
  for (uint64_t t : r->prompt) {
    h = splitmix64(h ^ t);
  }
  r->prompt_hash = h;

  // Prefix-cache match: longest cached chain of the prompt.
  const int64_t block_tokens =
      Flag::find("trpc_kv_prefix_block_tokens") != nullptr
          ? Flag::find("trpc_kv_prefix_block_tokens")->int64_value()
          : 128;
  if (opts_.registry != nullptr && r->nprompt > 0) {
    Key128 keys[kMaxChainBlocks];
    const size_t nkeys =
        kv_prefix_chain(r->prompt.data(), r->nprompt, block_tokens, keys,
                        kMaxChainBlocks);
    std::vector<KvPrefixMeta> replicas;
    const size_t nblocks = opts_.registry->match(keys, nkeys, &replicas);
    // Keep ONE replica per depth (the first listed), in chain order.
    r->matched.reserve(nblocks);
    uint32_t next_depth = 0;
    for (const auto& m : replicas) {
      if (m.depth == next_depth) {
        r->matched.push_back(m);
        r->fetch_total_bytes += m.len;
        ++next_depth;
      }
    }
    r->cached_tokens = static_cast<uint32_t>(
        std::min<uint64_t>(r->matched.size() * block_tokens, r->nprompt));
  }

  uint32_t max_new = w.max_new_tokens != 0
                         ? w.max_new_tokens
                         : static_cast<uint32_t>(
                               max_new_flag()->int64_value());
  max_new = std::min<uint32_t>(
      max_new, static_cast<uint32_t>(max_new_flag()->int64_value()));

  // Accept the offered stream: the per-request token channel.
  StreamOptions sopts;
  std::weak_ptr<InferReq> weak = r;
  sopts.on_closed = [weak](StreamId) {
    if (auto req = weak.lock()) {
      req->peer_closed.store(true, std::memory_order_release);
    }
  };
  StreamId sid = 0;
  if (StreamAccept(&sid, cntl, sopts) != 0) {
    release_slot(tenant);
    cntl->SetFailed(EINVAL, "stream accept failed");
    done();
    return;
  }
  r->sid = sid;
  // Never let one request's token output exceed the client's advertised
  // credit: the decode loop writes without parking.  A window that can't
  // even fit ONE TokenRecord is rejected outright — leaving max_new
  // unclamped would park the shared decode fiber on the first write,
  // stalling every tenant's requests (and the deadline reaper with them).
  const uint64_t credit = stream_send_window(sid);
  if (credit < sizeof(TokenRecord)) {
    StreamClose(sid);
    // Don't advertise the destroyed stream in the failed response — the
    // client's not-accepted path closes its offered end cleanly.
    cntl->call().accepted_stream = 0;
    release_slot(tenant);
    cntl->SetFailed(EINVAL,
                    "stream window smaller than one TokenRecord");
    done();
    return;
  }
  const uint64_t fit = credit / sizeof(TokenRecord);
  if (fit < max_new) {
    max_new = static_cast<uint32_t>(fit);
  }
  r->max_new = max_new > 0 ? max_new : 1;

  // Cancel plane: connection death or budget expiry triggers the scope;
  // the loop polls triggered() and Cancel() fans to in-flight fetches.
  r->scope = std::make_shared<CancelScope>();
  r->scope->socket = cntl->call().socket_id;
  r->scope->deadline_us = cntl->deadline_abs_us();

  {
    std::lock_guard<std::mutex> g(mu_);
    waiting_.push_back(r);
    waiting_n_.store(waiting_.size(), std::memory_order_release);
  }
  wake();

  InferSubmitReply reply;
  reply.request_id = r->id;
  reply.cached_tokens = r->cached_tokens;
  reply.block_tokens = static_cast<uint32_t>(block_tokens);
  resp->append(&reply, sizeof(reply));
  done();
}

// Pops admissible requests while slots remain.  Expired/cancelled waiters
// are finished (not admitted) — their slot never counts.  Caller holds NO
// lock; admitted requests are appended to active_ by the loop.
void InferScheduler::admit_locked(std::vector<ReqPtr>* admitted) {
  const size_t batch_max =
      static_cast<size_t>(batch_max_flag()->int64_value());
  std::lock_guard<std::mutex> g(mu_);
  while (active_.size() + admitted->size() < batch_max &&
         !waiting_.empty()) {
    ReqPtr r = waiting_.front();
    waiting_.pop_front();
    admitted->push_back(std::move(r));
  }
  waiting_n_.store(waiting_.size(), std::memory_order_release);
}

void InferScheduler::begin_prefill(const ReqPtr& r, int64_t now) {
  infer_vars().admitted_total << 1;
  infer_vars().prefill_tokens_total << r->nprompt;
  infer_vars().prefill_cached_tokens_total << r->cached_tokens;
  const int64_t bpt = bytes_per_token_flag()->int64_value();
  const uint32_t recompute = r->nprompt - r->cached_tokens;
  infer_vars().prefill_bytes_recomputed << recompute * bpt;
  r->ready_at_us =
      now + static_cast<int64_t>(recompute) * prefill_us_flag()->int64_value();
  if (timeline::enabled()) {
    timeline::record(timeline::kTokenStep, r->id,
                     (timeline::kTokenStepAdmit << 56) | r->cached_tokens);
  }
  if (!r->matched.empty()) {
    r->fetch_state.store(kFetchRunning, std::memory_order_release);
    struct FetchArg {
      InferScheduler* self;
      ReqPtr req;
      std::shared_ptr<FetchDrain> drain;
    };
    fetch_drain_->inflight.fetch_add(1, std::memory_order_acq_rel);
    auto* arg = new FetchArg{this, r, fetch_drain_};
    fiber_t fid;
    if (fiber_start(
            &fid,
            [](void* p) {
              std::unique_ptr<FetchArg> a(static_cast<FetchArg*>(p));
              a->self->fetch_blocks(a->req);
              // Retire AFTER the last scheduler touch: once inflight
              // hits zero stop() may free the scheduler, so only the
              // shared drain block is safe past this point.
              std::shared_ptr<FetchDrain> drain = std::move(a->drain);
              a.reset();
              drain->inflight.fetch_sub(1, std::memory_order_acq_rel);
              drain->ev.value.fetch_add(1, std::memory_order_release);
              drain->ev.wake_all();
            },
            arg) != 0) {
      fetch_drain_->inflight.fetch_sub(1, std::memory_order_acq_rel);
      delete arg;
      // No fiber: fall back to recompute for every matched block.
      r->fallback_tokens.store(r->cached_tokens, std::memory_order_release);
      r->fetch_state.store(kFetchDone, std::memory_order_release);
    }
  }
}

// Pulls every matched prefix block (local store or Kv.FetchPrefix RPC),
// whole-or-nothing per block, under the request's cancel scope — a
// mid-flight cancel aborts the in-flight RPC via StartCancel fan-out and
// credits every unpulled byte to deadline_cancel_saved_bytes.
void InferScheduler::fetch_blocks(const ReqPtr& r) {
  set_ambient_cancel(r->scope.get());
  set_ambient_deadline(r->scope->deadline_us);
  const int64_t block_tokens =
      Flag::find("trpc_kv_prefix_block_tokens") != nullptr
          ? Flag::find("trpc_kv_prefix_block_tokens")->int64_value()
          : 128;
  size_t fetched = 0;
  bool aborted = false;
  for (const auto& m : r->matched) {
    if (r->scope->triggered() ||
        r->peer_closed.load(std::memory_order_acquire)) {
      aborted = true;
      break;
    }
    int rc = 0;
    IOBuf out;
    if (!opts_.kv_fetch_addr.empty()) {
      std::lock_guard<std::mutex> g(fetch_ch_mu_);
      if (fetch_ch_ == nullptr) {
        fetch_ch_ = new Channel();
        if (fetch_ch_->Init(opts_.kv_fetch_addr) != 0) {
          delete fetch_ch_;
          fetch_ch_ = nullptr;
          rc = -1;
        }
      }
      if (fetch_ch_ != nullptr) {
        KvPrefixWire w;
        memset(&w, 0, sizeof(w));
        w.hash_hi = m.hash.hi;
        w.hash_lo = m.hash.lo;
        w.generation = m.generation;
        IOBuf req;
        req.append(&w, sizeof(w));
        Controller cntl;
        fetch_ch_->CallMethod(kKvPrefixFetchMethod, req, &out, &cntl);
        rc = cntl.Failed() ? cntl.error_code() : 0;
        if (rc == ECANCELED || cntl.error_code() == kEDeadlineExpired) {
          aborted = true;
          break;
        }
      }
    } else if (opts_.store != nullptr) {
      rc = opts_.store->fetch_prefix(m.hash, m.generation, &out);
    } else {
      rc = -1;
    }
    if (rc != 0) {
      // Non-cancel failure (stale replica, miss): recompute the rest of
      // the chain instead — blocks after a hole are unusable anyway.
      break;
    }
    ++fetched;
    r->fetch_done_bytes.fetch_add(out.size(), std::memory_order_acq_rel);
    infer_vars().prefill_bytes_cached << out.size();
  }
  set_ambient_cancel(nullptr);
  set_ambient_deadline(0);
  if (aborted) {
    const uint64_t saved =
        r->fetch_total_bytes -
        r->fetch_done_bytes.load(std::memory_order_acquire);
    if (saved > 0) {
      deadline_vars().cancel_saved_bytes << static_cast<int64_t>(saved);
    }
    infer_vars().prefix_fetch_aborted << 1;
  }
  const uint32_t unfetched = static_cast<uint32_t>(
      std::min<uint64_t>((r->matched.size() - fetched) * block_tokens,
                         r->cached_tokens));
  if (!aborted && unfetched > 0) {
    r->fallback_tokens.store(unfetched, std::memory_order_release);
  }
  r->fetch_state.store(kFetchDone, std::memory_order_release);
  wake();
}

// Publishes the prompt's UNCACHED blocks into the local store + registry
// so the next identical prompt hits (content-addressed: duplicate bytes
// dedup at kEKvExists).  Bytes derive deterministically from the chain
// key so equal prompts hash equal.
void InferScheduler::publish_blocks(const ReqPtr& r) {
  if (opts_.store == nullptr || !r->publish || r->nprompt == 0) {
    return;
  }
  const int64_t block_tokens =
      Flag::find("trpc_kv_prefix_block_tokens") != nullptr
          ? Flag::find("trpc_kv_prefix_block_tokens")->int64_value()
          : 128;
  Key128 keys[kMaxChainBlocks];
  const size_t nkeys = kv_prefix_chain(r->prompt.data(), r->nprompt,
                                       block_tokens, keys, kMaxChainBlocks);
  const int64_t bpt = bytes_per_token_flag()->int64_value();
  const size_t block_bytes =
      static_cast<size_t>(block_tokens) * static_cast<size_t>(bpt);
  std::vector<uint8_t> bytes(block_bytes);
  const size_t first_uncached = r->matched.size();
  for (size_t d = first_uncached; d < nkeys; ++d) {
    uint64_t seed = keys[d].hi ^ keys[d].lo;
    for (size_t i = 0; i < block_bytes; i += 8) {
      const uint64_t v = splitmix64(seed + i);
      const size_t n = std::min<size_t>(8, block_bytes - i);
      memcpy(bytes.data() + i, &v, n);
    }
    KvPrefixMeta meta;
    const int rc = opts_.store->publish_prefix(
        keys[d], static_cast<uint32_t>(d), bytes.data(), block_bytes,
        r->prompt.data() + d * block_tokens, block_tokens, 0, &meta);
    if (rc == kEKvExists) {
      infer_vars().publish_dedup_total << 1;
      continue;
    }
    if (rc != 0) {
      continue;
    }
    if (opts_.registry != nullptr) {
      snprintf(meta.node, sizeof(meta.node), "%s", opts_.node.c_str());
      uint64_t gen = 0;
      opts_.registry->put_prefix(meta, 0, &gen);
    }
  }
}

// One decode step for one active request.  Returns false when the
// request left the batch (done or cancelled).
bool InferScheduler::step_request(const ReqPtr& r, int64_t now) {
  if (!r->decoding) {
    if (r->fetch_state.load(std::memory_order_acquire) == kFetchRunning) {
      return true;  // prefix pull still in flight
    }
    const uint32_t fallback =
        r->fallback_tokens.exchange(0, std::memory_order_acq_rel);
    if (fallback > 0) {
      // Fetch fell back: pay recompute for the unfetched tokens.
      r->ready_at_us += static_cast<int64_t>(fallback) *
                        prefill_us_flag()->int64_value();
      r->cached_tokens -= std::min(fallback, r->cached_tokens);
      infer_vars().prefill_bytes_recomputed
          << static_cast<int64_t>(fallback) *
                 bytes_per_token_flag()->int64_value();
    }
    if (now < r->ready_at_us) {
      return true;  // still prefilling
    }
    publish_blocks(r);
    r->prompt.clear();
    r->prompt.shrink_to_fit();
    r->decoding = true;
    if (timeline::enabled()) {
      timeline::record(timeline::kTokenStep, r->id,
                       timeline::kTokenStepPrefillDone << 56);
    }
  }
  TokenRecord rec;
  rec.token = splitmix64(r->prompt_hash ^ (r->emitted + 1));
  rec.index = r->emitted;
  rec.flags = (r->emitted + 1 >= r->max_new) ? kTokenEos : 0;
  IOBuf chunk;
  chunk.append(&rec, sizeof(rec));
  if (StreamWrite(r->sid, std::move(chunk)) != 0) {
    finish(r, true);
    return false;
  }
  const int64_t t = monotonic_time_us();
  if (r->emitted == 0) {
    r->first_token_us = t;
    infer_vars().ttft << (t - r->arrival_us);
  } else {
    infer_vars().tpot << (t - r->last_token_us);
  }
  r->last_token_us = t;
  r->emitted += 1;
  infer_vars().tokens_total << 1;
  if (timeline::enabled()) {
    timeline::record(timeline::kTokenStep, r->id,
                     (timeline::kTokenStepToken << 56) | (r->emitted - 1));
  }
  if (r->emitted >= r->max_new) {
    finish(r, false);
    return false;
  }
  return true;
}

void InferScheduler::release_slot(const std::string& tenant) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = tenant_live_.find(tenant);
  if (it != tenant_live_.end() && --it->second <= 0) {
    tenant_live_.erase(it);
  }
  streams_live_.fetch_sub(1, std::memory_order_acq_rel);
}

void InferScheduler::finish(const ReqPtr& r, bool cancelled) {
  if (cancelled) {
    // Fan out: aborts in-flight prefix pulls (registered under the scope
    // as ambient cancel) and marks the scope for any late registration.
    r->scope->Cancel();
    if (!r->peer_closed.load(std::memory_order_acquire)) {
      TokenRecord rec;
      rec.index = r->emitted;
      rec.flags = kTokenCancelled;
      IOBuf chunk;
      chunk.append(&rec, sizeof(rec));
      StreamWrite(r->sid, std::move(chunk));  // best effort
    }
    infer_vars().cancelled_total << 1;
  } else {
    infer_vars().done_total << 1;
  }
  if (timeline::enabled()) {
    timeline::record(
        timeline::kTokenStep, r->id,
        ((cancelled ? timeline::kTokenStepCancel : timeline::kTokenStepEos)
         << 56) |
            r->emitted);
  }
  StreamClose(r->sid);
  release_slot(r->tenant);
}

void InferScheduler::loop() {
  infer_ensure_registered();
  std::vector<ReqPtr> admitted;
  while (!stop_.load(std::memory_order_acquire)) {
    int64_t now = monotonic_time_us();

    // 1) Leave: reap finished/cancelled requests FIRST so their slots are
    //    free for this same step's admission scan.
    for (size_t i = 0; i < active_.size();) {
      const ReqPtr& r = active_[i];
      if (r->peer_closed.load(std::memory_order_acquire) ||
          r->scope->triggered(now)) {
        finish(r, true);
        active_[i] = active_.back();
        active_.pop_back();
        continue;
      }
      ++i;
    }

    // 2) Join: admit from the waiting queue into freed slots.  Waiters
    //    whose budget died or whose client left are finished, not
    //    admitted.
    admitted.clear();
    admit_locked(&admitted);
    for (const ReqPtr& r : admitted) {
      if (r->peer_closed.load(std::memory_order_acquire) ||
          r->scope->triggered(now)) {
        finish(r, true);
        continue;
      }
      begin_prefill(r, now);
      active_.push_back(r);
    }
    active_n_.store(active_.size(), std::memory_order_release);

    if (active_.empty()) {
      const uint32_t snap =
          work_ev_.value.load(std::memory_order_acquire);
      bool empty;
      {
        std::lock_guard<std::mutex> g(mu_);
        empty = waiting_.empty();
      }
      if (empty && !stop_.load(std::memory_order_acquire)) {
        work_ev_.wait(snap, monotonic_time_us() + 50 * 1000);
      }
      continue;
    }

    // 3) One simulated batched forward pass for the whole step.
    const int64_t step_us = step_us_flag()->int64_value();
    if (step_us > 0) {
      fiber_sleep_us(step_us);
    } else {
      fiber_yield();
    }
    now = monotonic_time_us();

    // 4) Emit one token per decode-eligible request.
    for (size_t i = 0; i < active_.size();) {
      if (!step_request(active_[i], now)) {
        active_[i] = active_.back();
        active_.pop_back();
        continue;
      }
      ++i;
    }
    active_n_.store(active_.size(), std::memory_order_release);
    infer_vars().steps_total << 1;
  }

  // Stop: cancel everything still in flight.
  for (const ReqPtr& r : active_) {
    finish(r, true);
  }
  active_.clear();
  active_n_.store(0, std::memory_order_release);
  std::deque<ReqPtr> leftovers;
  {
    std::lock_guard<std::mutex> g(mu_);
    leftovers.swap(waiting_);
    waiting_n_.store(0, std::memory_order_release);
  }
  for (const ReqPtr& r : leftovers) {
    finish(r, true);
  }
}

std::string InferScheduler::dump_json() const {
  InferVars& v = infer_vars();
  double ttft[8] = {0};
  double tpot[8] = {0};
  v.ttft.read_stats(ttft);
  v.tpot.read_stats(tpot);
  std::string out = "{";
  auto num = [&out](const char* k, int64_t val, bool comma = true) {
    out += "\"";
    out += k;
    out += "\":";
    out += std::to_string(val);
    if (comma) {
      out += ",";
    }
  };
  num("active", static_cast<int64_t>(active()));
  num("waiting", static_cast<int64_t>(waiting()));
  num("streams_live", streams_live());
  num("streams_peak", streams_peak());
  num("submitted", v.submitted_total.get_value());
  num("admitted", v.admitted_total.get_value());
  num("done", v.done_total.get_value());
  num("cancelled", v.cancelled_total.get_value());
  num("shed", v.shed_total.get_value());
  num("tokens", v.tokens_total.get_value());
  num("steps", v.steps_total.get_value());
  num("prefill_tokens", v.prefill_tokens_total.get_value());
  num("cached_tokens", v.prefill_cached_tokens_total.get_value());
  num("bytes_recomputed", v.prefill_bytes_recomputed.get_value());
  num("bytes_cached", v.prefill_bytes_cached.get_value());
  num("fetch_aborted", v.prefix_fetch_aborted.get_value());
  num("publish_dedup", v.publish_dedup_total.get_value());
  out += "\"ttft\":{";
  num("count", static_cast<int64_t>(ttft[0]));
  num("p50_us", static_cast<int64_t>(ttft[3]));
  num("p99_us", static_cast<int64_t>(ttft[5]), false);
  out += "},\"tpot\":{";
  num("count", static_cast<int64_t>(tpot[0]));
  num("p50_us", static_cast<int64_t>(tpot[3]));
  num("p99_us", static_cast<int64_t>(tpot[5]), false);
  out += "}}";
  return out;
}

// ---- public surface -------------------------------------------------------

InferScheduler* infer_attach(Server* s, const InferOptions& opts) {
  infer_ensure_registered();
  auto* sched = new InferScheduler(s, opts);
  if (sched->start() != 0) {
    delete sched;
    return nullptr;
  }
  return sched;
}

void infer_stop(InferScheduler* sched) {
  if (sched == nullptr) {
    return;
  }
  sched->stop();
  delete sched;
}

size_t infer_active(InferScheduler* sched) { return sched->active(); }
size_t infer_waiting(InferScheduler* sched) { return sched->waiting(); }
int64_t infer_streams_live(InferScheduler* sched) {
  return sched->streams_live();
}
int64_t infer_streams_peak(InferScheduler* sched) {
  return sched->streams_peak();
}
std::string infer_dump_json(InferScheduler* sched) {
  return sched->dump_json();
}

// ---- flags / vars ---------------------------------------------------------

InferVars::InferVars() {
  submitted_total.expose(
      "infer_submitted_total",
      "Infer.Submit requests received (before admission)");
  admitted_total.expose(
      "infer_admitted_total",
      "requests admitted into the continuous batch (began prefill)");
  shed_total.expose(
      "infer_shed_total",
      "Infer.Submit requests shed with kEOverloaded: batch+queue "
      "saturated, or the tenant was over its weighted share under "
      "pressure (halved while burning its SLO error budget)");
  done_total.expose(
      "infer_done_total",
      "requests that completed generation (final token flagged EOS)");
  cancelled_total.expose(
      "infer_cancelled_total",
      "requests cancelled mid-flight: client disconnect, explicit "
      "stream close, or deadline expiry — slot freed the same step");
  tokens_total.expose(
      "infer_tokens_total",
      "tokens emitted across all requests (one per active request per "
      "decode step)");
  steps_total.expose(
      "infer_steps_total",
      "decode steps executed (each = one simulated batched forward "
      "pass + one token per active request)");
  prefill_tokens_total.expose(
      "infer_prefill_tokens_total",
      "prompt tokens across admitted requests (cached + recomputed)");
  prefill_cached_tokens_total.expose(
      "infer_prefill_cached_tokens_total",
      "prompt tokens whose prefill was skipped via a prefix-cache "
      "chain match (net/kvstore.h) instead of recomputed");
  prefill_bytes_recomputed.expose(
      "infer_prefill_bytes_recomputed_total",
      "simulated KV bytes recomputed during prefill (uncached prompt "
      "tokens x trpc_infer_bytes_per_token); the numerator of the "
      "bytes-recomputed ratio the serving bench reports");
  prefill_bytes_cached.expose(
      "infer_prefill_bytes_cached_total",
      "prefix-cache bytes pulled instead of recomputed (local store "
      "hits and Kv.FetchPrefix pulls that completed)");
  prefix_fetch_aborted.expose(
      "infer_prefix_fetch_aborted_total",
      "prefix-block fetch sequences aborted whole-or-nothing by "
      "cancellation mid-flight (unpulled bytes credited to "
      "deadline_cancel_saved_bytes)");
  publish_dedup_total.expose(
      "infer_prefix_publish_dedup_total",
      "post-prefill prefix publishes folded into an existing live "
      "block by content hash (kEKvExists — another request already "
      "published identical bytes)");
  ttft.expose(
      "infer_ttft",
      "time-to-first-token per request: Infer.Submit arrival to the "
      "first TokenRecord write (µs) — queue wait + prefill");
  tpot.expose(
      "infer_tpot",
      "time-per-output-token: gap between consecutive TokenRecord "
      "writes of one request (µs) — decode-step cadence under load");
}

InferVars& infer_vars() {
  static InferVars* v = new InferVars();
  return *v;
}

void infer_ensure_registered() {
  batch_max_flag();
  queue_max_flag();
  step_us_flag();
  prefill_us_flag();
  max_new_flag();
  bytes_per_token_flag();
  infer_vars();
}

}  // namespace trpc
