// Streamed-inference front door — continuous batching over multiplexed
// token streams (ROADMAP item 3, the workload every other plane exists
// for).
//
// One InferScheduler per serving process: requests arrive as normal RPCs
// ("Infer.Submit") that OFFER a stream (net/stream.h); the scheduler
// accepts the stream, admits the request into a continuously-batched
// decode loop, and pushes one TokenRecord per decode step down the
// request's stream.  Requests join and leave the running batch at every
// step — a finished or cancelled request frees its slot before the same
// step's admission scan, so the batch never idles a slot for a step.
//
// Prefill rides the PR 17 content-addressed prefix cache: the prompt's
// token chain (kv_prefix_chain) is matched against a KvRegistry, matched
// blocks are FETCHED (locally zero-copy or over Kv.FetchPrefix from a
// prefill node) instead of recomputed, and only the uncached suffix pays
// simulated prefill time (trpc_infer_prefill_us_per_token).  After
// prefill, the request's uncached blocks are published back so the next
// identical prompt hits.
//
// Cancellation composes the PR 15 plane end-to-end: every request owns a
// CancelScope bound to its submit connection + stamped deadline.  Client
// disconnect (socket failure → stream_on_connection_failed → on_closed),
// an explicit stream close, or budget expiry all cancel the request —
// closing its token stream, aborting in-flight prefix fetches mid-RPC
// (the fetch fiber runs under the scope as ambient cancel, so
// Channel::CallMethod registers the call for StartCancel fan-out), and
// crediting the bytes NOT pulled to deadline_cancel_saved_bytes.  The
// freed slot is re-admitted the same step.
//
// Admission is per-tenant: under pressure (live requests past half the
// box), a tenant above its weighted share (net/qos.h qos_tenant_weight)
// sheds with kEOverloaded (2005); a tenant currently burning its SLO
// error budget (stat/slo.h tenant_breached) has its share halved so
// in-SLO tenants degrade nothing at 2x overload.
#pragma once

#include <cstdint>
#include <string>

#include "base/iobuf.h"
#include "stat/latency_recorder.h"
#include "stat/reducer.h"

namespace trpc {

class Server;
class KvStore;
class KvRegistry;
class InferScheduler;

// ---- wire formats (fixed little-endian; mirrored by ----------------------
// brpc_tpu/rpc/infer.py — infer-wire marker) -------------------------------

// Infer.Submit request: header + n_prompt_tokens x u64 token ids.  The
// request must offer exactly one stream (StreamCreate before CallMethod);
// the response stream carries TokenRecords.
struct InferSubmitWire {
  uint32_t magic = 0;       // kInferMagic
  uint32_t flags = 0;       // kSubmitNoPublish: skip post-prefill publish
  uint32_t max_new_tokens = 0;  // 0 = flag default
  uint32_t n_prompt_tokens = 0;
};
constexpr uint32_t kInferMagic = 0x31464e49;  // "INF1"
constexpr uint32_t kSubmitNoPublish = 1;

// Infer.Submit response.
struct InferSubmitReply {
  uint64_t request_id = 0;
  uint32_t cached_tokens = 0;  // prefix-cache-matched prompt tokens
  uint32_t block_tokens = 0;   // chain block size the match used
};

// One decode step's output for one request (one stream chunk may carry
// exactly one record; readers parse 16-byte records).
struct TokenRecord {
  uint64_t token = 0;
  uint32_t index = 0;  // 0-based position in the generated sequence
  uint32_t flags = 0;
};
constexpr uint32_t kTokenEos = 1;        // final record of a completion
constexpr uint32_t kTokenCancelled = 2;  // stream cancelled mid-decode

// ---- scheduler ------------------------------------------------------------

struct InferOptions {
  // Prefix-cache wiring (all optional; nullptr disables the cache path).
  // `registry` answers chain matches; `store` serves local fetches and
  // receives post-prefill publishes.  When `kv_fetch_addr` is set,
  // matched blocks are pulled over Kv.FetchPrefix from that node instead
  // of the local store (prefill/decode disaggregation) — those pulls are
  // what mid-flight cancellation aborts.
  KvStore* store = nullptr;
  KvRegistry* registry = nullptr;
  std::string kv_fetch_addr;
  // Identity stamped on published prefix replicas.
  std::string node = "local";
};

// Registers "Infer.Submit" on `s` and starts the scheduler loop.  Returns
// nullptr when registration fails.  The scheduler must be stopped with
// infer_stop BEFORE the server is destroyed (it holds the Server* only
// for registration-time use; the loop owns no server state).
InferScheduler* infer_attach(Server* s, const InferOptions& opts);
// Stops the loop, cancels every queued/active request (closing their
// streams with kTokenCancelled), joins the loop fiber, waits for
// in-flight prefix-fetch fibers to retire, and frees the scheduler.
// Idempotent per pointer is NOT provided — call once.
void infer_stop(InferScheduler* sched);

// Introspection (capi / tests / the /infer builtin).
size_t infer_active(InferScheduler* sched);
size_t infer_waiting(InferScheduler* sched);
// Streams concurrently held (waiting + active), and the high-water mark —
// the ≥100k-logical-streams proof the orchestrator reads.
int64_t infer_streams_live(InferScheduler* sched);
int64_t infer_streams_peak(InferScheduler* sched);
// {"active","waiting","streams_live","streams_peak","submitted","done",
//  "cancelled","shed","tokens","steps","prefill_tokens","cached_tokens",
//  "bytes_recomputed","bytes_cached","fetch_aborted","publish_dedup",
//  "ttft":{count,p50_us,p99_us},"tpot":{count,p50_us,p99_us}}
std::string infer_dump_json(InferScheduler* sched);

// ---- flags / vars ---------------------------------------------------------

struct InferVars {
  Adder submitted_total;       // infer_submitted_total
  Adder admitted_total;        // infer_admitted_total
  Adder shed_total;            // infer_shed_total
  Adder done_total;            // infer_done_total
  Adder cancelled_total;       // infer_cancelled_total
  Adder tokens_total;          // infer_tokens_total
  Adder steps_total;           // infer_steps_total
  Adder prefill_tokens_total;  // infer_prefill_tokens_total
  Adder prefill_cached_tokens_total;  // infer_prefill_cached_tokens_total
  Adder prefill_bytes_recomputed;     // infer_prefill_bytes_recomputed_total
  Adder prefill_bytes_cached;         // infer_prefill_bytes_cached_total
  Adder prefix_fetch_aborted;  // infer_prefix_fetch_aborted_total
  Adder publish_dedup_total;   // infer_prefix_publish_dedup_total
  LatencyRecorder ttft;        // infer_ttft (submit → first token, µs)
  LatencyRecorder tpot;        // infer_tpot (inter-token gap, µs)
  InferVars();
};
InferVars& infer_vars();
// Registers the trpc_infer_* flags and the vars (idempotent).
void infer_ensure_registered();

}  // namespace trpc
