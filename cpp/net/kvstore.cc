#include "net/kvstore.h"

#include <errno.h>
#include <string.h>

#include <algorithm>
#include <limits>

#include "base/flags.h"
#include "base/logging.h"
#include "base/time.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/rma.h"
#include "net/server.h"
#include "stat/latency_recorder.h"
#include "stat/reducer.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

Flag* lease_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_kv_lease_ms", 30000,
        "default KV-block lease for publishes/registrations that pass "
        "lease_ms <= 0 (ms, [50, 86400000]); an expired lease "
        "invalidates the block everywhere — lookups answer kv-miss, "
        "fetches answer kv-stale");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 50 &&
               n <= 86400000;
      });
    }
    return flag;
  }();
  return f;
}

Flag* store_bytes_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_kv_store_bytes", 1ll << 30,
        "node-local KV-block store byte budget ([1MB, 64GB]); a publish "
        "that would exceed it evicts expired-then-LRU blocks (their "
        "generation tombstones survive, so evicted fetches answer "
        "kv-stale, never partial bytes)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= (1ll << 20) &&
               n <= (64ll << 30);
      });
    }
    return flag;
  }();
  return f;
}

Flag* prefix_hot_bytes_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_kv_prefix_hot_bytes", 256ll << 20,
        "hot-tier byte budget for content-addressed prefix blocks "
        "([1MB, 64GB]); hot blocks live in registered-RMA pages and "
        "serve zero-copy — exceeding the budget DEMOTES LRU blocks to "
        "the unregistered cold tier (never drops them)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= (1ll << 20) &&
               n <= (64ll << 30);
      });
    }
    return flag;
  }();
  return f;
}

Flag* prefix_block_tokens_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_kv_prefix_block_tokens", 128,
        "token span per prefix-cache block ([1, 65536]); chain keys fold "
        "one block_tokens-sized chunk at a time, so every node in the "
        "fleet MUST agree on this value for content hashes to dedup");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 1 && n <= 65536;
      });
    }
    return flag;
  }();
  return f;
}

int64_t effective_lease_us(int64_t lease_ms) {
  if (lease_ms <= 0) {
    lease_ms = lease_flag() != nullptr ? lease_flag()->int64_value() : 30000;
  }
  return monotonic_time_us() + lease_ms * 1000;
}

// ---- vars ----------------------------------------------------------------

struct KvVars {
  Adder publish_total;
  Adder evict_total;
  Adder fetch_total;
  Adder fetch_bytes;
  Adder stale_total;
  Adder register_total;
  Adder lookup_total;
  Adder lookup_miss_total;
  std::unique_ptr<PassiveStatus<long>> store_blocks;
  std::unique_ptr<PassiveStatus<long>> store_bytes;
  std::unique_ptr<PassiveStatus<long>> registry_blocks;
  KvVars() {
    publish_total.expose(
        "kv_publish_total",
        "KV blocks published into this node's block store");
    evict_total.expose(
        "kv_evict_total",
        "KV blocks evicted from this node's store (budget pressure, "
        "lease expiry, or explicit withdraw)");
    fetch_total.expose("kv_fetch_total",
                       "KV block fetches served by this node");
    fetch_bytes.expose("kv_fetch_bytes",
                       "payload bytes served by KV block fetches");
    stale_total.expose(
        "kv_stale_total",
        "KV fetches rejected with kv-stale (generation mismatch, lease "
        "lapsed, or evicted block) — each one invalidates a client's "
        "cached lookup");
    register_total.expose("kv_register_total",
                          "KV-block registrations accepted by the "
                          "registry on this node");
    lookup_total.expose("kv_lookup_total",
                        "KV-block lookups answered by the registry on "
                        "this node");
    lookup_miss_total.expose(
        "kv_lookup_miss_total",
        "registry lookups answering kv-miss (unknown block or expired "
        "lease)");
    store_blocks = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_store().count()); });
    store_blocks->expose("kv_store_blocks",
                         "KV blocks currently live in this node's store");
    store_bytes = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_store().bytes_used()); });
    store_bytes->expose(
        "kv_store_bytes",
        "payload bytes currently held by this node's KV store (bounded "
        "by trpc_kv_store_bytes)");
    registry_blocks = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_registry().count()); });
    registry_blocks->expose(
        "kv_registry_blocks",
        "KV-block records currently live in the registry on this node");
  }
};

KvVars& kv_vars() {
  static KvVars* v = new KvVars();
  return *v;
}

struct KvPrefixVars {
  Adder publish_total;
  Adder fetch_total;
  Adder put_total;
  Adder match_total;
  Adder match_blocks;
  std::unique_ptr<PassiveStatus<long>> dedup_total;
  std::unique_ptr<PassiveStatus<long>> promote_total;
  std::unique_ptr<PassiveStatus<long>> demote_total;
  std::unique_ptr<PassiveStatus<long>> hot_hit_total;
  std::unique_ptr<PassiveStatus<long>> cold_hit_total;
  std::unique_ptr<PassiveStatus<long>> store_blocks;
  std::unique_ptr<PassiveStatus<long>> store_hot_bytes;
  std::unique_ptr<PassiveStatus<long>> store_cold_bytes;
  std::unique_ptr<PassiveStatus<long>> registry_records;
  KvPrefixVars() {
    publish_total.expose(
        "kv_prefix_publish_total",
        "content-addressed prefix blocks published (fresh bytes copied "
        "into this node's two-tier prefix store)");
    fetch_total.expose("kv_prefix_fetch_total",
                       "prefix-block fetches served by this node (hot "
                       "zero-copy + cold/promoted)");
    put_total.expose(
        "kv_prefix_put_total",
        "prefix-replica registrations accepted by the registry on this "
        "node (one chain key folds N publishers into a replica set)");
    match_total.expose(
        "kv_prefix_match_total",
        "longest-cached-prefix queries answered by the registry on this "
        "node (KvReg.Match walks chain keys until first miss)");
    match_blocks.expose(
        "kv_prefix_match_blocks",
        "prefix blocks matched across all KvReg.Match answers (sum of "
        "matched depths — divide by kv_prefix_match_total for the mean "
        "cached-prefix length)");
    dedup_total = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          KvPrefixCounters::read(kv_prefix_counters().dedup));
    });
    dedup_total->expose(
        "kv_prefix_dedup_total",
        "publishes that folded into an existing replica set instead of "
        "minting a new record (fleet-wide content dedup events)");
    promote_total = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          KvPrefixCounters::read(kv_prefix_counters().promote));
    });
    promote_total->expose(
        "kv_prefix_promote_total",
        "cold prefix blocks promoted back into registered-RMA pages on "
        "fetch (promotion-on-hit)");
    demote_total = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          KvPrefixCounters::read(kv_prefix_counters().demote));
    });
    demote_total->expose(
        "kv_prefix_demote_total",
        "hot prefix blocks spilled to the unregistered cold tier under "
        "trpc_kv_prefix_hot_bytes pressure (demoted, not dropped)");
    hot_hit_total = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          KvPrefixCounters::read(kv_prefix_counters().hot_hits));
    });
    hot_hit_total->expose(
        "kv_prefix_hot_hit_total",
        "prefix fetches served zero-copy from hot registered pages");
    cold_hit_total = std::make_unique<PassiveStatus<long>>([] {
      return static_cast<long>(
          KvPrefixCounters::read(kv_prefix_counters().cold_hits));
    });
    cold_hit_total->expose(
        "kv_prefix_cold_hit_total",
        "prefix fetches that found the block demoted in the cold tier "
        "(each one attempts promotion back to hot)");
    store_blocks = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_store().prefix_count()); });
    store_blocks->expose(
        "kv_prefix_store_blocks",
        "prefix blocks currently live in this node's two-tier store");
    store_hot_bytes = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_store().prefix_hot_bytes()); });
    store_hot_bytes->expose(
        "kv_prefix_store_hot_bytes",
        "prefix bytes currently pinned in registered-RMA pages (bounded "
        "by trpc_kv_prefix_hot_bytes)");
    store_cold_bytes = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_store().prefix_cold_bytes()); });
    store_cold_bytes->expose(
        "kv_prefix_store_cold_bytes",
        "prefix bytes currently demoted to the unregistered cold tier "
        "(counted against trpc_kv_store_bytes)");
    registry_records = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_registry().prefix_count()); });
    registry_records->expose(
        "kv_prefix_registry_records",
        "chain keys with at least one live replica in the registry on "
        "this node");
  }
};

KvPrefixVars& kv_prefix_vars() {
  static KvPrefixVars* v = new KvPrefixVars();
  return *v;
}

void record_kv(uint64_t block_id, uint64_t op, uint64_t len) {
  if (timeline::enabled()) {
    timeline::record(timeline::kKvBlock, block_id,
                     (op << 56) | (len & ((1ull << 56) - 1)));
  }
}

}  // namespace

void kv_ensure_registered() {
  lease_flag();
  store_bytes_flag();
  prefix_hot_bytes_flag();
  prefix_block_tokens_flag();
  kv_vars();
  kv_prefix_vars();
}

KvPrefixCounters& kv_prefix_counters() {
  static KvPrefixCounters* c = new KvPrefixCounters();
  return *c;
}

// ---- content addressing --------------------------------------------------

namespace {

// splitmix64 finalizer: full-avalanche 64-bit mix, deterministic across
// processes and architectures (the dedup contract).
inline uint64_t kv_mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdull;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ull;
  x ^= x >> 33;
  return x;
}

}  // namespace

void kv_content_hash(const void* data, size_t len, const uint64_t* tokens,
                     size_t ntokens, Key128* out) {
  // Two lanes with distinct seeds and distinct fold ops (xor-mix vs
  // add-mix) so hi/lo fail independently — 128 bits of key space from
  // two 64-bit walks.  Length and token count seed the lanes: a prefix
  // of the bytes can never alias the whole.
  uint64_t h1 = 0x9e3779b97f4a7c15ull ^ kv_mix64(len);
  uint64_t h2 = 0xc2b2ae3d27d4eb4full ^ kv_mix64(ntokens + 0x100);
  const unsigned char* p = static_cast<const unsigned char*>(data);
  size_t i = 0;
  for (; i + 8 <= len; i += 8) {
    uint64_t w;
    memcpy(&w, p + i, 8);
    h1 = kv_mix64(h1 ^ w);
    h2 = kv_mix64(h2 + w);
  }
  if (i < len) {
    uint64_t tail = 0;
    memcpy(&tail, p + i, len - i);
    h1 = kv_mix64(h1 ^ tail);
    h2 = kv_mix64(h2 + tail);
  }
  for (size_t t = 0; t < ntokens; ++t) {
    h1 = kv_mix64(h1 ^ tokens[t]);
    h2 = kv_mix64(h2 + kv_mix64(tokens[t]));
  }
  out->hi = h1;
  out->lo = h2;
}

size_t kv_prefix_chain(const uint64_t* tokens, size_t ntokens,
                       int64_t block_tokens, Key128* keys,
                       size_t max_keys) {
  kv_ensure_registered();
  if (block_tokens <= 0) {
    block_tokens = prefix_block_tokens_flag() != nullptr
                       ? prefix_block_tokens_flag()->int64_value()
                       : 128;
  }
  const size_t bt = static_cast<size_t>(std::max<int64_t>(block_tokens, 1));
  const size_t nblocks = ntokens / bt;
  // Chain seed folds the block size: the same token stream chunked at a
  // different granularity must never alias the same chain keys.
  Key128 prev;
  prev.hi = 0x27d4eb2f165667c5ull ^ kv_mix64(bt);
  prev.lo = 0x85ebca77c2b2ae63ull + kv_mix64(bt);
  size_t written = 0;
  for (size_t b = 0; b < nblocks && written < max_keys; ++b) {
    uint64_t h1 = prev.hi;
    uint64_t h2 = prev.lo;
    for (size_t t = b * bt; t < (b + 1) * bt; ++t) {
      h1 = kv_mix64(h1 ^ tokens[t]);
      h2 = kv_mix64(h2 + kv_mix64(tokens[t] ^ 0x94d049bb133111ebull));
    }
    keys[written].hi = h1;
    keys[written].lo = h2;
    prev = keys[written];
    ++written;
  }
  return written;
}

// ---- KvStore -------------------------------------------------------------

KvStore& kv_store() {
  static KvStore* s = new KvStore();
  return *s;
}

void KvStore::evict_locked(uint64_t block_id, bool count_var) {
  auto it = blocks_.find(block_id);
  if (it == blocks_.end()) {
    return;
  }
  tombstones_[block_id] = it->second.meta.generation;
  bytes_ -= it->second.meta.len;
  record_kv(block_id, kKvOpEvict, it->second.meta.len);
  blocks_.erase(it);
  if (count_var) {
    kv_vars().evict_total << 1;
  }
}

int KvStore::publish(uint64_t block_id, const void* data, size_t len,
                     int64_t lease_ms, KvBlockMeta* out,
                     uint64_t min_generation) {
  kv_ensure_registered();
  if (data == nullptr || len == 0) {
    return -1;
  }
  uint64_t rkey = 0;
  uint64_t off = 0;
  std::shared_ptr<RmaMapping> map =
      rma_pin_exportable(data, len, &rkey, &off);
  if (map == nullptr) {
    return -1;  // not registered memory: the store serves zero-copy only
  }
  const uint64_t budget = static_cast<uint64_t>(std::max<int64_t>(
      store_bytes_flag() != nullptr ? store_bytes_flag()->int64_value()
                                    : (1ll << 30),
      1));
  if (len > budget) {
    return -1;  // cannot fit even an empty store
  }
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  if (it != blocks_.end()) {
    if (it->second.deadline_us > now) {
      return kEKvExists;  // live block: ownership is exclusive
    }
    evict_locked(block_id, /*count_var=*/true);  // lapsed: fold to tombstone
  }
  // Budget pressure: evict expired leases first, then LRU by touch_seq.
  while (bytes_ + len > budget && !blocks_.empty()) {
    uint64_t victim = 0;
    uint64_t oldest_touch = std::numeric_limits<uint64_t>::max();
    bool found_expired = false;
    for (const auto& [id, b] : blocks_) {
      if (b.deadline_us <= now) {
        victim = id;
        found_expired = true;
        break;
      }
      if (b.touch_seq < oldest_touch) {
        oldest_touch = b.touch_seq;
        victim = id;
      }
    }
    (void)found_expired;
    evict_locked(victim, /*count_var=*/true);
  }
  Block b;
  b.meta.block_id = block_id;
  // min_generation: a hot-restart successor continues the DEAD pid's
  // sequence (its own tombstones start empty) by flooring at
  // last-known-gen + 1, so the registry's zombie fence accepts the
  // takeover and old cached records fail kv-stale into a re-resolve.
  b.meta.generation =
      std::max(tombstones_[block_id] + 1, min_generation);
  tombstones_[block_id] = b.meta.generation;
  b.meta.rkey = rkey;
  b.meta.off = off;
  b.meta.len = len;
  b.data = static_cast<const char*>(data);
  b.map = std::move(map);
  b.deadline_us = effective_lease_us(lease_ms);
  b.touch_seq = ++touch_counter_;
  bytes_ += len;
  if (out != nullptr) {
    *out = b.meta;
  }
  record_kv(block_id, kKvOpPublish, len);
  blocks_[block_id] = std::move(b);
  kv_vars().publish_total << 1;
  return 0;
}

int KvStore::withdraw(uint64_t block_id) {
  std::lock_guard<std::mutex> g(mu_);
  if (blocks_.find(block_id) == blocks_.end()) {
    return kEKvMiss;
  }
  evict_locked(block_id, /*count_var=*/true);
  return 0;
}

size_t KvStore::withdraw_all() {
  std::lock_guard<std::mutex> g(mu_);
  size_t n = 0;
  while (!blocks_.empty()) {
    evict_locked(blocks_.begin()->first, /*count_var=*/true);
    ++n;
  }
  // Drain covers the prefix tier too: every cached prefix block
  // tombstones, so a decode side holding this node's replica records
  // gets kv-stale and fails over to another replica (or re-publishes) —
  // never bytes from a dying pid.
  while (!prefix_blocks_.empty()) {
    evict_prefix_locked(prefix_blocks_.begin()->first);
    ++n;
  }
  return n;
}

int KvStore::renew(uint64_t block_id, int64_t lease_ms) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  if (it == blocks_.end()) {
    return kEKvMiss;
  }
  it->second.deadline_us = effective_lease_us(lease_ms);
  return 0;
}

namespace {
// Deleter context for a served block: co-owns the region mapping so the
// bytes stay mapped until the response's last IOBuf reference drops
// (send queues, rma rails, a late cancel) — rma_free's munmap defers.
struct KvServeCtx {
  std::shared_ptr<RmaMapping> map;
};
void kv_serve_deleter(void*, void* vctx) {
  delete static_cast<KvServeCtx*>(vctx);
}
}  // namespace

int KvStore::fetch(uint64_t block_id, uint64_t expected_gen, IOBuf* out) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  const int64_t now = monotonic_time_us();
  if (it == blocks_.end() || it->second.deadline_us <= now) {
    if (it != blocks_.end()) {
      // Lease lapsed: fold to a tombstone NOW — serve time is the
      // validity decision point, so a fetch racing the expiry can
      // never admit the stale bytes.
      evict_locked(block_id, /*count_var=*/true);
    }
    const bool known = tombstones_.find(block_id) != tombstones_.end();
    if (known) {
      kv_vars().stale_total << 1;
      record_kv(block_id, kKvOpStale, 0);
      return kEKvStale;
    }
    return kEKvMiss;
  }
  Block& b = it->second;
  if (b.meta.generation != expected_gen) {
    kv_vars().stale_total << 1;
    record_kv(block_id, kKvOpStale, b.meta.len);
    return kEKvStale;
  }
  b.touch_seq = ++touch_counter_;
  auto* ctx = new KvServeCtx{b.map};
  out->append_user_data(const_cast<char*>(b.data), b.meta.len,
                        &kv_serve_deleter, ctx);
  kv_vars().fetch_total << 1;
  kv_vars().fetch_bytes << static_cast<int64_t>(b.meta.len);
  record_kv(block_id, kKvOpServe, b.meta.len);
  return 0;
}

int KvStore::pin(uint64_t block_id, uint64_t expected_gen,
                 const char** data, uint64_t* len,
                 std::shared_ptr<RmaMapping>* map, uint64_t* gen_out) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  const int64_t now = monotonic_time_us();
  if (it == blocks_.end() || it->second.deadline_us <= now) {
    if (it != blocks_.end()) {
      evict_locked(block_id, /*count_var=*/true);  // serve-time validity
    }
    return tombstones_.find(block_id) != tombstones_.end() ? kEKvStale
                                                           : kEKvMiss;
  }
  Block& b = it->second;
  if (expected_gen != 0 && b.meta.generation != expected_gen) {
    return kEKvStale;
  }
  b.touch_seq = ++touch_counter_;
  if (data != nullptr) {
    *data = b.data;
  }
  if (len != nullptr) {
    *len = b.meta.len;
  }
  if (map != nullptr) {
    *map = b.map;
  }
  if (gen_out != nullptr) {
    *gen_out = b.meta.generation;
  }
  return 0;
}

size_t KvStore::count() {
  std::lock_guard<std::mutex> g(mu_);
  return blocks_.size();
}

uint64_t KvStore::bytes_used() {
  std::lock_guard<std::mutex> g(mu_);
  return bytes_;
}

void KvStore::clear() {
  std::lock_guard<std::mutex> g(mu_);
  blocks_.clear();
  tombstones_.clear();
  bytes_ = 0;
  for (auto& [hash, b] : prefix_blocks_) {
    if (b.hot && b.hot_data != nullptr) {
      b.map.reset();
      rma_free(b.hot_data);
    }
  }
  prefix_blocks_.clear();
  prefix_tombstones_.clear();
  prefix_hot_bytes_ = 0;
  prefix_cold_bytes_ = 0;
}

// ---- KvStore prefix tier (two-tier content-addressed store) --------------

void KvStore::demote_locked(PrefixBlock* b) {
  if (!b->hot) {
    return;
  }
  // Copy out FIRST, then release the pages: any in-flight serve holds
  // its own mapping reference (KvServeCtx), so rma_free's munmap defers
  // past it — the demote is invisible to readers mid-response.
  b->cold.assign(b->hot_data, b->meta.len);
  b->map.reset();
  rma_free(b->hot_data);
  b->hot_data = nullptr;
  b->meta.rkey = 0;
  b->meta.off = 0;
  b->hot = false;
  prefix_hot_bytes_ -= b->meta.len;
  prefix_cold_bytes_ += b->meta.len;
  kv_prefix_counters().bump(kv_prefix_counters().demote);
  record_kv(b->meta.hash.lo, kKvOpDemote, b->meta.len);
}

void KvStore::evict_prefix_locked(const Key128& hash) {
  auto it = prefix_blocks_.find(hash);
  if (it == prefix_blocks_.end()) {
    return;
  }
  PrefixBlock& b = it->second;
  prefix_tombstones_[hash] = b.meta.generation;
  if (b.hot) {
    prefix_hot_bytes_ -= b.meta.len;
    b.map.reset();
    rma_free(b.hot_data);
  } else {
    prefix_cold_bytes_ -= b.meta.len;
  }
  record_kv(hash.lo, kKvOpEvict, b.meta.len);
  kv_vars().evict_total << 1;
  prefix_blocks_.erase(it);
}

bool KvStore::fit_hot_locked(uint64_t incoming, uint64_t hot_budget) {
  if (incoming > hot_budget) {
    return false;  // publishes straight to cold
  }
  // Hot pressure DEMOTES (never drops): the bytes stay serveable, they
  // just lose the zero-copy fast path until a hit promotes them back.
  while (prefix_hot_bytes_ + incoming > hot_budget) {
    PrefixBlock* victim = nullptr;
    uint64_t oldest_touch = std::numeric_limits<uint64_t>::max();
    for (auto& [hash, b] : prefix_blocks_) {
      if (b.hot && b.touch_seq < oldest_touch) {
        oldest_touch = b.touch_seq;
        victim = &b;
      }
    }
    if (victim == nullptr) {
      return false;  // nothing left to demote yet still over: can't fit
    }
    demote_locked(victim);
  }
  return true;
}

int KvStore::publish_prefix(const Key128& key, uint32_t depth,
                            const void* data, size_t len,
                            const uint64_t* tokens, size_t ntokens,
                            int64_t lease_ms, KvPrefixMeta* out,
                            uint64_t min_generation) {
  kv_ensure_registered();
  if (key.zero() || data == nullptr || len == 0) {
    return -1;
  }
  Key128 hash;
  kv_content_hash(data, len, tokens, ntokens, &hash);
  const uint64_t total_budget = static_cast<uint64_t>(std::max<int64_t>(
      store_bytes_flag() != nullptr ? store_bytes_flag()->int64_value()
                                    : (1ll << 30),
      1));
  const uint64_t hot_budget = static_cast<uint64_t>(std::max<int64_t>(
      prefix_hot_bytes_flag() != nullptr
          ? prefix_hot_bytes_flag()->int64_value()
          : (256ll << 20),
      1));
  if (len > total_budget) {
    return -1;
  }
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = prefix_blocks_.find(hash);
  if (it != prefix_blocks_.end()) {
    if (it->second.deadline_us > now) {
      // Live block with identical content: THE cache-hit path.  The
      // lease renews and the record echoes, but kEKvExists tells the
      // caller these bytes did NOT need recomputing/copying.
      PrefixBlock& b = it->second;
      b.deadline_us = effective_lease_us(lease_ms);
      b.touch_seq = ++touch_counter_;
      if (out != nullptr) {
        *out = b.meta;
      }
      return kEKvExists;
    }
    evict_prefix_locked(hash);  // lapsed: fold to tombstone, re-admit
  }
  // Total-store pressure (blocks + hot + cold vs trpc_kv_store_bytes):
  // expired blocks drop first, then LRU cold, then LRU hot — dropping
  // always tombstones so evicted fetches answer kv-stale.
  while (bytes_ + prefix_hot_bytes_ + prefix_cold_bytes_ + len >
             total_budget &&
         !prefix_blocks_.empty()) {
    Key128 victim;
    uint64_t oldest_cold = std::numeric_limits<uint64_t>::max();
    uint64_t oldest_hot = std::numeric_limits<uint64_t>::max();
    Key128 victim_cold;
    Key128 victim_hot;
    bool found = false;
    for (const auto& [h, b] : prefix_blocks_) {
      if (b.deadline_us <= now) {
        victim = h;
        found = true;
        break;
      }
      if (!b.hot && b.touch_seq < oldest_cold) {
        oldest_cold = b.touch_seq;
        victim_cold = h;
      }
      if (b.hot && b.touch_seq < oldest_hot) {
        oldest_hot = b.touch_seq;
        victim_hot = h;
      }
    }
    if (!found) {
      victim = oldest_cold != std::numeric_limits<uint64_t>::max()
                   ? victim_cold
                   : victim_hot;
    }
    evict_prefix_locked(victim);
  }
  if (bytes_ + prefix_hot_bytes_ + prefix_cold_bytes_ + len >
      total_budget) {
    return -1;  // regular blocks own the budget: don't evict them here
  }
  PrefixBlock b;
  b.meta.key = key;
  b.meta.hash = hash;
  b.meta.generation =
      std::max(prefix_tombstones_[hash] + 1, min_generation);
  prefix_tombstones_[hash] = b.meta.generation;
  b.meta.len = len;
  b.meta.depth = depth;
  b.deadline_us = effective_lease_us(lease_ms);
  b.touch_seq = ++touch_counter_;
  // Hot placement: store-owned registered pages so fetches serve
  // zero-copy.  Falls to the cold tier when the block outsizes the hot
  // budget or registered memory is exhausted — cold still serves.
  bool placed_hot = false;
  if (fit_hot_locked(len, hot_budget)) {
    uint64_t rkey = 0;
    void* pages = rma_alloc(len, &rkey);
    if (pages != nullptr) {
      memcpy(pages, data, len);
      uint64_t pin_rkey = 0;
      uint64_t pin_off = 0;
      b.map = rma_pin_exportable(pages, len, &pin_rkey, &pin_off);
      if (b.map != nullptr) {
        b.hot_data = static_cast<char*>(pages);
        b.meta.rkey = pin_rkey;
        b.meta.off = pin_off;
        b.hot = true;
        prefix_hot_bytes_ += len;
        placed_hot = true;
      } else {
        rma_free(pages);
      }
    }
  }
  if (!placed_hot) {
    b.cold.assign(static_cast<const char*>(data), len);
    prefix_cold_bytes_ += len;
  }
  if (out != nullptr) {
    *out = b.meta;
  }
  record_kv(hash.lo, kKvOpPublish, len);
  prefix_blocks_[hash] = std::move(b);
  kv_prefix_vars().publish_total << 1;
  return 0;
}

int KvStore::fetch_prefix(const Key128& hash, uint64_t expected_gen,
                          IOBuf* out) {
  kv_ensure_registered();
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = prefix_blocks_.find(hash);
  if (it == prefix_blocks_.end() || it->second.deadline_us <= now) {
    if (it != prefix_blocks_.end()) {
      evict_prefix_locked(hash);  // serve-time validity, as fetch()
    }
    if (prefix_tombstones_.find(hash) != prefix_tombstones_.end()) {
      kv_vars().stale_total << 1;
      record_kv(hash.lo, kKvOpStale, 0);
      return kEKvStale;
    }
    return kEKvMiss;
  }
  PrefixBlock& b = it->second;
  // expected_gen 0 accepts any live generation (content addressing
  // already names the exact bytes; the generation only fences zombies).
  if (expected_gen != 0 && b.meta.generation != expected_gen) {
    kv_vars().stale_total << 1;
    record_kv(hash.lo, kKvOpStale, b.meta.len);
    return kEKvStale;
  }
  b.touch_seq = ++touch_counter_;
  if (!b.hot) {
    kv_prefix_counters().bump(kv_prefix_counters().cold_hits);
    // Promotion-on-hit: copy back into registered pages so the NEXT
    // fetch is zero-copy again.  Failure to promote (registered memory
    // exhausted) still serves — a plain copy of the cold bytes.
    const uint64_t hot_budget = static_cast<uint64_t>(std::max<int64_t>(
        prefix_hot_bytes_flag() != nullptr
            ? prefix_hot_bytes_flag()->int64_value()
            : (256ll << 20),
        1));
    bool promoted = false;
    if (fit_hot_locked(b.meta.len, hot_budget)) {
      uint64_t rkey = 0;
      void* pages = rma_alloc(b.meta.len, &rkey);
      if (pages != nullptr) {
        memcpy(pages, b.cold.data(), b.meta.len);
        uint64_t pin_rkey = 0;
        uint64_t pin_off = 0;
        std::shared_ptr<RmaMapping> map =
            rma_pin_exportable(pages, b.meta.len, &pin_rkey, &pin_off);
        if (map != nullptr) {
          b.hot_data = static_cast<char*>(pages);
          b.map = std::move(map);
          b.meta.rkey = pin_rkey;
          b.meta.off = pin_off;
          b.hot = true;
          prefix_hot_bytes_ += b.meta.len;
          prefix_cold_bytes_ -= b.meta.len;
          b.cold.clear();
          b.cold.shrink_to_fit();
          kv_prefix_counters().bump(kv_prefix_counters().promote);
          record_kv(hash.lo, kKvOpPromote, b.meta.len);
          promoted = true;
        } else {
          rma_free(pages);
        }
      }
    }
    if (!promoted) {
      out->append(b.cold.data(), b.meta.len);
      kv_prefix_vars().fetch_total << 1;
      kv_vars().fetch_bytes << static_cast<int64_t>(b.meta.len);
      record_kv(hash.lo, kKvOpServe, b.meta.len);
      return 0;
    }
  } else {
    kv_prefix_counters().bump(kv_prefix_counters().hot_hits);
  }
  auto* ctx = new KvServeCtx{b.map};
  out->append_user_data(b.hot_data, b.meta.len, &kv_serve_deleter, ctx);
  kv_prefix_vars().fetch_total << 1;
  kv_vars().fetch_bytes << static_cast<int64_t>(b.meta.len);
  record_kv(hash.lo, kKvOpServe, b.meta.len);
  return 0;
}

int KvStore::withdraw_prefix(const Key128& hash) {
  std::lock_guard<std::mutex> g(mu_);
  if (prefix_blocks_.find(hash) == prefix_blocks_.end()) {
    return kEKvMiss;
  }
  evict_prefix_locked(hash);
  return 0;
}

size_t KvStore::prefix_count() {
  std::lock_guard<std::mutex> g(mu_);
  return prefix_blocks_.size();
}

uint64_t KvStore::prefix_hot_bytes() {
  std::lock_guard<std::mutex> g(mu_);
  return prefix_hot_bytes_;
}

uint64_t KvStore::prefix_cold_bytes() {
  std::lock_guard<std::mutex> g(mu_);
  return prefix_cold_bytes_;
}

// ---- KvRegistry ----------------------------------------------------------

KvRegistry& kv_registry() {
  static KvRegistry* r = new KvRegistry();
  return *r;
}

int KvRegistry::do_register(const KvBlockMeta& meta, int64_t lease_ms,
                            uint64_t* gen_out) {
  kv_ensure_registered();
  if (meta.block_id == 0 || meta.len == 0 || meta.generation == 0) {
    return kEKvStale;  // generation 0 is never minted
  }
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = entries_.find(meta.block_id);
  if (it != entries_.end()) {
    if (it->second.deadline_us <= now) {
      entries_.erase(it);  // lapsed: prune, fall through to admit
    } else if (meta.generation > it->second.meta.generation) {
      entries_.erase(it);  // re-publish with a newer generation replaces
    } else if (meta.generation == it->second.meta.generation) {
      return kEKvExists;  // double-register: ownership is exclusive
    } else {
      return kEKvStale;  // zombie publisher re-offering an old generation
    }
  }
  if (last_gen_[meta.block_id] != 0 &&
      meta.generation < last_gen_[meta.block_id]) {
    return kEKvStale;  // zombie publisher re-offering an old generation
  }
  Entry e;
  e.meta = meta;
  e.deadline_us = effective_lease_us(lease_ms);
  last_gen_[meta.block_id] =
      std::max(last_gen_[meta.block_id], meta.generation);
  entries_[meta.block_id] = e;
  if (gen_out != nullptr) {
    *gen_out = meta.generation;
  }
  kv_vars().register_total << 1;
  return 0;
}

int KvRegistry::lookup(uint64_t block_id, KvBlockMeta* out,
                       int64_t* lease_left_ms) {
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  kv_vars().lookup_total << 1;
  auto it = entries_.find(block_id);
  if (it == entries_.end() || it->second.deadline_us <= now) {
    if (it != entries_.end()) {
      entries_.erase(it);  // lazy lease pruning
    }
    kv_vars().lookup_miss_total << 1;
    return kEKvMiss;
  }
  if (out != nullptr) {
    *out = it->second.meta;
  }
  if (lease_left_ms != nullptr) {
    *lease_left_ms = (it->second.deadline_us - now) / 1000;
  }
  return 0;
}

int KvRegistry::evict(uint64_t block_id, uint64_t* gen_out) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = entries_.find(block_id);
  if (it == entries_.end()) {
    return kEKvMiss;
  }
  if (gen_out != nullptr) {
    *gen_out = it->second.meta.generation;
  }
  entries_.erase(it);
  return 0;
}

int KvRegistry::renew(uint64_t block_id, int64_t lease_ms,
                      uint64_t* gen_out) {
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = entries_.find(block_id);
  if (it == entries_.end() || it->second.deadline_us <= now) {
    if (it != entries_.end()) {
      entries_.erase(it);
    }
    return kEKvMiss;  // a lapsed lease cannot be revived, only re-registered
  }
  it->second.deadline_us = effective_lease_us(lease_ms);
  if (gen_out != nullptr) {
    *gen_out = it->second.meta.generation;
  }
  return 0;
}

// ---- KvRegistry prefix records (content-addressed replica sets) ----------

int KvRegistry::put_prefix(const KvPrefixMeta& meta, int64_t lease_ms,
                           uint64_t* gen_out) {
  kv_ensure_registered();
  if (meta.key.zero() || meta.hash.zero() || meta.len == 0 ||
      meta.generation == 0 || meta.node[0] == '\0') {
    return kEKvStale;  // generation 0 is never minted; anonymous
                       // replicas can't be fetched from
  }
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = prefix_.find(meta.key);
  if (it == prefix_.end()) {
    PrefixEntry e;
    e.hash = meta.hash;
    e.depth = meta.depth;
    e.len = meta.len;
    it = prefix_.emplace(meta.key, std::move(e)).first;
  } else if (it->second.hash != meta.hash) {
    // Same chain key, different bytes: token/content divergence (a
    // nondeterministic prefill, or corruption).  Never silently alias —
    // the publisher must treat its bytes as uncacheable.
    return kEKvStale;
  }
  PrefixEntry& e = it->second;
  // Lazy lease pruning (the fence map survives — pruning a replica
  // must not reopen the zombie window).
  e.replicas.erase(
      std::remove_if(e.replicas.begin(), e.replicas.end(),
                     [now](const PrefixReplica& r) {
                       return r.deadline_us <= now;
                     }),
      e.replicas.end());
  const std::string node(meta.node);
  uint64_t& fence = e.last_gen[node];
  if (meta.generation < fence) {
    return kEKvStale;  // zombie publisher re-offering an old generation
  }
  for (PrefixReplica& r : e.replicas) {
    if (node == r.meta.node) {
      if (meta.generation == r.meta.generation) {
        // Idempotent re-register: content addressing makes this the
        // common path (every cache hit re-offers) — renew the lease.
        r.deadline_us = effective_lease_us(lease_ms);
        if (gen_out != nullptr) {
          *gen_out = meta.generation;
        }
        return kEKvExists;
      }
      r.meta = meta;  // newer generation replaces in place
      r.deadline_us = effective_lease_us(lease_ms);
      fence = meta.generation;
      if (gen_out != nullptr) {
        *gen_out = meta.generation;
      }
      kv_prefix_vars().put_total << 1;
      return 0;
    }
  }
  const bool folded = !e.replicas.empty();
  PrefixReplica r;
  r.meta = meta;
  r.deadline_us = effective_lease_us(lease_ms);
  e.replicas.push_back(std::move(r));
  fence = std::max(fence, meta.generation);
  if (folded) {
    // N publishers, one record: the fleet-wide dedup event.
    kv_prefix_counters().bump(kv_prefix_counters().dedup);
  }
  kv_prefix_vars().put_total << 1;
  if (gen_out != nullptr) {
    *gen_out = meta.generation;
  }
  return 0;
}

size_t KvRegistry::match(const Key128* keys, size_t n,
                         std::vector<KvPrefixMeta>* out,
                         std::vector<int64_t>* lease_out) {
  kv_ensure_registered();
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  kv_prefix_vars().match_total << 1;
  size_t matched = 0;
  for (size_t i = 0; i < n; ++i) {
    auto it = prefix_.find(keys[i]);
    if (it == prefix_.end()) {
      break;  // first miss ends the longest cached prefix
    }
    PrefixEntry& e = it->second;
    e.replicas.erase(
        std::remove_if(e.replicas.begin(), e.replicas.end(),
                       [now](const PrefixReplica& r) {
                         return r.deadline_us <= now;
                       }),
        e.replicas.end());
    if (e.replicas.empty()) {
      break;  // all replicas lapsed: the chain stops here
    }
    for (const PrefixReplica& r : e.replicas) {
      if (out != nullptr) {
        out->push_back(r.meta);
      }
      if (lease_out != nullptr) {
        lease_out->push_back((r.deadline_us - now) / 1000);
      }
    }
    ++matched;
  }
  kv_prefix_vars().match_blocks << static_cast<int64_t>(matched);
  return matched;
}

int KvRegistry::evict_prefix(const Key128& key, const char* node) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = prefix_.find(key);
  if (it == prefix_.end()) {
    return kEKvMiss;
  }
  std::vector<PrefixReplica>& reps = it->second.replicas;
  for (auto r = reps.begin(); r != reps.end(); ++r) {
    if (node != nullptr && strncmp(r->meta.node, node,
                                   sizeof(r->meta.node)) == 0) {
      reps.erase(r);
      return 0;  // the fence map stays: no zombie window reopens
    }
  }
  return kEKvMiss;
}

size_t KvRegistry::prefix_count() {
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  size_t n = 0;
  for (const auto& [key, e] : prefix_) {
    for (const PrefixReplica& r : e.replicas) {
      if (r.deadline_us > now) {
        ++n;
        break;
      }
    }
  }
  return n;
}

size_t KvRegistry::prefix_replicas() {
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  size_t n = 0;
  for (const auto& [key, e] : prefix_) {
    for (const PrefixReplica& r : e.replicas) {
      if (r.deadline_us > now) {
        ++n;
      }
    }
  }
  return n;
}

size_t KvRegistry::count() {
  std::lock_guard<std::mutex> g(mu_);
  return entries_.size();
}

void KvRegistry::clear() {
  std::lock_guard<std::mutex> g(mu_);
  entries_.clear();
  last_gen_.clear();
  prefix_.clear();
}

// ---- native handlers -----------------------------------------------------

namespace {

bool parse_wire(const IOBuf& req, KvWire* w) {
  if (req.size() < sizeof(KvWire)) {
    return false;
  }
  req.copy_to(w, sizeof(KvWire));
  w->node[sizeof(w->node) - 1] = '\0';
  return true;
}

bool parse_prefix_wire(const IOBuf& req, KvPrefixWire* w) {
  if (req.size() < sizeof(KvPrefixWire)) {
    return false;
  }
  req.copy_to(w, sizeof(KvPrefixWire));
  w->node[sizeof(w->node) - 1] = '\0';
  return true;
}

void prefix_meta_to_wire(const KvPrefixMeta& m, int64_t lease_ms,
                         KvPrefixWire* w) {
  memset(w, 0, sizeof(*w));
  w->key_hi = m.key.hi;
  w->key_lo = m.key.lo;
  w->hash_hi = m.hash.hi;
  w->hash_lo = m.hash.lo;
  w->generation = m.generation;
  w->rkey = m.rkey;
  w->off = m.off;
  w->len = m.len;
  w->lease_ms = lease_ms;
  w->depth = m.depth;
  memcpy(w->node, m.node, sizeof(w->node));
}

void respond_gen(IOBuf* resp, uint64_t gen) {
  resp->append(&gen, sizeof(gen));
}

void fail_kv(Controller* cntl, int code, const char* what) {
  const char* why = code == kEKvMiss     ? "kv-miss"
                    : code == kEKvStale  ? "kv-stale"
                    : code == kEKvExists ? "kv-exists"
                                         : "kv-error";
  cntl->SetFailed(code, std::string(why) + ": " + what);
}

}  // namespace

int kv_attach_store(Server* s) {
  kv_ensure_registered();
  // Drain hook (Server::Drain, ISSUE 12): tombstone every published
  // block before the listener handoff — a decode cache holding this
  // node's records fails kv-stale, invalidates, and re-resolves through
  // the registry instead of ever fetching from a dying pid.
  s->add_drain_hook([] { kv_store().withdraw_all(); });
  const int rc_fetch = s->RegisterMethod(
      kKvFetchMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                         Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Kv.Fetch request");
          done();
          return;
        }
        if (cntl->remaining_us() == 0) {
          // The puller's budget died between dispatch and here (the
          // pre-dispatch shed catches arrival-expired requests; this
          // catches a budget that expired while other fetches queued
          // ahead): never pin megabytes of block pages for a response
          // the decode side has already abandoned.
          cntl->SetFailed(kEDeadlineExpired,
                          "deadline expired before block fetch");
          done();
          return;
        }
        const int rc = kv_store().fetch(w.block_id, w.generation, resp);
        if (rc != 0) {
          fail_kv(cntl, rc, "fetch");
        }
        done();
      });
  const int rc_prefix = s->RegisterMethod(
      kKvPrefixFetchMethod, [](Controller* cntl, const IOBuf& req,
                               IOBuf* resp, Closure done) {
        KvPrefixWire w;
        if (!parse_prefix_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Kv.FetchPrefix request");
          done();
          return;
        }
        if (cntl->remaining_us() == 0) {
          // Same shed as Kv.Fetch: never pin block pages for a response
          // whose budget already died in the queue.
          cntl->SetFailed(kEDeadlineExpired,
                          "deadline expired before prefix fetch");
          done();
          return;
        }
        Key128 hash;
        hash.hi = w.hash_hi;
        hash.lo = w.hash_lo;
        const int rc = kv_store().fetch_prefix(hash, w.generation, resp);
        if (rc != 0) {
          fail_kv(cntl, rc, "fetch-prefix");
        }
        done();
      });
  return rc_fetch == 0 && rc_prefix == 0 ? 0 : -1;
}

int kv_attach_registry(Server* s) {
  kv_ensure_registered();
  int rcs[6] = {0, 0, 0, 0, 0, 0};
  rcs[0] = s->RegisterMethod(
      kKvRegisterMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                            Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Register request");
          done();
          return;
        }
        KvBlockMeta m;
        m.block_id = w.block_id;
        m.generation = w.generation;
        m.rkey = w.rkey;
        m.off = w.off;
        m.len = w.len;
        memcpy(m.node, w.node, sizeof(m.node));
        uint64_t gen = 0;
        const int rc = kv_registry().do_register(m, w.lease_ms, &gen);
        if (rc != 0) {
          fail_kv(cntl, rc, "register");
        } else {
          respond_gen(resp, gen);
        }
        done();
      });
  rcs[1] = s->RegisterMethod(
      kKvLookupMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                          Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Lookup request");
          done();
          return;
        }
        KvBlockMeta m;
        int64_t left_ms = 0;
        const int rc = kv_registry().lookup(w.block_id, &m, &left_ms);
        if (rc != 0) {
          fail_kv(cntl, rc, "lookup");
        } else {
          KvWire o;
          memset(&o, 0, sizeof(o));
          o.block_id = m.block_id;
          o.generation = m.generation;
          o.rkey = m.rkey;
          o.off = m.off;
          o.len = m.len;
          o.lease_ms = left_ms;
          memcpy(o.node, m.node, sizeof(o.node));
          resp->append(&o, sizeof(o));
        }
        done();
      });
  rcs[2] = s->RegisterMethod(
      kKvEvictMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                         Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Evict request");
          done();
          return;
        }
        uint64_t gen = 0;
        const int rc = kv_registry().evict(w.block_id, &gen);
        if (rc != 0) {
          fail_kv(cntl, rc, "evict");
        } else {
          respond_gen(resp, gen);
        }
        done();
      });
  rcs[3] = s->RegisterMethod(
      kKvRenewMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                         Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Renew request");
          done();
          return;
        }
        uint64_t gen = 0;
        const int rc = kv_registry().renew(w.block_id, w.lease_ms, &gen);
        if (rc != 0) {
          fail_kv(cntl, rc, "renew");
        } else {
          respond_gen(resp, gen);  // the wire contract: one u64 generation
        }
        done();
      });
  rcs[4] = s->RegisterMethod(
      kKvPrefixPutMethod, [](Controller* cntl, const IOBuf& req,
                             IOBuf* resp, Closure done) {
        KvPrefixWire w;
        if (!parse_prefix_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.PutPrefix request");
          done();
          return;
        }
        KvPrefixMeta m;
        m.key.hi = w.key_hi;
        m.key.lo = w.key_lo;
        m.hash.hi = w.hash_hi;
        m.hash.lo = w.hash_lo;
        m.generation = w.generation;
        m.rkey = w.rkey;
        m.off = w.off;
        m.len = w.len;
        m.depth = w.depth;
        memcpy(m.node, w.node, sizeof(m.node));
        uint64_t gen = 0;
        const int rc = kv_registry().put_prefix(m, w.lease_ms, &gen);
        if (rc != 0) {
          // kEKvExists included: the caller already holds this exact
          // record (idempotent renew) — the Python client maps it to
          // its dedup/cache-hit accounting, not to a failure.
          fail_kv(cntl, rc, "put-prefix");
        } else {
          respond_gen(resp, gen);
        }
        done();
      });
  rcs[5] = s->RegisterMethod(
      kKvPrefixMatchMethod, [](Controller* cntl, const IOBuf& req,
                               IOBuf* resp, Closure done) {
        static_assert(sizeof(Key128) == 16, "Key128 is wire format");
        uint64_t nkeys = 0;
        if (req.size() < sizeof(nkeys)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Match request");
          done();
          return;
        }
        req.copy_to(&nkeys, sizeof(nkeys));
        if (nkeys == 0 || nkeys > 4096 ||
            req.size() < sizeof(nkeys) + nkeys * sizeof(Key128)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Match key count");
          done();
          return;
        }
        std::vector<Key128> keys(nkeys);
        req.copy_to(keys.data(), nkeys * sizeof(Key128), sizeof(nkeys));
        std::vector<KvPrefixMeta> metas;
        std::vector<int64_t> leases;
        kv_registry().match(keys.data(), keys.size(), &metas, &leases);
        // Response: u64 record count, then one KvPrefixWire per live
        // replica, grouped in chain order (lease_ms = remaining ms).
        // Zero records is a valid answer: no cached prefix.
        const uint64_t nrecords = metas.size();
        resp->append(&nrecords, sizeof(nrecords));
        for (size_t i = 0; i < metas.size(); ++i) {
          KvPrefixWire w;
          prefix_meta_to_wire(metas[i], leases[i], &w);
          resp->append(&w, sizeof(w));
        }
        done();
      });
  return rcs[0] == 0 && rcs[1] == 0 && rcs[2] == 0 && rcs[3] == 0 &&
                 rcs[4] == 0 && rcs[5] == 0
             ? 0
             : -1;
}

// ---- KvCache -------------------------------------------------------------

namespace {

// One registry RPC carrying a KvWire request; 0 or the call's error code.
int kv_call(Channel* ch, const char* method, const KvWire& w, IOBuf* resp) {
  IOBuf req;
  req.append(&w, sizeof(w));
  Controller cntl;
  ch->CallMethod(method, req, resp, &cntl);
  if (cntl.Failed()) {
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}

}  // namespace

int KvCache::lookup(uint64_t block_id, KvBlockMeta* out, bool refresh) {
  if (!refresh) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = cache_.find(block_id);
    if (it != cache_.end()) {
      *out = it->second;
      // Relaxed: monotonic stat counter, no ordering carried.
      hits_.fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
  }
  // Relaxed: monotonic stat counter, no ordering carried.
  misses_.fetch_add(1, std::memory_order_relaxed);
  KvWire w;
  memset(&w, 0, sizeof(w));
  w.block_id = block_id;
  IOBuf resp;
  const int rc = kv_call(reg_, kKvLookupMethod, w, &resp);
  if (rc != 0) {
    return rc;
  }
  KvWire o;
  if (!parse_wire(resp, &o)) {
    return -1;
  }
  KvBlockMeta m;
  m.block_id = o.block_id;
  m.generation = o.generation;
  m.rkey = o.rkey;
  m.off = o.off;
  m.len = o.len;
  memcpy(m.node, o.node, sizeof(m.node));
  {
    std::lock_guard<std::mutex> g(mu_);
    cache_[block_id] = m;
  }
  *out = m;
  return 0;
}

void KvCache::invalidate(uint64_t block_id) {
  std::lock_guard<std::mutex> g(mu_);
  cache_.erase(block_id);
}

int KvCache::fetch(Channel* node_ch, uint64_t block_id, IOBuf* out) {
  for (int attempt = 0; attempt < 2; ++attempt) {
    KvBlockMeta m;
    int rc = lookup(block_id, &m, /*refresh=*/attempt > 0);
    if (rc != 0) {
      return rc;
    }
    KvWire w;
    memset(&w, 0, sizeof(w));
    w.block_id = block_id;
    w.generation = m.generation;
    out->clear();
    rc = kv_call(node_ch, kKvFetchMethod, w, out);
    if (rc == 0) {
      return 0;
    }
    if (rc != kEKvStale && rc != kEKvMiss) {
      return rc;  // transport/chaos failure: the record may be fine
    }
    invalidate(block_id);  // generation-checked invalidation, retry once
  }
  return kEKvStale;
}

}  // namespace trpc
