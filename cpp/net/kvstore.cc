#include "net/kvstore.h"

#include <errno.h>
#include <string.h>

#include <algorithm>
#include <limits>

#include "base/flags.h"
#include "base/logging.h"
#include "base/time.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/rma.h"
#include "net/server.h"
#include "stat/latency_recorder.h"
#include "stat/reducer.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

Flag* lease_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_kv_lease_ms", 30000,
        "default KV-block lease for publishes/registrations that pass "
        "lease_ms <= 0 (ms, [50, 86400000]); an expired lease "
        "invalidates the block everywhere — lookups answer kv-miss, "
        "fetches answer kv-stale");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 50 &&
               n <= 86400000;
      });
    }
    return flag;
  }();
  return f;
}

Flag* store_bytes_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_kv_store_bytes", 1ll << 30,
        "node-local KV-block store byte budget ([1MB, 64GB]); a publish "
        "that would exceed it evicts expired-then-LRU blocks (their "
        "generation tombstones survive, so evicted fetches answer "
        "kv-stale, never partial bytes)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= (1ll << 20) &&
               n <= (64ll << 30);
      });
    }
    return flag;
  }();
  return f;
}

int64_t effective_lease_us(int64_t lease_ms) {
  if (lease_ms <= 0) {
    lease_ms = lease_flag() != nullptr ? lease_flag()->int64_value() : 30000;
  }
  return monotonic_time_us() + lease_ms * 1000;
}

// ---- vars ----------------------------------------------------------------

struct KvVars {
  Adder publish_total;
  Adder evict_total;
  Adder fetch_total;
  Adder fetch_bytes;
  Adder stale_total;
  Adder register_total;
  Adder lookup_total;
  Adder lookup_miss_total;
  std::unique_ptr<PassiveStatus<long>> store_blocks;
  std::unique_ptr<PassiveStatus<long>> store_bytes;
  std::unique_ptr<PassiveStatus<long>> registry_blocks;
  KvVars() {
    publish_total.expose(
        "kv_publish_total",
        "KV blocks published into this node's block store");
    evict_total.expose(
        "kv_evict_total",
        "KV blocks evicted from this node's store (budget pressure, "
        "lease expiry, or explicit withdraw)");
    fetch_total.expose("kv_fetch_total",
                       "KV block fetches served by this node");
    fetch_bytes.expose("kv_fetch_bytes",
                       "payload bytes served by KV block fetches");
    stale_total.expose(
        "kv_stale_total",
        "KV fetches rejected with kv-stale (generation mismatch, lease "
        "lapsed, or evicted block) — each one invalidates a client's "
        "cached lookup");
    register_total.expose("kv_register_total",
                          "KV-block registrations accepted by the "
                          "registry on this node");
    lookup_total.expose("kv_lookup_total",
                        "KV-block lookups answered by the registry on "
                        "this node");
    lookup_miss_total.expose(
        "kv_lookup_miss_total",
        "registry lookups answering kv-miss (unknown block or expired "
        "lease)");
    store_blocks = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_store().count()); });
    store_blocks->expose("kv_store_blocks",
                         "KV blocks currently live in this node's store");
    store_bytes = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_store().bytes_used()); });
    store_bytes->expose(
        "kv_store_bytes",
        "payload bytes currently held by this node's KV store (bounded "
        "by trpc_kv_store_bytes)");
    registry_blocks = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(kv_registry().count()); });
    registry_blocks->expose(
        "kv_registry_blocks",
        "KV-block records currently live in the registry on this node");
  }
};

KvVars& kv_vars() {
  static KvVars* v = new KvVars();
  return *v;
}

void record_kv(uint64_t block_id, uint64_t op, uint64_t len) {
  if (timeline::enabled()) {
    timeline::record(timeline::kKvBlock, block_id,
                     (op << 56) | (len & ((1ull << 56) - 1)));
  }
}

}  // namespace

void kv_ensure_registered() {
  lease_flag();
  store_bytes_flag();
  kv_vars();
}

// ---- KvStore -------------------------------------------------------------

KvStore& kv_store() {
  static KvStore* s = new KvStore();
  return *s;
}

void KvStore::evict_locked(uint64_t block_id, bool count_var) {
  auto it = blocks_.find(block_id);
  if (it == blocks_.end()) {
    return;
  }
  tombstones_[block_id] = it->second.meta.generation;
  bytes_ -= it->second.meta.len;
  record_kv(block_id, kKvOpEvict, it->second.meta.len);
  blocks_.erase(it);
  if (count_var) {
    kv_vars().evict_total << 1;
  }
}

int KvStore::publish(uint64_t block_id, const void* data, size_t len,
                     int64_t lease_ms, KvBlockMeta* out,
                     uint64_t min_generation) {
  kv_ensure_registered();
  if (data == nullptr || len == 0) {
    return -1;
  }
  uint64_t rkey = 0;
  uint64_t off = 0;
  std::shared_ptr<RmaMapping> map =
      rma_pin_exportable(data, len, &rkey, &off);
  if (map == nullptr) {
    return -1;  // not registered memory: the store serves zero-copy only
  }
  const uint64_t budget = static_cast<uint64_t>(std::max<int64_t>(
      store_bytes_flag() != nullptr ? store_bytes_flag()->int64_value()
                                    : (1ll << 30),
      1));
  if (len > budget) {
    return -1;  // cannot fit even an empty store
  }
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  if (it != blocks_.end()) {
    if (it->second.deadline_us > now) {
      return kEKvExists;  // live block: ownership is exclusive
    }
    evict_locked(block_id, /*count_var=*/true);  // lapsed: fold to tombstone
  }
  // Budget pressure: evict expired leases first, then LRU by touch_seq.
  while (bytes_ + len > budget && !blocks_.empty()) {
    uint64_t victim = 0;
    uint64_t oldest_touch = std::numeric_limits<uint64_t>::max();
    bool found_expired = false;
    for (const auto& [id, b] : blocks_) {
      if (b.deadline_us <= now) {
        victim = id;
        found_expired = true;
        break;
      }
      if (b.touch_seq < oldest_touch) {
        oldest_touch = b.touch_seq;
        victim = id;
      }
    }
    (void)found_expired;
    evict_locked(victim, /*count_var=*/true);
  }
  Block b;
  b.meta.block_id = block_id;
  // min_generation: a hot-restart successor continues the DEAD pid's
  // sequence (its own tombstones start empty) by flooring at
  // last-known-gen + 1, so the registry's zombie fence accepts the
  // takeover and old cached records fail kv-stale into a re-resolve.
  b.meta.generation =
      std::max(tombstones_[block_id] + 1, min_generation);
  tombstones_[block_id] = b.meta.generation;
  b.meta.rkey = rkey;
  b.meta.off = off;
  b.meta.len = len;
  b.data = static_cast<const char*>(data);
  b.map = std::move(map);
  b.deadline_us = effective_lease_us(lease_ms);
  b.touch_seq = ++touch_counter_;
  bytes_ += len;
  if (out != nullptr) {
    *out = b.meta;
  }
  record_kv(block_id, kKvOpPublish, len);
  blocks_[block_id] = std::move(b);
  kv_vars().publish_total << 1;
  return 0;
}

int KvStore::withdraw(uint64_t block_id) {
  std::lock_guard<std::mutex> g(mu_);
  if (blocks_.find(block_id) == blocks_.end()) {
    return kEKvMiss;
  }
  evict_locked(block_id, /*count_var=*/true);
  return 0;
}

size_t KvStore::withdraw_all() {
  std::lock_guard<std::mutex> g(mu_);
  size_t n = 0;
  while (!blocks_.empty()) {
    evict_locked(blocks_.begin()->first, /*count_var=*/true);
    ++n;
  }
  return n;
}

int KvStore::renew(uint64_t block_id, int64_t lease_ms) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  if (it == blocks_.end()) {
    return kEKvMiss;
  }
  it->second.deadline_us = effective_lease_us(lease_ms);
  return 0;
}

namespace {
// Deleter context for a served block: co-owns the region mapping so the
// bytes stay mapped until the response's last IOBuf reference drops
// (send queues, rma rails, a late cancel) — rma_free's munmap defers.
struct KvServeCtx {
  std::shared_ptr<RmaMapping> map;
};
void kv_serve_deleter(void*, void* vctx) {
  delete static_cast<KvServeCtx*>(vctx);
}
}  // namespace

int KvStore::fetch(uint64_t block_id, uint64_t expected_gen, IOBuf* out) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  const int64_t now = monotonic_time_us();
  if (it == blocks_.end() || it->second.deadline_us <= now) {
    if (it != blocks_.end()) {
      // Lease lapsed: fold to a tombstone NOW — serve time is the
      // validity decision point, so a fetch racing the expiry can
      // never admit the stale bytes.
      evict_locked(block_id, /*count_var=*/true);
    }
    const bool known = tombstones_.find(block_id) != tombstones_.end();
    if (known) {
      kv_vars().stale_total << 1;
      record_kv(block_id, kKvOpStale, 0);
      return kEKvStale;
    }
    return kEKvMiss;
  }
  Block& b = it->second;
  if (b.meta.generation != expected_gen) {
    kv_vars().stale_total << 1;
    record_kv(block_id, kKvOpStale, b.meta.len);
    return kEKvStale;
  }
  b.touch_seq = ++touch_counter_;
  auto* ctx = new KvServeCtx{b.map};
  out->append_user_data(const_cast<char*>(b.data), b.meta.len,
                        &kv_serve_deleter, ctx);
  kv_vars().fetch_total << 1;
  kv_vars().fetch_bytes << static_cast<int64_t>(b.meta.len);
  record_kv(block_id, kKvOpServe, b.meta.len);
  return 0;
}

int KvStore::pin(uint64_t block_id, uint64_t expected_gen,
                 const char** data, uint64_t* len,
                 std::shared_ptr<RmaMapping>* map, uint64_t* gen_out) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = blocks_.find(block_id);
  const int64_t now = monotonic_time_us();
  if (it == blocks_.end() || it->second.deadline_us <= now) {
    if (it != blocks_.end()) {
      evict_locked(block_id, /*count_var=*/true);  // serve-time validity
    }
    return tombstones_.find(block_id) != tombstones_.end() ? kEKvStale
                                                           : kEKvMiss;
  }
  Block& b = it->second;
  if (expected_gen != 0 && b.meta.generation != expected_gen) {
    return kEKvStale;
  }
  b.touch_seq = ++touch_counter_;
  if (data != nullptr) {
    *data = b.data;
  }
  if (len != nullptr) {
    *len = b.meta.len;
  }
  if (map != nullptr) {
    *map = b.map;
  }
  if (gen_out != nullptr) {
    *gen_out = b.meta.generation;
  }
  return 0;
}

size_t KvStore::count() {
  std::lock_guard<std::mutex> g(mu_);
  return blocks_.size();
}

uint64_t KvStore::bytes_used() {
  std::lock_guard<std::mutex> g(mu_);
  return bytes_;
}

void KvStore::clear() {
  std::lock_guard<std::mutex> g(mu_);
  blocks_.clear();
  tombstones_.clear();
  bytes_ = 0;
}

// ---- KvRegistry ----------------------------------------------------------

KvRegistry& kv_registry() {
  static KvRegistry* r = new KvRegistry();
  return *r;
}

int KvRegistry::do_register(const KvBlockMeta& meta, int64_t lease_ms,
                            uint64_t* gen_out) {
  kv_ensure_registered();
  if (meta.block_id == 0 || meta.len == 0 || meta.generation == 0) {
    return kEKvStale;  // generation 0 is never minted
  }
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = entries_.find(meta.block_id);
  if (it != entries_.end()) {
    if (it->second.deadline_us <= now) {
      entries_.erase(it);  // lapsed: prune, fall through to admit
    } else if (meta.generation > it->second.meta.generation) {
      entries_.erase(it);  // re-publish with a newer generation replaces
    } else if (meta.generation == it->second.meta.generation) {
      return kEKvExists;  // double-register: ownership is exclusive
    } else {
      return kEKvStale;  // zombie publisher re-offering an old generation
    }
  }
  if (last_gen_[meta.block_id] != 0 &&
      meta.generation < last_gen_[meta.block_id]) {
    return kEKvStale;  // zombie publisher re-offering an old generation
  }
  Entry e;
  e.meta = meta;
  e.deadline_us = effective_lease_us(lease_ms);
  last_gen_[meta.block_id] =
      std::max(last_gen_[meta.block_id], meta.generation);
  entries_[meta.block_id] = e;
  if (gen_out != nullptr) {
    *gen_out = meta.generation;
  }
  kv_vars().register_total << 1;
  return 0;
}

int KvRegistry::lookup(uint64_t block_id, KvBlockMeta* out,
                       int64_t* lease_left_ms) {
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  kv_vars().lookup_total << 1;
  auto it = entries_.find(block_id);
  if (it == entries_.end() || it->second.deadline_us <= now) {
    if (it != entries_.end()) {
      entries_.erase(it);  // lazy lease pruning
    }
    kv_vars().lookup_miss_total << 1;
    return kEKvMiss;
  }
  if (out != nullptr) {
    *out = it->second.meta;
  }
  if (lease_left_ms != nullptr) {
    *lease_left_ms = (it->second.deadline_us - now) / 1000;
  }
  return 0;
}

int KvRegistry::evict(uint64_t block_id, uint64_t* gen_out) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = entries_.find(block_id);
  if (it == entries_.end()) {
    return kEKvMiss;
  }
  if (gen_out != nullptr) {
    *gen_out = it->second.meta.generation;
  }
  entries_.erase(it);
  return 0;
}

int KvRegistry::renew(uint64_t block_id, int64_t lease_ms,
                      uint64_t* gen_out) {
  const int64_t now = monotonic_time_us();
  std::lock_guard<std::mutex> g(mu_);
  auto it = entries_.find(block_id);
  if (it == entries_.end() || it->second.deadline_us <= now) {
    if (it != entries_.end()) {
      entries_.erase(it);
    }
    return kEKvMiss;  // a lapsed lease cannot be revived, only re-registered
  }
  it->second.deadline_us = effective_lease_us(lease_ms);
  if (gen_out != nullptr) {
    *gen_out = it->second.meta.generation;
  }
  return 0;
}

size_t KvRegistry::count() {
  std::lock_guard<std::mutex> g(mu_);
  return entries_.size();
}

void KvRegistry::clear() {
  std::lock_guard<std::mutex> g(mu_);
  entries_.clear();
  last_gen_.clear();
}

// ---- native handlers -----------------------------------------------------

namespace {

bool parse_wire(const IOBuf& req, KvWire* w) {
  if (req.size() < sizeof(KvWire)) {
    return false;
  }
  req.copy_to(w, sizeof(KvWire));
  w->node[sizeof(w->node) - 1] = '\0';
  return true;
}

void respond_gen(IOBuf* resp, uint64_t gen) {
  resp->append(&gen, sizeof(gen));
}

void fail_kv(Controller* cntl, int code, const char* what) {
  const char* why = code == kEKvMiss     ? "kv-miss"
                    : code == kEKvStale  ? "kv-stale"
                    : code == kEKvExists ? "kv-exists"
                                         : "kv-error";
  cntl->SetFailed(code, std::string(why) + ": " + what);
}

}  // namespace

int kv_attach_store(Server* s) {
  kv_ensure_registered();
  // Drain hook (Server::Drain, ISSUE 12): tombstone every published
  // block before the listener handoff — a decode cache holding this
  // node's records fails kv-stale, invalidates, and re-resolves through
  // the registry instead of ever fetching from a dying pid.
  s->add_drain_hook([] { kv_store().withdraw_all(); });
  return s->RegisterMethod(
      kKvFetchMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                         Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Kv.Fetch request");
          done();
          return;
        }
        if (cntl->remaining_us() == 0) {
          // The puller's budget died between dispatch and here (the
          // pre-dispatch shed catches arrival-expired requests; this
          // catches a budget that expired while other fetches queued
          // ahead): never pin megabytes of block pages for a response
          // the decode side has already abandoned.
          cntl->SetFailed(kEDeadlineExpired,
                          "deadline expired before block fetch");
          done();
          return;
        }
        const int rc = kv_store().fetch(w.block_id, w.generation, resp);
        if (rc != 0) {
          fail_kv(cntl, rc, "fetch");
        }
        done();
      }) == 0
             ? 0
             : -1;
}

int kv_attach_registry(Server* s) {
  kv_ensure_registered();
  int rcs[4] = {0, 0, 0, 0};
  rcs[0] = s->RegisterMethod(
      kKvRegisterMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                            Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Register request");
          done();
          return;
        }
        KvBlockMeta m;
        m.block_id = w.block_id;
        m.generation = w.generation;
        m.rkey = w.rkey;
        m.off = w.off;
        m.len = w.len;
        memcpy(m.node, w.node, sizeof(m.node));
        uint64_t gen = 0;
        const int rc = kv_registry().do_register(m, w.lease_ms, &gen);
        if (rc != 0) {
          fail_kv(cntl, rc, "register");
        } else {
          respond_gen(resp, gen);
        }
        done();
      });
  rcs[1] = s->RegisterMethod(
      kKvLookupMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                          Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Lookup request");
          done();
          return;
        }
        KvBlockMeta m;
        int64_t left_ms = 0;
        const int rc = kv_registry().lookup(w.block_id, &m, &left_ms);
        if (rc != 0) {
          fail_kv(cntl, rc, "lookup");
        } else {
          KvWire o;
          memset(&o, 0, sizeof(o));
          o.block_id = m.block_id;
          o.generation = m.generation;
          o.rkey = m.rkey;
          o.off = m.off;
          o.len = m.len;
          o.lease_ms = left_ms;
          memcpy(o.node, m.node, sizeof(o.node));
          resp->append(&o, sizeof(o));
        }
        done();
      });
  rcs[2] = s->RegisterMethod(
      kKvEvictMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                         Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Evict request");
          done();
          return;
        }
        uint64_t gen = 0;
        const int rc = kv_registry().evict(w.block_id, &gen);
        if (rc != 0) {
          fail_kv(cntl, rc, "evict");
        } else {
          respond_gen(resp, gen);
        }
        done();
      });
  rcs[3] = s->RegisterMethod(
      kKvRenewMethod, [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                         Closure done) {
        KvWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad KvReg.Renew request");
          done();
          return;
        }
        uint64_t gen = 0;
        const int rc = kv_registry().renew(w.block_id, w.lease_ms, &gen);
        if (rc != 0) {
          fail_kv(cntl, rc, "renew");
        } else {
          respond_gen(resp, gen);  // the wire contract: one u64 generation
        }
        done();
      });
  return rcs[0] == 0 && rcs[1] == 0 && rcs[2] == 0 && rcs[3] == 0 ? 0 : -1;
}

// ---- KvCache -------------------------------------------------------------

namespace {

// One registry RPC carrying a KvWire request; 0 or the call's error code.
int kv_call(Channel* ch, const char* method, const KvWire& w, IOBuf* resp) {
  IOBuf req;
  req.append(&w, sizeof(w));
  Controller cntl;
  ch->CallMethod(method, req, resp, &cntl);
  if (cntl.Failed()) {
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}

}  // namespace

int KvCache::lookup(uint64_t block_id, KvBlockMeta* out, bool refresh) {
  if (!refresh) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = cache_.find(block_id);
    if (it != cache_.end()) {
      *out = it->second;
      // Relaxed: monotonic stat counter, no ordering carried.
      hits_.fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
  }
  // Relaxed: monotonic stat counter, no ordering carried.
  misses_.fetch_add(1, std::memory_order_relaxed);
  KvWire w;
  memset(&w, 0, sizeof(w));
  w.block_id = block_id;
  IOBuf resp;
  const int rc = kv_call(reg_, kKvLookupMethod, w, &resp);
  if (rc != 0) {
    return rc;
  }
  KvWire o;
  if (!parse_wire(resp, &o)) {
    return -1;
  }
  KvBlockMeta m;
  m.block_id = o.block_id;
  m.generation = o.generation;
  m.rkey = o.rkey;
  m.off = o.off;
  m.len = o.len;
  memcpy(m.node, o.node, sizeof(m.node));
  {
    std::lock_guard<std::mutex> g(mu_);
    cache_[block_id] = m;
  }
  *out = m;
  return 0;
}

void KvCache::invalidate(uint64_t block_id) {
  std::lock_guard<std::mutex> g(mu_);
  cache_.erase(block_id);
}

int KvCache::fetch(Channel* node_ch, uint64_t block_id, IOBuf* out) {
  for (int attempt = 0; attempt < 2; ++attempt) {
    KvBlockMeta m;
    int rc = lookup(block_id, &m, /*refresh=*/attempt > 0);
    if (rc != 0) {
      return rc;
    }
    KvWire w;
    memset(&w, 0, sizeof(w));
    w.block_id = block_id;
    w.generation = m.generation;
    out->clear();
    rc = kv_call(node_ch, kKvFetchMethod, w, out);
    if (rc == 0) {
      return 0;
    }
    if (rc != kEKvStale && rc != kEKvMiss) {
      return rc;  // transport/chaos failure: the record may be fine
    }
    invalidate(block_id);  // generation-checked invalidation, retry once
  }
  return kEKvStale;
}

}  // namespace trpc
