// Paged KV-block registry + node-local block store — block-addressed
// one-sided KV-cache transfer over the RMA fabric (ISSUE 11 tentpole).
//
// No direct brpc parity: the reference stops at connection-addressed
// RPC.  This is fabric-lib's (arXiv 2510.27656) central abstraction made
// concrete on our transport stack: a KV-cache block is addressed by
// BLOCK ID, not by connection — the registry maps
//   block_id → {node, rkey, offset, len, generation}
// and any client holding that record can fetch the bytes from the
// owning node, landing them ONE-SIDED in its own registered pages (the
// PR 10 direct-landing path: the fetch response is PUT straight into
// the caller's RmaBuffer, zero receiver-side copies).  T3-style overlap
// (arXiv 2401.16677) falls out of the existing planes: MB-scale block
// fetches ride the striped/RMA rails while the small token-RPC decode
// stream keeps dispatching through the messenger cut budget and QoS
// lanes — the disaggregated prefill/decode workload composes instead of
// head-of-line blocking.
//
// Roles:
//  - KvStore (one per process, `kv_store()`): the PREFILL side.  Blocks
//    are published out of exportable (rma_alloc'd) regions; the store
//    pins the region mapping so fetches serve the bytes zero-copy (an
//    IOBuf wrap of the registered pages) and rma_free can never unmap
//    them under an in-flight response.  Publishing mints the block's
//    GENERATION (monotonic per block id, tombstones survive eviction);
//    a byte budget (trpc_kv_store_bytes) evicts expired-then-LRU blocks
//    under pressure.  `kv_attach_store(Server*)` serves "Kv.Fetch".
//  - KvRegistry (`kv_registry()`): the directory.  Lease-based
//    ownership: every record carries a deadline; expired records answer
//    kEKvMiss and are pruned lazily.  Double-register of a live block
//    is rejected (kEKvExists) unless the incoming generation is newer
//    (the publisher re-published after a local evict).
//    `kv_attach_registry(Server*)` serves "KvReg.{Register,Lookup,
//    Evict,Renew}" — the registry can run on any node, including a
//    third party.
//  - KvCache: the DECODE-side lookup cache.  Lookups are cached until
//    proven stale: a fetch answered kEKvStale/kEKvMiss (generation
//    bumped, lease expired, block evicted) invalidates the cached
//    record, re-looks-up once, and retries — the generation check is
//    what makes caching safe, never a freshness timer.
//
// Fault semantics (the whole-or-nothing contract, inherited from the
// RMA/stripe planes and extended by generations):
//  - A chunk fault (drop/trunc/corrupt) during a block fetch fails the
//    CALL whole — the landing buffer is never observable as complete
//    with partial bytes (rma_resolve / stripe reassembly drop whole).
//  - Generation and lease are validated AT SERVE TIME, so a lease that
//    expires while the fetch is queued (svr_delay, chaos) answers
//    kEKvStale and the client admits nothing stale — there is no
//    admit-then-invalidate window.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>

#include "base/iobuf.h"

namespace trpc {

class Channel;
class Server;
struct RmaMapping;

// Error codes, continuing the 2004/2005 (kELimit/kEOverloaded) family.
// kEKvMiss: the block is unknown (never registered here, or expired and
// pruned) — look it up again or re-publish.  kEKvStale: the caller's
// record is outdated (generation bumped, lease lapsed, block evicted) —
// a cached lookup MUST be invalidated.  kEKvExists: double-register of
// a live block (ownership is exclusive while the lease holds).
constexpr int kEKvMiss = 2101;
constexpr int kEKvStale = 2102;
constexpr int kEKvExists = 2103;

// Addressing record: where one block's bytes live.  `node` is the
// owning node's RPC endpoint ("host:port") — any connection to it can
// serve the block; the block is NOT bound to a connection.
struct KvBlockMeta {
  uint64_t block_id = 0;
  uint64_t generation = 0;
  uint64_t rkey = 0;  // exportable region holding the bytes
  uint64_t off = 0;   // byte offset inside the region's data area
  uint64_t len = 0;
  char node[64] = {};
};

// Wire form shared by every Kv RPC (fixed little-endian, 112 bytes;
// mirrored by brpc_tpu/rpc/kv.py _WIRE — kv-wire marker for review):
// Register sends all fields; Lookup/Evict send block_id only; Fetch
// sends block_id + generation; Renew sends block_id + lease_ms.
// Lookup's RESPONSE is the same struct with lease_ms = remaining ms;
// Register/Evict/Renew respond with one u64 generation.
struct KvWire {
  uint64_t block_id;
  uint64_t generation;
  uint64_t rkey;
  uint64_t off;
  uint64_t len;
  int64_t lease_ms;
  char node[64];
};
static_assert(sizeof(KvWire) == 112, "KvWire is wire format — fixed");

// Method names (tstd, served by the attach functions below).
inline constexpr const char* kKvFetchMethod = "Kv.Fetch";
inline constexpr const char* kKvRegisterMethod = "KvReg.Register";
inline constexpr const char* kKvLookupMethod = "KvReg.Lookup";
inline constexpr const char* kKvEvictMethod = "KvReg.Evict";
inline constexpr const char* kKvRenewMethod = "KvReg.Renew";

// timeline kKvBlock `b` op tags (b = op<<56 | len; mirrored by
// observe.py TIMELINE_KV_OPS and tools/trace_stitch.py).
constexpr uint64_t kKvOpPublish = 1;
constexpr uint64_t kKvOpServe = 2;
constexpr uint64_t kKvOpEvict = 3;
constexpr uint64_t kKvOpStale = 4;

// ---- node-local block store (prefill side) -------------------------------

class KvStore {
 public:
  // Publishes [data, data+len) as block_id under a lease (lease_ms <= 0
  // uses trpc_kv_lease_ms).  `data` MUST lie inside an exportable
  // (rma_alloc'd) region — the store pins the region mapping and serves
  // fetches zero-copy from it.  Mints the generation (monotonic per
  // block id across evictions) and fills *out (node left empty — the
  // publisher stamps its own endpoint when registering).  Evicts
  // expired-then-LRU blocks to fit the trpc_kv_store_bytes budget.
  // Returns 0, kEKvExists when the block is live (withdraw first),
  // or -1 (not exportable memory / larger than the whole budget).
  // min_generation floors the minted generation: a hot-restart
  // successor (fresh pid, empty tombstones) passes the predecessor's
  // last registry generation + 1 so its takeover re-publish outranks
  // every cached record (net/naming.h drain flow).
  int publish(uint64_t block_id, const void* data, size_t len,
              int64_t lease_ms, KvBlockMeta* out,
              uint64_t min_generation = 0);
  // Explicit eviction.  The generation survives as a tombstone so a
  // re-publish mints a NEWER generation and stale fetches stay
  // detectable.  Returns 0, or kEKvMiss.
  int withdraw(uint64_t block_id);
  // Drain support (Server::Drain hook, net/naming.h): withdraws EVERY
  // live block, tombstoning each generation — a decode cache that still
  // holds this node's records gets kv-stale (invalidate + re-resolve),
  // never bytes from a process that is about to die.  Returns the count.
  size_t withdraw_all();
  // Extends the lease (lease_ms <= 0: the flag default).  0 or kEKvMiss.
  int renew(uint64_t block_id, int64_t lease_ms);
  // Serves one block: validates generation AND lease at serve time,
  // then appends the bytes zero-copy (the region mapping rides the
  // IOBuf deleter).  Returns 0, kEKvStale (generation mismatch, lease
  // lapsed, or evicted-but-tombstoned) or kEKvMiss (never seen).
  int fetch(uint64_t block_id, uint64_t expected_gen, IOBuf* out);
  // In-process zero-copy access for group-transfer machinery
  // (net/collective.h Reshard.Execute): pins the block's region mapping
  // and hands out the raw bytes.  expected_gen 0 accepts any live
  // generation.  Validity is decided now, like fetch; the returned
  // mapping reference keeps the pages alive past rma_free.  Returns 0,
  // kEKvStale, or kEKvMiss.
  int pin(uint64_t block_id, uint64_t expected_gen, const char** data,
          uint64_t* len, std::shared_ptr<RmaMapping>* map,
          uint64_t* gen_out);

  size_t count();
  uint64_t bytes_used();
  void clear();  // tests: drop every block AND tombstone

 private:
  struct Block {
    KvBlockMeta meta;
    const char* data = nullptr;
    std::shared_ptr<RmaMapping> map;
    int64_t deadline_us = 0;
    uint64_t touch_seq = 0;  // LRU clock (publish/fetch bumps)
  };
  // Evicts one block under mu_ (iterator-safe helper).
  void evict_locked(uint64_t block_id, bool count_var);
  std::mutex mu_;
  std::unordered_map<uint64_t, Block> blocks_;
  // Last generation minted per block id, surviving eviction: a
  // re-published block continues the sequence, and a fetch for an
  // evicted block answers kEKvStale (record invalid) instead of
  // kEKvMiss (record unknown).
  std::unordered_map<uint64_t, uint64_t> tombstones_;
  uint64_t bytes_ = 0;
  uint64_t touch_counter_ = 0;
};
KvStore& kv_store();

// ---- registry (directory) ------------------------------------------------

class KvRegistry {
 public:
  // Records meta under a lease.  Rejects kEKvExists while a live record
  // holds the block with generation >= meta.generation; a NEWER
  // generation replaces (re-publish).  A generation at or below the
  // last seen for this id is rejected kEKvStale (zombie publisher).
  // Returns 0 and echoes the accepted generation.
  int do_register(const KvBlockMeta& meta, int64_t lease_ms,
                  uint64_t* gen_out);
  // Fills *out (+ remaining lease ms).  Expired records prune here and
  // answer kEKvMiss.
  int lookup(uint64_t block_id, KvBlockMeta* out,
             int64_t* lease_left_ms = nullptr);
  int evict(uint64_t block_id, uint64_t* gen_out = nullptr);
  // Extends a live record's lease; echoes the current generation.
  int renew(uint64_t block_id, int64_t lease_ms,
            uint64_t* gen_out = nullptr);
  size_t count();
  void clear();  // tests

 private:
  struct Entry {
    KvBlockMeta meta;
    int64_t deadline_us = 0;
  };
  std::mutex mu_;
  std::unordered_map<uint64_t, Entry> entries_;
  std::unordered_map<uint64_t, uint64_t> last_gen_;
};
KvRegistry& kv_registry();

// Attach the native handlers (call before Server::Start).  Both may be
// attached to the same server; the registry may also run on a node that
// stores nothing.  Return 0, or -1 when any registration was refused
// (server already running).
int kv_attach_store(Server* s);
int kv_attach_registry(Server* s);

// ---- client-side lookup cache (decode side) ------------------------------

// Caches registry lookups with generation-checked invalidation.  NOT a
// freshness timer: a cached record is used until a fetch proves it
// stale (kEKvStale/kEKvMiss), then invalidated and re-resolved once.
class KvCache {
 public:
  // `registry_ch` (not owned) must outlive the cache.
  explicit KvCache(Channel* registry_ch) : reg_(registry_ch) {}

  // Cached lookup (refresh forces a registry round-trip).  0 or error.
  int lookup(uint64_t block_id, KvBlockMeta* out, bool refresh = false);
  void invalidate(uint64_t block_id);

  // Fetches block_id's bytes from `node_ch` (a channel to meta.node,
  // caller-routed) using the cached record; on a stale answer
  // invalidates, re-looks-up, and retries ONCE with the fresh
  // generation.  0 on success (bytes in *out), else the final error.
  int fetch(Channel* node_ch, uint64_t block_id, IOBuf* out);

  uint64_t hits() const {
    // Relaxed: monotonic test/stat counters — no ordering carried.
    return hits_.load(std::memory_order_relaxed);
  }
  uint64_t misses() const {
    // Relaxed: monotonic test/stat counters — no ordering carried.
    return misses_.load(std::memory_order_relaxed);
  }

 private:
  Channel* reg_;
  std::mutex mu_;
  std::unordered_map<uint64_t, KvBlockMeta> cache_;
  // Relaxed counters: diagnostics only, no synchronization piggybacks.
  std::atomic<uint64_t> hits_{0};
  std::atomic<uint64_t> misses_{0};
};

// Flag registration (idempotent; attach functions and the capi call it
// so /flags sees the kv knobs before first traffic).
void kv_ensure_registered();

}  // namespace trpc
