// Paged KV-block registry + node-local block store — block-addressed
// one-sided KV-cache transfer over the RMA fabric (ISSUE 11 tentpole).
//
// No direct brpc parity: the reference stops at connection-addressed
// RPC.  This is fabric-lib's (arXiv 2510.27656) central abstraction made
// concrete on our transport stack: a KV-cache block is addressed by
// BLOCK ID, not by connection — the registry maps
//   block_id → {node, rkey, offset, len, generation}
// and any client holding that record can fetch the bytes from the
// owning node, landing them ONE-SIDED in its own registered pages (the
// PR 10 direct-landing path: the fetch response is PUT straight into
// the caller's RmaBuffer, zero receiver-side copies).  T3-style overlap
// (arXiv 2401.16677) falls out of the existing planes: MB-scale block
// fetches ride the striped/RMA rails while the small token-RPC decode
// stream keeps dispatching through the messenger cut budget and QoS
// lanes — the disaggregated prefill/decode workload composes instead of
// head-of-line blocking.
//
// Roles:
//  - KvStore (one per process, `kv_store()`): the PREFILL side.  Blocks
//    are published out of exportable (rma_alloc'd) regions; the store
//    pins the region mapping so fetches serve the bytes zero-copy (an
//    IOBuf wrap of the registered pages) and rma_free can never unmap
//    them under an in-flight response.  Publishing mints the block's
//    GENERATION (monotonic per block id, tombstones survive eviction);
//    a byte budget (trpc_kv_store_bytes) evicts expired-then-LRU blocks
//    under pressure.  `kv_attach_store(Server*)` serves "Kv.Fetch".
//  - KvRegistry (`kv_registry()`): the directory.  Lease-based
//    ownership: every record carries a deadline; expired records answer
//    kEKvMiss and are pruned lazily.  Double-register of a live block
//    is rejected (kEKvExists) unless the incoming generation is newer
//    (the publisher re-published after a local evict).
//    `kv_attach_registry(Server*)` serves "KvReg.{Register,Lookup,
//    Evict,Renew}" — the registry can run on any node, including a
//    third party.
//  - KvCache: the DECODE-side lookup cache.  Lookups are cached until
//    proven stale: a fetch answered kEKvStale/kEKvMiss (generation
//    bumped, lease expired, block evicted) invalidates the cached
//    record, re-looks-up once, and retries — the generation check is
//    what makes caching safe, never a freshness timer.
//
// Fault semantics (the whole-or-nothing contract, inherited from the
// RMA/stripe planes and extended by generations):
//  - A chunk fault (drop/trunc/corrupt) during a block fetch fails the
//    CALL whole — the landing buffer is never observable as complete
//    with partial bytes (rma_resolve / stripe reassembly drop whole).
//  - Generation and lease are validated AT SERVE TIME, so a lease that
//    expires while the fetch is queued (svr_delay, chaos) answers
//    kEKvStale and the client admits nothing stale — there is no
//    admit-then-invalidate window.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "base/iobuf.h"

namespace trpc {

class Channel;
class Server;
struct RmaMapping;

// Error codes, continuing the 2004/2005 (kELimit/kEOverloaded) family.
// kEKvMiss: the block is unknown (never registered here, or expired and
// pruned) — look it up again or re-publish.  kEKvStale: the caller's
// record is outdated (generation bumped, lease lapsed, block evicted) —
// a cached lookup MUST be invalidated.  kEKvExists: double-register of
// a live block (ownership is exclusive while the lease holds).
constexpr int kEKvMiss = 2101;
constexpr int kEKvStale = 2102;
constexpr int kEKvExists = 2103;

// Addressing record: where one block's bytes live.  `node` is the
// owning node's RPC endpoint ("host:port") — any connection to it can
// serve the block; the block is NOT bound to a connection.
struct KvBlockMeta {
  uint64_t block_id = 0;
  uint64_t generation = 0;
  uint64_t rkey = 0;  // exportable region holding the bytes
  uint64_t off = 0;   // byte offset inside the region's data area
  uint64_t len = 0;
  char node[64] = {};
};

// Wire form shared by every Kv RPC (fixed little-endian, 112 bytes;
// mirrored by brpc_tpu/rpc/kv.py _WIRE — kv-wire marker for review):
// Register sends all fields; Lookup/Evict send block_id only; Fetch
// sends block_id + generation; Renew sends block_id + lease_ms.
// Lookup's RESPONSE is the same struct with lease_ms = remaining ms;
// Register/Evict/Renew respond with one u64 generation.
struct KvWire {
  uint64_t block_id;
  uint64_t generation;
  uint64_t rkey;
  uint64_t off;
  uint64_t len;
  int64_t lease_ms;
  char node[64];
};
static_assert(sizeof(KvWire) == 112, "KvWire is wire format — fixed");

// Method names (tstd, served by the attach functions below).
inline constexpr const char* kKvFetchMethod = "Kv.Fetch";
inline constexpr const char* kKvRegisterMethod = "KvReg.Register";
inline constexpr const char* kKvLookupMethod = "KvReg.Lookup";
inline constexpr const char* kKvEvictMethod = "KvReg.Evict";
inline constexpr const char* kKvRenewMethod = "KvReg.Renew";
inline constexpr const char* kKvPrefixPutMethod = "KvReg.PutPrefix";
inline constexpr const char* kKvPrefixMatchMethod = "KvReg.Match";
inline constexpr const char* kKvPrefixFetchMethod = "Kv.FetchPrefix";

// timeline kKvBlock `b` op tags (b = op<<56 | len; mirrored by
// observe.py TIMELINE_KV_OPS and tools/trace_stitch.py).
constexpr uint64_t kKvOpPublish = 1;
constexpr uint64_t kKvOpServe = 2;
constexpr uint64_t kKvOpEvict = 3;
constexpr uint64_t kKvOpStale = 4;
constexpr uint64_t kKvOpPromote = 5;  // cold prefix block re-pinned hot
constexpr uint64_t kKvOpDemote = 6;   // hot prefix block spilled cold

// ---- content addressing (prefix cache, ISSUE 17) -------------------------

// 128-bit content key.  crc32c is taken by the transport checksum
// plane, so prefix blocks use a two-lane 64-bit mix over the block
// bytes AND the token-id span: identical (bytes, tokens) pairs hash
// identically on every node — the fleet-wide dedup key.
struct Key128 {
  uint64_t hi = 0;
  uint64_t lo = 0;
  bool operator==(const Key128& o) const {
    return hi == o.hi && lo == o.lo;
  }
  bool operator!=(const Key128& o) const { return !(*this == o); }
  bool zero() const { return hi == 0 && lo == 0; }
};
struct Key128Hash {
  size_t operator()(const Key128& k) const {
    return static_cast<size_t>(k.hi ^ (k.lo * 0x9e3779b97f4a7c15ull));
  }
};

// Content hash of one prefix block: the block bytes plus the token-id
// span they were computed from (two prompts that collide on bytes but
// diverge on tokens must NOT dedup).  Deterministic across processes.
void kv_content_hash(const void* data, size_t len, const uint64_t* tokens,
                     size_t ntokens, Key128* out);

// Chain keys for a token-id sequence: key_i folds key_{i-1} with the
// i-th block_tokens-sized token chunk, so key_i names the WHOLE prefix
// through block i — the registry's "trie" is a flat map over chain
// keys, and longest-prefix match is a walk until first miss.  Computed
// from token ids alone: the decode side derives them without holding
// any bytes.  block_tokens <= 0 uses trpc_kv_prefix_block_tokens.
// Returns the number of FULL blocks written (partial tail ignored).
size_t kv_prefix_chain(const uint64_t* tokens, size_t ntokens,
                       int64_t block_tokens, Key128* keys, size_t max_keys);

// Addressing record for one prefix-block replica: chain key (where in
// the trie), content hash (what bytes), and where THIS replica lives.
struct KvPrefixMeta {
  Key128 key;         // chain key (token-derived)
  Key128 hash;        // content hash (bytes + token span)
  uint64_t generation = 0;
  uint64_t rkey = 0;  // valid while the replica is hot
  uint64_t off = 0;
  uint64_t len = 0;
  uint32_t depth = 0;  // 0-based block index in the prefix chain
  char node[64] = {};
};

// Wire form of every prefix-cache RPC (fixed little-endian, 144 bytes;
// mirrored by brpc_tpu/rpc/kv.py _PREFIX_WIRE — kv-wire marker).
// PutPrefix sends all fields; FetchPrefix sends hash + generation;
// Match sends a u64 count + count x 16-byte chain keys and answers a
// u64 record count + that many KvPrefixWire records (one per live
// replica, grouped in chain order — lease_ms = remaining ms).
struct KvPrefixWire {
  uint64_t key_hi;
  uint64_t key_lo;
  uint64_t hash_hi;
  uint64_t hash_lo;
  uint64_t generation;
  uint64_t rkey;
  uint64_t off;
  uint64_t len;
  int64_t lease_ms;
  uint32_t depth;
  uint32_t flags;  // bit 0: replica currently cold (tier telemetry)
  char node[64];
};
static_assert(sizeof(KvPrefixWire) == 144,
              "KvPrefixWire is wire format — fixed");

// Process-wide prefix-tier outcome counters (read by the capi and the
// perf harness; mirrored as vars by kvstore.cc).
struct KvPrefixCounters {
  std::atomic<uint64_t> promote{0};    // cold block re-pinned hot on fetch
  std::atomic<uint64_t> demote{0};     // hot block spilled to the heap tier
  std::atomic<uint64_t> hot_hits{0};   // prefix fetches served zero-copy
  std::atomic<uint64_t> cold_hits{0};  // prefix fetches served from cold
  std::atomic<uint64_t> dedup{0};      // registry replica folds (same hash)
  // Relaxed: monotonic stat counters — nothing is published through
  // them; a stale read only blurs a dashboard or test assertion.
  void bump(std::atomic<uint64_t>& c) {
    c.fetch_add(1, std::memory_order_relaxed);
  }
  // Relaxed: same monotonic-stat rationale as bump().
  static uint64_t read(const std::atomic<uint64_t>& c) {
    return c.load(std::memory_order_relaxed);
  }
};
KvPrefixCounters& kv_prefix_counters();

// ---- node-local block store (prefill side) -------------------------------

class KvStore {
 public:
  // Publishes [data, data+len) as block_id under a lease (lease_ms <= 0
  // uses trpc_kv_lease_ms).  `data` MUST lie inside an exportable
  // (rma_alloc'd) region — the store pins the region mapping and serves
  // fetches zero-copy from it.  Mints the generation (monotonic per
  // block id across evictions) and fills *out (node left empty — the
  // publisher stamps its own endpoint when registering).  Evicts
  // expired-then-LRU blocks to fit the trpc_kv_store_bytes budget.
  // Returns 0, kEKvExists when the block is live (withdraw first),
  // or -1 (not exportable memory / larger than the whole budget).
  // min_generation floors the minted generation: a hot-restart
  // successor (fresh pid, empty tombstones) passes the predecessor's
  // last registry generation + 1 so its takeover re-publish outranks
  // every cached record (net/naming.h drain flow).
  int publish(uint64_t block_id, const void* data, size_t len,
              int64_t lease_ms, KvBlockMeta* out,
              uint64_t min_generation = 0);
  // Explicit eviction.  The generation survives as a tombstone so a
  // re-publish mints a NEWER generation and stale fetches stay
  // detectable.  Returns 0, or kEKvMiss.
  int withdraw(uint64_t block_id);
  // Drain support (Server::Drain hook, net/naming.h): withdraws EVERY
  // live block, tombstoning each generation — a decode cache that still
  // holds this node's records gets kv-stale (invalidate + re-resolve),
  // never bytes from a process that is about to die.  Returns the count.
  size_t withdraw_all();
  // Extends the lease (lease_ms <= 0: the flag default).  0 or kEKvMiss.
  int renew(uint64_t block_id, int64_t lease_ms);
  // Serves one block: validates generation AND lease at serve time,
  // then appends the bytes zero-copy (the region mapping rides the
  // IOBuf deleter).  Returns 0, kEKvStale (generation mismatch, lease
  // lapsed, or evicted-but-tombstoned) or kEKvMiss (never seen).
  int fetch(uint64_t block_id, uint64_t expected_gen, IOBuf* out);
  // In-process zero-copy access for group-transfer machinery
  // (net/collective.h Reshard.Execute): pins the block's region mapping
  // and hands out the raw bytes.  expected_gen 0 accepts any live
  // generation.  Validity is decided now, like fetch; the returned
  // mapping reference keeps the pages alive past rma_free.  Returns 0,
  // kEKvStale, or kEKvMiss.
  int pin(uint64_t block_id, uint64_t expected_gen, const char** data,
          uint64_t* len, std::shared_ptr<RmaMapping>* map,
          uint64_t* gen_out);

  // ---- content-addressed prefix tier (two-tier store, ISSUE 17) ----
  //
  // Publishes one prefix block under its CONTENT hash.  Unlike
  // publish(), the store COPIES the bytes into a store-owned
  // registered-RMA region (hot tier) — callers need no RmaBuffer, and
  // demote/promote can move the bytes without caller coordination.
  // The content hash is computed here (bytes + token span) and echoed
  // in *out with the minted generation.  Re-publishing a LIVE block
  // with the same content hash is the cache-hit path: the lease renews
  // and *out fills, but the return is kEKvExists so callers can count
  // bytes-NOT-recomputed.  Budget: hot bytes under
  // trpc_kv_prefix_hot_bytes (LRU hot blocks DEMOTE to the cold heap
  // tier, never drop); total store bytes (blocks + hot + cold) under
  // trpc_kv_store_bytes (expired-then-LRU cold blocks drop with
  // generation tombstones).  Returns 0, kEKvExists, or -1.
  int publish_prefix(const Key128& key, uint32_t depth, const void* data,
                     size_t len, const uint64_t* tokens, size_t ntokens,
                     int64_t lease_ms, KvPrefixMeta* out,
                     uint64_t min_generation = 0);
  // Serves one prefix block by content hash: generation AND lease
  // validated at serve time (same stale rules as fetch()).  A hot hit
  // serves zero-copy from the registered pages; a cold hit PROMOTES the
  // block back into a registered region first (falling back to a plain
  // copy if registered memory is exhausted).  0, kEKvStale, kEKvMiss.
  int fetch_prefix(const Key128& hash, uint64_t expected_gen, IOBuf* out);
  // Explicit eviction by content hash (generation tombstones).
  int withdraw_prefix(const Key128& hash);

  size_t prefix_count();
  uint64_t prefix_hot_bytes();
  uint64_t prefix_cold_bytes();

  size_t count();
  uint64_t bytes_used();
  void clear();  // tests: drop every block AND tombstone

 private:
  struct Block {
    KvBlockMeta meta;
    const char* data = nullptr;
    std::shared_ptr<RmaMapping> map;
    int64_t deadline_us = 0;
    uint64_t touch_seq = 0;  // LRU clock (publish/fetch bumps)
  };
  struct PrefixBlock {
    KvPrefixMeta meta;        // rkey/off valid only while hot
    char* hot_data = nullptr;  // store-owned rma_alloc region (hot tier)
    std::shared_ptr<RmaMapping> map;  // pins hot pages across serves
    std::string cold;                 // the bytes while demoted
    bool hot = false;
    int64_t deadline_us = 0;
    uint64_t touch_seq = 0;
  };
  // Evicts one block under mu_ (iterator-safe helper).
  void evict_locked(uint64_t block_id, bool count_var);
  // Prefix-tier helpers, all under mu_: spill one hot block's bytes to
  // the heap tier / drop one block entirely (tombstoning) / make room.
  void demote_locked(PrefixBlock* b);
  void evict_prefix_locked(const Key128& hash);
  bool fit_hot_locked(uint64_t incoming, uint64_t hot_budget);
  std::mutex mu_;
  std::unordered_map<uint64_t, Block> blocks_;
  std::unordered_map<Key128, PrefixBlock, Key128Hash> prefix_blocks_;
  // Last generation minted per block id, surviving eviction: a
  // re-published block continues the sequence, and a fetch for an
  // evicted block answers kEKvStale (record invalid) instead of
  // kEKvMiss (record unknown).
  std::unordered_map<uint64_t, uint64_t> tombstones_;
  std::unordered_map<Key128, uint64_t, Key128Hash> prefix_tombstones_;
  uint64_t bytes_ = 0;
  uint64_t prefix_hot_bytes_ = 0;
  uint64_t prefix_cold_bytes_ = 0;
  uint64_t touch_counter_ = 0;
};
KvStore& kv_store();

// ---- registry (directory) ------------------------------------------------

class KvRegistry {
 public:
  // Records meta under a lease.  Rejects kEKvExists while a live record
  // holds the block with generation >= meta.generation; a NEWER
  // generation replaces (re-publish).  A generation at or below the
  // last seen for this id is rejected kEKvStale (zombie publisher).
  // Returns 0 and echoes the accepted generation.
  int do_register(const KvBlockMeta& meta, int64_t lease_ms,
                  uint64_t* gen_out);
  // Fills *out (+ remaining lease ms).  Expired records prune here and
  // answer kEKvMiss.
  int lookup(uint64_t block_id, KvBlockMeta* out,
             int64_t* lease_left_ms = nullptr);
  int evict(uint64_t block_id, uint64_t* gen_out = nullptr);
  // Extends a live record's lease; echoes the current generation.
  int renew(uint64_t block_id, int64_t lease_ms,
            uint64_t* gen_out = nullptr);

  // ---- content-addressed prefix records (replica sets, ISSUE 17) ----
  //
  // Records one replica of a prefix block.  N publishers of the SAME
  // chain key + content hash fold into ONE record with a replica set
  // (fleet-wide dedup); each replica keeps its own lease deadline and
  // generation, with the PR 12 zombie fence applied PER NODE (a
  // publisher re-offering a generation at or below its last accepted
  // one answers kEKvStale).  A chain key re-offered with a DIFFERENT
  // content hash is rejected kEKvStale — token/content divergence must
  // never silently alias.  Returns 0 and echoes the accepted
  // generation; kEKvExists on an exact same-node same-generation
  // double-register (the lease still renews — content-addressed
  // registration is idempotent).
  int put_prefix(const KvPrefixMeta& meta, int64_t lease_ms,
                 uint64_t* gen_out);
  // Longest cached prefix: walks keys[0..n) in order, stopping at the
  // first key with no live replica.  Appends one KvPrefixMeta per LIVE
  // replica of every matched block (grouped in chain order; expired
  // replicas prune here) plus its remaining lease into the parallel
  // lease_out (ms).  Returns the number of matched BLOCKS (depths).
  size_t match(const Key128* keys, size_t n,
               std::vector<KvPrefixMeta>* out,
               std::vector<int64_t>* lease_out = nullptr);
  // Drops one node's replica of one chain key (drain support).
  int evict_prefix(const Key128& key, const char* node);
  size_t prefix_count();   // live prefix records (chain keys)
  size_t prefix_replicas();  // live replicas across all records

  size_t count();
  void clear();  // tests

 private:
  struct Entry {
    KvBlockMeta meta;
    int64_t deadline_us = 0;
  };
  struct PrefixReplica {
    KvPrefixMeta meta;
    int64_t deadline_us = 0;
  };
  struct PrefixEntry {
    Key128 hash;        // the content hash every replica must agree on
    uint32_t depth = 0;
    uint64_t len = 0;
    std::vector<PrefixReplica> replicas;
    // Per-node zombie fence, surviving replica pruning: highest
    // generation ever accepted from each node for this chain key.
    std::unordered_map<std::string, uint64_t> last_gen;
  };
  std::mutex mu_;
  std::unordered_map<uint64_t, Entry> entries_;
  std::unordered_map<uint64_t, uint64_t> last_gen_;
  std::unordered_map<Key128, PrefixEntry, Key128Hash> prefix_;
};
KvRegistry& kv_registry();

// Attach the native handlers (call before Server::Start).  Both may be
// attached to the same server; the registry may also run on a node that
// stores nothing.  Return 0, or -1 when any registration was refused
// (server already running).
int kv_attach_store(Server* s);
int kv_attach_registry(Server* s);

// ---- client-side lookup cache (decode side) ------------------------------

// Caches registry lookups with generation-checked invalidation.  NOT a
// freshness timer: a cached record is used until a fetch proves it
// stale (kEKvStale/kEKvMiss), then invalidated and re-resolved once.
class KvCache {
 public:
  // `registry_ch` (not owned) must outlive the cache.
  explicit KvCache(Channel* registry_ch) : reg_(registry_ch) {}

  // Cached lookup (refresh forces a registry round-trip).  0 or error.
  int lookup(uint64_t block_id, KvBlockMeta* out, bool refresh = false);
  void invalidate(uint64_t block_id);

  // Fetches block_id's bytes from `node_ch` (a channel to meta.node,
  // caller-routed) using the cached record; on a stale answer
  // invalidates, re-looks-up, and retries ONCE with the fresh
  // generation.  0 on success (bytes in *out), else the final error.
  int fetch(Channel* node_ch, uint64_t block_id, IOBuf* out);

  uint64_t hits() const {
    // Relaxed: monotonic test/stat counters — no ordering carried.
    return hits_.load(std::memory_order_relaxed);
  }
  uint64_t misses() const {
    // Relaxed: monotonic test/stat counters — no ordering carried.
    return misses_.load(std::memory_order_relaxed);
  }

 private:
  Channel* reg_;
  std::mutex mu_;
  std::unordered_map<uint64_t, KvBlockMeta> cache_;
  // Relaxed counters: diagnostics only, no synchronization piggybacks.
  std::atomic<uint64_t> hits_{0};
  std::atomic<uint64_t> misses_{0};
};

// Flag registration (idempotent; attach functions and the capi call it
// so /flags sees the kv knobs before first traffic).
void kv_ensure_registered();

}  // namespace trpc
