// Ambient routing hint for cache-aware balancing (ISSUE 17).
//
// The c_hash_bl balancer walks a ketama ring blind to what each member
// already holds; for KV-prefix traffic the decode side KNOWS (from a
// KvReg.Match answer) which member holds the longest cached prefix.
// A hint is that knowledge made ambient: the caller installs the
// preferred endpoint around a ClusterChannel call, and the bounded-load
// walk honors it IFF the hinted member is healthy and under the load
// bound — bounded load always outranks affinity, so a hot replica's
// overflow still diffuses along the ring (veto) instead of melting the
// prefix owner.
//
// Thread-local by design: the sync ClusterChannel::CallMethod path
// selects on the caller's thread (the async wrapper re-installs ambient
// state in its fiber the same way trace context rides AsyncCall).  The
// hint is one-shot per attempt 0 — retries already exclude the tried
// node, so re-applying the hint would only re-pick a failed member.
#pragma once

#include <atomic>

#include "base/endpoint.h"

namespace trpc {

// Fleet-visible outcome counters, exposed as vars by cluster.cc
// (lb_hint_hit_total / lb_hint_veto_total / lb_hint_miss_total).
struct LbHintCounters {
  std::atomic<uint64_t> hit{0};    // hinted member selected
  std::atomic<uint64_t> veto{0};   // hinted member over the load bound
  std::atomic<uint64_t> miss{0};   // hinted member absent/unhealthy

  // Relaxed: monotonic stat counters — nothing is published through
  // them and staleness only blurs a dashboard read.
  void bump(std::atomic<uint64_t>& c) {
    c.fetch_add(1, std::memory_order_relaxed);
  }
  // Relaxed: same monotonic-stat rationale as bump().
  static uint64_t read(const std::atomic<uint64_t>& c) {
    return c.load(std::memory_order_relaxed);
  }
};
LbHintCounters& lb_hint_counters();  // defined in cluster.cc

namespace lb_hint_detail {
// One slot per thread: {set, endpoint}.  inline thread_local keeps the
// header self-contained (no TU to add for a hint that is pure state).
struct Slot {
  bool set = false;
  EndPoint ep;
};
inline thread_local Slot tls_slot;
}  // namespace lb_hint_detail

inline void lb_hint_set(const EndPoint& ep) {
  lb_hint_detail::tls_slot.set = true;
  lb_hint_detail::tls_slot.ep = ep;
}

inline void lb_hint_clear() { lb_hint_detail::tls_slot.set = false; }

// True (and fills *out) when a hint is installed on this thread.
inline bool lb_hint_get(EndPoint* out) {
  if (!lb_hint_detail::tls_slot.set) {
    return false;
  }
  if (out != nullptr) {
    *out = lb_hint_detail::tls_slot.ep;
  }
  return true;
}

// RAII scope for the capi / call sites: install on entry, always clear
// on exit (a leaked hint would silently re-route the thread's NEXT
// unrelated call).
class LbHintScope {
 public:
  explicit LbHintScope(const EndPoint& ep) { lb_hint_set(ep); }
  LbHintScope(const LbHintScope&) = delete;
  LbHintScope& operator=(const LbHintScope&) = delete;
  ~LbHintScope() { lb_hint_clear(); }
};

}  // namespace trpc
