#include "net/legacy_pbrpc.h"

#include <errno.h>

#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>

#include "base/logging.h"
#include "base/pbwire.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/controller.h"
#include "net/messenger.h"
#include "net/nshead.h"
#include "net/protocol.h"
#include "net/server.h"

namespace trpc {

namespace {

constexpr size_t kMaxBody = 64ull << 20;

// Hulu meta field numbers (policy/hulu_pbrpc_meta.proto).
constexpr uint32_t kHuluReqService = 1;
constexpr uint32_t kHuluReqMethodIndex = 2;
constexpr uint32_t kHuluReqCorrelation = 4;   // int64
constexpr uint32_t kHuluReqMethodName = 14;
constexpr uint32_t kHuluRspErrorCode = 1;
constexpr uint32_t kHuluRspErrorText = 2;
constexpr uint32_t kHuluRspCorrelation = 3;   // sint64 (zigzag)

// Sofa meta field numbers (policy/sofa_pbrpc_meta.proto).
constexpr uint32_t kSofaType = 1;             // 0 request / 1 response
constexpr uint32_t kSofaSequenceId = 2;
constexpr uint32_t kSofaMethod = 100;
constexpr uint32_t kSofaFailed = 200;
constexpr uint32_t kSofaErrorCode = 201;
constexpr uint32_t kSofaReason = 202;

// public_pbrpc field numbers (policy/public_pbrpc_meta.proto).
constexpr uint32_t kPubReqHead = 1;
constexpr uint32_t kPubReqBody = 2;
constexpr uint32_t kPubHeadLogId = 7;
constexpr uint32_t kPubBodyService = 3;
constexpr uint32_t kPubBodyMethodId = 4;
constexpr uint32_t kPubBodyId = 5;
constexpr uint32_t kPubBodyPayload = 6;
constexpr uint32_t kPubRspHead = 1;
constexpr uint32_t kPubRspBody = 2;
constexpr uint32_t kPubRspCode = 1;           // sint32 (zigzag)
constexpr uint32_t kPubRspText = 2;
constexpr uint32_t kPubRspPayload = 1;
constexpr uint32_t kPubRspError = 3;
constexpr uint32_t kPubRspId = 4;

uint32_t load_u32le(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

uint64_t load_u64le(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

void put_u32le(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

void put_u64le(std::string* out, uint64_t v) {
  out->append(reinterpret_cast<const char*>(&v), 8);
}

// ---- frame cutters (hulu / sofa) -----------------------------------------

struct MetaFrame {
  PbMessage meta;
  IOBuf payload;
};

// [HULU][body_size u32][meta_size u32] native order, meta+payload follow.
ParseError hulu_cut(IOBuf* source, InputMessage* out, Socket* sock,
                    bool probing) {
  uint8_t head[12];
  const size_t got = source->copy_to(head, sizeof(head), 0);
  if (got < 4) {
    return probing && std::memcmp(head, "HULU", got) != 0
               ? ParseError::kTryOtherProtocol
               : ParseError::kNotEnoughData;
  }
  if (std::memcmp(head, "HULU", 4) != 0) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  if (got < sizeof(head)) {
    return ParseError::kNotEnoughData;
  }
  const uint32_t body_size = load_u32le(head + 4);
  const uint32_t meta_size = load_u32le(head + 8);
  if (body_size > kMaxBody || meta_size > body_size) {
    return ParseError::kCorrupted;
  }
  if (source->size() < sizeof(head) + body_size) {
    return ParseError::kNotEnoughData;
  }
  source->pop_front(sizeof(head));
  auto frame = std::make_shared<MetaFrame>();
  IOBuf meta_buf;
  source->cutn(&meta_buf, meta_size);
  if (!frame->meta.parse(meta_buf.to_string())) {
    return ParseError::kCorrupted;
  }
  source->cutn(&frame->payload, body_size - meta_size);
  out->ctx = std::move(frame);
  out->socket = sock != nullptr ? sock->id() : 0;
  return ParseError::kOk;
}

// [SOFA][meta_size u32][body_size u64][message_size u64] native order.
ParseError sofa_cut(IOBuf* source, InputMessage* out, Socket* sock,
                    bool probing) {
  uint8_t head[24];
  const size_t got = source->copy_to(head, sizeof(head), 0);
  if (got < 4) {
    return probing && std::memcmp(head, "SOFA", got) != 0
               ? ParseError::kTryOtherProtocol
               : ParseError::kNotEnoughData;
  }
  if (std::memcmp(head, "SOFA", 4) != 0) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  if (got < sizeof(head)) {
    return ParseError::kNotEnoughData;
  }
  const uint32_t meta_size = load_u32le(head + 4);
  const uint64_t body_size = load_u64le(head + 8);
  const uint64_t msg_size = load_u64le(head + 16);
  if (msg_size != meta_size + body_size || msg_size > kMaxBody) {
    return ParseError::kCorrupted;
  }
  if (source->size() < sizeof(head) + msg_size) {
    return ParseError::kNotEnoughData;
  }
  source->pop_front(sizeof(head));
  auto frame = std::make_shared<MetaFrame>();
  IOBuf meta_buf;
  source->cutn(&meta_buf, meta_size);
  if (!frame->meta.parse(meta_buf.to_string())) {
    return ParseError::kCorrupted;
  }
  source->cutn(&frame->payload, body_size);
  out->ctx = std::move(frame);
  out->socket = sock != nullptr ? sock->id() : 0;
  return ParseError::kOk;
}

void hulu_pack(const PbMessage& meta, const IOBuf& payload, IOBuf* out) {
  std::string m = meta.serialize();
  std::string head = "HULU";
  put_u32le(&head, static_cast<uint32_t>(m.size() + payload.size()));
  put_u32le(&head, static_cast<uint32_t>(m.size()));
  out->append(head);
  out->append(m);
  out->append(payload);
}

void sofa_pack(const PbMessage& meta, const IOBuf& payload, IOBuf* out) {
  std::string m = meta.serialize();
  std::string head = "SOFA";
  put_u32le(&head, static_cast<uint32_t>(m.size()));
  put_u64le(&head, payload.size());
  put_u64le(&head, m.size() + payload.size());
  out->append(head);
  out->append(m);
  out->append(payload);
}

// ---- shared server dispatch ----------------------------------------------

// Runs the registry handler for `mkey`; `respond(cntl, response)` packs
// and writes the protocol's reply (called exactly once, possibly from
// the handler's own fiber).  When `latch` is non-null the caller parks
// on it (FIFO protocols).
void legacy_dispatch(
    Server* srv, Socket* sock, const std::string& mkey, IOBuf&& payload,
    std::function<void(Controller*, IOBuf*)> respond,
    std::shared_ptr<CountdownEvent> latch) {
  {  // Interceptor gate (same body as every serving protocol).
    int ec = 0;
    std::string et;
    if (!srv->accept_request(mkey, sock->remote(), &ec, &et)) {
      Controller fail;
      fail.SetFailed(ec, et);
      IOBuf empty;
      respond(&fail, &empty);
      if (latch) latch->signal();
      return;
    }
  }
  const Server::MethodProperty* prop = srv->find_method(mkey);
  if (prop == nullptr) {
    Controller fail;
    fail.SetFailed(ENOENT, "unknown method " + mkey);
    IOBuf empty;
    respond(&fail, &empty);
    if (latch) latch->signal();
    return;
  }
  std::shared_ptr<ConcurrencyLimiter> limiter = prop->limiter;
  if (limiter != nullptr && !limiter->on_request()) {
    Controller fail;
    fail.SetFailed(EAGAIN, "rejected by concurrency limiter");
    IOBuf empty;
    respond(&fail, &empty);
    if (latch) latch->signal();
    return;
  }
  auto* cntl = new Controller();
  cntl->set_method(mkey);
  auto* response = new IOBuf();
  const int64_t start_us = monotonic_time_us();
  std::shared_ptr<LatencyRecorder> lat = prop->latency;
  srv->in_flight.fetch_add(1, std::memory_order_acq_rel);
  Closure done = [srv, cntl, response, respond, latch, lat, limiter,
                  start_us] {
    if (limiter != nullptr) {
      limiter->on_response(monotonic_time_us() - start_us,
                           cntl->Failed());
    }
    respond(cntl, response);
    if (lat != nullptr) {
      *lat << (monotonic_time_us() - start_us);
    }
    delete response;
    delete cntl;
    srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    srv->in_flight.fetch_sub(1, std::memory_order_acq_rel);
    if (latch) latch->signal();
  };
  prop->handler(cntl, payload, response, std::move(done));
}

// ---- hulu server ---------------------------------------------------------

ParseError hulu_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing && static_cast<Server*>(sock->user_data) == nullptr) {
    return ParseError::kTryOtherProtocol;  // serving entry only
  }
  return hulu_cut(source, out, sock, probing);
}

void hulu_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto frame = std::static_pointer_cast<MetaFrame>(msg.ctx);
  if (srv == nullptr || frame == nullptr) {
    return;
  }
  const std::string service(frame->meta.get_bytes(kHuluReqService));
  const std::string mname(frame->meta.get_bytes(kHuluReqMethodName));
  const int64_t midx = static_cast<int64_t>(
      frame->meta.get_varint(kHuluReqMethodIndex));
  const int64_t cid =
      static_cast<int64_t>(frame->meta.get_varint(kHuluReqCorrelation));
  const std::string mkey =
      !mname.empty() ? service + "." + mname
                     : service + ".#" + std::to_string(midx);
  const SocketId sid = msg.socket;
  legacy_dispatch(
      srv, sock.get(), mkey, std::move(frame->payload),
      [sid, cid](Controller* cntl, IOBuf* response) {
        PbMessage meta;
        if (cntl->Failed()) {
          meta.add_varint(kHuluRspErrorCode,
                          static_cast<uint64_t>(cntl->error_code()));
          meta.add_bytes(kHuluRspErrorText, cntl->error_text());
        }
        meta.add_sint(kHuluRspCorrelation, cid);
        IOBuf out;
        hulu_pack(meta, cntl->Failed() ? IOBuf() : *response, &out);
        SocketRef s(Socket::Address(sid));
        if (s) {
          s->Write(std::move(out));
        }
      },
      nullptr);
}

void hulu_process_response(InputMessage&&) {}

// ---- sofa server ---------------------------------------------------------

ParseError sofa_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing && static_cast<Server*>(sock->user_data) == nullptr) {
    return ParseError::kTryOtherProtocol;
  }
  return sofa_cut(source, out, sock, probing);
}

void sofa_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto frame = std::static_pointer_cast<MetaFrame>(msg.ctx);
  if (srv == nullptr || frame == nullptr) {
    return;
  }
  const uint64_t seq = frame->meta.get_varint(kSofaSequenceId);
  const std::string mkey(frame->meta.get_bytes(kSofaMethod));
  const SocketId sid = msg.socket;
  legacy_dispatch(
      srv, sock.get(), mkey, std::move(frame->payload),
      [sid, seq](Controller* cntl, IOBuf* response) {
        PbMessage meta;
        meta.add_varint(kSofaType, 1);  // RESPONSE
        meta.add_varint(kSofaSequenceId, seq);
        if (cntl->Failed()) {
          meta.add_bool(kSofaFailed, true);
          meta.add_varint(kSofaErrorCode,
                          static_cast<uint64_t>(cntl->error_code()));
          meta.add_bytes(kSofaReason, cntl->error_text());
        }
        IOBuf out;
        sofa_pack(meta, cntl->Failed() ? IOBuf() : *response, &out);
        SocketRef s(Socket::Address(sid));
        if (s) {
          s->Write(std::move(out));
        }
      },
      nullptr);
}

void sofa_process_response(InputMessage&&) {}

// ---- nova server (nshead framing) ----------------------------------------

struct NovaFrame {
  NsheadHead head;
  IOBuf body;
};

ParseError nova_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || !srv->nova_pbrpc_enabled()) {
      return ParseError::kTryOtherProtocol;
    }
  }
  auto frame = std::make_shared<NovaFrame>();
  const int rc = nshead_cut_frame(source, &frame->head, &frame->body);
  if (rc == 0) {
    return probing ? nshead_probe_short(source)
                   : ParseError::kNotEnoughData;
  }
  if (rc < 0) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  out->ctx = std::move(frame);
  out->socket = sock->id();
  return ParseError::kOk;
}

// FIFO like raw nshead: inline + latch so async handlers keep order.
void nova_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto frame = std::static_pointer_cast<NovaFrame>(msg.ctx);
  if (srv == nullptr || frame == nullptr) {
    return;
  }
  const std::string mkey =
      "Nova.#" + std::to_string(frame->head.reserved);
  const SocketId sid = msg.socket;
  const NsheadHead req_head = frame->head;
  auto latch = std::make_shared<CountdownEvent>(1);
  legacy_dispatch(
      srv, sock.get(), mkey, std::move(frame->body),
      [sid, req_head](Controller* cntl, IOBuf* response) {
        NsheadHead h = req_head;
        h.version = 0;  // no compression flag on the response
        IOBuf out;
        nshead_pack(h, cntl->Failed() ? IOBuf() : *response, &out);
        SocketRef s(Socket::Address(sid));
        if (s) {
          s->Write(std::move(out));
        }
      },
      latch);
  latch->wait(-1);
}

void nova_process_response(InputMessage&&) {}

// ---- public_pbrpc server (nshead framing) --------------------------------

ParseError public_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || !srv->public_pbrpc_enabled()) {
      return ParseError::kTryOtherProtocol;
    }
  }
  auto frame = std::make_shared<NovaFrame>();
  const int rc = nshead_cut_frame(source, &frame->head, &frame->body);
  if (rc == 0) {
    return probing ? nshead_probe_short(source)
                   : ParseError::kNotEnoughData;
  }
  if (rc < 0) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  out->ctx = std::move(frame);
  out->socket = sock->id();
  return ParseError::kOk;
}

void public_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto frame = std::static_pointer_cast<NovaFrame>(msg.ctx);
  if (srv == nullptr || frame == nullptr) {
    return;
  }
  PbMessage req;
  PbMessage body;
  if (!req.parse(frame->body.to_string()) ||
      !req.get_message(kPubReqBody, &body)) {
    sock->SetFailed(EPROTO);
    return;
  }
  const std::string service(body.get_bytes(kPubBodyService));
  const uint64_t method_id = body.get_varint(kPubBodyMethodId);
  const uint64_t id = body.get_varint(kPubBodyId);
  IOBuf payload;
  payload.append(std::string(body.get_bytes(kPubBodyPayload)));
  const std::string mkey =
      service + ".#" + std::to_string(method_id);
  const SocketId sid = msg.socket;
  const NsheadHead req_head = frame->head;
  auto latch = std::make_shared<CountdownEvent>(1);
  legacy_dispatch(
      srv, sock.get(), mkey, std::move(payload),
      [sid, req_head, id](Controller* cntl, IOBuf* response) {
        PbMessage head;
        head.add_sint(kPubRspCode, cntl->Failed() ? cntl->error_code() : 0);
        if (cntl->Failed()) {
          head.add_bytes(kPubRspText, cntl->error_text());
        }
        PbMessage rbody;
        if (!cntl->Failed()) {
          rbody.add_bytes(kPubRspPayload, response->to_string());
        } else {
          rbody.add_varint(kPubRspError,
                           static_cast<uint64_t>(cntl->error_code()));
        }
        rbody.add_varint(kPubRspId, id);
        PbMessage rsp;
        rsp.add_message(kPubRspHead, head);
        rsp.add_message(kPubRspBody, rbody);
        IOBuf body_buf;
        body_buf.append(rsp.serialize());
        IOBuf out;
        nshead_pack(req_head, body_buf, &out);
        SocketRef s(Socket::Address(sid));
        if (s) {
          s->Write(std::move(out));
        }
      },
      latch);
  latch->wait(-1);
}

void public_process_response(InputMessage&&) {}

}  // namespace

void register_hulu_protocol() {
  static int once = [] {
    Protocol p = {"hulu", hulu_parse, hulu_process_request,
                  hulu_process_response, /*process_in_order=*/false};
    return register_protocol(p);
  }();
  (void)once;
}

void register_sofa_protocol() {
  static int once = [] {
    Protocol p = {"sofa", sofa_parse, sofa_process_request,
                  sofa_process_response, /*process_in_order=*/false};
    return register_protocol(p);
  }();
  (void)once;
}

void register_nova_protocol() {
  static int once = [] {
    Protocol p = {"nova", nova_parse, nova_process_request,
                  nova_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

void register_public_pbrpc_protocol() {
  static int once = [] {
    Protocol p = {"public_pbrpc", public_parse, public_process_request,
                  public_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- client --------------------------------------------------------------

namespace {

struct LegacyWaiter {
  CountdownEvent ev{1};
  LegacyRpcClient::Result result;
};

// One connection's in-flight calls: keyed by correlation id for
// hulu/sofa/public, FIFO deque for nova (no id on the wire).
struct LegacyCliConn {
  std::mutex mu;
  std::map<uint64_t, std::shared_ptr<LegacyWaiter>> by_id;
  std::deque<std::shared_ptr<LegacyWaiter>> fifo;
};

const char kLegacyCliTag = 0;

LegacyCliConn* lcli_conn_of(Socket* s) {
  return proto_conn_of<LegacyCliConn>(s, &kLegacyCliTag);
}

int install_legacy_conn(Socket* s) {
  lcli_conn_of(s);
  return 0;
}

std::shared_ptr<LegacyWaiter> take_by_id(Socket* sock, uint64_t id) {
  LegacyCliConn* c = lcli_conn_of(sock);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->by_id.find(id);
  if (it == c->by_id.end()) {
    return nullptr;
  }
  auto w = std::move(it->second);
  c->by_id.erase(it);
  return w;
}

std::shared_ptr<LegacyWaiter> take_fifo(Socket* sock) {
  LegacyCliConn* c = lcli_conn_of(sock);
  std::lock_guard<std::mutex> g(c->mu);
  if (c->fifo.empty()) {
    return nullptr;
  }
  auto w = std::move(c->fifo.front());
  c->fifo.pop_front();
  return w;
}

// -- hulu client protocol --

ParseError huluc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;
  }
  ParseError rc = hulu_cut(source, out, sock, /*probing=*/false);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

void huluc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto frame = std::static_pointer_cast<MetaFrame>(msg.ctx);
  const uint64_t cid =
      static_cast<uint64_t>(frame->meta.get_sint(kHuluRspCorrelation));
  auto w = take_by_id(sock.get(), cid);
  if (!w) {
    return;
  }
  const int ec =
      static_cast<int>(frame->meta.get_varint(kHuluRspErrorCode));
  if (ec != 0) {
    w->result.error_code = ec;
    w->result.error_text =
        std::string(frame->meta.get_bytes(kHuluRspErrorText));
  } else {
    w->result.ok = true;
    w->result.response = std::move(frame->payload);
  }
  w->ev.signal();
}

void huluc_process_request(InputMessage&&) {}

int huluc_protocol_index() {
  static const int index = [] {
    Protocol p = {"huluc", huluc_parse, huluc_process_request,
                  huluc_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

// -- sofa client protocol --

ParseError sofac_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;
  }
  ParseError rc = sofa_cut(source, out, sock, /*probing=*/false);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

void sofac_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto frame = std::static_pointer_cast<MetaFrame>(msg.ctx);
  const uint64_t seq = frame->meta.get_varint(kSofaSequenceId);
  auto w = take_by_id(sock.get(), seq);
  if (!w) {
    return;
  }
  if (frame->meta.get_bool(kSofaFailed)) {
    w->result.error_code =
        static_cast<int>(frame->meta.get_varint(kSofaErrorCode));
    w->result.error_text =
        std::string(frame->meta.get_bytes(kSofaReason));
    if (w->result.error_code == 0) {
      w->result.error_code = EREMOTE;
    }
  } else {
    w->result.ok = true;
    w->result.response = std::move(frame->payload);
  }
  w->ev.signal();
}

void sofac_process_request(InputMessage&&) {}

int sofac_protocol_index() {
  static const int index = [] {
    Protocol p = {"sofac", sofac_parse, sofac_process_request,
                  sofac_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

// -- nova / public client protocols (nshead frames back) --

ParseError nsfamc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;
  }
  auto frame = std::make_shared<NovaFrame>();
  const int rc = nshead_cut_frame(source, &frame->head, &frame->body);
  if (rc == 0) {
    return ParseError::kNotEnoughData;
  }
  if (rc < 0) {
    return ParseError::kCorrupted;
  }
  out->ctx = std::move(frame);
  out->meta.type = RpcMeta::kResponse;
  out->socket = sock->id();
  return ParseError::kOk;
}

void novac_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto frame = std::static_pointer_cast<NovaFrame>(msg.ctx);
  auto w = take_fifo(sock.get());
  if (!w) {
    return;
  }
  w->result.ok = true;
  w->result.response = std::move(frame->body);
  w->ev.signal();
}

void novac_process_request(InputMessage&&) {}

int novac_protocol_index() {
  static const int index = [] {
    Protocol p = {"novac", nsfamc_parse, novac_process_request,
                  novac_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

void publicc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto frame = std::static_pointer_cast<NovaFrame>(msg.ctx);
  PbMessage rsp, head, body;
  if (!rsp.parse(frame->body.to_string()) ||
      !rsp.get_message(kPubRspBody, &body)) {
    sock->SetFailed(EPROTO);
    return;
  }
  auto w = take_by_id(sock.get(), body.get_varint(kPubRspId));
  if (!w) {
    return;
  }
  int code = 0;
  if (rsp.get_message(kPubRspHead, &head)) {
    code = static_cast<int>(head.get_sint(kPubRspCode));
  }
  const int berr = static_cast<int>(body.get_varint(kPubRspError));
  if (code != 0 || berr != 0) {
    w->result.error_code = code != 0 ? code : berr;
    w->result.error_text = std::string(head.get_bytes(kPubRspText));
  } else {
    w->result.ok = true;
    w->result.response.append(
        std::string(body.get_bytes(kPubRspPayload)));
  }
  w->ev.signal();
}

int publicc_protocol_index() {
  static const int index = [] {
    Protocol p = {"publicc", nsfamc_parse, novac_process_request,
                  publicc_process_response, /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

int client_protocol_index(LegacyProto proto) {
  switch (proto) {
    case LegacyProto::kHulu:
      return huluc_protocol_index();
    case LegacyProto::kSofa:
      return sofac_protocol_index();
    case LegacyProto::kNova:
      return novac_protocol_index();
    case LegacyProto::kPublic:
      return publicc_protocol_index();
  }
  return -1;
}

}  // namespace

LegacyRpcClient::~LegacyRpcClient() {
  csock_.Shutdown();
}

int LegacyRpcClient::Init(const std::string& addr, LegacyProto proto,
                          const Options* opts) {
  fiber_init(0);
  proto_ = proto;
  if (opts != nullptr) {
    opts_ = *opts;
  }
  client_protocol_index(proto);
  return csock_.Init(addr);
}

LegacyRpcClient::Result LegacyRpcClient::call(const std::string& service,
                                              const std::string& method,
                                              int32_t method_index,
                                              const IOBuf& request) {
  Result fail;
  SocketId sid = 0;
  uint64_t id = 0;
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(client_protocol_index(proto_), install_legacy_conn,
                      &sid) != 0) {
      fail.error_code = EHOSTUNREACH;
      fail.error_text = "cannot reach " + endpoint2str(csock_.endpoint());
      return fail;
    }
    id = next_id_++;
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    fail.error_code = ECONNRESET;
    fail.error_text = "connection failed";
    return fail;
  }

  IOBuf out;
  switch (proto_) {
    case LegacyProto::kHulu: {
      PbMessage meta;
      meta.add_bytes(kHuluReqService, service);
      meta.add_varint(kHuluReqMethodIndex,
                      static_cast<uint64_t>(method_index));
      meta.add_varint(kHuluReqCorrelation, id);
      if (!method.empty()) {
        meta.add_bytes(kHuluReqMethodName, method);
      }
      hulu_pack(meta, request, &out);
      break;
    }
    case LegacyProto::kSofa: {
      PbMessage meta;
      meta.add_varint(kSofaType, 0);  // REQUEST
      meta.add_varint(kSofaSequenceId, id);
      meta.add_bytes(kSofaMethod, service + "." + method);
      sofa_pack(meta, request, &out);
      break;
    }
    case LegacyProto::kNova: {
      NsheadHead h;
      h.reserved = static_cast<uint32_t>(method_index);
      nshead_pack(h, request, &out);
      break;
    }
    case LegacyProto::kPublic: {
      PbMessage head;
      PbMessage body;
      body.add_bytes(kPubBodyService, service);
      body.add_varint(kPubBodyMethodId,
                      static_cast<uint64_t>(method_index));
      body.add_varint(kPubBodyId, id);
      body.add_bytes(kPubBodyPayload, request.to_string());
      PbMessage req;
      req.add_message(kPubReqHead, head);
      req.add_message(kPubReqBody, body);
      IOBuf body_buf;
      body_buf.append(req.serialize());
      NsheadHead h;
      nshead_pack(h, body_buf, &out);
      break;
    }
  }

  LegacyCliConn* c = lcli_conn_of(s.get());
  auto w = std::make_shared<LegacyWaiter>();
  const bool fifo = proto_ == LegacyProto::kNova;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (fifo) {
      c->fifo.push_back(w);
    } else {
      c->by_id.emplace(id, w);
    }
    if (s->Write(std::move(out)) != 0) {
      if (fifo) {
        c->fifo.pop_back();
      } else {
        c->by_id.erase(id);
      }
      fail.error_code = EPIPE;
      fail.error_text = "write failed";
      return fail;
    }
  }
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  if (w->ev.wait(deadline) != 0) {
    if (!fifo) {
      std::lock_guard<std::mutex> g(c->mu);
      c->by_id.erase(id);
    }
    // FIFO waiters stay queued so later replies keep their alignment.
    fail.error_code = ETIMEDOUT;
    fail.error_text = "timeout";
    return fail;
  }
  return std::move(w->result);
}

}  // namespace trpc
