// Legacy Baidu pbrpc protocol family — hulu / sofa / nova / public_pbrpc.
//
// Parity: /root/reference/src/brpc/policy/{hulu,sofa,nova,public}_pbrpc_
// protocol.cpp (+ their .proto metas).  All four are "frame + protobuf
// meta + payload" variants; the reference decodes the metas with
// generated protobuf classes, this runtime uses the pbwire codec
// (base/pbwire.h) with the field numbers straight from the public .proto
// files:
//   hulu   : 12B header [HULU][body_size u32][meta_size u32] (native
//            order), meta HuluRpcRequestMeta{1:service 2:method_index
//            4:correlation_id 5:log_id 14:method_name} / ResponseMeta
//            {1:error_code 2:error_text 3:sint64 correlation_id}.
//   sofa   : 24B header [SOFA][meta u32][body u64][msg u64] (native
//            order), meta SofaRpcMeta{1:type(0 req/1 rsp) 2:sequence_id
//            100:method 200:failed 201:error_code 202:reason}.
//   nova   : nshead framing; head.reserved = method index; body IS the
//            request payload (no meta).  FIFO correlation.
//   public : nshead framing; body = PublicPbrpcRequest{1:RequestHead
//            {7:log_id} 2:RequestBody{3:service 4:method_id 5:id
//            6:serialized_request}} / PublicPbrpcResponse{1:ResponseHead
//            {1:sint32 code 2:text} 2:ResponseBody{1:serialized_response
//            3:error 4:id}}.
//
// Serving model: all four dispatch into the Server's ONE method
// registry, so a handler registered once serves tstd AND every legacy
// protocol.  Method keys: "<service>.<method_name>" when the wire names
// the method, "<service>.#<index>" for index-addressed protocols
// (hulu without method_name, nova as "Nova.#<idx>", public).
#pragma once

#include <cstdint>
#include <string>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/proto_client.h"
#include "net/socket.h"

namespace trpc {

enum class LegacyProto : uint8_t {
  kHulu = 0,
  kSofa = 1,
  kNova = 2,
  kPublic = 3,
};

// Server side: hulu + sofa register unconditionally in Server::Start
// (their 4-byte magics are unambiguous); nova/public ride nshead and are
// enabled per server (Server::enable_nova_pbrpc / enable_public_pbrpc —
// at most one nshead personality per server, see server.h).
void register_hulu_protocol();
void register_sofa_protocol();
void register_nova_protocol();
void register_public_pbrpc_protocol();

// One client for the whole family.
class LegacyRpcClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
  };

  struct Result {
    bool ok = false;
    int error_code = 0;
    std::string error_text;
    IOBuf response;
  };

  ~LegacyRpcClient();
  int Init(const std::string& addr, LegacyProto proto,
           const Options* opts = nullptr);

  // `service` + `method` address the remote handler.  method is a name
  // ("Echo") where the protocol carries names (hulu sends BOTH name and
  // index, sofa sends "service.method"), and an index is required where
  // the wire is index-only (nova, public) — pass it in method_index.
  Result call(const std::string& service, const std::string& method,
              int32_t method_index, const IOBuf& request);

 private:
  LegacyProto proto_ = LegacyProto::kHulu;
  Options opts_;
  FiberMutex sock_mu_;
  ClientSocket csock_;
  uint64_t next_id_ = 1;
};

}  // namespace trpc
