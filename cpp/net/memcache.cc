#include "net/memcache.h"

#include <errno.h>

#include <cstring>
#include <ctime>
#include <deque>
#include <mutex>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/messenger.h"
#include "net/protocol.h"
#include "net/server.h"

namespace trpc {

namespace {

constexpr uint8_t kMagicRequest = 0x80;
constexpr uint8_t kMagicResponse = 0x81;
constexpr size_t kHeader = 24;
constexpr size_t kMaxBody = 64ull << 20;
constexpr size_t kMaxKey = 250;  // memcached's documented key limit

void put_u16(std::string* out, uint16_t v) {
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v));
}

void put_u32(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 24));
  out->push_back(static_cast<char>(v >> 16));
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v));
}

void put_u64(std::string* out, uint64_t v) {
  put_u32(out, static_cast<uint32_t>(v >> 32));
  put_u32(out, static_cast<uint32_t>(v));
}

uint16_t read_u16(const uint8_t* p) {
  return static_cast<uint16_t>((p[0] << 8) | p[1]);
}

uint32_t read_u32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | p[3];
}

uint64_t read_u64(const uint8_t* p) {
  return (static_cast<uint64_t>(read_u32(p)) << 32) | read_u32(p + 4);
}

void pack_frame(uint8_t magic, McOp op, uint16_t status_or_vb,
                uint32_t opaque, uint64_t cas, const std::string& extras,
                const std::string& key, const std::string& value,
                std::string* out) {
  out->push_back(static_cast<char>(magic));
  out->push_back(static_cast<char>(op));
  put_u16(out, static_cast<uint16_t>(key.size()));
  out->push_back(static_cast<char>(extras.size()));
  out->push_back(0);  // data type
  put_u16(out, status_or_vb);
  put_u32(out, static_cast<uint32_t>(extras.size() + key.size() +
                                     value.size()));
  put_u32(out, opaque);
  put_u64(out, cas);
  out->append(extras);
  out->append(key);
  out->append(value);
}

}  // namespace

void mc_pack_request(const McCommand& cmd, uint32_t opaque,
                     std::string* out) {
  std::string extras;
  std::string value;
  switch (cmd.op) {
    case McOp::kSet:
    case McOp::kAdd:
    case McOp::kReplace:
      put_u32(&extras, cmd.flags);
      put_u32(&extras, cmd.exptime);
      value = cmd.value;
      break;
    case McOp::kIncrement:
    case McOp::kDecrement:
      put_u64(&extras, cmd.delta);
      put_u64(&extras, cmd.initial);
      put_u32(&extras, cmd.exptime);
      break;
    case McOp::kTouch:
    case McOp::kFlush:
      put_u32(&extras, cmd.exptime);
      break;
    case McOp::kAppend:
    case McOp::kPrepend:
      value = cmd.value;
      break;
    default:
      break;
  }
  pack_frame(kMagicRequest, cmd.op, cmd.vbucket, opaque, cmd.cas,
             extras, cmd.key, value, out);
}

void mc_pack_response(McOp op, McStatus status, uint32_t opaque,
                      uint64_t cas, const std::string& extras,
                      const std::string& key, const std::string& value,
                      std::string* out) {
  pack_frame(kMagicResponse, op, static_cast<uint16_t>(status), opaque,
             cas, extras, key, value, out);
}

int mc_parse_frame(const std::string& data, size_t* pos, McFrame* out) {
  if (data.size() - *pos < kHeader) {
    return 0;
  }
  const uint8_t* h =
      reinterpret_cast<const uint8_t*>(data.data()) + *pos;
  if (h[0] != kMagicRequest && h[0] != kMagicResponse) {
    return -1;
  }
  const uint16_t key_len = read_u16(h + 2);
  const uint8_t extras_len = h[4];
  const uint32_t total = read_u32(h + 8);
  if (total > kMaxBody ||
      static_cast<uint32_t>(key_len) + extras_len > total) {
    return -1;
  }
  if (data.size() - *pos < kHeader + total) {
    return 0;
  }
  out->magic = h[0];
  out->op = static_cast<McOp>(h[1]);
  out->status_or_vbucket = read_u16(h + 6);
  out->opaque = read_u32(h + 12);
  out->cas = read_u64(h + 16);
  const char* body = data.data() + *pos + kHeader;
  out->extras.assign(body, extras_len);
  out->key.assign(body + extras_len, key_len);
  out->value.assign(body + extras_len + key_len,
                    total - extras_len - key_len);
  *pos += kHeader + total;
  return 1;
}

// ---- server-side service -------------------------------------------------

bool MemcacheService::expired_locked(const Item& it) const {
  return it.expire_at_us != 0 && monotonic_time_us() >= it.expire_at_us;
}

size_t MemcacheService::item_count() {
  LockGuard<FiberMutex> g(mu_);
  // Sweep entries whose keys were never touched after expiring.
  for (auto it = items_.begin(); it != items_.end();) {
    it = expired_locked(it->second) ? items_.erase(it) : std::next(it);
  }
  return items_.size();
}

McResult MemcacheService::Execute(const McCommand& cmd) {
  McResult r;
  LockGuard<FiberMutex> g(mu_);
  if (vbucket_filter_ && !cmd.key.empty() &&
      !vbucket_filter_(cmd.vbucket)) {
    r.status = McStatus::kNotMyVbucket;
    r.value = "not my vbucket";
    return r;
  }
  auto it = items_.find(cmd.key);
  if (it != items_.end() && expired_locked(it->second)) {
    // Lazy reclamation: an expired entry is erased the moment any op
    // touches its key, so short-TTL churn on live keys cannot grow the
    // map (item_count() sweeps the never-touched remainder).
    items_.erase(it);
    it = items_.end();
  }
  const bool present = it != items_.end();
  auto expiry = [&]() -> int64_t {
    if (cmd.exptime == 0) {
      return 0;
    }
    // Per the memcache protocol, exptime above 30 days is an ABSOLUTE
    // unix timestamp; at or below it is an offset from now.
    constexpr uint32_t kRelativeLimit = 60 * 60 * 24 * 30;
    int64_t rel_s = cmd.exptime <= kRelativeLimit
                        ? static_cast<int64_t>(cmd.exptime)
                        : static_cast<int64_t>(cmd.exptime) -
                              static_cast<int64_t>(::time(nullptr));
    if (rel_s <= 0) {
      return monotonic_time_us();  // already expired
    }
    return monotonic_time_us() + rel_s * 1000000;
  };
  switch (cmd.op) {
    case McOp::kGet: {
      if (!present) {
        r.status = McStatus::kNotFound;
        break;
      }
      r.value = it->second.value;
      r.flags = it->second.flags;
      r.cas = it->second.cas;
      break;
    }
    case McOp::kSet: {
      if (cmd.cas != 0 && present && it->second.cas != cmd.cas) {
        r.status = McStatus::kExists;
        break;
      }
      if (cmd.cas != 0 && !present) {
        r.status = McStatus::kNotFound;
        break;
      }
      Item& item = items_[cmd.key];
      item.value = cmd.value;
      item.flags = cmd.flags;
      item.cas = ++next_cas_;
      item.expire_at_us = expiry();
      r.cas = item.cas;
      break;
    }
    case McOp::kAdd:
    case McOp::kReplace: {
      if (cmd.op == McOp::kAdd ? present : !present) {
        r.status = McStatus::kNotStored;
        break;
      }
      Item& item = items_[cmd.key];
      item.value = cmd.value;
      item.flags = cmd.flags;
      item.cas = ++next_cas_;
      item.expire_at_us = expiry();
      r.cas = item.cas;
      break;
    }
    case McOp::kAppend:
    case McOp::kPrepend: {
      if (!present) {
        r.status = McStatus::kNotStored;
        break;
      }
      if (cmd.op == McOp::kAppend) {
        it->second.value += cmd.value;
      } else {
        it->second.value.insert(0, cmd.value);
      }
      it->second.cas = ++next_cas_;
      r.cas = it->second.cas;
      break;
    }
    case McOp::kDelete: {
      if (!present) {
        r.status = McStatus::kNotFound;
        break;
      }
      items_.erase(it);
      break;
    }
    case McOp::kIncrement:
    case McOp::kDecrement: {
      if (!present) {
        // exptime 0xffffffff means "don't create on miss" per the spec.
        if (cmd.exptime == 0xffffffffu) {
          r.status = McStatus::kNotFound;
          break;
        }
        Item& item = items_[cmd.key];
        item.value = std::to_string(cmd.initial);
        item.cas = ++next_cas_;
        item.expire_at_us = expiry();
        r.numeric = cmd.initial;
        r.cas = item.cas;
        break;
      }
      uint64_t cur = 0;
      const std::string& v = it->second.value;
      if (v.empty() ||
          v.find_first_not_of("0123456789") != std::string::npos) {
        r.status = McStatus::kDeltaBadValue;
        break;
      }
      cur = strtoull(v.c_str(), nullptr, 10);
      if (cmd.op == McOp::kIncrement) {
        cur += cmd.delta;  // wraps at 2^64 per spec
      } else {
        cur = cur >= cmd.delta ? cur - cmd.delta : 0;  // floors at 0
      }
      it->second.value = std::to_string(cur);
      it->second.cas = ++next_cas_;
      r.numeric = cur;
      r.cas = it->second.cas;
      break;
    }
    case McOp::kTouch: {
      if (!present) {
        r.status = McStatus::kNotFound;
        break;
      }
      it->second.expire_at_us = expiry();
      break;
    }
    case McOp::kFlush:
      items_.clear();
      break;
    case McOp::kNoop:
      break;
    case McOp::kVersion:
      r.value = "1.6.0-trpc";
      break;
    default:
      r.status = McStatus::kUnknownCommand;
      break;
  }
  return r;
}

// ---- server protocol -----------------------------------------------------

namespace {

// Parsed frame handed through InputMessage::ctx — the frame is decoded
// ONCE here (value stays an IOBuf, zero-copy off the read buffer; the
// hot path of a cache protocol must not flatten+reparse 64MB values).
struct McFrameCtx {
  McOp op = McOp::kGet;
  uint16_t status_or_vbucket = 0;
  uint32_t opaque = 0;
  uint64_t cas = 0;
  std::string extras;  // <= 20 bytes by construction
  std::string key;
  IOBuf value;
};

ParseError mc_cut(IOBuf* source, InputMessage* out, Socket* sock,
                  uint8_t want_magic, bool probing) {
  uint8_t head[kHeader];
  const size_t got = source->copy_to(head, sizeof(head), 0);
  if (got < 1) {
    return ParseError::kNotEnoughData;
  }
  if (head[0] != want_magic) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  if (got < kHeader) {
    return ParseError::kNotEnoughData;
  }
  const uint16_t key_len = read_u16(head + 2);
  const uint8_t extras_len = head[4];
  const uint32_t total = read_u32(head + 8);
  if (total > kMaxBody ||
      static_cast<uint32_t>(key_len) + extras_len > total) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  if (source->size() < kHeader + total) {
    return ParseError::kNotEnoughData;
  }
  auto f = std::make_shared<McFrameCtx>();
  f->op = static_cast<McOp>(head[1]);
  f->status_or_vbucket = read_u16(head + 6);
  f->opaque = read_u32(head + 12);
  f->cas = read_u64(head + 16);
  source->pop_front(kHeader);
  IOBuf ex, key;
  source->cutn(&ex, extras_len);
  source->cutn(&key, key_len);
  f->extras = ex.to_string();
  f->key = key.to_string();
  source->cutn(&f->value, total - extras_len - key_len);
  out->ctx = std::move(f);
  out->socket = sock != nullptr ? sock->id() : 0;
  return ParseError::kOk;
}

ParseError mc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || srv->memcache_service() == nullptr) {
      return ParseError::kTryOtherProtocol;
    }
  }
  return mc_cut(source, out, sock, kMagicRequest, probing);
}

// Runs INLINE in the read fiber (process_in_order): memcached answers on
// one connection strictly in arrival order.
void mc_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto f = std::static_pointer_cast<McFrameCtx>(msg.ctx);
  if (srv == nullptr || srv->memcache_service() == nullptr ||
      f == nullptr) {
    return;
  }

  McCommand cmd;
  cmd.op = f->op;
  cmd.key = std::move(f->key);
  cmd.value = f->value.to_string();  // the service API stores strings
  cmd.cas = f->cas;
  cmd.vbucket = f->status_or_vbucket;  // request header: vbucket id
  const uint8_t* ex = reinterpret_cast<const uint8_t*>(f->extras.data());
  switch (f->op) {
    case McOp::kSet:
    case McOp::kAdd:
    case McOp::kReplace:
      if (f->extras.size() != 8) {
        sock->SetFailed(EPROTO);
        return;
      }
      cmd.flags = read_u32(ex);
      cmd.exptime = read_u32(ex + 4);
      break;
    case McOp::kIncrement:
    case McOp::kDecrement:
      if (f->extras.size() != 20) {
        sock->SetFailed(EPROTO);
        return;
      }
      cmd.delta = read_u64(ex);
      cmd.initial = read_u64(ex + 8);
      cmd.exptime = read_u32(ex + 16);
      break;
    case McOp::kTouch:
    case McOp::kFlush:
      if (f->extras.size() == 4) {
        cmd.exptime = read_u32(ex);
      }
      break;
    default:
      break;
  }
  if (cmd.key.size() > kMaxKey) {
    std::string wire;
    mc_pack_response(f->op, McStatus::kRemoteError, f->opaque, 0, "", "",
                     "key too long", &wire);
    IOBuf out;
    out.append(wire);
    sock->Write(std::move(out));
    return;
  }

  {  // Interceptor gate (same body as every serving protocol).
    int ec = 0;
    std::string et;
    if (!srv->accept_request("memcache", sock->remote(), &ec, &et)) {
      std::string wire;
      mc_pack_response(f->op, McStatus::kRemoteError, f->opaque, 0, "",
                       "", et, &wire);
      IOBuf out;
      out.append(wire);
      sock->Write(std::move(out));
      return;
    }
  }

  McResult r = srv->memcache_service()->Execute(cmd);
  srv->requests_served.fetch_add(1, std::memory_order_relaxed);

  std::string extras, value;
  if (f->op == McOp::kGet && r.ok()) {
    put_u32(&extras, r.flags);
    value = std::move(r.value);
  } else if ((f->op == McOp::kIncrement || f->op == McOp::kDecrement) &&
             r.ok()) {
    put_u64(&value, r.numeric);
  } else if (f->op == McOp::kVersion || !r.ok()) {
    value = std::move(r.value);
  }
  std::string wire;
  mc_pack_response(f->op, r.status, f->opaque, r.cas, extras, "", value,
                   &wire);
  IOBuf out;
  out.append(wire);
  sock->Write(std::move(out));
}

void mc_process_response(InputMessage&&) {}

}  // namespace

void register_memcache_protocol() {
  static int once = [] {
    Protocol p = {"memcache", mc_parse, mc_process_request,
                  mc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- client --------------------------------------------------------------

namespace {

struct McWaiter {
  CountdownEvent ev{1};
  uint32_t opaque = 0;
  McResult result;
};

struct McCliConn {
  std::mutex mu;  // wire order == queue order (responses are FIFO)
  std::deque<std::shared_ptr<McWaiter>> pending;
};

const char kMcCliTag = 0;

McCliConn* mcli_conn_of(Socket* s) {
  return proto_conn_of<McCliConn>(s, &kMcCliTag);
}

int install_mc_conn(Socket* s) {
  mcli_conn_of(s);  // install state while single-threaded
  return 0;
}

ParseError mcc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;  // client sockets are pre-pinned
  }
  ParseError rc =
      mc_cut(source, out, sock, kMagicResponse, /*probing=*/false);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

void mcc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto f = std::static_pointer_cast<McFrameCtx>(msg.ctx);
  if (f == nullptr) {
    return;
  }
  McCliConn* c = mcli_conn_of(sock.get());
  std::shared_ptr<McWaiter> w;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->pending.empty()) {
      return;  // unsolicited
    }
    w = std::move(c->pending.front());
    c->pending.pop_front();
  }
  McResult& r = w->result;
  if (f->opaque != w->opaque) {
    r.status = McStatus::kRemoteError;
    r.value = "opaque mismatch";
  } else {
    r.status = static_cast<McStatus>(f->status_or_vbucket);
    r.cas = f->cas;
    if (f->op == McOp::kGet && r.ok()) {
      if (f->extras.size() >= 4) {
        r.flags = read_u32(
            reinterpret_cast<const uint8_t*>(f->extras.data()));
      }
      r.value = f->value.to_string();
    } else if ((f->op == McOp::kIncrement || f->op == McOp::kDecrement) &&
               r.ok() && f->value.size() == 8) {
      uint8_t nbuf[8];
      f->value.copy_to(nbuf, 8, 0);
      r.numeric = read_u64(nbuf);
    } else {
      r.value = f->value.to_string();
    }
  }
  w->ev.signal();
}

void mcc_process_request(InputMessage&&) {}

int mcc_protocol_index() {
  static const int index = [] {
    Protocol p = {"memcachec", mcc_parse, mcc_process_request,
                  mcc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

McResult client_error(std::string text) {
  McResult r;
  r.status = McStatus::kRemoteError;
  r.value = std::move(text);
  return r;
}

}  // namespace

MemcacheClient::~MemcacheClient() {
  csock_.Shutdown();
}

int MemcacheClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  mcc_protocol_index();
  return csock_.Init(addr);
}

std::vector<McResult> MemcacheClient::batch(
    const std::vector<McCommand>& cmds) {
  std::vector<McResult> results(cmds.size());
  SocketId sid = 0;
  std::string wire;
  std::vector<std::shared_ptr<McWaiter>> waiters;
  waiters.reserve(cmds.size());
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(mcc_protocol_index(), install_mc_conn, &sid) != 0) {
      std::fill(results.begin(), results.end(),
                client_error("cannot reach " +
                             endpoint2str(csock_.endpoint())));
      return results;
    }
    for (const McCommand& cmd : cmds) {
      auto w = std::make_shared<McWaiter>();
      w->opaque = next_opaque_++;
      mc_pack_request(cmd, w->opaque, &wire);
      waiters.push_back(std::move(w));
    }
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    std::fill(results.begin(), results.end(),
              client_error("connection failed"));
    return results;
  }
  McCliConn* c = mcli_conn_of(s.get());
  {
    // Queue order must equal wire order: both under one lock.
    std::lock_guard<std::mutex> g(c->mu);
    for (auto& w : waiters) {
      c->pending.push_back(w);
    }
    IOBuf frame;
    frame.append(wire);
    if (s->Write(std::move(frame)) != 0) {
      for (auto& r : results) {
        r = client_error("write failed");
      }
      return results;
    }
  }
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  for (size_t i = 0; i < waiters.size(); ++i) {
    if (waiters[i]->ev.wait(deadline) == 0) {
      results[i] = std::move(waiters[i]->result);
    } else {
      results[i] = client_error("timeout");
    }
  }
  return results;
}

McResult MemcacheClient::one(const McCommand& cmd) {
  std::vector<McResult> r = batch({cmd});
  return r.empty() ? client_error("empty batch") : std::move(r[0]);
}

McResult MemcacheClient::Get(const std::string& key) {
  McCommand c;
  c.op = McOp::kGet;
  c.key = key;
  return one(c);
}

McResult MemcacheClient::Set(const std::string& key,
                             const std::string& value, uint32_t flags,
                             uint32_t exptime, uint64_t cas) {
  McCommand c;
  c.op = McOp::kSet;
  c.key = key;
  c.value = value;
  c.flags = flags;
  c.exptime = exptime;
  c.cas = cas;
  return one(c);
}

McResult MemcacheClient::Add(const std::string& key,
                             const std::string& value, uint32_t flags,
                             uint32_t exptime) {
  McCommand c;
  c.op = McOp::kAdd;
  c.key = key;
  c.value = value;
  c.flags = flags;
  c.exptime = exptime;
  return one(c);
}

McResult MemcacheClient::Replace(const std::string& key,
                                 const std::string& value, uint32_t flags,
                                 uint32_t exptime) {
  McCommand c;
  c.op = McOp::kReplace;
  c.key = key;
  c.value = value;
  c.flags = flags;
  c.exptime = exptime;
  return one(c);
}

McResult MemcacheClient::Append(const std::string& key,
                                const std::string& value) {
  McCommand c;
  c.op = McOp::kAppend;
  c.key = key;
  c.value = value;
  return one(c);
}

McResult MemcacheClient::Prepend(const std::string& key,
                                 const std::string& value) {
  McCommand c;
  c.op = McOp::kPrepend;
  c.key = key;
  c.value = value;
  return one(c);
}

McResult MemcacheClient::Delete(const std::string& key) {
  McCommand c;
  c.op = McOp::kDelete;
  c.key = key;
  return one(c);
}

McResult MemcacheClient::Increment(const std::string& key, uint64_t delta,
                                   uint64_t initial, uint32_t exptime) {
  McCommand c;
  c.op = McOp::kIncrement;
  c.key = key;
  c.delta = delta;
  c.initial = initial;
  c.exptime = exptime;
  return one(c);
}

McResult MemcacheClient::Decrement(const std::string& key, uint64_t delta,
                                   uint64_t initial, uint32_t exptime) {
  McCommand c;
  c.op = McOp::kDecrement;
  c.key = key;
  c.delta = delta;
  c.initial = initial;
  c.exptime = exptime;
  return one(c);
}

McResult MemcacheClient::Touch(const std::string& key, uint32_t exptime) {
  McCommand c;
  c.op = McOp::kTouch;
  c.key = key;
  c.exptime = exptime;
  return one(c);
}

McResult MemcacheClient::Version() {
  McCommand c;
  c.op = McOp::kVersion;
  return one(c);
}

McResult MemcacheClient::Flush() {
  McCommand c;
  c.op = McOp::kFlush;
  return one(c);
}

}  // namespace trpc
