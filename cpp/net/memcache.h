// Memcache binary protocol — client AND a serving adaptor.
//
// Parity: the reference's memcache client (/root/reference/src/brpc/
// memcache.h MemcacheRequest/Response batching get/set/incr ops;
// policy/memcache_binary_protocol.cpp packs the 24-byte binary headers
// and cuts responses by total_body).  Condensed tpu-native form: one
// McCommand/McResult value pair instead of batched pb-like messages, a
// typed client whose batch() pipelines N commands on one connection
// (responses arrive in order; opaque ids double-check alignment), and —
// beyond the reference, which has no memcache server — a MemcacheService
// so loopback tests and cache-speaking servers need no external
// memcached (the reference's own tests fake one in-process).
//
// Wire facts (public memcache binary spec):
//   request : 0x80 opcode key_len_be16 extras_len dtype vbucket_be16
//             total_body_be32 opaque cas_be64, then extras+key+value
//   response: 0x81 opcode key_len_be16 extras_len dtype status_be16
//             total_body_be32 opaque cas_be64, then extras+key+value
//   SET/ADD/REPLACE extras = flags_be32 exptime_be32; GET rsp extras =
//   flags_be32; INCR/DECR extras = delta_be64 initial_be64 exptime_be32,
//   numeric response value = be64.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/proto_client.h"
#include "net/socket.h"

namespace trpc {

class Server;

enum class McOp : uint8_t {
  kGet = 0x00,
  kSet = 0x01,
  kAdd = 0x02,
  kReplace = 0x03,
  kDelete = 0x04,
  kIncrement = 0x05,
  kDecrement = 0x06,
  kFlush = 0x08,
  kNoop = 0x0a,
  kVersion = 0x0b,
  kAppend = 0x0e,
  kPrepend = 0x0f,
  kTouch = 0x1c,
};

enum class McStatus : uint16_t {
  kOk = 0x0000,
  kNotFound = 0x0001,
  kExists = 0x0002,          // CAS mismatch
  kNotStored = 0x0005,       // ADD on present / REPLACE on absent
  kDeltaBadValue = 0x0006,
  kNotMyVbucket = 0x0007,    // couchbase: routed to a non-owning node
  kUnknownCommand = 0x0081,
  kRemoteError = 0x0084,     // client-side transport failures map here
};

// One command (client -> server).
struct McCommand {
  McOp op = McOp::kGet;
  std::string key;
  std::string value;
  uint32_t flags = 0;
  uint32_t exptime = 0;
  uint64_t cas = 0;        // 0 = unconditional
  uint64_t delta = 1;      // incr/decr
  uint64_t initial = 0;    // incr/decr when key absent
  uint16_t vbucket = 0;    // couchbase routing (plain memcache: 0)
};

// One result (server -> client).
struct McResult {
  McStatus status = McStatus::kOk;
  std::string value;       // GET payload / error text / VERSION string
  uint32_t flags = 0;
  uint64_t cas = 0;
  uint64_t numeric = 0;    // incr/decr result

  bool ok() const { return status == McStatus::kOk; }
};

// ---- codec (exposed for tests) -------------------------------------------

// Packs one request frame (opaque correlates the response).
void mc_pack_request(const McCommand& cmd, uint32_t opaque,
                     std::string* out);
// Packs one response frame.
void mc_pack_response(McOp op, McStatus status, uint32_t opaque,
                      uint64_t cas, const std::string& extras,
                      const std::string& key, const std::string& value,
                      std::string* out);
// Parses one complete frame at (*pos) of either magic.  Outputs are
// only touched on success.  1 ok / 0 partial / -1 malformed.
struct McFrame {
  uint8_t magic = 0;
  McOp op = McOp::kGet;
  uint16_t status_or_vbucket = 0;
  uint32_t opaque = 0;
  uint64_t cas = 0;
  std::string extras, key, value;
};
int mc_parse_frame(const std::string& data, size_t* pos, McFrame* out);

// ---- server side ---------------------------------------------------------

// In-memory cache implementing the binary ops; assign via
// Server::set_memcache_service.  Entries carry flags + cas; exptime is
// honored with second granularity.  Thread-safe.
class MemcacheService {
 public:
  McResult Execute(const McCommand& cmd);
  // Live item count; also sweeps expired entries (expiry is otherwise
  // reclaimed lazily when an op touches the key).
  size_t item_count();

  // Couchbase-style ownership gate: when set, keyed ops whose vbucket
  // the filter rejects answer kNotMyVbucket instead of executing
  // (reference: policy/couchbase_protocol.* routes by the header's
  // vbucket field; a real cluster node enforces exactly this).
  void set_vbucket_filter(std::function<bool(uint16_t)> f) {
    LockGuard<FiberMutex> g(mu_);  // rebalance can race live requests
    vbucket_filter_ = std::move(f);
  }

 private:
  struct Item {
    std::string value;
    uint32_t flags = 0;
    uint64_t cas = 1;
    int64_t expire_at_us = 0;  // 0 = never
  };
  bool expired_locked(const Item& it) const;
  mutable FiberMutex mu_;
  std::map<std::string, Item> items_;
  uint64_t next_cas_ = 1;
  std::function<bool(uint16_t)> vbucket_filter_;
};

// Registers the memcache server protocol (idempotent); Server::Start
// calls it when a memcache_service is installed.
void register_memcache_protocol();

// ---- client side ---------------------------------------------------------

// Binary-protocol memcache client over one connection with pipelining
// (parity: memcache.h batched MemcacheRequest + pipelined_count).
class MemcacheClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
  };

  ~MemcacheClient();
  int Init(const std::string& addr, const Options* opts = nullptr);

  McResult Get(const std::string& key);
  McResult Set(const std::string& key, const std::string& value,
               uint32_t flags = 0, uint32_t exptime = 0, uint64_t cas = 0);
  McResult Add(const std::string& key, const std::string& value,
               uint32_t flags = 0, uint32_t exptime = 0);
  McResult Replace(const std::string& key, const std::string& value,
                   uint32_t flags = 0, uint32_t exptime = 0);
  McResult Append(const std::string& key, const std::string& value);
  McResult Prepend(const std::string& key, const std::string& value);
  McResult Delete(const std::string& key);
  McResult Increment(const std::string& key, uint64_t delta,
                     uint64_t initial = 0, uint32_t exptime = 0);
  McResult Decrement(const std::string& key, uint64_t delta,
                     uint64_t initial = 0, uint32_t exptime = 0);
  McResult Touch(const std::string& key, uint32_t exptime);
  McResult Version();
  McResult Flush();

  // Pipelines all commands in one write; results come back in order.
  std::vector<McResult> batch(const std::vector<McCommand>& cmds);

 private:
  McResult one(const McCommand& cmd);

  Options opts_;
  FiberMutex sock_mu_;
  ClientSocket csock_;
  uint32_t next_opaque_ = 1;
};

}  // namespace trpc
