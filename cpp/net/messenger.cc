#include "net/messenger.h"

#include <errno.h>

#include <algorithm>

#include "base/flags.h"
#include "base/logging.h"
#include "base/tls_cache.h"
#include "fiber/analysis.h"
#include "fiber/fiber.h"
#include "net/hotpath_stats.h"
#include "net/protocol.h"
#include "net/qos.h"
#include "net/stream.h"
#include "net/rma.h"
#include "net/stripe.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

constexpr size_t kReadChunk = 512 * 1024;
// Ceiling on one readv when the parser hinted a large frame remainder:
// big enough to amortize per-syscall cost, small enough that the cut
// budget below still interleaves other sockets' work.
constexpr size_t kMaxBulkRead = 8 * 1024 * 1024;

// Per-readable-sweep cut budget: after this many bytes are read+parsed
// in one sweep, the read fiber YIELDS its worker (re-armed, back of the
// run queue) so one 64MB socket cannot head-of-line-block the dispatch
// fibers of small RPCs queued behind it on the same worker.
Flag* cut_budget_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_messenger_cut_budget", 8ll << 20,
        "bytes one readable sweep may read+parse before yielding its "
        "worker to queued fibers ([0, 1GB]; 0 = never yield)");
    if (flag != nullptr) {
      // Range validator + introspectable bounds (the tuner's AIMD rule
      // actuates this knob and clamps into the declared range).
      flag->set_int_range(0, 1ll << 30);
    }
    return flag;
  }();
  return f;
}

// Eager definition (settable before the first readable sweep).
[[maybe_unused]] Flag* const g_cut_budget_flag_eager = cut_budget_flag();

thread_local bool tls_inline_dispatch = false;

// TLS InputMessage freelist: one is allocated per parsed message — at
// 100k+ qps the malloc/free pair plus the meta's string/vector churn is
// measurable (r5 profile).  Same pattern as the WriteNode cache:
// cross-thread imbalance degrades to plain malloc.
struct InputMessageCacheTag {};

void drain_input_message(void*& m) { delete static_cast<InputMessage*>(m); }

std::vector<void*>* tls_msg_cache() {
  return TlsFreeCache<void*, InputMessageCacheTag>::get(
      &drain_input_message);
}

constexpr size_t kMaxCachedMessages = 64;

InputMessage* alloc_input_message() {
  std::vector<void*>* cache = tls_msg_cache();
  if (cache != nullptr && !cache->empty()) {
    auto* m = static_cast<InputMessage*>(cache->back());
    cache->pop_back();
    return m;
  }
  return new InputMessage();
}

void free_input_message(InputMessage* m) {
  std::vector<void*>* cache = tls_msg_cache();
  if (cache != nullptr && cache->size() < kMaxCachedMessages) {
    // Release payload refs and per-call state NOW; meta keeps its
    // string/vector capacity for reuse.
    m->payload.clear();
    m->ctx.reset();
    m->meta.reset();
    m->socket = 0;
    m->arrival_us = 0;
    cache->push_back(m);
    return;
  }
  delete m;
}

// Shared by the inline (first-of-batch) and fiber dispatch paths.
void process_parsed_message(InputMessage* msg) {
  const Protocol* p = protocol_at(0);  // resolved below via pinned index
  Socket* s = Socket::Address(msg->socket);
  if (s != nullptr) {
    p = protocol_at(s->pinned_protocol);
    s->Dereference();
  }
  if (p != nullptr) {
    // kResponse is the only client-bound type; kAuth etc. are served.
    if (msg->meta.type == RpcMeta::kResponse) {
      p->process_response(std::move(*msg));
    } else {
      p->process_request(std::move(*msg));
    }
  }
  free_input_message(msg);
}

void process_message_fiber(void* arg) {
  process_parsed_message(static_cast<InputMessage*>(arg));
}

// Upper bound on messages batched per dispatch round (also the bulk-
// enqueue fan-out cap; the reference flushes unconditionally at the end
// of each read sweep, input_messenger.cpp:307-309).
constexpr size_t kDispatchBatch = 64;

// Batch of concurrent-protocol messages cut in one sweep.  Flushing
// bulk-enqueues fiber-bound messages through the scheduler's
// single-signal path FIRST, then — when the first message is a client
// RESPONSE — runs it INLINE on this dispatch fiber: the common
// single-response event (sync small RPC) completes with zero fiber
// spawns and zero ParkingLot signals.  Requests are NEVER run inline:
// a handler is arbitrary user code and may park for seconds, and an
// inline handler would serialize every later message on this connection
// behind it (a response completion only wakes the waiting call — bounded
// framework work).
struct DispatchBatch {
  InputMessage* msgs[kDispatchBatch];
  size_t n = 0;

  void flush() {
    if (n == 0) {
      return;
    }
    HotPathVars& hv = hotpath_vars();
    hv.dispatch_batches << 1;
    hv.dispatch_msgs << static_cast<int64_t>(n);
    hv.dispatch_max << static_cast<int64_t>(n);
    if (hotpath_sample16()) {
      hv.dispatch_batch << static_cast<int64_t>(n);
    }
    InputMessage* inline_msg = nullptr;
    size_t spawn_from = 0;
    if (msgs[0]->meta.type == RpcMeta::kResponse) {
      inline_msg = msgs[0];
      spawn_from = 1;
      hv.dispatch_inline << 1;
    }
    if (n > spawn_from) {
      void* args[kDispatchBatch];
      for (size_t i = spawn_from; i < n; ++i) {
        args[i - spawn_from] = msgs[i];
      }
      const size_t started = fiber_start_batch(process_message_fiber, args,
                                               n - spawn_from, 0);
      // Pool exhaustion: never drop a parsed message — run stragglers
      // inline (slow, but the pool being empty means the process is
      // drowning in fibers anyway).  Inline-window flag stays set so
      // user done() callbacks still divert off this dispatch fiber.
      if (started < n - spawn_from) {
        tls_inline_dispatch = true;
        analysis::ScopedDispatch scope("messenger exhaustion-inline window");
        for (size_t i = spawn_from + started; i < n; ++i) {
          process_parsed_message(msgs[i]);
        }
        tls_inline_dispatch = false;
      }
    }
    n = 0;
    if (inline_msg != nullptr) {
      // Mark the inline window: completion paths divert user callbacks
      // (async done) to their own fiber so arbitrary user code never
      // parks this connection's dispatch fiber.  The analysis scope
      // (ISSUE 7) turns any park that slips through into a reported
      // no-pinned-read-fiber violation.
      const SocketId sid = inline_msg->socket;
      if (timeline::enabled()) {
        timeline::record(timeline::kInlineBegin, sid, 0);
      }
      tls_inline_dispatch = true;
      {
        analysis::ScopedDispatch scope("messenger inline-response window");
        process_parsed_message(inline_msg);
      }
      tls_inline_dispatch = false;
      if (timeline::enabled()) {
        timeline::record(timeline::kInlineEnd, sid, 0);
      }
    }
  }
};

// Cut as many whole messages as available per readable sweep; batch
// concurrent-protocol messages and dispatch them in bulk (first inline,
// rest via one bulk fiber wakeup).  Order-sensitive frames (streams,
// auth, in-order protocols) flush the batch first and run inline, so
// per-connection processing order is exactly the pre-batching order.
// Returns the number of whole messages cut (the flight recorder's
// sweep_end cut count).
size_t cut_and_dispatch(Socket* s, SocketId id) {
  IOBuf& buf = s->read_buf();
  DispatchBatch batch;
  size_t cuts = 0;
  // QoS lane routing (net/qos.h): hoisted flag read — one atomic load
  // per sweep, zero when disabled (the default).
  const int qos_lanes = qos_lane_count();
  while (!buf.empty()) {
    InputMessage* msg = alloc_input_message();
    msg->socket = id;
    ParseError rc = ParseError::kTryOtherProtocol;
    if (s->pinned_protocol >= 0) {
      rc = protocol_at(s->pinned_protocol)->parse(&buf, msg, s);
    } else if (buf.size() <= s->probe_stall_len) {
      // Probe memo: every protocol already saw this prefix length and
      // asked for more bytes — skip the whole sweep until they arrive.
      hotpath_vars().probe_stall_skips << 1;
      rc = ParseError::kNotEnoughData;
    } else {
      // Pin ONLY on a successful parse: with a partial prefix several
      // protocols may legitimately say "need more data", and pinning early
      // would misroute the connection once the real format shows.
      hotpath_vars().probe_rounds << 1;
      for (int i = 0; i < protocol_count(); ++i) {
        rc = protocol_at(i)->parse(&buf, msg, s);
        if (rc == ParseError::kOk) {
          s->pinned_protocol = i;
          s->probe_stall_len = 0;
          break;
        }
        if (rc == ParseError::kNotEnoughData ||
            rc == ParseError::kCorrupted) {
          break;
        }
      }
      if (rc == ParseError::kNotEnoughData) {
        s->probe_stall_len = buf.size();
      }
    }
    switch (rc) {
      case ParseError::kOk: {
        ++cuts;
        if (msg->meta.type == RpcMeta::kStreamFrame) {
          // Stream frames keep per-connection arrival order: handled inline
          // (the per-stream ExecutionQueue serializes the user callback).
          batch.flush();
          stream_on_frame(std::move(*msg));
          free_input_message(msg);
          continue;
        }
        if (msg->meta.type == RpcMeta::kStripe) {
          // Stripe chunks are offset-addressed and order-free: consume
          // them here (the landing memcpy fans out to worker fibers) —
          // no batch flush, no dispatch fiber.
          stripe_on_chunk(std::move(*msg));
          free_input_message(msg);
          continue;
        }
        if (msg->meta.stripe_id != 0 &&
            (msg->meta.type == RpcMeta::kRequest ||
             msg->meta.type == RpcMeta::kResponse)) {
          // Striped HEAD: only chunk 0 rode this frame; the message
          // dispatches from the reassembly layer once every chunk lands.
          stripe_on_head(std::move(*msg));
          free_input_message(msg);
          continue;
        }
        if (msg->meta.rma_rkey != 0 &&
            (msg->meta.type == RpcMeta::kRequest ||
             msg->meta.type == RpcMeta::kResponse)) {
          // One-sided control frame (net/rma.h): the payload landed
          // out-of-band in a registered region.  Resolve swaps it in
          // (verifying the release-fenced completion bitmap) and the
          // message then dispatches like any other; a failed resolve
          // drops it whole — the call times out, never partial bytes.
          if (!rma_resolve(msg, s)) {
            free_input_message(msg);
            continue;
          }
        }
        const Protocol* p = protocol_at(s->pinned_protocol);
        if (p != nullptr && msg->meta.type == RpcMeta::kAuth) {
          // Credential frames verify INLINE in the read fiber: requests
          // cut after this frame must observe auth_ok (the reference's
          // first-message verify fight, input_messenger.cpp:271-289 —
          // spawning a fiber here would let a request race the verify).
          batch.flush();
          p->process_request(std::move(*msg));
          free_input_message(msg);
          continue;
        }
        if (p != nullptr && p->process_in_order) {
          // FIFO protocols (no correlation id): run inline, keeping this
          // connection's response order.
          // kResponse is the only client-bound type; everything else
          // (requests, kAuth credentials) belongs to the serving path.
          batch.flush();
          if (msg->meta.type == RpcMeta::kResponse) {
            p->process_response(std::move(*msg));
          } else {
            p->process_request(std::move(*msg));
          }
          free_input_message(msg);
        } else if (qos_lanes > 0 && msg->meta.type == RpcMeta::kRequest) {
          // Priority lanes: server-bound requests route through the QoS
          // weighted-fair dequeue instead of direct batch dispatch, so a
          // high-priority small RPC dispatches ahead of queued bulk work
          // even when both arrived in the same sweep (or on different
          // sockets whose sweeps interleave on one worker).  Responses
          // never queue here — a parked caller is itself the backpressure.
          qos_enqueue(qos_lane_for(msg->meta.qos_priority, qos_lanes),
                      msg->meta.qos_tenant, msg, &process_message_fiber);
        } else {
          batch.msgs[batch.n++] = msg;
          if (batch.n == kDispatchBatch) {
            batch.flush();
          }
        }
        continue;
      }
      case ParseError::kNotEnoughData:
        free_input_message(msg);
        batch.flush();
        return cuts;
      default:
        LOG(Warning) << "corrupted input on " << endpoint2str(s->remote())
                     << " (pinned=" << s->pinned_protocol << " proto="
                     << (s->pinned_protocol >= 0 &&
                                 protocol_at(s->pinned_protocol) != nullptr
                             ? protocol_at(s->pinned_protocol)->name
                             : "?")
                     << "), closing";
        free_input_message(msg);
        // Messages cut intact BEFORE the corruption still get delivered.
        batch.flush();
        s->SetFailed(EBADMSG);
        return cuts;
    }
  }
  batch.flush();
  return cuts;
}

}  // namespace

bool messenger_in_inline_dispatch() { return tls_inline_dispatch; }

void messenger_on_readable(SocketId id, void* /*ctx*/) {
  Socket* s = Socket::Address(id);
  if (s == nullptr) {
    return;
  }
  const int64_t budget = cut_budget_flag()->int64_value();
  int64_t swept = 0;
  size_t cuts_total = 0;
  const bool tl = timeline::enabled();  // hoisted: one load per sweep
  if (tl) {
    timeline::record(timeline::kSweepStart, id, 0);
  }
  while (!s->Failed()) {
    // Bulk hint: a parser that knows the current frame's remainder lets
    // this sweep read it in few large-block readvs instead of 512KB
    // slivers of 8KB blocks.
    size_t want = kReadChunk;
    if (s->read_block_hint > want) {
      want = std::min(s->read_block_hint, kMaxBulkRead);
    }
    const ssize_t rc =
        s->transport()->append_to_iobuf(s, &s->read_buf(), want);
    if (rc > 0) {
      cuts_total += cut_and_dispatch(s, id);
      swept += rc;
      if (budget > 0 && swept >= budget) {
        // Cut budget spent: hand the worker to whatever queued behind
        // this sweep (small-RPC dispatch fibers), then resume.  The
        // socket's bytes wait in the kernel/read_buf; nothing re-arms
        // because this fiber IS still the armed reader.
        hotpath_vars().cut_budget_yields << 1;
        swept = 0;
        fiber_yield();
      }
      continue;
    }
    if (rc == 0) {
      break;  // EAGAIN: drained
    }
    // EOF or error.  A not-yet-connected client socket gets spurious
    // HUP/ERR edges from epoll registration racing the non-blocking
    // connect — the connect path owns failure reporting there.
    if (!s->connected()) {
      break;
    }
    s->SetFailed(errno != 0 ? errno : ECONNRESET);
    break;
  }
  if (tl) {
    timeline::record(timeline::kSweepEnd, id, cuts_total);
  }
  s->Dereference();
}

}  // namespace trpc
