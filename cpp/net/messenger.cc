#include "net/messenger.h"

#include <errno.h>

#include "base/logging.h"
#include "base/tls_cache.h"
#include "fiber/fiber.h"
#include "net/protocol.h"
#include "net/stream.h"

namespace trpc {

namespace {

constexpr size_t kReadChunk = 512 * 1024;

// TLS InputMessage freelist: one is allocated per parsed message — at
// 100k+ qps the malloc/free pair plus the meta's string/vector churn is
// measurable (r5 profile).  Same pattern as the WriteNode cache:
// cross-thread imbalance degrades to plain malloc.
struct InputMessageCacheTag {};

void drain_input_message(void*& m) { delete static_cast<InputMessage*>(m); }

std::vector<void*>* tls_msg_cache() {
  return TlsFreeCache<void*, InputMessageCacheTag>::get(
      &drain_input_message);
}

constexpr size_t kMaxCachedMessages = 64;

InputMessage* alloc_input_message() {
  std::vector<void*>* cache = tls_msg_cache();
  if (cache != nullptr && !cache->empty()) {
    auto* m = static_cast<InputMessage*>(cache->back());
    cache->pop_back();
    return m;
  }
  return new InputMessage();
}

void free_input_message(InputMessage* m) {
  std::vector<void*>* cache = tls_msg_cache();
  if (cache != nullptr && cache->size() < kMaxCachedMessages) {
    // Release payload refs and per-call state NOW; meta keeps its
    // string/vector capacity for reuse.
    m->payload.clear();
    m->ctx.reset();
    m->meta.reset();
    m->socket = 0;
    cache->push_back(m);
    return;
  }
  delete m;
}

void process_message_fiber(void* arg) {
  InputMessage* msg = static_cast<InputMessage*>(arg);
  const Protocol* p = protocol_at(0);  // resolved below via pinned index
  Socket* s = Socket::Address(msg->socket);
  if (s != nullptr) {
    p = protocol_at(s->pinned_protocol);
    s->Dereference();
  }
  if (p != nullptr) {
    // kResponse is the only client-bound type; kAuth etc. are served.
    if (msg->meta.type == RpcMeta::kResponse) {
      p->process_response(std::move(*msg));
    } else {
      p->process_request(std::move(*msg));
    }
  }
  free_input_message(msg);
}

// Cut as many whole messages as available; dispatch each in its own fiber
// (the last one inline, like input_messenger.cpp:307-309's batch flush).
void cut_and_dispatch(Socket* s, SocketId id) {
  IOBuf& buf = s->read_buf();
  while (!buf.empty()) {
    InputMessage* msg = alloc_input_message();
    msg->socket = id;
    ParseError rc = ParseError::kTryOtherProtocol;
    if (s->pinned_protocol >= 0) {
      rc = protocol_at(s->pinned_protocol)->parse(&buf, msg, s);
    } else {
      // Pin ONLY on a successful parse: with a partial prefix several
      // protocols may legitimately say "need more data", and pinning early
      // would misroute the connection once the real format shows.
      for (int i = 0; i < protocol_count(); ++i) {
        rc = protocol_at(i)->parse(&buf, msg, s);
        if (rc == ParseError::kOk) {
          s->pinned_protocol = i;
          break;
        }
        if (rc == ParseError::kNotEnoughData ||
            rc == ParseError::kCorrupted) {
          break;
        }
      }
    }
    switch (rc) {
      case ParseError::kOk: {
        if (msg->meta.type == RpcMeta::kStreamFrame) {
          // Stream frames keep per-connection arrival order: handled inline
          // (the per-stream ExecutionQueue serializes the user callback).
          stream_on_frame(std::move(*msg));
          free_input_message(msg);
          continue;
        }
        const Protocol* p = protocol_at(s->pinned_protocol);
        if (p != nullptr && msg->meta.type == RpcMeta::kAuth) {
          // Credential frames verify INLINE in the read fiber: requests
          // cut after this frame must observe auth_ok (the reference's
          // first-message verify fight, input_messenger.cpp:271-289 —
          // spawning a fiber here would let a request race the verify).
          p->process_request(std::move(*msg));
          free_input_message(msg);
          continue;
        }
        if (p != nullptr && p->process_in_order) {
          // FIFO protocols (no correlation id): run inline, keeping this
          // connection's response order.
          // kResponse is the only client-bound type; everything else
          // (requests, kAuth credentials) belongs to the serving path.
          if (msg->meta.type == RpcMeta::kResponse) {
            p->process_response(std::move(*msg));
          } else {
            p->process_request(std::move(*msg));
          }
          free_input_message(msg);
        } else {
          fiber_start(nullptr, process_message_fiber, msg, 0);
        }
        continue;
      }
      case ParseError::kNotEnoughData:
        free_input_message(msg);
        return;
      default:
        LOG(Warning) << "corrupted input on " << endpoint2str(s->remote())
                     << " (pinned=" << s->pinned_protocol << " proto="
                     << (s->pinned_protocol >= 0 &&
                                 protocol_at(s->pinned_protocol) != nullptr
                             ? protocol_at(s->pinned_protocol)->name
                             : "?")
                     << "), closing";
        free_input_message(msg);
        s->SetFailed(EBADMSG);
        return;
    }
  }
}

}  // namespace

void messenger_on_readable(SocketId id, void* /*ctx*/) {
  Socket* s = Socket::Address(id);
  if (s == nullptr) {
    return;
  }
  while (!s->Failed()) {
    const ssize_t rc =
        s->transport()->append_to_iobuf(s, &s->read_buf(), kReadChunk);
    if (rc > 0) {
      cut_and_dispatch(s, id);
      continue;
    }
    if (rc == 0) {
      break;  // EAGAIN: drained
    }
    // EOF or error.  A not-yet-connected client socket gets spurious
    // HUP/ERR edges from epoll registration racing the non-blocking
    // connect — the connect path owns failure reporting there.
    if (!s->connected()) {
      break;
    }
    s->SetFailed(errno != 0 ? errno : ECONNRESET);
    break;
  }
  s->Dereference();
}

}  // namespace trpc
