// InputMessenger — cuts messages off the read buffer and dispatches.
//
// Parity: brpc InputMessenger (/root/reference/src/brpc/input_messenger.cpp:
// 83 CutInputMessage protocol multiplexing with per-socket pinning, :195
// ProcessNewMessage batching).  Runs inside the socket's read fiber.
#pragma once

#include "net/socket.h"

namespace trpc {

// Socket::Options::on_readable for any RPC connection (server or client).
void messenger_on_readable(SocketId id, void* ctx);

}  // namespace trpc
