// InputMessenger — cuts messages off the read buffer and dispatches.
//
// Parity: brpc InputMessenger (/root/reference/src/brpc/input_messenger.cpp:
// 83 CutInputMessage protocol multiplexing with per-socket pinning, :195
// ProcessNewMessage batching).  Runs inside the socket's read fiber.
#pragma once

#include "net/socket.h"

namespace trpc {

// Socket::Options::on_readable for any RPC connection (server or client).
void messenger_on_readable(SocketId id, void* ctx);

// True while the calling fiber is processing a first-of-batch message
// INLINE on a connection's dispatch fiber (the batched-dispatch fast
// path).  Completion paths use this to push arbitrary user callbacks
// (async done closures) into their own fiber instead of parking the read
// fiber — everything behind it on the connection would stall.
bool messenger_in_inline_dispatch();

}  // namespace trpc
