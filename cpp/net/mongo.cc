#include "net/mongo.h"

#include <errno.h>

#include <algorithm>
#include <cstring>
#include <deque>
#include <mutex>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/messenger.h"
#include "net/protocol.h"
#include "net/server.h"

namespace trpc {

namespace {

constexpr int32_t kOpMsg = 2013;
constexpr size_t kMaxMessage = 48 << 20;  // mongod's wire cap
constexpr size_t kMaxElements = 1 << 20;
constexpr int kMaxDepth = 32;
constexpr uint32_t kChecksumPresent = 1;
constexpr uint32_t kMoreToCome = 2;

void put_i32(std::string* out, int32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);  // LE on x86
}

void put_i64(std::string* out, int64_t v) {
  out->append(reinterpret_cast<const char*>(&v), 8);
}

bool get_i32(const std::string& in, size_t* pos, int32_t* v) {
  if (in.size() - *pos < 4) return false;
  std::memcpy(v, in.data() + *pos, 4);
  *pos += 4;
  return true;
}

bool get_i64(const std::string& in, size_t* pos, int64_t* v) {
  if (in.size() - *pos < 8) return false;
  std::memcpy(v, in.data() + *pos, 8);
  *pos += 8;
  return true;
}

bool get_cstring(const std::string& in, size_t* pos, std::string* out) {
  const size_t nul = in.find('\0', *pos);
  if (nul == std::string::npos) return false;
  out->assign(in, *pos, nul - *pos);
  *pos = nul + 1;
  return true;
}

}  // namespace

// ---- BSON builders -------------------------------------------------------

BsonValue BsonValue::Double(double v) {
  BsonValue b;
  b.type = kDouble;
  b.d = v;
  return b;
}
BsonValue BsonValue::Str(std::string v) {
  BsonValue b;
  b.type = kString;
  b.str = std::move(v);
  return b;
}
BsonValue BsonValue::Document(BsonDoc v) {
  BsonValue b;
  b.type = kDoc;
  b.doc = std::make_shared<BsonDoc>(std::move(v));
  return b;
}
BsonValue BsonValue::Array(std::vector<BsonValue> v) {
  BsonValue b;
  b.type = kArray;
  b.doc = std::make_shared<BsonDoc>();
  for (size_t i = 0; i < v.size(); ++i) {
    b.doc->emplace_back(std::to_string(i), std::move(v[i]));
  }
  return b;
}
BsonValue BsonValue::Binary(std::string v, uint8_t subtype) {
  BsonValue b;
  b.type = kBinary;
  b.str = std::move(v);
  b.subtype = subtype;
  return b;
}
BsonValue BsonValue::ObjectId(const std::string& bytes12) {
  BsonValue b;
  b.type = kObjectId;
  b.str = bytes12.substr(0, 12);
  b.str.resize(12, '\0');
  return b;
}
BsonValue BsonValue::Bool(bool v) {
  BsonValue b;
  b.type = kBool;
  b.b = v;
  return b;
}
BsonValue BsonValue::DateTime(int64_t ms) {
  BsonValue b;
  b.type = kDateTime;
  b.i = ms;
  return b;
}
BsonValue BsonValue::Null() { return BsonValue(); }
BsonValue BsonValue::Int32(int32_t v) {
  BsonValue b;
  b.type = kInt32;
  b.i = v;
  return b;
}
BsonValue BsonValue::Int64(int64_t v) {
  BsonValue b;
  b.type = kInt64;
  b.i = v;
  return b;
}

bool BsonValue::operator==(const BsonValue& o) const {
  if (type != o.type) return false;
  switch (type) {
    case kDouble:
      return d == o.d;
    case kString:
      return str == o.str;
    case kDoc:
    case kArray:
      return (doc == nullptr) == (o.doc == nullptr) &&
             (doc == nullptr || *doc == *o.doc);
    case kBinary:
      return subtype == o.subtype && str == o.str;
    case kObjectId:
      return str == o.str;
    case kBool:
      return b == o.b;
    case kDateTime:
    case kInt64:
    case kInt32:
      return i == o.i;
    case kNull:
      return true;
  }
  return false;
}

const BsonValue* bson_find(const BsonDoc& doc, const std::string& key) {
  for (const auto& [k, v] : doc) {
    if (k == key) return &v;
  }
  return nullptr;
}

// ---- BSON codec ----------------------------------------------------------

namespace {

void write_value(const BsonValue& v, std::string* out);

void write_doc_body(const BsonDoc& doc, std::string* out) {
  const size_t len_at = out->size();
  put_i32(out, 0);  // patched below
  for (const auto& [name, v] : doc) {
    out->push_back(static_cast<char>(v.type));
    out->append(name);
    out->push_back('\0');
    write_value(v, out);
  }
  out->push_back('\0');
  const int32_t total = static_cast<int32_t>(out->size() - len_at);
  std::memcpy(out->data() + len_at, &total, 4);
}

void write_value(const BsonValue& v, std::string* out) {
  switch (v.type) {
    case BsonValue::kDouble: {
      int64_t bits;
      std::memcpy(&bits, &v.d, 8);
      put_i64(out, bits);
      break;
    }
    case BsonValue::kString:
      put_i32(out, static_cast<int32_t>(v.str.size()) + 1);
      out->append(v.str);
      out->push_back('\0');
      break;
    case BsonValue::kDoc:
    case BsonValue::kArray:
      write_doc_body(v.doc != nullptr ? *v.doc : BsonDoc{}, out);
      break;
    case BsonValue::kBinary:
      put_i32(out, static_cast<int32_t>(v.str.size()));
      out->push_back(static_cast<char>(v.subtype));
      out->append(v.str);
      break;
    case BsonValue::kObjectId:
      out->append(v.str.data(), 12);
      break;
    case BsonValue::kBool:
      out->push_back(v.b ? 1 : 0);
      break;
    case BsonValue::kDateTime:
    case BsonValue::kInt64:
      put_i64(out, v.i);
      break;
    case BsonValue::kNull:
      break;
    case BsonValue::kInt32:
      put_i32(out, static_cast<int32_t>(v.i));
      break;
  }
}

int read_value(const std::string& in, size_t* pos, uint8_t type,
               BsonValue* out, int depth);

int read_doc_body(const std::string& in, size_t* pos, BsonDoc* out,
                  int depth) {
  if (depth > kMaxDepth) return -1;
  const size_t start = *pos;
  int32_t total;
  if (!get_i32(in, pos, &total)) return 0;
  if (total < 5 || static_cast<size_t>(total) > kMaxMessage) return -1;
  if (in.size() - start < static_cast<size_t>(total)) return 0;
  const size_t end = start + total;
  out->clear();
  while (*pos < end - 1) {
    if (out->size() > kMaxElements) return -1;
    const uint8_t type = static_cast<uint8_t>(in[*pos]);
    ++*pos;
    std::string name;
    if (!get_cstring(in, pos, &name) || *pos > end) return -1;
    BsonValue v;
    const int rc = read_value(in, pos, type, &v, depth + 1);
    if (rc != 1 || *pos > end) return rc == 0 ? -1 : rc;  // bounded by total
    out->emplace_back(std::move(name), std::move(v));
  }
  if (*pos != end - 1 || in[*pos] != '\0') return -1;
  ++*pos;
  return 1;
}

int read_value(const std::string& in, size_t* pos, uint8_t type,
               BsonValue* out, int depth) {
  switch (type) {
    case BsonValue::kDouble: {
      int64_t bits;
      if (!get_i64(in, pos, &bits)) return -1;
      out->type = BsonValue::kDouble;
      std::memcpy(&out->d, &bits, 8);
      return 1;
    }
    case BsonValue::kString: {
      int32_t len;
      if (!get_i32(in, pos, &len) || len < 1 ||
          in.size() - *pos < static_cast<size_t>(len)) {
        return -1;
      }
      out->type = BsonValue::kString;
      out->str.assign(in, *pos, len - 1);
      if (in[*pos + len - 1] != '\0') return -1;
      *pos += len;
      return 1;
    }
    case BsonValue::kDoc:
    case BsonValue::kArray: {
      out->type = static_cast<BsonValue::Type>(type);
      out->doc = std::make_shared<BsonDoc>();
      return read_doc_body(in, pos, out->doc.get(), depth);
    }
    case BsonValue::kBinary: {
      int32_t len;
      if (!get_i32(in, pos, &len) || len < 0 ||
          in.size() - *pos < static_cast<size_t>(len) + 1) {
        return -1;
      }
      out->type = BsonValue::kBinary;
      out->subtype = static_cast<uint8_t>(in[*pos]);
      ++*pos;
      out->str.assign(in, *pos, len);
      *pos += len;
      return 1;
    }
    case BsonValue::kObjectId: {
      if (in.size() - *pos < 12) return -1;
      out->type = BsonValue::kObjectId;
      out->str.assign(in, *pos, 12);
      *pos += 12;
      return 1;
    }
    case BsonValue::kBool: {
      if (*pos >= in.size()) return -1;
      out->type = BsonValue::kBool;
      out->b = in[*pos] != 0;
      ++*pos;
      return 1;
    }
    case BsonValue::kDateTime:
    case BsonValue::kInt64: {
      if (!get_i64(in, pos, &out->i)) return -1;
      out->type = static_cast<BsonValue::Type>(type);
      return 1;
    }
    case BsonValue::kNull:
      out->type = BsonValue::kNull;
      return 1;
    case BsonValue::kInt32: {
      int32_t v;
      if (!get_i32(in, pos, &v)) return -1;
      out->type = BsonValue::kInt32;
      out->i = v;
      return 1;
    }
    default:
      return -1;  // decimal128 / regex / code: not in the condensed set
  }
}

}  // namespace

void bson_write_doc(const BsonDoc& doc, std::string* out) {
  write_doc_body(doc, out);
}

int bson_read_doc(const std::string& in, size_t* pos, BsonDoc* out,
                  int depth) {
  return read_doc_body(in, pos, out, depth);
}

// ---- message framing -----------------------------------------------------

namespace {

struct MongoFrame {
  int32_t request_id = 0;
  int32_t response_to = 0;
  uint32_t flags = 0;
  BsonDoc body;
};

void mongo_pack(int32_t request_id, int32_t response_to,
                const BsonDoc& body, std::string* out) {
  const size_t start = out->size();
  put_i32(out, 0);  // length, patched
  put_i32(out, request_id);
  put_i32(out, response_to);
  put_i32(out, kOpMsg);
  put_i32(out, 0);  // flagBits
  out->push_back(0);  // section kind 0
  bson_write_doc(body, out);
  const int32_t total = static_cast<int32_t>(out->size() - start);
  std::memcpy(out->data() + start, &total, 4);
}

// Cuts one OP_MSG off `source`.  The opcode at offset 12 is the probe
// discriminator.
ParseError mongo_cut(IOBuf* source, InputMessage* out, Socket* sock,
                     bool probing) {
  uint8_t head[16];
  const size_t got = source->copy_to(head, sizeof(head), 0);
  if (got < sizeof(head)) {
    // Short prefix: hold unless the length bytes already rule us out
    // (mongo messages are < 48MB, so byte 3 must be 0x00..0x03).
    if (probing && got >= 4 && head[3] > 0x03) {
      return ParseError::kTryOtherProtocol;
    }
    return ParseError::kNotEnoughData;
  }
  int32_t len, opcode;
  std::memcpy(&len, head, 4);
  std::memcpy(&opcode, head + 12, 4);
  if (opcode != kOpMsg || len < 16 ||
      static_cast<size_t>(len) > kMaxMessage) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  if (source->size() < static_cast<size_t>(len)) {
    return ParseError::kNotEnoughData;
  }
  std::string raw;
  raw.resize(len);
  source->copy_to(raw.data(), len, 0);
  source->pop_front(len);

  auto frame = std::make_shared<MongoFrame>();
  size_t pos = 4;
  int32_t rid, rto, op;
  get_i32(raw, &pos, &rid);
  get_i32(raw, &pos, &rto);
  get_i32(raw, &pos, &op);
  frame->request_id = rid;
  frame->response_to = rto;
  int32_t flags;
  if (!get_i32(raw, &pos, &flags)) {
    return ParseError::kCorrupted;
  }
  frame->flags = static_cast<uint32_t>(flags);
  if (frame->flags & kChecksumPresent) {
    return ParseError::kCorrupted;  // crc32c sections not negotiated
  }
  if (pos >= raw.size() || raw[pos] != 0) {
    return ParseError::kCorrupted;  // only kind-0 body sections
  }
  ++pos;
  if (bson_read_doc(raw, &pos, &frame->body, 0) != 1) {
    return ParseError::kCorrupted;
  }
  out->ctx = std::move(frame);
  out->socket = sock != nullptr ? sock->id() : 0;
  return ParseError::kOk;
}

}  // namespace

// ---- server --------------------------------------------------------------

bool MongoService::AddCommandHandler(const std::string& name,
                                     CommandHandler h) {
  std::string lower = name;
  std::transform(lower.begin(), lower.end(), lower.begin(), ::tolower);
  return handlers_.emplace(std::move(lower), std::move(h)).second;
}

const MongoService::CommandHandler* MongoService::FindCommandHandler(
    const std::string& lower) const {
  auto it = handlers_.find(lower);
  return it == handlers_.end() ? nullptr : &it->second;
}

BsonDoc MongoService::ok_reply() {
  return {{"ok", BsonValue::Double(1)}};
}

namespace {

ParseError mongo_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || srv->mongo_service() == nullptr) {
      return ParseError::kTryOtherProtocol;
    }
  }
  return mongo_cut(source, out, sock, probing);
}

BsonDoc builtin_command(const std::string& cmd, Server* srv) {
  if (cmd == "ping") {
    return MongoService::ok_reply();
  }
  if (cmd == "hello" || cmd == "ismaster") {
    BsonDoc d;
    d.emplace_back("isWritablePrimary", BsonValue::Bool(true));
    d.emplace_back("maxBsonObjectSize", BsonValue::Int32(16 << 20));
    d.emplace_back("maxMessageSizeBytes", BsonValue::Int32(48 << 20));
    d.emplace_back("maxWireVersion", BsonValue::Int32(17));
    d.emplace_back("minWireVersion", BsonValue::Int32(0));
    d.emplace_back("ok", BsonValue::Double(1));
    return d;
  }
  if (cmd == "buildinfo") {
    BsonDoc d;
    d.emplace_back("version", BsonValue::Str("7.0.0-trpc"));
    d.emplace_back("ok", BsonValue::Double(1));
    return d;
  }
  (void)srv;
  return {};
}

void mongo_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto frame = std::static_pointer_cast<MongoFrame>(msg.ctx);
  if (srv == nullptr || srv->mongo_service() == nullptr ||
      frame == nullptr || frame->body.empty()) {
    return;
  }
  std::string cmd = frame->body.front().first;
  std::transform(cmd.begin(), cmd.end(), cmd.begin(), ::tolower);

  BsonDoc reply;
  {  // Interceptor gate.
    int ec = 0;
    std::string et;
    if (cmd != "ping" && cmd != "hello" && cmd != "ismaster" &&
        !srv->accept_request(cmd, sock->remote(), &ec, &et)) {
      reply.emplace_back("ok", BsonValue::Double(0));
      reply.emplace_back("errmsg", BsonValue::Str(et));
      reply.emplace_back("code", BsonValue::Int32(13));  // Unauthorized
    }
  }
  if (reply.empty()) {
    const MongoService::CommandHandler* h =
        srv->mongo_service()->FindCommandHandler(cmd);
    if (h != nullptr) {
      reply = (*h)(frame->body);
      srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    } else {
      reply = builtin_command(cmd, srv);
      if (reply.empty()) {
        reply.emplace_back("ok", BsonValue::Double(0));
        reply.emplace_back(
            "errmsg", BsonValue::Str("no such command: '" + cmd + "'"));
        reply.emplace_back("code", BsonValue::Int32(59));
      }
    }
  }
  if (frame->flags & kMoreToCome) {
    return;  // fire-and-forget (unacknowledged writes)
  }
  std::string wire;
  static std::atomic<int32_t> reply_id{1000};
  mongo_pack(reply_id.fetch_add(1), frame->request_id, reply, &wire);
  IOBuf out;
  out.append(wire);
  sock->Write(std::move(out));
}

void mongo_process_response(InputMessage&&) {}

}  // namespace

void register_mongo_protocol() {
  static int once = [] {
    Protocol p = {"mongo", mongo_parse, mongo_process_request,
                  mongo_process_response,
                  /*process_in_order=*/false};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- client --------------------------------------------------------------

namespace {

struct MongoWaiter {
  CountdownEvent ev{1};
  bool ok = false;
  BsonDoc reply;
};

struct MongoCliConn {
  std::mutex mu;
  std::map<int32_t, std::shared_ptr<MongoWaiter>> pending;  // by requestID
};

const char kMongoCliTag = 0;

MongoCliConn* mcli_conn_of(Socket* s) {
  return proto_conn_of<MongoCliConn>(s, &kMongoCliTag);
}

int install_mongo_conn(Socket* s) {
  mcli_conn_of(s);
  return 0;
}

ParseError mongoc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;
  }
  ParseError rc = mongo_cut(source, out, sock, /*probing=*/false);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

void mongoc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto frame = std::static_pointer_cast<MongoFrame>(msg.ctx);
  MongoCliConn* c = mcli_conn_of(sock.get());
  std::shared_ptr<MongoWaiter> w;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->pending.find(frame->response_to);
    if (it == c->pending.end()) {
      return;
    }
    w = std::move(it->second);
    c->pending.erase(it);
  }
  w->ok = true;
  w->reply = std::move(frame->body);
  w->ev.signal();
}

void mongoc_process_request(InputMessage&&) {}

int mongoc_protocol_index() {
  static const int index = [] {
    Protocol p = {"mongoc", mongoc_parse, mongoc_process_request,
                  mongoc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

}  // namespace

MongoClient::~MongoClient() {
  csock_.Shutdown();
}

int MongoClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  mongoc_protocol_index();
  return csock_.Init(addr);
}

MongoClient::Result MongoClient::run_command(const BsonDoc& cmd) {
  Result fail;
  SocketId sid = 0;
  int32_t rid = 0;
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(mongoc_protocol_index(), install_mongo_conn,
                      &sid) != 0) {
      fail.errmsg = "cannot reach " + endpoint2str(csock_.endpoint());
      return fail;
    }
    rid = static_cast<int32_t>(next_request_++);
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    fail.errmsg = "connection failed";
    return fail;
  }
  MongoCliConn* c = mcli_conn_of(s.get());
  auto w = std::make_shared<MongoWaiter>();
  {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.emplace(rid, w);
  }
  std::string wire;
  mongo_pack(rid, 0, cmd, &wire);
  IOBuf out;
  out.append(wire);
  if (s->Write(std::move(out)) != 0) {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.erase(rid);
    fail.errmsg = "write failed";
    return fail;
  }
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  if (w->ev.wait(deadline) != 0) {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.erase(rid);
    fail.errmsg = "timeout";
    return fail;
  }
  Result r;
  r.ok = true;
  r.reply = std::move(w->reply);
  return r;
}

}  // namespace trpc
