// Mongo wire protocol (OP_MSG) server adaptor + client, with a BSON codec.
//
// Parity: the reference's server-side mongo adaptor
// (/root/reference/src/brpc/policy/mongo_protocol.cpp + mongo_head.h:
// standard 16-byte message header, pb-described sections) lets a brpc
// server answer mongo drivers.  Condensed tpu-native form: a hand-rolled
// BSON value tree (no libbson), the modern OP_MSG framing (opcode 2013,
// kind-0 body section), a MongoService mapping command names (the FIRST
// element's key, per the mongo command convention) to handlers, and a
// client correlating replies by responseTo for tests/tools.
//
// Wire facts (public BSON + mongo wire spec):
//   header  : i32 messageLength, i32 requestID, i32 responseTo, i32 opCode
//   OP_MSG  : u32 flagBits, sections*, [u32 crc when bit 0 set — rejected]
//   section : u8 kind (0 = one BSON doc; 1 = doc sequence, unsupported)
//   BSON doc: i32 total, {u8 type, cstring name, value}*, 0x00
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/proto_client.h"
#include "net/socket.h"

namespace trpc {

class Server;

// ---- BSON ----------------------------------------------------------------

struct BsonValue;

// A document is an ordered element list (mongo cares about the order of
// the first key — it names the command).
using BsonDoc = std::vector<std::pair<std::string, BsonValue>>;

struct BsonValue {
  enum Type : uint8_t {
    kDouble = 0x01,
    kString = 0x02,
    kDoc = 0x03,
    kArray = 0x04,
    kBinary = 0x05,
    kObjectId = 0x07,
    kBool = 0x08,
    kDateTime = 0x09,  // int64 ms since epoch
    kNull = 0x0a,
    kInt32 = 0x10,
    kInt64 = 0x12,
  };
  Type type = kNull;
  double d = 0;
  int64_t i = 0;
  bool b = false;
  std::string str;             // string / objectid(12B) / binary payload
  uint8_t subtype = 0;         // binary subtype
  std::shared_ptr<BsonDoc> doc;  // kDoc / kArray (array keys "0","1",...)

  static BsonValue Double(double v);
  static BsonValue Str(std::string v);
  static BsonValue Document(BsonDoc v);
  static BsonValue Array(std::vector<BsonValue> v);
  static BsonValue Binary(std::string v, uint8_t subtype = 0);
  static BsonValue ObjectId(const std::string& bytes12);
  static BsonValue Bool(bool v);
  static BsonValue DateTime(int64_t ms);
  static BsonValue Null();
  static BsonValue Int32(int32_t v);
  static BsonValue Int64(int64_t v);

  bool operator==(const BsonValue& o) const;
};

// Finds the first element named `key` (nullptr when absent).
const BsonValue* bson_find(const BsonDoc& doc, const std::string& key);

// Serializes a document (including its i32 length and terminator).
void bson_write_doc(const BsonDoc& doc, std::string* out);
// Parses one document at (*pos); 1 ok / 0 partial / -1 malformed.
// Depth- and size-bounded.
int bson_read_doc(const std::string& in, size_t* pos, BsonDoc* out,
                  int depth = 0);

// ---- server side ---------------------------------------------------------

// Command handlers keyed by command name (first element key, matched
// case-insensitively like mongod).  The handler returns the reply
// document; add "ok": 1.0 yourself (or use ok_reply()).  Unhandled
// commands get {ok: 0, errmsg, code: 59 CommandNotFound}, except the
// handshake commands (hello / isMaster / ping / buildInfo) which have
// builtin defaults so stock drivers can connect.
class MongoService {
 public:
  using CommandHandler = std::function<BsonDoc(const BsonDoc& request)>;

  bool AddCommandHandler(const std::string& name, CommandHandler h);
  const CommandHandler* FindCommandHandler(const std::string& lower) const;

  static BsonDoc ok_reply();

 private:
  std::map<std::string, CommandHandler> handlers_;
};

void register_mongo_protocol();

// ---- client side ---------------------------------------------------------

class MongoClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
  };

  ~MongoClient();
  int Init(const std::string& addr, const Options* opts = nullptr);

  // Runs one command (OP_MSG roundtrip).  ok=false with errmsg filled on
  // transport errors; command-level failures come back in the doc
  // ("ok": 0) like a real driver.
  struct Result {
    bool ok = false;
    std::string errmsg;
    BsonDoc reply;
  };
  Result run_command(const BsonDoc& cmd);

 private:
  Options opts_;
  FiberMutex sock_mu_;
  ClientSocket csock_;
  uint32_t next_request_ = 1;
};

}  // namespace trpc
