#include "net/mpegts.h"

#include <cstring>

namespace trpc {

namespace {

constexpr size_t kTsPacket = 188;
constexpr uint8_t kSync = 0x47;

void put_pts(std::string* out, uint64_t pts) {
  // 33 bits over 5 bytes: 0010 | pts[32:30] | 1 | pts[29:15] | 1 |
  // pts[14:0] | 1.
  out->push_back(static_cast<char>(0x20 | ((pts >> 29) & 0x0e) | 1));
  out->push_back(static_cast<char>(pts >> 22));
  out->push_back(static_cast<char>(((pts >> 14) & 0xfe) | 1));
  out->push_back(static_cast<char>(pts >> 7));
  out->push_back(static_cast<char>(((pts << 1) & 0xfe) | 1));
}

bool get_pts(const uint8_t* p, uint64_t* pts) {
  if ((p[0] & 0x01) == 0 || (p[2] & 0x01) == 0 || (p[4] & 0x01) == 0) {
    return false;  // marker bits
  }
  *pts = (static_cast<uint64_t>(p[0] & 0x0e) << 29) |
         (static_cast<uint64_t>(p[1]) << 22) |
         (static_cast<uint64_t>(p[2] & 0xfe) << 14) |
         (static_cast<uint64_t>(p[3]) << 7) | (p[4] >> 1);
  return true;
}

// Builds a PSI section (pointer_field + table through CRC).
std::string psi_section(uint8_t table_id, uint16_t table_id_ext,
                        const std::string& body) {
  std::string sec;
  sec.push_back(static_cast<char>(table_id));
  const size_t len = 5 + body.size() + 4;  // after length field, incl CRC
  sec.push_back(static_cast<char>(0xb0 | ((len >> 8) & 0x0f)));
  sec.push_back(static_cast<char>(len));
  sec.push_back(static_cast<char>(table_id_ext >> 8));
  sec.push_back(static_cast<char>(table_id_ext));
  sec.push_back(static_cast<char>(0xc1));  // version 0, current
  sec.push_back(0);                        // section_number
  sec.push_back(0);                        // last_section_number
  sec.append(body);
  const uint32_t crc = mpeg_crc32(
      reinterpret_cast<const uint8_t*>(sec.data()), sec.size());
  for (int i = 3; i >= 0; --i) {
    sec.push_back(static_cast<char>(crc >> (8 * i)));
  }
  return std::string(1, '\0') + sec;  // pointer_field = 0
}

}  // namespace

uint32_t mpeg_crc32(const uint8_t* data, size_t n) {
  uint32_t crc = 0xffffffffu;
  for (size_t i = 0; i < n; ++i) {
    crc ^= static_cast<uint32_t>(data[i]) << 24;
    for (int b = 0; b < 8; ++b) {
      crc = (crc & 0x80000000u) ? (crc << 1) ^ 0x04c11db7u : crc << 1;
    }
  }
  return crc;
}

void TsMuxer::WritePacket(uint16_t pid, bool pusi, const uint8_t* payload,
                          size_t n, size_t* consumed, std::string* out,
                          const uint64_t* pcr) {
  uint8_t* cc = pid == kVideoPid ? &cc_[0]
                : pid == kAudioPid ? &cc_[1]
                : pid == kPmtPid ? &cc_pmt_ : &cc_pat_;
  std::string pkt;
  pkt.push_back(static_cast<char>(kSync));
  pkt.push_back(static_cast<char>((pusi ? 0x40 : 0) | ((pid >> 8) & 0x1f)));
  pkt.push_back(static_cast<char>(pid));
  const size_t room = kTsPacket - 4;

  // Adaptation-field content (after its length byte): PCR, then any
  // stuffing needed to land the payload tail exactly on 188 bytes.
  std::string af;
  if (pcr != nullptr) {
    af.push_back(0x10);  // PCR_flag
    // 33-bit base | 6 reserved (all ones) | 9-bit extension (0).
    const uint64_t base = *pcr & ((1ull << 33) - 1);
    const uint64_t v = (base << 15) | (0x3full << 9);
    for (int i = 5; i >= 0; --i) {
      af.push_back(static_cast<char>(v >> (8 * i)));
    }
  }
  bool has_af = !af.empty();
  size_t space = room - (has_af ? 1 + af.size() : 0);
  if (n < space) {
    size_t deficit = space - n;
    if (!has_af) {
      has_af = true;
      --deficit;  // the length byte itself absorbs one
      if (deficit > 0) {
        af.push_back(0);  // flags
        af.append(deficit - 1, '\xff');
      }
    } else {
      af.append(deficit, '\xff');
    }
    space = n;
  }
  pkt.push_back(
      static_cast<char>((has_af ? 0x30 : 0x10) | (*cc & 0x0f)));
  if (has_af) {
    pkt.push_back(static_cast<char>(af.size()));
    pkt.append(af);
  }
  pkt.append(reinterpret_cast<const char*>(payload), space);
  *consumed = space;
  *cc = (*cc + 1) & 0x0f;
  out->append(pkt);
}

void TsMuxer::WriteTables(std::string* out) {
  // PAT: program 1 → PMT PID.
  std::string pat_body;
  pat_body.push_back(0);
  pat_body.push_back(1);  // program_number 1
  pat_body.push_back(static_cast<char>(0xe0 | ((kPmtPid >> 8) & 0x1f)));
  pat_body.push_back(static_cast<char>(kPmtPid));
  const std::string pat = psi_section(0x00, /*tsid=*/1, pat_body);
  size_t consumed = 0;
  WritePacket(0x0000, /*pusi=*/true,
              reinterpret_cast<const uint8_t*>(pat.data()), pat.size(),
              &consumed, out);
  // PMT: PCR on video; H.264 (0x1b) + AAC ADTS (0x0f).
  std::string pmt_body;
  pmt_body.push_back(static_cast<char>(0xe0 | ((kVideoPid >> 8) & 0x1f)));
  pmt_body.push_back(static_cast<char>(kVideoPid));  // PCR PID
  pmt_body.push_back(static_cast<char>(0xf0));
  pmt_body.push_back(0);  // program_info_length 0
  const struct {
    uint8_t type;
    uint16_t pid;
  } streams[] = {{0x1b, kVideoPid}, {0x0f, kAudioPid}};
  for (const auto& s : streams) {
    pmt_body.push_back(static_cast<char>(s.type));
    pmt_body.push_back(static_cast<char>(0xe0 | ((s.pid >> 8) & 0x1f)));
    pmt_body.push_back(static_cast<char>(s.pid));
    pmt_body.push_back(static_cast<char>(0xf0));
    pmt_body.push_back(0);  // ES_info_length 0
  }
  const std::string pmt = psi_section(0x02, /*program=*/1, pmt_body);
  WritePacket(kPmtPid, /*pusi=*/true,
              reinterpret_cast<const uint8_t*>(pmt.data()), pmt.size(),
              &consumed, out);
}

size_t TsMuxer::WriteFrame(bool video, uint64_t pts90k,
                           const std::string& data, std::string* out) {
  // PES header: 000001 | stream_id | length | '10' flags | PTS.
  std::string pes;
  pes.append("\x00\x00\x01", 3);
  pes.push_back(static_cast<char>(video ? 0xe0 : 0xc0));
  const size_t tail = 3 + 5 + data.size();  // flags(2)+hdrlen(1)+PTS+data
  // PES_packet_length: 0 is legal for video (unbounded); audio must fit.
  const bool unbounded = tail > 0xffff;
  pes.push_back(static_cast<char>(unbounded ? 0 : tail >> 8));
  pes.push_back(static_cast<char>(unbounded ? 0 : tail));
  pes.push_back(static_cast<char>(0x80));  // marker '10'
  pes.push_back(static_cast<char>(0x80));  // PTS only
  pes.push_back(5);                        // header data length
  put_pts(&pes, pts90k & ((1ull << 33) - 1));
  pes.append(data);

  const uint16_t pid = video ? kVideoPid : kAudioPid;
  size_t off = 0, packets = 0;
  bool first = true;
  while (off < pes.size()) {
    size_t consumed = 0;
    // PCR rides the first packet of every video frame (video is the
    // PMT-declared PCR PID).
    WritePacket(pid, first,
                reinterpret_cast<const uint8_t*>(pes.data()) + off,
                pes.size() - off, &consumed, out,
                first && video ? &pts90k : nullptr);
    off += consumed;
    first = false;
    ++packets;
  }
  return packets;
}

// ---- demux ---------------------------------------------------------------

namespace {

struct PesAssembly {
  std::string bytes;
  bool open = false;
};

// Parses one complete PES (header + payload) into a frame.
bool finish_pes(uint16_t pid, PesAssembly* as,
                std::vector<TsFrame>* frames) {
  if (!as->open) {
    return true;
  }
  as->open = false;
  std::string pes = std::move(as->bytes);
  as->bytes.clear();
  if (pes.size() < 9 || pes[0] != 0 || pes[1] != 0 || pes[2] != 1) {
    return false;
  }
  const uint8_t* p = reinterpret_cast<const uint8_t*>(pes.data());
  const size_t hdr_len = p[8];
  if (pes.size() < 9 + hdr_len) {
    return false;
  }
  TsFrame f;
  f.pid = pid;
  if ((p[7] & 0x80) != 0) {  // PTS present
    if (hdr_len < 5 || !get_pts(p + 9, &f.pts90k)) {
      return false;
    }
  }
  f.data = pes.substr(9 + hdr_len);
  // Bounded PES: trim any stuffing the length excludes.
  const size_t declared = (static_cast<size_t>(p[4]) << 8) | p[5];
  if (declared != 0) {
    const size_t payload_len = declared - 3 - hdr_len;
    if (payload_len > f.data.size()) {
      return false;
    }
    f.data.resize(payload_len);
  }
  frames->push_back(std::move(f));
  return true;
}

}  // namespace

bool ts_demux(const std::string& in, std::vector<TsFrame>* frames,
              std::map<uint16_t, uint8_t>* stream_types) {
  if (in.size() % kTsPacket != 0) {
    return false;
  }
  std::map<uint16_t, PesAssembly> pes;
  std::map<uint16_t, int> last_cc;
  uint16_t pmt_pid = 0xffff;
  for (size_t off = 0; off < in.size(); off += kTsPacket) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(in.data()) + off;
    if (p[0] != kSync) {
      return false;
    }
    const bool pusi = (p[1] & 0x40) != 0;
    const uint16_t pid = (static_cast<uint16_t>(p[1] & 0x1f) << 8) | p[2];
    const uint8_t afc = (p[3] >> 4) & 3;
    const uint8_t cc = p[3] & 0x0f;
    size_t pos = 4;
    if (afc == 0 || afc == 2 || pid == 0x1fff) {
      // ISO 13818-1: the counter does not increment on packets without
      // payload, and is undefined on null packets — neither checks.
      continue;
    }
    auto lc = last_cc.find(pid);
    if (lc != last_cc.end() && ((lc->second + 1) & 0x0f) != cc) {
      return false;  // continuity break
    }
    last_cc[pid] = cc;
    if (afc == 3) {
      const size_t af_len = p[4];
      pos = 5 + af_len;
      if (pos > kTsPacket) {
        return false;
      }
    }
    const uint8_t* payload = p + pos;
    const size_t n = kTsPacket - pos;
    if (pid == 0x0000 || pid == pmt_pid) {
      if (!pusi || n < 1) {
        continue;  // multi-packet PSI not produced by this muxer
      }
      const size_t ptr = payload[0];
      if (1 + ptr + 3 > n) {
        return false;
      }
      const uint8_t* sec = payload + 1 + ptr;
      const size_t sec_len =
          ((static_cast<size_t>(sec[1]) & 0x0f) << 8) | sec[2];
      if (3 + sec_len > n - 1 - ptr) {
        return false;
      }
      const size_t total = 3 + sec_len;
      const uint32_t crc = mpeg_crc32(sec, total - 4);
      const uint32_t want = (static_cast<uint32_t>(sec[total - 4]) << 24) |
                            (static_cast<uint32_t>(sec[total - 3]) << 16) |
                            (static_cast<uint32_t>(sec[total - 2]) << 8) |
                            sec[total - 1];
      if (crc != want) {
        return false;
      }
      if (sec[0] == 0x00 && total >= 12) {  // PAT
        pmt_pid = (static_cast<uint16_t>(sec[10] & 0x1f) << 8) | sec[11];
      } else if (sec[0] == 0x02 && stream_types != nullptr) {  // PMT
        size_t q = 12;  // past PCR pid + program_info_length (0)
        while (q + 5 <= total - 4) {
          const uint8_t type = sec[q];
          const uint16_t es_pid =
              (static_cast<uint16_t>(sec[q + 1] & 0x1f) << 8) | sec[q + 2];
          (*stream_types)[es_pid] = type;
          const size_t es_info =
              ((static_cast<size_t>(sec[q + 3]) & 0x0f) << 8) | sec[q + 4];
          q += 5 + es_info;
        }
      }
      continue;
    }
    PesAssembly& as = pes[pid];
    if (pusi) {
      if (!finish_pes(pid, &as, frames)) {
        return false;
      }
      as.open = true;
    }
    if (as.open) {
      as.bytes.append(reinterpret_cast<const char*>(payload), n);
      // A bounded PES (declared length != 0) completes the moment its
      // bytes are in — keeping frames in true arrival order instead of
      // parking finished audio until the next start indicator.
      if (as.bytes.size() >= 6) {
        const uint8_t* hp =
            reinterpret_cast<const uint8_t*>(as.bytes.data());
        const size_t declared =
            (static_cast<size_t>(hp[4]) << 8) | hp[5];
        if (declared != 0 && as.bytes.size() >= 6 + declared) {
          if (!finish_pes(pid, &as, frames)) {
            return false;
          }
        }
      }
    }
  }
  for (auto& [pid, as] : pes) {
    if (!finish_pes(pid, &as, frames)) {
      return false;
    }
  }
  return true;
}

}  // namespace trpc
