// MPEG-TS muxing/demuxing: pack media frames into 188-byte transport
// stream packets (PAT/PMT signalling, PES framing, PTS timestamps).
//
// Parity: the reference's ts.{h,cpp} (~1.7k LoC) muxes RTMP streams
// into TS for HLS-style consumers.  Condensed single-program form: one
// PAT (program 1 → PMT), one PMT (H.264 video PID 0x100 + AAC audio
// PID 0x101, PCR on video), PES with 33-bit PTS, adaptation-field
// stuffing, per-PID continuity counters.  The demuxer exists for tests
// and tooling: it reassembles PES payloads and checks PSI CRCs (MPEG
// CRC-32, the non-reflected 0x04C11DB7 variant).
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

namespace trpc {

// MPEG CRC-32 (poly 0x04C11DB7, init 0xFFFFFFFF, no reflection, no
// final xor) — the PSI section checksum.  Exposed for tests.
uint32_t mpeg_crc32(const uint8_t* data, size_t n);

class TsMuxer {
 public:
  static constexpr uint16_t kPmtPid = 0x1000;
  static constexpr uint16_t kVideoPid = 0x100;
  static constexpr uint16_t kAudioPid = 0x101;

  // Appends PAT + PMT (callers emit them at stream start and then
  // periodically, e.g. every keyframe, so joiners mid-stream sync).
  void WriteTables(std::string* out);

  // Appends one frame as PES split across TS packets.  `video` selects
  // PID/stream id; pts90k is the presentation time in 90kHz ticks
  // (33 bits used).  Returns the number of TS packets written.
  size_t WriteFrame(bool video, uint64_t pts90k, const std::string& data,
                    std::string* out);

 private:
  // `pcr` non-null emits a PCR (27MHz clock reference, base from the
  // 90kHz tick) in this packet's adaptation field — ISO 13818-1 wants
  // one on the declared PCR PID regularly; this muxer stamps every
  // video frame's first packet.
  void WritePacket(uint16_t pid, bool pusi, const uint8_t* payload,
                   size_t n, size_t* consumed, std::string* out,
                   const uint64_t* pcr = nullptr);
  // Continuity counters are per PID: video, audio, PAT, PMT.
  uint8_t cc_[2] = {0, 0};
  uint8_t cc_pat_ = 0;
  uint8_t cc_pmt_ = 0;
};

// Demuxed elementary frame.
struct TsFrame {
  uint16_t pid = 0;
  uint64_t pts90k = 0;
  std::string data;
};

// Parses a whole TS byte string: returns false on framing/CRC errors.
// Fills frames (complete PES payloads, in arrival order) and the
// PMT-announced pid→stream_type map.
bool ts_demux(const std::string& in, std::vector<TsFrame>* frames,
              std::map<uint16_t, uint8_t>* stream_types);

}  // namespace trpc
