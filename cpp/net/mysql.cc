#include "net/mysql.h"

#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>

#include "base/logging.h"
#include "base/sha1.h"
#include "base/time.h"
#include "fiber/fiber.h"

namespace trpc {

namespace {

constexpr size_t kMaxPacket = 64ull << 20;

// Capability flags (public protocol constants).
constexpr uint32_t kLongPassword = 0x1;
constexpr uint32_t kConnectWithDb = 0x8;
constexpr uint32_t kProtocol41 = 0x200;
constexpr uint32_t kTransactions = 0x2000;
constexpr uint32_t kSecureConnection = 0x8000;
constexpr uint32_t kPluginAuth = 0x80000;

constexpr uint8_t kComQuit = 0x01;
constexpr uint8_t kComInitDb = 0x02;
constexpr uint8_t kComQuery = 0x03;
constexpr uint8_t kComPing = 0x0e;
constexpr uint8_t kComStmtPrepare = 0x16;
constexpr uint8_t kComStmtExecute = 0x17;
constexpr uint8_t kComStmtClose = 0x19;

// Column type codes the binary-row decoder understands.
constexpr uint8_t kTypeLong = 0x03;
constexpr uint8_t kTypeLongLong = 0x08;
constexpr uint8_t kTypeVarString = 0xfd;

// ---- fd IO with fiber-parking waits --------------------------------------

int read_n(int fd, void* buf, size_t n, int64_t deadline_us) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    const ssize_t rc = ::read(fd, p, n);
    if (rc > 0) {
      p += rc;
      n -= rc;
      continue;
    }
    if (rc == 0) {
      return -1;  // peer closed
    }
    if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
      return -1;
    }
    if (errno != EINTR &&
        fiber_fd_wait(fd, EPOLLIN, deadline_us) < 0) {
      return -1;  // timeout
    }
  }
  return 0;
}

int write_all(int fd, const void* buf, size_t n, int64_t deadline_us) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    const ssize_t rc = ::send(fd, p, n, MSG_NOSIGNAL);
    if (rc > 0) {
      p += rc;
      n -= rc;
      continue;
    }
    if (rc < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
        errno != EINTR) {
      return -1;
    }
    if (errno != EINTR &&
        fiber_fd_wait(fd, EPOLLOUT, deadline_us) < 0) {
      return -1;
    }
  }
  return 0;
}

// ---- packet layer --------------------------------------------------------

// The 3-byte length field caps one wire packet at 0xffffff; larger
// payloads travel as a run of full chunks terminated by a short
// (possibly empty) one, with consecutive sequence numbers.  Both ends
// here speak that splitting, so payloads up to kMaxPacket are safe.
constexpr size_t kChunk = 0xffffff;

int read_packet(int fd, std::string* payload, uint8_t* seq,
                int64_t deadline_us) {
  payload->clear();
  while (true) {
    uint8_t head[4];
    if (read_n(fd, head, 4, deadline_us) != 0) {
      return -1;
    }
    const uint32_t len = head[0] | (head[1] << 8) | (head[2] << 16);
    *seq = head[3];
    if (payload->size() + len > kMaxPacket) {
      return -1;
    }
    const size_t old = payload->size();
    payload->resize(old + len);
    if (read_n(fd, payload->data() + old, len, deadline_us) != 0) {
      return -1;
    }
    if (len < kChunk) {
      return 0;
    }
  }
}

int write_packet(int fd, const std::string& payload, uint8_t seq,
                 int64_t deadline_us) {
  if (payload.size() > kMaxPacket) {
    return -1;
  }
  size_t off = 0;
  while (true) {
    const size_t n = std::min(kChunk, payload.size() - off);
    uint8_t head[4] = {static_cast<uint8_t>(n),
                       static_cast<uint8_t>(n >> 8),
                       static_cast<uint8_t>(n >> 16), seq++};
    if (write_all(fd, head, 4, deadline_us) != 0 ||
        write_all(fd, payload.data() + off, n, deadline_us) != 0) {
      return -1;
    }
    off += n;
    if (n < kChunk) {  // a short packet terminates the run
      return 0;
    }
  }
}

// ---- primitive readers ---------------------------------------------------

bool get_lenenc(const std::string& p, size_t* pos, uint64_t* out) {
  if (*pos >= p.size()) {
    return false;
  }
  const uint8_t first = static_cast<uint8_t>(p[*pos]);
  ++*pos;
  if (first < 0xfb) {
    *out = first;
    return true;
  }
  int n = first == 0xfc ? 2 : first == 0xfd ? 3 : first == 0xfe ? 8 : -1;
  if (n < 0 || p.size() - *pos < static_cast<size_t>(n)) {
    return false;
  }
  uint64_t v = 0;
  for (int i = 0; i < n; ++i) {
    v |= static_cast<uint64_t>(static_cast<uint8_t>(p[*pos + i]))
         << (8 * i);
  }
  *pos += n;
  *out = v;
  return true;
}

bool get_lenenc_str(const std::string& p, size_t* pos, std::string* out) {
  uint64_t len;
  if (!get_lenenc(p, pos, &len) || p.size() - *pos < len) {
    return false;
  }
  out->assign(p, *pos, len);
  *pos += len;
  return true;
}

bool get_nul_str(const std::string& p, size_t* pos, std::string* out) {
  const size_t nul = p.find('\0', *pos);
  if (nul == std::string::npos) {
    return false;
  }
  out->assign(p, *pos, nul - *pos);
  *pos = nul + 1;
  return true;
}

void put_u32le(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

bool is_eof_packet(const std::string& p) {
  return !p.empty() && static_cast<uint8_t>(p[0]) == 0xfe && p.size() < 9;
}

// Parses an ERR packet into the result.
void parse_err(const std::string& p, MysqlClient::Result* r) {
  r->ok = false;
  size_t pos = 1;
  if (p.size() >= 3) {
    r->error_code = static_cast<uint8_t>(p[1]) |
                    (static_cast<uint8_t>(p[2]) << 8);
    pos = 3;
  }
  if (pos < p.size() && p[pos] == '#') {
    pos += 6;  // '#' + 5-char sqlstate
  }
  if (pos <= p.size()) {
    r->error_text.assign(p, pos, p.size() - pos);
  }
}

// Parses an OK packet into the result.
bool parse_ok(const std::string& p, MysqlClient::Result* r) {
  size_t pos = 1;
  if (!get_lenenc(p, &pos, &r->affected_rows) ||
      !get_lenenc(p, &pos, &r->last_insert_id)) {
    return false;
  }
  r->ok = true;
  return true;
}

}  // namespace

// ---- scramble ------------------------------------------------------------

std::string MysqlClient::native_scramble(const std::string& password,
                                         const std::string& nonce20) {
  if (password.empty()) {
    return "";
  }
  const std::string h1 = sha1(password);
  const std::string h2 = sha1(h1);
  const std::string h3 = sha1(nonce20 + h2);
  std::string out(20, '\0');
  for (int i = 0; i < 20; ++i) {
    out[i] = h1[i] ^ h3[i];
  }
  return out;
}

// ---- connection ----------------------------------------------------------

MysqlClient::~MysqlClient() {
  if (fd_ >= 0) {
    std::string quit(1, static_cast<char>(kComQuit));
    write_packet(fd_, quit, 0, monotonic_time_us() + 100000);
    ::close(fd_);
  }
}

int MysqlClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  return hostname2endpoint(addr.c_str(), &ep_);
}

void MysqlClient::drop_connection() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
    ++session_gen_;  // invalidates prepared-statement handles
  }
}

int MysqlClient::ensure_connected() {
  if (fd_ >= 0) {
    return 0;
  }
  const int64_t deadline =
      monotonic_time_us() + opts_.timeout_ms * 1000;
  // "unix:/var/run/mysqld/mysqld.sock" is the canonical local address.
  const bool un = ep_.is_unix();
  int fd = ::socket(un ? AF_UNIX : AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) {
    return -1;
  }
  sockaddr_storage ss = {};
  socklen_t ss_len;
  if (un) {
    sockaddr_un sun = endpoint2sockaddr_un(ep_);
    memcpy(&ss, &sun, sizeof(sun));
    ss_len = sizeof(sun);
  } else {
    sockaddr_in sin = {};
    sin.sin_family = AF_INET;
    sin.sin_addr.s_addr = ep_.ip;  // already network byte order
    sin.sin_port = htons(static_cast<uint16_t>(ep_.port));
    memcpy(&ss, &sin, sizeof(sin));
    ss_len = sizeof(sin);
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&ss), ss_len) != 0 &&
      errno != EINPROGRESS) {
    ::close(fd);
    return -1;
  }
  if (fiber_fd_wait(fd, EPOLLOUT, deadline) < 0) {
    ::close(fd);
    return -1;
  }
  int soerr = 0;
  socklen_t slen = sizeof(soerr);
  if (::getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 ||
      soerr != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  // --- greeting (server speaks first) ---
  std::string pkt;
  uint8_t seq = 0;
  if (read_packet(fd, &pkt, &seq, deadline) != 0 || pkt.empty()) {
    ::close(fd);
    return -1;
  }
  if (static_cast<uint8_t>(pkt[0]) == 0xff) {
    ::close(fd);  // server rejected us before auth (too many conns, ...)
    return -1;
  }
  if (static_cast<uint8_t>(pkt[0]) != 10) {
    ::close(fd);  // only protocol V10
    return -1;
  }
  size_t pos = 1;
  std::string server_version;
  if (!get_nul_str(pkt, &pos, &server_version) || pkt.size() < pos + 13) {
    ::close(fd);
    return -1;
  }
  pos += 4;  // thread id
  std::string nonce = pkt.substr(pos, 8);
  pos += 8 + 1;  // auth-data-1 + filler
  if (pkt.size() < pos + 2) {
    ::close(fd);
    return -1;
  }
  uint32_t caps = static_cast<uint8_t>(pkt[pos]) |
                  (static_cast<uint8_t>(pkt[pos + 1]) << 8);
  pos += 2;
  if (pkt.size() >= pos + 1 + 2 + 2 + 1 + 10) {
    pos += 1 + 2;  // charset, status
    caps |= (static_cast<uint32_t>(static_cast<uint8_t>(pkt[pos])) |
             (static_cast<uint32_t>(static_cast<uint8_t>(pkt[pos + 1]))
              << 8))
            << 16;
    const uint8_t auth_len = static_cast<uint8_t>(pkt[pos + 2]);
    pos += 2 + 1 + 10;
    if (caps & kSecureConnection) {
      const size_t part2 =
          auth_len > 8 ? static_cast<size_t>(auth_len) - 8 : 13;
      if (pkt.size() >= pos + part2) {
        // part2 includes a trailing NUL; the scramble nonce is 20 bytes.
        nonce += pkt.substr(pos, part2 >= 13 ? 12 : part2);
        pos += part2;
      }
    }
  }

  // --- HandshakeResponse41 ---
  uint32_t my_caps = kLongPassword | kProtocol41 | kTransactions |
                     kSecureConnection | kPluginAuth;
  if (!opts_.database.empty()) {
    my_caps |= kConnectWithDb;
  }
  std::string rsp;
  put_u32le(&rsp, my_caps);
  put_u32le(&rsp, 16 << 20);  // max packet
  rsp.push_back(33);          // utf8_general_ci
  rsp.append(23, '\0');
  rsp.append(opts_.user);
  rsp.push_back('\0');
  const std::string scr = native_scramble(opts_.password, nonce);
  rsp.push_back(static_cast<char>(scr.size()));
  rsp.append(scr);
  if (!opts_.database.empty()) {
    rsp.append(opts_.database);
    rsp.push_back('\0');
  }
  rsp.append("mysql_native_password");
  rsp.push_back('\0');
  if (write_packet(fd, rsp, static_cast<uint8_t>(seq + 1), deadline) !=
      0) {
    ::close(fd);
    return -1;
  }

  // --- auth result (possibly via AuthSwitchRequest) ---
  if (read_packet(fd, &pkt, &seq, deadline) != 0 || pkt.empty()) {
    ::close(fd);
    return -1;
  }
  if (static_cast<uint8_t>(pkt[0]) == 0xfe && pkt.size() > 1) {
    // AuthSwitchRequest: only mysql_native_password is speakable.
    size_t sp = 1;
    std::string plugin, data;
    if (!get_nul_str(pkt, &sp, &plugin) ||
        plugin != "mysql_native_password") {
      ::close(fd);
      return -1;
    }
    data = pkt.substr(sp);
    if (!data.empty() && data.back() == '\0') {
      data.pop_back();
    }
    if (write_packet(fd, native_scramble(opts_.password, data),
                     static_cast<uint8_t>(seq + 1), deadline) != 0 ||
        read_packet(fd, &pkt, &seq, deadline) != 0 || pkt.empty()) {
      ::close(fd);
      return -1;
    }
  }
  if (static_cast<uint8_t>(pkt[0]) != 0x00) {
    LOG(Warning) << "mysql auth failed for user " << opts_.user;
    ::close(fd);
    return -1;
  }
  fd_ = fd;
  return 0;
}

// ---- resultset reader (shared by text and binary protocols) --------------

namespace {

// Reads a resultset whose HEADER packet is `first` (already consumed):
// column definitions + EOF, then rows + EOF.  `binary` picks the row
// format (COM_STMT_EXECUTE's typed rows vs COM_QUERY's lenenc text).
// Returns 0 on success (r->ok set), -1 on a protocol error the caller
// must treat as connection-fatal; a row-level ERR packet fills *r and
// returns 0 (the connection survives).
int read_resultset(int fd, const std::string& first, int64_t deadline,
                   bool binary, MysqlClient::Result* r) {
  size_t pos = 0;
  uint64_t ncols = 0;
  if (!get_lenenc(first, &pos, &ncols) || ncols == 0 || ncols > 4096) {
    r->error_text = "malformed resultset header";
    return -1;
  }
  std::vector<uint8_t> col_types;
  std::vector<bool> col_unsigned;
  std::string pkt;
  uint8_t seq = 0;
  for (uint64_t i = 0; i < ncols; ++i) {
    if (read_packet(fd, &pkt, &seq, deadline) != 0) {
      r->error_text = "short column definitions";
      return -1;
    }
    size_t cp = 0;
    std::string skip, name;
    uint8_t ctype = 0xfd;  // VAR_STRING
    bool is_unsigned = false;
    if (get_lenenc_str(pkt, &cp, &skip) &&  // catalog ("def")
        get_lenenc_str(pkt, &cp, &skip) &&  // schema
        get_lenenc_str(pkt, &cp, &skip) &&  // table
        get_lenenc_str(pkt, &cp, &skip) &&  // org_table
        get_lenenc_str(pkt, &cp, &name)) {
      r->columns.push_back(std::move(name));
      // org_name + fixed part: 0x0c, charset u16, length u32, type u8,
      // flags u16 (bit 5 = UNSIGNED), decimals, filler.
      std::string org;
      if (get_lenenc_str(pkt, &cp, &org) && pkt.size() >= cp + 10) {
        ctype = static_cast<uint8_t>(pkt[cp + 7]);
        const uint16_t flags = static_cast<uint16_t>(
            static_cast<uint8_t>(pkt[cp + 8]) |
            (static_cast<uint8_t>(pkt[cp + 9]) << 8));
        is_unsigned = (flags & 0x20) != 0;
      }
    } else {
      r->columns.push_back("col" + std::to_string(i));
    }
    col_types.push_back(ctype);
    col_unsigned.push_back(is_unsigned);
  }
  if (read_packet(fd, &pkt, &seq, deadline) != 0 || !is_eof_packet(pkt)) {
    r->error_text = "missing EOF after column definitions";
    return -1;
  }
  while (true) {
    if (read_packet(fd, &pkt, &seq, deadline) != 0) {
      r->error_text = "short resultset";
      return -1;
    }
    if (is_eof_packet(pkt)) {
      break;
    }
    if (!pkt.empty() && static_cast<uint8_t>(pkt[0]) == 0xff) {
      parse_err(pkt, r);
      return 0;
    }
    std::vector<std::optional<std::string>> row;
    if (!binary) {
      size_t rp = 0;
      for (uint64_t i = 0; i < ncols; ++i) {
        if (rp < pkt.size() && static_cast<uint8_t>(pkt[rp]) == 0xfb) {
          row.emplace_back(std::nullopt);
          ++rp;
          continue;
        }
        std::string cell;
        if (!get_lenenc_str(pkt, &rp, &cell)) {
          r->error_text = "malformed row";
          return -1;
        }
        row.emplace_back(std::move(cell));
      }
    } else {
      if (pkt.empty() || static_cast<uint8_t>(pkt[0]) != 0x00) {
        r->error_text = "malformed binary row";
        return -1;
      }
      const size_t bitmap_len = (ncols + 7 + 2) / 8;
      if (pkt.size() < 1 + bitmap_len) {
        r->error_text = "short binary row";
        return -1;
      }
      const uint8_t* bm =
          reinterpret_cast<const uint8_t*>(pkt.data()) + 1;
      size_t rp = 1 + bitmap_len;
      for (uint64_t i = 0; i < ncols; ++i) {
        const size_t bit = i + 2;
        if (bm[bit / 8] & (1 << (bit % 8))) {
          row.emplace_back(std::nullopt);
          continue;
        }
        // Fixed-length binary types, signedness-aware; everything else
        // is length-encoded (strings, blobs, decimals, dates-as-text).
        auto fixed_int = [&](size_t nbytes) -> bool {
          if (pkt.size() - rp < nbytes) {
            return false;
          }
          uint64_t u = 0;
          std::memcpy(&u, pkt.data() + rp, nbytes);
          rp += nbytes;
          if (col_unsigned[i]) {
            row.emplace_back(std::to_string(u));
          } else {
            // Sign-extend from nbytes.
            const int shift = static_cast<int>(64 - 8 * nbytes);
            row.emplace_back(std::to_string(
                shift == 0
                    ? static_cast<int64_t>(u)
                    : (static_cast<int64_t>(u << shift) >> shift)));
          }
          return true;
        };
        bool ok = true;
        switch (col_types[i]) {
          case 0x01:  // TINY
            ok = fixed_int(1);
            break;
          case 0x02:  // SHORT
          case 0x0d:  // YEAR
            ok = fixed_int(2);
            break;
          case 0x03:  // LONG
          case 0x09:  // INT24 (transferred as 4 bytes)
            ok = fixed_int(4);
            break;
          case 0x08:  // LONGLONG
            ok = fixed_int(8);
            break;
          case 0x04: {  // FLOAT
            float f;
            if ((ok = pkt.size() - rp >= 4)) {
              std::memcpy(&f, pkt.data() + rp, 4);
              rp += 4;
              row.emplace_back(std::to_string(f));
            }
            break;
          }
          case 0x05: {  // DOUBLE
            double d;
            if ((ok = pkt.size() - rp >= 8)) {
              std::memcpy(&d, pkt.data() + rp, 8);
              rp += 8;
              row.emplace_back(std::to_string(d));
            }
            break;
          }
          default: {
            std::string cell;
            ok = get_lenenc_str(pkt, &rp, &cell);
            if (ok) {
              row.emplace_back(std::move(cell));
            }
            break;
          }
        }
        if (!ok) {
          r->error_text = "malformed binary row";
          return -1;
        }
      }
    }
    r->rows.push_back(std::move(row));
  }
  r->ok = true;
  return 0;
}

}  // namespace

// ---- commands ------------------------------------------------------------

MysqlClient::Result MysqlClient::command(uint8_t com,
                                         const std::string& arg) {
  Result r;
  LockGuard<FiberMutex> g(mu_);
  const int64_t deadline =
      monotonic_time_us() + opts_.timeout_ms * 1000;
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (ensure_connected() != 0) {
      r.error_code = 2003;  // CR_CONN_HOST_ERROR
      r.error_text = "cannot connect to " + endpoint2str(ep_);
      return r;
    }
    std::string req(1, static_cast<char>(com));
    req.append(arg);
    std::string pkt;
    uint8_t seq = 0;
    if (write_packet(fd_, req, 0, deadline) != 0 ||
        read_packet(fd_, &pkt, &seq, deadline) != 0 || pkt.empty()) {
      // Dead connection: drop it and retry ONCE on a fresh one (only
      // for the first failure — a second means the server is gone).
      drop_connection();
      continue;
    }

    const uint8_t first = static_cast<uint8_t>(pkt[0]);
    if (first == 0xff) {
      parse_err(pkt, &r);
      return r;
    }
    if (first == 0x00) {
      if (!parse_ok(pkt, &r)) {
        r.error_text = "malformed OK packet";
      }
      return r;
    }
    // Resultset: shared reader (text rows).
    if (read_resultset(fd_, pkt, deadline, /*binary=*/false, &r) != 0) {
      drop_connection();
      return r;
    }
    return r;
  }
  r.error_code = 2013;  // CR_SERVER_LOST
  r.error_text = "lost connection during query";
  return r;
}

MysqlClient::Result MysqlClient::Query(const std::string& sql) {
  return command(kComQuery, sql);
}

int MysqlClient::Prepare(const std::string& sql, Stmt* out, Result* err) {
  LockGuard<FiberMutex> g(mu_);
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  if (ensure_connected() != 0) {
    if (err != nullptr) {
      err->error_code = 2003;
      err->error_text = "cannot connect";
    }
    return -1;
  }
  std::string req(1, static_cast<char>(kComStmtPrepare));
  req.append(sql);
  std::string pkt;
  uint8_t seq = 0;
  if (write_packet(fd_, req, 0, deadline) != 0 ||
      read_packet(fd_, &pkt, &seq, deadline) != 0 || pkt.empty()) {
    drop_connection();
    if (err != nullptr) {
      err->error_code = 2013;
      err->error_text = "lost connection during prepare";
    }
    return -1;
  }
  if (static_cast<uint8_t>(pkt[0]) == 0xff) {
    // Server-side failure (syntax error, unknown table): the session is
    // HEALTHY — dropping it here would silently roll back an open
    // transaction.
    if (err != nullptr) {
      parse_err(pkt, err);
    }
    return -1;
  }
  if (pkt.size() < 12 || static_cast<uint8_t>(pkt[0]) != 0x00) {
    drop_connection();
    if (err != nullptr) {
      err->error_code = 2027;  // CR_MALFORMED_PACKET
      err->error_text = "malformed PREPARE-OK";
    }
    return -1;
  }
  // PREPARE-OK: [00] stmt_id u32 | num_columns u16 | num_params u16 |
  // filler | warnings u16 — then param defs + EOF, column defs + EOF.
  const uint8_t* p = reinterpret_cast<const uint8_t*>(pkt.data());
  out->id = p[1] | (p[2] << 8) | (p[3] << 16)
            | (static_cast<uint32_t>(p[4]) << 24);
  out->n_cols = static_cast<uint16_t>(p[5] | (p[6] << 8));
  out->n_params = static_cast<uint16_t>(p[7] | (p[8] << 8));
  out->session = session_gen_;
  for (int section = 0; section < 2; ++section) {
    const int defs = section == 0 ? out->n_params : out->n_cols;
    if (defs == 0) {
      continue;
    }
    for (int i = 0; i <= defs; ++i) {  // defs + trailing EOF
      if (read_packet(fd_, &pkt, &seq, deadline) != 0 ||
          (i == defs && !is_eof_packet(pkt))) {
        drop_connection();
        if (err != nullptr) {
          err->error_code = 2013;  // CR_SERVER_LOST (mid-definitions)
          err->error_text = "lost connection draining statement defs";
        }
        return -1;
      }
    }
  }
  return 0;
}

void MysqlClient::CloseStmt(const Stmt& stmt) {
  LockGuard<FiberMutex> g(mu_);
  if (fd_ < 0 || stmt.session != session_gen_) {
    // A handle from before a reconnect must not be closed on the fresh
    // session: the server may have reassigned the id to a live
    // statement, and COM_STMT_CLOSE would silently destroy that one.
    return;
  }
  std::string req(1, static_cast<char>(kComStmtClose));
  put_u32le(&req, stmt.id);
  write_packet(fd_, req, 0, monotonic_time_us() + opts_.timeout_ms * 1000);
  // COM_STMT_CLOSE has no response by design.
}

MysqlClient::Result MysqlClient::ExecuteStmt(
    const Stmt& stmt,
    const std::vector<std::optional<std::string>>& params) {
  Result r;
  LockGuard<FiberMutex> g(mu_);
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  if (ensure_connected() != 0) {
    r.error_code = 2003;
    r.error_text = "not connected";
    return r;
  }
  if (stmt.session != session_gen_) {
    // The handle was prepared on a connection that has since died; the
    // fresh session does not know the id — surface that instead of the
    // server's "unknown prepared statement handler".
    r.error_code = 2030;  // CR_NO_PREPARE_STMT
    r.error_text = "statement invalidated by reconnect; re-Prepare";
    return r;
  }
  if (params.size() != stmt.n_params) {
    r.error_code = 2031;  // CR_PARAMS_NOT_BOUND
    r.error_text = "parameter count mismatch";
    return r;
  }
  for (const auto& param : params) {
    if (param.has_value() && param->size() >= (1u << 24)) {
      r.error_code = 2027;  // CR_MALFORMED_PACKET (would need lenenc-8)
      r.error_text = "parameter exceeds 16MB";
      return r;
    }
  }
  std::string req(1, static_cast<char>(kComStmtExecute));
  put_u32le(&req, stmt.id);
  req.push_back(0);  // flags: CURSOR_TYPE_NO_CURSOR
  put_u32le(&req, 1);  // iteration count
  if (!params.empty()) {
    std::string bitmap((params.size() + 7) / 8, '\0');
    for (size_t i = 0; i < params.size(); ++i) {
      if (!params[i].has_value()) {
        bitmap[i / 8] |= static_cast<char>(1 << (i % 8));
      }
    }
    req.append(bitmap);
    req.push_back(1);  // new-params-bound
    for (size_t i = 0; i < params.size(); ++i) {
      req.push_back(static_cast<char>(kTypeVarString));
      req.push_back(0);  // signed
    }
    for (const auto& param : params) {
      if (!param.has_value()) {
        continue;  // carried by the NULL bitmap
      }
      // lenenc length (all test/realistic params < 16MB).
      const size_t n = param->size();
      if (n < 0xfb) {
        req.push_back(static_cast<char>(n));
      } else if (n <= 0xffff) {
        req.push_back(static_cast<char>(0xfc));
        req.push_back(static_cast<char>(n));
        req.push_back(static_cast<char>(n >> 8));
      } else {
        req.push_back(static_cast<char>(0xfd));
        req.push_back(static_cast<char>(n));
        req.push_back(static_cast<char>(n >> 8));
        req.push_back(static_cast<char>(n >> 16));
      }
      req.append(*param);
    }
  }
  std::string pkt;
  uint8_t seq = 0;
  if (write_packet(fd_, req, 0, deadline) != 0 ||
      read_packet(fd_, &pkt, &seq, deadline) != 0 || pkt.empty()) {
    drop_connection();
    r.error_code = 2013;
    r.error_text = "lost connection during execute";
    return r;
  }
  const uint8_t first = static_cast<uint8_t>(pkt[0]);
  if (first == 0xff) {
    parse_err(pkt, &r);
    return r;
  }
  if (first == 0x00) {
    if (!parse_ok(pkt, &r)) {
      r.error_text = "malformed OK packet";
    }
    return r;
  }
  // Binary resultset: shared reader (typed binary rows).
  if (read_resultset(fd_, pkt, deadline, /*binary=*/true, &r) != 0) {
    drop_connection();
  }
  return r;
}

int MysqlClient::Ping() {
  return command(kComPing, "").ok ? 0 : -1;
}

int MysqlClient::SelectDb(const std::string& db) {
  Result r = command(kComInitDb, db);
  return r.ok ? 0 : -1;
}

}  // namespace trpc
