// MySQL client protocol — handshake, native-password auth, text queries.
//
// Parity: the reference fork's notable addition is a full mysql client
// (/root/reference/src/brpc/policy/mysql/, 22 files: handshake +
// scramble, COM_QUERY text resultsets, prepared statements,
// transactions with socket binding).  Condensed tpu-native form: one
// MysqlClient owning ONE bound connection (the reference binds a socket
// for transactions — BIND_SOCK in controller.cpp IssueRPC — because the
// conversation is stateful; here every client IS a bound connection),
// speaking the public wire protocol:
//   packets    : 3-byte little-endian length + sequence id
//   handshake  : V10 greeting, HandshakeResponse41,
//                mysql_native_password scramble
//                SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw)))
//   COM_QUERY  : OK / ERR / resultset (column defs, text rows, EOF)
//   COM_PING / COM_INIT_DB / COM_QUIT
// The fd is non-blocking; waits park the calling fiber (fiber_fd_wait),
// not the worker thread.
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

#include "base/endpoint.h"
#include "fiber/sync.h"

namespace trpc {

class MysqlClient {
 public:
  struct Options {
    std::string user = "root";
    std::string password;
    std::string database;  // optional initial schema
    int64_t timeout_ms = 3000;
  };

  struct Result {
    bool ok = false;
    uint16_t error_code = 0;
    std::string error_text;
    // OK-packet fields (INSERT/UPDATE/...).
    uint64_t affected_rows = 0;
    uint64_t last_insert_id = 0;
    // Resultset fields (SELECT/SHOW/...); NULL cells are nullopt.
    std::vector<std::string> columns;
    std::vector<std::vector<std::optional<std::string>>> rows;
  };

  ~MysqlClient();

  // Resolves and stores options; the connection is established lazily on
  // the first command (and re-established after failures).
  int Init(const std::string& addr, const Options* opts = nullptr);

  // One statement.  Transactions are plain statements on this bound
  // connection: Query("BEGIN") ... Query("COMMIT").
  Result Query(const std::string& sql);

  // Prepared statements (binary protocol).  Params bind as strings
  // (MYSQL_TYPE_VAR_STRING — the server coerces, same as the text
  // protocol) or NULL via nullopt; binary resultset rows decode the
  // common column types (strings/blobs, LONG/LONGLONG, NULL bitmap).
  struct Stmt {
    uint32_t id = 0;
    uint16_t n_params = 0;
    uint16_t n_cols = 0;
    uint64_t session = 0;  // connection generation; invalidated on drop
  };
  // err (optional) receives server-side failure details (the connection
  // stays healthy on an ERR reply — a syntax error must not roll back
  // an open transaction by dropping the session).
  int Prepare(const std::string& sql, Stmt* out, Result* err = nullptr);
  Result ExecuteStmt(const Stmt& stmt,
                     const std::vector<std::optional<std::string>>& params);
  void CloseStmt(const Stmt& stmt);  // fire-and-forget COM_STMT_CLOSE
  // COM_PING round trip; 0 on success.
  int Ping();
  // USE <db> via COM_INIT_DB; 0 on success.
  int SelectDb(const std::string& db);

  // The mysql_native_password proof for `password` against a 20-byte
  // nonce (exposed for tests and the fake server).
  static std::string native_scramble(const std::string& password,
                                     const std::string& nonce20);

 private:
  int ensure_connected();  // caller holds mu_
  void drop_connection();
  Result command(uint8_t com, const std::string& arg);

  EndPoint ep_;
  Options opts_;
  FiberMutex mu_;  // the whole conversation is serialized
  int fd_ = -1;
  uint64_t session_gen_ = 0;  // bumped on drop; stamps Stmt handles
};

}  // namespace trpc
