#include "net/naming.h"

#include <string.h>

#include <algorithm>

#include "base/flags.h"
#include "base/json.h"
#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/server.h"
#include "stat/digest.h"
#include "stat/reducer.h"
#include "stat/slo.h"

namespace trpc {

namespace {

Flag* lease_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_naming_lease_ms", 10000,
        "default membership lease for announcements that pass "
        "lease_ms <= 0 (ms, [200, 3600000]); a member whose announcer "
        "stops renewing falls out of every watcher's view within one "
        "lease");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 200 &&
               n <= 3600000;
      });
    }
    return flag;
  }();
  return f;
}

Flag* watch_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_naming_watch_ms", 10000,
        "server-side park budget for one Naming.Watch long-poll round "
        "(ms, [50, 600000]); a change answers immediately — this only "
        "caps how long an idle watcher fiber stays parked");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 50 && n <= 600000;
      });
    }
    return flag;
  }();
  return f;
}

std::atomic<bool> g_fleet_publish{false};

Flag* fleet_publish_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_bool(
        "trpc_fleet_publish", false,
        "fleet observability publication: each Announcer renew round "
        "also publishes the node's latency digest + SLO attainment blob "
        "(stat/digest.h digest-wire 2) onto its own naming:// membership "
        "record, feeding /fleet and tools/fleet_top.py (default off; "
        "payloads are lease/epoch-fenced and die with the member)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
      flag->on_update([](Flag* self) {
        g_fleet_publish.store(self->bool_value(),
                              std::memory_order_release);
      });
    }
    return flag;
  }();
  return f;
}

struct NamingVars {
  Adder announce_total;
  Adder withdraw_total;
  Adder expire_total;
  Adder watch_wake_total;
  Adder publish_total;
  Adder stats_pull_total;
  NamingVars() {
    announce_total.expose(
        "naming_announce_total",
        "membership announcements accepted by the registry on this node "
        "(new members, epoch takeovers, and lease renewals)");
    withdraw_total.expose(
        "naming_withdraw_total",
        "membership withdrawals accepted by the registry on this node "
        "(graceful drains and explicit leaves)");
    expire_total.expose(
        "naming_expire_total",
        "members pruned by lease expiry (announcer died or stopped "
        "renewing) — each one is a membership change watchers see");
    watch_wake_total.expose(
        "naming_watch_wake_total",
        "Naming.Watch long-polls answered because the membership "
        "version moved (push deliveries, as opposed to idle timeouts)");
    publish_total.expose(
        "fleet_publish_total",
        "stats payloads accepted onto membership records by the "
        "registry on this node (frozen at 0 while trpc_fleet_publish "
        "has never been on anywhere in the fleet)");
    stats_pull_total.expose(
        "fleet_stats_pull_total",
        "Naming.Stats pulls served by the registry on this node "
        "(/fleet renders and fleet_top.py refreshes)");
  }
};

NamingVars& naming_vars() {
  static NamingVars* v = new NamingVars();
  return *v;
}

int64_t effective_lease_us(int64_t lease_ms) {
  if (lease_ms <= 0) {
    lease_ms = lease_flag() != nullptr ? lease_flag()->int64_value() : 10000;
  }
  return monotonic_time_us() + lease_ms * 1000;
}

// Withdraw-tombstone TTL: generous vs one in-flight renewal RPC (the
// race it fences), bounded so addr churn can't grow the map forever.
int64_t tombstone_expire_us() {
  const int64_t lease_ms =
      lease_flag() != nullptr ? lease_flag()->int64_value() : 10000;
  return monotonic_time_us() +
         std::max<int64_t>(60000, 4 * lease_ms) * 1000;
}

void copy_str(char* dst, size_t cap, const std::string& src) {
  const size_t n = std::min(src.size(), cap - 1);
  memcpy(dst, src.data(), n);
  memset(dst + n, 0, cap - n);
}

std::string wire_str(const char* src, size_t cap) {
  return std::string(src, strnlen(src, cap));
}

}  // namespace

void naming_ensure_registered() {
  lease_flag();
  watch_flag();
  fleet_publish_flag();
  naming_vars();
}

bool fleet_publish_enabled() {
  return g_fleet_publish.load(std::memory_order_relaxed);
}

// ---- NamingRegistry -------------------------------------------------------

NamingRegistry& naming_registry() {
  static NamingRegistry* r = new NamingRegistry();
  return *r;
}

NamingRegistry::Service* NamingRegistry::service_locked(
    const std::string& name) {
  return &services_[name];
}

void NamingRegistry::prune_locked(Service* s) {
  const int64_t now = monotonic_time_us();
  bool changed = false;
  for (auto it = s->members.begin(); it != s->members.end();) {
    if (it->second.deadline_us <= now) {
      it = s->members.erase(it);
      changed = true;
      naming_vars().expire_total << 1;
    } else {
      ++it;
    }
  }
  // Expired withdraw tombstones fall out silently (no version bump —
  // nothing a watcher can observe changes).
  for (auto it = s->withdrawn_epochs.begin();
       it != s->withdrawn_epochs.end();) {
    if (it->second.expire_us <= now) {
      it = s->withdrawn_epochs.erase(it);
    } else {
      ++it;
    }
  }
  if (changed) {
    ++s->version;
    // Release: a watcher that observes the bumped event value must see
    // the membership mutation made above (it re-reads under mu_, but the
    // event wake itself races the lock-free fast check).
    s->changed->value.fetch_add(1, std::memory_order_release);
    s->changed->wake_all();
  }
}

int NamingRegistry::announce(const std::string& service,
                             const NamingMember& m, int64_t lease_ms) {
  naming_ensure_registered();
  std::lock_guard<std::mutex> g(mu_);
  Service* s = service_locked(service);
  prune_locked(s);
  auto tomb = s->withdrawn_epochs.find(m.addr);
  if (tomb != s->withdrawn_epochs.end() && m.epoch <= tomb->second.epoch) {
    // Zombie-renewal fence: this epoch (or an older one) explicitly
    // withdrew — a renewal that raced its own Withdraw must not
    // resurrect the member.  A successor's newer epoch passes (and
    // clears the tombstone below).
    return kENamingStaleEpoch;
  }
  auto it = s->members.find(m.addr);
  bool changed = false;
  if (it == s->members.end()) {
    changed = true;
  } else if (m.epoch < it->second.m.epoch) {
    return kENamingStaleEpoch;  // zombie predecessor of a restarted node
  } else {
    // Same epoch = renewal; newer epoch = takeover.  Either way a zone/
    // weight/epoch difference is a change watchers must see.
    changed = m.epoch != it->second.m.epoch ||
              m.weight != it->second.m.weight || m.zone != it->second.m.zone;
  }
  if (tomb != s->withdrawn_epochs.end()) {
    s->withdrawn_epochs.erase(tomb);  // newer epoch: takeover admitted
  }
  Member rec;
  rec.m = m;
  rec.m.lease_left_ms = 0;
  rec.deadline_us = effective_lease_us(lease_ms);
  s->members[m.addr] = std::move(rec);
  naming_vars().announce_total << 1;
  if (changed) {
    ++s->version;
    // Release: see prune_locked.
    s->changed->value.fetch_add(1, std::memory_order_release);
    s->changed->wake_all();
  }
  return 0;
}

int NamingRegistry::withdraw(const std::string& service,
                             const std::string& addr, uint64_t epoch) {
  std::lock_guard<std::mutex> g(mu_);
  Service* s = service_locked(service);
  prune_locked(s);
  auto it = s->members.find(addr);
  if (it == s->members.end()) {
    // Goal state already holds (idempotent leave) — but still fence the
    // epoch so an in-flight renewal racing this withdraw cannot
    // resurrect the member afterwards.
    Service::Tombstone& t = s->withdrawn_epochs[addr];
    t.epoch = std::max(t.epoch, epoch);
    t.expire_us = tombstone_expire_us();
    return 0;
  }
  if (epoch < it->second.m.epoch) {
    return kENamingStaleEpoch;  // zombie must not unregister the successor
  }
  Service::Tombstone& t = s->withdrawn_epochs[addr];
  t.epoch = std::max(t.epoch, std::max(epoch, it->second.m.epoch));
  t.expire_us = tombstone_expire_us();
  s->members.erase(it);
  naming_vars().withdraw_total << 1;
  ++s->version;
  // Release: see prune_locked.
  s->changed->value.fetch_add(1, std::memory_order_release);
  s->changed->wake_all();
  return 0;
}

int NamingRegistry::resolve(const std::string& service,
                            std::vector<NamingMember>* out,
                            uint64_t* version) {
  std::lock_guard<std::mutex> g(mu_);
  auto sit = services_.find(service);
  if (sit == services_.end()) {
    return kENamingMiss;
  }
  Service* s = &sit->second;
  prune_locked(s);
  const int64_t now = monotonic_time_us();
  out->clear();
  out->reserve(s->members.size());
  for (const auto& [addr, rec] : s->members) {
    NamingMember m = rec.m;
    m.lease_left_ms = (rec.deadline_us - now) / 1000;
    out->push_back(std::move(m));
  }
  // Deterministic order: watchers diff successive views by position-
  // independent content, but tests and logs read far better sorted.
  std::sort(out->begin(), out->end(),
            [](const NamingMember& a, const NamingMember& b) {
              return a.addr < b.addr;
            });
  if (version != nullptr) {
    *version = s->version;
  }
  return 0;
}

int NamingRegistry::watch(const std::string& service, uint64_t known_version,
                          int64_t park_budget_ms,
                          std::vector<NamingMember>* out, uint64_t* version,
                          const std::function<bool()>& keep_waiting) {
  const int64_t deadline_us =
      monotonic_time_us() + std::max<int64_t>(park_budget_ms, 0) * 1000;
  std::shared_ptr<Event> ev;
  {
    std::lock_guard<std::mutex> g(mu_);
    // Creates the service entry if needed: a watcher of a not-yet-
    // announced service parks until its first member arrives.  The
    // shared_ptr co-owns the Event past a concurrent clear().
    ev = service_locked(service)->changed;
  }
  while (true) {
    uint32_t snap;
    {
      std::lock_guard<std::mutex> g(mu_);
      Service* s = service_locked(service);
      prune_locked(s);
      if (s->version != known_version) {
        break;  // changed (or the caller's view was never current)
      }
      // Snapshot INSIDE the lock: a bump between this load and wait()
      // makes wait return EWOULDBLOCK instead of missing the wake.
      // Acquire pairs with the bump's release.
      snap = ev->value.load(std::memory_order_acquire);
    }
    const int64_t now = monotonic_time_us();
    if (now >= deadline_us ||
        (keep_waiting != nullptr && !keep_waiting())) {
      break;  // idle timeout / host leaving: answer the unchanged view
    }
    // Sliced park (<= 250ms per round): the keep_waiting re-check above
    // bounds how long a parked watcher fiber can stall its host's
    // Stop()/Join — a change still wakes it immediately.
    const int64_t slice_us = std::min(deadline_us, now + 250 * 1000);
    if (ev->wait(snap, slice_us) == 0) {
      naming_vars().watch_wake_total << 1;
    }
  }
  return resolve(service, out, version);
}

int NamingRegistry::publish(const std::string& service,
                            const std::string& addr, uint64_t epoch,
                            std::string payload) {
  std::lock_guard<std::mutex> g(mu_);
  auto sit = services_.find(service);
  if (sit == services_.end()) {
    return kENamingMiss;
  }
  Service* s = &sit->second;
  prune_locked(s);
  auto it = s->members.find(addr);
  if (it == s->members.end()) {
    return kENamingMiss;  // expired/unknown member: a dead node can't publish
  }
  if (epoch < it->second.m.epoch) {
    return kENamingStaleEpoch;  // zombie can't overwrite the successor's stats
  }
  it->second.payload = std::move(payload);
  it->second.payload_us = monotonic_time_us();
  naming_vars().publish_total << 1;
  // Deliberately NO version bump: stats churn every renew round and must
  // not wake membership watchers (same reason lease renewals don't).
  return 0;
}

int NamingRegistry::stats(const std::string& service,
                          std::vector<NamingStatsRecord>* out,
                          uint64_t* version) {
  std::lock_guard<std::mutex> g(mu_);
  auto sit = services_.find(service);
  if (sit == services_.end()) {
    return kENamingMiss;
  }
  Service* s = &sit->second;
  prune_locked(s);
  const int64_t now = monotonic_time_us();
  out->clear();
  out->reserve(s->members.size());
  for (const auto& [addr, rec] : s->members) {
    NamingStatsRecord r;
    r.member = rec.m;
    r.member.lease_left_ms = (rec.deadline_us - now) / 1000;
    r.age_ms = rec.payload_us > 0 ? (now - rec.payload_us) / 1000 : -1;
    r.payload = rec.payload;
    out->push_back(std::move(r));
  }
  std::sort(out->begin(), out->end(),
            [](const NamingStatsRecord& a, const NamingStatsRecord& b) {
              return a.member.addr < b.member.addr;
            });
  naming_vars().stats_pull_total << 1;
  if (version != nullptr) {
    *version = s->version;
  }
  return 0;
}

size_t NamingRegistry::member_count(const std::string& service) {
  std::lock_guard<std::mutex> g(mu_);
  auto sit = services_.find(service);
  if (sit == services_.end()) {
    return 0;
  }
  prune_locked(&sit->second);
  return sit->second.members.size();
}

void NamingRegistry::wake_all() {
  std::lock_guard<std::mutex> g(mu_);
  for (auto& [name, s] : services_) {
    // Version bump, not just a wake: the watch loop re-parks on a
    // spurious wake when the version is unchanged, and a draining host
    // needs its watcher fibers to ANSWER (they hold in_flight slots the
    // quiesce wait would otherwise spin on).
    ++s.version;
    // Release: parked watchers re-read state under mu_ after waking.
    s.changed->value.fetch_add(1, std::memory_order_release);
    s.changed->wake_all();
  }
}

void NamingRegistry::clear() {
  std::lock_guard<std::mutex> g(mu_);
  for (auto& [name, s] : services_) {
    ++s.version;  // release parked watchers (see wake_all)
    s.changed->value.fetch_add(1, std::memory_order_release);
    s.changed->wake_all();
  }
  // Parked watchers still co-own their Event via the shared_ptr they
  // copied in watch(); dropping the map can never free it under them.
  services_.clear();
}

// ---- wire helpers ---------------------------------------------------------

namespace {

bool parse_wire(const IOBuf& req, NamingWire* w) {
  if (req.size() < sizeof(NamingWire)) {
    return false;
  }
  req.copy_to(w, sizeof(NamingWire));
  w->service[sizeof(w->service) - 1] = '\0';
  w->addr[sizeof(w->addr) - 1] = '\0';
  w->zone[sizeof(w->zone) - 1] = '\0';
  return true;
}

void pack_member_row(IOBuf* out, const NamingMember& m) {
  NamingWire row;
  memset(&row, 0, sizeof(row));
  copy_str(row.addr, sizeof(row.addr), m.addr);
  copy_str(row.zone, sizeof(row.zone), m.zone);
  row.weight = m.weight;
  row.epoch = m.epoch;
  row.lease_ms = m.lease_left_ms;
  out->append(&row, sizeof(row));
}

void pack_view(IOBuf* out, const std::vector<NamingMember>& members,
               uint64_t version) {
  NamingWire head;
  memset(&head, 0, sizeof(head));
  head.version = version;
  head.weight = static_cast<int32_t>(members.size());
  out->append(&head, sizeof(head));
  for (const NamingMember& m : members) {
    pack_member_row(out, m);
  }
}

int unpack_view(const IOBuf& resp, std::vector<NamingMember>* out,
                uint64_t* version) {
  if (resp.size() < sizeof(NamingWire)) {
    return -1;
  }
  std::string flat = resp.to_string();
  const auto* head = reinterpret_cast<const NamingWire*>(flat.data());
  const size_t count = static_cast<size_t>(std::max(head->weight, 0));
  if (flat.size() < sizeof(NamingWire) * (count + 1)) {
    return -1;
  }
  out->clear();
  out->reserve(count);
  for (size_t i = 1; i <= count; ++i) {
    const auto* row =
        reinterpret_cast<const NamingWire*>(flat.data() +
                                            i * sizeof(NamingWire));
    NamingMember m;
    m.addr = wire_str(row->addr, sizeof(row->addr));
    m.zone = wire_str(row->zone, sizeof(row->zone));
    m.weight = row->weight;
    m.epoch = row->epoch;
    m.lease_left_ms = row->lease_ms;
    out->push_back(std::move(m));
  }
  if (version != nullptr) {
    *version = head->version;
  }
  return 0;
}

void fail_naming(Controller* cntl, int code, const char* what) {
  const char* why = code == kENamingStaleEpoch ? "naming-stale-epoch"
                    : code == kENamingMiss    ? "naming-miss"
                                              : "naming-error";
  cntl->SetFailed(code, std::string(why) + ": " + what);
}

}  // namespace

// ---- native handlers ------------------------------------------------------

int naming_attach(Server* s) {
  naming_ensure_registered();
  int rcs[4] = {0, 0, 0, 0};
  rcs[0] = s->RegisterMethod(
      kNamingAnnounceMethod, [](Controller* cntl, const IOBuf& req,
                                IOBuf* resp, Closure done) {
        NamingWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Naming.Announce request");
          done();
          return;
        }
        NamingMember m;
        m.addr = wire_str(w.addr, sizeof(w.addr));
        m.zone = wire_str(w.zone, sizeof(w.zone));
        m.weight = std::max(w.weight, 1);
        m.epoch = w.epoch;
        const int rc = naming_registry().announce(
            wire_str(w.service, sizeof(w.service)), m, w.lease_ms);
        if (rc != 0) {
          fail_naming(cntl, rc, "announce");
        } else {
          uint64_t ok = 1;
          resp->append(&ok, sizeof(ok));
        }
        done();
      });
  rcs[1] = s->RegisterMethod(
      kNamingWithdrawMethod, [](Controller* cntl, const IOBuf& req,
                                IOBuf* resp, Closure done) {
        NamingWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Naming.Withdraw request");
          done();
          return;
        }
        const int rc = naming_registry().withdraw(
            wire_str(w.service, sizeof(w.service)),
            wire_str(w.addr, sizeof(w.addr)), w.epoch);
        if (rc != 0) {
          fail_naming(cntl, rc, "withdraw");
        } else {
          uint64_t ok = 1;
          resp->append(&ok, sizeof(ok));
        }
        done();
      });
  rcs[2] = s->RegisterMethod(
      kNamingResolveMethod, [](Controller* cntl, const IOBuf& req,
                               IOBuf* resp, Closure done) {
        NamingWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Naming.Resolve request");
          done();
          return;
        }
        std::vector<NamingMember> members;
        uint64_t version = 0;
        const int rc = naming_registry().resolve(
            wire_str(w.service, sizeof(w.service)), &members, &version);
        if (rc != 0) {
          fail_naming(cntl, rc, "resolve");
        } else {
          pack_view(resp, members, version);
        }
        done();
      });
  rcs[3] = s->RegisterMethod(
      kNamingWatchMethod, [s](Controller* cntl, const IOBuf& req,
                              IOBuf* resp, Closure done) {
        NamingWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Naming.Watch request");
          done();
          return;
        }
        // Park budget: the smaller of the caller's ask and the server's
        // cap — a malicious/buggy client must not pin handler fibers.
        int64_t budget =
            watch_flag() != nullptr ? watch_flag()->int64_value() : 10000;
        if (w.lease_ms > 0) {
          budget = std::min(budget, w.lease_ms);
        }
        std::vector<NamingMember> members;
        uint64_t version = 0;
        // keep_waiting: a parked watcher holds one of the HOST server's
        // in_flight slots — answer early the moment the host stops or
        // drains, instead of stalling its Join through the park budget.
        const int rc = naming_registry().watch(
            wire_str(w.service, sizeof(w.service)), w.version, budget,
            &members, &version,
            [s] { return s->running() && !s->draining(); });
        if (rc != 0 && rc != kENamingMiss) {
          fail_naming(cntl, rc, "watch");
        } else {
          // kENamingMiss after a full park = still no members; answer an
          // empty view so the watcher's loop stays cheap and uniform.
          pack_view(resp, members, version);
        }
        done();
      });
  int rc_pub = s->RegisterMethod(
      kNamingPublishMethod, [](Controller* cntl, const IOBuf& req,
                               IOBuf* resp, Closure done) {
        NamingWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Naming.Publish request");
          done();
          return;
        }
        // Payload rides after the fixed header.
        const std::string flat = req.to_string();
        std::string payload = flat.substr(sizeof(NamingWire));
        const int rc = naming_registry().publish(
            wire_str(w.service, sizeof(w.service)),
            wire_str(w.addr, sizeof(w.addr)), w.epoch, std::move(payload));
        if (rc != 0) {
          fail_naming(cntl, rc, "publish");
        } else {
          uint64_t ok = 1;
          resp->append(&ok, sizeof(ok));
        }
        done();
      });
  int rc_stats = s->RegisterMethod(
      kNamingStatsMethod, [](Controller* cntl, const IOBuf& req,
                             IOBuf* resp, Closure done) {
        NamingWire w;
        if (!parse_wire(req, &w)) {
          cntl->SetFailed(EINVAL, "bad Naming.Stats request");
          done();
          return;
        }
        std::vector<NamingStatsRecord> records;
        uint64_t version = 0;
        const int rc = naming_registry().stats(
            wire_str(w.service, sizeof(w.service)), &records, &version);
        if (rc != 0) {
          fail_naming(cntl, rc, "stats");
        } else {
          // Head row (version, weight=count), then per member one
          // NamingWire row + u64 payload_len + payload bytes.
          NamingWire head;
          memset(&head, 0, sizeof(head));
          head.version = version;
          head.weight = static_cast<int32_t>(records.size());
          resp->append(&head, sizeof(head));
          for (const NamingStatsRecord& r : records) {
            NamingWire row;
            memset(&row, 0, sizeof(row));
            copy_str(row.addr, sizeof(row.addr), r.member.addr);
            copy_str(row.zone, sizeof(row.zone), r.member.zone);
            row.weight = r.member.weight;
            row.epoch = r.member.epoch;
            row.lease_ms = r.age_ms;  // publish age rides the lease slot
            resp->append(&row, sizeof(row));
            const uint64_t plen = r.payload.size();
            resp->append(&plen, sizeof(plen));
            resp->append(r.payload.data(), r.payload.size());
          }
        }
        done();
      });
  s->add_drain_hook([] { naming_registry().wake_all(); });
  return rcs[0] == 0 && rcs[1] == 0 && rcs[2] == 0 && rcs[3] == 0 &&
                 rc_pub == 0 && rc_stats == 0
             ? 0
             : -1;
}

// ---- client helpers -------------------------------------------------------

namespace {

// One naming RPC round-trip; 0 or the call's error code.
int naming_call(Channel* ch, const char* method, const NamingWire& w,
                IOBuf* resp, int64_t timeout_ms = 0) {
  IOBuf req;
  req.append(&w, sizeof(w));
  Controller cntl;
  if (timeout_ms > 0) {
    cntl.set_timeout_ms(timeout_ms);
  }
  ch->CallMethod(method, req, resp, &cntl);
  if (cntl.Failed()) {
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}

}  // namespace

int naming_announce(Channel* ch, const std::string& service,
                    const NamingMember& m, int64_t lease_ms) {
  NamingWire w;
  memset(&w, 0, sizeof(w));
  copy_str(w.service, sizeof(w.service), service);
  copy_str(w.addr, sizeof(w.addr), m.addr);
  copy_str(w.zone, sizeof(w.zone), m.zone);
  w.weight = m.weight;
  w.epoch = m.epoch;
  w.lease_ms = lease_ms;
  IOBuf resp;
  return naming_call(ch, kNamingAnnounceMethod, w, &resp);
}

int naming_withdraw(Channel* ch, const std::string& service,
                    const std::string& addr, uint64_t epoch) {
  NamingWire w;
  memset(&w, 0, sizeof(w));
  copy_str(w.service, sizeof(w.service), service);
  copy_str(w.addr, sizeof(w.addr), addr);
  w.epoch = epoch;
  IOBuf resp;
  return naming_call(ch, kNamingWithdrawMethod, w, &resp);
}

int naming_resolve(Channel* ch, const std::string& service,
                   std::vector<NamingMember>* out, uint64_t* version) {
  NamingWire w;
  memset(&w, 0, sizeof(w));
  copy_str(w.service, sizeof(w.service), service);
  IOBuf resp;
  const int rc = naming_call(ch, kNamingResolveMethod, w, &resp);
  if (rc != 0) {
    return rc;
  }
  return unpack_view(resp, out, version);
}

int naming_watch(Channel* ch, const std::string& service,
                 std::vector<NamingMember>* out, uint64_t* version,
                 int64_t park_budget_ms, int64_t timeout_ms) {
  NamingWire w;
  memset(&w, 0, sizeof(w));
  copy_str(w.service, sizeof(w.service), service);
  w.version = version != nullptr ? *version : 0;
  w.lease_ms = park_budget_ms;
  IOBuf resp;
  const int rc = naming_call(ch, kNamingWatchMethod, w, &resp, timeout_ms);
  if (rc != 0) {
    return rc;
  }
  return unpack_view(resp, out, version);
}

int naming_publish(Channel* ch, const std::string& service,
                   const std::string& addr, uint64_t epoch,
                   const std::string& payload) {
  NamingWire w;
  memset(&w, 0, sizeof(w));
  copy_str(w.service, sizeof(w.service), service);
  copy_str(w.addr, sizeof(w.addr), addr);
  w.epoch = epoch;
  IOBuf req;
  req.append(&w, sizeof(w));
  req.append(payload.data(), payload.size());
  IOBuf resp;
  Controller cntl;
  ch->CallMethod(kNamingPublishMethod, req, &resp, &cntl);
  if (cntl.Failed()) {
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}

int naming_stats(Channel* ch, const std::string& service,
                 std::vector<NamingStatsRecord>* out, uint64_t* version) {
  NamingWire w;
  memset(&w, 0, sizeof(w));
  copy_str(w.service, sizeof(w.service), service);
  IOBuf resp;
  const int rc = naming_call(ch, kNamingStatsMethod, w, &resp);
  if (rc != 0) {
    return rc;
  }
  const std::string flat = resp.to_string();
  if (flat.size() < sizeof(NamingWire)) {
    return -1;
  }
  const auto* head = reinterpret_cast<const NamingWire*>(flat.data());
  const size_t count = static_cast<size_t>(std::max(head->weight, 0));
  if (version != nullptr) {
    *version = head->version;
  }
  out->clear();
  out->reserve(count);
  size_t pos = sizeof(NamingWire);
  for (size_t i = 0; i < count; ++i) {
    if (flat.size() < pos + sizeof(NamingWire) + sizeof(uint64_t)) {
      return -1;
    }
    const auto* row =
        reinterpret_cast<const NamingWire*>(flat.data() + pos);
    pos += sizeof(NamingWire);
    uint64_t plen = 0;
    memcpy(&plen, flat.data() + pos, sizeof(plen));
    pos += sizeof(plen);
    if (flat.size() < pos + plen) {
      return -1;
    }
    NamingStatsRecord r;
    r.member.addr = wire_str(row->addr, sizeof(row->addr));
    r.member.zone = wire_str(row->zone, sizeof(row->zone));
    r.member.weight = row->weight;
    r.member.epoch = row->epoch;
    r.age_ms = row->lease_ms;
    r.payload.assign(flat.data() + pos, plen);
    pos += plen;
    out->push_back(std::move(r));
  }
  return 0;
}

// ---- Announcer ------------------------------------------------------------

Announcer::~Announcer() {
  Withdraw();
  stopping_.store(true, std::memory_order_release);
  if (renewer_started_.load(std::memory_order_acquire)) {
    renew_wake_.value.fetch_add(1, std::memory_order_release);
    renew_wake_.wake_all();
    while (renew_done_.value.load(std::memory_order_acquire) == 0) {
      renew_done_.wait(0, -1);
    }
    // Same teardown fence as ~ClusterChannel: the wake that satisfied us
    // may still be inside wake_all touching the Event.
    while (!renewer_exited_.load(std::memory_order_acquire)) {
      sched_yield();
    }
  }
}

int Announcer::Start(const std::string& registry_addr,
                     const std::string& service,
                     const std::string& self_addr, const std::string& zone,
                     int weight, uint64_t epoch) {
  naming_ensure_registered();
  ch_ = std::make_unique<Channel>();
  Channel::Options opts;
  opts.timeout_ms = 2000;
  if (ch_->Init(registry_addr, &opts) != 0) {
    ch_.reset();
    return -1;
  }
  service_ = service;
  self_addr_ = self_addr;
  zone_ = zone;
  weight_ = std::max(weight, 1);
  // Realtime µs: strictly newer across restarts of the same endpoint
  // (monotonic clocks restart at boot-relative values per process).
  epoch_ = epoch != 0 ? epoch : static_cast<uint64_t>(realtime_us());
  NamingMember m;
  m.addr = self_addr_;
  m.zone = zone_;
  m.weight = weight_;
  m.epoch = epoch_;
  if (naming_announce(ch_.get(), service_, m, 0) != 0) {
    ch_.reset();
    return -1;
  }
  publish_stats();  // fresh node visible in /fleet before a renew round
  bool expect = false;
  if (renewer_started_.compare_exchange_strong(expect, true)) {
    fiber_init(0);
    if (fiber_start(nullptr, &Announcer::renew_fiber, this, 0) != 0) {
      renewer_started_.store(false, std::memory_order_release);
    }
  }
  return 0;
}

void Announcer::Withdraw() {
  if (withdrawn_.exchange(true)) {
    return;
  }
  if (ch_ != nullptr) {
    naming_withdraw(ch_.get(), service_, self_addr_, epoch_);
  }
}

void Announcer::publish_stats() {
  // Fleet publication rides the renew cadence (lease/3): one relaxed
  // flag load when off, one digest snapshot + Publish RPC when on.
  if (!fleet_publish_enabled() || stats_provider_ == nullptr ||
      ch_ == nullptr) {
    return;
  }
  const std::string payload = stats_provider_();
  if (payload.empty()) {
    return;
  }
  naming_publish(ch_.get(), service_, self_addr_, epoch_, payload);
}

void Announcer::renew_fiber(void* arg) {
  auto* self = static_cast<Announcer*>(arg);
  const int64_t lease_ms =
      lease_flag() != nullptr ? lease_flag()->int64_value() : 10000;
  while (!self->stopping_.load(std::memory_order_acquire)) {
    // Renew at lease/3 so two consecutive drops still keep us alive.
    const uint32_t snap =
        self->renew_wake_.value.load(std::memory_order_acquire);
    self->renew_wake_.wait(
        snap, monotonic_time_us() + std::max<int64_t>(lease_ms / 3, 100) *
                                        1000);
    if (self->stopping_.load(std::memory_order_acquire) ||
        self->withdrawn_.load(std::memory_order_acquire)) {
      break;
    }
    NamingMember m;
    m.addr = self->self_addr_;
    m.zone = self->zone_;
    m.weight = self->weight_;
    m.epoch = self->epoch_;
    const int rc = naming_announce(self->ch_.get(), self->service_, m, 0);
    if (rc == kENamingStaleEpoch) {
      // A successor announced a newer epoch on our addr: we are the
      // zombie — stop renewing instead of fighting the takeover.
      break;
    }
    self->publish_stats();
  }
  self->renew_done_.value.store(1, std::memory_order_release);
  self->renew_done_.wake_all();
  // LAST access to *self (see ~Announcer).
  self->renewer_exited_.store(true, std::memory_order_release);
}

int server_announce(Server* srv, const std::string& registry_addr,
                    const std::string& service, const std::string& zone,
                    int weight) {
  if (srv == nullptr || !srv->running() || srv->port() <= 0) {
    return -1;
  }
  auto a = std::make_shared<Announcer>();
  const std::string self_addr =
      "127.0.0.1:" + std::to_string(srv->port());
  // Fleet observability provider: with trpc_fleet_publish on, each renew
  // round snapshots the server's SLO engine (digests + attainment) into a
  // digest-wire 2 blob on this node's membership record.  The server
  // outlives the announcer (own_component below), so the raw pointer is
  // safe for the announcer's lifetime.
  a->set_stats_provider([srv]() -> std::string {
    auto slo = srv->slo_engine();
    if (slo == nullptr || !slo::enabled()) {
      return std::string();
    }
    return slo->encode_blob(realtime_us());
  });
  if (a->Start(registry_addr, service, self_addr, zone, weight) != 0) {
    return -1;
  }
  // Withdraw FIRST in the drain sequence (hooks run before the in-flight
  // wait): watchers re-balance away while remaining work completes.
  srv->add_drain_hook([a] { a->Withdraw(); });
  srv->own_component(a);
  return 0;
}

// ---- fleet aggregation ----------------------------------------------------

std::string fleet_dump_json(const std::string& service) {
  std::vector<NamingStatsRecord> records;
  uint64_t version = 0;
  const int rc = naming_registry().stats(service, &records, &version);
  Json root = Json::object();
  root.set("service", Json::str(service));
  root.set("publish_enabled", Json::boolean(fleet_publish_enabled()));
  if (rc != 0) {
    root.set("error", Json::str(rc == kENamingMiss ? "naming-miss"
                                                   : "naming-error"));
    root.set("nodes", Json::array());
    root.set("tenants", Json::array());
    return root.dump();
  }
  root.set("version", Json::number(static_cast<double>(version)));

  // Per-tenant fleet aggregate: digests MERGE (octave-wise pooling) and
  // window counters SUM; burn rates are recomputed from the pooled
  // counters — the fleet burns budget as one pool, it does not average
  // per-node burn rates (nor p99s).
  struct Agg {
    LatencyDigest digest;
    int64_t p99_target_us = INT64_MAX;
    double avail_target = 0;
    int64_t fast_total = 0, fast_bad = 0, fast_err = 0;
    int64_t slow_total = 0, slow_bad = 0, slow_err = 0;
    int nodes = 0;
    int breached_nodes = 0;
  };
  std::map<std::string, Agg> tenants;

  Json nodes = Json::array();
  for (const NamingStatsRecord& r : records) {
    Json node = Json::object();
    node.set("addr", Json::str(r.member.addr));
    node.set("zone", Json::str(r.member.zone));
    node.set("epoch", Json::number(static_cast<double>(r.member.epoch)));
    node.set("age_ms", Json::number(static_cast<double>(r.age_ms)));
    FleetNodeBlob blob;
    const bool ok = !r.payload.empty() &&
                    fleet_blob_decode(r.payload.data(), r.payload.size(),
                                      &blob);
    node.set("published", Json::boolean(ok));
    nodes.push_back(std::move(node));
    if (!ok) {
      continue;
    }
    for (FleetTenantRecord& t : blob.tenants) {
      Agg& a = tenants[t.tenant];
      digest_merge(&a.digest, t.digest);
      a.p99_target_us = std::min(a.p99_target_us, t.p99_target_us);
      a.avail_target = std::max(a.avail_target, t.avail_target);
      a.fast_total += t.fast_total;
      a.fast_bad += t.fast_bad;
      a.fast_err += t.fast_err;
      a.slow_total += t.slow_total;
      a.slow_bad += t.slow_bad;
      a.slow_err += t.slow_err;
      ++a.nodes;
      if (t.breached) {
        ++a.breached_nodes;
      }
    }
  }
  root.set("nodes", std::move(nodes));

  Json tarr = Json::array();
  for (auto& [name, a] : tenants) {
    Json t = Json::object();
    t.set("tenant", Json::str(name));
    t.set("nodes", Json::number(a.nodes));
    t.set("breached_nodes", Json::number(a.breached_nodes));
    t.set("p99_target_us",
          Json::number(a.p99_target_us == INT64_MAX
                           ? -1.0
                           : static_cast<double>(a.p99_target_us)));
    t.set("avail_target", Json::number(a.avail_target));
    t.set("rate", Json::number(a.digest.qps()));
    t.set("p50_us", Json::number(static_cast<double>(
                        digest_percentile_us(a.digest, 0.5))));
    t.set("p99_us", Json::number(static_cast<double>(
                        digest_percentile_us(a.digest, 0.99))));
    t.set("avg_us", Json::number(a.digest.avg_us()));
    t.set("count", Json::number(static_cast<double>(a.digest.count)));
    const double err_rate =
        a.slow_total > 0
            ? static_cast<double>(a.slow_err) / a.slow_total
            : 0.0;
    t.set("error_rate", Json::number(err_rate));
    const double allowed = std::max(1.0 - a.avail_target, 1e-6);
    const double burn_fast =
        a.fast_total > 0
            ? (static_cast<double>(a.fast_bad) / a.fast_total) / allowed
            : 0.0;
    const double burn_slow =
        a.slow_total > 0
            ? (static_cast<double>(a.slow_bad) / a.slow_total) / allowed
            : 0.0;
    t.set("burn_fast", Json::number(burn_fast));
    t.set("burn_slow", Json::number(burn_slow));
    t.set("budget_remaining",
          Json::number(std::max(0.0, std::min(1.0, 1.0 - burn_slow))));
    tarr.push_back(std::move(t));
  }
  root.set("tenants", std::move(tarr));
  return root.dump();
}

}  // namespace trpc
