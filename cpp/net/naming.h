// Cluster naming service — push-based membership over the RPC plane
// (ISSUE 12 tentpole).
//
// Parity: brpc's NamingService push model (naming_service.h:45-56 —
// actions->ResetServers pushed from a watcher thread) and its
// NamingServiceThread sharing, grown past the reference: where brpc only
// CONSUMES external naming systems (BNS, consul, nacos), this registry
// IS one — any Server can host it, nodes announce themselves under the
// same lease semantics as the KV registry (net/kvstore.h: expired =
// gone, epoch-checked re-announce), and clients receive push-based
// membership deltas through a parked Watch RPC (long-poll over the
// existing request path; plain Resolve is the poll fallback), feeding
// ClusterChannel so adds/removals/weight changes apply without
// reconnect storms.
//
// Model:
//  - NamingRegistry (process-global `naming_registry()`): service name →
//    member set.  Each member {addr, zone, weight, epoch} holds a lease;
//    expired members prune lazily on any read and count as a membership
//    change.  EPOCH rules (the zombie fence): a re-announce with the
//    recorded epoch renews the lease; a NEWER epoch replaces the member
//    (restarted process); an OLDER one is rejected kENamingStaleEpoch —
//    a zombie predecessor can never shadow its successor.
//  - Every mutation bumps the service VERSION and wakes parked watchers;
//    pure lease renewals do not (watchers would spin on heartbeats).
//  - `naming_attach(Server*)` serves Naming.{Announce,Withdraw,Resolve,
//    Watch}.  Watch parks its handler fiber (bounded by the smaller of
//    the caller's budget and trpc_naming_watch_ms) until the version
//    moves, then answers the full member list — deltas are computed
//    client-side against the previous view, which makes the wire
//    idempotent and loss-tolerant (a missed wake only costs latency,
//    never correctness).
//  - Announcer: the server-side self-registration helper.  Announces
//    {addr, zone, weight} under a fresh epoch (realtime µs — strictly
//    newer across restarts of the same endpoint), renews at lease/3
//    from a private fiber, and withdraws on Server::Drain (hook) or
//    destruction.
//
// Drain + hot restart (net/server.h Drain/StartFromHandoff) composes
// with this: a draining node withdraws its announcement FIRST (watchers
// re-balance away immediately), answers kEDraining while in-flight work
// completes, then hands its SO_REUSEPORT listener set to the successor,
// which announces the same addr under a newer epoch.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "fiber/event.h"

namespace trpc {

class Channel;
class Server;

// Error codes, continuing the 2004..2103 family (concurrency_limiter.h,
// kvstore.h).  kENamingStaleEpoch: an announce/withdraw carried an epoch
// OLDER than the recorded member's — the caller is a zombie predecessor
// of a restarted node and must not touch the record.
constexpr int kENamingStaleEpoch = 2111;
constexpr int kENamingMiss = 2112;  // unknown service (resolve/watch)

// Method names (tstd, served by naming_attach).
inline constexpr const char* kNamingAnnounceMethod = "Naming.Announce";
inline constexpr const char* kNamingWithdrawMethod = "Naming.Withdraw";
inline constexpr const char* kNamingResolveMethod = "Naming.Resolve";
inline constexpr const char* kNamingWatchMethod = "Naming.Watch";
// Fleet observability publication (ISSUE 19): a member attaches an opaque
// stats payload (digest + SLO attainment blob, stat/digest.h digest-wire
// 2) to its own membership record; Stats returns every live member's
// latest payload.  Payloads ride the member's lease — a dead node's stats
// vanish with its membership — and are epoch-fenced like announces.
inline constexpr const char* kNamingPublishMethod = "Naming.Publish";
inline constexpr const char* kNamingStatsMethod = "Naming.Stats";

// One member of a named service (also the resolve/watch response row).
struct NamingMember {
  std::string addr;  // "host:port"
  std::string zone;  // locality label ("" = unknown)
  int32_t weight = 1;
  uint64_t epoch = 0;
  int64_t lease_left_ms = 0;  // response-only
};

// Wire form shared by every Naming RPC (fixed little-endian, 176 bytes;
// mirrored by brpc_tpu/rpc/naming.py _WIRE — naming-wire marker):
//   Announce: service+addr+zone+weight+epoch+lease_ms
//   Withdraw: service+addr+epoch
//   Resolve:  service
//   Watch:    service + version (the caller's known version) + lease_ms
//             reused as the park budget in ms
// Resolve/Watch RESPONSE: one NamingWire header whose version is the
// current version and weight is the member count, followed by count
// member rows (addr/zone/weight/epoch filled, lease_ms = remaining).
struct NamingWire {
  char service[64];
  char addr[64];
  char zone[16];
  int32_t weight;
  uint32_t reserved;
  uint64_t epoch;
  int64_t lease_ms;
  uint64_t version;
};
static_assert(sizeof(NamingWire) == 176, "NamingWire is wire format");

// One member's published stats (Naming.Stats response row).
struct NamingStatsRecord {
  NamingMember member;
  int64_t age_ms = -1;  // since the member's last publish; -1 = never
  std::string payload;  // opaque (digest-wire 2 blob for fleet nodes)
};

// ---- registry (any node can host it) -------------------------------------

class NamingRegistry {
 public:
  // Upserts (service, addr).  Epoch rules above; lease_ms <= 0 uses
  // trpc_naming_lease_ms.  Returns 0, or kENamingStaleEpoch.
  int announce(const std::string& service, const NamingMember& m,
               int64_t lease_ms);
  // Removes (service, addr) when `epoch` >= the recorded member's.
  // Idempotent: an unknown member answers 0 (the caller's goal state —
  // "I am not a member" — already holds).  kENamingStaleEpoch when a
  // LIVE record holds a newer epoch (zombie withdraw must not unregister
  // the successor).
  int withdraw(const std::string& service, const std::string& addr,
               uint64_t epoch);
  // Fills *out (pruning expired members) and *version.  kENamingMiss for
  // a service with no live members and no history.
  int resolve(const std::string& service, std::vector<NamingMember>* out,
              uint64_t* version);
  // Parks the CALLING fiber until the service's version != known_version
  // (or park_budget_ms passes), then resolves.  Returns resolve()'s
  // result; *version always reflects the answered view.  An unknown
  // service parks too (the first announce is exactly the change a
  // watcher is waiting for).  `keep_waiting` (nullable) is re-checked
  // every park slice (<= ~250ms): when it turns false the watch answers
  // early — the Naming.Watch handler passes the host server's
  // running-and-not-draining state so a parked watcher fiber can never
  // stall a plain Stop()/Join through its park budget.
  int watch(const std::string& service, uint64_t known_version,
            int64_t park_budget_ms, std::vector<NamingMember>* out,
            uint64_t* version,
            const std::function<bool()>& keep_waiting = nullptr);

  // Attaches `payload` to the LIVE member (service, addr).  Lease/epoch
  // fenced: kENamingMiss when the member is unknown or expired (a dead
  // node cannot publish), kENamingStaleEpoch when `epoch` is older than
  // the recorded member's (a zombie predecessor cannot overwrite its
  // successor's stats).  Does NOT bump the service version — stats churn
  // every renew round and must not wake membership watchers.
  int publish(const std::string& service, const std::string& addr,
              uint64_t epoch, std::string payload);
  // Fills *out with every live member + its latest payload (empty when
  // the member never published).  kENamingMiss like resolve().
  int stats(const std::string& service,
            std::vector<NamingStatsRecord>* out, uint64_t* version);

  size_t member_count(const std::string& service);
  // RELEASES every parked watcher (drain hook: a draining registry host
  // must not hold watcher fibers through its in-flight wait).  Bumps
  // each service's version so the watch loop answers instead of
  // re-parking; clients see a spurious no-delta refresh, which is
  // idempotent.
  void wake_all();
  void clear();  // tests

 private:
  struct Member {
    NamingMember m;
    int64_t deadline_us = 0;
    // Latest published stats payload (dies with the member).
    std::string payload;
    int64_t payload_us = 0;  // monotonic stamp of the last publish
  };
  struct Service {
    std::unordered_map<std::string, Member> members;  // by addr
    // Highest explicitly-WITHDRAWN epoch per addr (the zombie-renewal
    // fence): a late in-flight renewal racing its own Withdraw must not
    // resurrect the member, so an announce at or below this epoch is
    // rejected.  A successor's newer epoch passes.  Lease EXPIRY does
    // not tombstone — a partitioned node that heals may legitimately
    // re-announce its live epoch.  TTL-bounded (max(60s, 4 leases),
    // pruned with the members): the fence only needs to outlive an
    // in-flight renewal RPC, and ephemeral-port churn on a long-lived
    // registry must not grow this map forever.
    struct Tombstone {
      uint64_t epoch = 0;
      int64_t expire_us = 0;
    };
    std::unordered_map<std::string, Tombstone> withdrawn_epochs;
    uint64_t version = 1;
    // Watchers park here; every version bump increments value + wakes.
    // shared_ptr: a parked watcher co-owns the Event, so clear() while
    // a Watch long-poll is in flight can never free it underneath.
    std::shared_ptr<Event> changed = std::make_shared<Event>();
  };
  // Prunes expired members of s (bumping version if any fell); mu_ held.
  void prune_locked(Service* s);
  Service* service_locked(const std::string& name);
  std::mutex mu_;
  std::unordered_map<std::string, Service> services_;
};
NamingRegistry& naming_registry();

// Attaches the native handlers (call before Server::Start).  Also
// registers a drain hook that wakes parked watchers.  Returns 0, or -1
// when any registration was refused (server already running).
int naming_attach(Server* s);

// ---- client-side RPC helpers (shared by Announcer / RegistryNS) ----------

// One announce round-trip over `ch`.  0, kENamingStaleEpoch, or the
// transport error.
int naming_announce(Channel* ch, const std::string& service,
                    const NamingMember& m, int64_t lease_ms);
int naming_withdraw(Channel* ch, const std::string& service,
                    const std::string& addr, uint64_t epoch);
int naming_resolve(Channel* ch, const std::string& service,
                   std::vector<NamingMember>* out, uint64_t* version);
// Long-poll: answers when the registry's version != *version (or after
// its park budget).  Updates *version to the answered view's.
int naming_watch(Channel* ch, const std::string& service,
                 std::vector<NamingMember>* out, uint64_t* version,
                 int64_t park_budget_ms, int64_t timeout_ms);
// Publishes an opaque stats payload onto (service, addr)'s live record.
int naming_publish(Channel* ch, const std::string& service,
                   const std::string& addr, uint64_t epoch,
                   const std::string& payload);
// Pulls every live member's latest payload.
int naming_stats(Channel* ch, const std::string& service,
                 std::vector<NamingStatsRecord>* out, uint64_t* version);

// Fleet aggregation over the LOCAL registry (the /fleet builtin and
// trpc_fleet_dump): resolves `service`'s live members, decodes each
// published digest-wire 2 blob, merges digests octave-wise per tenant and
// rank-walks the pooled samples — fleet per-tenant rate / p50 / p99 /
// error-rate / budget-remaining / burn-rate, never averaged node p99s.
std::string fleet_dump_json(const std::string& service);

// ---- Announcer (server-side self-registration) ---------------------------

class Announcer {
 public:
  ~Announcer();  // withdraws + joins the renew fiber
  // Announces `self_addr` into `service` at the registry and starts the
  // renew fiber.  Epoch defaults to realtime µs (0 = mint one).
  // Returns 0, or -1 (channel init / first announce failed).
  int Start(const std::string& registry_addr, const std::string& service,
            const std::string& self_addr, const std::string& zone,
            int weight, uint64_t epoch = 0);
  // Withdraws the announcement and stops renewing (idempotent; the
  // Server::Drain hook calls this FIRST so watchers re-balance before
  // in-flight work drains).
  void Withdraw();
  uint64_t epoch() const { return epoch_; }
  const std::string& self_addr() const { return self_addr_; }
  // Installs the stats provider the renew fiber publishes each round
  // while the reloadable `trpc_fleet_publish` flag is on (an empty return
  // skips the round).  Call BEFORE Start — Start publishes once
  // immediately so a fresh node is visible in /fleet without waiting a
  // renew round.
  void set_stats_provider(std::function<std::string()> fn) {
    stats_provider_ = std::move(fn);
  }

 private:
  static void renew_fiber(void* arg);
  // One publication round (flag-gated; no-op without a provider).
  void publish_stats();
  std::unique_ptr<Channel> ch_;
  std::string service_;
  std::string self_addr_;
  std::string zone_;
  int weight_ = 1;
  uint64_t epoch_ = 0;
  std::function<std::string()> stats_provider_;
  std::atomic<bool> withdrawn_{false};
  std::atomic<bool> stopping_{false};
  std::atomic<bool> renewer_started_{false};
  Event renew_wake_;
  Event renew_done_;
  std::atomic<bool> renewer_exited_{false};
};

// Creates an Announcer for `srv` (must be started; uses its port),
// announces "127.0.0.1:<port>" and wires Withdraw into the server's
// drain hooks; the server owns the announcer for its lifetime.  Returns
// 0, or -1.
int server_announce(Server* srv, const std::string& registry_addr,
                    const std::string& service, const std::string& zone,
                    int weight);

// Flag registration (idempotent): trpc_naming_lease_ms,
// trpc_naming_watch_ms, trpc_fleet_publish.
void naming_ensure_registered();

// True while the reloadable trpc_fleet_publish flag is on (one relaxed
// load — announcer renew rounds gate their publish on it).
bool fleet_publish_enabled();

}  // namespace trpc
