#include "net/nshead.h"

#include <errno.h>

#include <cstring>

#include <deque>
#include <map>
#include <memory>
#include <mutex>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/messenger.h"
#include "net/protocol.h"
#include "net/server.h"

namespace trpc {

namespace {

constexpr size_t kMaxBody = 64ull << 20;

// Cuts one nshead frame (head stays in out->meta via ctx).  The magic at
// offset 24 is the probe discriminator.
struct NsheadFrame {
  NsheadHead head;
  IOBuf body;
};

ParseError nshead_cut(IOBuf* source, InputMessage* out, Socket* sock,
                      bool probing) {
  NsheadHead head;
  IOBuf body;
  const int rc = nshead_cut_frame(source, &head, &body);
  if (rc == 0) {
    return probing ? nshead_probe_short(source)
                   : ParseError::kNotEnoughData;
  }
  if (rc < 0) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  auto frame = std::make_shared<NsheadFrame>();
  frame->head = head;
  frame->body = std::move(body);
  out->ctx = std::move(frame);
  out->socket = sock != nullptr ? sock->id() : 0;
  return ParseError::kOk;
}

}  // namespace

ParseError nshead_probe_short(IOBuf* source) {
  // Probing with an incomplete header: HOLD the connection (returning
  // kTryOtherProtocol would let the probe loop fall through every
  // protocol and kill a legitimate fragmented first frame) — but only
  // while the bytes seen could still become an nshead frame.  The magic
  // at offset 24 rules frames out as soon as 28 bytes are visible; the
  // leading id/version bytes are arbitrary and rule out nothing.
  uint8_t pre[28];
  const size_t got = source->copy_to(pre, sizeof(pre), 0);
  if (got >= sizeof(pre)) {
    uint32_t magic;
    std::memcpy(&magic, pre + 24, 4);
    if (magic != kNsheadMagic) {
      return ParseError::kTryOtherProtocol;
    }
  }
  return ParseError::kNotEnoughData;
}

int nshead_cut_frame(IOBuf* source, NsheadHead* head, IOBuf* body) {
  const size_t got = source->copy_to(head, sizeof(*head), 0);
  if (got < sizeof(*head)) {
    return 0;
  }
  if (head->magic_num != kNsheadMagic || head->body_len > kMaxBody) {
    return -1;
  }
  if (source->size() < sizeof(*head) + head->body_len) {
    return 0;
  }
  source->pop_front(sizeof(*head));
  source->cutn(body, head->body_len);
  return 1;
}

void nshead_pack(const NsheadHead& head, const IOBuf& body, IOBuf* out) {
  NsheadHead h = head;
  h.magic_num = kNsheadMagic;
  h.body_len = static_cast<uint32_t>(body.size());
  out->append(&h, sizeof(h));
  out->append(body);
}

// ---- nshead server -------------------------------------------------------

namespace {

ParseError nshead_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || srv->nshead_service() == nullptr) {
      return ParseError::kTryOtherProtocol;
    }
  }
  return nshead_cut(source, out, sock, probing);
}

// Inline in the read fiber: the wire has no correlation id, so responses
// must leave in arrival order.
void nshead_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto frame = std::static_pointer_cast<NsheadFrame>(msg.ctx);
  if (srv == nullptr || srv->nshead_service() == nullptr ||
      frame == nullptr) {
    return;
  }
  {  // Interceptor gate (same body as every serving protocol).
    int ec = 0;
    std::string et;
    if (!srv->accept_request("nshead", sock->remote(), &ec, &et)) {
      sock->SetFailed(EACCES);
      return;
    }
  }
  NsheadHead resp_head = frame->head;  // echo id/version/log_id/provider
  IOBuf resp_body;
  srv->nshead_service()->handler()(frame->head, frame->body, &resp_head,
                                   &resp_body);
  srv->requests_served.fetch_add(1, std::memory_order_relaxed);
  IOBuf out;
  nshead_pack(resp_head, resp_body, &out);
  sock->Write(std::move(out));
}

void nshead_process_response(InputMessage&&) {}

}  // namespace

void register_nshead_protocol() {
  static int once = [] {
    Protocol p = {"nshead", nshead_parse, nshead_process_request,
                  nshead_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- nshead client -------------------------------------------------------

namespace {

struct NsheadWaiter {
  CountdownEvent ev{1};
  bool ok = false;
  NsheadHead head;
  IOBuf body;
};

struct NsheadCliConn {
  std::mutex mu;  // queue order == wire order (FIFO correlation)
  std::deque<std::shared_ptr<NsheadWaiter>> pending;
};

const char kNsheadCliTag = 0;

NsheadCliConn* nscli_conn_of(Socket* s) {
  return proto_conn_of<NsheadCliConn>(s, &kNsheadCliTag);
}

int install_nshead_conn(Socket* s) {
  nscli_conn_of(s);
  return 0;
}

ParseError nsheadc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;  // client sockets are pre-pinned
  }
  ParseError rc = nshead_cut(source, out, sock, /*probing=*/false);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

void nsheadc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto frame = std::static_pointer_cast<NsheadFrame>(msg.ctx);
  NsheadCliConn* c = nscli_conn_of(sock.get());
  std::shared_ptr<NsheadWaiter> w;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->pending.empty()) {
      return;  // unsolicited
    }
    w = std::move(c->pending.front());
    c->pending.pop_front();
  }
  w->ok = true;
  w->head = frame->head;
  w->body = std::move(frame->body);
  w->ev.signal();
}

void nsheadc_process_request(InputMessage&&) {}

int nsheadc_protocol_index() {
  static const int index = [] {
    Protocol p = {"nsheadc", nsheadc_parse, nsheadc_process_request,
                  nsheadc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

}  // namespace

NsheadClient::~NsheadClient() {
  csock_.Shutdown();
}

int NsheadClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  nsheadc_protocol_index();
  return csock_.Init(addr);
}

int NsheadClient::call(const NsheadHead& head, const IOBuf& body,
                       NsheadHead* resp_head, IOBuf* resp_body) {
  SocketId sid = 0;
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(nsheadc_protocol_index(), install_nshead_conn,
                      &sid) != 0) {
      return -1;
    }
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  NsheadCliConn* c = nscli_conn_of(s.get());
  auto w = std::make_shared<NsheadWaiter>();
  {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.push_back(w);
    IOBuf out;
    nshead_pack(head, body, &out);
    if (s->Write(std::move(out)) != 0) {
      c->pending.pop_back();
      return -1;
    }
  }
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  if (w->ev.wait(deadline) != 0 || !w->ok) {
    return -1;  // waiter stays queued so later replies keep alignment
  }
  if (resp_head != nullptr) {
    *resp_head = w->head;
  }
  if (resp_body != nullptr) {
    *resp_body = std::move(w->body);
  }
  return 0;
}

// ---- esp -----------------------------------------------------------------

bool EspService::AddMessageHandler(uint32_t msg, Handler h) {
  return handlers_.emplace(msg, std::move(h)).second;
}

const EspService::Handler* EspService::FindMessageHandler(
    uint32_t msg) const {
  auto it = handlers_.find(msg);
  return it == handlers_.end() ? nullptr : &it->second;
}

namespace {

struct EspFrame {
  EspHead head;
  IOBuf body;
};

ParseError esp_cut(IOBuf* source, InputMessage* out, Socket* sock,
                   bool probing) {
  EspHead head;
  const size_t got = source->copy_to(&head, sizeof(head), 0);
  if (got < sizeof(head)) {
    // esp has NO magic: an esp-enabled server claims the connection on
    // faith (the reference only ever speaks esp client-side; a server
    // installing an EspService is dedicating the port to it).  A short
    // prefix therefore HOLDS — killing it would break any fragmented
    // first frame on a dedicated esp port.
    return ParseError::kNotEnoughData;
  }
  if (head.body_len < 0 || static_cast<size_t>(head.body_len) > kMaxBody) {
    return probing ? ParseError::kTryOtherProtocol
                   : ParseError::kCorrupted;
  }
  if (source->size() < sizeof(head) + head.body_len) {
    return ParseError::kNotEnoughData;
  }
  source->pop_front(sizeof(head));
  auto frame = std::make_shared<EspFrame>();
  frame->head = head;
  source->cutn(&frame->body, head.body_len);
  out->ctx = std::move(frame);
  out->socket = sock != nullptr ? sock->id() : 0;
  return ParseError::kOk;
}

ParseError esp_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || srv->esp_service() == nullptr) {
      return ParseError::kTryOtherProtocol;
    }
  }
  return esp_cut(source, out, sock, probing);
}

void esp_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto frame = std::static_pointer_cast<EspFrame>(msg.ctx);
  if (srv == nullptr || srv->esp_service() == nullptr ||
      frame == nullptr) {
    return;
  }
  {  // Interceptor gate.
    int ec = 0;
    std::string et;
    if (!srv->accept_request("esp#" + std::to_string(frame->head.msg),
                             sock->remote(), &ec, &et)) {
      sock->SetFailed(EACCES);
      return;
    }
  }
  const EspService::Handler* h =
      srv->esp_service()->FindMessageHandler(frame->head.msg);
  IOBuf resp_body;
  if (h != nullptr) {
    (*h)(frame->head, frame->body, &resp_body);
  }
  srv->requests_served.fetch_add(1, std::memory_order_relaxed);
  EspHead resp = frame->head;  // echoes msg_id (the correlation contract)
  std::swap(resp.from, resp.to);
  resp.body_len = static_cast<int32_t>(resp_body.size());
  IOBuf out;
  out.append(&resp, sizeof(resp));
  out.append(resp_body);
  sock->Write(std::move(out));
}

void esp_process_response(InputMessage&&) {}

}  // namespace

void register_esp_protocol() {
  static int once = [] {
    Protocol p = {"esp", esp_parse, esp_process_request,
                  esp_process_response,
                  /*process_in_order=*/false};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- esp client ----------------------------------------------------------

namespace {

struct EspWaiter {
  CountdownEvent ev{1};
  bool ok = false;
  IOBuf body;
};

struct EspCliConn {
  std::mutex mu;
  std::map<uint64_t, std::shared_ptr<EspWaiter>> pending;  // by msg_id
};

const char kEspCliTag = 0;

EspCliConn* espcli_conn_of(Socket* s) {
  return proto_conn_of<EspCliConn>(s, &kEspCliTag);
}

int install_esp_conn(Socket* s) {
  espcli_conn_of(s);
  return 0;
}

ParseError espc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;
  }
  ParseError rc = esp_cut(source, out, sock, /*probing=*/false);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

void espc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto frame = std::static_pointer_cast<EspFrame>(msg.ctx);
  EspCliConn* c = espcli_conn_of(sock.get());
  std::shared_ptr<EspWaiter> w;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->pending.find(frame->head.msg_id);
    if (it == c->pending.end()) {
      return;  // unsolicited / timed out
    }
    w = std::move(it->second);
    c->pending.erase(it);
  }
  w->ok = true;
  w->body = std::move(frame->body);
  w->ev.signal();
}

void espc_process_request(InputMessage&&) {}

int espc_protocol_index() {
  static const int index = [] {
    Protocol p = {"espc", espc_parse, espc_process_request,
                  espc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

}  // namespace

EspClient::~EspClient() {
  csock_.Shutdown();
}

int EspClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  espc_protocol_index();
  return csock_.Init(addr);
}

int EspClient::call(uint32_t msg, const IOBuf& body, IOBuf* resp_body) {
  SocketId sid = 0;
  EspHead head;
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(espc_protocol_index(), install_esp_conn, &sid) !=
        0) {
      return -1;
    }
    head.msg_id = next_msg_id_++;
  }
  head.msg = msg;
  head.to = static_cast<uint64_t>(opts_.to_stub);
  head.body_len = static_cast<int32_t>(body.size());

  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  EspCliConn* c = espcli_conn_of(s.get());
  auto w = std::make_shared<EspWaiter>();
  {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.emplace(head.msg_id, w);
  }
  IOBuf out;
  out.append(&head, sizeof(head));
  out.append(body);
  if (s->Write(std::move(out)) != 0) {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.erase(head.msg_id);
    return -1;
  }
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  if (w->ev.wait(deadline) != 0 || !w->ok) {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.erase(head.msg_id);
    return -1;
  }
  if (resp_body != nullptr) {
    *resp_body = std::move(w->body);
  }
  return 0;
}

}  // namespace trpc
