// nshead + esp — Baidu legacy fixed-header protocols, server AND client.
//
// Parity: the reference serves nshead-family traffic through
// NsheadService (/root/reference/src/brpc/nshead_service.h; wire struct
// nshead.h: 36-byte native-order header with magic 0xfb709394 and
// body_len) and speaks esp client-side (esp_message.h / esp_head.h:
// packed 32-byte head {from,to,msg,msg_id,body_len}, native order;
// policy/esp_protocol.cpp correlates responses by msg_id).  Condensed
// forms: raw byte-level services (handlers see head + body IOBuf) and
// per-protocol clients in the RedisClient mold — nshead correlates FIFO
// (the wire has no id the peer must echo), esp by msg_id.
//
// These are also the substrate for the nova/public pbrpc protocols
// (net/legacy_pbrpc.h), which ride the same nshead framing.
#pragma once

#include <cstdint>
#include <cstring>
#include <functional>
#include <map>
#include <string>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/proto_client.h"
#include "net/protocol.h"
#include "net/socket.h"

namespace trpc {

class Server;

// ---- nshead --------------------------------------------------------------

constexpr uint32_t kNsheadMagic = 0xfb709394u;

#pragma pack(push, 1)
// 36 bytes, native byte order on the wire (the reference inherits this
// from the unchangeable public/nshead definition).
struct NsheadHead {
  uint16_t id = 0;
  uint16_t version = 0;
  uint32_t log_id = 0;
  char provider[16] = {};
  uint32_t magic_num = kNsheadMagic;
  uint32_t reserved = 0;
  uint32_t body_len = 0;
};
#pragma pack(pop)
static_assert(sizeof(NsheadHead) == 36, "nshead wire layout");

// Raw nshead server: one handler sees every message (head + body) and
// fills the response body (+ optionally mutates the response head, which
// starts as a copy of the request's with body_len fixed up).  Assign via
// Server::set_nshead_service.  Runs inline in the read fiber: responses
// leave in arrival order (the wire has no correlation id).
class NsheadService {
 public:
  using Handler = std::function<void(const NsheadHead& head,
                                     const IOBuf& body,
                                     NsheadHead* resp_head,
                                     IOBuf* resp_body)>;
  explicit NsheadService(Handler h) : handler_(std::move(h)) {}
  const Handler& handler() const { return handler_; }

 private:
  Handler handler_;
};

void register_nshead_protocol();

// Packs head (fixing body_len) + body.
void nshead_pack(const NsheadHead& head, const IOBuf& body, IOBuf* out);

// Cuts one complete nshead frame off `source` (shared by the raw nshead
// protocol and the nova/public pbrpc personalities that ride the same
// framing).  Returns 1 ok / 0 not-enough-data / -1 not-nshead (probing:
// magic mismatch or oversized body; the caller maps -1 to
// kTryOtherProtocol while probing, kCorrupted once pinned).
int nshead_cut_frame(IOBuf* source, NsheadHead* head, IOBuf* body);

// Probe-time policy for an incomplete nshead header: hold while the
// visible prefix could still be nshead (magic checked once 28 bytes are
// visible), else kTryOtherProtocol.  Shared with nova/public pbrpc.
ParseError nshead_probe_short(IOBuf* source);

// FIFO nshead client (one connection; responses arrive in order).
class NsheadClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
  };

  ~NsheadClient();
  int Init(const std::string& addr, const Options* opts = nullptr);

  // One exchange; returns 0 and fills resp_head/resp_body, or -1.
  int call(const NsheadHead& head, const IOBuf& body,
           NsheadHead* resp_head, IOBuf* resp_body);

 private:
  Options opts_;
  FiberMutex sock_mu_;
  ClientSocket csock_;
};

// ---- esp -----------------------------------------------------------------

#pragma pack(push, 1)
struct EspHead {
  uint64_t from = 0;  // {stub u16, port u16, ip u32} packed
  uint64_t to = 0;
  uint32_t msg = 0;      // message/command number
  uint64_t msg_id = 0;   // correlation id, echoed by the peer
  int32_t body_len = 0;
};
#pragma pack(pop)
static_assert(sizeof(EspHead) == 32, "esp wire layout");

// esp server: handlers keyed by msg number; the reply echoes msg_id.
// Assign via Server::set_esp_service.
class EspService {
 public:
  using Handler =
      std::function<void(const EspHead& head, const IOBuf& body,
                         IOBuf* resp_body)>;
  bool AddMessageHandler(uint32_t msg, Handler h);
  const Handler* FindMessageHandler(uint32_t msg) const;

 private:
  std::map<uint32_t, Handler> handlers_;
};

void register_esp_protocol();

// esp client: call(msg, body) correlates the response by msg_id, so
// concurrent calls on the shared connection are fine.
class EspClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
    uint16_t to_stub = 0;  // copied into EspHead.to
  };

  ~EspClient();
  int Init(const std::string& addr, const Options* opts = nullptr);

  int call(uint32_t msg, const IOBuf& body, IOBuf* resp_body);

 private:
  Options opts_;
  FiberMutex sock_mu_;
  ClientSocket csock_;
  uint64_t next_msg_id_ = 1;
};

}  // namespace trpc
