#include "net/progressive.h"

#include <cstdio>

#include "net/controller.h"

namespace trpc {

std::shared_ptr<ProgressiveAttachment>
Controller::CreateProgressiveAttachment() {
  if (progressive_ == nullptr) {
    progressive_ = std::make_shared<ProgressiveAttachment>();
  }
  return progressive_;
}

namespace {

void append_chunk(IOBuf* out, const IOBuf& data) {
  if (data.empty()) {
    return;  // a zero-length chunk would terminate the body
  }
  char head[24];
  const int n = snprintf(head, sizeof(head), "%zx\r\n", data.size());
  out->append(head, static_cast<size_t>(n));
  out->append(data);
  out->append("\r\n", 2);
}

}  // namespace

int ProgressiveAttachment::Write(const IOBuf& data) {
  std::lock_guard<std::mutex> g(mu_);
  if (closed_ || pre_closed_) {
    return -1;
  }
  if (sid_ == 0) {
    append_chunk(&queued_, data);  // rides the headers write at bind()
    return 0;
  }
  SocketRef s(Socket::Address(sid_));
  if (!s) {
    return -1;
  }
  IOBuf out;
  append_chunk(&out, data);
  return s->Write(std::move(out));
}

void ProgressiveAttachment::close() {
  std::shared_ptr<CountdownEvent> notify;
  {
    std::lock_guard<std::mutex> g(mu_);
    if (closed_ || pre_closed_) {
      return;
    }
    if (sid_ == 0) {
      pre_closed_ = true;  // terminator rides the headers write
      return;
    }
    closed_ = true;
    SocketRef s(Socket::Address(sid_));
    if (s) {
      IOBuf fin;
      fin.append("0\r\n\r\n", 5);
      s->Write(std::move(fin), /*close_after=*/!keep_alive_);
    }
    notify = std::move(on_closed_);
  }
  if (notify != nullptr) {
    notify->signal();  // release the connection's response ordering
  }
}

void ProgressiveAttachment::bind(SocketId sid, bool keep_alive,
                                 std::shared_ptr<CountdownEvent> on_closed,
                                 IOBuf&& head) {
  std::shared_ptr<CountdownEvent> notify;
  {
    std::lock_guard<std::mutex> g(mu_);
    keep_alive_ = keep_alive;
    head.append(std::move(queued_));
    bool terminated = false;
    if (pre_closed_) {
      head.append("0\r\n\r\n", 5);
      closed_ = true;
      terminated = true;
      notify = std::move(on_closed);
    } else {
      on_closed_ = std::move(on_closed);
    }
    SocketRef s(Socket::Address(sid));
    if (s) {
      s->Write(std::move(head),
               /*close_after=*/terminated && !keep_alive);
    }
    // Publish the socket only AFTER the headers are queued: Socket::Write
    // is FIFO, so later Write()/close() bytes order behind them.
    sid_ = sid;
  }
  if (notify != nullptr) {
    notify->signal();
  }
}

void ProgressiveAttachment::abandon() {
  std::lock_guard<std::mutex> g(mu_);
  closed_ = true;
  queued_.clear();
}

}  // namespace trpc
