// Progressive bodies — incremental huge payloads without buffering.
//
// Parity: brpc's ProgressiveAttachment
// (/root/reference/src/brpc/progressive_attachment.h:32 — the server
// responds headers immediately, then streams body pieces for as long as
// it likes) and ProgressiveReader (progressive_reader.h — the client
// consumes response pieces through a callback instead of accumulating).
// SURVEY §5 names these the long-context analogue: a 100GB body moves
// end-to-end under constant memory.
//
// This runtime's forms:
// - ProgressiveAttachment rides HTTP/1.1 chunked encoding: the handler
//   creates one from its Controller, calls done() (headers flush with
//   Transfer-Encoding: chunked), and keeps Write()ing from any fiber;
//   close() (or destruction) sends the terminating chunk.  Pipelined
//   requests on the connection wait until the attachment closes —
//   HTTP/1.1 responses cannot interleave.
// - ProgressiveReader rides the h2 client: DATA frames are handed to the
//   callback as they arrive instead of accumulating in the response
//   buffer.  (For tstd, streaming RPC with credit windows — net/stream.h
//   — is the first-class progressive path.)
#pragma once

#include <memory>
#include <mutex>
#include <string>

#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/socket.h"

namespace trpc {

class ProgressiveAttachment {
 public:
  ~ProgressiveAttachment() { close(); }

  // Appends one body piece (one HTTP chunk).  Pieces written before the
  // response headers flush are queued and ride the same write as the
  // headers.  Returns 0, or -1 after close()/connection failure.
  int Write(const IOBuf& data);

  // Sends the terminating chunk; idempotent.  The connection survives
  // (keep-alive) unless the request asked for close.
  void close();

  // -- internal (serving-path wiring) ----------------------------------
  // Binds the attachment to its connection AND writes `head` (the
  // response headers) followed by any queued pieces, all under the
  // attachment's lock — publishing the socket before the headers are on
  // the wire would let a concurrent Write()/close() put chunk bytes
  // ahead of the status line, and releasing the ordering latch early
  // would let a pipelined response overtake.  `on_closed` releases the
  // connection's response order when the attachment closes.
  void bind(SocketId sid, bool keep_alive,
            std::shared_ptr<CountdownEvent> on_closed, IOBuf&& head);

  // Serving-path discard (HEAD requests): headers went out alone; all
  // writes are dropped and close() becomes a no-op.
  void abandon();

 private:
  std::mutex mu_;
  SocketId sid_ = 0;  // 0 until bound
  bool keep_alive_ = true;
  bool closed_ = false;
  bool pre_closed_ = false;  // closed before headers flushed
  IOBuf queued_;             // chunk-framed pieces awaiting bind
  std::shared_ptr<CountdownEvent> on_closed_;
};

// Client-side consumer of a progressive response (h2: one callback per
// DATA frame).  Implementations must tolerate calls from the
// connection's read fiber; on_part returning false cancels the stream.
class ProgressiveReader {
 public:
  virtual ~ProgressiveReader() = default;
  virtual bool on_part(const IOBuf& piece) = 0;
  // Always called exactly once, after the last part (or on failure).
  virtual void on_done(int error_code, const std::string& error_text) = 0;
};

}  // namespace trpc
