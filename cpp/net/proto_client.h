// Shared scaffold for the per-protocol clients (redis/thrift/memcache/
// nshead/esp/legacy-pbrpc): lazy-connecting pinned socket + typed
// per-connection parse state.  One implementation of the
// reconnect-while-failed and install-before-first-byte logic instead of
// a hand-kept copy per client.
#pragma once

#include <functional>
#include <memory>
#include <string>

#include "base/endpoint.h"
#include "net/messenger.h"
#include "net/socket.h"
#include "net/tls.h"

namespace trpc {

// Returns the connection's protocol-private state, installing a fresh
// `Conn` when absent or owned by another protocol.  `tag` identifies the
// owner (one static char per protocol).  Safe to call from the read
// fiber and from call sites that installed it before the first byte.
template <typename Conn>
Conn* proto_conn_of(Socket* s, const char* tag) {
  if (s->parse_state == nullptr || s->parse_state_owner != tag) {
    s->parse_state = std::make_shared<Conn>();
    s->parse_state_owner = tag;
  }
  return static_cast<Conn*>(s->parse_state.get());
}

// One lazily-connected socket bound to a protocol index.  Callers
// serialize ensure() under their own mutex (they also allocate ids /
// sequence numbers under it).
class ClientSocket {
 public:
  // Resolves the address; 0 on success.
  int Init(const std::string& addr) {
    return hostname2endpoint(addr.c_str(), &ep_);
  }
  const EndPoint& endpoint() const { return ep_; }

  // Future sockets handshake TLS first (https client path; ssl_helper
  // client-side parity).  `alpn_wire`: RFC 7301 list to advertise;
  // `sni_host`: server_name to send (IP literals filtered downstream).
  // Returns 0, or -1 when libssl is unavailable.
  int EnableTls(const std::string& alpn_wire = "",
                const std::string& sni_host = "") {
    std::string err;
    tls_ctx_ = tls_client_ctx(&err);
    if (tls_ctx_ == nullptr) {
      return -1;
    }
    alpn_ = alpn_wire;
    sni_ = sni_host;
    return 0;
  }

  // Fills *out with a live socket id, creating a fresh socket (lazy
  // connect in the write fiber) when absent or failed.  `pinned_index`
  // is the client protocol to pin; `install` runs on a fresh socket
  // while it is still single-threaded (install parse state, send an
  // auth preamble, ...).  Returns 0 on success.
  int ensure(int pinned_index,
             const std::function<int(Socket*)>& install, SocketId* out) {
    Socket* s = Socket::Address(sock_);
    if (s != nullptr) {
      if (!s->Failed()) {
        *out = sock_;
        s->Dereference();
        return 0;
      }
      s->Dereference();
    }
    Socket::Options sopts;
    sopts.fd = -1;  // lazy connect in the write fiber
    sopts.remote = ep_;
    sopts.on_readable = &messenger_on_readable;
    if (tls_ctx_ != nullptr) {
      sopts.transport = tls_transport();
      sopts.transport_ctx_holder = tls_conn_client(tls_ctx_, alpn_, sni_);
    }
    if (Socket::Create(sopts, &sock_) != 0) {
      return -1;
    }
    SocketRef fresh(Socket::Address(sock_));
    if (!fresh) {
      return -1;
    }
    fresh->pinned_protocol = pinned_index;
    if (install && install(fresh.get()) != 0) {
      fresh->SetFailed(ECONNABORTED);
      return -1;
    }
    *out = sock_;
    return 0;
  }

  // Fails the current socket (client destructors).
  void Shutdown() {
    SocketRef s(Socket::Address(sock_));
    if (s) {
      s->SetFailed(ESHUTDOWN);
    }
  }

 private:
  EndPoint ep_;
  SocketId sock_ = 0;
  void* tls_ctx_ = nullptr;  // leaked-singleton SSL_CTX when TLS enabled
  std::string alpn_;
  std::string sni_;
};

}  // namespace trpc
