#include "net/protocol.h"

#include "base/compress.h"
#include "base/time.h"

#include <cstring>
#include <mutex>
#include <vector>

#include "base/logging.h"
#include "net/socket.h"
#include "stat/capture.h"

namespace trpc {

namespace {

// Fixed-capacity registry: entries are address-stable for the lifetime of
// the process, so hot-path Protocol* caches can never dangle on a
// concurrent registration (a growing vector would reallocate).
constexpr int kMaxProtocols = 16;
std::mutex& proto_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
Protocol g_protocols[kMaxProtocols];
std::atomic<int> g_proto_count{0};

// -- little-endian scalar helpers ----------------------------------------

void put_u32(std::string* s, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);
  s->append(b, 4);
}

void put_u64(std::string* s, uint64_t v) {
  char b[8];
  memcpy(b, &v, 8);
  s->append(b, 8);
}

uint32_t get_u32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

uint64_t get_u64(const char* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

constexpr char kMagic[4] = {'T', 'R', 'P', '1'};
constexpr size_t kHeaderLen = 4 + 4 + 8;  // magic | meta_len | payload_len

std::string encode_meta(const RpcMeta& m) {
  std::string s;
  s.push_back(static_cast<char>(m.type));
  put_u64(&s, m.correlation_id);
  put_u32(&s, static_cast<uint32_t>(m.error_code));
  put_u32(&s, m.attachment_size);
  put_u64(&s, m.stream_id);
  s.push_back(static_cast<char>(m.stream_flags));
  put_u64(&s, m.ack_bytes);
  put_u32(&s, static_cast<uint32_t>(m.method.size()));
  s.append(m.method);
  put_u32(&s, static_cast<uint32_t>(m.error_text.size()));
  s.append(m.error_text);
  // Optional tail, only when any of its fields is active: decoders treat
  // it as length-gated (they only look past error_text when bytes
  // remain), so presence/absence are both wire-compatible — and the
  // streaming hot path never pays for it.  Layout: trace(24B), then
  // compress+checksum(6B), then batch streams(4B+), then stripe(24B),
  // then qos(3B+), then rma(52B), then deadline(8B); each later group
  // implies every earlier one.
  const bool has_deadline = m.deadline_us != 0;
  const bool has_rma =
      m.rma_rkey != 0 || m.rma_resp_rkey != 0 || has_deadline;
  const bool has_qos =
      m.qos_priority != 0 || !m.qos_tenant.empty() || has_rma;
  const bool has_stripe = m.stripe_id != 0 || has_qos;
  const bool has_streams = !m.extra_streams.empty() || has_stripe;
  const bool has_comp =
      m.compress_type != 0 || m.has_checksum || has_streams;
  if (m.trace_id != 0 || has_comp) {
    // tail-group 1 (trace): trace/span/parent ids, 24B.
    put_u64(&s, m.trace_id);
    put_u64(&s, m.span_id);
    put_u64(&s, m.parent_span_id);
    if (has_comp) {
      // tail-group 2 (compress): compress id + checksum presence/value, 6B.
      s.push_back(static_cast<char>(m.compress_type));
      s.push_back(m.has_checksum ? 1 : 0);
      put_u32(&s, m.checksum);
      if (has_streams) {
        // tail-group 3 (streams): batch stream offers (count + pairs).
        put_u32(&s, static_cast<uint32_t>(m.extra_streams.size()));
        for (const auto& [sid, window] : m.extra_streams) {
          put_u64(&s, sid);
          put_u64(&s, window);
        }
        if (has_stripe) {
          // tail-group 4 (stripe): large-message striping (net/stripe.h).
          put_u64(&s, m.stripe_id);
          put_u64(&s, m.stripe_offset);
          put_u64(&s, m.stripe_total);
          if (has_qos) {
            // tail-group 5 (qos): QoS tag (net/qos.h).  Tenant clamps to
            // the decoder's 64-byte cap HERE — the single choke point —
            // so an over-long name set through any surface (e.g. the
            // public Channel::Options field) truncates instead of
            // producing a frame the peer rejects as corrupt.
            s.push_back(static_cast<char>(m.qos_priority));
            const uint16_t tlen = static_cast<uint16_t>(
                m.qos_tenant.size() > 64 ? 64 : m.qos_tenant.size());
            s.push_back(static_cast<char>(tlen & 0xff));
            s.push_back(static_cast<char>(tlen >> 8));
            s.append(m.qos_tenant.data(), tlen);
            if (has_rma) {
              // tail-group 6 (rma): one-sided transfer descriptor +
              // response-landing advertisement (net/rma.h), 52B.
              put_u64(&s, m.rma_rkey);
              put_u64(&s, m.rma_off);
              put_u64(&s, m.rma_len);
              put_u32(&s, m.rma_chunk);
              put_u64(&s, m.rma_resp_rkey);
              put_u64(&s, m.rma_resp_max);
              put_u64(&s, m.rma_resp_off);
              if (has_deadline) {
                // tail-group 7 (deadline): remaining budget µs, 8B
                // (net/deadline.h).
                put_u64(&s, m.deadline_us);
              }
            }
          }
        }
      }
    }
  }
  return s;
}

bool decode_meta(const std::string& s, RpcMeta* m) {
  const char* p = s.data();
  const char* end = p + s.size();
  if (end - p < 1 + 8 + 4 + 4 + 8 + 1 + 8 + 4) {
    return false;
  }
  m->type = static_cast<RpcMeta::Type>(*p++);
  m->correlation_id = get_u64(p);
  p += 8;
  m->error_code = static_cast<int32_t>(get_u32(p));
  p += 4;
  m->attachment_size = get_u32(p);
  p += 4;
  m->stream_id = get_u64(p);
  p += 8;
  m->stream_flags = static_cast<uint8_t>(*p++);
  m->ack_bytes = get_u64(p);
  p += 8;
  const uint32_t mlen = get_u32(p);
  p += 4;
  // 64-bit arithmetic: mlen near UINT32_MAX must not wrap the bound check.
  if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(mlen) + 4) {
    return false;
  }
  m->method.assign(p, mlen);
  p += mlen;
  const uint32_t elen = get_u32(p);
  p += 4;
  if (static_cast<uint64_t>(end - p) < static_cast<uint64_t>(elen)) {
    return false;
  }
  m->error_text.assign(p, elen);
  p += elen;
  if (end - p >= 24) {  // tail-group 1 (trace)
    m->trace_id = get_u64(p);
    m->span_id = get_u64(p + 8);
    m->parent_span_id = get_u64(p + 16);
    p += 24;
    if (end - p >= 6) {  // tail-group 2 (compress)
      m->compress_type = static_cast<uint8_t>(*p++);
      m->has_checksum = *p++ != 0;
      m->checksum = get_u32(p);
      p += 4;
      if (end - p >= 4) {  // tail-group 3 (streams)
        const uint32_t count = get_u32(p);
        p += 4;
        if (count > 256 ||
            static_cast<uint64_t>(end - p) < count * 16ull) {
          return false;
        }
        m->extra_streams.reserve(count);
        for (uint32_t i = 0; i < count; ++i) {
          m->extra_streams.emplace_back(get_u64(p), get_u64(p + 8));
          p += 16;
        }
        if (end - p >= 24) {  // tail-group 4 (stripe)
          m->stripe_id = get_u64(p);
          m->stripe_offset = get_u64(p + 8);
          m->stripe_total = get_u64(p + 16);
          p += 24;
          if (end - p >= 3) {  // tail-group 5 (qos)
            m->qos_priority = static_cast<uint8_t>(*p++);
            const uint16_t tlen =
                static_cast<uint16_t>(static_cast<uint8_t>(p[0])) |
                (static_cast<uint16_t>(static_cast<uint8_t>(p[1])) << 8);
            p += 2;
            if (tlen > 64 ||
                static_cast<uint64_t>(end - p) < static_cast<uint64_t>(tlen)) {
              return false;
            }
            m->qos_tenant.assign(p, tlen);
            p += tlen;
            if (end - p >= 44) {  // tail-group 6 (rma)
              m->rma_rkey = get_u64(p);
              m->rma_off = get_u64(p + 8);
              m->rma_len = get_u64(p + 16);
              m->rma_chunk = get_u32(p + 24);
              m->rma_resp_rkey = get_u64(p + 28);
              m->rma_resp_max = get_u64(p + 36);
              if (end - p >= 52) {
                m->rma_resp_off = get_u64(p + 44);
                p += 52;
                if (end - p >= 8) {  // tail-group 7 (deadline)
                  m->deadline_us = get_u64(p);
                  p += 8;
                }
              } else {
                // Previous-version frame (44B group, pre-rma_resp_off):
                // the descriptor is intact, the landing offset defaults
                // to the region start — mixed-version one-sided traffic
                // keeps working across a rolling upgrade.
                m->rma_resp_off = 0;
                p += 44;
              }
            }
          }
        }
      }
    }
  }
  return true;
}

ParseError tstd_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  // Reject a wrong magic as soon as the available prefix disagrees, so the
  // messenger can offer the bytes to other protocols without waiting.
  char header[kHeaderLen];
  const size_t avail = source->copy_to(header, kHeaderLen);
  if (memcmp(header, kMagic, std::min<size_t>(avail, 4)) != 0) {
    return ParseError::kTryOtherProtocol;
  }
  if (avail < kHeaderLen) {
    return ParseError::kNotEnoughData;
  }
  const uint32_t meta_len = get_u32(header + 4);
  const uint64_t payload_len = get_u64(header + 8);
  if (meta_len > 64 * 1024 * 1024 || payload_len > (1ull << 40)) {
    return ParseError::kCorrupted;
  }
  if (source->size() < kHeaderLen + meta_len + payload_len) {
    // Bulk-read hint: the frame length is known, so the messenger can
    // read the remainder into a few LARGE blocks (one readv iovec each)
    // instead of 8KB slivers — under gVisor-style kernels the per-iovec
    // cost is what caps large-message goodput.
    if (sock != nullptr) {
      sock->read_block_hint =
          kHeaderLen + meta_len + payload_len - source->size();
    }
    return ParseError::kNotEnoughData;
  }
  if (sock != nullptr) {
    sock->read_block_hint = 0;
  }
  source->pop_front(kHeaderLen);
  std::string meta_bytes;
  {
    IOBuf meta_buf;
    source->cutn(&meta_buf, meta_len);
    meta_bytes = meta_buf.to_string();
  }
  if (!decode_meta(meta_bytes, &out->meta)) {
    return ParseError::kCorrupted;
  }
  if (out->meta.deadline_us != 0 || capture::enabled()) {
    // Anchor the relative budget to OUR clock at cut time: queueing
    // (QoS lanes, dispatch backlog) then counts against it.  Unstamped
    // traffic skips the clock read — unless traffic capture is on,
    // which needs a parse-time arrival for every request so recorded
    // queue time and inter-arrival gaps are honest.
    out->arrival_us = monotonic_time_us();
  }
  source->cutn(&out->payload, payload_len);
  if (out->meta.has_checksum &&
      crc32c(out->payload) != out->meta.checksum) {
    // The transport delivered different bytes than were sent: the
    // connection's framing can no longer be trusted.
    return ParseError::kCorrupted;
  }
  return ParseError::kOk;
}

}  // namespace

void tstd_pack(IOBuf* out, const RpcMeta& meta, const IOBuf& payload) {
  const std::string meta_bytes = encode_meta(meta);
  std::string header;
  header.append(kMagic, 4);
  put_u32(&header, static_cast<uint32_t>(meta_bytes.size()));
  put_u64(&header, payload.size());
  out->append(header);
  out->append(meta_bytes);
  out->append(payload);  // zero-copy block share
}

int register_protocol(const Protocol& p) {
  std::lock_guard<std::mutex> g(proto_mu());
  const int n = g_proto_count.load(std::memory_order_relaxed);
  if (n >= kMaxProtocols) {
    return -1;
  }
  g_protocols[n] = p;
  g_proto_count.store(n + 1, std::memory_order_release);
  return n;
}

const Protocol* protocol_at(int index) {
  if (index < 0 || index >= g_proto_count.load(std::memory_order_acquire)) {
    return nullptr;
  }
  return &g_protocols[index];
}

int protocol_count() {
  return g_proto_count.load(std::memory_order_acquire);
}

// process_request / process_response are installed by server.cc/channel.cc.
void tstd_process_request(InputMessage&& msg);
void tstd_process_response(InputMessage&& msg);

const Protocol& tstd_protocol() {
  static Protocol p = {"tstd", tstd_parse, tstd_process_request,
                       tstd_process_response};
  static int registered = register_protocol(p);
  (void)registered;
  return p;
}

}  // namespace trpc
