// Protocol registry + the default framed protocol ("tstd").
//
// Parity: brpc's Protocol vtable + registry (/root/reference/src/brpc/
// protocol.h:77-186) and the baidu_std wire format (policy/
// baidu_rpc_protocol.cpp: 12-byte "PRPC" header + pb RpcMeta).  Re-designed
// wire: magic "TRP1" | meta_len u32 | payload_len u64, meta is a hand-rolled
// little-endian TLV (no protobuf dependency in the runtime) carrying type,
// correlation id, method, error code/text, attachment split.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <utility>
#include <vector>

#include "base/iobuf.h"

namespace trpc {

class Socket;
using SocketId = uint64_t;

enum class ParseError : int {
  kOk = 0,
  kNotEnoughData = 1,   // keep bytes, wait for more
  kTryOtherProtocol = 2,
  kCorrupted = 3,       // kill the connection
};

struct RpcMeta {
  enum Type : uint8_t {
    kRequest = 0,
    kResponse = 1,
    kStreamFrame = 2,
    // Connection-scoped credential, sent as the FIRST frame (auth.h).
    kAuth = 3,
    // Large-message striping (net/stripe.h): one chunk of a payload that
    // was cut into K concurrent frames.  correlation_id carries the
    // stripe id; the chunk lands at stripe_offset of a stripe_total-byte
    // reassembly buffer.  May arrive on ANY connection between the two
    // processes (multi-rail), in any order.
    kStripe = 4,
    // Cascading-cancel control frame (net/deadline.h): correlation_id
    // names the in-flight REQUEST to cancel on the receiving server —
    // its cancel scope fans out to every downstream call and transfer
    // the handler started.  Empty payload; never answered (the caller
    // already gave up on the call).
    kCancel = 5,
  };
  // Stream flags (parity: streaming_rpc_meta.proto frame types).
  enum StreamFlags : uint8_t {
    kStreamData = 0,
    kStreamClose = 1,
    kStreamAck = 2,  // ack_bytes reopens the sender's credit window
  };
  Type type = kRequest;
  uint64_t correlation_id = 0;
  int32_t error_code = 0;
  uint32_t attachment_size = 0;  // trailing bytes of payload
  // Streaming: a request/response carrying stream_id offers/accepts a
  // stream (stream settings piggyback, baidu_rpc_protocol.cpp:633 parity);
  // a kStreamFrame addresses the RECEIVER's stream id.
  uint64_t stream_id = 0;
  uint8_t stream_flags = 0;
  uint64_t ack_bytes = 0;
  // Batch stream establishment (StreamIds parity, ref stream.h:114):
  // further (stream_id, window) offers/acceptances beyond the first,
  // index-aligned between request and response.  Optional wire tail.
  std::vector<std::pair<uint64_t, uint64_t>> extra_streams;
  // rpcz trace context (span.h parity: trace_id/span_id/parent propagate
  // inside the meta like the reference's RpcMeta).  Optional wire tail —
  // absent (zero) when the peer predates it or rpcz is off.
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_span_id = 0;
  // Negotiated per call (policy/gzip_compress.* + crc32c_checksum.*
  // parity): payload compression id and crc32c over the on-wire payload
  // (0 = unchecked).  Ride the optional tail with the trace context.
  uint8_t compress_type = 0;
  bool has_checksum = false;  // presence flag: a zero CRC is still a CRC
  uint32_t checksum = 0;
  // Large-message striping (net/stripe.h).  On a HEAD frame
  // (kRequest/kResponse): stripe_id != 0 announces that only the first
  // chunk rides this frame and stripe_total payload bytes follow across
  // kStripe frames sharing the id.  On a kStripe chunk: the payload
  // lands at [stripe_offset, stripe_offset+len) of the reassembly
  // buffer.  Zero everywhere on the (sub-threshold) hot path — the
  // fourth optional wire-tail group, absent from small frames.
  uint64_t stripe_id = 0;
  uint64_t stripe_offset = 0;
  uint64_t stripe_total = 0;
  // QoS tag (net/qos.h): priority class (0 = highest lane; also the
  // default, so untagged traffic rides the top lane when lanes are on)
  // and the tenant the request bills to (per-tenant weighted-fair
  // dequeue + admission control).  Fifth optional wire-tail group —
  // absent (zero/empty) on untagged traffic, so the default hot path
  // never pays for it.
  uint8_t qos_priority = 0;
  std::string qos_tenant;
  // One-sided RMA (net/rma.h).  On a control frame (kRequest/kResponse
  // with rma_rkey != 0 and an EMPTY payload): the body landed
  // out-of-band — rma_len bytes written by the sender into the named
  // registered region at rma_off of its data area (kRmaDirectOff = the
  // region's own data start, completion bitmap in the region header),
  // in rma_chunk-sized chunks whose release-fenced completion bits the
  // receiver verifies before dispatch.  rma_resp_rkey/rma_resp_max on a
  // REQUEST advertise the caller's registered landing region so the
  // response can be put straight into the caller's buffer, rma_resp_off
  // bytes into its data area (collective pulls land a shard mid-region;
  // 0 = the region start, the batch-plane shape).  Sixth optional
  // wire-tail group — all-zero (absent) on every non-rma frame.
  uint64_t rma_rkey = 0;
  uint64_t rma_off = 0;
  uint64_t rma_len = 0;
  uint32_t rma_chunk = 0;
  uint64_t rma_resp_rkey = 0;
  uint64_t rma_resp_max = 0;
  uint64_t rma_resp_off = 0;
  // End-to-end deadline (net/deadline.h): the caller's REMAINING budget
  // in µs at send time (relative, so clock skew between hosts never
  // corrupts it; the receiver anchors it to its own arrival clock).
  // Seventh optional wire-tail group — zero (absent) when the caller
  // has no deadline, so unset traffic stays byte-identical.
  uint64_t deadline_us = 0;
  std::string method;
  std::string error_text;

  // Back to defaults, RETAINING string/vector capacity (the pooled
  // InputMessage reuse path; a fresh `= RpcMeta{}` would free it).
  void reset() {
    type = kRequest;
    correlation_id = 0;
    error_code = 0;
    attachment_size = 0;
    stream_id = 0;
    stream_flags = 0;
    ack_bytes = 0;
    extra_streams.clear();
    trace_id = 0;
    span_id = 0;
    parent_span_id = 0;
    compress_type = 0;
    has_checksum = false;
    checksum = 0;
    stripe_id = 0;
    stripe_offset = 0;
    stripe_total = 0;
    qos_priority = 0;
    qos_tenant.clear();
    rma_rkey = 0;
    rma_off = 0;
    rma_len = 0;
    rma_chunk = 0;
    rma_resp_rkey = 0;
    rma_resp_max = 0;
    rma_resp_off = 0;
    deadline_us = 0;
    method.clear();
    error_text.clear();
  }
};

struct InputMessage {
  RpcMeta meta;
  IOBuf payload;  // body (+ attachment tail per meta.attachment_size)
  SocketId socket = 0;
  // Arrival clock of a deadline-stamped request, read at parse (cut)
  // time: the server's absolute deadline is arrival_us + deadline_us,
  // so time spent queued in a QoS lane counts against the budget.  0 on
  // unstamped traffic — the hot path never reads the clock for it.
  int64_t arrival_us = 0;
  // Protocol-private context (the reference subclasses InputMessageBase per
  // protocol; an opaque pointer is the condensed seam).  HTTP stores its
  // parsed HttpRequest here.
  std::shared_ptr<void> ctx;
};

struct Protocol {
  const char* name;
  // Cuts ONE complete message off `source` (or reports NotEnoughData).
  // `sock` may be null (protocol unit tests); parsers use it only for
  // incremental state (Socket::parse_state).
  ParseError (*parse)(IOBuf* source, InputMessage* out, Socket* sock);
  // Server side: handle a request message (runs in its own fiber).
  void (*process_request)(InputMessage&& msg);
  // Client side: handle a response message.
  void (*process_response)(InputMessage&& msg);
  // True for protocols WITHOUT correlation ids (HTTP/1.1): messages on one
  // connection are processed in order in the read fiber so responses stay
  // FIFO; tstd dispatches each message to its own fiber instead.
  bool process_in_order = false;
};

// Registry (parity: RegisterProtocol, protocol.h:186).  Index is pinned on
// the socket after first successful parse.
int register_protocol(const Protocol& p);
const Protocol* protocol_at(int index);
int protocol_count();

// The default framed protocol; registered on first use by Server/Channel.
const Protocol& tstd_protocol();

// Helpers shared by server/channel: build one framed message.
void tstd_pack(IOBuf* out, const RpcMeta& meta, const IOBuf& payload);

}  // namespace trpc
