#include "net/qos.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>

#include "base/flags.h"
#include "base/logging.h"
#include "fiber/analysis.h"
#include "fiber/fiber.h"
#include "net/protocol.h"
#include "stat/timeline.h"
#include "stat/variable.h"

namespace trpc {

extern std::atomic<int64_t> g_socket_count;  // net/builtin.cc

namespace {

// ---- flags --------------------------------------------------------------

Flag* lanes_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_qos_lanes", 0,
        "active QoS priority lanes (0 = subsystem off; 2..4 routes tagged "
        "requests through weighted-fair dispatch lanes)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long n = strtol(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' &&
               (n == 0 || (n >= 2 && n <= kQosMaxLanes));
      });
    }
    return flag;
  }();
  return f;
}

bool valid_weights(const std::string& v) {
  int count = 0;
  const char* p = v.c_str();
  while (*p != '\0') {
    char* end = nullptr;
    const long w = strtol(p, &end, 10);
    if (end == p || w < 1 || w > 4096) {
      return false;
    }
    ++count;
    p = end;
    if (*p == ',') {
      ++p;
      if (*p == '\0') {
        return false;  // trailing comma
      }
    } else if (*p != '\0') {
      return false;
    }
  }
  return count >= 1 && count <= kQosMaxLanes;
}

Flag* weights_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_string(
        "trpc_qos_lane_weights", "8,4,2,1",
        "per-lane DRR weights, highest lane first (CSV; lanes beyond the "
        "list weigh 1)");
    if (flag != nullptr) {
      flag->set_validator(valid_weights);
    }
    return flag;
  }();
  return f;
}

// Eager definitions so /flags?setvalue can set them before first traffic.
[[maybe_unused]] Flag* const g_lanes_eager = lanes_flag();
[[maybe_unused]] Flag* const g_weights_eager = weights_flag();

void parse_weights(int64_t out[kQosMaxLanes]) {
  for (int i = 0; i < kQosMaxLanes; ++i) {
    out[i] = 1;
  }
  const std::string s = weights_flag()->string_value();
  const char* p = s.c_str();
  for (int i = 0; i < kQosMaxLanes && *p != '\0'; ++i) {
    char* end = nullptr;
    const long w = strtol(p, &end, 10);
    if (end == p) {
      break;  // validator keeps this unreachable; belt and braces
    }
    out[i] = w;
    p = *end == ',' ? end + 1 : end;
  }
}

// ---- tenant weight registry --------------------------------------------

std::mutex& weight_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}

std::map<std::string, int>& weight_map() {
  static auto* m = new std::map<std::string, int>();
  return *m;
}

// ---- lanes --------------------------------------------------------------

// Messages per unit of lane weight handed out each DRR round.  Small
// enough that a starved low lane waits at most one round's worth of
// higher-lane quanta, large enough to amortize the round bookkeeping.
constexpr int kQuantumUnit = 4;
constexpr size_t kQosDispatchBatch = 64;  // messenger's kDispatchBatch

struct Shard {
  std::mutex mu;
  std::deque<InputMessage*> q;
  // Max weight among tenants enqueued since the shard last drained empty:
  // the shard's DRR quantum inside its lane, so a weight-8 tenant's shard
  // is popped 8x per cursor pass of a weight-1 tenant's.
  int weight_hint = 1;
};

struct Lane {
  Shard shards[kQosLaneShards];
  std::atomic<int64_t> depth{0};
  // Drainer-owned DRR state (only the role holder touches these).
  int64_t deficit = 0;
  int cursor = 0;
  int credit = 0;
};

struct QosState {
  Lane lanes[kQosMaxLanes];
  std::atomic<bool> draining{false};
  std::atomic<bool> paused{false};
  std::atomic<void (*)(int, const std::string&)> tap{nullptr};
};

QosState& state() {
  static QosState* s = new QosState();
  return *s;
}

int64_t total_depth() {
  int64_t n = 0;
  for (Lane& lane : state().lanes) {
    // Acquire: pairs with the enqueue's depth increment so a drainer
    // deciding "empty" cannot miss a message already published.
    n += lane.depth.load(std::memory_order_acquire);
  }
  return n;
}

// Pops the next message of `lane` under its shard DRR (cursor advances
// after `credit = weight_hint` pops or when the shard empties).  Drainer
// role holder only.  nullptr when the whole lane is empty.
InputMessage* lane_pop(Lane& lane) {
  for (int scanned = 0; scanned < kQosLaneShards;) {
    Shard& sh = lane.shards[lane.cursor];
    std::unique_lock<std::mutex> g(sh.mu);
    if (sh.q.empty()) {
      sh.weight_hint = 1;  // decays once the backlog clears
      g.unlock();
      lane.cursor = (lane.cursor + 1) % kQosLaneShards;
      lane.credit = 0;
      ++scanned;
      continue;
    }
    if (lane.credit == 0) {
      lane.credit = sh.weight_hint;
    }
    InputMessage* m = sh.q.front();
    sh.q.pop_front();
    const bool emptied = sh.q.empty();
    g.unlock();
    if (--lane.credit == 0 || emptied) {
      lane.cursor = (lane.cursor + 1) % kQosLaneShards;
      lane.credit = 0;
    }
    lane.depth.fetch_sub(1, std::memory_order_acq_rel);
    return m;
  }
  return nullptr;
}

// Dispatch batch mirroring the messenger's bulk fiber spawn.  Messages
// the exhausted pool could not start are NOT run inline here — the
// caller holds the process-wide drainer role, and an inline handler that
// parks would wedge dispatch for every lane and socket at once.  They
// spill to `overflow` instead, processed by drive() AFTER the role is
// released (stalling only the one enqueuing fiber, exactly like the
// direct messenger path's exhaustion fallback).
struct QosBatch {
  void* args[kQosDispatchBatch];
  size_t n = 0;

  void flush(void (*process)(void*), std::vector<void*>* overflow) {
    if (n == 0) {
      return;
    }
    const size_t started = fiber_start_batch(process, args, n, 0);
    for (size_t i = started; i < n; ++i) {
      overflow->push_back(args[i]);
    }
    n = 0;
  }
};

// Pops one drainer acquisition may make before handing the role to a
// fresh fiber: the drainer runs INSIDE a read fiber's sweep, and without
// a budget one fiber could be pinned servicing the whole server's lanes
// while its own socket's remaining buffered frames go unparsed — the
// same head-of-line class trpc_messenger_cut_budget bounds on the
// direct path.
constexpr int64_t kDrainBudgetPops = 1024;

// Weighted-fair drain: DRR rounds across lanes (per-lane quantum = lane
// weight x kQuantumUnit; classic deficit reset when a lane runs dry)
// until every lane is empty, the pop budget is spent, or the test pause
// lands.  Returns false when it stopped on budget (backlog remains).
// Drainer role holder only.
bool drain_all(void (*process)(void*), std::vector<void*>* overflow) {
  QosState& st = state();
  int64_t weights[kQosMaxLanes];
  parse_weights(weights);
  QosVars& vars = qos_vars();
  QosBatch batch;
  int64_t budget = kDrainBudgetPops;
  bool any = true;
  // Acquire on paused/depth: the drainer must observe the test pause
  // flag and enqueue publications from other threads, not cached zeros.
  while (any && budget > 0 && !st.paused.load(std::memory_order_acquire)) {
    any = false;
    for (int i = 0; i < kQosMaxLanes; ++i) {
      Lane& lane = st.lanes[i];
      // Acquire: pairs with enqueue publication (see loop header).
      if (lane.depth.load(std::memory_order_acquire) == 0) {
        lane.deficit = 0;  // an idle lane accrues no credit (DRR)
        continue;
      }
      any = true;
      lane.deficit += weights[i] * kQuantumUnit;
      if (timeline::enabled()) {
        // a = lane | shard cursor at round start << 8; b = the DRR
        // quantum this round granted the lane.
        timeline::record(
            timeline::kQosDrain,
            static_cast<uint64_t>(i) |
                (static_cast<uint64_t>(lane.cursor) << 8),
            static_cast<uint64_t>(weights[i] * kQuantumUnit));
      }
      while (lane.deficit > 0) {
        InputMessage* m = lane_pop(lane);
        if (m == nullptr) {
          lane.deficit = 0;
          break;
        }
        --lane.deficit;
        --budget;
        vars.lane_dispatch[i] << 1;
        // Acquire: the test tap's callable must be fully constructed
        // before this drainer invokes it.
        auto tap = st.tap.load(std::memory_order_acquire);
        if (tap != nullptr) {
          tap(i, m->meta.qos_tenant);
        }
        batch.args[batch.n++] = m;
        if (batch.n == kQosDispatchBatch) {
          batch.flush(process, overflow);
        }
      }
    }
  }
  batch.flush(process, overflow);
  return budget > 0;
}

void drive(void (*process)(void*));

struct DrainHandoff {
  void (*process)(void*);
};

void drain_handoff_fiber(void* p) {
  std::unique_ptr<DrainHandoff> h(static_cast<DrainHandoff*>(p));
  drive(h->process);
}

// Claims the drainer role and drains; loops to close the race where a
// producer enqueued after the drain finished but saw the role taken.
// When an acquisition stops on its pop budget, the remaining backlog is
// handed to a FRESH fiber so the enqueuing read fiber gets back to its
// own socket's sweep (on fiber-pool exhaustion it keeps draining here —
// slow beats stranded).
void drive(void (*process)(void*)) {
  QosState& st = state();
  for (;;) {
    if (st.paused.load(std::memory_order_acquire)) {
      return;
    }
    if (st.draining.exchange(true, std::memory_order_acq_rel)) {
      return;  // current drainer will observe our message
    }
    std::vector<void*> overflow;
    bool finished;
    {
      // The drainer role is process-wide: a park while holding it wedges
      // every lane and socket at once — dispatch scope for the analysis
      // blocking detector (ISSUE 7).
      analysis::ScopedDispatch scope("qos drainer role");
      finished = drain_all(process, &overflow);
    }
    st.draining.store(false, std::memory_order_release);
    // Pool-exhaustion stragglers run AFTER the role release: a parking
    // handler now stalls only this fiber, never global lane dispatch.
    for (void* m : overflow) {
      process(m);
    }
    if (st.paused.load(std::memory_order_acquire) ||
        total_depth() == 0) {
      return;
    }
    if (!finished) {
      auto* h = new DrainHandoff{process};
      if (fiber_start(nullptr, drain_handoff_fiber, h, 0) == 0) {
        return;
      }
      delete h;
    }
  }
}

size_t shard_for(const std::string& tenant) {
  if (tenant.empty()) {
    // Untagged traffic round-robins so it cannot collapse onto (and then
    // monopolize) a single shard.
    static thread_local uint32_t rr = 0;
    return (rr++) % kQosLaneShards;
  }
  return std::hash<std::string>{}(tenant) % kQosLaneShards;
}

}  // namespace

int qos_lane_count() {
  const int64_t n = lanes_flag()->int64_value();
  return n >= 2 ? static_cast<int>(n) : 0;
}

int qos_lane_for(uint8_t priority, int lanes) {
  if (lanes <= 0) {
    return 0;
  }
  return priority >= lanes ? lanes - 1 : priority;
}

void qos_enqueue(int lane_idx, const std::string& tenant, InputMessage* msg,
                 void (*process)(void*)) {
  if (lane_idx < 0 || lane_idx >= kQosMaxLanes) {
    lane_idx = kQosMaxLanes - 1;
  }
  Lane& lane = state().lanes[lane_idx];
  Shard& sh = lane.shards[shard_for(tenant)];
  const int w = qos_tenant_weight(tenant);
  {
    std::lock_guard<std::mutex> g(sh.mu);
    sh.q.push_back(msg);
    if (w > sh.weight_hint) {
      sh.weight_hint = w;
    }
  }
  lane.depth.fetch_add(1, std::memory_order_acq_rel);
  qos_vars().enqueued << 1;
  drive(process);
}

int64_t qos_lane_depth(int lane) {
  if (lane < 0 || lane >= kQosMaxLanes) {
    return 0;
  }
  // Acquire: vars/tests reading depth pair with enqueue publication.
  return state().lanes[lane].depth.load(std::memory_order_acquire);
}

void qos_set_tenant_weight(const std::string& tenant, int weight) {
  weight = weight < 1 ? 1 : (weight > 1024 ? 1024 : weight);
  std::lock_guard<std::mutex> g(weight_mu());
  weight_map()[tenant] = weight;
}

int qos_tenant_weight(const std::string& tenant) {
  if (tenant.empty()) {
    return 1;
  }
  std::lock_guard<std::mutex> g(weight_mu());
  auto it = weight_map().find(tenant);
  return it != weight_map().end() ? it->second : 1;
}

void qos_test_pause(bool paused) {
  state().paused.store(paused, std::memory_order_release);
}

void qos_test_tap(void (*tap)(int, const std::string&)) {
  state().tap.store(tap, std::memory_order_release);
}

void qos_test_drive(void (*process)(void*)) { drive(process); }

// ---- vars ---------------------------------------------------------------

QosVars::QosVars() {
  enqueued.expose("qos_enqueue_total",
                  "requests routed through the QoS priority lanes");
  shed_total.expose(
      "qos_shed_total",
      "requests shed by per-tenant admission control (kEOverloaded)");
  for (int i = 0; i < kQosMaxLanes; ++i) {
    // No "_total" here: the Prometheus renderer appends it to counters.
    lane_dispatch[i].expose(
        "qos_lane_dispatch_" + std::to_string(i),
        "requests dispatched from QoS lane " + std::to_string(i));
    lane_depth.push_back(std::make_unique<PassiveStatus<long>>(
        [i] { return static_cast<long>(qos_lane_depth(i)); }));
    lane_depth.back()->expose(
        "qos_lane_depth_" + std::to_string(i),
        "requests currently queued in QoS lane " + std::to_string(i));
  }
  live_sockets = std::make_unique<PassiveStatus<long>>([] {
    // Relaxed: a monotonic-ish diagnostic gauge — off-by-a-few during a
    // churn burst is fine, no data hangs off the count.
    return static_cast<long>(
        g_socket_count.load(std::memory_order_relaxed));
  });
  live_sockets->expose(
      "rpc_socket_live",
      "live sockets in the socket map (the 100k-connection front door's "
      "memory driver; pair with process_memory_rss_kb)");
}

QosVars& qos_vars() {
  static QosVars* v = new QosVars();
  return *v;
}

void expose_qos_variables() { qos_vars(); }

// ---- TenantGovernor -----------------------------------------------------

namespace {

bool valid_tenant_name(const std::string& s) {
  if (s.empty() || s.size() > 64) {
    return false;
  }
  for (char c : s) {
    const bool ok = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
                    (c >= '0' && c <= '9') || c == '_' || c == '-' ||
                    c == '.' || c == '*';
    if (!ok) {
      return false;
    }
  }
  return true;
}

std::string var_safe(const std::string& tenant) {
  if (tenant == "*") {
    return "default";  // "qos_tenant__" would be unreadable in /vars
  }
  std::string s = tenant;
  for (char& c : s) {
    if (!((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
          (c >= '0' && c <= '9') || c == '_')) {
      c = '_';
    }
  }
  return s;
}

// Servers are long-lived but tests create many: suffix duplicate names
// like observe.py's unique_var_name so a second governor's recorder never
// shadows the first's series.
std::string unique_name(const std::string& base) {
  std::string probe;
  std::string name = base;
  for (int i = 2; Variable::read_exposed(name, &probe); ++i) {
    name = base + "_" + std::to_string(i);
  }
  return name;
}

}  // namespace

std::shared_ptr<TenantGovernor> TenantGovernor::parse(
    const std::string& spec, std::string* err) {
  err->clear();
  if (spec.empty()) {
    return nullptr;
  }
  auto gov = std::make_shared<TenantGovernor>();
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t end = spec.find(';', pos);
    if (end == std::string::npos) {
      end = spec.size();
    }
    const std::string clause = spec.substr(pos, end - pos);
    pos = end + 1;
    if (clause.empty()) {
      continue;
    }
    const size_t colon = clause.find(':');
    if (colon == std::string::npos) {
      *err = "clause missing ':': " + clause;
      return nullptr;
    }
    auto entry = std::make_unique<Entry>();
    entry->name = clause.substr(0, colon);
    if (!valid_tenant_name(entry->name)) {
      *err = "bad tenant name: " + entry->name;
      return nullptr;
    }
    // key=val pairs.
    size_t kp = colon + 1;
    while (kp < clause.size()) {
      size_t ke = clause.find(',', kp);
      if (ke == std::string::npos) {
        ke = clause.size();
      }
      const std::string kv = clause.substr(kp, ke - kp);
      kp = ke + 1;
      if (kv.empty()) {
        continue;
      }
      const size_t eq = kv.find('=');
      if (eq == std::string::npos) {
        *err = "bad key=val: " + kv;
        return nullptr;
      }
      const std::string key = kv.substr(0, eq);
      const std::string val = kv.substr(eq + 1);
      if (key == "weight") {
        char* wend = nullptr;
        const long w = strtol(val.c_str(), &wend, 10);
        if (wend == val.c_str() || *wend != '\0' || w < 1 || w > 1024) {
          *err = "bad weight: " + val;
          return nullptr;
        }
        entry->weight = static_cast<int>(w);
      } else if (key == "limit") {
        auto [ok, limiter] = parse_concurrency_spec(val);
        if (!ok) {
          *err = "bad limit spec: " + val;
          return nullptr;
        }
        entry->limiter = std::move(limiter);
      } else {
        *err = "unknown key: " + key;
        return nullptr;
      }
    }
    const std::string base = "qos_tenant_" + var_safe(entry->name);
    entry->latency = std::make_shared<LatencyRecorder>();
    entry->latency->expose(
        unique_name(base),
        "per-tenant QoS latency/qps of tenant '" + entry->name + "'");
    entry->shed = std::make_shared<Adder>();
    entry->shed->expose(
        unique_name(base + "_shed_total"),
        "requests shed for tenant '" + entry->name + "' by admission "
        "control");
    if (entry->name == "*") {
      gov->default_entry_ = entry.get();
    }
    gov->entries_.push_back(std::move(entry));
  }
  if (gov->entries_.empty()) {
    *err = "empty spec";
    return nullptr;
  }
  // Weights land in the process-global registry (the weighted-fair
  // dequeue reads it at enqueue time) only once the WHOLE spec
  // validated — a rejected spec must not leave half its weights behind.
  // The registry is process-global by design (the messenger has no
  // server context at enqueue time): governors on two servers sharing a
  // tenant name share its weight, last SetQos wins.
  for (const auto& e : gov->entries_) {
    if (e->name != "*") {
      qos_set_tenant_weight(e->name, e->weight);
    }
  }
  return gov;
}

TenantGovernor::Entry* TenantGovernor::find(const std::string& tenant) {
  if (!tenant.empty()) {
    for (const auto& e : entries_) {
      if (e->name == tenant) {
        return e.get();
      }
    }
  }
  return default_entry_;
}

TenantGovernor::Entry* TenantGovernor::admit(const std::string& tenant,
                                             bool* admitted) {
  Entry* e = find(tenant);
  if (e == nullptr) {
    *admitted = true;  // no clause: unlimited
    return nullptr;
  }
  if (e->limiter != nullptr && !e->limiter->on_request()) {
    *e->shed << 1;
    qos_vars().shed_total << 1;
    *admitted = false;
    return e;
  }
  *admitted = true;
  return e;
}

void TenantGovernor::on_response(Entry* e, int64_t latency_us, bool error) {
  if (e == nullptr) {
    return;
  }
  if (e->limiter != nullptr) {
    e->limiter->on_response(latency_us, error);
  }
  if (latency_us > 0) {
    *e->latency << latency_us;
  }
}

}  // namespace trpc
