// QoS — per-tenant quality of service: priority dispatch lanes with
// weighted-fair (deficit round-robin) dequeue, and per-tenant admission
// control wrapping the concurrency limiters.
//
// No direct brpc parity: the reference stops at a per-method
// ConcurrencyLimiter (concurrency_limiter.h) and a global
// -max_concurrency.  This subsystem is the "framework owns isolation"
// argument of "RPC Considered Harmful" (PAPERS.md) made concrete: the
// messenger routes tagged requests into N priority lanes drained by DRR
// over per-lane shard queues (tenants hash to shards, shard quanta scale
// with tenant weight), and a per-Server TenantGovernor admits or sheds
// each request against its tenant's own limiter BEFORE the handler runs,
// answering rejects with kEOverloaded — a status the cluster client's
// retry/hedging/quarantine machinery routes around.
//
// Everything here is OFF by default: with trpc_qos_lanes=0 and no
// governor installed, the hot path reads one flag per sweep and is
// otherwise byte-identical to the pre-QoS pipeline.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "net/concurrency_limiter.h"
#include "stat/latency_recorder.h"
#include "stat/reducer.h"

namespace trpc {

struct InputMessage;

// Lanes are priority classes: lane 0 is served with the largest DRR
// quantum (highest priority), lane kQosMaxLanes-1 the smallest.  A
// request's wire tag `qos_priority` IS its lane index (clamped).
constexpr int kQosMaxLanes = 4;
// Tenant-hash shard queues per lane: tenants map stably to shards, so a
// flooding tenant fills ITS shard while the round-robin drain keeps
// serving the others (approximate per-tenant fairness inside one lane;
// exact when tenants hash apart, which the weighted quanta then scale).
constexpr int kQosLaneShards = 8;

// Number of active lanes: the validated reloadable flag trpc_qos_lanes
// (0 = subsystem disabled, the default; 2..kQosMaxLanes enables).
int qos_lane_count();
// Lane for a wire priority tag under `lanes` active lanes (clamped into
// [0, lanes-1]).
int qos_lane_for(uint8_t priority, int lanes);

// Enqueues a parsed server-bound request into its lane and drives the
// weighted-fair drain (the enqueuing read fiber becomes the drainer when
// the role is free).  Takes ownership of `msg`; `process` consumes and
// frees it, running on a fiber (fiber_start_batch) exactly like the
// messenger's direct dispatch path.
void qos_enqueue(int lane, const std::string& tenant, InputMessage* msg,
                 void (*process)(void*));

// Live queued depth of one lane (test + /vars surface).
int64_t qos_lane_depth(int lane);

// Process-global tenant weight registry feeding the shard DRR quanta
// (installed by TenantGovernor::parse / tests).  Weight clamps to
// [1, 1024]; unknown tenants weigh 1.
void qos_set_tenant_weight(const std::string& tenant, int weight);
int qos_tenant_weight(const std::string& tenant);

// ---- test hooks ---------------------------------------------------------
// Pause suspends the drain (enqueues accumulate) so ordering tests can
// stage a backlog; resume with pause(false) then qos_test_drive.
void qos_test_pause(bool paused);
// Tap observes each message at POP time (drainer-ordered, pre-fiber):
// the deterministic view of the weighted-fair dequeue order.
void qos_test_tap(void (*tap)(int lane, const std::string& tenant));
// Drives a drain round from a test (same body the enqueue path runs).
void qos_test_drive(void (*process)(void*));

// ---- stat vars ----------------------------------------------------------
struct QosVars {
  Adder enqueued;                      // qos_enqueue_total
  Adder shed_total;                    // admission rejects, all tenants
  Adder lane_dispatch[kQosMaxLanes];   // qos_lane_dispatch_total_<i>
  std::vector<std::unique_ptr<PassiveStatus<long>>> lane_depth;  // gauges
  std::unique_ptr<PassiveStatus<long>> live_sockets;  // socket-map size
  QosVars();
};
QosVars& qos_vars();
// Idempotent registration (Server::Start calls it like the hotpath vars).
void expose_qos_variables();

// ---- per-tenant admission control ---------------------------------------
// One governor per Server (Server::SetQos).  Spec grammar, ';'-separated
// tenant clauses:
//
//   <tenant>:key=val[,key=val...]
//     weight=N          DRR shard quantum scale (1..1024, default 1)
//     limit=<spec>      concurrency_limiter.h grammar: "<N>" | "auto" |
//                       "timeout:<MS>" (absent = unlimited)
//
// The tenant name "*" is the default clause for requests whose tenant has
// no clause of its own (including the empty tenant).  A request whose
// tenant resolves to no clause at all is admitted unlimited.
// Rejections answer kEOverloaded (distinct from the per-method kELimit so
// clients can tell "this method is saturated" from "this server is
// shedding your tenant").
class TenantGovernor {
 public:
  struct Entry {
    std::string name;
    int weight = 1;
    std::shared_ptr<ConcurrencyLimiter> limiter;  // null = unlimited
    // qos_tenant_<name>: per-tenant qps/p50/p99 via the observe plane.
    std::shared_ptr<LatencyRecorder> latency;
    // qos_tenant_<name>_shed_total: requests this tenant had shed.
    std::shared_ptr<Adder> shed;
  };

  // Returns nullptr and fills *err on a malformed spec (a typo must not
  // silently mean "no QoS").  Empty spec → nullptr with empty *err
  // (governor removed).
  static std::shared_ptr<TenantGovernor> parse(const std::string& spec,
                                               std::string* err);

  // Admission for one request.  Returns the entry that admitted it (to
  // pair with on_response exactly once), nullptr with *admitted=true when
  // no clause applies (unlimited), or *admitted=false when the tenant's
  // limiter shed the request (no on_response then).
  Entry* admit(const std::string& tenant, bool* admitted);
  void on_response(Entry* e, int64_t latency_us, bool error);

  const std::vector<std::unique_ptr<Entry>>& entries() const {
    return entries_;
  }

 private:
  Entry* find(const std::string& tenant);
  std::vector<std::unique_ptr<Entry>> entries_;  // address-stable
  Entry* default_entry_ = nullptr;
};

}  // namespace trpc
