#include "net/redis.h"

#include <errno.h>

#include <cstring>

#include <algorithm>
#include <atomic>
#include <deque>
#include <memory>
#include <mutex>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/messenger.h"
#include "net/protocol.h"
#include "net/server.h"

namespace trpc {

namespace {

constexpr size_t kMaxBulk = 64ull << 20;   // bound one bulk string
constexpr size_t kMaxElements = 1 << 20;   // bound one array
constexpr int kMaxDepth = 8;               // bound reply nesting
constexpr size_t kMaxLine = 64 * 1024;     // bound one status/error line

// The parsers are templated over a byte source so the wire paths scan
// the socket's IOBuf IN PLACE (no per-wakeup flatten — a trickled 64MB
// bulk must not memcpy the whole accumulation on every readable edge)
// while the public std::string entry points (tests, fuzzer) share the
// exact same logic.

struct StringSrc {
  const std::string& s;
  size_t size() const { return s.size(); }
  // Copies up to n bytes at pos into dst; returns bytes copied.
  size_t copy(size_t pos, size_t n, char* dst) const {
    if (pos >= s.size()) {
      return 0;
    }
    const size_t take = std::min(n, s.size() - pos);
    memcpy(dst, s.data() + pos, take);
    return take;
  }
  void extract(size_t pos, size_t n, std::string* out) const {
    out->assign(s, pos, n);
  }
};

struct IOBufSrc {
  const IOBuf* b;
  size_t size() const { return b->size(); }
  size_t copy(size_t pos, size_t n, char* dst) const {
    return b->copy_to(dst, n, pos);
  }
  void extract(size_t pos, size_t n, std::string* out) const {
    out->resize(n);
    b->copy_to(out->data(), n, pos);
  }
};

// Finds "\r\n" starting at `from`, scanning at most `max_scan` bytes of
// available data, in bounded chunks (one byte of overlap catches a CRLF
// spanning a chunk edge).  Returns the \r offset, SIZE_MAX when not
// found within the available bytes, SIZE_MAX - 1 when the scan limit was
// exhausted (malformed: the line is too long).
template <class Src>
size_t find_crlf(const Src& src, size_t from, size_t max_scan) {
  char buf[4096];
  const size_t end = std::min(src.size(), from + max_scan);
  size_t pos = from;
  while (pos < end) {
    const size_t want = std::min(sizeof(buf), end - pos + 1);
    const size_t got = src.copy(pos, want, buf);
    if (got < 2) {
      break;
    }
    for (size_t i = 0; i + 1 < got; ++i) {
      if (buf[i] == '\r' && buf[i + 1] == '\n') {
        return pos + i;
      }
    }
    pos += got - 1;  // overlap one byte
    if (pos + 1 >= end && end < src.size()) {
      return SIZE_MAX - 1;  // scanned the full budget without a CRLF
    }
    if (got < want) {
      break;
    }
  }
  return from + max_scan <= src.size() ? SIZE_MAX - 1 : SIZE_MAX;
}

// Reads "<digits>\r\n" (optionally signed) at *pos.  1 ok / 0 partial /
// -1 malformed.
template <class Src>
int parse_int_line(const Src& data, size_t* pos, int64_t* out) {
  char buf[36];
  const size_t got = data.copy(*pos, sizeof(buf), buf);
  size_t nl = SIZE_MAX;
  for (size_t i = 0; i + 1 < got; ++i) {
    if (buf[i] == '\r' && buf[i + 1] == '\n') {
      nl = i;
      break;
    }
  }
  if (nl == SIZE_MAX) {
    return got >= 34 ? -1 : 0;  // int lines are short
  }
  if (nl == 0) {
    return -1;
  }
  size_t i = 0;
  bool neg = false;
  if (buf[0] == '-') {
    neg = true;
    ++i;
  }
  if (i == nl) {
    return -1;
  }
  // Accumulate the magnitude unsigned so INT64_MIN (magnitude 2^63) is
  // representable; bound-check BEFORE multiplying (UB-free).
  const uint64_t limit =
      neg ? static_cast<uint64_t>(INT64_MAX) + 1 : INT64_MAX;
  uint64_t v = 0;
  for (; i < nl; ++i) {
    if (buf[i] < '0' || buf[i] > '9') {
      return -1;
    }
    const uint64_t d = buf[i] - '0';
    if (v > (limit - d) / 10) {
      return -1;  // would overflow
    }
    v = v * 10 + d;
  }
  *out = neg ? static_cast<int64_t>(0 - v) : static_cast<int64_t>(v);
  *pos += nl + 2;
  return 1;
}

}  // namespace

namespace {

// Status/error lines are CRLF-delimited on the wire: embedded newlines in
// handler-supplied text would desync the whole RESP stream (the bytes
// after the first CRLF parse as the NEXT pipelined reply).  Bulk strings
// are length-prefixed and need no such laundering.
void append_line_safe(const std::string& s, std::string* out) {
  for (char c : s) {
    out->push_back(c == '\r' || c == '\n' ? ' ' : c);
  }
}

}  // namespace

void RedisReply::serialize(std::string* out) const {
  switch (type) {
    case kNil:
      out->append("$-1\r\n");
      break;
    case kStatus:
      out->push_back('+');
      append_line_safe(str, out);
      out->append("\r\n");
      break;
    case kError:
      out->push_back('-');
      append_line_safe(str, out);
      out->append("\r\n");
      break;
    case kInteger:
      out->push_back(':');
      out->append(std::to_string(integer));
      out->append("\r\n");
      break;
    case kString:
      out->push_back('$');
      out->append(std::to_string(str.size()));
      out->append("\r\n");
      out->append(str);
      out->append("\r\n");
      break;
    case kArray:
      out->push_back('*');
      out->append(std::to_string(elements.size()));
      out->append("\r\n");
      for (const RedisReply& e : elements) {
        e.serialize(out);
      }
      break;
  }
}

namespace {

template <class Src>
char marker_at(const Src& data, size_t pos) {
  char c = 0;
  data.copy(pos, 1, &c);
  return c;
}

// Verifies the two bytes at `pos` are CRLF.  1 ok / 0 partial / -1 bad.
template <class Src>
int check_crlf(const Src& data, size_t pos) {
  char crlf[2];
  if (data.copy(pos, 2, crlf) < 2) {
    return 0;
  }
  return crlf[0] == '\r' && crlf[1] == '\n' ? 1 : -1;
}

template <class Src>
int parse_reply_t(const Src& data, size_t* pos, RedisReply* out,
                  int depth) {
  if (depth > kMaxDepth) {
    return -1;
  }
  if (*pos >= data.size()) {
    return 0;
  }
  const char marker = marker_at(data, *pos);
  size_t p = *pos + 1;
  switch (marker) {
    case '+':
    case '-': {
      const size_t nl = find_crlf(data, p, kMaxLine);
      if (nl == SIZE_MAX) {
        return 0;
      }
      if (nl == SIZE_MAX - 1) {
        return -1;  // line exceeds the scan budget
      }
      out->type = marker == '+' ? RedisReply::kStatus : RedisReply::kError;
      data.extract(p, nl - p, &out->str);
      *pos = nl + 2;
      return 1;
    }
    case ':': {
      int64_t v = 0;
      const int rc = parse_int_line(data, &p, &v);
      if (rc != 1) {
        return rc;
      }
      out->type = RedisReply::kInteger;
      out->integer = v;
      *pos = p;
      return 1;
    }
    case '$': {
      int64_t len = 0;
      const int rc = parse_int_line(data, &p, &len);
      if (rc != 1) {
        return rc;
      }
      if (len < 0) {
        out->type = RedisReply::kNil;  // null bulk
        *pos = p;
        return 1;
      }
      if (static_cast<size_t>(len) > kMaxBulk) {
        return -1;
      }
      if (data.size() - p < static_cast<size_t>(len) + 2) {
        return 0;
      }
      const int crc = check_crlf(data, p + len);
      if (crc != 1) {
        return crc;
      }
      out->type = RedisReply::kString;
      data.extract(p, len, &out->str);
      *pos = p + len + 2;
      return 1;
    }
    case '*': {
      int64_t n = 0;
      const int rc = parse_int_line(data, &p, &n);
      if (rc != 1) {
        return rc;
      }
      if (n < 0) {
        out->type = RedisReply::kNil;  // null array
        *pos = p;
        return 1;
      }
      if (static_cast<size_t>(n) > kMaxElements) {
        return -1;
      }
      out->type = RedisReply::kArray;
      out->elements.clear();
      out->elements.reserve(std::min<size_t>(n, 1024));
      for (int64_t i = 0; i < n; ++i) {
        RedisReply e;
        const int erc = parse_reply_t(data, &p, &e, depth + 1);
        if (erc != 1) {
          return erc;
        }
        out->elements.push_back(std::move(e));
      }
      *pos = p;
      return 1;
    }
    default:
      return -1;
  }
}

template <class Src>
int parse_command_t(const Src& data, size_t* pos,
                    std::vector<std::string>* args) {
  if (*pos >= data.size()) {
    return 0;
  }
  if (marker_at(data, *pos) != '*') {
    return -1;  // inline commands unsupported (real clients send arrays)
  }
  size_t p = *pos + 1;
  int64_t n = 0;
  int rc = parse_int_line(data, &p, &n);
  if (rc != 1) {
    return rc;
  }
  if (n <= 0 || static_cast<size_t>(n) > kMaxElements) {
    return -1;
  }
  args->clear();
  args->reserve(std::min<size_t>(n, 64));
  for (int64_t i = 0; i < n; ++i) {
    if (p >= data.size()) {
      return 0;
    }
    if (marker_at(data, p) != '$') {
      return -1;  // commands are arrays of BULK strings only
    }
    ++p;
    int64_t len = 0;
    rc = parse_int_line(data, &p, &len);
    if (rc != 1) {
      return rc;
    }
    if (len < 0 || static_cast<size_t>(len) > kMaxBulk) {
      return -1;
    }
    if (data.size() - p < static_cast<size_t>(len) + 2) {
      return 0;
    }
    const int crc = check_crlf(data, p + len);
    if (crc != 1) {
      return crc;
    }
    std::string arg;
    data.extract(p, len, &arg);
    args->push_back(std::move(arg));
    p += len + 2;
  }
  *pos = p;
  return 1;
}

}  // namespace

int resp_parse_reply(const std::string& data, size_t* pos, RedisReply* out,
                     int depth) {
  return parse_reply_t(StringSrc{data}, pos, out, depth);
}

int resp_parse_command(const std::string& data, size_t* pos,
                       std::vector<std::string>* args) {
  return parse_command_t(StringSrc{data}, pos, args);
}

void resp_pack_command(const std::vector<std::string>& args,
                       std::string* out) {
  out->push_back('*');
  out->append(std::to_string(args.size()));
  out->append("\r\n");
  for (const std::string& a : args) {
    out->push_back('$');
    out->append(std::to_string(a.size()));
    out->append("\r\n");
    out->append(a);
    out->append("\r\n");
  }
}

// ---- service registry ----------------------------------------------------

bool RedisService::AddCommandHandler(const std::string& name,
                                     CommandHandler handler) {
  std::string lower = name;
  std::transform(lower.begin(), lower.end(), lower.begin(), ::tolower);
  return handlers_.emplace(std::move(lower), std::move(handler)).second;
}

const RedisService::CommandHandler* RedisService::FindCommandHandler(
    const std::string& lower) const {
  auto it = handlers_.find(lower);
  return it == handlers_.end() ? nullptr : &it->second;
}

// ---- server protocol -----------------------------------------------------

namespace {

ParseError redis_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  // Claim only connections to a redis-enabled server, and only when the
  // bytes look like a command array ('*' is unambiguous among our
  // protocols: tstd opens "TRP1", HTTP with a method, h2 with "PRI").
  Server* srv = static_cast<Server*>(sock->user_data);
  if (sock->pinned_protocol < 0) {
    if (srv == nullptr || srv->redis_service() == nullptr ||
        source->front() != '*') {
      return ParseError::kTryOtherProtocol;
    }
  }
  size_t pos = 0;
  auto args = std::make_shared<std::vector<std::string>>();
  const int rc = parse_command_t(IOBufSrc{source}, &pos, args.get());
  if (rc == 0) {
    return ParseError::kNotEnoughData;
  }
  if (rc < 0) {
    return ParseError::kCorrupted;
  }
  source->pop_front(pos);
  out->meta.type = RpcMeta::kRequest;
  out->ctx = std::move(args);
  out->socket = sock->id();
  return ParseError::kOk;
}

void redis_respond(Socket* sock, const RedisReply& reply,
                   bool close_after = false) {
  std::string wire;
  reply.serialize(&wire);
  IOBuf out;
  out.append(wire);
  sock->Write(std::move(out), close_after);
}

// Runs INLINE in the read fiber (process_in_order): commands on one
// connection execute strictly in arrival order, like redis-server.
void redis_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto args = std::static_pointer_cast<std::vector<std::string>>(msg.ctx);
  if (srv == nullptr || args == nullptr || args->empty()) {
    return;
  }
  std::string cmd = (*args)[0];
  std::transform(cmd.begin(), cmd.end(), cmd.begin(), ::tolower);

  // Connection auth: redis's own AUTH command maps onto the server's
  // authenticator (parity with the kAuth frame / authorization header).
  if (srv->authenticator() != nullptr) {
    if (cmd == "auth") {
      if (args->size() >= 2 &&
          srv->authenticator()->verify_credential(
              args->back(), sock->remote()) == 0) {
        sock->auth_ok.store(true, std::memory_order_release);
        redis_respond(sock.get(), RedisReply::Status("OK"));
      } else {
        redis_respond(sock.get(),
                      RedisReply::Error("ERR invalid password"));
      }
      return;
    }
    if (!sock->auth_ok.load(std::memory_order_acquire) && cmd != "ping" &&
        cmd != "quit") {
      redis_respond(sock.get(),
                    RedisReply::Error("NOAUTH Authentication required."));
      return;
    }
  }

  // Interceptor gate (same body as every other serving protocol).
  {
    int ec = 0;
    std::string et;
    if (cmd != "ping" && !srv->accept_request(cmd, sock->remote(), &ec, &et)) {
      redis_respond(sock.get(), RedisReply::Error(
                                    "ERR " + std::to_string(ec) + ": " + et));
      return;
    }
  }

  const RedisService::CommandHandler* handler =
      srv->redis_service()->FindCommandHandler(cmd);
  if (handler != nullptr) {
    redis_respond(sock.get(), (*handler)(*args));
    srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  // Builtin fallbacks a stock redis client expects during handshake.
  if (cmd == "ping") {
    redis_respond(sock.get(), args->size() > 1
                                  ? RedisReply::Bulk((*args)[1])
                                  : RedisReply::Status("PONG"));
  } else if (cmd == "echo" && args->size() > 1) {
    redis_respond(sock.get(), RedisReply::Bulk((*args)[1]));
  } else if (cmd == "quit") {
    redis_respond(sock.get(), RedisReply::Status("OK"),
                  /*close_after=*/true);
  } else if (cmd == "select") {
    redis_respond(sock.get(), RedisReply::Status("OK"));
  } else if (cmd == "command") {
    redis_respond(sock.get(), RedisReply::Array({}));
  } else {
    redis_respond(sock.get(),
                  RedisReply::Error("ERR unknown command '" + cmd + "'"));
  }
  srv->requests_served.fetch_add(1, std::memory_order_relaxed);
}

void redis_process_response(InputMessage&&) {
  // Server protocol entry: the client speaks through "redisc" below.
}

}  // namespace

void register_redis_protocol() {
  static int once = [] {
    Protocol p = {"redis", redis_parse, redis_process_request,
                  redis_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- client --------------------------------------------------------------

namespace {

// A pipelined call waiting for its FIFO slot's reply.  Abandoned waiters
// (timeouts) stay in the queue so later replies keep their alignment —
// the reply simply lands in a slot nobody reads.
struct RedisWaiter {
  CountdownEvent ev{1};
  RedisReply reply;
};

struct RedisCliConn {
  std::mutex mu;  // queue order must match wire order
  std::deque<std::shared_ptr<RedisWaiter>> pending;
};

const char kRedisCliTag = 0;

RedisCliConn* cli_conn_of(Socket* s) {
  return proto_conn_of<RedisCliConn>(s, &kRedisCliTag);
}

ParseError redisc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    // Client sockets are PRE-pinned by RedisClient; an unpinned socket in
    // the probing loop belongs to some other protocol — a registered
    // redis client must never hijack (or corrupt-kill) server-side
    // probing in the same process.
    return ParseError::kTryOtherProtocol;
  }
  size_t pos = 0;
  auto reply = std::make_shared<RedisReply>();
  const int rc = parse_reply_t(IOBufSrc{source}, &pos, reply.get(), 0);
  if (rc == 0) {
    return ParseError::kNotEnoughData;
  }
  if (rc < 0) {
    return ParseError::kCorrupted;
  }
  source->pop_front(pos);
  out->meta.type = RpcMeta::kResponse;
  out->ctx = std::move(reply);
  out->socket = sock->id();
  return ParseError::kOk;
}

// Inline in the read fiber (process_in_order): pops the FIFO waiter.
void redisc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  auto reply = std::static_pointer_cast<RedisReply>(msg.ctx);
  RedisCliConn* c = cli_conn_of(sock.get());
  std::shared_ptr<RedisWaiter> w;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->pending.empty()) {
      return;  // unsolicited reply: drop
    }
    w = std::move(c->pending.front());
    c->pending.pop_front();
  }
  w->reply = std::move(*reply);
  w->ev.signal();
}

void redisc_process_request(InputMessage&&) {}

int redisc_protocol_index() {
  static const int index = [] {
    Protocol p = {"redisc", redisc_parse, redisc_process_request,
                  redisc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

RedisReply client_error(const std::string& text) {
  return RedisReply::Error("(client) " + text);
}

}  // namespace

RedisClient::~RedisClient() {
  csock_.Shutdown();
}

int RedisClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  redisc_protocol_index();
  return csock_.Init(addr);
}

std::vector<RedisReply> RedisClient::pipeline(
    const std::vector<std::vector<std::string>>& cmds) {
  std::vector<RedisReply> replies(cmds.size());
  SocketId sid = 0;
  // The install hook sends the AUTH preamble on fresh connections; its
  // waiter rides the FIFO like any command, keeping reply alignment.
  auto install = [this](Socket* fresh) -> int {
    cli_conn_of(fresh);  // install state while single-threaded
    if (opts_.password.empty()) {
      return 0;
    }
    RedisCliConn* c = cli_conn_of(fresh);
    std::string wire;
    resp_pack_command({"AUTH", opts_.password}, &wire);
    auto w = std::make_shared<RedisWaiter>();
    std::lock_guard<std::mutex> cg(c->mu);
    c->pending.push_back(w);
    IOBuf frame;
    frame.append(wire);
    return fresh->Write(std::move(frame));
  };
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(redisc_protocol_index(), install, &sid) != 0) {
      std::fill(replies.begin(), replies.end(),
                client_error("cannot reach " +
                             endpoint2str(csock_.endpoint())));
      return replies;
    }
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    std::fill(replies.begin(), replies.end(),
              client_error("connection failed"));
    return replies;
  }
  RedisCliConn* c = cli_conn_of(s.get());
  std::string wire;
  std::vector<std::shared_ptr<RedisWaiter>> waiters;
  waiters.reserve(cmds.size());
  for (const auto& cmd : cmds) {
    resp_pack_command(cmd, &wire);
    waiters.push_back(std::make_shared<RedisWaiter>());
  }
  {
    // Queue order must equal wire order: both happen under one lock.
    std::lock_guard<std::mutex> g(c->mu);
    for (auto& w : waiters) {
      c->pending.push_back(w);
    }
    IOBuf frame;
    frame.append(wire);
    if (s->Write(std::move(frame)) != 0) {
      for (size_t i = 0; i < waiters.size(); ++i) {
        replies[i] = client_error("write failed");
      }
      return replies;
    }
  }
  const int64_t deadline =
      monotonic_time_us() + opts_.timeout_ms * 1000;
  for (size_t i = 0; i < waiters.size(); ++i) {
    if (waiters[i]->ev.wait(deadline) == 0) {
      replies[i] = std::move(waiters[i]->reply);
    } else {
      replies[i] = client_error("timeout");
    }
  }
  return replies;
}

RedisReply RedisClient::execute(const std::vector<std::string>& args) {
  std::vector<RedisReply> r = pipeline({args});
  return r.empty() ? client_error("empty pipeline") : std::move(r[0]);
}

}  // namespace trpc
