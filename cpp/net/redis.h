// Redis (RESP) protocol — server AND client, with pipelining.
//
// Parity: the reference speaks redis both ways
// (/root/reference/src/brpc/redis.h:194 RedisService lets a user build a
// redis-speaking server; policy/redis_protocol.cpp parses commands;
// redis_command.cpp packs them; socket.h:392 pipelined_count correlates
// in-flight requests FIFO).  Condensed tpu-native form: RedisReply is a
// plain value type (no arena), the service registers std::function
// handlers like Server::RegisterMethod, and the client correlates
// pipelined replies through a FIFO waiter queue on the connection —
// the pipelining substrate SURVEY §5 names for long-context streams.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/proto_client.h"
#include "net/socket.h"

namespace trpc {

class Server;

// One RESP value (request args arrive as flat string vectors instead).
struct RedisReply {
  enum Type : uint8_t {
    kNil = 0,      // $-1\r\n (null bulk) or *-1\r\n (null array)
    kStatus = 1,   // +OK\r\n
    kError = 2,    // -ERR ...\r\n
    kInteger = 3,  // :42\r\n
    kString = 4,   // $3\r\nfoo\r\n (bulk)
    kArray = 5,    // *N\r\n followed by N replies
  };
  Type type = kNil;
  int64_t integer = 0;
  std::string str;  // status / error text / bulk payload
  std::vector<RedisReply> elements;

  static RedisReply Status(std::string s) {
    RedisReply r;
    r.type = kStatus;
    r.str = std::move(s);
    return r;
  }
  static RedisReply Error(std::string s) {
    RedisReply r;
    r.type = kError;
    r.str = std::move(s);
    return r;
  }
  static RedisReply Integer(int64_t v) {
    RedisReply r;
    r.type = kInteger;
    r.integer = v;
    return r;
  }
  static RedisReply Bulk(std::string s) {
    RedisReply r;
    r.type = kString;
    r.str = std::move(s);
    return r;
  }
  static RedisReply Nil() { return RedisReply(); }
  static RedisReply Array(std::vector<RedisReply> el) {
    RedisReply r;
    r.type = kArray;
    r.elements = std::move(el);
    return r;
  }

  bool is_error() const { return type == kError; }
  // RESP serialization (both directions use the same encoding).
  void serialize(std::string* out) const;
};

// ---- codec (exposed for tests + the fuzzer) ------------------------------

// Parses one complete reply starting at (*data)[*pos].  Returns 1 and
// advances *pos past it on success, 0 when more bytes are needed, -1 on
// malformed input.  Depth/size-bounded.
int resp_parse_reply(const std::string& data, size_t* pos, RedisReply* out,
                     int depth = 0);

// Parses one complete command — a RESP array of bulk strings, the only
// form real clients send.  Same return convention.
int resp_parse_command(const std::string& data, size_t* pos,
                       std::vector<std::string>* args);

// Packs a command in the array-of-bulk-strings form clients send.
void resp_pack_command(const std::vector<std::string>& args,
                       std::string* out);

// ---- server side ---------------------------------------------------------

// Container of command handlers; assign to Server::set_redis_service to
// make the server speak redis on its port (alongside tstd/HTTP/h2 —
// protocol probing routes by the leading '*').  Handlers run inline in
// the read fiber, strictly in per-connection arrival order, exactly like
// redis-server (redis.h:246 Run() ordering contract).
class RedisService {
 public:
  // args[0] is the command name (matched case-insensitively).
  using CommandHandler =
      std::function<RedisReply(const std::vector<std::string>& args)>;

  // Registers `handler` for command `name`.  False if already present.
  bool AddCommandHandler(const std::string& name, CommandHandler handler);
  const CommandHandler* FindCommandHandler(const std::string& lower) const;

 private:
  std::map<std::string, CommandHandler> handlers_;
};

// Registers the redis server protocol with the registry (idempotent);
// Server::Start calls it when a redis_service is installed.
void register_redis_protocol();

// ---- client side ---------------------------------------------------------

// Redis client over the runtime's socket layer with FIFO pipelining:
// execute() is one round trip; pipeline() writes N commands in one batch
// and collects the N replies in order (socket.h:392 pipelined_count
// parity — correlation is arrival order, there are no ids on the wire).
class RedisClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
    // AUTH command sent on fresh connections ("" = none).
    std::string password;
  };

  ~RedisClient();
  int Init(const std::string& addr, const Options* opts = nullptr);

  // One command, one reply.  Error replies come back as kError (not a
  // transport failure); transport/timeout failures return kError with
  // str "(client) ...".
  RedisReply execute(const std::vector<std::string>& args);

  // Pipelines all commands in one write; replies arrive in order.
  std::vector<RedisReply> pipeline(
      const std::vector<std::vector<std::string>>& cmds);

 private:
  Options opts_;
  FiberMutex sock_mu_;
  ClientSocket csock_;
};

}  // namespace trpc
