#include "net/redis_cluster.h"

#include <cstring>

namespace trpc {

namespace {

// CRC16-CCITT table, generated from poly 0x1021 (the redis cluster spec
// appendix publishes this exact table; it is derivable from the poly).
uint16_t crc16_tab[256];
bool crc16_init = [] {
  for (int i = 0; i < 256; ++i) {
    uint16_t c = static_cast<uint16_t>(i << 8);
    for (int b = 0; b < 8; ++b) {
      c = static_cast<uint16_t>((c << 1) ^ ((c & 0x8000) ? 0x1021 : 0));
    }
    crc16_tab[i] = c;
  }
  return true;
}();

bool parse_redirect(const std::string& err, const char* kind,
                    std::string* addr, int* slot) {
  // "MOVED 3999 127.0.0.1:6381" / "ASK 3999 127.0.0.1:6381"
  const size_t klen = strlen(kind);
  if (err.compare(0, klen, kind) != 0 || err.size() <= klen ||
      err[klen] != ' ') {
    return false;
  }
  const size_t slot_beg = klen + 1;
  const size_t sp = err.find(' ', slot_beg);
  if (sp == std::string::npos || sp + 1 >= err.size()) {
    return false;
  }
  char* end = nullptr;
  const long s = strtol(err.c_str() + slot_beg, &end, 10);
  if (end != err.c_str() + sp || s < 0 ||
      s >= RedisClusterClient::kSlots) {
    return false;
  }
  *slot = static_cast<int>(s);
  *addr = err.substr(sp + 1);
  return true;
}

}  // namespace

uint16_t redis_crc16(const char* data, size_t len) {
  uint16_t crc = 0;
  for (size_t i = 0; i < len; ++i) {
    crc = static_cast<uint16_t>(
        (crc << 8) ^
        crc16_tab[((crc >> 8) ^ static_cast<uint8_t>(data[i])) & 0xff]);
  }
  return crc;
}

uint16_t redis_key_slot(const std::string& key) {
  size_t beg = 0, len = key.size();
  const size_t open = key.find('{');
  if (open != std::string::npos) {
    const size_t close = key.find('}', open + 1);
    if (close != std::string::npos && close > open + 1) {
      beg = open + 1;
      len = close - beg;  // non-empty tag: hash only the tag
    }
  }
  return redis_crc16(key.data() + beg, len) % RedisClusterClient::kSlots;
}

int RedisClusterClient::Init(const std::vector<std::string>& seeds,
                             const Options* opts) {
  if (seeds.empty()) {
    return -1;
  }
  if (opts != nullptr) {
    opts_ = *opts;
  }
  seeds_ = seeds;
  slots_.assign(kSlots, std::string());
  return 0;
}

RedisClient* RedisClusterClient::client_for(const std::string& addr) {
  // Callers hold mu_.
  auto it = pool_.find(addr);
  if (it != pool_.end()) {
    return it->second.get();
  }
  auto cli = std::make_unique<RedisClient>();
  RedisClient::Options copts;
  copts.timeout_ms = opts_.timeout_ms;
  copts.password = opts_.password;
  if (cli->Init(addr, &copts) != 0) {
    return nullptr;
  }
  return pool_.emplace(addr, std::move(cli)).first->second.get();
}

int RedisClusterClient::RefreshSlotMap() {
  // CLUSTER SLOTS reply: array of [start, end, [ip, port, ...master],
  // ...replicas].  Any answering node serves; replicas are ignored —
  // this client routes to masters only, like the reference.
  std::vector<std::string> nodes;
  {
    LockGuard<FiberMutex> g(mu_);
    for (const auto& kv : pool_) {
      nodes.push_back(kv.first);
    }
  }
  nodes.insert(nodes.end(), seeds_.begin(), seeds_.end());
  for (const auto& addr : nodes) {
    RedisClient* cli;
    {
      LockGuard<FiberMutex> g(mu_);
      cli = client_for(addr);
    }
    if (cli == nullptr) {
      continue;
    }
    RedisReply r = cli->execute({"CLUSTER", "SLOTS"});
    if (r.type != RedisReply::kArray || r.elements.empty()) {
      continue;
    }
    LockGuard<FiberMutex> g(mu_);
    bool any = false;
    for (const RedisReply& range : r.elements) {
      if (range.type != RedisReply::kArray || range.elements.size() < 3 ||
          range.elements[0].type != RedisReply::kInteger ||
          range.elements[1].type != RedisReply::kInteger ||
          range.elements[2].type != RedisReply::kArray ||
          range.elements[2].elements.size() < 2) {
        continue;
      }
      const int64_t beg = range.elements[0].integer;
      const int64_t end = range.elements[1].integer;
      const RedisReply& master = range.elements[2];
      if (beg < 0 || end >= kSlots || beg > end) {
        continue;
      }
      const std::string owner = master.elements[0].str + ":" +
                                std::to_string(master.elements[1].integer);
      for (int64_t s = beg; s <= end; ++s) {
        slots_[s] = owner;
      }
      any = true;
    }
    if (any) {
      return 0;
    }
  }
  return -1;
}

std::string RedisClusterClient::slot_owner(int slot) {
  LockGuard<FiberMutex> g(mu_);
  return (slot >= 0 && slot < kSlots) ? slots_[slot] : std::string();
}

RedisReply RedisClusterClient::execute(
    const std::vector<std::string>& args) {
  if (args.empty()) {
    return RedisReply::Error("(client) empty command");
  }
  const bool keyed = args.size() > 1;
  const int slot = keyed ? redis_key_slot(args[1]) : -1;

  std::string target;
  if (keyed) {
    LockGuard<FiberMutex> g(mu_);
    target = slots_[slot];
  }
  if (target.empty()) {
    if (keyed && RefreshSlotMap() == 0) {
      LockGuard<FiberMutex> g(mu_);
      target = slots_[slot];
    }
    if (target.empty()) {
      target = seeds_[0];
    }
  }

  bool asking = false;
  RedisReply last;
  for (int hop = 0; hop <= opts_.max_redirects; ++hop) {
    RedisClient* cli;
    {
      LockGuard<FiberMutex> g(mu_);
      cli = client_for(target);
    }
    if (cli == nullptr) {
      return RedisReply::Error("(client) cannot reach " + target);
    }
    if (asking) {
      // ASK is one-shot: the target only serves the key when the command
      // is preceded by ASKING on the same connection.
      std::vector<RedisReply> rs = cli->pipeline({{"ASKING"}, args});
      last = rs.size() > 1 ? std::move(rs[1])
                           : RedisReply::Error("(client) short pipeline");
      asking = false;
    } else {
      last = cli->execute(args);
    }
    std::string next;
    int moved_slot = 0;
    if (last.is_error() &&
        parse_redirect(last.str, "MOVED", &next, &moved_slot)) {
      {
        LockGuard<FiberMutex> g(mu_);
        slots_[moved_slot] = next;  // permanent: table was stale
      }
      target = std::move(next);
      continue;
    }
    if (last.is_error() &&
        parse_redirect(last.str, "ASK", &next, &moved_slot)) {
      target = std::move(next);  // one-shot: table stays
      asking = true;
      continue;
    }
    return last;
  }
  return last;  // redirect budget exhausted: surface the loop
}

}  // namespace trpc
