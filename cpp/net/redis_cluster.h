// Redis Cluster client: slot-mapped routing with MOVED/ASK redirects.
//
// Parity: /root/reference/src/brpc/redis_cluster.cpp (1,219 LoC) keeps a
// slot→node table refreshed from CLUSTER SLOTS and re-issues commands on
// -MOVED (permanent, update the table) / -ASK (one-shot, prefix ASKING)
// redirect errors.  Condensed form here: a pool of pipelined RedisClients
// keyed by node address, a 16384-entry owner table under a mutex, and a
// bounded redirect loop per command.  Slot hashing is the spec's
// CRC16-CCITT over the {hash tag} when present.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "fiber/sync.h"
#include "net/redis.h"

namespace trpc {

// CRC16-CCITT (XMODEM: poly 0x1021, init 0) — the redis cluster spec hash.
uint16_t redis_crc16(const char* data, size_t len);

// Slot of `key`: honours {hash tags} (first '{' with a non-empty segment
// before the next '}' hashes only that segment).  Range [0, 16384).
uint16_t redis_key_slot(const std::string& key);

class RedisClusterClient {
 public:
  static constexpr int kSlots = 16384;

  struct Options {
    int64_t timeout_ms = 1000;
    std::string password;  // forwarded to every node connection
    int max_redirects = 5;
  };

  // Seeds are "host:port" of any cluster members; the slot map is pulled
  // lazily from them (CLUSTER SLOTS) on first use and after MOVED.
  int Init(const std::vector<std::string>& seeds,
           const Options* opts = nullptr);

  // Routes by the command's first key (args[1]); keyless commands go to
  // the first healthy node.  Redirects are followed up to max_redirects;
  // exceeding that returns the last redirect error verbatim.
  RedisReply execute(const std::vector<std::string>& args);

  // Re-pulls the slot table from the first seed/node that answers
  // CLUSTER SLOTS.  0 on success.  Called lazily; exposed for tests.
  int RefreshSlotMap();

  // Current owner of `slot` ("" when unknown).  For tests/diagnostics.
  std::string slot_owner(int slot);

 private:
  RedisClient* client_for(const std::string& addr);

  Options opts_;
  std::vector<std::string> seeds_;
  FiberMutex mu_;  // guards slots_ and pool_
  std::vector<std::string> slots_;
  std::map<std::string, std::unique_ptr<RedisClient>> pool_;
};

}  // namespace trpc
