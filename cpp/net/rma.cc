#include "net/rma.h"

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <unordered_map>
#include <vector>

#include "base/compress.h"
#include "base/flags.h"
#include "base/logging.h"
#include "base/time.h"
#include "fiber/event.h"
#include "fiber/fiber.h"
#include "net/fault.h"
#include "net/hotpath_stats.h"
#include "net/ici_transport.h"
#include "net/socket.h"
#include "net/stripe.h"
#include "stat/reducer.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

constexpr uint64_t kRmaMagic = 0x545250524d413154ull;  // "TRPRMA1T"
// Region layout: [RmaSegHdr, padded to kRmaDataOffset][data area].
// Window spans reserve kRmaSpanHdr at their start for the transfer
// header; direct (caller-buffer) transfers use the RmaSegHdr's embedded
// header so the payload can land at data offset 0.
constexpr uint32_t kRmaDataOffset = 8192;
constexpr uint32_t kRmaSpanHdr = 8192;
constexpr uint32_t kRmaMaxChunks = 1024;
constexpr uint32_t kRmaBitWords = kRmaMaxChunks / 64;
// Window slots fit ONE bitmap word: span allocation is a single CAS and
// a span is always a contiguous run of ≤ 64 slots.
constexpr uint32_t kRmaWindowSlots = 64;
constexpr uint32_t kXferCrcPresent = 1u << 0;

// One transfer's completion state, shared memory.  The sender writes
// the scalar fields before any chunk, sets chunk_bits with release as
// each chunk's bytes land, and the receiver admits the payload only
// when every bit reads set (acquire) — the control frame alone never
// proves the bytes arrived (a faulted chunk leaves its bit clear).
struct RmaXfer {
  // Release/acquire: `total` is the sender's first store and doubles as
  // the header-initialized marker for the direct path.
  std::atomic<uint64_t> total;
  // The transfer's correlation id, stamped at init and matched at
  // resolve: a LATE put from a timed-out call that re-initializes a
  // reused direct landing region after the live call's init makes the
  // live resolve reject (clean whole-call failure) instead of admitting
  // interleaved bytes.  (A stale writer racing mid-flight is inherent to
  // shared memory — see the reuse contract in rma.h/RmaBuffer.)
  uint64_t token;
  uint32_t chunk_bytes;
  uint32_t nchunks;
  uint32_t flags;  // kXferCrcPresent: chunk_crc[] carries per-chunk crc32c
  uint32_t pad;
  // Release per set bit (pairs with the receiver's acquire scan): a set
  // bit publishes that chunk's payload bytes.
  std::atomic<uint64_t> chunk_bits[kRmaBitWords];
  uint32_t chunk_crc[kRmaMaxChunks];
};
static_assert(sizeof(RmaXfer) <= kRmaSpanHdr, "span header overflow");

struct RmaSegHdr {
  uint64_t magic;
  uint32_t data_off;
  uint32_t nslots;  // 0: plain region (no window allocator)
  uint64_t data_len;
  uint32_t slot_bytes;
  uint32_t reserved;
  // Window slot bitmap, shared: the PEER allocates spans (CAS set,
  // acquire — a freed slot's payload reads must not be reordered before
  // the claim), the owner frees them (fetch_and clear, release — the
  // consumer finished reading before the slot recycles).
  std::atomic<uint64_t> slot_map;
  // Direct-to-region transfers (caller landing buffers) complete here.
  RmaXfer direct;
};
static_assert(sizeof(RmaSegHdr) <= kRmaDataOffset, "region header overflow");

int64_t flag_value(Flag* f, int64_t dflt) {
  return f != nullptr ? f->int64_value() : dflt;
}

Flag* int_flag(const char* name, int64_t dflt, const char* desc, int64_t lo,
               int64_t hi) {
  Flag* f = Flag::define_int64(name, dflt, desc);
  if (f != nullptr) {
    // Range validator + introspectable bounds in one declaration (the
    // tuner and /flags?format=json read them back).
    f->set_int_range(lo, hi);
  }
  return f;
}

Flag* window_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_rma_window_bytes", 256ll << 20,
        "per-connection one-sided receive window for NEW shm/ici "
        "connections (bytes, 0 disables the rma plane, else a power of "
        "two in [16MB, 4GB]; the largest one-sided transfer is the "
        "window minus one 4MB-granularity slot)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' &&
               (n == 0 || (n >= (16ll << 20) && n <= (4ll << 30) &&
                           (n & (n - 1)) == 0));
      });
      // Bounds hint only: the validator additionally requires 0 or a
      // power of two, so set_int_range would be too permissive.  The
      // tuner's window rule doubles within these bounds (preserving
      // power-of-two) and never touches a 0 (= disabled) window.
      flag->set_bounds_hint(16ll << 20, 4ll << 30);
    }
    return flag;
  }();
  return f;
}

Flag* shm_rails_flag() {
  static Flag* f = int_flag(
      "trpc_shm_rails", 4,
      "concurrent one-sided writer lanes for rma transfers over shm "
      "connections (parallel rail fibers, each owning a contiguous "
      "chunk range)",
      1, 16);
  return f;
}

Flag* ici_rails_flag() {
  static Flag* f = int_flag(
      "trpc_ici_rails", 4,
      "concurrent one-sided writer lanes for rma transfers over ici "
      "connections (parallel rail fibers, each owning a contiguous "
      "chunk range)",
      1, 16);
  return f;
}

Flag* scavenge_flag() {
  static Flag* f = int_flag(
      "trpc_rma_span_scavenge_ms", 10000,
      "age after which an allocated-but-never-admitted receive-window "
      "span is reclaimed (ms, [50, 600000]) — a dropped control frame "
      "(chaos, dying sender) otherwise leaks the slots until connection "
      "teardown, and group-transfer schedules hammer the window hard "
      "enough that the leak stops being theoretical; must exceed the "
      "slowest legitimate write+control latency",
      50, 600000);
  return f;
}

[[maybe_unused]] Flag* const g_rma_flags_eager[] = {
    window_flag(), shm_rails_flag(), ici_rails_flag(), scavenge_flag()};

// ---- registry ------------------------------------------------------------

// TRUSTED geometry snapshot of a region.  The live header lives in
// peer-writable shared memory, so every consumer works from a snapshot
// taken when WE created the region (registry) or validated the mapping
// (peer windows) — a peer scribbling its header afterwards can corrupt
// its own data plane but can never push our arithmetic out of bounds
// (slot_bytes=0 division, data_off past the mapping, ...).
struct RmaGeom {
  uint64_t data_len = 0;
  uint32_t slot_bytes = 0;
  uint32_t nslots = 0;  // 0: plain region
};

// Scavenger state for one receive window (owner side).  `admitted`
// marks slots whose span rma_resolve admitted and whose payload is
// still referenced — exempt from scavenging however old; per-slot
// first-seen stamps (guarded by reg_mu — only the scavenger pass reads
// or writes them) age everything else.
struct WindowScav {
  // Release on set (admit) / clear (last payload ref dropped) pairs
  // with the scavenger's acquire read: an admitted span is never aged.
  std::atomic<uint64_t> admitted{0};
  int64_t first_seen_us[kRmaWindowSlots] = {};
};

struct RegionRec {
  uint64_t rkey = 0;
  std::shared_ptr<RmaMapping> map;  // null for local pins (rma_reg)
  std::string name;                 // shm name for exportable regions
  const char* pin_base = nullptr;   // local pins: the pinned range
  size_t pin_len = 0;
  bool window = false;
  std::shared_ptr<WindowScav> scav;  // windows only
  // rma_free arrived while a landing bind (an in-flight call's resp_buf)
  // still referenced this region: the striped copy-path fallback holds
  // the raw data pointer, so the unmap defers until the last bind drops
  // (rma_landing_unbind) instead of pulling pages out from under a late
  // landing memcpy.
  bool free_pending = false;
  RmaGeom geom;
};

struct LandingBind {
  uint64_t rkey = 0;
  uint64_t cap = 0;
  uint64_t off = 0;  // landing offset inside the region's data area
};

std::mutex& reg_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::vector<RegionRec>& regions() {
  static auto* v = new std::vector<RegionRec>();
  return *v;
}
std::unordered_map<uint64_t, LandingBind>& landing_binds() {
  static auto* m = new std::unordered_map<uint64_t, LandingBind>();
  return *m;
}
// Relaxed: ordinal mint only needs uniqueness, no ordering.
std::atomic<uint32_t> g_next_ordinal{1};

std::string rma_shm_name(int32_t pid, uint32_t ordinal) {
  char name[64];
  snprintf(name, sizeof(name), "/trpc_rma_%d_%u", pid, ordinal);
  return name;
}

uint64_t make_rkey(uint32_t ordinal) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(getpid())) << 32) |
         ordinal;
}

RmaSegHdr* hdr_of(const std::shared_ptr<RmaMapping>& m) {
  return reinterpret_cast<RmaSegHdr*>(m->base);
}

// Creates + registers one exportable region.  window: initialize the
// slot allocator over the data area.
void* region_create(size_t data_len, bool window, uint64_t* rkey_out) {
  if (data_len == 0 || data_len > (4ull << 30)) {
    return nullptr;
  }
  // Relaxed: ordinal mint needs uniqueness only, no ordering.
  const uint32_t ord =
      g_next_ordinal.fetch_add(1, std::memory_order_relaxed);
  const std::string name = rma_shm_name(getpid(), ord);
  const size_t bytes = kRmaDataOffset + data_len;
  const int fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    return nullptr;
  }
  if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
    close(fd);
    shm_unlink(name.c_str());
    return nullptr;
  }
  void* mem =
      mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name.c_str());
    return nullptr;
  }
  auto* h = static_cast<RmaSegHdr*>(mem);
  memset(static_cast<void*>(h), 0, sizeof(RmaSegHdr));
  h->data_off = kRmaDataOffset;
  h->data_len = data_len;
  if (window) {
    h->nslots = kRmaWindowSlots;
    h->slot_bytes = static_cast<uint32_t>(data_len / kRmaWindowSlots);
  }
  // Release via the magic store position: peers validate magic before
  // trusting any other field (plain store is fine — the name is only
  // shipped to peers after this returns).
  h->magic = kRmaMagic;
  auto mapping = std::make_shared<RmaMapping>();
  mapping->base = static_cast<char*>(mem);
  mapping->len = bytes;
  mapping->owned = true;
  RegionRec rec;
  rec.rkey = make_rkey(ord);
  rec.map = mapping;
  rec.name = name;
  rec.window = window;
  if (window) {
    rec.scav = std::make_shared<WindowScav>();
  }
  rec.geom.data_len = data_len;
  rec.geom.slot_bytes = h->slot_bytes;
  rec.geom.nslots = h->nslots;
  {
    std::lock_guard<std::mutex> g(reg_mu());
    regions().push_back(std::move(rec));
  }
  if (rkey_out != nullptr) {
    *rkey_out = make_rkey(ord);
  }
  return static_cast<char*>(mem) + kRmaDataOffset;
}

// Local-registry lookup (receiver side; loopback peer resolution) with
// the TRUSTED creation-time geometry.
std::shared_ptr<RmaMapping> local_region(
    uint64_t rkey, bool* window, RmaGeom* geom,
    std::shared_ptr<WindowScav>* scav = nullptr) {
  std::lock_guard<std::mutex> g(reg_mu());
  for (const RegionRec& r : regions()) {
    if (r.rkey == rkey && r.map != nullptr) {
      if (window != nullptr) {
        *window = r.window;
      }
      if (geom != nullptr) {
        *geom = r.geom;
      }
      if (scav != nullptr) {
        *scav = r.scav;
      }
      return r.map;
    }
  }
  return nullptr;
}

// Slot-run mask of a span [off, off+need) under geometry g.
uint64_t span_slot_mask(const RmaGeom& g, uint64_t off, uint64_t need) {
  const uint32_t k =
      static_cast<uint32_t>((need + g.slot_bytes - 1) / g.slot_bytes);
  const uint32_t start = static_cast<uint32_t>(off / g.slot_bytes);
  const uint64_t run = k >= 64 ? ~0ull : ((1ull << k) - 1);
  return run << start;
}

// Cross-pid peer mappings cached by rkey (bounded, FIFO-evicted): the
// direct-landing path puts into the SAME caller regions over and over
// (a decode node cycling a handful of landing buffers), and paying
// shm_open+mmap+munmap plus cold soft-faults per transfer capped the
// cross-process KV pull at ~1.2 GB/s where the in-process path ran 6+.
// A hit is revalidated against the shm object's CURRENT inode (one
// shm_open+fstat, no mmap, pages stay warm): rkeys embed pid+ordinal,
// and pid RECYCLING can re-mint an old rkey for a brand-new region — an
// identity check is what makes the cache safe, not the mint alone.  A
// peer that merely freed its region is harmless either way: the
// receiver's resolve rejects the transfer whole.
struct PeerMapEntry {
  std::shared_ptr<RmaMapping> map;
  RmaGeom geom;
  dev_t dev = 0;  // shm object identity at map time
  ino_t ino = 0;
};
struct PeerMapCache {
  std::mutex mu;
  std::unordered_map<uint64_t, PeerMapEntry> map;
  std::vector<uint64_t> order;  // insertion order for eviction
};
PeerMapCache& peer_map_cache() {
  static auto* c = new PeerMapCache();
  return *c;
}
constexpr size_t kPeerMapCacheCap = 64;

// Maps a PEER's exportable region by rkey, snapshotting its geometry
// from the header ONCE under validation (all later arithmetic uses the
// snapshot).  Loopback (peer pid == ours) shares the registry's own
// mapping — same virtual address, and the shared refcount defers
// rma_free's munmap past this user.  Cross-pid mappings come from the
// bounded cache above.
std::shared_ptr<RmaMapping> map_peer_region(uint64_t rkey, RmaGeom* geom) {
  const int32_t pid = static_cast<int32_t>(rkey >> 32);
  const uint32_t ord = static_cast<uint32_t>(rkey);
  if (pid == getpid()) {
    return local_region(rkey, nullptr, geom);
  }
  const std::string name = rma_shm_name(pid, ord);
  {
    PeerMapCache& c = peer_map_cache();
    std::lock_guard<std::mutex> g(c.mu);
    auto it = c.map.find(rkey);
    if (it != c.map.end()) {
      // Revalidate identity: the same rkey naming a DIFFERENT shm
      // object (pid recycled, ordinal re-minted) must not serve the
      // dead peer's orphaned pages.
      struct stat st;
      const int vfd = shm_open(name.c_str(), O_RDONLY, 0600);
      const bool same = vfd >= 0 && fstat(vfd, &st) == 0 &&
                        st.st_dev == it->second.dev &&
                        st.st_ino == it->second.ino;
      if (vfd >= 0) {
        close(vfd);
      }
      if (same) {
        if (geom != nullptr) {
          *geom = it->second.geom;
        }
        return it->second.map;
      }
      c.map.erase(it);  // stale identity: fall through to a fresh map
      for (auto oit = c.order.begin(); oit != c.order.end(); ++oit) {
        if (*oit == rkey) {
          c.order.erase(oit);
          break;
        }
      }
    }
  }
  const int fd = shm_open(name.c_str(), O_RDWR, 0600);
  if (fd < 0) {
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      st.st_size < static_cast<off_t>(kRmaDataOffset)) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    return nullptr;
  }
  auto m = std::make_shared<RmaMapping>();
  m->base = static_cast<char*>(mem);
  m->len = static_cast<size_t>(st.st_size);
  m->owned = true;
  const RmaSegHdr* h = hdr_of(m);
  // Copy-then-validate: each field is read ONCE into the snapshot; the
  // live header may be scribbled by its owner afterwards.
  RmaGeom snap;
  snap.data_len = h->data_len;
  snap.slot_bytes = h->slot_bytes;
  snap.nslots = h->nslots;
  if (h->magic != kRmaMagic || h->data_off != kRmaDataOffset ||
      snap.data_len == 0 || snap.data_len > m->len - kRmaDataOffset) {
    return nullptr;  // mapping dtor unmaps
  }
  if (snap.nslots != 0 &&
      (snap.nslots != kRmaWindowSlots || snap.slot_bytes < kRmaSpanHdr ||
       static_cast<uint64_t>(snap.slot_bytes) * snap.nslots >
           snap.data_len)) {
    return nullptr;
  }
  if (geom != nullptr) {
    *geom = snap;
  }
  {
    PeerMapCache& c = peer_map_cache();
    std::lock_guard<std::mutex> g(c.mu);
    if (c.map.size() >= kPeerMapCacheCap && !c.order.empty()) {
      c.map.erase(c.order.front());  // shared_ptr defers the munmap
      c.order.erase(c.order.begin());
    }
    PeerMapEntry e;
    e.map = m;
    e.geom = snap;
    e.dev = st.st_dev;  // identity captured at map time (fstat above)
    e.ino = st.st_ino;
    if (c.map.emplace(rkey, std::move(e)).second) {
      c.order.push_back(rkey);
    }
  }
  return m;
}

// ---- window span allocator ----------------------------------------------

// Claims a contiguous run of slots covering `need` bytes (trusted
// geometry only — never the live header's).  Single-word CAS: ≤ 64
// slots per window by construction.  -1 when no run fits (window full —
// the caller falls back to the copy path).
int span_alloc(RmaSegHdr* h, const RmaGeom& g, uint64_t need,
               uint64_t* off_out) {
  const uint32_t k =
      static_cast<uint32_t>((need + g.slot_bytes - 1) / g.slot_bytes);
  if (k == 0 || k > g.nslots) {
    return -1;
  }
  const uint64_t run = k == 64 ? ~0ull : ((1ull << k) - 1);
  // Acquire on the claim: the payload bytes we are about to write into
  // a recycled slot must not be ordered before the observation that the
  // receiver freed it.
  uint64_t cur = h->slot_map.load(std::memory_order_acquire);
  while (true) {
    int start = -1;
    for (uint32_t s = 0; s + k <= g.nslots; ++s) {
      if ((cur & (run << s)) == 0) {
        start = static_cast<int>(s);
        break;
      }
    }
    if (start < 0) {
      return -1;
    }
    // Acquire on both CAS orders: claiming (or re-reading) the bitmap
    // must happen-before our writes into possibly-recycled slots — pairs
    // with span_free's release clear after the consumer's last read.
    if (h->slot_map.compare_exchange_weak(cur, cur | (run << start),
                                          std::memory_order_acquire,
                                          std::memory_order_acquire)) {
      *off_out = static_cast<uint64_t>(start) * g.slot_bytes;
      return 0;
    }
  }
}

void span_free(RmaSegHdr* h, const RmaGeom& g, uint64_t off,
               uint64_t need) {
  const uint32_t k =
      static_cast<uint32_t>((need + g.slot_bytes - 1) / g.slot_bytes);
  const uint32_t start = static_cast<uint32_t>(off / g.slot_bytes);
  const uint64_t run = k == 64 ? ~0ull : ((1ull << k) - 1);
  // Release: every read of the span's payload happened before the slots
  // recycle to the allocating peer.
  h->slot_map.fetch_and(~(run << start), std::memory_order_release);
}

// ---- send path -----------------------------------------------------------

// Effective chunk size: the configured stripe chunk, grown until the
// count fits the bitmap.
uint64_t effective_chunk(uint64_t total) {
  uint64_t chunk = std::max<uint64_t>(64 << 10, stripe_chunk_bytes());
  while ((total + chunk - 1) / chunk > kRmaMaxChunks) {
    chunk *= 2;
  }
  return chunk;
}

void xfer_init(RmaXfer* x, uint64_t total, uint64_t chunk, bool crc,
               uint64_t token) {
  const uint32_t nchunks =
      static_cast<uint32_t>((total + chunk - 1) / chunk);
  x->token = token;
  x->chunk_bytes = static_cast<uint32_t>(chunk);
  x->nchunks = nchunks;
  x->flags = crc ? kXferCrcPresent : 0;
  for (uint32_t i = 0; i < kRmaBitWords; ++i) {
    // Relaxed: bits are re-published per chunk with release below; the
    // zeroing itself is ordered by the `total` release store that marks
    // the header live.
    x->chunk_bits[i].store(0, std::memory_order_relaxed);
  }
  // Release: publishes the scalar header fields (and the cleared bitmap)
  // before any chunk bit can be observed set.
  x->total.store(total, std::memory_order_release);
}

struct RailJob {
  RmaXfer* x = nullptr;
  char* dst_base = nullptr;  // payload base in the peer region
  IOBuf data;                // this rail's contiguous byte range
  uint32_t first_chunk = 0;
  uint64_t chunk = 0;
  uint64_t total = 0;
  uint64_t cid = 0;     // timeline correlation
  uint32_t rail = 0;
  bool crc = false;
  EndPoint peer;
  std::atomic<uint32_t>* remaining = nullptr;
  // Deadline plane (net/deadline.h): polled between chunks; a triggered
  // token stops this rail (skipped bytes counted into *aborted — the
  // cancel_saved_bytes accounting and the caller's no-control-frame
  // decision).  The token's scope is kept alive by the rma_try_send
  // caller across put_body's bounded join.
  DeadlineToken tok;
  std::atomic<uint64_t>* aborted = nullptr;
};

// Writes one rail's chunk range: memcpy into the peer region, then a
// release-fenced bit per chunk.  Fault points compose with the global
// transport actor (kTx): drop skips write+bit, trunc writes a prefix and
// skips the bit (whole-call failure either way), delay parks first.
void rail_run(RailJob* j) {
  FaultActor& fa = FaultActor::global();
  const bool tl = timeline::enabled();
  uint32_t ci = j->first_chunk;
  uint64_t off = static_cast<uint64_t>(ci) * j->chunk;
  while (!j->data.empty()) {
    if (j->aborted != nullptr && j->tok.aborted()) {
      // Cascading cancel / expired budget: stop within one chunk.  The
      // remaining chunks' bits stay clear, so the receiver (if the
      // control frame raced out at all) drops the transfer whole.
      j->aborted->fetch_add(j->data.size(), std::memory_order_acq_rel);
      break;
    }
    IOBuf piece;
    j->data.cutn(&piece, j->chunk);
    const uint64_t n = piece.size();
    bool write_bytes = true;
    bool set_bit = true;
    uint64_t trunc_to = n;
    bool corrupt = false;
    if (fa.active()) {
      // Same kTx decision stream as the byte plane (FaultTransport), so
      // chunk faults replay by seed alongside everything else.  delay
      // faults compose via the control frame's rx path instead — a
      // delayed ring read stalls the whole transfer's completion.
      const FaultDecision d = fa.decide(FaultPoint::kTx, j->peer);
      switch (d.kind) {
        case FaultKind::kDrop:
        case FaultKind::kReset:
          write_bytes = false;
          set_bit = false;
          break;
        case FaultKind::kTrunc:
        case FaultKind::kPartial:
          trunc_to = n > 1 ? d.rand % n : 0;
          set_bit = false;
          break;
        case FaultKind::kCorrupt:
          corrupt = true;  // flip one byte AFTER the copy
          break;
        default:
          break;
      }
    }
    if (write_bytes) {
      piece.copy_to(j->dst_base + off, trunc_to);
      if (corrupt && trunc_to > 0) {
        // One flipped byte in the landed chunk: the per-chunk CRC (when
        // the call checksums) rejects the whole transfer at resolve.
        j->dst_base[off] ^= 0x20;
      }
    }
    if (set_bit) {
      if (j->crc) {
        j->x->chunk_crc[ci] = crc32c(piece);
      }
      // Release: publishes this chunk's payload bytes (and its CRC slot)
      // to the receiver's acquire bitmap scan.
      j->x->chunk_bits[ci / 64].fetch_or(1ull << (ci % 64),
                                         std::memory_order_release);
    }
    if (tl) {
      // Rail index carries the rma marker bit so Perfetto's rail tracks
      // show one-sided puts distinctly from ring-copied stripe sends.
      timeline::record(timeline::kStripeSend, j->cid,
                       ((timeline::kStripeRmaRailBit |
                         static_cast<uint64_t>(j->rail))
                        << 48) |
                           off);
    }
    ci += 1;
    off += n;
  }
  // Release on the countdown: the joining sender must observe every
  // chunk write this rail issued before sending the control frame.
  j->remaining->fetch_sub(1, std::memory_order_release);
}

void rail_fiber(void* arg) {
  auto* j = static_cast<RailJob*>(arg);
  rail_run(j);
  delete j;
}

// Cuts body into rail ranges and writes them concurrently; returns when
// every rail finished.  payload_dst points at the transfer's payload
// base in the peer region.
// Returns the bytes SKIPPED by a mid-transfer cancel (0 = fully put).
uint64_t put_body(RmaXfer* x, char* payload_dst, IOBuf&& body,
                  uint64_t chunk, int rails, uint64_t cid, bool crc,
                  const EndPoint& peer, const DeadlineToken& tok) {
  const uint64_t total = body.size();
  const uint32_t nchunks =
      static_cast<uint32_t>((total + chunk - 1) / chunk);
  const uint32_t want =
      std::max(1u, std::min<uint32_t>(static_cast<uint32_t>(rails),
                                      nchunks));
  const uint32_t per = (nchunks + want - 1) / want;  // chunks per rail
  // Rails actually used: ceil(nchunks/per) — may be fewer than `want`
  // when the rounding above packs the chunks tighter (the join counts
  // REAL rails, or it would wait forever on lanes that never ran).
  const uint32_t r = (nchunks + per - 1) / per;
  std::atomic<uint32_t> remaining{r};
  std::atomic<uint64_t> aborted_bytes{0};
  RailJob* inline_job = nullptr;
  for (uint32_t i = 0; i < r; ++i) {
    auto* j = new RailJob();
    j->x = x;
    j->dst_base = payload_dst;
    j->first_chunk = i * per;
    j->chunk = chunk;
    j->total = total;
    j->cid = cid;
    j->rail = i;
    j->crc = crc;
    j->peer = peer;
    j->remaining = &remaining;
    j->tok = tok;
    j->aborted = &aborted_bytes;
    const uint64_t rail_bytes =
        std::min<uint64_t>(static_cast<uint64_t>(per) * chunk, body.size());
    body.cutn(&j->data, rail_bytes);
    const bool last = i + 1 == r;
    if (!last) {
      if (fiber_start(nullptr, rail_fiber, j, 0) != 0) {
        rail_run(j);
        delete j;
      }
    } else {
      inline_job = j;  // the caller is rail r-1's writer
      break;
    }
  }
  if (inline_job != nullptr) {
    rail_run(inline_job);
    delete inline_job;
  }
  // Bounded join: each rail is a finite chunk-range memcpy.  Acquire
  // pairs with the rails' release countdown so every chunk write
  // happens-before the control frame below.
  while (remaining.load(std::memory_order_acquire) != 0) {
    if (in_fiber()) {
      fiber_sleep_us(20);
    } else {
      usleep(20);
    }
  }
  // Acquire pairs with the rails' abort accounting above.
  return aborted_bytes.load(std::memory_order_acquire);
}

// Queues the zero-payload control frame.  0 on success.
int send_control(SocketId primary, RpcMeta&& meta) {
  IOBuf frame;
  tstd_pack(&frame, meta, IOBuf());
  SocketRef s(Socket::Address(primary));
  return s && s->Write(std::move(frame)) == 0 ? 0 : -1;
}

// Resolves (and caches) the peer's window for a session.
std::shared_ptr<RmaMapping> resolve_peer_window(RmaSession* rs,
                                                uint64_t* rkey_out,
                                                RmaGeom* geom_out) {
  std::lock_guard<std::mutex> g(rs->mu);
  // Acquire: the peer published its window rkey into the shared segment
  // after fully creating the region.
  const uint64_t prk =
      rs->peer_rkey_slot != nullptr
          ? rs->peer_rkey_slot->load(std::memory_order_acquire)
          : 0;
  if (prk == 0) {
    return nullptr;
  }
  if (rs->peer_map == nullptr || rs->peer_rkey != prk) {
    RmaGeom snap;
    std::shared_ptr<RmaMapping> m = map_peer_region(prk, &snap);
    if (m == nullptr || snap.nslots == 0) {
      return nullptr;
    }
    rs->peer_map = std::move(m);
    rs->peer_rkey = prk;
    rs->peer_data_len = snap.data_len;
    rs->peer_slot_bytes = snap.slot_bytes;
    rs->peer_nslots = snap.nslots;
  }
  *rkey_out = rs->peer_rkey;
  geom_out->data_len = rs->peer_data_len;
  geom_out->slot_bytes = rs->peer_slot_bytes;
  geom_out->nslots = rs->peer_nslots;
  return rs->peer_map;
}

// Deleter context for a window-span payload: frees the span's slots in
// OUR OWN window when the consumer's last reference drops, holding the
// mapping alive meanwhile.  Carries the trusted geometry — the deleter
// may run long after a hostile peer scribbled the live header.
struct SpanCtx {
  std::shared_ptr<RmaMapping> map;
  std::shared_ptr<WindowScav> scav;  // null when scav state is gone
  RmaGeom geom;
  uint64_t off = 0;
  uint64_t need = 0;
};

// Forgets the scavenger's first-seen stamps for a span's slots: called
// whenever the OWNER knows the span's identity ended (payload freed, or
// a faulted transfer rejected) so a successor span allocated into the
// same slots ages from ITS OWN birth — without this, a busy slot
// recycled between scavenger ticks would inherit its predecessor's age
// and a healthy in-flight span could be reclaimed early.
void scav_forget_span(WindowScav* scav, const RmaGeom& g, uint64_t off,
                      uint64_t need) {
  if (scav == nullptr) {
    return;
  }
  const uint64_t mask = span_slot_mask(g, off, need);
  std::lock_guard<std::mutex> lk(reg_mu());
  for (uint32_t i = 0; i < kRmaWindowSlots; ++i) {
    if ((mask & (1ull << i)) != 0) {
      scav->first_seen_us[i] = 0;
    }
  }
}

void span_deleter(void*, void* vctx) {
  auto* ctx = static_cast<SpanCtx*>(vctx);
  if (ctx->scav != nullptr) {
    // Clear the admitted marks BEFORE the slots recycle: a slot that
    // reads set-but-not-admitted merely starts aging fresh (harmless);
    // the reverse order could shield a brand-new span with stale marks.
    // Release pairs with the scavenger's acquire read.
    ctx->scav->admitted.fetch_and(
        ~span_slot_mask(ctx->geom, ctx->off, ctx->need),
        std::memory_order_release);
    scav_forget_span(ctx->scav.get(), ctx->geom, ctx->off, ctx->need);
  }
  span_free(hdr_of(ctx->map), ctx->geom, ctx->off, ctx->need);
  delete ctx;
}

// Deleter context for a direct (caller-region) payload: the caller owns
// the bytes; only the mapping refcount is held (so rma_free defers).
struct DirectCtx {
  std::shared_ptr<RmaMapping> map;
};

void direct_deleter(void*, void* vctx) {
  delete static_cast<DirectCtx*>(vctx);
}

// Verifies a transfer header + bitmap + optional CRCs against the data
// area.  All header fields are copied locally FIRST: the header lives in
// shared memory and a hostile peer can mutate it between check and use.
bool xfer_verify(const RmaXfer* x, uint64_t want_token, const char* payload,
                 uint64_t want_len, uint64_t avail) {
  // Acquire: pairs with the sender's header-publishing release store.
  const uint64_t total = x->total.load(std::memory_order_acquire);
  const uint64_t token = x->token;
  const uint64_t chunk = x->chunk_bytes;
  const uint32_t nchunks = x->nchunks;
  const uint32_t flags = x->flags;
  if (token != want_token || total == 0 || total != want_len ||
      total > avail || chunk < 1024 ||
      nchunks == 0 || nchunks > kRmaMaxChunks ||
      static_cast<uint64_t>(nchunks - 1) * chunk >= total ||
      static_cast<uint64_t>(nchunks) * chunk < total) {
    return false;
  }
  for (uint32_t i = 0; i < nchunks; i += 64) {
    const uint32_t in_word = std::min(64u, nchunks - i);
    const uint64_t want =
        in_word == 64 ? ~0ull : ((1ull << in_word) - 1);
    // Acquire: a set bit publishes that chunk's payload bytes.
    if ((x->chunk_bits[i / 64].load(std::memory_order_acquire) & want) !=
        want) {
      return false;  // incomplete transfer: faulted chunk — drop whole
    }
  }
  if (flags & kXferCrcPresent) {
    for (uint32_t i = 0; i < nchunks; ++i) {
      const uint64_t off = static_cast<uint64_t>(i) * chunk;
      const uint64_t n = std::min(chunk, total - off);
      if (crc32c(payload + off, n) != x->chunk_crc[i]) {
        return false;
      }
    }
  }
  return true;
}

}  // namespace

RmaMapping::~RmaMapping() {
  if (base != nullptr && owned) {
    munmap(base, len);
  }
}

RmaSession::~RmaSession() {
  if (local_rkey != 0) {
    // Release the window region: unlink + drop the registry ref; the
    // munmap defers past any still-wrapped payload.
    std::lock_guard<std::mutex> g(reg_mu());
    auto& v = regions();
    for (auto it = v.begin(); it != v.end(); ++it) {
      if (it->rkey == local_rkey) {
        if (!it->name.empty()) {
          shm_unlink(it->name.c_str());
        }
        v.erase(it);
        break;
      }
    }
  }
}

std::shared_ptr<RmaSession> rma_session_create() {
  const int64_t bytes = flag_value(window_flag(), 0);
  if (bytes <= 0) {
    return nullptr;
  }
  uint64_t rkey = 0;
  if (region_create(static_cast<size_t>(bytes), /*window=*/true, &rkey) ==
      nullptr) {
    return nullptr;
  }
  auto s = std::make_shared<RmaSession>();
  s->local_rkey = rkey;
  return s;
}

void* rma_alloc(size_t len, uint64_t* rkey_out) {
  return region_create(len, /*window=*/false, rkey_out);
}

void rma_free(void* data) {
  if (data == nullptr) {
    return;
  }
  const char* base = static_cast<const char*>(data) - kRmaDataOffset;
  std::lock_guard<std::mutex> g(reg_mu());
  auto& v = regions();
  for (auto it = v.begin(); it != v.end(); ++it) {
    if (it->map != nullptr && it->map->base == base) {
      if (!it->name.empty()) {
        shm_unlink(it->name.c_str());  // no NEW peer maps either way
      }
      for (const auto& [cid, bind] : landing_binds()) {
        if (bind.rkey == it->rkey) {
          // An in-flight call still lands here (possibly via the striped
          // copy path, which holds the raw pointer): defer the erase —
          // and with it the munmap — to the last unbind.
          it->free_pending = true;
          return;
        }
      }
      v.erase(it);  // mapping refcount defers the munmap
      return;
    }
  }
}

uint64_t rma_reg(const void* buf, size_t len) {
  if (buf == nullptr || len == 0) {
    return 0;
  }
  // Relaxed: ordinal mint needs uniqueness only, no ordering.
  const uint32_t ord =
      g_next_ordinal.fetch_add(1, std::memory_order_relaxed);
  const uint64_t rkey = make_rkey(ord);
  RegionRec rec;
  rec.rkey = rkey;
  rec.pin_base = static_cast<const char*>(buf);
  rec.pin_len = len;
  std::lock_guard<std::mutex> g(reg_mu());
  regions().push_back(std::move(rec));
  return rkey;
}

int rma_unreg(uint64_t rkey) {
  std::lock_guard<std::mutex> g(reg_mu());
  auto& v = regions();
  for (auto it = v.begin(); it != v.end(); ++it) {
    if (it->rkey == rkey && it->map == nullptr) {
      v.erase(it);
      return 0;
    }
  }
  return -1;
}

bool rma_exportable(const void* buf, size_t len, uint64_t* rkey,
                    uint64_t* off) {
  return rma_pin_exportable(buf, len, rkey, off) != nullptr;
}

size_t rma_region_count() {
  std::lock_guard<std::mutex> g(reg_mu());
  return regions().size();
}

namespace {

Adder& span_scavenged_var() {
  static Adder* a = [] {
    auto* v = new Adder();
    v->expose("rma_span_scavenged",
              "receive-window slots reclaimed by the span scavenger "
              "(allocated by a peer but never admitted — the control "
              "frame was dropped or the sender died mid-put; bounded by "
              "trpc_rma_span_scavenge_ms)");
    return v;
  }();
  return *a;
}

[[maybe_unused]] Adder& g_scavenged_eager = span_scavenged_var();

}  // namespace

size_t rma_scavenge(int64_t now_us) {
  if (now_us == 0) {
    now_us = monotonic_time_us();
  }
  const int64_t age_us = flag_value(scavenge_flag(), 10000) * 1000;
  size_t reclaimed = 0;
  std::lock_guard<std::mutex> g(reg_mu());
  for (RegionRec& r : regions()) {
    if (!r.window || r.map == nullptr || r.scav == nullptr) {
      continue;
    }
    RmaSegHdr* h = hdr_of(r.map);
    // Acquire pairs with the peer's CAS claim (span_alloc) — a slot
    // counted here was fully published before this scan.
    const uint64_t cur = h->slot_map.load(std::memory_order_acquire);
    // Acquire pairs with rma_resolve's admit / span_deleter's clear.
    const uint64_t admitted =
        r.scav->admitted.load(std::memory_order_acquire);
    uint64_t reclaim = 0;
    for (uint32_t i = 0; i < kRmaWindowSlots; ++i) {
      const uint64_t bit = 1ull << i;
      if ((cur & bit) == 0 || (admitted & bit) != 0) {
        r.scav->first_seen_us[i] = 0;  // free, or a live admitted span
        continue;
      }
      if (r.scav->first_seen_us[i] == 0) {
        r.scav->first_seen_us[i] = now_us;  // start aging
      } else if (now_us - r.scav->first_seen_us[i] > age_us) {
        reclaim |= bit;
        r.scav->first_seen_us[i] = 0;
      }
    }
    if (reclaim != 0) {
      // Release mirrors span_free: nothing of ours reads the span, but
      // the allocating peer's next claim must not fold into stale state.
      h->slot_map.fetch_and(~reclaim, std::memory_order_release);
      reclaimed += static_cast<size_t>(__builtin_popcountll(reclaim));
    }
  }
  if (reclaimed != 0) {
    span_scavenged_var() << static_cast<int64_t>(reclaimed);
  }
  return reclaimed;
}

size_t rma_spans_in_use() {
  // The drain quiesce poll doubles as the scavenger's lazy tick: a
  // leaked span must not hold a draining server hostage.
  rma_scavenge();
  std::lock_guard<std::mutex> g(reg_mu());
  size_t n = 0;
  for (const RegionRec& r : regions()) {
    if (!r.window || r.map == nullptr) {
      continue;
    }
    // Acquire: pairs with the peer's CAS claim so a span counted here
    // was fully published before we read the bitmap.
    n += static_cast<size_t>(__builtin_popcountll(
        hdr_of(r.map)->slot_map.load(std::memory_order_acquire)));
  }
  return n;
}

// The one authoritative exportable-region scan: rma_exportable is a
// thin boolean wrapper over it.
std::shared_ptr<RmaMapping> rma_pin_exportable(const void* buf, size_t len,
                                               uint64_t* rkey,
                                               uint64_t* off) {
  const char* p = static_cast<const char*>(buf);
  std::lock_guard<std::mutex> g(reg_mu());
  for (const RegionRec& r : regions()) {
    if (r.map == nullptr || r.window || r.free_pending) {
      continue;  // windows are connection-owned, not caller landings;
                 // free_pending regions accept no NEW registrations
    }
    const char* data = r.map->base + kRmaDataOffset;
    if (p >= data && len <= r.geom.data_len &&
        p + len <= data + r.geom.data_len) {
      if (rkey != nullptr) {
        *rkey = r.rkey;
      }
      if (off != nullptr) {
        *off = static_cast<uint64_t>(p - data);
      }
      return r.map;
    }
  }
  return nullptr;
}

void rma_landing_bind(uint64_t cid, void* buf, size_t cap) {
  uint64_t rkey = 0;
  uint64_t off = 0;
  if (!rma_exportable(buf, cap, &rkey, &off)) {
    return;  // copy-path landing only (arbitrary caller memory)
  }
  std::lock_guard<std::mutex> g(reg_mu());
  for (const auto& [other_cid, bind] : landing_binds()) {
    if (bind.rkey == rkey && other_cid != cid) {
      // One direct transfer per region at a time: the region header
      // holds a single completion descriptor.  This call still lands
      // via the striped copy path — correct, just not zero-copy.
      return;
    }
  }
  landing_binds()[cid] = LandingBind{rkey, cap, off};
}

void rma_landing_unbind(uint64_t cid) {
  std::lock_guard<std::mutex> g(reg_mu());
  auto it = landing_binds().find(cid);
  if (it == landing_binds().end()) {
    return;
  }
  const uint64_t rkey = it->second.rkey;
  landing_binds().erase(it);
  for (const auto& [other_cid, bind] : landing_binds()) {
    if (bind.rkey == rkey) {
      return;  // another in-flight call still lands in the region
    }
  }
  auto& v = regions();
  for (auto rit = v.begin(); rit != v.end(); ++rit) {
    if (rit->rkey == rkey && rit->free_pending) {
      v.erase(rit);  // the deferred rma_free completes here
      return;
    }
  }
}

uint64_t rma_landing_rkey(uint64_t cid, uint64_t* max_out,
                          uint64_t* off_out) {
  std::lock_guard<std::mutex> g(reg_mu());
  auto it = landing_binds().find(cid);
  if (it == landing_binds().end()) {
    return 0;
  }
  if (max_out != nullptr) {
    *max_out = it->second.cap;
  }
  if (off_out != nullptr) {
    *off_out = it->second.off;
  }
  return it->second.rkey;
}

int rma_rails_for(int socket_mode) {
  return static_cast<int>(
      socket_mode == static_cast<int>(SocketMode::kIci)
          ? flag_value(ici_rails_flag(), 4)
          : flag_value(shm_rails_flag(), 4));
}

void rma_advertise_response(SocketId sid, uint64_t cid, RpcMeta* meta) {
  uint64_t max = 0;
  uint64_t off = 0;
  const uint64_t rkey = rma_landing_rkey(cid, &max, &off);
  if (rkey == 0) {
    return;
  }
  SocketRef s(Socket::Address(sid));
  if (!s || s->transport() == nullptr ||
      s->transport()->rma(s.get()) == nullptr) {
    return;  // no one-sided plane on this connection
  }
  meta->rma_resp_rkey = rkey;
  meta->rma_resp_max = max;
  meta->rma_resp_off = off;
}

int rma_try_send(SocketId primary, RpcMeta* meta, IOBuf* body,
                 uint64_t target_rkey, uint64_t target_max,
                 uint64_t target_off, const DeadlineToken& tok) {
  const uint64_t total = body->size();
  if (meta->stream_id != 0 || !stripe_eligible(total)) {
    return 1;
  }
  SocketRef s(Socket::Address(primary));
  if (!s || s->transport() == nullptr) {
    return 1;
  }
  RmaSession* rs = s->transport()->rma(s.get());
  if (rs == nullptr) {
    return 1;
  }
  if (s->mode() == SocketMode::kIci &&
      ici_payload_prefers_descriptors(*body)) {
    return 1;  // staging-backed bodies ride sender-owned descriptors
  }
  const uint64_t chunk = effective_chunk(total);
  const bool crc = meta->has_checksum;
  const int rails = rma_rails_for(static_cast<int>(s->mode()));
  const uint64_t cid = meta->correlation_id;
  const EndPoint peer = s->remote();

  // Direct-to-region: the peer advertised a registered caller buffer for
  // this payload (response landing) — write at data offset 0, completion
  // bitmap in the region header.
  if (target_rkey != 0 && total <= target_max) {
    RmaGeom tg;
    std::shared_ptr<RmaMapping> m = map_peer_region(target_rkey, &tg);
    if (m != nullptr) {
      RmaSegHdr* h = hdr_of(m);
      if (tg.nslots == 0 && target_off <= tg.data_len &&
          total <= tg.data_len - target_off) {
        if (timeline::enabled()) {
          timeline::record(timeline::kStripeCut, cid, total);
        }
        xfer_init(&h->direct, total, chunk, crc, cid);
        const uint32_t nchunks =
            static_cast<uint32_t>((total + chunk - 1) / chunk);
        const uint64_t skipped =
            put_body(&h->direct, m->base + kRmaDataOffset + target_off,
                     std::move(*body), chunk, rails, cid, crc, peer, tok);
        if (skipped != 0) {
          // Cancelled mid-transfer: no control frame — the receiver
          // never admits the partial put; the caller's fid is already
          // dying (the cancel reached it first).
          deadline_vars().cancel_saved_bytes
              << static_cast<int64_t>(skipped);
          return -1;
        }
        meta->rma_rkey = target_rkey;
        meta->rma_off = kRmaDirectOff;
        meta->rma_len = total;
        meta->rma_chunk = chunk;
        // The control frame's payload is empty, so a checksummed call's
        // frame carries crc32c("") == 0 — has_checksum stays SET (the
        // server derives response-checksum intent from it; the real
        // integrity rides the per-chunk CRCs in the transfer header).
        meta->checksum = 0;
        hotpath_vars().rma_tx_msgs << 1;
        hotpath_vars().rma_tx_chunks << nchunks;
        hotpath_vars().rma_tx_bytes << static_cast<int64_t>(total);
        return send_control(primary, std::move(*meta)) == 0 ? 0 : -1;
      }
    }
    // Advertised region unusable: fall through to the window path.
  }

  uint64_t peer_rkey = 0;
  RmaGeom wg;
  std::shared_ptr<RmaMapping> m = resolve_peer_window(rs, &peer_rkey, &wg);
  if (m == nullptr) {
    return 1;  // peer window not published (old peer / disabled)
  }
  RmaSegHdr* h = hdr_of(m);
  uint64_t off = 0;
  const uint64_t need = kRmaSpanHdr + total;
  if (span_alloc(h, wg, need, &off) != 0) {
    hotpath_vars().rma_window_full << 1;
    return 1;  // window full: copy path carries this one
  }
  auto* x = reinterpret_cast<RmaXfer*>(m->base + kRmaDataOffset + off);
  if (timeline::enabled()) {
    timeline::record(timeline::kStripeCut, cid, total);
  }
  xfer_init(x, total, chunk, crc, cid);
  const uint32_t nchunks =
      static_cast<uint32_t>((total + chunk - 1) / chunk);
  const uint64_t skipped =
      put_body(x, reinterpret_cast<char*>(x) + kRmaSpanHdr,
               std::move(*body), chunk, rails, cid, crc, peer, tok);
  if (skipped != 0) {
    // Cancelled mid-transfer: reclaim the span now (no control frame
    // will ever admit it) and fail the call whole.
    deadline_vars().cancel_saved_bytes << static_cast<int64_t>(skipped);
    span_free(h, wg, off, need);
    return -1;
  }
  meta->rma_rkey = peer_rkey;
  meta->rma_off = off;
  meta->rma_len = total;
  meta->rma_chunk = chunk;
  // Empty control payload: crc32c("") == 0; has_checksum stays SET so
  // the server still derives response-checksum intent from the request.
  meta->checksum = 0;
  hotpath_vars().rma_tx_msgs << 1;
  hotpath_vars().rma_tx_chunks << nchunks;
  hotpath_vars().rma_tx_bytes << static_cast<int64_t>(total);
  if (send_control(primary, std::move(*meta)) != 0) {
    span_free(h, wg, off, need);  // control never queued: reclaim now
    return -1;
  }
  return 0;
}

bool rma_resolve(InputMessage* msg, Socket* sock) {
  {
    // Lazy scavenger tick, rate-limited to ~4/s: while one-sided
    // traffic flows, leaked spans (dropped control frames) reclaim
    // without any dedicated thread; the drain poll covers idle windows.
    static std::atomic<int64_t> last_scan{0};
    const int64_t now = monotonic_time_us();
    // Relaxed: the limiter only needs an approximate winner; the
    // scavenger itself synchronizes through reg_mu and the bitmaps.
    int64_t prev = last_scan.load(std::memory_order_relaxed);
    if (now - prev > 250 * 1000 &&
        last_scan.compare_exchange_strong(prev, now,
                                          std::memory_order_relaxed)) {
      rma_scavenge(now);
    }
  }
  RpcMeta& m = msg->meta;
  const uint64_t rkey = m.rma_rkey;
  const uint64_t total = m.rma_len;
  const bool direct = m.rma_off == kRmaDirectOff;
  auto reject = [&](const char* why) {
    hotpath_vars().rma_rejected << 1;
    LOG(Warning) << "rma control rejected (" << why << ", rkey=" << rkey
                 << " off=" << m.rma_off << " len=" << total << ")";
    return false;
  };
  if (total == 0 || !msg->payload.empty()) {
    return reject("bad control frame");
  }
  if (direct) {
    // Response into the caller's registered buffer: the rkey must be the
    // one THIS process advertised for this cid — a control frame naming
    // anything else (freed region, another caller's buffer) drops whole.
    if (m.type != RpcMeta::kResponse) {
      return reject("direct put on a non-response");
    }
    uint64_t cap = 0;
    uint64_t land_off = 0;
    if (rma_landing_rkey(m.correlation_id, &cap, &land_off) != rkey ||
        total > cap) {
      return reject("not the advertised landing");
    }
    bool window = false;
    RmaGeom geom;  // trusted creation-time geometry, never the header's
    std::shared_ptr<RmaMapping> map = local_region(rkey, &window, &geom);
    if (map == nullptr || window) {
      return reject("unknown region");
    }
    RmaSegHdr* h = hdr_of(map);
    // The landing offset comes from the LOCAL bind (what this process
    // registered), never the frame — a control frame cannot steer the
    // payload pointer anywhere the caller didn't bind.
    if (land_off > geom.data_len || total > geom.data_len - land_off) {
      return reject("landing out of bounds");
    }
    char* payload = map->base + kRmaDataOffset + land_off;
    if (!xfer_verify(&h->direct, m.correlation_id, payload, total,
                     geom.data_len - land_off)) {
      return reject("incomplete or corrupt transfer");
    }
    auto* ctx = new DirectCtx{std::move(map)};
    msg->payload.append_user_data(payload, total, &direct_deleter, ctx);
  } else {
    // Window span: only the window bound to THIS connection's session is
    // addressable — the control frame cannot name other local regions.
    RmaSession* rs = sock != nullptr && sock->transport() != nullptr
                         ? sock->transport()->rma(sock)
                         : nullptr;
    if (rs == nullptr || rs->local_rkey != rkey) {
      return reject("not this connection's window");
    }
    bool window = false;
    RmaGeom geom;  // trusted creation-time geometry, never the header's
    std::shared_ptr<WindowScav> scav;
    std::shared_ptr<RmaMapping> map =
        local_region(rkey, &window, &geom, &scav);
    if (map == nullptr || !window) {
      return reject("unknown window");
    }
    RmaSegHdr* h = hdr_of(map);
    const uint64_t need = kRmaSpanHdr + total;
    if (m.rma_off % geom.slot_bytes != 0 || m.rma_off >= geom.data_len ||
        need > geom.data_len - m.rma_off) {
      return reject("span out of bounds");
    }
    // A span is addressable only while its slots are ALLOCATED: clear
    // bits mean the scavenger reclaimed it (its control frame was
    // presumed lost — this is that frame, arriving late).  Neither
    // admit nor free: a successor span may already own the memory.
    // Acquire pairs with the peer's claim CAS.
    const uint64_t slot_mask = span_slot_mask(geom, m.rma_off, need);
    if ((h->slot_map.load(std::memory_order_acquire) & slot_mask) !=
        slot_mask) {
      return reject("span was scavenged");
    }
    auto* x = reinterpret_cast<RmaXfer*>(map->base + kRmaDataOffset +
                                         m.rma_off);
    char* payload = reinterpret_cast<char*>(x) + kRmaSpanHdr;
    // Token gate on RECLAMATION: only a frame whose correlation id owns
    // the span header may free the slots on verification failure — a
    // scavenged-and-reused span (successor's token) or a hostile frame
    // must reject WITHOUT freeing someone else's live span.  Acquire
    // pairs with the sender's header-publishing release store.
    const bool owns =
        x->total.load(std::memory_order_acquire) != 0 &&
        x->token == m.correlation_id;
    if (!xfer_verify(x, m.correlation_id, payload, total,
                     geom.data_len - m.rma_off - kRmaSpanHdr)) {
      if (owns) {
        scav_forget_span(scav.get(), geom, m.rma_off, need);
        span_free(h, geom, m.rma_off, need);  // reclaim the faulted span
      }
      return reject("incomplete or corrupt transfer");
    }
    if (scav != nullptr) {
      // Admit marks: the span is live for as long as the payload holds
      // a reference — the scavenger must never age it.  Release pairs
      // with the scavenger's acquire read.
      scav->admitted.fetch_or(span_slot_mask(geom, m.rma_off, need),
                              std::memory_order_release);
    }
    auto* ctx = new SpanCtx{std::move(map), std::move(scav), geom,
                            m.rma_off, need};
    msg->payload.append_user_data(payload, total, &span_deleter, ctx);
  }
  if (timeline::enabled()) {
    timeline::record(timeline::kStripeDone, m.correlation_id, total);
  }
  hotpath_vars().rma_rx_msgs << 1;
  // The payload is in place: clear the transfer fields (the response
  // advertisement, if any, stays — it belongs to the request's reply
  // path) and let the messenger dispatch the message normally.
  m.rma_rkey = 0;
  m.rma_off = 0;
  m.rma_len = 0;
  m.rma_chunk = 0;
  // Chunk CRCs were verified out-of-band; the zeroed checksum must not
  // masquerade as a whole-body one, but has_checksum stays as parsed —
  // the server derives response-checksum intent from it (the same
  // contract as stripe.cc's dispatch_entry).
  m.checksum = 0;
  return true;
}

// -- readiness maps --------------------------------------------------------
//
// Producer-stamped chunk-ready bitmaps with the RmaXfer fence
// discipline: stamp = release fetch_or after the producer's writes,
// test = acquire scan so a true answer publishes those writes.  Maps
// are process-local; waiters park on a fiber Event so both fibers and
// pthreads (ctypes callers) can block.

namespace {

struct ReadyMap {
  const char* base = nullptr;
  uint64_t len = 0;
  uint64_t granularity = 0;
  uint64_t nchunks = 0;
  std::vector<std::atomic<uint64_t>> bits;
  // Monotonic count of bytes stamped (first-time bits only).
  // relaxed: stats only, read with no ordering requirement.
  std::atomic<uint64_t> ready_bytes{0};
  // Bumped (and woken) on every stamp so range waiters re-scan.
  Event changed;

  ReadyMap(const void* b, uint64_t l, uint64_t g)
      : base(static_cast<const char*>(b)),
        len(l),
        granularity(g),
        nchunks((l + g - 1) / g),
        bits((nchunks + 63) / 64) {}
};

std::mutex& ready_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}

std::unordered_map<uint64_t, std::shared_ptr<ReadyMap>>& ready_reg() {
  static auto* reg =
      new std::unordered_map<uint64_t, std::shared_ptr<ReadyMap>>();
  return *reg;
}

uint64_t& ready_next_handle() {
  static uint64_t next = 1;
  return next;
}

std::shared_ptr<ReadyMap> ready_find(uint64_t handle) {
  std::lock_guard<std::mutex> g(ready_mu());
  auto it = ready_reg().find(handle);
  return it == ready_reg().end() ? nullptr : it->second;
}

// Chunk index range [first, last] covering [off, off+len); false when
// the span falls outside the map.
bool ready_span(const ReadyMap& m, uint64_t off, uint64_t len,
                uint64_t* first, uint64_t* last) {
  if (len == 0 || off > m.len || m.len - off < len) return false;
  *first = off / m.granularity;
  *last = (off + len - 1) / m.granularity;
  return true;
}

// Acquire scan: 1 when every chunk in [first, last] is stamped.
bool ready_all_set(const ReadyMap& m, uint64_t first, uint64_t last) {
  for (uint64_t c = first; c <= last; ++c) {
    // acquire: pairs with the stamp's release fetch_or — observing the
    // bit set publishes the producer's buffer writes up to the stamp.
    const uint64_t w = m.bits[c / 64].load(std::memory_order_acquire);
    if (!(w & (1ull << (c % 64)))) return false;
  }
  return true;
}

}  // namespace

uint64_t rma_ready_create(const void* base, uint64_t len,
                          uint64_t granularity) {
  if (base == nullptr || len == 0 || granularity == 0) return 0;
  auto map = std::make_shared<ReadyMap>(base, len, granularity);
  std::lock_guard<std::mutex> g(ready_mu());
  const uint64_t h = ready_next_handle()++;
  ready_reg().emplace(h, std::move(map));
  return h;
}

int rma_ready_stamp(uint64_t handle, uint64_t off, uint64_t len) {
  auto m = ready_find(handle);
  if (!m) return -1;
  uint64_t first, last;
  if (!ready_span(*m, off, len, &first, &last)) return -1;
  // Alignment contract: stamps cover whole chunks so a later test of
  // any sub-range is never half-true.
  if (off % m->granularity != 0) return -1;
  if (len % m->granularity != 0 && off + len != m->len) return -1;
  uint64_t fresh_bytes = 0;
  for (uint64_t c = first; c <= last; ++c) {
    const uint64_t bit = 1ull << (c % 64);
    // release: publishes the producer's preceding buffer writes to any
    // consumer whose acquire scan observes this bit (RmaXfer pattern).
    const uint64_t prev =
        m->bits[c / 64].fetch_or(bit, std::memory_order_release);
    if (!(prev & bit)) {
      fresh_bytes += std::min(m->granularity, m->len - c * m->granularity);
    }
  }
  if (fresh_bytes != 0) {
    // relaxed: stats counter, no ordering needed beyond the bit fence.
    m->ready_bytes.fetch_add(fresh_bytes, std::memory_order_relaxed);
  }
  // relaxed: the Event word is only a wakeup ticket — waiters re-scan
  // the bitmap (acquire) after every wake, so no ordering rides on it.
  m->changed.value.fetch_add(1, std::memory_order_relaxed);
  m->changed.wake_all();
  return 0;
}

int rma_ready_test(uint64_t handle, uint64_t off, uint64_t len) {
  auto m = ready_find(handle);
  if (!m) return -1;
  uint64_t first, last;
  if (!ready_span(*m, off, len, &first, &last)) return -1;
  return ready_all_set(*m, first, last) ? 1 : 0;
}

int rma_ready_wait(uint64_t handle, uint64_t off, uint64_t len,
                   int64_t deadline_us) {
  for (;;) {
    auto m = ready_find(handle);
    if (!m) return EINVAL;  // destroyed under a parked waiter
    uint64_t first, last;
    if (!ready_span(*m, off, len, &first, &last)) return EINVAL;
    // relaxed: ticket read only; the authoritative answer is the
    // acquire bitmap scan below, re-run after every wake.
    const uint32_t v = m->changed.value.load(std::memory_order_relaxed);
    if (ready_all_set(*m, first, last)) return 0;
    if (deadline_us >= 0 && monotonic_time_us() >= deadline_us) {
      return ETIMEDOUT;
    }
    m->changed.wait(v, deadline_us);
  }
}

uint64_t rma_ready_bytes(uint64_t handle) {
  auto m = ready_find(handle);
  // relaxed: stats read, no ordering requirement.
  return m ? m->ready_bytes.load(std::memory_order_relaxed) : 0;
}

void rma_ready_destroy(uint64_t handle) {
  std::shared_ptr<ReadyMap> m;
  {
    std::lock_guard<std::mutex> g(ready_mu());
    auto it = ready_reg().find(handle);
    if (it == ready_reg().end()) return;
    m = std::move(it->second);
    ready_reg().erase(it);
  }
  // Wake parked waiters; they re-resolve the handle and see EINVAL.
  // relaxed: wakeup ticket only (see rma_ready_stamp).
  m->changed.value.fetch_add(1, std::memory_order_relaxed);
  m->changed.wake_all();
}

size_t rma_ready_maps() {
  std::lock_guard<std::mutex> g(ready_mu());
  return ready_reg().size();
}

}  // namespace trpc
